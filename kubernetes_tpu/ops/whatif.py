"""What-if scan kernels: preemption victim search as one device launch.

The oracle dry-run (plugins/defaultpreemption.py selectVictimsOnNode,
reference default_preemption.go:592) runs the full filter chain once per
candidate node per victim add-back — O(candidates x victims) host filter
runs per preemptor, the last oracle-bound workload class in BENCH_CONFIGS
after PR 5's session deltas. This module re-expresses that dry run as ONE
fused device program per preemptor:

  * every candidate node's victim set arrives as a batch of INVERSE carry
    deltas (the PR-5 delta algebra run in reverse: a victim leaving node
    i moves exactly the node's utilization row, the PTS pair counts at
    node i's topology pairs, and the preemptor's own IPA term counts in
    node i's groups);
  * base feasibility ("all lower-priority victims removed",
    default_preemption.go:626) is evaluated for ALL nodes at once against
    a SCRATCH copy of the session carry — the live carry chain is never
    donated to, chained on, or invalidated;
  * the reprieve loop (:633 — victims added back highest-priority-first,
    the PDB-violating group first, while the preemptor still fits) runs
    as an in-launch lax.scan over victim slots, vectorized over every
    node: each step re-adds one slot's deltas and re-tests the exact
    filter set (fit, pod count, PodTopologySpread skew with the global
    min re-derived per node via a min/second-min decomposition,
    InterPodAffinity counts) — the sequential greedy the oracle runs,
    node-parallel because nodes' dry runs are independent;
  * nominated pods ride as POSITIVE deltas with the framework's two-pass
    semantics (framework.go:610: pass with them added AND without).

Exactness domain: the preemptor may carry pod (anti-)affinity terms and
topology-spread constraints — the capability the numpy fast planner's
envelope must reject — because the session prologue already computes the
per-template IPA/PTS statics the adjustments are applied to. The planner
(scheduler/preemption_device.py) gates the envelope: no extenders, no
host ports or PVCs on the preemptor, and no existing/nominated pod whose
required anti-affinity term matches the preemptor (those terms are the
one filter input a victim EVICTION cannot express as a count decrement).

Parity is pinned three ways in tests/test_preemption_fast.py: device vs
numpy-fast vs oracle on the fast envelope, device vs oracle on the
affinity/spread extension.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import kernel as K
from .hoisted import (
    HoistedSession,
    _PORT_STEP_KEYS,
    _eval_reqs_batch_np,
    batch_bucket,
    template_fingerprint,
)
from .kernel import _CNT, _I64

# IPA term-table keys of ONE template the host victim-matcher reads
_TERM_SLICE_KEYS = tuple(
    f"{prefix}_{suffix}"
    for prefix in ("ipaaa", "ipaa")
    for suffix in ("op", "rkey", "pairs", "ns", "valid", "key")
)


def ipa_victim_matches_np(tt: Dict, rows_list: List[Dict]):
    """(manti [B, TAA], mall [B]) — does victim b match the preemptor's
    required anti-affinity term t / ALL of its required affinity terms
    (podMatchesAllAffinityTerms, filtering.go:357)? Host numpy twin of
    kernel._ipa_term_matches for a handful of victim rows; namespaces
    and term validity included."""
    B = len(rows_list)
    taa = tt["ipaaa_valid"].shape[0]
    ta = tt["ipaa_valid"].shape[0]
    manti = np.zeros((B, taa), np.int32)
    mall = np.zeros(B, np.int32)
    if B == 0:
        return manti, mall
    pp = np.stack([np.asarray(r["self_ppair"]) for r in rows_list]).astype(bool)
    pk = np.stack([np.asarray(r["self_pkey"]) for r in rows_list]).astype(bool)
    ns = np.asarray([int(np.asarray(r["self_ns"])) for r in rows_list])

    def fam(prefix, width):
        valid = tt[f"{prefix}_valid"].astype(bool)
        if not valid.any():
            return np.zeros((B, width), bool), valid
        m = _eval_reqs_batch_np(
            tt[f"{prefix}_op"], tt[f"{prefix}_rkey"], tt[f"{prefix}_pairs"],
            pp, pk,
        )  # [B, T]
        ns_tbl = tt[f"{prefix}_ns"]  # [T, X]
        ns_ok = (
            (ns_tbl[None, :, :] == ns[:, None, None]) & (ns_tbl[None, :, :] != 0)
        ).any(axis=-1)  # [B, T]
        return m & ns_ok & valid[None, :], valid

    m_anti, _ = fam("ipaaa", taa)
    manti = m_anti.astype(np.int32)
    m_aff, aff_valid = fam("ipaa", ta)
    if aff_valid.any():
        mall = np.all(
            np.where(aff_valid[None, :], m_aff, True), axis=1
        ).astype(np.int32)
    return manti, mall


# ---------------------------------------------------------------------------
# the fused what-if program


@functools.partial(
    jax.jit, static_argnames=("tj", "dyn_ipa", "dyn_ports", "has_nom")
)
def _whatif_run(
    S: Dict, c_static: Dict, carry: Dict,
    v_valid, v_cnt, v_req, v_mfs, v_manti, v_mall,
    nom_req, nom_cnt, nom_mfs, nom_manti, nom_mall,
    pre_req, pre_cnt, pre_shared, pre_anti, pre_aff, pre_atot,
    tj: int = 0, dyn_ipa: bool = False, dyn_ports: bool = False,
    has_nom: bool = False,
):
    """One preemptor's whole dry run: fits_now[N], base feasibility with
    every victim evicted, and the reprieve walk — one launch.

    Victim tensors are [N, L] slot-ordered PER NODE in the oracle's
    reprieve order (PDB-violating group first, then the rest, each by
    MoreImportantPod); pre_* are the already-claimed-victim aggregates
    (earlier waves / earlier pods of this wave) applied to EVERY state —
    pre_shared/pre_anti/pre_aff at topology-PAIR granularity because a
    claimed victim on another node still drains this node's shared
    groups. All adjustments are exact at the evaluated node, which is
    the only lane each node's verdict reads.

    A slot may hold a whole same-node GANG UNIT (gang-aware preemption:
    a gang's co-located members evict together or not at all): its
    req/mfs/manti/mall are the members' sums and v_cnt [N, L] carries
    the member count the pod-count filter must release/re-add per slot.
    Singleton slots pass v_cnt == v_valid, preserving the original
    per-pod arithmetic bit-for-bit."""

    def sel(key):
        return S[key][tj]

    req = sel("req")
    req_check = sel("req_check")
    req_has_any = sel("req_has_any")
    alloc = c_static["alloc"]
    allowed = c_static["allowed_pods"]
    free0 = alloc - carry["requested"] + pre_req          # [N, R]
    cnt0 = carry["pod_count"].astype(_I64) - pre_cnt      # [N]

    # -- eviction-invariant gate -------------------------------------------
    static_gate = sel("static_mask")
    if dyn_ports:
        static_gate = static_gate & K.ports_mask(
            carry["cp_any"], carry["cp_wild"], carry["cp_trip"],
            {k: sel(k) for k in _PORT_STEP_KEYS},
        )

    # -- IPA effective counts: prologue statics + session-assumed dynamics
    #    (the D1-D3 composition of ops/hoisted._eval_pod) + claimed-victim
    #    pair-level drains ---------------------------------------------------
    if dyn_ipa:
        u_cnt, k_cnt = carry["u_cnt"], carry["k_cnt"]
        pok, nk = c_static["pair_of_key"], c_static["nkey"]
        kaa = S["ipaaa_key"]                          # [U, TAA]
        cnt1 = jax.vmap(lambda uc, pv: uc[pv])(
            u_cnt, pok[:, kaa].transpose(1, 0, 2)
        )  # [U, N, TAA]
        g1 = S["M_anti"][:, :, tj]                    # [U, TAA]
        nk1 = nk[:, kaa].transpose(1, 0, 2)           # [U, N, TAA]
        fail_existing_dyn = jnp.any(
            g1[:, None, :] & nk1 & (cnt1 > 0), axis=(0, 2)
        )  # [N]
        g2 = S["M_anti"][tj].astype(_CNT)             # [TAA, U]
        w2 = g2 @ u_cnt                               # [TAA, Vnp]
        anti_key = sel("ipaaa_key")
        pair_nt = pok[:, anti_key]                    # [N, TAA]
        anti_dyn = jax.vmap(
            lambda wv, pv: wv[pv], in_axes=(0, 1), out_axes=1
        )(w2, pair_nt)                                # [N, TAA]
        g3 = S["match_all"][tj].astype(_CNT)          # [U]
        w3 = g3 @ u_cnt                               # [Vnp]
        aff_key = sel("ipaa_key")
        pair_na = pok[:, aff_key]                     # [N, Ta]
        aff_dyn = w3[pair_na]                         # [N, Ta]
        aff_total_dyn = jnp.sum(
            sel("ipaa_valid")[None, :] * g3[:, None] * k_cnt[:, aff_key]
        )
        anti_pre = jax.vmap(
            lambda vec, pv: vec[pv], in_axes=(0, 1), out_axes=1
        )(pre_anti, pair_nt)                          # [N, TAA]
        aff_pre = pre_aff[pair_na]                    # [N, Ta]
        anti_eff = sel("ipa_anti_cnt_n") + anti_dyn - anti_pre
        aff_eff = sel("ipa_aff_cnt_n") + aff_dyn - aff_pre
        aff_total_eff = sel("ipa_aff_total") + aff_total_dyn - pre_atot
        fail_exist = sel("ipa_fail_existing") | fail_existing_dyn
        anti_valid = sel("ipaaa_valid")
        anti_key_on = sel("ipa_anti_key_on_node")     # [N, TAA]
        aff_valid = sel("ipaa_valid")
        aff_key_on = nk[:, aff_key]                   # [N, Ta]
        aff_all_keys = sel("ipa_aff_all_keys")
        has_aff = sel("ipa_has_aff")
        self_match_all = sel("ipa_self_match_all")
        # one evicted matches-all victim on node n drains aff_total by
        # the number of its node's scattered term entries
        aff_keys_cnt = jnp.sum(
            aff_valid[None, :] & aff_key_on, axis=1
        ).astype(_CNT)                                # [N]
        static_gate = static_gate & ~fail_exist

    # -- PTS base: shared counts (claimed drains applied), min structure ----
    f_valid = sel("f_valid")
    any_f = jnp.any(f_valid)
    shared = jnp.sum(
        jnp.where(
            sel("f_same_key")[:, :, None], carry["f_cnt"][tj][None, :, :], 0
        ),
        axis=1,
    ) - pre_shared                                    # [C, Vnp]
    reg_real = sel("f_reg_real")                      # [C, Vnp]
    pair_cn = sel("f_pair_cn")                        # [N, C]
    self_m = sel("f_self_match")                      # [C]
    key_on_f = sel("f_key_on_node")                   # [N, C]
    fail_missing = jnp.any(f_valid[None, :] & ~key_on_f, axis=1)
    f_skew = sel("f_skew")
    big = jnp.iinfo(_CNT).max
    masked = jnp.where(reg_real, shared, big)
    min1 = jnp.min(masked, axis=1)                    # [C]
    cnt_min1 = jnp.sum(masked == min1[:, None], axis=1)
    min2 = jnp.min(jnp.where(masked == min1[:, None], big, masked), axis=1)
    shared_at = jnp.take_along_axis(shared.T, pair_cn, axis=0)   # [N, C]
    reg_at = jnp.take_along_axis(reg_real.T, pair_cn, axis=0)    # [N, C]
    # global min with this node's own pair EXCLUDED: re-enters adjusted
    min_excl = jnp.where(
        reg_at & (shared_at == min1[None, :]) & (cnt_min1[None, :] == 1),
        min2[None, :], min1[None, :],
    )                                                 # [N, C]

    def feas_one(ev, use_nom: bool):
        ev_req, ev_cnt, ev_mfs, ev_manti, ev_mall = ev
        # NodeResourcesFit + pod count (fit.go:230; victims freed, the
        # node's nominated pods added back — framework.go:610)
        freeN = free0 + ev_req
        cntN = cnt0 - ev_cnt
        if use_nom:
            freeN = freeN - nom_req
            cntN = cntN + nom_cnt
        over = (req[None, :] > freeN) & req_check[None, :]
        fit_ok = ~(
            (req_has_any & jnp.any(over, axis=1))
            | ((cntN + 1) > allowed)
        )
        # PodTopologySpread: counts at this node's pairs drop by the
        # evicted matches; the global min is re-derived with this
        # node's (only-modified) pair re-entered at its adjusted value
        delta = ev_mfs - (nom_mfs if use_nom else 0)  # [N, C]
        pair_adj = shared_at - delta
        cnt_eff = jnp.where(reg_at, pair_adj, 0)
        min_eff = jnp.where(
            reg_at, jnp.minimum(min_excl, pair_adj), min1[None, :]
        )
        min_eff = jnp.where(min_eff == big, 0, min_eff)
        skew = cnt_eff + self_m[None, :] - min_eff
        fail_skew = jnp.any(
            f_valid[None, :] & key_on_f & (skew > f_skew[None, :]), axis=1
        )
        pts_ok = ~(any_f & (fail_missing | fail_skew))
        ok = static_gate & fit_ok & pts_ok
        if dyn_ipa:
            anti_adj = anti_eff - jnp.where(anti_key_on, ev_manti, 0)
            aff_adj = aff_eff - jnp.where(aff_key_on, ev_mall[:, None], 0)
            tot_adj = aff_total_eff - ev_mall * aff_keys_cnt
            if use_nom:
                anti_adj = anti_adj + jnp.where(anti_key_on, nom_manti, 0)
                aff_adj = aff_adj + jnp.where(
                    aff_key_on, nom_mall[:, None], 0
                )
                tot_adj = tot_adj + nom_mall * aff_keys_cnt
            fail_anti = jnp.any(
                anti_valid[None, :] & anti_key_on & (anti_adj > 0), axis=1
            )
            pods_exist = jnp.all(
                jnp.where(aff_valid[None, :], aff_adj > 0, True), axis=1
            )
            aff_ok = ~has_aff | (
                aff_all_keys
                & (pods_exist | ((tot_adj == 0) & self_match_all))
            )
            ok = ok & ~fail_anti & aff_ok
        return ok

    def feas(ev):
        ok = feas_one(ev, False)
        if has_nom:
            ok = ok & feas_one(ev, True)
        return ok

    n = v_valid.shape[0]
    L = v_valid.shape[1]
    zero_ev = (
        jnp.zeros_like(free0), jnp.zeros(n, _I64),
        jnp.zeros_like(shared_at), jnp.zeros_like(v_manti[:, 0]),
        jnp.zeros(n, _CNT),
    )
    fits_now = feas(zero_ev)
    all_ev = (
        jnp.sum(v_req, axis=1),
        jnp.sum(v_cnt, axis=1).astype(_I64),
        jnp.sum(v_mfs, axis=1),
        jnp.sum(v_manti, axis=1),
        jnp.sum(v_mall, axis=1).astype(_CNT),
    )
    base = feas(all_ev)

    def reprieve(state, l):
        ev_req, ev_cnt, ev_mfs, ev_manti, ev_mall = state
        valid_l = v_valid[:, l]
        cand = (
            ev_req - v_req[:, l],
            ev_cnt - v_cnt[:, l].astype(_I64),
            ev_mfs - v_mfs[:, l],
            ev_manti - v_manti[:, l],
            ev_mall - v_mall[:, l].astype(_CNT),
        )
        reprieved = feas(cand) & valid_l
        take = reprieved
        state = tuple(
            jnp.where(
                take.reshape((n,) + (1,) * (old.ndim - 1)), new, old
            )
            for old, new in zip(state, cand)
        )
        return state, valid_l & ~reprieved

    _, victims = jax.lax.scan(reprieve, all_ev, jnp.arange(L))
    return {
        "fits_now": fits_now,
        "base": base,
        "victims": jnp.transpose(victims),  # [N, L]
    }


@functools.partial(jax.jit, static_argnames=("tj", "dyn_ports"))
def _gang_fits_run(S: Dict, c_static: Dict, carry: Dict, k,
                   tj: int = 0, dyn_ports: bool = False):
    """Joint co-placement feasibility for k members of template tj as
    one positive-delta launch: per-node template MULTIPLICITY m_i = how
    many copies the node absorbs at once (min over checked dims of
    floor(free / req), capped by pod-count headroom, zeroed where the
    eviction-invariant static gate fails), feasible iff
    sum(min(m_i, k)) >= k.

    This is the gang-level upgrade of fits_now: k independent per-member
    fit checks all pass on a node with room for ONE member, yet the gang
    as a whole may not place — exactly the blind spot that lets two
    half-reserved gangs deadlock. Optimistic by design: affinity/spread
    couplings between the members themselves (and same-host-port
    members beyond the first) are not modeled, so False is definitive
    ("cannot place even ignoring inter-member constraints") while True
    means "capacity exists". The deadlock breaker wants exactly that
    polarity — it prefers backing off a gang whose demand provably
    exceeds the cluster."""

    def sel(key):
        return S[key][tj]

    req = sel("req")
    req_check = sel("req_check")
    free = c_static["alloc"] - carry["requested"]          # [N, R]
    headroom = (
        c_static["allowed_pods"] - carry["pod_count"].astype(_I64)
    )                                                      # [N]
    gate = sel("static_mask")
    if dyn_ports:
        gate = gate & K.ports_mask(
            carry["cp_any"], carry["cp_wild"], carry["cp_trip"],
            {p: sel(p) for p in _PORT_STEP_KEYS},
        )
    big = jnp.asarray(jnp.iinfo(_I64).max // 2, _I64)
    checked = req_check & (req > 0)
    per_dim = jnp.where(
        checked[None, :],
        jnp.floor_divide(free, jnp.where(checked, req, 1)[None, :])
        .astype(_I64),
        big,
    )                                                      # [N, R]
    m = jnp.minimum(jnp.min(per_dim, axis=1), headroom)    # [N]
    m = jnp.where(gate, jnp.maximum(m, 0), 0)
    return jnp.sum(jnp.minimum(m, k)) >= k


# ---------------------------------------------------------------------------
# context: the scratch snapshot the launches plan against


class WhatifUnavailable(RuntimeError):
    """The what-if path cannot serve this preemptor (template outside
    the session envelope, unencodable pod, node-table skew); the planner
    falls one rung to the numpy fast path or the oracle."""

    def __init__(self, message: str, reason: str = "context"):
        super().__init__(message)
        self.reason = reason


class WhatifContext:
    """One scratch what-if view of the cluster: session statics + a
    SCRATCH copy of the carry, plus the host-side numpy caches the
    per-preemptor tensor prep reads. Built from the live HoistedSession
    (zero uploads — the carry leaves are copied on-device, never
    donated) or from a non-donating encoding snapshot (the pallas /
    sharded sessions keep their carry in kernel-private scaled layouts;
    the host encoding is their exact state mirror after harvest, so the
    scratch hoisted view built from it scores the same cluster)."""

    def __init__(self, sess: HoistedSession, carry: Dict, node_names):
        self._sess = sess
        self.carry = carry
        self.node_names = list(node_names)
        self.n_lanes = int(carry["requested"].shape[0])
        self.fps = sess._fps
        self.dyn_ipa = sess._dyn_ipa
        self.dyn_ports = sess._dyn_ports
        self.tp_np = sess._tp_np  # match_matrices_np tables
        self._np_cache: Dict[int, Dict] = {}  # tj -> host-side slices
        self.vnp = int(np.asarray(sess._c_static["npair"]).shape[1])
        self._pok_np: Optional[np.ndarray] = None

    @classmethod
    def from_session(cls, sess: HoistedSession, node_names) -> "WhatifContext":
        carry = {k: jnp.array(v, copy=True) for k, v in sess._carry.items()}
        return cls(sess, carry, node_names)

    @classmethod
    def from_host_snapshot(cls, host: Dict, node_names,
                           pod_arrays: Dict, mesh=None) -> "WhatifContext":
        """Throwaway single-template hoisted view over a host-array
        snapshot (ClusterEncoding.host_snapshot). The snapshot is
        already a consistent copy, so the EXPENSIVE part — the device
        upload and the prologue build — runs outside the encoding
        owner's lock. Never touches the encoder's cached device dict
        (no donation) and never counts as a session build. With `mesh`,
        the snapshot is node-sharded first (parallel/sharded
        shard_cluster) so the scratch view's statics and carry inherit
        the mesh placement through GSPMD — at 100k nodes an unsharded
        what-if copy would replicate the full cluster on every host."""
        if mesh is not None:
            from ..parallel.sharded import shard_cluster

            cluster = shard_cluster(
                {k: np.asarray(a) for k, a in host.items()}, mesh)
        else:
            cluster = {k: jnp.asarray(a) for k, a in host.items()}
        sess = HoistedSession(cluster, [pod_arrays], multipod_k=1)
        return cls(sess, sess._carry, node_names)

    @classmethod
    def from_encoding(cls, enc, pod_arrays: Dict) -> "WhatifContext":
        """from_host_snapshot over the encoding's current state (single-
        threaded callers: tests, the probe)."""
        return cls.from_host_snapshot(
            enc.host_snapshot(), enc.node_names, pod_arrays)

    # -- host-side per-template slices -------------------------------------

    def pok_np(self) -> np.ndarray:
        if self._pok_np is None:
            self._pok_np = np.asarray(self._sess._c_static["pair_of_key"])
        return self._pok_np

    def template_index(self, pod_arrays: Dict) -> int:
        fp = template_fingerprint(pod_arrays)
        tj = self.fps.get(fp)
        if tj is None:
            raise WhatifUnavailable(
                "preemptor template not in the what-if view",
                reason="template",
            )
        return tj

    def np_slices(self, tj: int) -> Dict:
        got = self._np_cache.get(tj)
        if got is not None:
            return got
        sess = self._sess
        out = {
            "f_same_key": np.asarray(sess._S["f_same_key"])[tj],
            "f_pair_cn": np.asarray(sess._S["f_pair_cn"])[tj],
        }
        if self.dyn_ipa:
            for k in _TERM_SLICE_KEYS:
                out[k] = np.asarray(sess._tp[k])[tj]
        else:
            # term-free template: zero-width anti/aff tables
            out.update({
                "ipaaa_valid": np.zeros(1, bool),
                "ipaa_valid": np.zeros(1, bool),
                "ipaaa_key": np.zeros(1, np.int32),
                "ipaa_key": np.zeros(1, np.int32),
            })
        self._np_cache[tj] = out
        return out

    def run(self, tj: int, v, nom, pre):
        """Launch the fused what-if program; returns device arrays
        (caller bounds the wait and decodes). v/nom/pre are dicts of
        numpy tensors shaped as _whatif_run documents."""
        from ..utils import devtime
        sess = self._sess
        if devtime.enabled():
            # Measured path: the launch is synchronous (block_until_ready
            # inside the record window) so submit→ready is device time,
            # not host wall-clock to the first decode. Decision-inert:
            # the caller's watchdog wait then sees an already-ready tree.
            lt = devtime.launch(
                "kernel", "whatif", tj=tj,
                h2d_bytes=devtime.payload_bytes((v, nom, pre)))
            ys = self._run_impl(tj, v, nom, pre, sess)
            # ktpu: allow-sync(devtime fence: whatif launch is timed end-to-end inside its measurement window)
            jax.block_until_ready(ys)
            lt.done(d2h_bytes=devtime.payload_bytes(ys))
            return ys
        return self._run_impl(tj, v, nom, pre, sess)

    def _run_impl(self, tj: int, v, nom, pre, sess):
        # singleton slots: count == validity (one member per slot)
        v_cnt = v.get("cnt")
        if v_cnt is None:
            v_cnt = np.asarray(v["valid"]).astype(np.int64)
        return _whatif_run(
            sess._S, sess._c_static, self.carry,
            jnp.asarray(v["valid"]), jnp.asarray(v_cnt),
            jnp.asarray(v["req"]), jnp.asarray(v["mfs"]),
            jnp.asarray(v["manti"]), jnp.asarray(v["mall"]),
            jnp.asarray(nom["req"]), jnp.asarray(nom["cnt"]),
            jnp.asarray(nom["mfs"]), jnp.asarray(nom["manti"]),
            jnp.asarray(nom["mall"]),
            jnp.asarray(pre["req"]), jnp.asarray(pre["cnt"]),
            jnp.asarray(pre["shared"]), jnp.asarray(pre["anti"]),
            jnp.asarray(pre["aff"]), jnp.asarray(pre["atot"]),
            tj=tj, dyn_ipa=self.dyn_ipa, dyn_ports=self.dyn_ports,
            has_nom=bool(nom["has_nom"]),
        )

    def gang_fits(self, tj: int, k: int) -> bool:
        """Can k members of template tj co-place right now? One launch
        over the scratch carry (_gang_fits_run); optimistic on
        inter-member couplings — see the kernel docstring."""
        if k <= 1:
            k = 1
        out = _gang_fits_run(
            self._sess._S, self._sess._c_static, self.carry,
            jnp.asarray(k, _I64), tj=tj, dyn_ports=self.dyn_ports,
        )
        return bool(out)


def slot_bucket(n_slots: int) -> int:
    """Pow2 victim-slot bucket (min 4): every distinct L is a fresh XLA
    compile of the reprieve scan, and production victim counts are
    ragged."""
    return batch_bucket(max(n_slots, 1), minimum=4)
