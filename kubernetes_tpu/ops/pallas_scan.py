"""Pallas mega-kernel for the hoisted scheduling session: the WHOLE batch
scan runs as ONE kernel launch with the carry held in registers.

Why: the tunnel runtime pays a fixed cost per fused-kernel launch, and
the lax.scan step compiles to dozens of fusions — per-pod cost ~1ms
regardless of the math (PERF_NOTES.md). Inside one pallas kernel the
per-op cost is VPU cycles, so a fori_loop over pods turns 1024 steps x
~25 launches into ONE launch.

Design notes (vs ops/hoisted.py _step, whose semantics this mirrors):

- **int64-free**: Mosaic has no 64-bit types. Resource quantities
  (milli-CPU, memory bytes, ...) are rescaled per dimension by the GCD
  of every value in the session. This is EXACT, not approximate: the
  fit comparisons, least-allocated's `(cap-req)*100 // cap`, and
  balanced's fractions are invariant under a common rescale (floors of
  equal rationals are equal). Falls back (PallasUnsupported) if the
  rescaled magnitudes overflow the int32 headroom.
- **gather-free PTS counts**: pair-count tables [C, Vnp] (Vnp ~ 11k,
  dominated by per-node hostname pairs) become (a) per-node count rows
  for constraints whose pairs are node-distinct (hostname), and (b)
  compact Vz<=128-lane tables for shared-value keys (zone, ...), with a
  static one-hot [N, Vz] so count-to-node expansion and scored-set
  registration are MXU matvecs instead of gathers (unsupported in
  Mosaic).
- float64 score math (PTS topology weights, IPA/balanced normalization)
  runs in float32 in-kernel. Decision parity with the f64 path is pinned
  by tests on every workload shape we ship; divergence is only possible
  where two nodes' scores straddle an f32 rounding boundary, in which
  case either choice is a max-score node.
- jnp.argmax tie semantics (first max) are reproduced manually (min
  index among maxima) — Mosaic's argmax lane order is unspecified.

Reference frame: same as ops/hoisted.py — this replaces
findNodesThatPassFilters + RunScorePlugins (generic_scheduler.go:235,
framework.go:723) for template-stamped batchable pods, restructured as a
single accelerator program.
"""

from __future__ import annotations

import functools
import math
import os as _os
import time as _time
from typing import Dict, List, NamedTuple, Optional

from ..utils import knobs

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .hoisted import (
    _session_prologue,
    _stack_templates,
    match_matrices_np,
    template_fingerprint,
    templates_have_ports,
    templates_have_terms,
)
from .kernel import DEFAULT_WEIGHTS, MAX_NODE_SCORE

VZ = 128          # compact pair-value lanes per shared-value key
LANE = 128
SUB = 8
POS_BIG = 2 ** 30
NEG_BIG = -(2 ** 30)

CARRY_KEYS = ("requested", "nzpc", "cnt_fn", "cnt_sn")

_MISSING = object()  # exec-cache sentinel (None = AOT failed, use jit)


class PallasUnsupported(Exception):
    """This cluster/template shape can't ride the pallas path; callers
    fall back to the jnp HoistedSession.

    `reason` is a FIXED slug per raise site (no interpolated shape
    numbers) — it feeds the scheduler_tpu_session_builds_total metric's
    reason label, where unbounded values would mint unbounded series."""

    def __init__(self, message: str, reason: str = "other"):
        super().__init__(message)
        self.reason = reason


def _ceil(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _pad_tc(a: np.ndarray, t_n: int) -> np.ndarray:
    """[T, X<=8] -> [T, 8] zero-padded (per-term scalar tables)."""
    out = np.zeros((t_n, SUB), a.dtype)
    out[:, : a.shape[1]] = a
    return out


def _pad2(a: np.ndarray, rows: int = SUB, lanes: int = LANE) -> np.ndarray:
    """Pad the last two dims up to multiples of (rows, lanes)."""
    r, c = a.shape[-2], a.shape[-1]
    widths = [(0, 0)] * (a.ndim - 2) + [
        (0, _ceil(r, rows) - r), (0, _ceil(c, lanes) - c)]
    return np.pad(a, widths)


def _gcd_all(*arrays) -> int:
    g = 0
    for a in arrays:
        for v in np.unique(np.abs(np.asarray(a, dtype=np.int64))):
            g = math.gcd(g, int(v))
            if g == 1:
                return 1
    return max(g, 1)


@functools.partial(jax.jit, static_argnames=("n",))
def _pack_group(n: int, *arrs):
    return jnp.concatenate([a.ravel() for a in arrs])


def _fetch_packed(tree: Dict) -> Dict:
    """Device->host fetch of a dict of device arrays in ONE transfer per
    dtype group. Fetching the ~80 prologue outputs one np.asarray at a
    time cost a 56ms tunnel round-trip EACH — 4.6s of every session
    rebuild was pure transfer latency."""
    items = [(k, v) for k, v in tree.items()]
    by_dtype: Dict = {}
    for k, v in items:
        by_dtype.setdefault(jnp.asarray(v).dtype, []).append(k)
    out: Dict = {}
    for dtype, keys in by_dtype.items():
        arrs = [jnp.asarray(tree[k]) for k in keys]
        packed = np.asarray(_pack_group(len(arrs), *arrs))
        off = 0
        for k, a in zip(keys, arrs):
            size = int(np.prod(a.shape)) if a.shape else 1
            out[k] = packed[off:off + size].reshape(a.shape)
            off += size
    return out


def batch_prologue(fps: Dict, tp_np: Dict, pod_arrays_list: List[Dict],
                   minimum: int, require_unbound: bool = True):
    """Shared host-side batch prep for the session schedule paths
    (PallasSession.schedule, _dispatch_mode, ShardedPallasSession):
    pow2 length bucket (each distinct Bp is a fresh compile; production
    batches are ragged), template ids, and the match matrices — computed
    on HOST (match_matrices_np): an on-device compute + readback here
    would wait out the previous batch's scan and kill the
    dispatch/harvest overlap. Returns (Bp, tmpl[Bp], mfa, msa)."""
    from .hoisted import batch_bucket

    B = len(pod_arrays_list)
    Bp = batch_bucket(B, minimum=minimum)
    tmpl = np.zeros(Bp, np.int32)
    for i, pa in enumerate(pod_arrays_list):
        if require_unbound and bool(np.asarray(pa["has_node_name"])):
            raise ValueError("session pods must be unbound")
        tmpl[i] = fps[template_fingerprint(pa)]
    mfa, msa = match_matrices_np(tp_np, pod_arrays_list)
    return Bp, tmpl, mfa, msa


@functools.partial(jax.jit, donate_argnums=(0,))
def _carry_delta_scan(carry, prow_f, prow_s, src_rows, perno_rows, xs):
    """Apply a batch of cluster-event deltas to a pallas-layout carry in
    ONE fused launch (shared by PallasSession and the sharded mirror —
    the math is layout-identical, only Np differs). Each event is the
    jnp twin of the kernel's _apply_updates with `best := node` and a
    sign folded into the payload: utilization columns plus the same-pair
    count masks (prow == prow[:, node], -1 lanes never update, exactly
    the kernel's gating), with cnt_sn's perno/src factor reproduced
    verbatim. lax.scan keeps the launch count at ONE regardless of the
    event count; padding rows are node 0 with all-zero payloads."""

    def step(c, x):
        c = dict(c)
        n = x["node"]
        c["requested"] = c["requested"].at[:, n].add(x["dres"])
        c["nzpc"] = c["nzpc"].at[:, n].add(x["dnzpc"])
        pf_b = jax.lax.dynamic_index_in_dim(prow_f, n, axis=1)  # [TCp, 1]
        same_f = (prow_f == pf_b) & (prow_f >= 0)
        c["cnt_fn"] = c["cnt_fn"] + x["mf"][:, None] * same_f
        ps_b = jax.lax.dynamic_index_in_dim(prow_s, n, axis=1)
        same_s = (prow_s == ps_b) & (prow_s >= 0)
        src_b = jax.lax.dynamic_index_in_dim(src_rows, n, axis=1)
        factor = perno_rows + (1 - perno_rows) * src_b       # [TCp, 1]
        c["cnt_sn"] = c["cnt_sn"] + x["ms"][:, None] * factor * same_s
        return c, None

    carry, _ = jax.lax.scan(step, carry, xs)
    return carry


class _Cfg(NamedTuple):
    """Value-hashable kernel configuration — the ONLY static jit input.
    Sessions with equal shapes/weights share one compiled program; the
    cluster statics flow in as dynamic args (see _dispatch)."""

    shapes: tuple
    weights: tuple
    ur: int
    carry_keys: tuple
    interpret: bool
    mode: str = "full"  # full | eval | apply (see _build_kernel)
    mk: int = 1  # multi-pod step width (full mode only; pow2, <= 64)


class PallasSession:
    """HoistedSession-compatible API over the single-launch kernel.

    Semantics: identical to ops/hoisted.py HoistedSession (same
    prologue, same carry discipline) — parity pinned by
    tests/test_pallas_scan.py. Raises PallasUnsupported when the cluster
    shape needs a fallback (e.g. a shared-value topology key with more
    than 128 distinct values).
    """

    # KTPU_EXPLAIN: the Mosaic kernel's scan does not surface per-plugin
    # mask/score sections — explain mode rides the jnp hoisted session
    # (TPUBackend demotes with session_builds{reason="explain"})
    supports_explain = False

    @staticmethod
    def explain_payload(ys):
        return None

    def __init__(self, cluster: Dict, template_arrays_list: List[Dict],
                 weights: Optional[Dict[str, int]] = None,
                 interpret: bool = False,
                 multipod_k: Optional[int] = None):
        from .kernel import multipod_k as _resolve_mk

        # multi-pod scan steps (conflict-SUFFIX contract: the kernel
        # defers commits within a group, detects conflicts with the
        # shared algebra, and leaves the conflicted suffix uncommitted
        # + flagged in out row 3 for the backend's host replay).
        # KTPU_MULTIPOD_K=1 is the kill switch.
        self.multipod_k = _resolve_mk(multipod_k)
        if templates_have_ports(template_arrays_list):
            # the jnp HoistedSession carries host-port tables; the pallas
            # kernel does not (yet) — signal a fallback, not an error
            raise PallasUnsupported(
                "templates with host ports ride the jnp hoisted session",
                reason="host-ports",
            )
        # affinity-term templates ARE supported: the D1-D5 deltas
        # (ops/hoisted.py term-machinery block) ride per-(template, key)
        # per-node count carries updated with the same same-pair-mask
        # trick as the PTS counts — see _build_ipa below
        self.dyn_ipa = templates_have_terms(template_arrays_list)
        self.weights = dict(weights or DEFAULT_WEIGHTS)
        self.interpret = interpret
        self._fps = {
            template_fingerprint(t): i for i, t in enumerate(template_arrays_list)
        }
        # pad the template axis to a pow2 bucket (min 2) with inert
        # copies of template 0 (never referenced by a pod's tmpl index):
        # a workload introducing its 2nd..Nth template then reuses the
        # compiled program instead of paying a mid-window recompile —
        # the unschedulable-churn bench lost 21s of its 23s window to
        # exactly that rebuild
        from ..models.vocab import bucket_capacity

        Tb = bucket_capacity(len(template_arrays_list), minimum=2)
        template_arrays_list = list(template_arrays_list) + [
            template_arrays_list[0]
        ] * (Tb - len(template_arrays_list))
        # first-max tie-break + score output rely on f32-exact totals:
        # every plugin score is <= MAX_NODE_SCORE after normalization
        if sum(abs(int(v)) for v in self.weights.values()) \
                * (MAX_NODE_SCORE + 1) >= 2 ** 24:
            raise PallasUnsupported("weights too large for exact f32 totals",
                                    reason="weights-exceed-f32")
        tp = _stack_templates(template_arrays_list)
        self._tp = tp
        # numpy copies of the selector tables schedule() evaluates on
        # HOST per batch (match_matrices_np) — the jnp path would block
        # the dispatch behind the previous batch's scan (device stream
        # ordering), serializing the scheduler's 1-deep pipeline
        self._tp_np = {
            k: np.asarray(tp[k])
            for k in ("ptsf_op", "ptsf_rkey", "ptsf_pairs",
                      "ptss_op", "ptss_rkey", "ptss_pairs", "self_ns")
        }
        from .hoisted import TERM_NP_KEYS

        # delta classifier input (tpu_backend): a foreign pod matching a
        # template's own IPA terms perturbs the prologue statics, so its
        # event cannot ride the carry-delta path
        self._term_np = (
            {k: np.asarray(tp[k]) for k in TERM_NP_KEYS}
            if self.dyn_ipa else None
        )
        S = _fetch_packed(
            _session_prologue(cluster, tp, dyn_ipa=self.dyn_ipa)
        )
        c = _fetch_packed(cluster)
        self._build(c, S)
        self._ipa = self._build_ipa(c, S, tp) if self.dyn_ipa else None
        if self._ipa is not None:
            # SMEM scalar extension: [T,3] has_aff/self_match_all/aff_total,
            # then anti_valid/aff_valid [T,8] each, then the w45 GCD
            # scale (offsets in _build_kernel). The scale rides SMEM, not
            # the static config: sessions whose weights differ only by a
            # common factor share one compiled program.
            extra = np.concatenate([
                np.stack([
                    self._ipa["has_aff"], self._ipa["self_match_all"],
                    self._ipa["aff_total"],
                ], axis=1).reshape(-1),
                self._ipa["anti_valid"].reshape(-1),
                self._ipa["aff_valid"].reshape(-1),
                np.array([self._ipa["w45_scale"]]),
            ]).astype(np.int32)
            self._scalars = np.concatenate([self._scalars, extra])
        self._carry = None
        self._bundle = None
        # (Bp, mode) -> AOT-compiled executable (None = AOT unavailable,
        # dispatch through jit). Shared between the serving path and the
        # warm_buckets daemon thread; plain dict ops are GIL-atomic and a
        # rare duplicate compile is absorbed by the persistent cache.
        self._exec: Dict = {}

    # -- host-side prologue remap ------------------------------------------

    def _build(self, c: Dict, S: Dict) -> None:
        T, N = S["static_mask"].shape
        C = S["f_valid"].shape[1]
        self.T, self.C, self.N = T, C, N
        Np = _ceil(N, LANE)
        self.Np = Np
        CP = SUB  # constraint rows padded to 8 per template: dynamic
        # (CP, Np) block reads at t*CP are provably 8-aligned for Mosaic
        if C > CP:
            raise PallasUnsupported(f"{C} constraints > {CP} per template",
                                    reason="too-many-constraints")
        TC = T * C
        TCp = T * CP
        self.CP = CP
        self.TCp = TCp
        R = c["alloc"].shape[1]
        self.R = R
        tp = self._tp

        # ---- exact per-dimension GCD rescale to int32 ----
        alloc = c["alloc"].astype(np.int64).T.copy()            # [R, N]
        requested = c["requested"].astype(np.int64).T.copy()
        req = np.asarray(tp["req"]).astype(np.int64)            # [T, R]
        nz_requested = c["nz_requested"].astype(np.int64).T.copy()  # [2, N]
        nz_req = np.asarray(tp["nz_req"]).astype(np.int64)      # [T, 2]
        # per-dimension rescale factors survive the build: incoming
        # session deltas (tpu_backend carry patches) must divide by the
        # SAME gcd to stay exact — an indivisible delta is classified
        # structural instead (delta_compatible)
        self._gcd = np.ones(R, np.int64)
        for r in range(R):
            extra = [nz_requested[r], nz_req[:, r]] if r < 2 else []
            g = _gcd_all(alloc[r], requested[r], req[:, r], *extra)
            self._gcd[r] = g
            alloc[r] //= g
            requested[r] //= g
            req[:, r] //= g
            if r < 2:
                nz_requested[r] //= g
                nz_req[:, r] //= g
        hi = max((int(a.max(initial=0)) for a in
                  (alloc, requested, req, nz_requested, nz_req)), default=0)
        if hi * (MAX_NODE_SCORE + 1) >= 2 ** 31:
            raise PallasUnsupported(
                f"rescaled resource magnitude {hi} too large for int32",
                reason="resource-magnitude")

        self._alloc = _pad2(alloc.astype(np.int32))             # [Rp, Np]
        self._requested0 = _pad2(requested.astype(np.int32))
        nzpc = np.zeros((SUB, N), np.int64)
        nzpc[0] = nz_requested[0]
        nzpc[1] = nz_requested[1]
        nzpc[2] = c["pod_count"].astype(np.int64)
        nzpc[3] = c["allowed_pods"].astype(np.int64)
        self._nzpc0 = _pad2(nzpc.astype(np.int32))              # [8, Np]
        self._req_s = req.astype(np.int32)
        self._nz_req_s = nz_req.astype(np.int32)
        self._req_check_s = np.asarray(tp["req_check"]).astype(np.int32)
        self._req_has_any_s = np.asarray(tp["req_has_any"]).astype(np.int32)

        # ---- per-template [T, N] statics: row t*SR+i ----
        stat_rows = [
            S["static_mask"], S["raw_ipa"], S["cnt_taint"],
            S["cnt_nodeaff"], S["sc_image"], S["sc_avoid"],
            np.zeros_like(S["static_mask"]), S["s_src"],
        ]
        if any(np.abs(a.astype(np.int64)).max(initial=0) >= POS_BIG
               for a in stat_rows):
            # POS_BIG (2^30), not 2^31: the kernel's min/max sentinels must
            # stay strictly above any genuine value
            raise PallasUnsupported("static score magnitude exceeds sentinel",
                                    reason="score-magnitude")
        SR = len(stat_rows)  # == 8
        self.SR = SR
        stat = np.stack([a.astype(np.int32) for a in stat_rows], axis=1)
        self._stat = _pad2(stat.reshape(T * SR, N))             # [T*SR, Np]

        # ---- PTS: per-constraint representation ----
        valid_nodes = c["valid"].astype(bool)

        def col(side, t, cc):
            return S[f"{side}_pair_cn"][t, :, cc]

        def node_distinct(column):
            real = column[valid_nodes]
            return len(real) == 0 or len(np.unique(real)) == len(real)

        uid_of: Dict[bytes, int] = {}
        uids: List[np.ndarray] = []

        def classify(side, force_host=None, intern=True):
            """-> (keyid [T,C], perno [T,C] bool): perno = per-node count
            representation; otherwise compact key `keyid`. With
            intern=False only perno is computed (the filter path works
            entirely per-node and must not consume the key/value budgets
            that exist for score-side registration)."""
            keyid = np.full((T, C), -1, np.int32)
            perno = np.zeros((T, C), bool)
            for t in range(T):
                for cc in range(C):
                    if not S[f"{side}_valid"][t, cc]:
                        continue
                    column = col(side, t, cc)
                    is_host = (force_host[t, cc] if force_host is not None
                               else node_distinct(column))
                    if is_host:
                        perno[t, cc] = True
                        continue
                    if not intern:
                        continue
                    key = column.tobytes()
                    u = uid_of.get(key)
                    if u is None:
                        u = len(uids)
                        uid_of[key] = u
                        uids.append(column.copy())
                    keyid[t, cc] = u
            return keyid, perno

        # score side MUST follow the prologue's hostname flag (it selects
        # the log(n_scored) weight semantics, not just a representation)
        s_hostflag = S["s_hostname"].astype(bool)
        fk, fh = classify("f", intern=False)
        sk, sh = classify("s", force_host=s_hostflag)
        # a non-hostname score constraint whose pairs are node-distinct
        # would blow the 128-lane vocab — unsupported
        self._f_keyid, self._f_perno = fk, fh
        self._s_keyid, self._s_perno = sk, sh

        K = max(len(uids), 1)
        if len(uids) > 4:
            raise PallasUnsupported(f"{len(uids)} distinct shared-value keys",
                                    reason="too-many-topology-keys")
        self.K = K
        onehot = np.zeros((K, Np, VZ), np.float32)
        zof: List[Dict[int, int]] = []
        for u, column in enumerate(uids):
            vals = np.unique(column[valid_nodes])
            vals = vals[vals > 0]
            if len(vals) > VZ:
                raise PallasUnsupported(
                    f"topology key {u} has {len(vals)} values > {VZ}",
                    reason="too-many-topology-values")
            m = {int(v): z for z, v in enumerate(vals)}
            zof.append(m)
            zid = np.array([m.get(int(v), -1) for v in column], np.int32)
            ok = (zid >= 0) & valid_nodes
            onehot[u, np.arange(N)[ok], zid[ok]] = 1.0
        self._onehot = onehot

        def gather_rows(side, cnt_tcv, perno, perno_src=None):
            """[T, C, Vnp] pair counts -> per-NODE count rows [TCp, Np]:
            row (t*CP+c), lane n = count of the pair node n belongs to."""
            out = np.zeros((TCp, Np), np.int32)
            for t in range(T):
                for cc in range(C):
                    row = t * CP + cc
                    if perno[t, cc] and perno_src is not None:
                        out[row, :N] = perno_src[t, cc]
                    else:
                        out[row, :N] = cnt_tcv[t, cc][col(side, t, cc)]
            return out

        self._cnt_fn0 = gather_rows("f", S["f_cnt0"], fh)
        self._cnt_sn0 = gather_rows(
            "s", S["s_cnt0"], sh,
            perno_src=S["h_cnt0"].astype(np.int64))

        # static per-node structures
        prow_f = np.full((TCp, Np), -1, np.int32)
        prow_s = np.full((TCp, Np), -1, np.int32)
        regrow_f = np.zeros((TCp, Np), np.int32)
        zvalid_node_s = np.zeros((TCp, Np), np.int32)
        zvalid_s = np.zeros((TCp, VZ), np.int32)
        for t in range(T):
            for cc in range(C):
                row = t * CP + cc
                if S["f_valid"][t, cc]:
                    column = col("f", t, cc)
                    prow_f[row, :N] = np.where(valid_nodes, column, -1)
                    regrow_f[row, :N] = S["f_reg_real"][t, cc][column]
                if S["s_valid"][t, cc]:
                    column = col("s", t, cc)
                    prow_s[row, :N] = np.where(valid_nodes, column, -1)
                    if not sh[t, cc] and sk[t, cc] >= 0:
                        zvalid_node_s[row, :N] = (column > 0) & valid_nodes
                        for pair, zz in zof[sk[t, cc]].items():
                            zvalid_s[row, zz] = 1
        self._prow_f = prow_f
        self._prow_s = prow_s
        self._regrow_f = regrow_f
        self._zvalid_node_s = zvalid_node_s
        self._zvalid_s = zvalid_s
        if max(prow_f.max(), prow_s.max()) >= 2 ** 24:
            raise PallasUnsupported("pair ids exceed exact-f32 range",
                                    reason="pair-ids-exceed-f32")

        def tcn(a):  # [T, N, C] bool -> [TCp, Np] i32 (stride CP)
            out = np.zeros((TCp, Np), np.int32)
            for t in range(T):
                for cc in range(C):
                    out[t * CP + cc, :N] = a[t, :, cc]
            return out

        self._konn_f = tcn(S["f_key_on_node"])
        self._konn_s = tcn(S["s_key_on_node"])
        # session-delta statics: row-expanded s_src (score-count node
        # eligibility per row's template) and the per-row perno flag —
        # the jnp twin of the kernel's _apply_updates factor, used by
        # apply_deltas to patch cnt_sn exactly as an in-scan assume would
        src_rows = np.zeros((TCp, Np), np.int32)
        perno_rows = np.zeros((TCp, 1), np.int32)
        for t in range(T):
            for cc in range(C):
                src_rows[t * CP + cc, :N] = S["s_src"][t].astype(np.int32)
                perno_rows[t * CP + cc, 0] = int(self._s_perno[t, cc])
        self._src_rows = src_rows
        self._perno_rows = perno_rows
        self._delta_statics = None  # device copies, built on first apply
        sha = np.zeros((_ceil(T, SUB), Np), np.int32)
        sha[:T, :N] = S["s_has_all"].astype(np.int32)
        self._shasall = sha
        vn = np.zeros((SUB, Np), np.int32)
        vn[:, :N] = c["valid"].astype(np.int32)[None, :]
        self._valid_n = vn

        # row -> template one-hot [T, TCp, VZ] and identity [TCp, LANE]
        if TCp > LANE:
            raise PallasUnsupported(f"T*CP={TCp} exceeds {LANE} match lanes",
                                    reason="too-many-match-lanes")
        rowt = np.zeros((T, TCp, VZ), np.int32)
        for t in range(T):
            rowt[t, t * CP:t * CP + C, :] = 1
        self._rowt = rowt
        # identity mapping match-lane (t*CP+cc) -> row (t*CP+cc)
        eye = np.zeros((TCp, LANE), np.float32)
        for i in range(TCp):
            if i < LANE:
                eye[i, i] = 1.0
        self._eye = eye

        # multipod IPA interference superset (filled by _build_ipa when
        # the session carries term templates; zeros otherwise): row u,
        # lane t != 0 means assuming a template-u pod can perturb a
        # template-t evaluation through the D1-D5 term machinery — the
        # multipod conflict test then replays instead of speculating
        self._gmat = np.zeros((_ceil(T, SUB), LANE), np.float32)

        # SMEM scalar table
        self._scalars = self._pack_scalars(S)

    # ktpu: allow-sync(session build: one-time host packing of affinity planes, runs before first dispatch)
    def _build_ipa(self, c: Dict, S: Dict, tp: Dict) -> Dict:
        """InterPodAffinity term machinery for the single-launch kernel.

        The hoisted scan's D1-D5 deltas (ops/hoisted.py term-machinery
        block) all reduce to per-(assumed-template u, topology key ki)
        counts gathered at each node's (ki, value) group. The pallas port
        keeps those counts PER NODE (the same representation trick as the
        PTS cnt_fn/cnt_sn rows): carry row (u*8 + ki) of `ucnt` holds,
        for every node n, the number of session-assumed u-pods in n's
        ki-group — updated on assume with a same-pair mask from `prow_ipa`
        (pair id per node per key; -1 where the node lacks the key, which
        makes the nkey gating implicit: rows never accumulate on keyless
        nodes). `kcnt` row (u*8+ki) carries the scalar total (lanes all
        equal). Every D1-D5 read then becomes a STATIC gate/weight matrix
        (template x term match booleans from _term_gates, resolved host-
        side) times ucnt — one MXU dot each:
          D1 fail-existing  : g1[t] . (ucnt > 0) > 0
          D2 own-anti counts: wanti[t-block] @ ucnt  (+ static anti rows)
          D3 own-aff counts : waff[t-block] @ ucnt   (+ static aff rows)
          D4+D5 score       : w45[t] @ ucnt  (weights pre-folded)
          presence flags    : gpres[t] . rowany(ucnt > 0)
          aff_total delta   : w3tot[t] . kcnt[:, 0]
        Exactness: counts are integers in f32 (exact < 2^24); the 0/1
        dots are bounded by 8 * count; the score dot is guarded below.
        """
        T, N, Np = self.T, self.N, self.Np
        aa_key = np.asarray(tp["ipaaa_key"])
        aa_valid = np.asarray(tp["ipaaa_valid"]).astype(bool)
        a_key = np.asarray(tp["ipaa_key"])
        a_valid = np.asarray(tp["ipaa_valid"]).astype(bool)
        p_key = np.asarray(tp["ipap_key"])
        p_valid = np.asarray(tp["ipap_valid"]).astype(bool)
        p_w = np.asarray(tp["ipap_weight"]).astype(np.int64)
        if aa_key.shape[1] > SUB or a_key.shape[1] > SUB:
            raise PallasUnsupported(
                f"{max(aa_key.shape[1], a_key.shape[1])} required "
                f"(anti-)affinity terms > {SUB} per template",
                reason="too-many-ipa-terms")
        # distinct topology keys across every template's valid terms
        keys: set = set()
        for k_tbl, v_tbl in ((aa_key, aa_valid), (a_key, a_valid),
                             (p_key, p_valid)):
            keys.update(int(x) for x in k_tbl[v_tbl])
        ki_list = sorted(keys)
        if len(ki_list) > SUB:
            raise PallasUnsupported(
                f"{len(ki_list)} IPA topology keys > {SUB}",
                reason="too-many-ipa-keys")
        ki_of = {k: i for i, k in enumerate(ki_list)}
        UR = T * SUB  # ucnt rows: (u * 8 + ki)
        # rough VMEM budget: Np-wide blocks (anti/aff statics + ucnt +
        # prow/ipa_stat) plus the T^2-scaling gate/weight matrices and
        # the kcnt carry must not blow the 16MB scope
        np_rows = 3 * T * SUB + UR + SUB + _ceil(2 * T, SUB)
        t2_bytes = (2 * (T * SUB) * UR + 4 * _ceil(T, SUB) * UR
                    + UR * LANE) * 4
        if np_rows * Np * 4 + t2_bytes > 8 * 2 ** 20:
            raise PallasUnsupported("IPA blocks exceed the VMEM budget",
                                    reason="ipa-vmem-budget")

        pok = c["pair_of_key"].astype(np.int64)  # [N, K]
        nkey = c["nkey"].astype(bool)
        valid_nodes = c["valid"].astype(bool)
        prow_ipa = np.full((SUB, Np), -1, np.int32)
        for i, key in enumerate(ki_list):
            ok = nkey[:, key] & valid_nodes
            prow_ipa[i, :N] = np.where(ok, pok[:, key], -1)
        if prow_ipa.max(initial=0) >= 2 ** 24:
            raise PallasUnsupported("IPA pair ids exceed exact-f32 range",
                                    reason="pair-ids-exceed-f32")

        M_anti = np.asarray(S["M_anti"]).astype(bool)   # [T, TAA, T]
        M_aff = np.asarray(S["M_aff"]).astype(bool)     # [T, TA, T]
        M_pref = np.asarray(S["M_pref"]).astype(bool)   # [T, TP, T]
        match_all = np.asarray(S["match_all"]).astype(bool)  # [T, T]
        hard_w = int(np.asarray(c["hard_pod_affinity_weight"]))

        # multipod template-interference superset (the host twin of the
        # hoisted prologue's G_ipa; symmetrized — a false positive only
        # costs a replay, never a wrong decision)
        a1 = M_anti.any(axis=1)
        a2 = M_aff.any(axis=1)
        a3 = M_pref.any(axis=1)
        g = (a1 | a1.T | a2 | a2.T | a3 | a3.T | match_all | match_all.T)
        self._gmat[:T, :T] = g.astype(np.float32)

        t_pad = _ceil(T, SUB)  # per-template matrices: row t (T can be >8)
        g1 = np.zeros((t_pad, UR), np.float32)
        wanti = np.zeros((T * SUB, UR), np.float32)
        waff = np.zeros((T * SUB, UR), np.float32)
        w3tot = np.zeros((t_pad, UR), np.float32)
        w45_i = np.zeros((t_pad, UR), np.int64)
        gpres = np.zeros((t_pad, UR), np.float32)

        def cx(u, key):
            return u * SUB + ki_of[int(key)]

        for t in range(T):
            # D1: assumed u-pods' anti terms repel t where t matches them
            for u in range(T):
                for tau in range(aa_key.shape[1]):
                    if aa_valid[u, tau] and M_anti[u, tau, t]:
                        g1[t, cx(u, aa_key[u, tau])] = 1.0
            # D2: assumed pods counting toward t's own anti terms
            for tau in range(aa_key.shape[1]):
                if not aa_valid[t, tau]:
                    continue
                for u in range(T):
                    if M_anti[t, tau, u]:
                        wanti[t * SUB + tau, cx(u, aa_key[t, tau])] = 1.0
            # D3: assumed pods matching ALL of t's affinity terms
            for tau in range(a_key.shape[1]):
                if not a_valid[t, tau]:
                    continue
                for u in range(T):
                    if match_all[t, u]:
                        waff[t * SUB + tau, cx(u, a_key[t, tau])] = 1.0
                        w3tot[t, cx(u, a_key[t, tau])] += 1.0
            # D4: assumed pods' score terms vs t (required-aff at
            # hardPodAffinityWeight; preferred at signed weight) and
            # D5: t's own preferred terms vs assumed pods
            for u in range(T):
                for tau in range(a_key.shape[1]):
                    if a_valid[u, tau] and M_aff[u, tau, t] and hard_w > 0:
                        w45_i[t, cx(u, a_key[u, tau])] += hard_w
                        gpres[t, cx(u, a_key[u, tau])] = 1.0
                for tau in range(p_key.shape[1]):
                    if p_valid[u, tau] and M_pref[u, tau, t]:
                        w45_i[t, cx(u, p_key[u, tau])] += int(p_w[u, tau])
                        gpres[t, cx(u, p_key[u, tau])] = 1.0
                for tau in range(p_key.shape[1]):
                    if p_valid[t, tau] and M_pref[t, tau, u]:
                        w45_i[t, cx(u, p_key[t, tau])] += int(p_w[t, tau])
                        gpres[t, cx(u, p_key[t, tau])] = 1.0
        # score-dot exactness: |w|.sum * count must stay < 2^24 in f32.
        # Weights first shed their common GCD (the kernel multiplies the
        # int32 dot result back by w45_scale): the harness's weight-100
        # preferred-affinity templates (sum|w| 300) ride the kernel as
        # sum|w/g| 3 instead of downgrading to the hoisted session —
        # the Preferred-affinity configs' silent ~4x slow path.
        w45_scale = _gcd_all(w45_i)
        w45_i //= w45_scale
        # with the scaled dot cast to int32 BEFORE the multiply, only
        # the dot itself must be exact: cap session assumed counts at
        # 2^16 (far above any bench window) -> sum|w/g| < 2^8
        scaled_sum = int(np.abs(w45_i).sum(axis=1).max(initial=0))
        if scaled_sum >= 256:
            raise PallasUnsupported(
                "IPA score weights too large for exact f32 dot",
                reason="ipa-score-weights")
        # ... and the RESTORED magnitude must keep int32 headroom: the
        # multiply-back delta (scale * scaled-sum * count) has to stay
        # clear of the 2^30 score sentinel at the same 2^16 count cap,
        # or raw_ipa's int32 add could wrap for extreme weight mixes
        # (e.g. {100, 25400}: gcd 100, scaled sum 255) that the
        # pre-scale guard used to reject outright
        if w45_scale * scaled_sum >= 2 ** 14:
            raise PallasUnsupported(
                "IPA score weights too large for int32 score headroom",
                reason="ipa-score-weights")

        # static per-term per-node blocks (rows t*8+term)
        anti_static = np.zeros((T * SUB, Np), np.int32)
        anti_konn = np.zeros((T * SUB, Np), np.int32)
        aff_static = np.zeros((T * SUB, Np), np.int32)
        anti_cnt_n = np.asarray(S["ipa_anti_cnt_n"])    # [T, N, TAA]
        anti_kon = np.asarray(S["ipa_anti_key_on_node"])
        aff_cnt_n = np.asarray(S["ipa_aff_cnt_n"])      # [T, N, TA]
        for t in range(T):
            for tau in range(aa_key.shape[1]):
                anti_static[t * SUB + tau, :N] = anti_cnt_n[t, :, tau]
                anti_konn[t * SUB + tau, :N] = anti_kon[t, :, tau]
            for tau in range(a_key.shape[1]):
                aff_static[t * SUB + tau, :N] = aff_cnt_n[t, :, tau]
        # per-template per-node statics (rows t*2 / t*2+1)
        ipa_stat = np.zeros((_ceil(2 * T, SUB), Np), np.int32)
        fe = np.asarray(S["ipa_fail_existing"])         # [T, N]
        aak = np.asarray(S["ipa_aff_all_keys"])
        for t in range(T):
            ipa_stat[2 * t, :N] = fe[t]
            ipa_stat[2 * t + 1, :N] = aak[t]
        if max(int(anti_static.max(initial=0)),
               int(aff_static.max(initial=0))) >= POS_BIG:
            raise PallasUnsupported("IPA static counts exceed sentinel",
                                    reason="score-magnitude")
        return dict(
            UR=UR,
            prow_ipa=prow_ipa, ipa_stat=ipa_stat,
            anti_static=anti_static, anti_konn=anti_konn,
            aff_static=aff_static,
            g1=g1, wanti=wanti, waff=waff, w3tot=w3tot,
            w45=w45_i.astype(np.float32), w45_scale=w45_scale, gpres=gpres,
            # SMEM scalar extension: per-t has_aff/self_match_all/
            # aff_total + per-term valid flags
            has_aff=np.asarray(S["ipa_has_aff"]).astype(np.int32),
            self_match_all=np.asarray(
                S["ipa_self_match_all"]).astype(np.int32),
            aff_total=np.asarray(S["ipa_aff_total"]).astype(np.int32),
            anti_valid=_pad_tc(aa_valid.astype(np.int32), T),
            aff_valid=_pad_tc(a_valid.astype(np.int32), T),
        )

    # ktpu: allow-sync(session build: packs static scalar rows on host before upload)
    def _pack_scalars(self, S) -> np.ndarray:
        T, C, R = self.T, self.C, self.R
        # the sharded two-phase session (ops/sharded_scan.py) reads these
        # as structured tables instead of SMEM offsets
        self._sc_tables = {
            k: np.asarray(S[k]).copy()
            for k in ("f_valid", "s_valid", "f_skew", "s_skew",
                      "f_self_match", "s_first", "f_same_key", "s_same_key",
                      "ipa_present")
        }
        per_t = np.concatenate([
            self._req_s, self._req_check_s,
            self._req_has_any_s[:, None], self._nz_req_s,
            S["ipa_present"].astype(np.int32)[:, None]], axis=1)  # [T, 2R+4]
        tc = np.stack([
            S["f_valid"].astype(np.int32), S["s_valid"].astype(np.int32),
            S["f_skew"].astype(np.int32), S["s_skew"].astype(np.int32),
            S["f_self_match"].astype(np.int32), S["s_first"].astype(np.int32),
            self._f_keyid, self._s_keyid,
            self._f_perno.astype(np.int32), self._s_perno.astype(np.int32),
        ], axis=0)  # [10, T, C]
        return np.concatenate([
            per_t.reshape(-1), tc.reshape(-1),
            S["f_same_key"].astype(np.int32).reshape(-1),
            S["s_same_key"].astype(np.int32).reshape(-1),
        ]).astype(np.int32)

    # -- scheduling --------------------------------------------------------

    def _initial_carry(self):
        z = jnp.asarray
        carry = {
            "requested": z(self._requested0), "nzpc": z(self._nzpc0),
            "cnt_fn": z(self._cnt_fn0), "cnt_sn": z(self._cnt_sn0),
        }
        if self._ipa is not None:
            # session starts with zero ASSUMED pods (existing pods live in
            # the static tables) — mirrors _init_dynamic_carries
            carry["ucnt"] = jnp.zeros((self._ipa["UR"], self.Np), jnp.int32)
            carry["kcnt"] = jnp.zeros((self._ipa["UR"], LANE), jnp.int32)
        return carry

    def _get_bundle(self):
        """(cfg, statics, ipa) for _dispatch: cfg is the value-hashed
        static config; statics/ipa are device-resident dynamic args."""
        if self._bundle is None:
            z = jnp.asarray
            ipa = None
            carry_keys = CARRY_KEYS
            if self._ipa is not None:
                ipa = {
                    k: z(self._ipa[k])
                    for k in ("ipa_stat", "anti_static", "anti_konn",
                              "aff_static", "prow_ipa", "g1", "wanti",
                              "waff", "w3tot", "w45", "gpres")
                }
                carry_keys = CARRY_KEYS + ("ucnt", "kcnt")
            statics = {
                "alloc": z(self._alloc), "stat": z(self._stat),
                "onehot": z(self._onehot), "regrow_f": z(self._regrow_f),
                "zvalid_node_s": z(self._zvalid_node_s),
                "zvalid_s": z(self._zvalid_s),
                "konn_f": z(self._konn_f), "konn_s": z(self._konn_s),
                "shasall": z(self._shasall), "valid_n": z(self._valid_n),
                "rowt": z(self._rowt), "eye": z(self._eye),
                "prow_f": z(self._prow_f), "prow_s": z(self._prow_s),
                "gmat": z(self._gmat),
                "scalars": z(self._scalars),
            }
            cfg = _Cfg(
                shapes=(self.T, self.C, self.Np, self.R, self.SR,
                        self.TCp, self.K, self.CP),
                weights=tuple(sorted(self.weights.items())),
                ur=(self._ipa["UR"] if self._ipa else 0),
                carry_keys=carry_keys,
                interpret=self.interpret,
                mk=self.multipod_k,
            )
            self._bundle = (cfg, statics, ipa)
        return self._bundle

    def _pack_batch(self, B, Bp, tmpl, mfa, msa):
        """Per-batch host->device payload as TWO arrays instead of four
        (B_real, tmpl, mfT, msT): each transfer over the tunnel carries
        fixed latency, and the per-dispatch payload is part of the ~580ms
        fixed cost PERF_NOTES tracks. meta = [B_real | tmpl]; match lanes
        (t*CP+c) = that constraint row per pod, filter block then score
        block — int8 on the wire (weights are 0/1), widened on-device."""
        T, C, CP = self.T, self.C, self.CP
        meta = np.empty(1 + Bp, np.int32)
        meta[0] = B
        meta[1:] = tmpl
        match = np.zeros((Bp, 2 * LANE), np.int8)
        for t in range(T):
            match[:B, t * CP:t * CP + C] = mfa[t].reshape(B, C)
            match[:B, LANE + t * CP:LANE + t * CP + C] = msa[t].reshape(B, C)
        return meta, match

    def schedule(self, pod_arrays_list: List[Dict]):
        """Enqueue one batch; returns the (8, Bp) device result rows —
        row 0 best / row 1 score / row 2 n_feasible. decisions() blocks."""
        B = len(pod_arrays_list)
        Bp, tmpl, mfa, msa = batch_prologue(
            self._fps, self._tp_np, pod_arrays_list, minimum=LANE)
        meta, match = self._pack_batch(B, Bp, tmpl, mfa, msa)
        out = self._run_dispatch(meta, match)
        # bucket rides the result so a harvest-side device fault can
        # retire exactly the executable that produced the bad payload
        # (tpu_backend.py retry path)
        return {"rows": out, "n": B, "bucket": Bp, "mk": self.multipod_k}

    @staticmethod
    # ktpu: allow-sync(harvest decode: host consumes batch verdicts after the launch completes)
    def decisions(ys) -> List[int]:
        return [int(v) for v in np.asarray(ys["rows"])[0, :ys["n"]]]

    @staticmethod
    # ktpu: allow-sync(harvest decode: host reads conflict planes after the launch completes)
    def conflict_stats(ys):
        """(n_conflicts, replay_suffix_start) from out row 3: the kernel
        leaves the conflicted suffix UNCOMMITTED (flag 1) — the backend
        replays exactly those pods through the session, whose carry
        holds the committed prefix. n_conflicts is 1 — ONE detection
        headed the suffix; the flags after it are collateral (the
        kernel cannot know which of them would conflict against the
        replayed carry), and any genuine later conflict is re-detected
        — and re-counted — when the replayed suffix runs. (0, None)
        when the batch ran one-pod-per-step (row 3 is the -1 init
        then)."""
        if ys.get("mk", 1) <= 1:
            return 0, None
        flags = np.asarray(ys["rows"])[3, :ys["n"]] > 0
        if not flags.any():
            return 0, None
        return 1, int(np.argmax(flags))

    def retire_exec(self, bucket: Optional[int] = None,
                    mode: Optional[str] = None) -> int:
        """Retire AOT executables after a device fault: a dispatch that
        raised, wedged, or harvested garbage leaves its compiled program
        suspect. Entries are pinned to None (= dispatch through jit), the
        same retired state the arg-mismatch path uses — warm_buckets
        never resurrects a retired entry, and _run_dispatch never
        recompiles one. With `bucket` given, absent entries are pinned
        too: the backend quarantines a suspect bucket on every REBUILT
        session (the _exec cache dies with its session, but the fault
        does not), and lifts it only after the bucket harvests cleanly
        through jit. bucket/mode both None retires every existing
        entry. Returns the number of entries pinned."""
        n = 0
        modes = (mode,) if mode is not None else ("full", "eval", "apply")
        if bucket is not None:
            for m in modes:
                if self._exec.get((bucket, m), _MISSING) is not None:
                    self._exec[(bucket, m)] = None
                    n += 1
            return n
        for key in list(self._exec):
            if mode is not None and key[1] != mode:
                continue
            if self._exec.get(key) is not None:
                self._exec[key] = None
                n += 1
        return n

    # -- incremental device-state deltas -----------------------------------

    def delta_compatible(self, dres, dnz) -> bool:
        """A utilization delta rides this session's int32 carry only when
        the build-time per-dimension GCD rescale stays exact on it and
        the rescaled magnitudes keep the int32 headroom the build
        guaranteed."""
        dres = np.asarray(dres, np.int64)
        if dres.shape[0] != self._gcd.shape[0]:
            return False
        if (dres % self._gcd != 0).any():
            return False
        dnz = np.asarray(dnz, np.int64)
        if (dnz % self._gcd[:2] != 0).any():
            return False
        hi = max(
            int(np.abs(dres // self._gcd).max(initial=0)),
            int(np.abs(dnz // self._gcd[:2]).max(initial=0)),
        )
        return hi * (MAX_NODE_SCORE + 1) < 2 ** 31

    def _delta_rows(self, d) -> tuple:
        """One backend delta dict -> (node, dres[Rp] scaled, dnzpc[8],
        mf[TCp], ms[TCp]) in this session's carry layout."""
        rp = self._requested0.shape[0]
        dres = np.zeros(rp, np.int32)
        dnzpc = np.zeros(SUB, np.int32)
        mf_rows = np.zeros(self.TCp, np.int32)
        ms_rows = np.zeros(self.TCp, np.int32)
        if d["kind"] == "node-alloc":
            dnzpc[3] = d["dallowed"]
        else:
            dres[: self.R] = (
                np.asarray(d["dres"], np.int64) // self._gcd
            ).astype(np.int32)
            dnzpc[0] = int(d["dnz"][0]) // int(self._gcd[0])
            dnzpc[1] = int(d["dnz"][1]) // int(self._gcd[1])
            dnzpc[2] = d["dcount"]
            for t in range(self.T):
                mf_rows[t * self.CP: t * self.CP + self.C] = d["mf"][t]
                ms_rows[t * self.CP: t * self.CP + self.C] = d["ms"][t]
        return d["node"], dres, dnzpc, mf_rows, ms_rows

    def _patch_alloc_static(self, d) -> None:
        """node-alloc prologue patch: the static alloc columns move (the
        prologue never reads alloc, so nothing else needs recompute).
        The CUMULATIVE rescaled magnitude must keep the int32 headroom
        the build guaranteed — delta_compatible bounds one delta, not
        the sum of many capacity bumps — so the patched column is
        re-checked and an overflow raises (the backend's apply wrapper
        downgrades to a rebuild, whose own envelope then decides)."""
        scaled = (np.asarray(d["dalloc"], np.int64) // self._gcd).astype(
            np.int32)
        n = d["node"]
        col = self._alloc[: self.R, n].astype(np.int64) + scaled
        if int(np.abs(col).max(initial=0)) * (MAX_NODE_SCORE + 1) >= 2 ** 31:
            raise ValueError(
                "cumulative alloc patches exceed the int32 score headroom")
        self._alloc[: self.R, n] += scaled
        if self._bundle is not None:
            cfg, statics, ipa = self._bundle
            statics = dict(statics)
            statics["alloc"] = statics["alloc"].at[:self.R, n].add(
                jnp.asarray(scaled))
            self._bundle = (cfg, statics, ipa)

    def apply_deltas(self, deltas: List[Dict]) -> None:
        """Absorb batched cluster-event deltas into the carry (and the
        alloc statics) without a session rebuild — the pallas face of
        the session-delta contract (see HoistedSession.apply_deltas).
        With no dispatch yet (carry unmaterialized) the numpy seed
        arrays are patched host-side; otherwise one fused
        _carry_delta_scan launch chains onto the in-flight carry."""
        for d in deltas:
            if d["kind"] == "node-alloc":
                self._patch_alloc_static(d)
        rows = [self._delta_rows(d) for d in deltas]
        if self._carry is None:
            for n, dres, dnzpc, mf_rows, ms_rows in rows:
                self._requested0[:, n] += dres
                self._nzpc0[:, n] += dnzpc
                same_f = (
                    (self._prow_f == self._prow_f[:, n][:, None])
                    & (self._prow_f >= 0)
                )
                self._cnt_fn0 += mf_rows[:, None] * same_f
                same_s = (
                    (self._prow_s == self._prow_s[:, n][:, None])
                    & (self._prow_s >= 0)
                )
                factor = (
                    self._perno_rows
                    + (1 - self._perno_rows) * self._src_rows[:, n][:, None]
                )
                self._cnt_sn0 += ms_rows[:, None] * factor * same_s
            return
        e = len(rows)
        from .hoisted import batch_bucket

        ep = batch_bucket(e, minimum=8)  # pow2: one compile per bucket
        xs = {
            "node": np.zeros(ep, np.int32),
            "dres": np.zeros((ep, self._requested0.shape[0]), np.int32),
            "dnzpc": np.zeros((ep, SUB), np.int32),
            "mf": np.zeros((ep, self.TCp), np.int32),
            "ms": np.zeros((ep, self.TCp), np.int32),
        }
        for i, (n, dres, dnzpc, mf_rows, ms_rows) in enumerate(rows):
            xs["node"][i] = n
            xs["dres"][i] = dres
            xs["dnzpc"][i] = dnzpc
            xs["mf"][i] = mf_rows
            xs["ms"][i] = ms_rows
        if self._delta_statics is None:
            self._delta_statics = {
                "prow_f": jnp.asarray(self._prow_f),
                "prow_s": jnp.asarray(self._prow_s),
                "src_rows": jnp.asarray(self._src_rows),
                "perno_rows": jnp.asarray(self._perno_rows),
            }
        ds = self._delta_statics
        self._carry = _carry_delta_scan(
            self._carry, ds["prow_f"], ds["prow_s"], ds["src_rows"],
            ds["perno_rows"], {k: jnp.asarray(v) for k, v in xs.items()},
        )

    # -- dispatch plumbing: persistent executables ------------------------

    def _carry_struct(self) -> Dict:
        """ShapeDtypeStructs of the carry, WITHOUT touching self._carry:
        warm_buckets runs on a daemon thread concurrently with
        schedule() — a warm-thread write of self._carry would silently
        zero the assumes of any batch dispatched in between."""
        structs = {
            "requested": jax.ShapeDtypeStruct(
                self._requested0.shape, jnp.int32),
            "nzpc": jax.ShapeDtypeStruct(self._nzpc0.shape, jnp.int32),
            "cnt_fn": jax.ShapeDtypeStruct(self._cnt_fn0.shape, jnp.int32),
            "cnt_sn": jax.ShapeDtypeStruct(self._cnt_sn0.shape, jnp.int32),
        }
        if self._ipa is not None:
            structs["ucnt"] = jax.ShapeDtypeStruct(
                (self._ipa["UR"], self.Np), jnp.int32)
            structs["kcnt"] = jax.ShapeDtypeStruct(
                (self._ipa["UR"], LANE), jnp.int32)
        return structs

    def _compile_exec(self, Bp: int, mode: str = "full"):
        """AOT lower+compile the dispatch for one (batch bucket, mode).
        The compiled executable is invoked DIRECTLY on the serving path
        (persistent executable reuse): every dispatch then runs the same
        loaded program object — no jit-dispatch signature hashing, and no
        per-launch program re-resolution for the runtime to pay."""
        cfg, statics, ipa = self._get_bundle()
        if mode != "full":
            cfg = cfg._replace(mode=mode)

        def st(x):
            return jax.ShapeDtypeStruct(jnp.shape(x), jnp.asarray(x).dtype)

        statics_s = {k: st(v) for k, v in statics.items()}
        ipa_s = {k: st(v) for k, v in ipa.items()} if ipa else None
        args = [
            cfg, statics_s, ipa_s,
            jax.ShapeDtypeStruct((1 + Bp,), jnp.int32),
            self._carry_struct(),
            jax.ShapeDtypeStruct((Bp, 2 * LANE), jnp.int8),
        ]
        if mode == "apply":
            args.append(jax.ShapeDtypeStruct((2 * Bp,), jnp.int32))
        return _dispatch.lower(*args).compile()

    def _run_dispatch(self, meta: np.ndarray, match: np.ndarray,
                      mode: str = "full", forced=None):
        """Execute one dispatch through the persistent-executable cache
        (fallback: the plain jit path). Owns the carry swap — the carry
        buffers are donated to the launch and replaced by its outputs."""
        if self._carry is None:
            self._carry = self._initial_carry()
        Bp = int(meta.shape[0]) - 1
        meta = jnp.asarray(meta)
        match = jnp.asarray(match)
        key = (Bp, mode)
        fn = self._exec.get(key, _MISSING)
        if not knobs.get_bool("KTPU_PALLAS_AOT"):
            fn = None  # kill switch wins even over warm-installed execs
        elif fn is _MISSING:
            # Counted miss path: a dispatch-time compile is a stall the
            # device timeline must attribute (warm_buckets prefills are
            # deliberate and uncounted).
            from ..utils import devtime
            t0 = _time.perf_counter()
            try:
                fn = self._compile_exec(Bp, mode)
            except Exception:  # noqa: BLE001 — jit path still works
                fn = None
            self._exec[key] = fn
            if devtime.enabled():
                devtime.TIMELINE.compile_event(
                    "pallas-bucket", t0, _time.perf_counter() - t0,
                    bucket=Bp, mode=mode, ok=fn is not None)
        if fn is not None:
            args = [meta, self._carry, match]
            if mode == "apply":
                args.append(jnp.asarray(forced, jnp.int32))
            try:
                out, self._carry = fn(self._get_bundle()[1],
                                      self._get_bundle()[2], *args)
                return out
            except (TypeError, ValueError):
                # arg-structure/layout mismatch is raised BEFORE
                # execution (carry buffers untouched): retire this
                # executable and serve through jit from now on
                self._exec[key] = None
        cfg, statics, ipa = self._get_bundle()
        if mode != "full":
            cfg = cfg._replace(mode=mode)
        fv = None if forced is None else jnp.asarray(forced, jnp.int32)
        out, self._carry = _dispatch(
            cfg, statics, ipa, meta, self._carry, match, forced=fv)
        return out

    def warm_buckets(self, sizes=(LANE, 256, 512, 1024, 2048)) -> None:
        """AOT-compile the dispatch for the ragged-tail batch buckets
        WITHOUT dispatching: .lower().compile() populates jax's caches
        including the persistent one, so a mid-window first-tail-bucket
        batch pays a cache hit instead of a fresh ~30s Mosaic compile (a
        gang rep that drained into a never-seen bucket measured 160
        pods/s against its siblings' 1300). Compiled executables land in
        self._exec, so the serving path reuses the very same loaded
        program. Runs on a daemon thread: it must NEVER write
        self._carry (a mid-warm schedule() would have its batch's
        assumes silently zeroed by the overwrite) — all shapes come from
        _carry_struct. Failures are non-fatal (the lazy path works)."""
        aot = knobs.get_bool("KTPU_PALLAS_AOT")
        for Bp in sizes:
            try:
                if (Bp, "full") in self._exec:
                    # present entries stand: a None means the serving
                    # path RETIRED this executable — do not resurrect it
                    continue
                compiled = self._compile_exec(Bp)
                # with the AOT kill switch set, warming still fills the
                # (persistent) compile caches, but the serving path must
                # keep dispatching through jit — don't install
                if aot:
                    self._exec[(Bp, "full")] = compiled
            except Exception:  # noqa: BLE001 — warming is best-effort
                return

    # -- split eval/apply (the sharded session's building blocks) ----------
    # A multi-chip session cannot let each shard apply its own local
    # best: the winner is a cross-shard argmax. These run the SAME
    # kernel in mode="eval" (masks/scores/local best, carries untouched)
    # and mode="apply" (commit externally-decided placements; off-shard
    # lanes no-op), so eval -> global argmax -> apply replays the full
    # kernel exactly (pinned by tests/test_pallas_scan.py
    # TestEvalApplySplit).

    def _dispatch_mode(self, pod_arrays_list, mode, forced=None):
        B = len(pod_arrays_list)
        Bp, tmpl, mfa, msa = batch_prologue(
            self._fps, self._tp_np, pod_arrays_list, minimum=LANE,
            require_unbound=False)
        meta, match = self._pack_batch(B, Bp, tmpl, mfa, msa)
        fvec = None
        if mode == "apply":
            fvec = np.zeros(2 * Bp, np.int32)
            for i, (lane, ok) in enumerate(forced):
                fvec[2 * i] = lane
                fvec[2 * i + 1] = ok
        out = self._run_dispatch(meta, match, mode=mode, forced=fvec)
        return {"rows": out, "n": B}

    def evaluate(self, pod_arrays_list: List[Dict]):
        """Local (best, score) per pod WITHOUT carry updates — every pod
        evaluated against the same carry state."""
        ys = self._dispatch_mode(pod_arrays_list, "eval")
        rows = np.asarray(ys["rows"])
        return [
            (int(rows[0, i]), int(rows[1, i])) for i in range(ys["n"])
        ]

    def apply_decisions(
        self, pod_arrays_list: List[Dict], decisions: List[int]
    ) -> None:
        """Commit placements (node lane or -1 = unplaced / off-shard)
        to the session carry."""
        forced = [(d if d >= 0 else -1, 1 if d >= 0 else 0)
                  for d in decisions]
        self._dispatch_mode(pod_arrays_list, "apply", forced=forced)


# ---------------------------------------------------------------------------
# kernel


def _build_kernel(shapes, weights, Bp: int, ur: int = 0,
                  mode: str = "full", mk: int = 1):
    """mode: "full" = eval + select + apply own decision (single-device
    session); "eval" = masks/scores/local-best only, carries untouched;
    "apply" = apply an externally-decided (cross-shard) placement to the
    carries. The sharded session alternates eval/apply around an ICI
    argmax (ShardedPallasSession).

    mk > 1 (full mode): multi-pod steps with exact conflict detection —
    mk pods are evaluated against the GROUP-START carry (their evals
    share no data dependency), then committed in order; a pod whose
    evaluation an earlier commit could have perturbed (same node, PTS
    match-gate, IPA template gate, or the fit/balanced/least recheck —
    the same algebra as ops/hoisted.py _step_multi) starts the CONFLICT
    SUFFIX: it and every later pod of the batch stay UNCOMMITTED, out
    row 3 flags them, and the host replays exactly that suffix through
    the session (tpu_backend._harvest_locked) — bit-identical to
    one-pod-per-step either way."""
    from ..utils import knobs as _knobs

    skip = frozenset(
        _knobs.get_str("KTPU_PALLAS_SKIP").split(","))  # profiling only
    T, C, Np, R, SR, TCp, K, CP = shapes
    W = dict(weights)
    dyn_ipa = ur > 0 and "ipa" not in skip
    row_len = 2 * R + 4
    off_tc = T * row_len
    off_fsame = off_tc + 10 * T * C
    off_ssame = off_fsame + T * C * C
    # IPA scalar extension (appended when the session has term templates)
    off_ipa_t = off_ssame + T * C * C
    off_av = off_ipa_t + 3 * T
    off_w45s = off_av + 2 * T * SUB  # w45 GCD scale (one scalar)
    (W_F_VALID, W_S_VALID, W_F_SKEW, W_S_SKEW, W_F_SELF, W_S_FIRST,
     W_F_KEY, W_S_KEY, W_F_PERNO, W_S_PERNO) = range(10)

    def kernel(*refs):
        forced_ref = None
        if mode == "apply":
            forced_ref = refs[0]  # SMEM [2*Bp]: (local lane | -1, ok)
            refs = refs[1:]
        (breal_ref, tmpl_ref, sc_ref, mf_ref, ms_ref,
         alloc_ref, stat_ref, onehot_ref, regrowf_ref, zvnode_ref,
         zvalid_ref, konnf_ref, konns_ref, shasall_ref, validn_ref,
         rowt_ref, eye_ref, prowf_ref, prows_ref, gmat_ref) = refs[:20]
        i = 20
        if ur > 0:
            (ipastat_ref, antic_ref, antik_ref, affc_ref, prowipa_ref,
             g1_ref, wanti_ref, waff_ref, w3tot_ref, w45_ref,
             gpres_ref) = refs[i:i + 11]
            i += 11
        ncarry = 6 if ur > 0 else 4
        carry_in = refs[i:i + ncarry]
        i += ncarry
        out_ref = refs[i]
        carry_refs = refs[i + 1:]
        requested_in, nzpc_in = carry_in[0], carry_in[1]
        requested_ref, nzpc_ref, cntfn_ref, cntsn_ref = carry_refs[:4]
        if ur > 0:
            ucnt_ref, kcnt_ref = carry_refs[4], carry_refs[5]
        # carries live in the OUTPUT refs (initialized from the inputs);
        # refs — unlike loop-carried values — support dynamic row reads
        for cin, cref in zip(carry_in, carry_refs):
            cref[:] = cin[:]
        out_ref[:] = jnp.full((SUB, Bp), -1, jnp.int32)

        sc = sc_ref
        f32 = jnp.float32

        def sm_t(t, i):
            return sc[t * row_len + i]

        def sm_tc(which, t, cc):
            return sc[off_tc + which * T * C + t * C + cc]

        def sm_fsame(t, ci, cj):
            return sc[off_fsame + (t * C + ci) * C + cj]

        def sm_ssame(t, ci, cj):
            return sc[off_ssame + (t * C + ci) * C + cj]

        def dotz(mat_1v, k):
            """(1, VZ) . onehot[k]^T -> (1, Np)."""
            return jax.lax.dot_general(
                mat_1v, onehot_ref[k], (((1,), (1,)), ((), ())),
                preferred_element_type=f32)

        def dotn(mat_1n, k):
            """(1, Np) . onehot[k] -> (1, VZ)."""
            return jax.lax.dot_general(
                mat_1n, onehot_ref[k], (((1,), (0,)), ((), ())),
                preferred_element_type=f32)

        def doth(a, b, dims):
            """Exact-f32 dot (counts/ids above 2^8 need HIGHEST)."""
            return jax.lax.dot_general(
                a, b, dims, preferred_element_type=f32,
                precision=jax.lax.Precision.HIGHEST)

        def sm_ipa_t(t, i):
            return sc[off_ipa_t + t * 3 + i]

        def sm_av(which, t, tau):
            return sc[off_av + which * T * SUB + t * SUB + tau]

        def _col_av(which, t):
            """(SUB, 1) f32 column of per-(t, term) valid flags."""
            i0 = jax.lax.broadcasted_iota(jnp.int32, (SUB, 1), 0)
            out = jnp.zeros((SUB, 1), f32)
            for tau in range(SUB):
                e = (i0 == tau).astype(f32)
                out = out + sm_av(which, t, tau).astype(f32) * e
            return out

        def _apply_updates(b, t, lane_n, best, oki, okf):
            """Carry updates for pod b landing on node lane `best` (all
            no-ops when best is off this kernel's node range — `hot` is
            then all-zero, which is exactly how the sharded session's
            non-owning shards stay consistent)."""
            hot = (lane_n == best).astype(jnp.int32) * oki   # (1, Np)
            hotf = hot.astype(f32)
            for r in range(R):
                requested_ref[r:r + 1, :] = (
                    requested_ref[r:r + 1, :] + hot * sm_t(t, r))
            nzpc_ref[0:1, :] = nzpc_ref[0:1, :] + hot * sm_t(t, 2 * R + 1)
            nzpc_ref[1:2, :] = nzpc_ref[1:2, :] + hot * sm_t(t, 2 * R + 2)
            nzpc_ref[2:3, :] = nzpc_ref[2:3, :] + hot

            # per-row match weights: column b of mf/ms via identity-dot
            mf_vec = mf_ref[pl.ds(b, 1), :].astype(f32)      # (1, LANE)
            ms_vec = ms_ref[pl.ds(b, 1), :].astype(f32)
            mf_col = jax.lax.dot_general(
                eye_ref[:], mf_vec, (((1,), (1,)), ((), ())),
                preferred_element_type=f32)                  # (TCp, 1)
            ms_col = jax.lax.dot_general(
                eye_ref[:], ms_vec, (((1,), (1,)), ((), ())),
                preferred_element_type=f32)

            # pair id at best, per row (one matvec each side); same-pair
            # lanes get the count delta — hostname rows degenerate to
            # same-NODE exactly like the pair-space update they mirror
            pf = prowf_ref[:].astype(f32)
            zb_f = jax.lax.dot_general(
                pf, hotf, (((1,), (1,)), ((), ())),
                preferred_element_type=f32,
                precision=jax.lax.Precision.HIGHEST)         # (TCp, 1)
            m_f = ((pf == zb_f) & (prowf_ref[:] >= 0)).astype(f32) * okf
            ps_ = prows_ref[:].astype(f32)
            zb_s = jax.lax.dot_general(
                ps_, hotf, (((1,), (1,)), ((), ())),
                preferred_element_type=f32,
                precision=jax.lax.Precision.HIGHEST)
            m_s = ((ps_ == zb_s) & (prows_ref[:] >= 0)).astype(f32) * okf

            # s_src factor at best per row's template (zone rows only; the
            # per-node/hostname update has no src gate, mirroring _step)
            srcrow = jnp.zeros((TCp, 1), f32)
            for tt in range(T):
                srow = stat_ref[pl.ds(tt * SR + 7, 1), :]
                v = jnp.sum(
                    jnp.where(lane_n == best, srow, jnp.int32(0)).astype(f32))
                srcrow = srcrow + rowt_ref[tt][:, 0:1].astype(f32) * v
            pernosel = _stack_tc(sm_tc, W_S_PERNO, T, C, TCp)             # (TCp, 1)
            factor = pernosel + (f32(1.0) - pernosel) * srcrow

            cntfn_ref[:] = (cntfn_ref[:].astype(f32)
                            + mf_col * m_f).astype(jnp.int32)
            cntsn_ref[:] = (cntsn_ref[:].astype(f32)
                            + ms_col * factor * m_s).astype(jnp.int32)

            if dyn_ipa:
                # the assumed pod joins its node's topology groups for
                # every IPA key the node carries: same-pair mask from
                # prow_ipa (-1 rows = node lacks key -> no-op), written
                # into template t's own 8-row ucnt block
                pi = prowipa_ref[:].astype(f32)                # (SUB, Np)
                zb_i = doth(pi, hotf, (((1,), (1,)), ((), ())))  # (SUB, 1)
                m_i = ((pi == zb_i)
                       & (prowipa_ref[:] >= 0)).astype(f32) * okf
                base_u = pl.multiple_of(t * SUB, SUB)
                ucnt_ref[pl.ds(base_u, SUB), :] = (
                    ucnt_ref[pl.ds(base_u, SUB), :].astype(f32) + m_i
                ).astype(jnp.int32)
                hask = doth((pi >= 0).astype(f32), hotf,
                            (((1,), (1,)), ((), ())))          # (SUB, 1)
                kcnt_ref[pl.ds(base_u, SUB), :] = (
                    kcnt_ref[pl.ds(base_u, SUB), :].astype(f32)
                    + hask * okf
                ).astype(jnp.int32)

        def fit_row(t):
            """NodeResourcesFit row against the CURRENT carry refs —
            shared by the eval and the multipod conflict recheck (the
            fit leg of kernel.multipod_utilization_conflicts)."""
            over = jnp.zeros((1, Np), jnp.bool_)
            for r in range(R):
                free = alloc_ref[r:r + 1, :] - requested_ref[r:r + 1, :]
                over = over | ((sm_t(t, r) > free) & (sm_t(t, R + r) != 0))
            fail_dims = (sm_t(t, 2 * R) != 0) & over
            fail_count = (nzpc_ref[2:3, :] + jnp.int32(1)) > nzpc_in[3:4, :]
            return jnp.logical_not(fail_count | fail_dims)

        def resource_rows(t):
            """(balanced, least) rows against the CURRENT carry refs —
            shared by the eval and the multipod wbl recheck."""
            nz_cpu = (nzpc_ref[0:1, :] + sm_t(t, 2 * R + 1)).astype(f32)
            nz_mem = (nzpc_ref[1:2, :] + sm_t(t, 2 * R + 2)).astype(f32)
            cap_cpu = alloc_ref[0:1, :].astype(f32)
            cap_mem = alloc_ref[1:2, :].astype(f32)
            frac_c = jnp.where(cap_cpu == 0, f32(1.0), nz_cpu / cap_cpu)
            frac_m = jnp.where(cap_mem == 0, f32(1.0), nz_mem / cap_mem)
            balanced = ((f32(1.0) - jnp.abs(frac_c - frac_m))
                        * MAX_NODE_SCORE).astype(jnp.int32)
            balanced = jnp.where((frac_c >= 1) | (frac_m >= 1),
                                 jnp.int32(0), balanced)

            def least_dim(cap, reqq):
                d = ((cap - reqq) * MAX_NODE_SCORE
                     // jnp.where(cap == 0, jnp.int32(1), cap))
                return jnp.where((cap == 0) | (reqq > cap), jnp.int32(0), d)

            least = (least_dim(alloc_ref[0:1, :],
                               nzpc_ref[0:1, :] + sm_t(t, 2 * R + 1))
                     + least_dim(alloc_ref[1:2, :],
                                 nzpc_ref[1:2, :] + sm_t(t, 2 * R + 2))
                     ) // jnp.int32(2)
            return balanced, least

        def lane_gate(which, t):
            """(1, LANE) gate over match lanes: 1.0 at lane (t*CP+c) for
            template t's VALID constraint slots — counts written to
            invalid slots are never read, so gating the multipod PTS
            conflict test on them is what makes it exact."""
            lanei1 = jax.lax.broadcasted_iota(jnp.int32, (1, LANE), 1)
            out = jnp.zeros((1, LANE), f32)
            for tt in range(T):
                sel = (t == tt).astype(f32)
                for cc in range(C):
                    e = (lanei1 == (tt * CP + cc)).astype(f32)
                    out = out + sel * sm_tc(which, tt, cc).astype(f32) * e
            return out

        def eval_pod(b):
            """Filter + score pod b against the CURRENT carry refs
            WITHOUT committing — the eval half of one_pod, reused by the
            multipod group body (where all mk pods run it against the
            group-start refs before any commit)."""
            t = tmpl_ref[b]
            # NOTHING big is hoisted out of the loop: values live across
            # iterations spill out of vector registers and the
            # spill/restore swamps the step (measured; see PERF_NOTES)
            lane_n = jax.lax.broadcasted_iota(jnp.int32, (1, Np), 1)
            valid_n = validn_ref[0:1, :]

            def trow(i):
                return stat_ref[pl.ds(t * SR + i, 1), :]

            static_mask = trow(0)
            raw_ipa = trow(1)
            cnt_taint = trow(2)
            cnt_nodeaff = trow(3)
            sc_image = trow(4)
            sc_avoid = trow(5)
            ipa_present = sm_t(t, 2 * R + 3)


            # ---- NodeResourcesFit (exact int32 after GCD rescale) ----
            mask_fit = fit_row(t)

            # ---- PTS filter (per-node counts; all C constraints as one
            # (C, Np) block — fewer dynamic reads, wider VPU ops) ----
            if "ptsf" in skip:
                fail_pts = jnp.zeros((1, Np), jnp.bool_)
            else:
                base = pl.multiple_of(t * CP, SUB)
                cntf = cntfn_ref[pl.ds(base, CP), :].astype(f32)   # (CP, Np)
                sameM = _sq_from_smem(sm_fsame, t, C, CP)          # (CP, CP)
                sh = jax.lax.dot_general(
                    sameM, cntf, (((1,), (0,)), ((), ())),
                    preferred_element_type=f32,
                    precision=jax.lax.Precision.HIGHEST)           # (CP, Np)
                reg = regrowf_ref[pl.ds(base, CP), :]
                big = f32(POS_BIG)
                min_c = jnp.min(jnp.where(reg != 0, sh, big),
                                axis=1, keepdims=True)             # (C, 1)
                min_c = jnp.where(min_c == big, f32(0.0), min_c)
                cnt_n = jnp.where(reg != 0, sh, f32(0.0))
                konn = konnf_ref[pl.ds(base, CP), :]
                vld = _col_tc(sm_tc, W_F_VALID, t, C, CP)      # (CP, 1)
                selfm = _col_tc(sm_tc, W_F_SELF, t, C, CP)
                maxskew = _col_tc(sm_tc, W_F_SKEW, t, C, CP)
                fail_missing = (vld != 0) & (konn == 0)
                skew = cnt_n + selfm - min_c
                fail_skew = (vld != 0) & (konn != 0) & (skew > maxskew)
                # axis-0 reduction via ones-dot (Mosaic can't lower
                # multi_reduction over the sublane axis here)
                onesC = jnp.ones((1, CP), f32)
                fail_pts = jax.lax.dot_general(
                    onesC, (fail_missing | fail_skew).astype(f32),
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=f32) > 0                # (1, Np)

            # ---- InterPodAffinity: static parts + assumed-pod counts
            # (D1-D3 of the hoisted term machinery as gate-matrix dots
            # over the per-node ucnt carry; see _build_ipa) ----
            if dyn_ipa:
                ucf = ucnt_ref[:].astype(f32)                  # (UR, Np)
                pos = (ucnt_ref[:] > 0).astype(f32)
                # D1: assumed pods' anti terms repel this pod
                g1row = g1_ref[pl.ds(t, 1), :]                 # (1, UR)
                fail1 = doth(g1row, pos, (((1,), (0,)), ((), ()))) > 0
                fe_static = ipastat_ref[pl.ds(2 * t, 1), :]
                aff_allk = ipastat_ref[pl.ds(2 * t + 1, 1), :]
                base8 = pl.multiple_of(t * SUB, SUB)
                # D2: assumed pods vs this pod's own anti terms
                anti_dyn = doth(wanti_ref[pl.ds(base8, SUB), :], ucf,
                                (((1,), (0,)), ((), ())))      # (SUB, Np)
                a_stat = antic_ref[pl.ds(base8, SUB), :].astype(f32)
                akonn = antik_ref[pl.ds(base8, SUB), :]
                avld = _col_av(0, t)                           # (SUB, 1)
                onesS = jnp.ones((1, SUB), f32)
                fail_anti_rows = ((avld != 0) & (akonn != 0)
                                  & ((a_stat + anti_dyn) > 0)).astype(f32)
                fail_anti = doth(onesS, fail_anti_rows,
                                 (((1,), (0,)), ((), ()))) > 0  # (1, Np)
                # D3: assumed pods matching ALL of this pod's aff terms
                aff_dyn = doth(waff_ref[pl.ds(base8, SUB), :], ucf,
                               (((1,), (0,)), ((), ())))
                f_stat = affc_ref[pl.ds(base8, SUB), :].astype(f32)
                fvld = _col_av(1, t)
                miss_rows = ((fvld != 0)
                             & ((f_stat + aff_dyn) <= 0)).astype(f32)
                pods_missing = doth(onesS, miss_rows,
                                    (((1,), (0,)), ((), ()))) > 0
                kc0 = kcnt_ref[:, 0:1].astype(f32)             # (UR, 1)
                w3row = w3tot_ref[pl.ds(t, 1), :]
                at_dyn = jnp.sum(doth(w3row, kc0, (((1,), (0,)), ((), ()))))
                counts_empty = (sm_ipa_t(t, 2).astype(f32) + at_dyn) == 0
                has_aff = sm_ipa_t(t, 0)
                smatch = sm_ipa_t(t, 1)
                aff_ok = ((has_aff == 0)
                          | ((aff_allk != 0)
                             & (jnp.logical_not(pods_missing)
                                | (counts_empty & (smatch != 0)))))
                mask_ipa = (jnp.logical_not((fe_static != 0) | fail1)
                            & jnp.logical_not(fail_anti) & aff_ok)
            else:
                mask_ipa = jnp.ones((1, Np), jnp.bool_)

            feasible = ((static_mask != 0) & mask_fit
                        & jnp.logical_not(fail_pts) & mask_ipa
                        & (valid_n != 0))
            n_feasible = jnp.sum(feasible.astype(f32)).astype(jnp.int32)

            # ---- resource scores ----
            balanced, least = resource_rows(t)

            # ---- PTS score ----
            shasall = shasall_ref[pl.ds(t, 1), :]
            scored = feasible & (shasall != 0)
            ignored = feasible & (shasall == 0)
            scored_f32 = scored.astype(f32)
            n_scored = jnp.sum(scored_f32)
            # zone-presence among scored nodes, per key: (1, VZ) and its
            # per-node expansion — the ONLY matvecs in the step
            zp = []
            zpn = []
            for k in range(K) if "zp" not in skip else ():
                p = (dotn(scored_f32, k) > 0).astype(f32)
                zp.append(p)
                zpn.append(dotz(p, k))
            if "zp" in skip:
                zp = [jnp.zeros((1, VZ), f32)] * K
                zpn = [jnp.zeros((1, Np), f32)] * K
            zval_l = None  # (set in the vectorized score block)
            if "ptss" in skip:
                raw = jnp.zeros((1, Np), f32)
                have_s = jnp.int32(0)
            else:
                base = pl.multiple_of(t * CP, SUB)
                cnts = cntsn_ref[pl.ds(base, CP), :].astype(f32)   # (CP, Np)
                sameS = _sq_from_smem(sm_ssame, t, C, CP)
                sh = jax.lax.dot_general(
                    sameS, cnts, (((1,), (0,)), ((), ())),
                    preferred_element_type=f32,
                    precision=jax.lax.Precision.HIGHEST)           # (CP, Np)
                vld = _col_tc(sm_tc, W_S_VALID, t, C, CP)      # (CP, 1)
                perno = _col_tc(sm_tc, W_S_PERNO, t, C, CP)
                key = _col_tc(sm_tc, W_S_KEY, t, C, CP)
                first = _col_tc(sm_tc, W_S_FIRST, t, C, CP)
                sskew = _col_tc(sm_tc, W_S_SKEW, t, C, CP)
                have_s = (jnp.sum(
                    jax.lax.dot_general(
                        jnp.ones((1, CP), f32), vld,
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=f32)) > 0).astype(jnp.int32)
                zval_l = zvalid_ref[pl.ds(base, CP), :].astype(f32)  # (CP, VZ)
                zval_n = zvnode_ref[pl.ds(base, CP), :]              # (CP, Np)
                topo = jnp.zeros((CP, 1), f32)
                regn = jnp.zeros((CP, Np), f32)
                for k in range(K):
                    use = (jnp.logical_not(perno != 0)
                           & (key == k)).astype(f32)               # (C, 1)
                    topo = topo + use * jnp.sum(zp[k] * zval_l, axis=1,
                                                keepdims=True)
                    regn = regn + use * zpn[k]
                regn = regn * (zval_n != 0)
                topo_size = jnp.where(first != 0, topo, f32(0.0))
                weight = jnp.log(jnp.where(perno != 0, n_scored, topo_size)
                                 + f32(2.0))                       # (C, 1)
                cnt_n = jnp.where(perno != 0, sh,
                                  jnp.where(regn > 0, sh, f32(0.0)))
                konn = konns_ref[pl.ds(base, CP), :]
                term = jnp.where(
                    (vld != 0) & (konn != 0),
                    cnt_n * weight + (sskew - f32(1.0)),
                    f32(0.0))
                raw = jax.lax.dot_general(
                    jnp.ones((1, CP), f32), term,
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=f32,
                    precision=jax.lax.Precision.HIGHEST)           # (1, Np)
            raw_i = raw.astype(jnp.int32)
            min_r = jnp.min(jnp.where(scored, raw_i, jnp.int32(POS_BIG)))
            max_r = jnp.max(jnp.where(scored, raw_i, jnp.int32(0)))
            min_r = jnp.where(min_r == POS_BIG, jnp.int32(0), min_r)
            norm = (MAX_NODE_SCORE * (max_r + min_r - raw_i)
                    // jnp.where(max_r == 0, jnp.int32(1), max_r))
            norm = jnp.where(max_r == 0, jnp.int32(MAX_NODE_SCORE), norm)
            norm = jnp.where(ignored, jnp.int32(0), norm)
            sc_pts = jnp.where(have_s != 0, norm, jnp.int32(0))

            # ---- IPA score: static raw + assumed-pod terms (D4+D5) ----
            if dyn_ipa:
                w45row = w45_ref[pl.ds(t, 1), :]
                dyn45 = doth(w45row, ucf, (((1,), (0,)), ((), ())))
                # the f32 dot ran on GCD-scaled weights (exactness needs
                # only sum|w/g| * count < 2^24); the int32 multiply
                # restores real magnitudes exactly
                raw_ipa = raw_ipa + dyn45.astype(jnp.int32) * sc[off_w45s]
                rowany = jnp.max(pos, axis=1, keepdims=True)   # (UR, 1)
                gp = gpres_ref[pl.ds(t, 1), :]
                pres_dyn = jnp.sum(
                    doth(gp, rowany, (((1,), (0,)), ((), ())))) > 0
                present = (ipa_present != 0) | pres_dyn
            else:
                present = ipa_present != 0

            # ---- IPA normalize ----
            min_i = jnp.min(jnp.where(feasible, raw_ipa, jnp.int32(POS_BIG)))
            max_i = jnp.max(jnp.where(feasible, raw_ipa, jnp.int32(NEG_BIG)))
            diff = (max_i - min_i).astype(f32)
            ipa = jnp.where(
                diff > 0,
                (MAX_NODE_SCORE * ((raw_ipa - min_i).astype(f32)
                                   / jnp.where(diff > 0, diff, f32(1.0))))
                .astype(jnp.int32),
                jnp.zeros((1, Np), jnp.int32))
            ipa = jnp.where(present, ipa, jnp.zeros((1, Np), jnp.int32))

            # ---- default-normalized taint / node-affinity ----
            def norm_default(counts, reverse):
                mx = jnp.max(jnp.where(feasible, counts, jnp.int32(0)))
                scaled = (MAX_NODE_SCORE * counts
                          // jnp.where(mx == 0, jnp.int32(1), mx))
                if reverse:
                    return jnp.where(mx == 0, jnp.int32(MAX_NODE_SCORE),
                                     jnp.int32(MAX_NODE_SCORE) - scaled)
                return jnp.where(mx == 0, counts, scaled)

            sc_taint = norm_default(cnt_taint, True)
            sc_nodeaff = norm_default(cnt_nodeaff, False)

            total = (balanced * W["balanced"] + sc_image * W["image"]
                     + ipa * W["ipa"] + least * W["least"]
                     + sc_nodeaff * W["node_affinity"]
                     + sc_avoid * W["prefer_avoid"]
                     + sc_pts * W["pts"] + sc_taint * W["taint"])
            total = jnp.where(feasible, total, jnp.int32(-1))

            # first-max (jnp.argmax tie semantics; exact — scores < 2^24)
            tf = total.astype(f32)
            m = jnp.max(tf)
            idx = jnp.where(tf >= m, lane_n, jnp.int32(POS_BIG))
            best = jnp.min(idx).astype(jnp.int32)
            ok = (m >= 0) & (b < breal_ref[0])
            wbl = balanced * W["balanced"] + least * W["least"]
            return t, lane_n, best, m, ok, n_feasible, total, wbl

        def one_pod(b):
            if mode == "apply":
                # forced decision (the cross-shard winner, mapped to this
                # shard's local lanes or -1): updates only, no eval
                t = tmpl_ref[b]
                lane_n = jax.lax.broadcasted_iota(jnp.int32, (1, Np), 1)
                best = forced_ref[2 * b]
                oki = forced_ref[2 * b + 1]
                okf = oki.astype(f32)
                _apply_updates(b, t, lane_n, best, oki, okf)
                return jnp.int32(0)
            t, lane_n, best, m, ok, n_feasible, total, wbl = eval_pod(b)
            oki = ok.astype(jnp.int32)
            okf = oki.astype(f32)

            if "updates" in skip or mode == "eval":
                # eval-only: best/score/feasible out, carries untouched
                # (the sharded session applies the GLOBAL decision in a
                # separate "apply" launch after the cross-shard argmax)
                subi0 = jax.lax.broadcasted_iota(jnp.int32, (SUB, Bp), 0)
                lanei0 = jax.lax.broadcasted_iota(jnp.int32, (SUB, Bp), 1)
                at_b0 = lanei0 == b
                o = out_ref[:]
                o = jnp.where(at_b0 & (subi0 == 0),
                              jnp.where(ok, best, jnp.int32(-1)), o)
                o = jnp.where(at_b0 & (subi0 == 1),
                              jnp.where(ok, m.astype(jnp.int32),
                                        jnp.int32(-1)), o)
                o = jnp.where(at_b0 & (subi0 == 2), n_feasible, o)
                out_ref[:] = o
                return jnp.int32(0)
            _apply_updates(b, t, lane_n, best, oki, okf)

            subi = jax.lax.broadcasted_iota(jnp.int32, (SUB, Bp), 0)
            lanei = jax.lax.broadcasted_iota(jnp.int32, (SUB, Bp), 1)
            at_b = lanei == b
            o = out_ref[:]
            o = jnp.where(at_b & (subi == 0),
                          jnp.where(ok, best, jnp.int32(-1)), o)
            o = jnp.where(at_b & (subi == 1),
                          jnp.where(ok, m.astype(jnp.int32), jnp.int32(-1)),
                          o)
            o = jnp.where(at_b & (subi == 2), n_feasible, o)
            out_ref[:] = o

        def write_multi(b, best, score, nfeas, okc, flag):
            """Out rows for one multipod-group pod: 0 best / 1 score /
            2 n_feasible / 3 conflict-suffix flag (1 = NOT committed,
            host must replay)."""
            subi = jax.lax.broadcasted_iota(jnp.int32, (SUB, Bp), 0)
            lanei = jax.lax.broadcasted_iota(jnp.int32, (SUB, Bp), 1)
            at_b = lanei == b
            placed = okc != 0
            o = out_ref[:]
            o = jnp.where(at_b & (subi == 0),
                          jnp.where(placed, best, jnp.int32(-1)), o)
            o = jnp.where(at_b & (subi == 1),
                          jnp.where(placed, score, jnp.int32(-1)), o)
            o = jnp.where(at_b & (subi == 2), nfeas, o)
            o = jnp.where(at_b & (subi == 3), flag, o)
            out_ref[:] = o

        def multi_group(j, seen):
            """mk pods per step: parallel-in-spirit evals against the
            group-start carry refs (commits are DEFERRED, so nothing a
            later eval reads has moved), then in-order commits gated by
            the exact conflict test. `seen` carries the suffix flag
            ACROSS groups: later groups' evals chained on a carry
            missing suffix commits are invalid too."""
            base = j.astype(jnp.int32) * jnp.int32(mk)
            evs = [eval_pod(base + jnp.int32(i)) for i in range(mk)]
            conf_seen = seen
            committed = []  # (best, okc, tmpl) of this group's prefix
            for i in range(mk):
                b = base + jnp.int32(i)
                t, lane_n, best, m, ok, nfeas, total, wbl = evs[i]
                score_i = jnp.max(total)  # int32 twin of the f32 argmax m
                conf = jnp.int32(0)
                if i > 0:
                    gate_f = lane_gate(W_F_VALID, t)
                    gate_s = lane_gate(W_S_VALID, t)
                for e, (be, oke, te) in enumerate(committed):
                    same = oke * ((be == best)
                                  & (m >= 0)).astype(jnp.int32)
                    # PTS: pod e's Mf/Ms lanes of template t, valid-gated
                    mf_e = mf_ref[pl.ds(base + jnp.int32(e), 1),
                                  :].astype(f32)
                    ms_e = ms_ref[pl.ds(base + jnp.int32(e), 1),
                                  :].astype(f32)
                    hit = (jnp.sum(mf_e * gate_f)
                           + jnp.sum(ms_e * gate_s)) > 0
                    conf = jnp.maximum(conf, jnp.maximum(
                        same, oke * hit.astype(jnp.int32)))
                    if ur > 0:
                        # IPA template-interference superset (gmat)
                        grow = gmat_ref[pl.ds(te, 1), :]
                        lanei1 = jax.lax.broadcasted_iota(
                            jnp.int32, (1, LANE), 1)
                        gv = jnp.sum(jnp.where(lanei1 == t, grow,
                                               f32(0.0)))
                        conf = jnp.maximum(
                            conf, oke * (gv > 0).astype(jnp.int32))
                # utilization legs (kernel.multipod_utilization_conflicts
                # mirrored in Mosaic): fit/balanced/least are the only
                # carry-reading plugins left once the count gates are
                # clean — recheck them against the CURRENT refs
                fit_new = fit_row(t)
                bal2, least2 = resource_rows(t)
                new_tot = total - wbl + (bal2 * W["balanced"]
                                         + least2 * W["least"])
                feas_old = total >= 0
                flip = jnp.max(jnp.where(
                    feas_old & jnp.logical_not(fit_new),
                    f32(1.0), f32(0.0))) > 0
                over = jnp.max(jnp.where(
                    feas_old & fit_new
                    & ((new_tot > score_i)
                       | ((new_tot == score_i) & (lane_n < best))),
                    f32(1.0), f32(0.0))) > 0
                util = (flip | (over & (m >= 0))).astype(jnp.int32)
                conf = jnp.maximum(conf, util)
                conf = conf * (b < breal_ref[0]).astype(jnp.int32)
                conf_seen = jnp.maximum(conf_seen, conf)
                okc = ok.astype(jnp.int32) * (jnp.int32(1) - conf_seen)
                _apply_updates(b, t, lane_n, best, okc, okc.astype(f32))
                committed.append((best, okc, t))
                write_multi(b, best, score_i, nfeas, okc, conf_seen)
            return conf_seen

        if mode == "full" and mk > 1 and "updates" not in skip:
            jax.lax.fori_loop(0, Bp // mk, multi_group, jnp.int32(0))
            return

        # manual unroll: U pods per loop iteration amortizes Mosaic's
        # per-iteration bookkeeping (the marginal-cost floor; partial
        # `unroll=` is unsupported by the TPU lowering). b >= B_real
        # iterations are no-ops via the ok gate.
        U = int(_knobs.get_int("KTPU_PALLAS_GROUP"))
        while Bp % U:
            U //= 2

        def body(j, _):
            base = j.astype(jnp.int32) * jnp.int32(U)
            for i in range(U):
                one_pod(base + jnp.int32(i))
            return jnp.int32(0)

        jax.lax.fori_loop(0, Bp // U, body, jnp.int32(0))

    return kernel


def _sq_from_smem(sm_pair, t, C, CP):
    """(CP, CP) f32 same-key matrix from SMEM scalars.

    Built as a sum of scalar x static-one-hot constants — Mosaic cannot
    shape-cast stacked scalars into 2D."""
    i0 = jax.lax.broadcasted_iota(jnp.int32, (CP, CP), 0)
    i1 = jax.lax.broadcasted_iota(jnp.int32, (CP, CP), 1)
    out = jnp.zeros((CP, CP), jnp.float32)
    for ci in range(C):
        for cj in range(C):
            e = ((i0 == ci) & (i1 == cj)).astype(jnp.float32)
            out = out + sm_pair(t, ci, cj).astype(jnp.float32) * e
    return out


def _col_tc(sm_tc, which, t, C, CP):
    """(CP, 1) f32 column of per-(t, c) SMEM scalars (one-hot sums)."""
    i0 = jax.lax.broadcasted_iota(jnp.int32, (CP, 1), 0)
    out = jnp.zeros((CP, 1), jnp.float32)
    for cc in range(C):
        e = (i0 == cc).astype(jnp.float32)
        out = out + sm_tc(which, t, cc).astype(jnp.float32) * e
    return out


def _stack_tc(sm_tc, which, T, C, TCp):
    """(TCp, 1) f32 from per-(t,c) SMEM scalars (one-hot sums)."""
    CP = TCp // T
    i0 = jax.lax.broadcasted_iota(jnp.int32, (TCp, 1), 0)
    out = jnp.zeros((TCp, 1), jnp.float32)
    for t in range(T):
        for cc in range(C):
            e = (i0 == (t * CP + cc)).astype(jnp.float32)
            out = out + (sm_tc(which, t, cc) != 0).astype(jnp.float32) * e
    return out


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("carry",))
def _dispatch(cfg: "_Cfg", statics: Dict, ipa: Optional[Dict],
              meta, carry: Dict, match, forced=None):
    # meta = [B_real | tmpl] (int32), match = [mfT | msT] (int8): the
    # whole per-batch payload in two transfers — the split happens here
    # on-device. B_real stays a DYNAMIC (SMEM) scalar: variable batch
    # lengths must not recompile the kernel (only the padded width Bp is
    # static). The cluster statics arrive as DYNAMIC pytree args, NOT
    # via the static cfg: baking them in as trace constants made every
    # session rebuild a fresh program (different constants -> jit cache
    # miss AND persistent-cache miss) — the 20-30s "warm" rebuild the
    # churn workload paid mid-window. cfg hashes by VALUE, so two
    # sessions with the same shapes share one compiled program.
    Bp = int(meta.shape[0]) - 1
    B_real = meta[:1]
    tmpl = meta[1:]
    kernel = _build_kernel(cfg.shapes, cfg.weights, Bp, cfg.ur,
                           mode=cfg.mode, mk=cfg.mk)
    # widen the int8 wire format on-device (i8 VMEM rows would need
    # 32-sublane alignment in the kernel; one cheap convert avoids that)
    mfT = match[:, :LANE].astype(jnp.int32)
    msT = match[:, LANE:].astype(jnp.int32)
    carry_keys = cfg.carry_keys
    carry_in = [carry[k] for k in carry_keys]
    ipa_in = []
    if ipa is not None:
        ipa_in = [ipa[k] for k in
                  ("ipa_stat", "anti_static", "anti_konn", "aff_static",
                   "prow_ipa", "g1", "wanti", "waff", "w3tot", "w45",
                   "gpres")]
    out_shape = (
        jax.ShapeDtypeStruct((SUB, Bp), jnp.int32),
        *[jax.ShapeDtypeStruct(x.shape, x.dtype) for x in carry_in],
    )
    vm = pl.BlockSpec(memory_space=pltpu.VMEM)
    sm = pl.BlockSpec(memory_space=pltpu.SMEM)
    pre_args: tuple = ()
    pre_specs: list = []
    if cfg.mode == "apply":
        pre_args = (forced.astype(jnp.int32),)
        pre_specs = [sm]
    n_pre = len(pre_specs) + 20 + len(ipa_in)  # inputs before the carries
    # trace the kernel with x64 OFF: every input is explicitly 32-bit,
    # and weak python literals must not widen ops to i64/f64 (Mosaic has
    # no 64-bit types)
    from jax._src.config import enable_x64 as _x64_ctx

    with _x64_ctx(False):
        results = pl.pallas_call(
            kernel,
            out_shape=out_shape,
            in_specs=(pre_specs + [sm, sm, sm, vm, vm] + [vm] * 15
                      + [vm] * len(ipa_in) + [vm] * len(carry_in)),
            out_specs=tuple([vm] * (1 + len(carry_in))),
            input_output_aliases={n_pre + i: 1 + i
                                  for i in range(len(carry_in))},
            interpret=cfg.interpret,
        )(*pre_args, B_real, tmpl, statics["scalars"], mfT, msT,
          statics["alloc"], statics["stat"], statics["onehot"],
          statics["regrow_f"], statics["zvalid_node_s"],
          statics["zvalid_s"], statics["konn_f"], statics["konn_s"],
          statics["shasall"], statics["valid_n"], statics["rowt"],
          statics["eye"], statics["prow_f"], statics["prow_s"],
          statics["gmat"], *ipa_in, *carry_in)
    return results[0], dict(zip(carry_keys, results[1:]))
