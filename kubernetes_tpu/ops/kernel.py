"""The fused scheduling kernel: one XLA dispatch filters and scores every
node for one pending pod.

This replaces the reference's two hot loops — findNodesThatPassFilters
(reference: pkg/scheduler/core/generic_scheduler.go:235, 16 goroutines,
adaptive node subsampling at :177) and RunScorePlugins
(pkg/scheduler/framework/runtime/framework.go:723) — with dense masked
arithmetic over the ClusterEncoding matrices. No subsampling: every node is
evaluated, removing the 5-50% scoring compromise the Go implementation
makes at 5k-node scale.

Every plugin of the default profile (reference:
pkg/scheduler/algorithmprovider/registry.go:71 getDefaultConfig) is
reproduced bit-exactly; see the per-section docstrings for the formula
provenance. Scores are int64 in [0,100] x weight (interface.go:95).

Outputs (dict):
  feasible[N]    final filter mask
  total[N]       weighted sum of normalized scores (int64)
  mask_*/score_* per-plugin masks and weighted normalized scores for
                 status reconstruction and oracle parity tests
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp

from ..models.encoding import (
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    EFFECT_PREFER_NO_SCHEDULE,
    ST_PREFERRED_AFFINITY,
    ST_PREFERRED_ANTI,
    ST_REQUIRED_AFFINITY,
)
from .eval import eval_reqs, eval_reqs_single, ns_member

MAX_NODE_SCORE = 100
MB = 1024 * 1024
MIN_IMG_THRESHOLD = 23 * MB  # image_locality.go:33
MAX_CONTAINER_THRESHOLD = 1000 * MB

# Default-profile score plugin weights
# (reference: pkg/scheduler/algorithmprovider/registry.go:110-131)
DEFAULT_WEIGHTS = {
    "balanced": 1,
    "image": 1,
    "ipa": 1,
    "least": 1,
    "node_affinity": 1,
    "prefer_avoid": 10000,
    "pts": 2,
    "taint": 1,
}

_I64 = jnp.int64
_F64 = jnp.float64
# Counting dtype for the pod-table sweeps (PTS/IPA pair counts, match
# sums). int64 is EMULATED on the TPU vector unit — the four
# affinity/topology sections dominated the fused step at ~12.6ms of
# 14.1ms per pod before this. Counts are bounded by the pod-table size
# and weighted sums by 100*weight*terms, so int32 holds them exactly and
# score parity with the int64 oracle is preserved; section outputs are
# cast back to int64 at the [N]-sized boundary.
_CNT = jnp.int32


def _seg_sum(data, segment_ids, num_segments):
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


def _seg_max_bool(flags, segment_ids, num_segments):
    return (
        jax.ops.segment_max(
            flags.astype(jnp.int32), segment_ids, num_segments=num_segments
        )
        > 0
    )


# ---------------------------------------------------------------------------
# Filters


def fit_mask(requested, pod_count, alloc, allowed_pods, req, req_check, req_has_any):
    """NodeResourcesFit (fit.go:230 fitsRequest): insufficient if
    request > allocatable − requested per checked dim, or pod count full.
    Shared by the generic kernel and the hoisted scan step."""
    free = alloc - requested
    over = (req[None, :] > free) & req_check[None, :]
    fail_dims = req_has_any & jnp.any(over, axis=1)
    fail_count = (pod_count.astype(_I64) + 1) > allowed_pods
    return ~(fail_count | fail_dims)


def ports_mask(pair_any, pair_wild, triple, p: Dict):
    """NodePorts conflict mask over the given port tables (reference:
    nodeports/node_ports.go HostPortInfo: a wildcard-ip want conflicts
    with any same (proto,port); a specific-ip want conflicts with a
    wildcard holder or the exact triple). Shared by the one-pod kernel
    (static cluster tables) and the hoisted scan step (carried tables) so
    the semantics cannot diverge."""
    pa = pair_any[:, p["want_pair"]] > 0     # [N, MP]
    pw = pair_wild[:, p["want_pair"]] > 0
    tr = triple[:, p["want_triple"]] > 0
    conflict = jnp.where(p["want_wild"][None, :], pa, pw | tr) & p["want_valid"][None, :]
    return ~jnp.any(conflict, axis=1)


def _filter_basics(c: Dict, p: Dict):
    """NodeName, NodeUnschedulable, TaintToleration, NodePorts,
    NodeResourcesFit masks. References: nodename/node_name.go,
    nodeunschedulable/node_unschedulable.go,
    tainttoleration/taint_toleration.go:55,
    nodeports/node_ports.go, noderesources/fit.go:230."""
    n = c["valid"].shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    mask_name = ~p["has_node_name"] | (idx == p["node_name_idx"])
    mask_unsched = ~(c["unschedulable"] & ~p["tolerates_unsched"])
    eff = c["taint_effect"][None, :]
    hard_taint = (eff == EFFECT_NO_SCHEDULE) | (eff == EFFECT_NO_EXECUTE)
    mask_taint = ~jnp.any(c["taints"] & hard_taint & ~p["tol_ns"][None, :], axis=1)
    mask_ports = ports_mask(
        c["ports_pair_any"], c["ports_pair_wild"], c["ports_triple"], p
    )
    mask_fit = fit_mask(
        c["requested"], c["pod_count"], c["alloc"], c["allowed_pods"],
        p["req"], p["req_check"], p["req_has_any"],
    )
    return mask_name, mask_unsched, mask_taint, mask_ports, mask_fit


def _node_match(c: Dict, p: Dict):
    """pod_matches_node_selector_and_affinity over all nodes (reference:
    pkg/scheduler/framework/plugins/helper/node_affinity.go:27). Shared by
    the NodeAffinity filter and both PodTopologySpread passes."""
    sel_ok = eval_reqs(
        p["nodesel_op"], p["nodesel_key"], p["nodesel_pairs"],
        c["npair"], c["nkey"],
        threshold=p["nodesel_thr"], num=c["nnum"], num_valid=c["nnum_valid"],
    )  # [N]
    term_ok = eval_reqs(
        p["aff_op"], p["aff_key"], p["aff_pairs"],
        c["npair"], c["nkey"],
        threshold=p["aff_thr"], num=c["nnum"], num_valid=c["nnum_valid"],
    )  # [N, T]
    aff_ok = jnp.any(term_ok & p["aff_valid"][None, :], axis=1)
    return sel_ok & jnp.where(p["has_node_affinity"], aff_ok, True)


def _pts_filter(c: Dict, p: Dict, node_match):
    """PodTopologySpread PreFilter+Filter (reference:
    pkg/scheduler/framework/plugins/podtopologyspread/filtering.go:224
    preFilter pair registration, :313 Filter skew check)."""
    n = c["valid"].shape[0]
    vnp = c["npair"].shape[1]
    valid_c = p["ptsf_valid"]  # [C]
    any_c = jnp.any(valid_c)
    key_c = p["ptsf_key"]
    pair_cn = c["pair_of_key"][:, key_c]  # [N, C] pair id of (key_c, value on node)
    has_all_keys = jnp.all(jnp.where(valid_c[None, :], c["nkey"][:, key_c], True), axis=1)
    eligible = node_match & has_all_keys & c["valid"]
    # registered topology pairs (filtering.go:224): eligible nodes only
    reg = jax.vmap(
        lambda pids: _seg_max_bool(eligible, jnp.where(eligible, pids, 0), vnp),
        in_axes=1,
    )(pair_cn)  # [C, Vnp]
    # pods matching each constraint's selector in the incoming pod's namespace
    match_pc = eval_reqs(p["ptsf_op"], p["ptsf_rkey"], p["ptsf_pairs"], c["ppair"], c["pkey"])
    match_pc = (
        match_pc
        & c["pvalid"][:, None]
        & ~c["pterm"][:, None]
        & (c["pns"] == p["self_ns"])[:, None]
    )  # [P, C]
    node_counts = jax.vmap(
        lambda m: _seg_sum(m.astype(_CNT), c["pnode"], n), in_axes=1
    )(match_pc)  # [C, N]
    count_pair = jax.vmap(
        lambda cnts, pids: _seg_sum(cnts, pids, vnp), in_axes=(0, 1)
    )(node_counts, pair_cn)  # [C, Vnp]
    # TpPairToMatchNum is ONE map keyed by (key, value): constraints sharing
    # a topology key accumulate into the same entries (filtering.go:246)
    same_key = (
        (key_c[:, None] == key_c[None, :]) & valid_c[:, None] & valid_c[None, :]
    )  # [C, C]
    shared_cnt = jnp.sum(
        jnp.where(same_key[:, :, None], count_pair[None, :, :], 0), axis=1
    )  # [C, Vnp]
    col = jnp.arange(vnp)[None, :]
    reg_real = reg & (col > 0)
    big = jnp.iinfo(_CNT).max
    min_c = jnp.min(jnp.where(reg_real, shared_cnt, big), axis=1)
    min_c = jnp.where(min_c == big, 0, min_c)  # no registered pairs -> 0
    self_match = eval_reqs_single(
        p["ptsf_op"], p["ptsf_rkey"], p["ptsf_pairs"], p["self_ppair"], p["self_pkey"]
    ).astype(_CNT)  # [C]
    cnt_n = jnp.take_along_axis(shared_cnt.T, pair_cn, axis=0)  # [N, C] counts at node pair
    reg_n = jnp.take_along_axis(reg_real.T, pair_cn, axis=0)
    cnt_n = jnp.where(reg_n, cnt_n, 0)
    key_on_node = c["nkey"][:, key_c]  # [N, C]
    fail_missing = jnp.any(valid_c[None, :] & ~key_on_node, axis=1)
    skew = cnt_n + self_match[None, :] - min_c[None, :]
    fail_skew = jnp.any(
        valid_c[None, :] & key_on_node & (skew > p["ptsf_skew"][None, :].astype(_CNT)),
        axis=1,
    )
    mask = ~(any_c & (fail_missing | fail_skew))
    # missing-key failures are UnschedulableAndUnresolvable (filtering.go:316)
    unresolvable = any_c & fail_missing
    return mask, unresolvable


def _ipa_term_matches(c: Dict, p: Dict, prefix: str):
    """Per-term match of every existing pod: selector + namespaces."""
    match_pt = eval_reqs(
        p[f"{prefix}_op"], p[f"{prefix}_rkey"], p[f"{prefix}_pairs"],
        c["ppair"], c["pkey"],
    )  # [P, T]
    return match_pt & ns_member(
        p[f"{prefix}_ns"][None, :, :], c["pns"][:, None, None]
    )


def _ipa_scatter_terms(c: Dict, match_pt, keys, valid):
    """Accumulate matches into the ONE (key,value)-keyed global map
    (topologyToMatchedTermCount is shared across terms, filtering.go:60)."""
    vnp = c["npair"].shape[1]
    pair_pt = c["pair_of_key"][c["pnode"][:, None], keys[None, :]]  # [P, T]
    m = match_pt & c["pvalid"][:, None] & valid[None, :]
    cnt = jax.vmap(
        lambda mm, pids: _seg_sum(mm.astype(_CNT), pids, vnp), in_axes=(1, 1)
    )(m, pair_pt)  # [T, Vnp]
    return jnp.sum(cnt, axis=0).at[0].set(0)  # [Vnp]


def _ipa_filter_parts(c: Dict, p: Dict) -> Dict:
    """Static pieces of the InterPodAffinity Filter for one incoming pod
    against the REAL pod/term tables. _ipa_filter composes them directly;
    the hoisted session (ops/hoisted.py) adds in-scan dynamic counts from
    session-assumed pods before composing, so the decomposition is the
    single source of truth for the filtering.go math."""
    # existing pods' required anti-affinity terms vs the incoming pod
    # (filtering.go:162 existing anti-affinity map)
    vnp = c["npair"].shape[1]
    match_at = (
        eval_reqs_single(c["at_op"], c["at_rkey"], c["at_pairs"], p["self_ppair"], p["self_pkey"])
        & ns_member(c["at_ns"], p["self_ns"])
        & c["at_valid"]
        & c["pvalid"][c["at_src"]]
    )  # [A]
    at_pair = c["pair_of_key"][c["pnode"][c["at_src"]], c["at_key"]]  # [A]
    existing_cnt = _seg_sum(match_at.astype(_CNT), at_pair, vnp)
    existing_cnt = existing_cnt.at[0].set(0)
    # gather per node LABEL (pair_of_key, ~K columns) instead of sweeping the
    # whole [N, Vnp] pair matrix: nodes carry few labels, Vnp is huge
    hit_per_key = (existing_cnt > 0)[c["pair_of_key"]] & c["nkey"]  # [N, K]
    fail_existing = jnp.any(hit_per_key, axis=1)

    # incoming required anti-affinity (filtering.go:341 satisfyPodAntiAffinity):
    # a pod matching ANY term contributes at that term's topology pair
    anti_valid = p["ipaaa_valid"]
    anti_vec = _ipa_scatter_terms(
        c, _ipa_term_matches(c, p, "ipaaa"), p["ipaaa_key"], anti_valid
    )
    pair_nt = c["pair_of_key"][:, p["ipaaa_key"]]  # [N, Taa]
    anti_key_on_node = c["nkey"][:, p["ipaaa_key"]]
    anti_cnt_n = anti_vec[pair_nt]  # [N, Taa]

    # incoming required affinity (filtering.go:357 satisfyPodAffinity): a pod
    # must match ALL terms to contribute (podMatchesAllAffinityTerms)
    aff_valid = p["ipaa_valid"]
    has_aff = jnp.any(aff_valid)
    match_all = jnp.all(
        jnp.where(aff_valid[None, :], _ipa_term_matches(c, p, "ipaa"), True), axis=1
    ) & has_aff  # [P]
    aff_vec = _ipa_scatter_terms(c, match_all[:, None], p["ipaa_key"], aff_valid)
    pair_na = c["pair_of_key"][:, p["ipaa_key"]]
    aff_cnt_n = aff_vec[pair_na]  # [N, Ta]
    key_aff = c["nkey"][:, p["ipaa_key"]]
    aff_all_keys = jnp.all(jnp.where(aff_valid[None, :], key_aff, True), axis=1)
    # first-pod-in-series escape hatch (filtering.go:357): the global map is
    # empty AND the incoming pod matches its own terms
    aff_total = jnp.sum(aff_vec)
    self_match_all = has_aff & jnp.all(
        jnp.where(
            aff_valid,
            eval_reqs_single(
                p["ipaa_op"], p["ipaa_rkey"], p["ipaa_pairs"],
                p["self_ppair"], p["self_pkey"],
            )
            & ns_member(p["ipaa_ns"], p["self_ns"]),
            True,
        )
    )
    return dict(
        fail_existing=fail_existing,
        anti_cnt_n=anti_cnt_n,
        anti_key_on_node=anti_key_on_node,
        aff_cnt_n=aff_cnt_n,
        aff_all_keys=aff_all_keys,
        aff_total=aff_total,
        self_match_all=self_match_all,
        has_aff=has_aff,
    )


def ipa_compose(p: Dict, parts: Dict, anti_dyn=0, aff_dyn=0, aff_total_dyn=0,
                fail_existing_dyn=False):
    """Compose the InterPodAffinity mask from static parts + dynamic
    in-scan count deltas (all deltas default to the pure-static case).
    anti_dyn/aff_dyn broadcast against [N, Taa]/[N, Ta]."""
    anti_valid = p["ipaaa_valid"]
    fail_anti = jnp.any(
        anti_valid[None, :]
        & parts["anti_key_on_node"]
        & ((parts["anti_cnt_n"] + anti_dyn) > 0),
        axis=1,
    )
    aff_valid = p["ipaa_valid"]
    pods_exist = jnp.all(
        jnp.where(aff_valid[None, :], (parts["aff_cnt_n"] + aff_dyn) > 0, True),
        axis=1,
    )
    counts_empty = (parts["aff_total"] + aff_total_dyn) == 0
    aff_ok = ~parts["has_aff"] | (
        parts["aff_all_keys"]
        & (pods_exist | (counts_empty & parts["self_match_all"]))
    )
    mask = ~(parts["fail_existing"] | fail_existing_dyn) & ~fail_anti & aff_ok
    unresolvable = ~aff_ok  # affinity miss is UnschedulableAndUnresolvable (:374)
    return mask, unresolvable


def _ipa_filter(c: Dict, p: Dict):
    """InterPodAffinity PreFilter+Filter (reference:
    pkg/scheduler/framework/plugins/interpodaffinity/filtering.go:162
    existing anti-affinity map, :194 incoming maps, :374 Filter)."""
    return ipa_compose(p, _ipa_filter_parts(c, p))


# ---------------------------------------------------------------------------
# Scores (each returns raw-normalized int64 in [0,100] BEFORE weighting)


def balanced_score(nz_requested, nz_req, alloc):
    """(1 - |cpuFraction - memFraction|) * 100, fractions over NonZero
    requested+pod (reference: noderesources/balanced_allocation.go:82,
    resource_allocation.go:91). Shared by kernel + hoisted step."""
    cpu_req = (nz_requested[:, 0] + nz_req[0]).astype(_F64)
    mem_req = (nz_requested[:, 1] + nz_req[1]).astype(_F64)
    cpu_cap = alloc[:, 0].astype(_F64)
    mem_cap = alloc[:, 1].astype(_F64)
    cpu_frac = jnp.where(cpu_cap == 0, 1.0, cpu_req / cpu_cap)
    mem_frac = jnp.where(mem_cap == 0, 1.0, mem_req / mem_cap)
    diff = jnp.abs(cpu_frac - mem_frac)
    score = ((1.0 - diff) * MAX_NODE_SCORE).astype(_I64)
    return jnp.where((cpu_frac >= 1) | (mem_frac >= 1), 0, score)


def least_allocated_score(nz_requested, nz_req, alloc):
    """leastResourceScorer with default cpu/mem weights 1/1 (reference:
    noderesources/least_allocated.go:93,:108). Shared by kernel +
    hoisted step."""

    def one(dim):
        cap = alloc[:, dim]
        req = nz_requested[:, dim] + nz_req[dim]
        s = (cap - req) * MAX_NODE_SCORE // jnp.where(cap == 0, 1, cap)
        return jnp.where((cap == 0) | (req > cap), 0, s)

    return (one(0) + one(1)) // 2


def _score_balanced(c: Dict, p: Dict):
    return balanced_score(c["nz_requested"], p["nz_req"], c["alloc"])


def _score_least(c: Dict, p: Dict):
    return least_allocated_score(c["nz_requested"], p["nz_req"], c["alloc"])


def _score_image(c: Dict, p: Dict):
    """ImageLocality (reference: imagelocality/image_locality.go:48 Score,
    :91 sumImageScores, :118 normalizedImageName)."""
    total = jnp.maximum(c["n_nodes"].astype(_F64), 1.0)
    sizes = c["img_size"][:, p["images"]]  # [N, MC]
    spread = c["img_nodes"][p["images"]].astype(_F64) / total  # [MC]
    contrib = (sizes.astype(_F64) * spread[None, :]).astype(_I64)
    sum_scores = jnp.sum(contrib, axis=1)
    max_threshold = MAX_CONTAINER_THRESHOLD * p["n_containers"].astype(_I64)
    sum_scores = jnp.clip(sum_scores, MIN_IMG_THRESHOLD, max_threshold)
    score = (
        MAX_NODE_SCORE * (sum_scores - MIN_IMG_THRESHOLD)
        // jnp.maximum(max_threshold - MIN_IMG_THRESHOLD, 1)
    )
    return jnp.where(p["n_containers"] == 0, 0, score)


def _score_prefer_avoid(c: Dict, p: Dict):
    """NodePreferAvoidPods (reference:
    nodepreferavoidpods/node_prefer_avoid_pods.go:58): 0 when the node's
    preferAvoidPods annotation names the pod's RC/RS controller."""
    avoided = c["avoid"][:, p["avoid_ctrl"]]
    return jnp.where(avoided, 0, MAX_NODE_SCORE).astype(_I64)


def _taint_count(c: Dict, p: Dict):
    """Untolerated PreferNoSchedule taints per node (pre-normalization)."""
    prefer = c["taint_effect"][None, :] == EFFECT_PREFER_NO_SCHEDULE
    return jnp.sum(c["taints"] & prefer & ~p["tol_prefer"][None, :], axis=1).astype(_I64)


def _score_taint(c: Dict, p: Dict, feasible):
    """TaintToleration: count untolerated PreferNoSchedule taints, then
    DefaultNormalizeScore reverse (reference:
    tainttoleration/taint_toleration.go:107, helper/normalize_score.go:26)."""
    return _normalize_default(_taint_count(c, p), feasible, reverse=True)


def _nodeaff_count(c: Dict, p: Dict):
    """Matched preferred-term weight sum per node (pre-normalization)."""
    match = eval_reqs(
        p["npref_op"], p["npref_key"], p["npref_pairs"],
        c["npair"], c["nkey"],
        threshold=p["npref_thr"], num=c["nnum"], num_valid=c["nnum_valid"],
    )  # [N, T]
    return jnp.sum(match.astype(_I64) * p["npref_weight"][None, :], axis=1)


def _score_node_affinity(c: Dict, p: Dict, feasible):
    """NodeAffinity Score: sum preferred-term weights whose preference
    matches, then DefaultNormalizeScore (reference:
    nodeaffinity/node_affinity.go:139)."""
    return _normalize_default(_nodeaff_count(c, p), feasible, reverse=False)


def _normalize_default(scores, feasible, reverse: bool):
    """DefaultNormalizeScore (reference: helper/normalize_score.go:26):
    scale by the max over the feasible set; reverse subtracts from 100."""
    max_count = jnp.max(jnp.where(feasible, scores, 0))
    scaled = MAX_NODE_SCORE * scores // jnp.where(max_count == 0, 1, max_count)
    if reverse:
        out = jnp.where(max_count == 0, MAX_NODE_SCORE, MAX_NODE_SCORE - scaled)
    else:
        out = jnp.where(max_count == 0, scores, scaled)
    return out


def _score_pts(c: Dict, p: Dict, node_match, feasible):
    """PodTopologySpread PreScore+Score+NormalizeScore (reference:
    podtopologyspread/scoring.go:221 preScore pair registration, :279
    topologyNormalizingWeight, :287 Score, :247 NormalizeScore)."""
    n = c["valid"].shape[0]
    vnp = c["npair"].shape[1]
    valid_c = p["ptss_valid"]
    any_c = jnp.any(valid_c)
    key_c = p["ptss_key"]
    hostname = p["ptss_hostname"]
    key_on_node = c["nkey"][:, key_c]  # [N, C]
    has_all = jnp.all(jnp.where(valid_c[None, :], key_on_node, True), axis=1)
    ignored = feasible & ~has_all  # scoring.go:233 ignored filtered nodes
    scored = feasible & has_all
    pair_cn = c["pair_of_key"][:, key_c]  # [N, C]
    # pair registration over filtered nodes (non-hostname constraints)
    reg = jax.vmap(
        lambda pids: _seg_max_bool(scored, jnp.where(scored, pids, 0), vnp),
        in_axes=1,
    )(pair_cn)  # [C, Vnp]
    col = jnp.arange(vnp)[None, :]
    reg_real = reg & (col > 0) & ~hostname[:, None] & valid_c[:, None]
    # duplicate-key constraints register no pairs of their own -> size 0
    # (pair_counts is one (key,value)-keyed map, scoring.go:221-240)
    topo_size = jnp.where(p["ptss_first"], jnp.sum(reg_real, axis=1), 0).astype(_F64)
    n_scored = jnp.sum(scored).astype(_F64)
    weight = jnp.log(jnp.where(hostname, n_scored, topo_size) + 2.0)  # [C]
    # pod counts per pair over ALL nodes passing nodeSelector/affinity+keys
    match_pc = eval_reqs(p["ptss_op"], p["ptss_rkey"], p["ptss_pairs"], c["ppair"], c["pkey"])
    match_pc = (
        match_pc
        & c["pvalid"][:, None]
        & ~c["pterm"][:, None]
        & (c["pns"] == p["self_ns"])[:, None]
    )  # [P, C]
    node_counts = jax.vmap(
        lambda m: _seg_sum(m.astype(_CNT), c["pnode"], n), in_axes=1
    )(match_pc)  # [C, N]
    src = node_match & has_all & c["valid"]  # scoring.go:252 count eligibility
    count_pair = jax.vmap(
        lambda cnts, pids: _seg_sum(cnts * src.astype(_CNT), pids, vnp),
        in_axes=(0, 1),
    )(node_counts, pair_cn)  # [C, Vnp]
    # one shared (key,value)-keyed map across same-key constraints
    same_key = (
        (key_c[:, None] == key_c[None, :]) & valid_c[:, None] & valid_c[None, :]
    )
    shared_cnt = jnp.sum(
        jnp.where(same_key[:, :, None], count_pair[None, :, :], 0), axis=1
    )  # [C, Vnp]
    cnt_n = jnp.take_along_axis(shared_cnt.T, pair_cn, axis=0)  # [N, C]
    reg_n = jnp.take_along_axis(reg_real.T, pair_cn, axis=0)
    cnt_n = jnp.where(reg_n, cnt_n, 0)
    cnt_n = jnp.where(hostname[None, :], node_counts.T, cnt_n)
    terms = jnp.where(
        valid_c[None, :] & key_on_node,
        cnt_n.astype(_F64) * weight[None, :]
        + (p["ptss_skew"][None, :].astype(_F64) - 1.0),
        0.0,
    )
    raw = jnp.sum(terms, axis=1).astype(_I64)  # int(score) truncation
    # NormalizeScore (scoring.go:247)
    big = jnp.iinfo(jnp.int64).max
    min_s = jnp.min(jnp.where(scored, raw, big))
    max_s = jnp.max(jnp.where(scored, raw, 0))
    min_s = jnp.where(min_s == big, 0, min_s)
    norm = MAX_NODE_SCORE * (max_s + min_s - raw) // jnp.where(max_s == 0, 1, max_s)
    norm = jnp.where(max_s == 0, MAX_NODE_SCORE, norm)
    norm = jnp.where(ignored, 0, norm)
    return jnp.where(any_c, norm, 0)


def _score_ipa(c: Dict, p: Dict, feasible):
    """InterPodAffinity PreScore+Score+NormalizeScore (reference:
    interpodaffinity/scoring.go:88 processExistingPod, :225 Score, :247
    NormalizeScore)."""
    raw, any_present = _score_ipa_raw(c, p)
    return _score_ipa_normalize(raw, any_present, feasible)


def _score_ipa_raw(c: Dict, p: Dict):
    """Per-node raw IPA score + whether any term matched (pre-normalize);
    independent of the feasible set."""
    vnp = c["npair"].shape[1]
    hard_w = c["hard_pod_affinity_weight"].astype(_CNT)
    # (a) incoming preferred terms vs existing pods
    match_pt = eval_reqs(p["ipap_op"], p["ipap_rkey"], p["ipap_pairs"], c["ppair"], c["pkey"])
    match_pt = (
        match_pt
        & c["pvalid"][:, None]
        & ns_member(p["ipap_ns"][None, :, :], c["pns"][:, None, None])
        & p["ipap_valid"][None, :]
    )  # [P, T]
    pair_pt = c["pair_of_key"][c["pnode"][:, None], p["ipap_key"][None, :]]
    cnt_t = jax.vmap(
        lambda m, pids: _seg_sum(m.astype(_CNT), pids, vnp), in_axes=(1, 1)
    )(match_pt, pair_pt)  # [T, Vnp]
    cnt_t = cnt_t.at[:, 0].set(0)
    score_vec = jnp.sum(cnt_t * p["ipap_weight"].astype(_CNT)[:, None], axis=0)  # [Vnp]
    present = jnp.any(cnt_t > 0, axis=0)
    # (b) existing pods' terms vs the incoming pod
    w_st = jnp.where(
        c["st_kind"] == ST_REQUIRED_AFFINITY,
        hard_w,
        jnp.where(
            c["st_kind"] == ST_PREFERRED_AFFINITY,
            c["st_weight"].astype(_CNT),
            -c["st_weight"].astype(_CNT),
        ),
    )
    match_st = (
        eval_reqs_single(c["st_op"], c["st_rkey"], c["st_pairs"], p["self_ppair"], p["self_pkey"])
        & ns_member(c["st_ns"], p["self_ns"])
        & c["st_valid"]
        & c["pvalid"][c["st_src"]]
        & ~((c["st_kind"] == ST_REQUIRED_AFFINITY) & (hard_w <= 0))
    )  # [S]
    st_pair = c["pair_of_key"][c["pnode"][c["st_src"]], c["st_key"]]
    score_vec = score_vec + _seg_sum(jnp.where(match_st, w_st, 0), st_pair, vnp)
    present = present | (_seg_sum(match_st.astype(_CNT), st_pair, vnp) > 0)
    present = present.at[0].set(False)
    score_vec = score_vec.at[0].set(0)
    # Score(): sum score_vec over the node's label pairs — gather per label
    # via pair_of_key ([N, K], K ~ label-key vocab) instead of the dense
    # [N, Vnp] sweep; pair id 0 (no label) contributes score_vec[0] == 0
    raw = jnp.sum(
        jnp.where(c["nkey"], score_vec[c["pair_of_key"]], 0), axis=1
    )
    return raw, jnp.any(present)


def _score_ipa_normalize(raw, any_present, feasible):
    big = jnp.iinfo(_CNT).max
    min_s = jnp.min(jnp.where(feasible, raw, big))
    max_s = jnp.max(jnp.where(feasible, raw, -big))
    diff = (max_s - min_s).astype(_F64)
    norm = jnp.where(
        diff > 0,
        (MAX_NODE_SCORE * ((raw - min_s).astype(_F64) / jnp.where(diff > 0, diff, 1.0))).astype(_I64),
        0,
    )
    return jnp.where(any_present, norm, 0)


# ---------------------------------------------------------------------------


def schedule_pod(c: Dict, p: Dict, weights: Dict[str, int] = None) -> Dict:
    """Filter + score every node for one pending pod. Pure; jit-friendly."""
    w = weights or DEFAULT_WEIGHTS
    mask_name, mask_unsched, mask_taint, mask_ports, mask_fit = _filter_basics(c, p)
    node_match = _node_match(c, p)
    mask_pts, pts_unresolvable = _pts_filter(c, p, node_match)
    mask_ipa, ipa_unresolvable = _ipa_filter(c, p)
    feasible = (
        c["valid"]
        & mask_name
        & mask_unsched
        & mask_taint
        & mask_ports
        & mask_fit
        & node_match
        & mask_pts
        & mask_ipa
    )
    out = {
        "feasible": feasible,
        "mask_name": mask_name,
        "mask_unsched": mask_unsched,
        "mask_taint": mask_taint,
        "mask_ports": mask_ports,
        "mask_fit": mask_fit,
        "mask_node_affinity": node_match,
        "mask_pts": mask_pts,
        "pts_unresolvable": pts_unresolvable,
        "mask_ipa": mask_ipa,
        "ipa_unresolvable": ipa_unresolvable,
    }
    scores = {
        "balanced": _score_balanced(c, p),
        "least": _score_least(c, p),
        "image": _score_image(c, p),
        "prefer_avoid": _score_prefer_avoid(c, p),
        "taint": _score_taint(c, p, feasible),
        "node_affinity": _score_node_affinity(c, p, feasible),
        "pts": _score_pts(c, p, node_match, feasible),
        "ipa": _score_ipa(c, p, feasible),
    }
    total = jnp.zeros_like(scores["balanced"])
    for name, s in scores.items():
        weighted = s * w[name]
        out[f"score_{name}"] = weighted
        total = total + weighted
    out["total"] = jnp.where(feasible, total, -1)
    return out


@functools.partial(jax.jit, static_argnames=("weights_key",))
def _jitted(c, p, weights_key):
    return schedule_pod(c, p, dict(weights_key))


def schedule_pod_jit(c: Dict, p: Dict, weights: Dict[str, int] = None) -> Dict:
    key = tuple(sorted((weights or DEFAULT_WEIGHTS).items()))
    return _jitted(c, p, key)


@functools.partial(jax.jit, static_argnames=("weights_key",))
def _jitted_vmapped(c, P, weights_key):
    return jax.vmap(lambda p: schedule_pod(c, p, dict(weights_key)))(P)


def schedule_pods_jit(c: Dict, P: Dict, weights: Dict[str, int] = None) -> Dict:
    """Batched independent evaluation: every pod in the stacked arrays P
    ([B, ...] rows) against the SAME cluster state — per-pod masks,
    scores and totals in one dispatch. This is the status-recovery path
    for preemption dry-runs (default_preemption.go:320 dryRunPreemption
    consumes per-node failure statuses): re-dispatching failed pods one
    at a time was a session teardown + a full kernel launch each over
    the tunnel; one vmapped launch amortizes all of it."""
    key = tuple(sorted((weights or DEFAULT_WEIGHTS).items()))
    return _jitted_vmapped(c, P, key)


# ---------------------------------------------------------------------------
# Multi-pod scan steps (PERF_NOTES round 9): k pods decided per scan step
# with EXACT conflict replay. The policy knob and the shared
# utilization-side conflict algebra live here so the hoisted, pallas and
# sharded steps cannot drift apart.

DEFAULT_MULTIPOD_K = 4


def multipod_k(explicit=None, dyn_ports: bool = False,
               platform: str = "") -> int:
    """Resolve the multi-pod step width for a session build.

    Precedence: port-carrying sessions are pinned to 1 (the carried
    NodePorts tables are OUTSIDE the conflict algebra — a same-step port
    clash would not be detected); then an explicit constructor argument;
    then KTPU_MULTIPOD_K (the kill switch: =1 restores one-pod-per-step
    everywhere); then the platform default — DEFAULT_MULTIPOD_K on TPU,
    1 elsewhere (the CPU build env runs the whole test suite through
    these scans; paying the k-wide vmapped eval compile there buys
    nothing, and the parity suites pass k explicitly). The result is
    clamped to a power of two <= 64 so every pow2 batch bucket divides
    into whole steps."""
    from ..utils import knobs

    if dyn_ports:
        return 1
    if explicit is not None:
        k = int(explicit)
    else:
        env = knobs.get_int("KTPU_MULTIPOD_K", default=0)
        if env:
            k = int(env)
        else:
            if not platform:
                import jax as _jax

                platform = _jax.devices()[0].platform
            k = DEFAULT_MULTIPOD_K if platform == "tpu" else 1
    k = max(1, k)
    p = 1
    while p * 2 <= min(k, 64):
        p *= 2
    return p


def multipod_utilization_conflicts(feasible, total, best, score, lane,
                                   fit_new, wbl_old, wbl_new):
    """The utilization side of the exact conflict test, shared by the
    multipod steps (hoisted in-device replay, sharded suffix flags; the
    pallas kernel mirrors it in Mosaic — divergences are bugs).

    Premise: with the PTS/IPA count gates already clean, committing the
    step's earlier pods changed this pod's true score vector ONLY
    through NodeResourcesFit / BalancedAllocation / LeastAllocated at
    the committed nodes — every other plugin reads statics or counts,
    and the normalization sets are untouched as long as feasibility did
    not move. So re-evaluating exactly those three against the current
    carry decides exactness:

      fit_flip  — a speculatively-feasible node no longer fits (the
                  carry only grows, so fit is monotone non-increasing):
                  the feasible SET changed, which perturbs the PTS/IPA/
                  taint/node-affinity normalizations at every node —
                  the speculative decision cannot stand;
      overtake  — a still-feasible node's new total now beats (or
                  first-max-ties below) the speculative winner: the
                  argmax moved. At untouched nodes wbl_new == wbl_old,
                  so the test degenerates to comparisons the spec argmax
                  already won — no touched-node bookkeeping is needed.

    All args are per-node rows (any layout: [N] vectors, (1, Np) shard
    blocks); returns (fit_flip_row, overtake_row) for the caller to
    any()/reduce — the sharded step pmax-reduces them globally."""
    new_total = total + (wbl_new - wbl_old)
    fit_flip = feasible & ~fit_new
    overtake = (
        feasible & fit_new
        & ((new_total > score) | ((new_total == score) & (lane < best)))
    )
    return fit_flip, overtake
