"""Device-side evaluation of compiled requirement tables.

The host compiles every selector to integer tables over interned vocabs
(models/selectors.py); these primitives evaluate them against entity
matrices (nodes or pods) with pure gathers — no string work on device.

Semantics mirror api.labels.requirement_matches (reference:
staging/src/k8s.io/apimachinery/pkg/labels/selector.go:194 Matches):
  In            any listed (key,value) pair present
  NotIn         no listed pair present (missing key matches)
  Exists        key present
  DoesNotExist  key absent
  Gt / Lt       key present, integer-valued, compares to threshold
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..models.selectors import (
    OP_EXISTS,
    OP_FALSE,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_EXISTS,
    OP_NOT_IN,
)


def eval_reqs(
    op: jnp.ndarray,        # [..., R] int8
    key: jnp.ndarray,       # [..., R] int32
    pairs: jnp.ndarray,     # [..., R, V] int32
    pair_bits: jnp.ndarray,  # [E, P] bool
    key_bits: jnp.ndarray,   # [E, K] bool
    threshold: Optional[jnp.ndarray] = None,  # [..., R] int64
    num: Optional[jnp.ndarray] = None,        # [E, K] int64
    num_valid: Optional[jnp.ndarray] = None,  # [E, K] bool
) -> jnp.ndarray:
    """AND over the R requirement rows -> match [E, ...].

    Column 0 of every entity matrix is the never-present sentinel, so pad
    ids (0) and unknown strings resolve to False without branching.
    """
    has_pair = pair_bits[:, pairs]            # [E, ..., R, V]
    any_pair = jnp.any(has_pair, axis=-1)     # [E, ..., R]
    has_key = key_bits[:, key]                # [E, ..., R]
    res = jnp.ones_like(has_key)              # OP_PAD -> True
    res = jnp.where(op == OP_IN, any_pair, res)
    res = jnp.where(op == OP_NOT_IN, ~any_pair, res)
    res = jnp.where(op == OP_EXISTS, has_key, res)
    res = jnp.where(op == OP_NOT_EXISTS, ~has_key, res)
    if num is not None:
        val = num[:, key]                     # [E, ..., R]
        ok = num_valid[:, key] & has_key
        res = jnp.where(op == OP_GT, ok & (val > threshold), res)
        res = jnp.where(op == OP_LT, ok & (val < threshold), res)
    else:
        # numeric ops over entities without numeric matrices never match
        res = jnp.where((op == OP_GT) | (op == OP_LT), False, res)
    res = jnp.where(op == OP_FALSE, False, res)
    return jnp.all(res, axis=-1)              # [E, ...]


def eval_reqs_single(
    op, key, pairs, pair_vec: jnp.ndarray, key_vec: jnp.ndarray,
) -> jnp.ndarray:
    """Evaluate tables against ONE entity given as flat bit vectors.

    pair_vec [P] bool, key_vec [K] bool -> match [...] (table lead dims).
    Used for cluster-wide affinity term tables vs the incoming pod.
    """
    any_pair = jnp.any(pair_vec[pairs], axis=-1)   # [..., R]
    has_key = key_vec[key]                         # [..., R]
    res = jnp.ones_like(has_key)
    res = jnp.where(op == OP_IN, any_pair, res)
    res = jnp.where(op == OP_NOT_IN, ~any_pair, res)
    res = jnp.where(op == OP_EXISTS, has_key, res)
    res = jnp.where(op == OP_NOT_EXISTS, ~has_key, res)
    res = jnp.where((op == OP_GT) | (op == OP_LT), False, res)
    res = jnp.where(op == OP_FALSE, False, res)
    return jnp.all(res, axis=-1)


def ns_member(ns_sets: jnp.ndarray, ns_id: jnp.ndarray) -> jnp.ndarray:
    """ns_sets [..., S] int32 (0-padded), ns_id scalar/broadcast int32 ->
    bool [...]: is ns_id in the set? Mirrors the resolved namespaces check
    of AffinityTerm.matches (reference: pkg/scheduler/framework/types.go
    PodMatchesTermsNamespaceAndSelector via util/topologies.go:40)."""
    return jnp.any((ns_sets == ns_id) & (ns_sets != 0), axis=-1)
