"""Sharded two-phase scheduling session: the pallas session's math over a
jax.sharding.Mesh, exact.

The single-launch pallas kernel (ops/pallas_scan.py) cannot span chips: a
Mosaic program owns one core's VMEM, and the per-pod loop needs GLOBAL
reductions each step (score normalization min/max over ALL nodes —
reference helper/normalize_score.go:24, framework/runtime/framework.go:757
— the PTS min-match, the cross-node argmax). Sharding those away silently
changes decisions. So the mesh path restructures each per-pod step into
the two-phase form (VERDICT r4 #2 / PERF_NOTES "Sharded pallas"):

  raw partials   — every shard computes masks/counts/scores over ITS node
                   slice only, from node-sharded carries (the pallas
                   session's node-space carry layout: requested/nzpc/
                   cnt_fn/cnt_sn, all [rows, N] — nothing pair-global);
  collectives    — the handful of cross-shard scalars ride named-axis
                   collectives over ICI (psum/pmax/pmin): the PTS filter's
                   per-constraint min-match, zone-presence (<=128-lane
                   vocab rows), n_scored/n_feasible, the four normalize
                   min/max pairs, the argmax (max score, then min global
                   lane among maxima = the first-max convention), and the
                   winner's pair-ids for the count updates;
  finish + apply — normalization and totals are shard-local elementwise;
                   the winning shard alone takes the carry updates (the
                   same off-shard no-op trick as the kernel's apply mode:
                   `hot` is all-zero off the winner).

The step body runs under shard_map inside ONE jit-compiled lax.scan per
batch — one device dispatch per batch, carries device-resident across
batches, exactly the session discipline of HoistedSession/PallasSession.
Decisions are BIT-IDENTICAL to the single-device PallasSession (same
int32 rescaled resources, f32 score math, first-max tie-break); parity is
pinned by tests/test_sharded_scan.py over fuzzed clusters on a virtual
8-device CPU mesh.

Statics and envelope come from PallasSession's own prologue (the GCD
int32 rescale, per-template static rows, compact topology vocab): a shape
the pallas kernel rejects is rejected here with the same PallasUnsupported
reasons. Templates with affinity TERMS ride the sharded session too: the
D1-D5 ucnt carry is per-node (shards like everything else), kcnt holds
per-shard partial key totals psum'd at read, and the presence flags
(rowany) are a pmax.

Reference frame: pkg/scheduler/internal/parallelize/parallelism.go:27,56
(the 16-goroutine node chunking this replaces) and
framework/plugins/helper/normalize_score.go:24 (the global normalize that
must not be sharded away).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.partition import (
    SESSION_PARTITION_RULES,
    session_specs,
    shard_map_compat,
    shard_tree,
)
from ..parallel.sharded import NODE_AXIS
from . import kernel as K_ops
from .hoisted import template_fingerprint
from .kernel import MAX_NODE_SCORE
from .pallas_scan import (
    LANE,
    POS_BIG,
    SUB as SUB_IPA,
    PallasSession,
    PallasUnsupported,
    _carry_delta_scan,
    _ceil,
    batch_prologue,
)

_CARRY_KEYS = ("requested", "nzpc", "cnt_fn", "cnt_sn")


def _doth(a, b, dims):
    """Exact-f32 dot (counts/pair-ids above 2^8 need HIGHEST) — the same
    convention as the pallas kernel's doth."""
    return jax.lax.dot_general(
        a, b, dims, preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST)


def _fit_row(cfg, statics, tables, carry, t):
    """NodeResourcesFit row for template t against `carry` (local, no
    collectives) — shared by the eval and the multipod step's conflict
    recheck (the fit leg of kernel.multipod_utilization_conflicts)."""
    (T, C, CP, R, SR, K, Npl, TCp, UR) = cfg[0]
    requested, nzpc = carry["requested"], carry["nzpc"]
    alloc = statics["alloc"]
    req_t = jax.lax.dynamic_index_in_dim(tables["req"], t, 0,
                                         keepdims=False)          # (R,)
    req_check = jax.lax.dynamic_index_in_dim(tables["req_check"], t, 0,
                                             keepdims=False)
    over = jnp.zeros((1, Npl), jnp.bool_)
    for r in range(R):
        free = alloc[r:r + 1, :] - requested[r:r + 1, :]
        over = over | ((req_t[r] > free) & (req_check[r] != 0))
    fail_dims = (tables["req_has_any"][t] != 0) & over
    fail_count = (nzpc[2:3, :] + jnp.int32(1)) > nzpc[3:4, :]
    return jnp.logical_not(fail_count | fail_dims)


def _resource_scores(cfg, statics, tables, carry, t):
    """(balanced, least) rows for template t against `carry` (local, no
    collectives) — shared by the eval and the multipod step's wbl
    recheck (the balanced/least legs of the conflict algebra)."""
    (T, C, CP, R, SR, K, Npl, TCp, UR) = cfg[0]
    f32 = jnp.float32
    nzpc = carry["nzpc"]
    alloc = statics["alloc"]
    nz_req = jax.lax.dynamic_index_in_dim(tables["nz_req"], t, 0,
                                          keepdims=False)         # (2,)
    nz_cpu = (nzpc[0:1, :] + nz_req[0]).astype(f32)
    nz_mem = (nzpc[1:2, :] + nz_req[1]).astype(f32)
    cap_cpu = alloc[0:1, :].astype(f32)
    cap_mem = alloc[1:2, :].astype(f32)
    frac_c = jnp.where(cap_cpu == 0, f32(1.0), nz_cpu / cap_cpu)
    frac_m = jnp.where(cap_mem == 0, f32(1.0), nz_mem / cap_mem)
    balanced = ((f32(1.0) - jnp.abs(frac_c - frac_m))
                * MAX_NODE_SCORE).astype(jnp.int32)
    balanced = jnp.where((frac_c >= 1) | (frac_m >= 1),
                         jnp.int32(0), balanced)

    def least_dim(cap, reqq):
        d = ((cap - reqq) * MAX_NODE_SCORE
             // jnp.where(cap == 0, jnp.int32(1), cap))
        return jnp.where((cap == 0) | (reqq > cap), jnp.int32(0), d)

    least = (least_dim(alloc[0:1, :], nzpc[0:1, :] + nz_req[0])
             + least_dim(alloc[1:2, :], nzpc[1:2, :] + nz_req[1])
             ) // jnp.int32(2)
    return balanced, least


def _eval_fn(cfg, statics, tables, carry, x):
    """Filter + score one pod against `carry` WITHOUT carry updates
    (local partials -> collectives -> finish -> cross-shard argmax).
    Mirrors ops/pallas_scan.py _build_kernel one_pod (mode="full")
    line for line; divergences are bugs. Returns everything the commit
    and the multipod conflict test need."""
    (T, C, CP, R, SR, K, Npl, TCp, UR) = cfg[0]
    W = dict(cfg[1])
    f32 = jnp.float32
    t = x["tmpl"]
    shard = jax.lax.axis_index(NODE_AXIS)
    glane = shard * Npl + jnp.arange(Npl, dtype=jnp.int32)[None, :]  # (1,Npl)

    def psum(v):
        return jax.lax.psum(v, NODE_AXIS)

    def pmax(v):
        return jax.lax.pmax(v, NODE_AXIS)

    def pmin(v):
        return jax.lax.pmin(v, NODE_AXIS)

    nzpc = carry["nzpc"]
    cnt_fn, cnt_sn = carry["cnt_fn"], carry["cnt_sn"]
    alloc = statics["alloc"]
    valid_n = statics["valid_n"][0:1, :]
    stat3 = statics["stat"]                      # (T, SR, Npl)

    def trow(i):
        return jax.lax.dynamic_index_in_dim(stat3, t, 0,
                                            keepdims=False)[i:i + 1, :]

    static_mask = trow(0)
    raw_ipa = trow(1)
    cnt_taint = trow(2)
    cnt_nodeaff = trow(3)
    sc_image = trow(4)
    sc_avoid = trow(5)

    def tc8(a):
        """[T, C] table -> (CP, 1) column for template t."""
        row = jax.lax.dynamic_index_in_dim(a, t, 0, keepdims=False)  # (C,)
        return jnp.pad(row, (0, CP - C))[:, None]

    def block(a):
        """[TCp, Npl] -> this template's (CP, Npl) rows."""
        return jax.lax.dynamic_slice_in_dim(a, t * CP, CP, axis=0)

    # ---- NodeResourcesFit (exact int32 after the session's GCD rescale)
    mask_fit = _fit_row(cfg, statics, tables, carry, t)

    # ---- PTS filter: local shifted counts, GLOBAL per-constraint min
    cntf = block(cnt_fn).astype(f32)                              # (CP,Npl)
    sameM = jax.lax.dynamic_index_in_dim(
        tables["f_same"], t, 0, keepdims=False)                   # (CP,CP)
    sh = jax.lax.dot_general(
        sameM, cntf, (((1,), (0,)), ((), ())),
        preferred_element_type=f32,
        precision=jax.lax.Precision.HIGHEST)
    reg = block(statics["regrow_f"])
    big = f32(POS_BIG)
    min_c_l = jnp.min(jnp.where(reg != 0, sh, big), axis=1, keepdims=True)
    min_c = pmin(min_c_l)                      # -- collective 1 (CP,1)
    min_c = jnp.where(min_c == big, f32(0.0), min_c)
    cnt_n = jnp.where(reg != 0, sh, f32(0.0))
    konn = block(statics["konn_f"])
    vld = tc8(tables["f_valid"])
    selfm = tc8(tables["f_self_match"]).astype(f32)
    maxskew = tc8(tables["f_skew"]).astype(f32)
    fail_missing = (vld != 0) & (konn == 0)
    skew = cnt_n + selfm - min_c
    fail_skew = (vld != 0) & (konn != 0) & (skew > maxskew)
    fail_pts = jnp.any(fail_missing | fail_skew, axis=0, keepdims=True)

    # ---- InterPodAffinity: static parts + assumed-pod term carries
    # (the pallas kernel's D1-D5 machinery; ucnt is node-sharded, kcnt
    # holds PER-SHARD partial totals psum'd at read) ----
    if UR > 0:
        ucnt, kcnt = carry["ucnt"], carry["kcnt"]
        ucf = ucnt.astype(f32)                            # (UR, Npl)
        pos = (ucnt > 0).astype(f32)

        def t_row(a):                                     # [T?, UR] row t
            return jax.lax.dynamic_index_in_dim(a, t, 0, keepdims=True)

        def t_block(a):                                   # [T, SUB, *]
            return jax.lax.dynamic_index_in_dim(a, t, 0, keepdims=False)

        # D1: assumed pods' required anti terms repel this pod
        fail1 = _doth(t_row(tables["g1"]), pos,
                      (((1,), (0,)), ((), ()))) > 0       # (1, Npl)
        ipa2 = jax.lax.dynamic_index_in_dim(
            statics["ipa_stat"], t, 0, keepdims=False)    # (2, Npl)
        fe_static = ipa2[0:1, :]
        aff_allk = ipa2[1:2, :]
        # D2: assumed pods vs this pod's own anti terms
        anti_dyn = _doth(t_block(tables["wanti"]), ucf,
                         (((1,), (0,)), ((), ())))        # (SUB, Npl)
        a_stat = t_block(statics["anti_static"]).astype(f32)
        akonn = t_block(statics["anti_konn"])
        avld = jax.lax.dynamic_index_in_dim(
            tables["anti_valid"], t, 0, keepdims=False)[:, None]
        fail_anti = jnp.any(
            (avld != 0) & (akonn != 0) & ((a_stat + anti_dyn) > 0),
            axis=0, keepdims=True)                        # (1, Npl)
        # D3: assumed pods matching ALL of this pod's affinity terms
        aff_dyn = _doth(t_block(tables["waff"]), ucf,
                        (((1,), (0,)), ((), ())))
        f_stat = t_block(statics["aff_static"]).astype(f32)
        fvld = jax.lax.dynamic_index_in_dim(
            tables["aff_valid"], t, 0, keepdims=False)[:, None]
        pods_missing = jnp.any(
            (fvld != 0) & ((f_stat + aff_dyn) <= 0),
            axis=0, keepdims=True)
        kc0_g = psum(kcnt).astype(f32)   # -- collective: global totals
        at_dyn = jnp.sum(_doth(t_row(tables["w3tot"]), kc0_g,
                               (((1,), (0,)), ((), ()))))
        counts_empty = (tables["aff_total"][t].astype(f32) + at_dyn) == 0
        has_aff_t = tables["has_aff"][t]
        smatch = tables["self_match_all"][t]
        aff_ok = ((has_aff_t == 0)
                  | ((aff_allk != 0)
                     & (jnp.logical_not(pods_missing)
                        | (counts_empty & (smatch != 0)))))
        mask_ipa = (jnp.logical_not((fe_static != 0) | fail1)
                    & jnp.logical_not(fail_anti) & aff_ok)
    else:
        mask_ipa = jnp.ones((1, Npl), jnp.bool_)

    feasible = ((static_mask != 0) & mask_fit
                & jnp.logical_not(fail_pts) & mask_ipa & (valid_n != 0))
    n_feasible = psum(jnp.sum(feasible.astype(jnp.int32)))

    # ---- resource scores (local) ----
    balanced, least = _resource_scores(cfg, statics, tables, carry, t)

    # ---- PTS score: zone presence is a cross-shard OR ----
    shasall = jax.lax.dynamic_index_in_dim(
        statics["shasall"], t, 0, keepdims=True)                  # (1,Npl)
    scored = feasible & (shasall != 0)
    ignored = feasible & (shasall == 0)
    scored_f32 = scored.astype(f32)
    n_scored = psum(jnp.sum(scored_f32))       # -- collective 2 (scalars)
    zp = []
    zpn = []
    for k in range(K):
        cnt_z = jax.lax.dot_general(
            scored_f32, statics["onehot"][k], (((1,), (0,)), ((), ())),
            preferred_element_type=f32)                           # (1,VZ)
        p = (psum(cnt_z) > 0).astype(f32)      # -- collective 2 (VZ rows)
        zp.append(p)
        zpn.append(jax.lax.dot_general(
            p, statics["onehot"][k], (((1,), (1,)), ((), ())),
            preferred_element_type=f32))                          # (1,Npl)
    cnts = block(cnt_sn).astype(f32)
    sameS = jax.lax.dynamic_index_in_dim(
        tables["s_same"], t, 0, keepdims=False)
    sh_s = jax.lax.dot_general(
        sameS, cnts, (((1,), (0,)), ((), ())),
        preferred_element_type=f32,
        precision=jax.lax.Precision.HIGHEST)                      # (CP,Npl)
    vld_s = tc8(tables["s_valid"])
    perno = tc8(tables["s_perno"])
    key_s = tc8(tables["s_keyid"])
    first = tc8(tables["s_first"])
    sskew = tc8(tables["s_skew"]).astype(f32)
    have_s = (jnp.sum(vld_s) > 0).astype(jnp.int32)
    zval_l = block(statics["zvalid_s_rows"]).astype(f32)          # (CP,VZ)
    zval_n = block(statics["zvalid_node_s"])
    topo = jnp.zeros((CP, 1), f32)
    regn = jnp.zeros((CP, Npl), f32)
    for k in range(K):
        use = (jnp.logical_not(perno != 0) & (key_s == k)).astype(f32)
        topo = topo + use * jnp.sum(zp[k] * zval_l, axis=1, keepdims=True)
        regn = regn + use * zpn[k]
    regn = regn * (zval_n != 0)
    topo_size = jnp.where(first != 0, topo, f32(0.0))
    weight = jnp.log(jnp.where(perno != 0, n_scored, topo_size) + f32(2.0))
    cnt_n_s = jnp.where(perno != 0, sh_s,
                        jnp.where(regn > 0, sh_s, f32(0.0)))
    konn_s = block(statics["konn_s"])
    term = jnp.where((vld_s != 0) & (konn_s != 0),
                     cnt_n_s * weight + (sskew - f32(1.0)), f32(0.0))
    # same HIGHEST ones-dot reduction as the kernel (pallas_scan.py
    # raw): f32 accumulation order must match for bit-parity on TPU
    raw = jax.lax.dot_general(
        jnp.ones((1, CP), f32), term, (((1,), (0,)), ((), ())),
        preferred_element_type=f32,
        precision=jax.lax.Precision.HIGHEST)                      # (1,Npl)
    raw_i = raw.astype(jnp.int32)
    min_r = pmin(jnp.min(jnp.where(scored, raw_i, jnp.int32(POS_BIG))))
    max_r = pmax(jnp.max(jnp.where(scored, raw_i, jnp.int32(0))))
    min_r = jnp.where(min_r == POS_BIG, jnp.int32(0), min_r)
    norm = (MAX_NODE_SCORE * (max_r + min_r - raw_i)
            // jnp.where(max_r == 0, jnp.int32(1), max_r))
    norm = jnp.where(max_r == 0, jnp.int32(MAX_NODE_SCORE), norm)
    norm = jnp.where(ignored, jnp.int32(0), norm)
    sc_pts = jnp.where(have_s != 0, norm, jnp.int32(0))

    # ---- IPA score: static raw + assumed-pod terms (D4+D5) ----
    present = tables["ipa_present"][t] != 0
    if UR > 0:
        dyn45 = _doth(t_row(tables["w45"]), ucf, (((1,), (0,)), ((), ())))
        # w45 is GCD-scaled (pallas_scan._build_ipa); the int32 multiply
        # restores real weight magnitudes exactly — same convention as
        # the single-device kernel
        raw_ipa = raw_ipa + dyn45.astype(jnp.int32) * tables["w45_scale"]
        rowany = pmax(jnp.max(pos, axis=1, keepdims=True))  # (UR,1)
        pres_dyn = jnp.sum(_doth(t_row(tables["gpres"]), rowany,
                                 (((1,), (0,)), ((), ())))) > 0
        present = present | pres_dyn
    min_i = pmin(jnp.min(jnp.where(feasible, raw_ipa, jnp.int32(POS_BIG))))
    max_i = pmax(jnp.max(jnp.where(feasible, raw_ipa,
                                   jnp.int32(-POS_BIG))))
    diff = (max_i - min_i).astype(f32)
    ipa = jnp.where(
        diff > 0,
        (MAX_NODE_SCORE * ((raw_ipa - min_i).astype(f32)
                           / jnp.where(diff > 0, diff, f32(1.0))))
        .astype(jnp.int32),
        jnp.zeros((1, Npl), jnp.int32))
    ipa = jnp.where(present, ipa, jnp.zeros((1, Npl), jnp.int32))

    # ---- default-normalized taint / node-affinity ----
    def norm_default(counts, reverse):
        mx = pmax(jnp.max(jnp.where(feasible, counts, jnp.int32(0))))
        scaled = (MAX_NODE_SCORE * counts
                  // jnp.where(mx == 0, jnp.int32(1), mx))
        if reverse:
            return jnp.where(mx == 0, jnp.int32(MAX_NODE_SCORE),
                             jnp.int32(MAX_NODE_SCORE) - scaled)
        return jnp.where(mx == 0, counts, scaled)

    sc_taint = norm_default(cnt_taint, True)
    sc_nodeaff = norm_default(cnt_nodeaff, False)

    total = (balanced * W["balanced"] + sc_image * W["image"]
             + ipa * W["ipa"] + least * W["least"]
             + sc_nodeaff * W["node_affinity"]
             + sc_avoid * W["prefer_avoid"]
             + sc_pts * W["pts"] + sc_taint * W["taint"])
    total = jnp.where(feasible, total, jnp.int32(-1))

    # ---- cross-shard first-max argmax -- collectives 3+4 ----
    tf = total.astype(f32)
    m = pmax(jnp.max(tf))
    cand = jnp.min(jnp.where(tf >= m, glane, jnp.int32(POS_BIG)))
    best = pmin(cand).astype(jnp.int32)
    ok = (m >= 0) & x["valid"]
    return dict(
        feasible=feasible, total=total, n_feasible=n_feasible,
        best=best, score=m, ok=ok, glane=glane,
        balanced=balanced, least=least,
    )


def _commit_fn(cfg, statics, tables, carry, x, t, best, oki):
    """Winner-shard carry updates for one decided pod (hot == 0 on every
    other shard) — the apply side of the step, shared by _step_fn and
    the multipod step (where `oki` additionally carries the
    conflict-suffix gate: flagged pods must NOT commit; the host
    replays them)."""
    (T, C, CP, R, SR, K, Npl, TCp, UR) = cfg[0]
    f32 = jnp.float32

    def psum(v):
        return jax.lax.psum(v, NODE_AXIS)

    shard = jax.lax.axis_index(NODE_AXIS)
    glane = shard * Npl + jnp.arange(Npl, dtype=jnp.int32)[None, :]
    requested, nzpc = carry["requested"], carry["nzpc"]
    cnt_fn, cnt_sn = carry["cnt_fn"], carry["cnt_sn"]
    stat3 = statics["stat"]
    req_t = jax.lax.dynamic_index_in_dim(tables["req"], t, 0,
                                         keepdims=False)
    nz_req = jax.lax.dynamic_index_in_dim(tables["nz_req"], t, 0,
                                          keepdims=False)
    okf = oki.astype(f32)
    hot = (glane == best).astype(jnp.int32) * oki                 # (1,Npl)
    hotf = hot.astype(f32)
    new_requested = requested
    for r in range(R):
        new_requested = new_requested.at[r:r + 1, :].add(hot * req_t[r])
    new_nzpc = nzpc.at[0:1, :].add(hot * nz_req[0])
    new_nzpc = new_nzpc.at[1:2, :].add(hot * nz_req[1])
    new_nzpc = new_nzpc.at[2:3, :].add(hot)

    mf_col = x["mf"][:, None].astype(f32)                         # (TCp,1)
    ms_col = x["ms"][:, None].astype(f32)
    pf = statics["prow_f"].astype(f32)                            # (TCp,Npl)
    # pair id at the winning node, shared across shards -- collective 5
    zb_f = psum(jax.lax.dot_general(
        pf, hotf, (((1,), (1,)), ((), ())),
        preferred_element_type=f32,
        precision=jax.lax.Precision.HIGHEST))                     # (TCp,1)
    m_f = ((pf == zb_f) & (statics["prow_f"] >= 0)).astype(f32) * okf
    ps_ = statics["prow_s"].astype(f32)
    zb_s = psum(jax.lax.dot_general(
        ps_, hotf, (((1,), (1,)), ((), ())),
        preferred_element_type=f32,
        precision=jax.lax.Precision.HIGHEST))
    m_s = ((ps_ == zb_s) & (statics["prow_s"] >= 0)).astype(f32) * okf

    # s_src factor at the winning node per template (stat row 7)
    src_all = stat3[:, 7, :].astype(f32)                          # (T,Npl)
    v_t = psum(jax.lax.dot_general(
        src_all, hotf, (((1,), (1,)), ((), ())),
        preferred_element_type=f32))                              # (T,1)
    # expand to (TCp,1): row t*CP+c gets v_t[t]
    v_rows = jnp.repeat(v_t, CP, axis=0)                          # (TCp,1)
    pernosel = tables["s_perno_rows"][:, None].astype(f32)        # (TCp,1)
    factor = pernosel + (f32(1.0) - pernosel) * v_rows

    new_cnt_fn = (cnt_fn.astype(f32) + mf_col * m_f).astype(jnp.int32)
    new_cnt_sn = (cnt_sn.astype(f32)
                  + ms_col * factor * m_s).astype(jnp.int32)

    new_carry = {
        "requested": new_requested, "nzpc": new_nzpc,
        "cnt_fn": new_cnt_fn, "cnt_sn": new_cnt_sn,
    }
    if UR > 0:
        # the assumed pod joins its node's topology groups for every IPA
        # key the node carries: same-pair mask from prow_ipa (-1 rows =
        # node lacks key -> no-op), written into template t's 8-row ucnt
        # block; kcnt accumulates the PER-SHARD key-presence totals
        # (nonzero only on the winner's shard — global totals psum at
        # read), mirroring the kernel's _apply_updates
        pi = statics["prow_ipa"].astype(f32)              # (SUB, Npl)
        zb_i = psum(_doth(pi, hotf, (((1,), (1,)), ((), ()))))  # (SUB,1)
        m_i = ((pi == zb_i)
               & (statics["prow_ipa"] >= 0)).astype(f32) * okf
        base_u = t * SUB_IPA
        ublock = jax.lax.dynamic_slice_in_dim(ucnt, base_u, SUB_IPA, 0)
        new_ucnt = jax.lax.dynamic_update_slice_in_dim(
            ucnt, (ublock.astype(f32) + m_i).astype(jnp.int32),
            base_u, 0)
        hask_l = _doth((pi >= 0).astype(f32), hotf,
                       (((1,), (1,)), ((), ())))          # (SUB, 1) local
        kblock = jax.lax.dynamic_slice_in_dim(kcnt, base_u, SUB_IPA, 0)
        new_kcnt = jax.lax.dynamic_update_slice_in_dim(
            kcnt, (kblock.astype(f32) + hask_l * okf).astype(jnp.int32),
            base_u, 0)
        new_carry["ucnt"] = new_ucnt
        new_carry["kcnt"] = new_kcnt
    return new_carry


def _step_fn(cfg, statics, tables, carry, x):
    """One pod through the two-phase step (runs per shard, inside
    shard_map): _eval_fn -> _commit_fn, the one-pod-per-step reference
    path."""
    e = _eval_fn(cfg, statics, tables, carry, x)
    ok, best = e["ok"], e["best"]
    new_carry = _commit_fn(cfg, statics, tables, carry, x, x["tmpl"],
                           best, ok.astype(jnp.int32))
    y = {
        "best": jnp.where(ok, best, jnp.int32(-1)),
        "score": jnp.where(ok, e["score"].astype(jnp.int32),
                           jnp.int32(-1)),
        "n_feasible": e["n_feasible"],
    }
    return new_carry, y


def _step_multi_fn(cfg, statics, tables, k, carry, xk, seen_in):
    """k pods per scan step for the sharded session: every pod of the
    group is evaluated against the GROUP-START carry (k independent
    evals — no carry chain between them), then committed in order with
    the exact conflict test of the hoisted multipod step
    (ops/hoisted.py _step_multi; the utilization legs ride the shared
    kernel.multipod_utilization_conflicts, pmax-reduced globally).

    Unlike the hoisted step there is NO in-device replay: a replay
    branch would put collectives under lax.cond inside shard_map.
    Instead the step uses the CONFLICT-SUFFIX contract the pallas
    kernel shares: the first conflicted pod and everything after it in
    the group are left UNCOMMITTED and flagged in ys["conflicts"]; the
    backend replays exactly that suffix sequentially through the live
    session (tpu_backend._harvest_locked), which chains on the
    committed-prefix carry — bit-identical to one-pod-per-step either
    way. Every conflict predicate is built from replicated scalars
    (pmax/psum-reduced), so all shards gate commits identically."""
    (T, C, CP, R, SR, K, Npl, TCp, UR) = cfg[0]
    W = dict(cfg[1])
    f32 = jnp.float32
    w_bal = W["balanced"]
    w_least = W["least"]

    def x_at(i):
        return {kk: xk[kk][i] for kk in xk}

    evs = [_eval_fn(cfg, statics, tables, carry, x_at(i)) for i in range(k)]
    carry_i = carry
    # the suffix flag rides the SCAN carry (`seen_in`): a conflict in an
    # earlier group invalidates every later group too — their evals
    # chained on a carry missing the suffix commits — so once set,
    # nothing later in the batch commits and everything is flagged for
    # the host replay
    conf_seen = seen_in
    committed = []  # (best, okc) of the already-committed prefix
    ys = {"best": [], "score": [], "n_feasible": [], "conflicts": []}
    for i in range(k):
        e = evs[i]
        x = x_at(i)
        t = x["tmpl"]
        # global int32 winner score for the exact overtake comparison
        # (e["score"] is the f32 argmax value; totals are int32)
        score_i = jax.lax.pmax(jnp.max(e["total"]), NODE_AXIS)
        same = jnp.bool_(False)
        pts = jnp.bool_(False)
        ipa = jnp.bool_(False)
        fv = jnp.pad(jax.lax.dynamic_index_in_dim(
            tables["f_valid"], t, 0, keepdims=False), (0, CP - C)
        ).astype(f32)
        sv = jnp.pad(jax.lax.dynamic_index_in_dim(
            tables["s_valid"], t, 0, keepdims=False), (0, CP - C)
        ).astype(f32)
        for j2 in range(i):
            bj, okj = committed[j2]
            prior = okj != 0
            same = same | (prior & (bj == e["best"]))
            # PTS: pod j2's Mf/Ms lanes of template t, valid-gated —
            # nonzero means the f/s/h counts this pod read moved
            mfj = jax.lax.dynamic_slice_in_dim(xk["mf"][j2], t * CP, CP)
            msj = jax.lax.dynamic_slice_in_dim(xk["ms"][j2], t * CP, CP)
            pts = pts | (prior
                         & ((jnp.sum(mfj * fv) + jnp.sum(msj * sv)) > 0))
            if UR > 0:
                g = tables["gmat"][xk["tmpl"][j2], t]
                ipa = ipa | (prior & (g > 0))
        same = same & (score_i >= 0)
        fit_new = _fit_row(cfg, statics, tables, carry_i, t)
        bal_new, least_new = _resource_scores(cfg, statics, tables,
                                              carry_i, t)
        flip_row, over_row = K_ops.multipod_utilization_conflicts(
            e["feasible"], e["total"], e["best"], score_i, e["glane"],
            fit_new,
            e["balanced"] * w_bal + e["least"] * w_least,
            bal_new * w_bal + least_new * w_least,
        )
        util_local = jnp.any(flip_row) | (jnp.any(over_row)
                                          & (score_i >= 0))
        util = jax.lax.psum(util_local.astype(jnp.int32), NODE_AXIS) > 0
        conf_i = (same | pts | ipa | util) & x["valid"]
        conf_seen = conf_seen | conf_i
        okc = (e["ok"] & jnp.logical_not(conf_seen)).astype(jnp.int32)
        carry_i = _commit_fn(cfg, statics, tables, carry_i, x, t,
                             e["best"], okc)
        committed.append((e["best"], okc))
        placed = okc != 0
        ys["best"].append(jnp.where(placed, e["best"], jnp.int32(-1)))
        ys["score"].append(jnp.where(placed, e["score"].astype(jnp.int32),
                                     jnp.int32(-1)))
        ys["n_feasible"].append(e["n_feasible"])
        ys["conflicts"].append(conf_seen.astype(jnp.int32))
    return carry_i, {kk: jnp.stack(v) for kk, v in ys.items()}, conf_seen


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "mesh", "k"),
    donate_argnames=("carry",),
)
def _sharded_scan(cfg, mesh, statics, tables, carry, xs, k: int = 1):
    # placements are DECLARED, not wired: the same rule table that placed
    # the session state at build time (parallel/partition.py
    # SESSION_PARTITION_RULES) yields the shard_map in/out specs, so a
    # new carry or static either matches a rule or fails at trace time
    ys_spec = {"best": P(), "score": P(), "n_feasible": P()}
    if k > 1:
        ys_spec["conflicts"] = P()
        # fold the batch axis into [steps, k] (pow2 buckets divide by
        # the pow2 k) — the k-wide step evaluates a whole group against
        # the step-initial carry
        bp = int(np.shape(xs["tmpl"])[0])
        xs = {kk: v.reshape((bp // k, k) + v.shape[1:])
              for kk, v in xs.items()}
    statics_spec = session_specs("statics", statics)
    tables_spec = session_specs("tables", tables)
    carry_spec = session_specs("carry", carry)
    xs_spec = session_specs("xs", xs)

    def body(statics, tables, carry, xs):
        if k > 1:
            def step(state, x):
                c, seen = state
                c, y, seen = _step_multi_fn(cfg, statics, tables, k,
                                            c, x, seen)
                return (c, seen), y

            (carry, _), ys = jax.lax.scan(
                step, (carry, jnp.bool_(False)), xs)
            return carry, ys
        step = functools.partial(_step_fn, cfg, statics, tables)
        return jax.lax.scan(step, carry, xs)

    carry, ys = shard_map_compat(
        body, mesh,
        in_specs=(statics_spec, tables_spec, carry_spec, xs_spec),
        out_specs=(carry_spec, ys_spec),
    )(statics, tables, carry, xs)
    if k > 1:
        ys = {kk: v.reshape((-1,) + v.shape[2:]) for kk, v in ys.items()}
    return carry, ys


@functools.partial(
    jax.jit, donate_argnames=("statics", "delta", "carry"))
def _node_col_apply(statics, delta, carry, lane, cols):
    """Write one node lane's columns into the sharded session state in a
    single fused launch. The node-axis position of every leaf comes from
    the same rule table that placed it, so the scatter follows whatever
    sharding the rules declared."""
    out = {"statics": dict(statics), "delta": dict(delta),
           "carry": dict(carry)}
    for group, g_cols in cols.items():
        specs = session_specs(group, out[group])
        for k2, colv in g_cols.items():
            arr = out[group][k2]
            axis = list(specs[k2]).index(NODE_AXIS)
            out[group][k2] = jax.lax.dynamic_update_slice_in_dim(
                arr, jnp.asarray(colv).astype(arr.dtype), lane, axis=axis)
    return out["statics"], out["delta"], out["carry"]


class ShardedPallasSession:
    """Session API (schedule/decisions) over the two-phase sharded scan.

    Construction derives every static from PallasSession's prologue (the
    envelope gates — GCD int32 rescale bounds, <=8 constraints, <=128
    topology values, f32-exact weights, the IPA term/key budgets — apply
    identically), then splits the node axis over the mesh. Affinity-TERM
    templates are supported: the D1-D5 ucnt carry is node-sharded like
    every other per-node count, and the two scalars that are genuinely
    global (the kcnt key-presence totals and the rowany presence flags)
    ride psum/pmax. Raises PallasUnsupported exactly where the pallas
    kernel would."""

    # KTPU_EXPLAIN demotes the mesh to the GSPMD hoisted session — the
    # two-phase scan's phase-A argmax discards the per-plugin sections
    # explain mode needs (same contract as PallasSession)
    supports_explain = False

    @staticmethod
    def explain_payload(ys):
        return None

    # ktpu: allow-sync(session build: host mirrors of shard planes built once at construction)
    def __init__(self, cluster: Dict, template_arrays_list: List[Dict],
                 weights: Optional[Dict[str, int]] = None,
                 mesh: Optional[Mesh] = None,
                 multipod_k: Optional[int] = None):
        assert mesh is not None, "ShardedPallasSession needs a mesh"
        if len(mesh.devices.ravel()) < 1:
            raise PallasUnsupported("empty mesh", reason="other")
        inner = PallasSession(cluster, template_arrays_list, weights)
        # multi-pod steps (conflict-SUFFIX contract: flagged pods are
        # uncommitted; the backend replays them through the live session)
        self.multipod_k = K_ops.multipod_k(multipod_k)
        self.mesh = mesh
        self.weights = inner.weights
        self._fps = inner._fps
        self._tp_np = inner._tp_np
        # session-delta interface (tpu_backend classification + apply):
        # same GCD-divisibility envelope and term-match gate as the
        # single-device pallas carry this mirrors
        self._gcd = inner._gcd
        self.dyn_ipa = inner.dyn_ipa
        self._term_np = inner._term_np
        # host mirror of the scaled alloc columns: apply_deltas re-checks
        # the CUMULATIVE int32 score headroom on node-alloc patches (the
        # same guard as PallasSession._patch_alloc_static)
        self._alloc = inner._alloc
        self.T, self.C, self.CP = inner.T, inner.C, inner.CP
        self.R, self.SR, self.K = inner.R, inner.SR, inner.K
        self.TCp = inner.TCp
        nsh = len(mesh.devices.ravel())
        Npl = _ceil(max(inner.Np // nsh, 1), LANE)
        while Npl * nsh < inner.Np:
            Npl += LANE
        self.Npl, self.Nps = Npl, Npl * nsh
        self.UR = inner._ipa["UR"] if inner._ipa is not None else 0
        self._cfg = (
            (self.T, self.C, self.CP, self.R, self.SR, self.K,
             Npl, self.TCp, self.UR),
            tuple(sorted(self.weights.items())),
        )

        def padn(a, axis, fill=0):
            a = np.asarray(a)
            pad = self.Nps - a.shape[axis]
            if pad == 0:
                return a
            widths = [(0, 0)] * a.ndim
            widths[axis] = (0, pad)
            return np.pad(a, widths, constant_values=fill)

        T, SR, TCp = self.T, self.SR, self.TCp
        statics = {
            "alloc": padn(inner._alloc, 1),
            # (T, SR, Nps): template-indexed static rows
            "stat": padn(inner._stat[:T * SR], 1).reshape(T, SR, self.Nps),
            "regrow_f": padn(inner._regrow_f, 1),
            "zvalid_node_s": padn(inner._zvalid_node_s, 1),
            "konn_f": padn(inner._konn_f, 1),
            "konn_s": padn(inner._konn_s, 1),
            "shasall": padn(inner._shasall[:T], 1),
            "valid_n": padn(inner._valid_n[0:1], 1),
            "prow_f": padn(inner._prow_f, 1, fill=-1),
            "prow_s": padn(inner._prow_s, 1, fill=-1),
            "onehot": padn(inner._onehot, 1),
            # replicated but grouped here for the step's block() reads
            "zvalid_s_rows": inner._zvalid_s,
        }
        tb = inner._sc_tables
        CP = self.CP

        def same_pad(a):  # [T, C, C] -> [T, CP, CP]
            out = np.zeros((T, CP, CP), np.float32)
            out[:, :self.C, :self.C] = a
            return out

        tables = {
            "req": inner._req_s,
            "req_check": inner._req_check_s,
            "req_has_any": inner._req_has_any_s,
            "nz_req": inner._nz_req_s,
            "f_valid": tb["f_valid"].astype(np.int32),
            "s_valid": tb["s_valid"].astype(np.int32),
            "f_skew": tb["f_skew"].astype(np.int32),
            "s_skew": tb["s_skew"].astype(np.int32),
            "f_self_match": tb["f_self_match"].astype(np.int32),
            "s_first": tb["s_first"].astype(np.int32),
            "s_perno": inner._s_perno.astype(np.int32),
            "s_keyid": inner._s_keyid,
            "f_same": same_pad(tb["f_same_key"]),
            "s_same": same_pad(tb["s_same_key"]),
            "ipa_present": tb["ipa_present"].astype(np.int32),
            "s_perno_rows": _perno_rows(inner._s_perno, T, self.C, CP),
            # multipod IPA interference superset (pallas _build_ipa; all
            # zeros for term-free sessions): G[u, t] != 0 means assuming
            # a template-u pod can perturb a template-t evaluation
            "gmat": inner._gmat[:T, :T],
        }
        if self.UR:
            # IPA term machinery (pallas _build_ipa products): node-axis
            # blocks reshaped template-major for the step's
            # dynamic_index reads; gate/weight matrices replicated
            ipa = inner._ipa
            S8, UR = SUB_IPA, self.UR
            statics["ipa_stat"] = padn(
                ipa["ipa_stat"][:2 * T], 1).reshape(T, 2, self.Nps)
            statics["anti_static"] = padn(
                ipa["anti_static"], 1).reshape(T, S8, self.Nps)
            statics["anti_konn"] = padn(
                ipa["anti_konn"], 1).reshape(T, S8, self.Nps)
            statics["aff_static"] = padn(
                ipa["aff_static"], 1).reshape(T, S8, self.Nps)
            statics["prow_ipa"] = padn(ipa["prow_ipa"], 1, fill=-1)
            tables["g1"] = ipa["g1"][:T]
            tables["wanti"] = ipa["wanti"].reshape(T, S8, UR)
            tables["waff"] = ipa["waff"].reshape(T, S8, UR)
            tables["w3tot"] = ipa["w3tot"][:T]
            tables["w45"] = ipa["w45"][:T]
            tables["w45_scale"] = np.int32(ipa["w45_scale"])
            tables["gpres"] = ipa["gpres"][:T]
            tables["has_aff"] = ipa["has_aff"].astype(np.int32)
            tables["self_match_all"] = ipa["self_match_all"].astype(np.int32)
            tables["aff_total"] = ipa["aff_total"].astype(np.int32)
            tables["anti_valid"] = ipa["anti_valid"].astype(np.int32)
            tables["aff_valid"] = ipa["aff_valid"].astype(np.int32)
        # session-delta statics (apply_deltas): the same-pair masks read
        # prow_f/prow_s (node-sharded statics); the cnt_sn factor needs
        # the row-expanded s_src (node-sharded) + perno flags
        delta_statics = {
            "src_rows": padn(inner._src_rows, 1),
            "perno_rows": inner._perno_rows,
        }
        carry0 = {
            "requested": padn(inner._requested0, 1),
            "nzpc": padn(inner._nzpc0, 1),
            "cnt_fn": padn(inner._cnt_fn0, 1),
            "cnt_sn": padn(inner._cnt_sn0, 1),
        }
        if self.UR:
            # session starts with zero ASSUMED pods (existing pods live
            # in the static tables); kcnt is PER-SHARD partial totals —
            # one column per shard, psum'd at read
            carry0["ucnt"] = np.zeros((self.UR, self.Nps), np.int32)
            carry0["kcnt"] = np.zeros((self.UR, nsh), np.int32)
        # device placement is DECLARED by the session rule table
        # (parallel/partition.py SESSION_PARTITION_RULES): node-sharded
        # leaves split over the mesh so collectives ride ICI, tables
        # replicate, and a leaf no rule covers fails construction loudly
        placed = shard_tree(
            {"statics": statics, "tables": tables,
             "delta": delta_statics, "carry": carry0},
            SESSION_PARTITION_RULES, mesh)
        self._statics = placed["statics"]
        self._tables = placed["tables"]
        self._delta_statics = placed["delta"]
        self._carry = placed["carry"]

        # ---- node-delta envelope (node_join_delta / node_leave_delta) --
        # Node add/remove stays a per-lane column write when NOTHING
        # cross-node can change: no assumed-term machinery (UR), no
        # existing-pod affinity terms, no image-locality scores (they
        # embed the global node count), and hostname-only score
        # topologies (zone one-hots embed a global value vocab). Within
        # that envelope a 1-node slice session reproduces the full
        # rebuild's column exactly (see node_join_delta).
        self._templates = list(template_arrays_list)
        f_valid_b = np.asarray(tb["f_valid"], bool)
        s_valid_b = np.asarray(tb["s_valid"], bool)
        rows_f = np.zeros(TCp, bool)
        rows_s = np.zeros(TCp, bool)
        for t in range(T):
            rows_f[t * CP:t * CP + self.C] = f_valid_b[t]
            rows_s[t * CP:t * CP + self.C] = s_valid_b[t]
        self._rows_f_valid, self._rows_s_valid = rows_f, rows_s
        # host mirrors of the sharded pair rows: the fresh-pair /
        # pair-distinct envelope checks run against these (kept in sync
        # by the node deltas themselves)
        self._prow_f_np = padn(inner._prow_f, 1, fill=-1)
        self._prow_s_np = padn(inner._prow_s, 1, fill=-1)
        cluster_terms = bool(
            np.asarray(cluster["at_valid"]).any()
            or np.asarray(cluster["st_valid"]).any())
        img_rows = inner._stat[:T * SR].reshape(T, SR, -1)[:, 4, :]
        self._node_delta_ok = (
            self.UR == 0 and not cluster_terms
            and not img_rows.any()
            and bool(np.all(inner._s_perno[s_valid_b])))

    def schedule(self, pod_arrays_list: List[Dict]) -> Dict:
        """Enqueue one batch (async); decisions(ys) blocks. KeyError on
        an unregistered template — the backend rebuilds, same contract as
        the other sessions."""
        B = len(pod_arrays_list)
        Bp, tmpl, mfa, msa = batch_prologue(
            self._fps, self._tp_np, pod_arrays_list, minimum=64)
        T, C, CP, TCp = self.T, self.C, self.CP, self.TCp
        mfx = np.zeros((Bp, TCp), np.float32)
        msx = np.zeros((Bp, TCp), np.float32)
        for t in range(T):
            mfx[:B, t * CP:t * CP + C] = mfa[t].reshape(B, C)
            msx[:B, t * CP:t * CP + C] = msa[t].reshape(B, C)
        xs = {
            "tmpl": jnp.asarray(tmpl),
            "valid": jnp.asarray(np.arange(Bp) < B),
            "mf": jnp.asarray(mfx),
            "ms": jnp.asarray(msx),
        }
        k = min(self.multipod_k, Bp)
        self._carry, ys = _sharded_scan(
            self._cfg, self.mesh, self._statics, self._tables,
            self._carry, xs, k=k)
        out = {"best": ys["best"], "score": ys["score"],
               "n_feasible": ys["n_feasible"], "_b_real": B}
        if k > 1:
            out["conflicts"] = ys["conflicts"]
        return out

    @staticmethod
    # ktpu: allow-sync(harvest decode: host consumes batch verdicts after the launch completes)
    def decisions(ys: Dict) -> List[int]:
        best = np.asarray(ys["best"])
        return [int(v) for v in best[: ys["_b_real"]]]

    @staticmethod
    # ktpu: allow-sync(harvest decode: host reads conflict planes after the launch completes)
    def conflict_stats(ys: Dict):
        """(n_conflicts, replay_suffix_start): the sharded multipod step
        does NOT replay in-device (collectives under lax.cond) — the
        first flagged pod and everything after it in the batch were left
        uncommitted, and the caller must replay exactly that suffix
        through the session (the carry holds the committed prefix).
        n_conflicts is 1 — one detection headed the suffix; later flags
        are collateral, and genuine later conflicts are re-detected and
        re-counted when the replayed suffix runs."""
        c = ys.get("conflicts")
        if c is None:
            return 0, None
        flags = np.asarray(c)[: ys["_b_real"]] != 0
        if not flags.any():
            return 0, None
        return 1, int(np.argmax(flags))

    # -- incremental device-state deltas -----------------------------------

    # same GCD-divisibility / int32-headroom envelope as the pallas carry
    # this mirrors (self._gcd is the inner session's)
    delta_compatible = PallasSession.delta_compatible

    def apply_deltas(self, deltas: List[Dict]) -> None:
        """Sharded face of the session-delta contract, extended with the
        node-axis deltas (node-join / node-leave): pod/alloc deltas batch
        through the fused _carry_delta_scan in runs, node deltas apply as
        per-lane column writes BETWEEN those runs — ordering matters,
        because a pod delta may reference a lane a node-join in the same
        flush introduced."""
        run: List[Dict] = []
        for d in deltas:
            if d["kind"] in ("node-join", "node-leave"):
                if run:
                    self._apply_pod_deltas(run)
                    run = []
                self._statics, self._delta_statics, self._carry = \
                    _node_col_apply(
                        self._statics, self._delta_statics, self._carry,
                        jnp.int32(d["lane"]), d["cols"])
            else:
                run.append(d)
        if run:
            self._apply_pod_deltas(run)

    def _apply_pod_deltas(self, deltas: List[Dict]) -> None:
        """Per-shard counts patch through the SAME fused
        _carry_delta_scan as HoistedSession.apply_deltas — the
        node-sharded carry and the sharded prow/src statics flow through
        GSPMD, so each shard updates only its node slice and the
        per-shard kcnt partials are untouched (batchable pods never
        enter the assumed-term counts)."""
        rp = int(self._carry["requested"].shape[0])
        rows = []
        for d in deltas:
            dres = np.zeros(rp, np.int32)
            dnzpc = np.zeros(SUB_IPA, np.int32)
            mf_rows = np.zeros(self.TCp, np.int32)
            ms_rows = np.zeros(self.TCp, np.int32)
            if d["kind"] == "node-alloc":
                scaled = (
                    np.asarray(d["dalloc"], np.int64) // self._gcd
                ).astype(np.int32)
                n = d["node"]
                col = self._alloc[: self.R, n].astype(np.int64) + scaled
                if int(np.abs(col).max(initial=0)) \
                        * (MAX_NODE_SCORE + 1) >= 2 ** 31:
                    # cumulative capacity bumps outgrew the int32 score
                    # headroom the build guaranteed: rebuild decides
                    raise ValueError(
                        "cumulative alloc patches exceed the int32 "
                        "score headroom")
                self._alloc[: self.R, n] += scaled
                self._statics["alloc"] = (
                    self._statics["alloc"].at[: self.R, n].add(
                        jnp.asarray(scaled))
                )
                dnzpc[3] = d["dallowed"]
            else:
                dres[: self.R] = (
                    np.asarray(d["dres"], np.int64) // self._gcd
                ).astype(np.int32)
                dnzpc[0] = int(d["dnz"][0]) // int(self._gcd[0])
                dnzpc[1] = int(d["dnz"][1]) // int(self._gcd[1])
                dnzpc[2] = d["dcount"]
                for t in range(self.T):
                    mf_rows[t * self.CP: t * self.CP + self.C] = d["mf"][t]
                    ms_rows[t * self.CP: t * self.CP + self.C] = d["ms"][t]
            rows.append((d["node"], dres, dnzpc, mf_rows, ms_rows))
        from .hoisted import batch_bucket

        ep = batch_bucket(len(rows), minimum=8)
        xs = {
            "node": np.zeros(ep, np.int32),
            "dres": np.zeros((ep, rp), np.int32),
            "dnzpc": np.zeros((ep, SUB_IPA), np.int32),
            "mf": np.zeros((ep, self.TCp), np.int32),
            "ms": np.zeros((ep, self.TCp), np.int32),
        }
        for i, (n, dres, dnzpc, mf_rows, ms_rows) in enumerate(rows):
            xs["node"][i] = n
            xs["dres"][i] = dres
            xs["dnzpc"][i] = dnzpc
            xs["mf"][i] = mf_rows
            xs["ms"][i] = ms_rows
        self._carry = _carry_delta_scan(
            self._carry, self._statics["prow_f"], self._statics["prow_s"],
            self._delta_statics["src_rows"],
            self._delta_statics["perno_rows"],
            {k: jnp.asarray(v) for k, v in xs.items()},
        )

    # -- node-axis deltas --------------------------------------------------

    def _pair_rows_shared(self, pf: np.ndarray, ps: np.ndarray,
                          lane: int) -> bool:
        """True when any pair id in (pf, ps) also appears at ANOTHER lane
        of the same valid constraint row. A shared pair couples lanes
        through the registration rows (f_reg_real in the prologue): the
        node event would change columns other than `lane`, so it must go
        structural. Pair id 0 (node lacks the key) is exempt — konn==0
        gates those lanes dead for the row."""
        for rows_valid, col, mirror in (
                (self._rows_f_valid, pf, self._prow_f_np),
                (self._rows_s_valid, ps, self._prow_s_np)):
            hit = ((mirror == col[:, None]) & (col[:, None] > 0)
                   & rows_valid[:, None])
            hit[:, lane] = False
            if hit.any():
                return True
        return False

    def node_join_delta(self, slice_cluster: Dict,
                        lane: int) -> Optional[Dict]:
        """Column-write delta for a node ADD at `lane`, or None when the
        add falls outside the delta envelope (caller rebuilds).

        The column comes from a 1-node PallasSession built on the node's
        own slice of the encoding (pod rows and term tables zeroed, see
        ClusterEncoding.node_slice_cluster). Inside the envelope —
        _node_delta_ok, fresh pair ids, a pod-free node — that slice's
        lane 0 IS what a full rebuild would put at `lane`: every
        surviving static is per-node, pair ids are global encoding vocab
        ids, and a fresh pair's registration equals the node's own
        eligibility. The alloc column is rescaled by the LIVE session's
        per-dimension GCD from the raw encoding values (the slice
        derives its own, coarser GCD)."""
        if not self._node_delta_ok or not (0 <= lane < self.Nps):
            return None
        try:
            s1 = PallasSession(slice_cluster, self._templates, self.weights)
        except (PallasUnsupported, KeyError):
            return None
        T, SR, TCp = self.T, self.SR, self.TCp
        if (s1.T, s1.C, s1.CP, s1.SR, s1.R) != (
                T, self.C, self.CP, SR, self.R):
            return None
        raw = np.asarray(slice_cluster["alloc"], np.int64)[0]     # [R]
        if np.any(raw % self._gcd[: self.R]):
            return None
        scaled = raw // self._gcd[: self.R]
        if int(np.abs(scaled).max(initial=0)) * (MAX_NODE_SCORE + 1) \
                >= 2 ** 31:
            return None
        # a fresh node carries no pods: its utilization columns are zero
        # apart from the allowed-pods budget (nzpc row 3)
        if s1._requested0[:, 0].any() or s1._nzpc0[:3, 0].any():
            return None
        pf = s1._prow_f[: TCp, 0].copy()
        ps = s1._prow_s[: TCp, 0].copy()
        if int(max(pf.max(initial=0), ps.max(initial=0))) >= 2 ** 24:
            return None
        if self._pair_rows_shared(pf, ps, lane):
            return None
        stat_col = s1._stat[: T * SR].reshape(T, SR, -1)[:, :, 0]
        if stat_col[:, 1].any() or stat_col[:, 4].any():
            # the slice disagrees with the live envelope (terms / image
            # scores at the joining node) — structural
            return None
        alloc_col = np.zeros(self._alloc.shape[0], np.int32)
        alloc_col[: self.R] = scaled.astype(np.int32)
        cols = {
            "statics": {
                "alloc": alloc_col[:, None],
                "stat": stat_col[:, :, None],
                "regrow_f": s1._regrow_f[: TCp, 0:1],
                "konn_f": s1._konn_f[: TCp, 0:1],
                "konn_s": s1._konn_s[: TCp, 0:1],
                "shasall": s1._shasall[: T, 0:1],
                "valid_n": np.ones((1, 1), np.int32),
                "prow_f": pf[:, None],
                "prow_s": ps[:, None],
            },
            "delta": {"src_rows": s1._src_rows[: TCp, 0:1]},
            "carry": {
                "requested": np.zeros(
                    (int(self._carry["requested"].shape[0]), 1), np.int32),
                "nzpc": s1._nzpc0[:, 0:1],
                "cnt_fn": s1._cnt_fn0[: TCp, 0:1],
                "cnt_sn": s1._cnt_sn0[: TCp, 0:1],
            },
        }
        # host mirrors move at QUEUE time so later joins/leaves in the
        # same flush check against the post-queue state
        self._prow_f_np[:, lane] = pf
        self._prow_s_np[:, lane] = ps
        self._alloc[:, lane] = alloc_col
        return {"kind": "node-join", "lane": lane, "cols": cols}

    def node_leave_delta(self, lane: int) -> Optional[Dict]:
        """Column-clear delta for a node REMOVE at `lane` (the lane
        reverts to padding form: invalid, zero statics and counts, -1
        pair rows), or None outside the envelope. The caller guarantees
        the node hosts no pods; shared pair ids go structural for the
        same registration reason as joins."""
        if not self._node_delta_ok or not (0 <= lane < self.Nps):
            return None
        if self._pair_rows_shared(self._prow_f_np[:, lane],
                                  self._prow_s_np[:, lane], lane):
            return None
        T, SR, TCp = self.T, self.SR, self.TCp
        z = np.zeros((TCp, 1), np.int32)
        cols = {
            "statics": {
                "alloc": np.zeros((self._alloc.shape[0], 1), np.int32),
                "stat": np.zeros((T, SR, 1), np.int32),
                "regrow_f": z, "konn_f": z, "konn_s": z,
                "shasall": np.zeros((T, 1), np.int32),
                "valid_n": np.zeros((1, 1), np.int32),
                "prow_f": np.full((TCp, 1), -1, np.int32),
                "prow_s": np.full((TCp, 1), -1, np.int32),
            },
            "delta": {"src_rows": z},
            "carry": {
                "requested": np.zeros(
                    (int(self._carry["requested"].shape[0]), 1), np.int32),
                "nzpc": np.zeros(
                    (int(self._carry["nzpc"].shape[0]), 1), np.int32),
                "cnt_fn": z, "cnt_sn": z,
            },
        }
        self._prow_f_np[:, lane] = -1
        self._prow_s_np[:, lane] = -1
        self._alloc[:, lane] = 0
        return {"kind": "node-leave", "lane": lane, "cols": cols}


def _perno_rows(s_perno: np.ndarray, T: int, C: int, CP: int) -> np.ndarray:
    out = np.zeros(T * CP, np.float32)
    for t in range(T):
        out[t * CP:t * CP + C] = s_perno[t].astype(np.float32)
    return out
