"""Controller loop tests: replicaset, deployment, job, daemonset,
statefulset, endpoints, namespace, GC, nodelifecycle.

Mirrors the reference's controller unit/integration style (reference:
pkg/controller/replicaset/replica_set_test.go et al.): a real in-proc
apiserver + store, informers, and the controller under test; pod
execution is faked by flipping pod status (the integration suites' "pods
never run" property, test/integration/ README).
"""

from __future__ import annotations

import copy
import time

import pytest

from kubernetes_tpu.api import apps, batch, types as v1
from kubernetes_tpu.apiserver.server import APIServer, NotFound
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.daemonset import DaemonSetController
from kubernetes_tpu.controllers.deployment import DeploymentController
from kubernetes_tpu.controllers.endpoints import EndpointsController
from kubernetes_tpu.controllers.garbagecollector import GarbageCollector
from kubernetes_tpu.controllers.job import JobController
from kubernetes_tpu.controllers.namespace import NamespaceController
from kubernetes_tpu.controllers.nodelifecycle import NodeLifecycleController
from kubernetes_tpu.controllers.replicaset import ReplicaSetController
from kubernetes_tpu.controllers.statefulset import StatefulSetController

from .util import make_node


def wait_until(cond, timeout: float = 10.0, interval: float = 0.05) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


def pod_template(labels) -> v1.PodTemplateSpec:
    return v1.PodTemplateSpec(
        metadata=v1.ObjectMeta(labels=dict(labels)),
        spec=v1.PodSpec(
            containers=[
                v1.Container(
                    name="c",
                    image="img:1",
                    resources=v1.ResourceRequirements(requests={"cpu": "100m"}),
                )
            ]
        ),
    )


def mark_running_ready(client: Clientset, pod: v1.Pod, ip: str = "10.0.0.1") -> None:
    p = copy.deepcopy(pod)
    p.status.phase = "Running"
    p.status.pod_ip = ip
    p.status.start_time = time.time()
    p.status.conditions = [v1.PodCondition(type="Ready", status="True")]
    client.pods.update_status(p)


@pytest.fixture()
def cluster():
    api = APIServer()
    client = Clientset(api)
    informers = SharedInformerFactory(client)
    yield api, client, informers
    informers.stop()


def start(informers, *controllers):
    informers.start()
    assert informers.wait_for_cache_sync()
    for c in controllers:
        c.run()


# ---------------------------------------------------------------------------


def test_replicaset_scales_up_and_down(cluster):
    api, client, informers = cluster
    ctrl = ReplicaSetController(client, informers)
    start(informers, ctrl)
    rs = apps.ReplicaSet(
        metadata=v1.ObjectMeta(name="web", namespace="default"),
        spec=apps.ReplicaSetSpec(
            replicas=3,
            selector=v1.LabelSelector(match_labels={"app": "web"}),
            template=pod_template({"app": "web"}),
        ),
    )
    client.replicasets.create(rs)
    wait_until(lambda: len(client.pods.list(namespace="default")[0]) == 3)
    pods, _ = client.pods.list(namespace="default")
    assert all(
        p.metadata.owner_references[0].name == "web" for p in pods
    )
    # status converges
    wait_until(
        lambda: client.replicasets.get("web", "default").status.replicas == 3
    )
    # scale down
    live = client.replicasets.get("web", "default")
    live.spec.replicas = 1
    client.replicasets.update(live)
    wait_until(lambda: len(client.pods.list(namespace="default")[0]) == 1)
    ctrl.stop()


def test_replicaset_adopts_orphans_and_replaces_deleted(cluster):
    api, client, informers = cluster
    ctrl = ReplicaSetController(client, informers)
    start(informers, ctrl)
    orphan = v1.Pod(
        metadata=v1.ObjectMeta(
            name="orphan", namespace="default", labels={"app": "web"}
        ),
        spec=v1.PodSpec(containers=[v1.Container(name="c")]),
    )
    client.pods.create(orphan)
    rs = apps.ReplicaSet(
        metadata=v1.ObjectMeta(name="web", namespace="default"),
        spec=apps.ReplicaSetSpec(
            replicas=2,
            selector=v1.LabelSelector(match_labels={"app": "web"}),
            template=pod_template({"app": "web"}),
        ),
    )
    client.replicasets.create(rs)
    wait_until(lambda: len(client.pods.list(namespace="default")[0]) == 2)
    adopted = client.pods.get("orphan", "default")
    assert adopted.metadata.owner_references and (
        adopted.metadata.owner_references[0].kind == "ReplicaSet"
    )
    # kill one pod; controller replaces it
    victim = client.pods.list(namespace="default")[0][0]
    client.pods.delete(victim.metadata.name, "default")
    wait_until(lambda: len(client.pods.list(namespace="default")[0]) == 2)
    ctrl.stop()


def test_deployment_rolling_update(cluster):
    api, client, informers = cluster
    rs_ctrl = ReplicaSetController(client, informers)
    d_ctrl = DeploymentController(client, informers)
    start(informers, rs_ctrl, d_ctrl)
    d = apps.Deployment(
        metadata=v1.ObjectMeta(name="api", namespace="default"),
        spec=apps.DeploymentSpec(
            replicas=3,
            selector=v1.LabelSelector(match_labels={"app": "api"}),
            template=pod_template({"app": "api"}),
        ),
    )
    client.deployments.create(d)
    wait_until(lambda: len(client.pods.list(namespace="default")[0]) == 3)

    # keep pods ready so the rollout can make progress
    stop_flag = []

    def readiness_loop():
        while not stop_flag:
            for p in client.pods.list(namespace="default")[0]:
                if p.status.phase != "Running":
                    try:
                        mark_running_ready(client, p)
                    except Exception:
                        pass
            time.sleep(0.05)

    import threading

    t = threading.Thread(target=readiness_loop, daemon=True)
    t.start()
    try:
        wait_until(
            lambda: client.deployments.get("api", "default").status.available_replicas
            == 3
        )
        old_rs = client.replicasets.list(namespace="default")[0][0]
        # rollout: change the template
        live = client.deployments.get("api", "default")
        live.spec.template.spec.containers[0].image = "img:2"
        client.deployments.update(live)

        def rolled_out():
            rses, _ = client.replicasets.list(namespace="default")
            if len(rses) < 2:
                return False
            new = [r for r in rses if r.metadata.uid != old_rs.metadata.uid]
            old = [r for r in rses if r.metadata.uid == old_rs.metadata.uid]
            return (
                new
                and new[0].status.available_replicas == 3
                and old
                and old[0].status.replicas == 0
            )

        wait_until(rolled_out, timeout=20)
        # every surviving pod runs the new image
        for p in client.pods.list(namespace="default")[0]:
            assert p.spec.containers[0].image == "img:2"
    finally:
        stop_flag.append(True)
        t.join(timeout=2)
    d_ctrl.stop()
    rs_ctrl.stop()


def test_job_runs_to_completion(cluster):
    api, client, informers = cluster
    ctrl = JobController(client, informers)
    start(informers, ctrl)
    job = batch.Job(
        metadata=v1.ObjectMeta(name="calc", namespace="default"),
        spec=batch.JobSpec(
            parallelism=2,
            completions=3,
            template=pod_template({"job": "calc"}),
        ),
    )
    client.jobs.create(job)
    wait_until(
        lambda: sum(
            1
            for p in client.pods.list(namespace="default")[0]
            if p.status.phase not in ("Succeeded", "Failed")
        )
        == 2
    )
    # complete pods as they appear until the job finishes
    deadline = time.time() + 15

    def finished():
        j = client.jobs.get("calc", "default")
        for c in j.status.conditions or []:
            if c.type == "Complete" and c.status == "True":
                return True
        return False

    while time.time() < deadline and not finished():
        for p in client.pods.list(namespace="default")[0]:
            if p.status.phase not in ("Succeeded", "Failed"):
                done = copy.deepcopy(p)
                done.status.phase = "Succeeded"
                try:
                    client.pods.update_status(done)
                except Exception:
                    pass
        time.sleep(0.05)
    assert finished()
    j = client.jobs.get("calc", "default")
    assert j.status.succeeded >= 3
    ctrl.stop()


def test_job_backoff_limit_fails_job(cluster):
    api, client, informers = cluster
    ctrl = JobController(client, informers)
    start(informers, ctrl)
    job = batch.Job(
        metadata=v1.ObjectMeta(name="flaky", namespace="default"),
        spec=batch.JobSpec(
            parallelism=1, completions=1, backoff_limit=1,
            template=pod_template({"job": "flaky"}),
        ),
    )
    client.jobs.create(job)

    def job_failed():
        j = client.jobs.get("flaky", "default")
        return any(
            c.type == "Failed" and c.status == "True" for c in j.status.conditions or []
        )

    deadline = time.time() + 15
    while time.time() < deadline and not job_failed():
        for p in client.pods.list(namespace="default")[0]:
            if p.status.phase not in ("Succeeded", "Failed"):
                dead = copy.deepcopy(p)
                dead.status.phase = "Failed"
                try:
                    client.pods.update_status(dead)
                except Exception:
                    pass
        time.sleep(0.05)
    assert job_failed()
    ctrl.stop()


def test_daemonset_one_pod_per_eligible_node(cluster):
    api, client, informers = cluster
    ctrl = DaemonSetController(client, informers)
    for i in range(3):
        client.nodes.create(make_node(f"node-{i}"))
    tainted = make_node(
        "node-tainted",
        taints=[v1.Taint(key="dedicated", value="gpu", effect="NoSchedule")],
    )
    client.nodes.create(tainted)
    start(informers, ctrl)
    ds = apps.DaemonSet(
        metadata=v1.ObjectMeta(name="agent", namespace="kube-system"),
        spec=apps.DaemonSetSpec(
            selector=v1.LabelSelector(match_labels={"app": "agent"}),
            template=pod_template({"app": "agent"}),
        ),
    )
    client.daemonsets.create(ds)
    wait_until(lambda: len(client.pods.list(namespace="kube-system")[0]) == 3)
    pods, _ = client.pods.list(namespace="kube-system")
    pinned = {DaemonSetController._pinned_node(p) for p in pods}
    assert pinned == {"node-0", "node-1", "node-2"}
    # new node joins → new daemon pod
    client.nodes.create(make_node("node-3"))
    wait_until(lambda: len(client.pods.list(namespace="kube-system")[0]) == 4)
    ctrl.stop()


def test_statefulset_ordered_creation(cluster):
    api, client, informers = cluster
    ctrl = StatefulSetController(client, informers)
    start(informers, ctrl)
    ss = apps.StatefulSet(
        metadata=v1.ObjectMeta(name="db", namespace="default"),
        spec=apps.StatefulSetSpec(
            replicas=3,
            selector=v1.LabelSelector(match_labels={"app": "db"}),
            template=pod_template({"app": "db"}),
        ),
    )
    client.statefulsets.create(ss)
    # only db-0 exists until it's ready
    wait_until(lambda: len(client.pods.list(namespace="default")[0]) == 1)
    time.sleep(0.3)
    pods, _ = client.pods.list(namespace="default")
    assert [p.metadata.name for p in pods] == ["db-0"]
    mark_running_ready(client, pods[0])
    wait_until(lambda: len(client.pods.list(namespace="default")[0]) == 2)
    for p in client.pods.list(namespace="default")[0]:
        if p.status.phase != "Running":
            mark_running_ready(client, p, ip="10.0.0.2")
    wait_until(
        lambda: {p.metadata.name for p in client.pods.list(namespace="default")[0]}
        == {"db-0", "db-1", "db-2"}
    )
    # scale down removes highest ordinal first
    for p in client.pods.list(namespace="default")[0]:
        if p.status.phase != "Running":
            mark_running_ready(client, p, ip="10.0.0.3")
    live = client.statefulsets.get("db", "default")
    live.spec.replicas = 1
    client.statefulsets.update(live)
    wait_until(
        lambda: {p.metadata.name for p in client.pods.list(namespace="default")[0]}
        == {"db-0"},
        timeout=15,
    )
    ctrl.stop()


def test_endpoints_controller_tracks_ready_pods(cluster):
    api, client, informers = cluster
    ctrl = EndpointsController(client, informers)
    start(informers, ctrl)
    svc = v1.Service(
        metadata=v1.ObjectMeta(name="web", namespace="default"),
        spec=v1.ServiceSpec(
            selector={"app": "web"},
            ports=[v1.ServicePort(name="http", port=80, target_port=8080)],
        ),
    )
    client.services.create(svc)
    pod = v1.Pod(
        metadata=v1.ObjectMeta(name="w1", namespace="default", labels={"app": "web"}),
        spec=v1.PodSpec(containers=[v1.Container(name="c")], node_name="node-0"),
    )
    client.pods.create(pod)
    mark_running_ready(client, client.pods.get("w1", "default"), ip="10.1.2.3")

    def ep_ready():
        try:
            ep = client.endpoints.get("web", "default")
        except NotFound:
            return False
        if not ep.subsets:
            return False
        addrs = ep.subsets[0].addresses or []
        return [a.ip for a in addrs] == ["10.1.2.3"]

    wait_until(ep_ready)
    ep = client.endpoints.get("web", "default")
    assert ep.subsets[0].ports[0].port == 8080
    # pod becomes unready → moves to notReadyAddresses
    p = client.pods.get("w1", "default")
    p.status.conditions = [v1.PodCondition(type="Ready", status="False")]
    client.pods.update_status(p)

    def ep_not_ready():
        ep = client.endpoints.get("web", "default")
        if not ep.subsets:
            return False
        s = ep.subsets[0]
        return not s.addresses and [a.ip for a in s.not_ready_addresses or []] == [
            "10.1.2.3"
        ]

    wait_until(ep_not_ready)
    ctrl.stop()


def test_namespace_deletion_drains_contents(cluster):
    api, client, informers = cluster
    ctrl = NamespaceController(client, informers)
    start(informers, ctrl)
    client.namespaces.create(v1.Namespace(metadata=v1.ObjectMeta(name="scratch")))
    wait_until(
        lambda: "kubernetes"
        in (client.namespaces.get("scratch").metadata.finalizers or [])
    )
    client.configmaps.create(
        v1.ConfigMap(
            metadata=v1.ObjectMeta(name="cfg", namespace="scratch"),
            data={"k": "v"},
        )
    )
    client.pods.create(
        v1.Pod(
            metadata=v1.ObjectMeta(name="p", namespace="scratch"),
            spec=v1.PodSpec(containers=[v1.Container(name="c")]),
        )
    )
    client.namespaces.delete("scratch")

    def gone():
        try:
            client.namespaces.get("scratch")
            return False
        except NotFound:
            return True

    wait_until(gone)
    assert client.configmaps.list(namespace="scratch")[0] == []
    assert client.pods.list(namespace="scratch")[0] == []
    ctrl.stop()


def test_garbage_collector_cascades(cluster):
    api, client, informers = cluster
    gc = GarbageCollector(client, scan_interval=0.05)
    rs = apps.ReplicaSet(
        metadata=v1.ObjectMeta(name="owner", namespace="default"),
        spec=apps.ReplicaSetSpec(
            replicas=0, selector=v1.LabelSelector(match_labels={"a": "b"})
        ),
    )
    created = client.replicasets.create(rs)
    pod = v1.Pod(
        metadata=v1.ObjectMeta(
            name="child",
            namespace="default",
            owner_references=[
                v1.OwnerReference(
                    api_version="apps/v1",
                    kind="ReplicaSet",
                    name="owner",
                    uid=created.metadata.uid,
                    controller=True,
                )
            ],
        ),
        spec=v1.PodSpec(containers=[v1.Container(name="c")]),
    )
    client.pods.create(pod)
    gc.run()
    time.sleep(0.3)
    # owner alive → child kept
    assert client.pods.get("child", "default") is not None
    client.replicasets.delete("owner", "default")

    def child_gone():
        try:
            client.pods.get("child", "default")
            return False
        except NotFound:
            return True

    wait_until(child_gone)
    gc.stop()


def test_nodelifecycle_marks_unknown_taints_and_evicts(cluster):
    api, client, informers = cluster
    ctrl = NodeLifecycleController(
        client,
        informers,
        node_monitor_period=0.1,
        node_monitor_grace_period=0.5,
    )
    node = make_node("node-a")
    node.status.conditions = [
        v1.NodeCondition(
            type="Ready", status="True", last_heartbeat_time=time.time()
        )
    ]
    client.nodes.create(node)
    pod = v1.Pod(
        metadata=v1.ObjectMeta(name="victim", namespace="default"),
        spec=v1.PodSpec(containers=[v1.Container(name="c")], node_name="node-a"),
    )
    client.pods.create(pod)
    start(informers)
    ctrl.run()
    # no heartbeats arrive → grace period expires
    wait_until(
        lambda: any(
            c.type == "Ready" and c.status == "Unknown"
            for c in client.nodes.get("node-a").status.conditions or []
        ),
        timeout=5,
    )
    wait_until(
        lambda: any(
            t.key == v1.TAINT_NODE_UNREACHABLE
            for t in client.nodes.get("node-a").spec.taints or []
        ),
        timeout=5,
    )

    def evicted():
        try:
            client.pods.get("victim", "default")
            return False
        except NotFound:
            return True

    wait_until(evicted, timeout=5)
    ctrl.stop()
