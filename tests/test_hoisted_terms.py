"""Affinity/host-port pods on the hoisted fast path: decision parity of
the term-carrying scan (ops/hoisted.py dynamic-IPA/ports machinery)
against the per-pod kernel with a host sync after EVERY pod — the
sequential path that tests/test_kernel_parity.py pins to the Go oracle.

Reference semantics under test: interpodaffinity/filtering.go:162
(existing-anti map), :341 (incoming anti), :357 (incoming affinity +
first-pod escape hatch), scoring.go:88 (processExistingPod weights),
nodeports/node_ports.go (HostPortInfo conflicts)."""

import copy

import numpy as np
import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.ops.hoisted import (
    HoistedSession,
    schedule_batch_hoisted,
    template_fingerprint,
    templates_have_ports,
    templates_have_terms,
)
from kubernetes_tpu.testing.synth import synth_cluster

from .test_hoisted import _presized_encoding
from .util import make_pod


def _encode_all(enc, pe, pods):
    return [
        {k: v for k, v in pe.encode(p).items() if not k.startswith("_")}
        for p in pods
    ]


def _sequential_reference(nodes, init_pods, pending):
    """Per-pod kernel dispatch + full host sync between pods: the slow
    exact path (tpu_backend.schedule semantics, first-max tie-break)."""
    from kubernetes_tpu.ops.kernel import schedule_pod_jit

    enc, pe = _presized_encoding(nodes, init_pods, pending)
    out = []
    for p in pending:
        pa = {k: v for k, v in pe.encode(p).items() if not k.startswith("_")}
        o = schedule_pod_jit(enc.device_state(), pa)
        total = np.asarray(o["total"])
        if not np.asarray(o["feasible"]).any():
            out.append(-1)
            continue
        best = int(np.argmax(total))
        out.append(best)
        p.spec.node_name = enc.node_names[best]
        enc.add_pod(p, enc.node_names[best])
    return out


def _one_shot(nodes, init_pods, pending):
    enc, pe = _presized_encoding(nodes, init_pods, pending)
    arrays = _encode_all(enc, pe, pending)
    decisions, _ = schedule_batch_hoisted(enc.device_state(), arrays)
    return decisions


def _session(nodes, init_pods, pending, batch):
    enc, pe = _presized_encoding(nodes, init_pods, pending)
    arrays = _encode_all(enc, pe, pending)
    templates, seen = [], set()
    for a in arrays:
        fp = template_fingerprint(a)
        if fp not in seen:
            seen.add(fp)
            templates.append(a)
    sess = HoistedSession(enc.device_state(), templates)
    out = []
    for i in range(0, len(pending), batch):
        out.extend(HoistedSession.decisions(sess.schedule(arrays[i : i + batch])))
    return out


def _assert_all_paths_match(nodes, init_pods, pending, batch=5):
    ref = _sequential_reference(
        nodes, copy.deepcopy(init_pods), copy.deepcopy(pending)
    )
    one = _one_shot(nodes, copy.deepcopy(init_pods), copy.deepcopy(pending))
    ses = _session(nodes, init_pods, pending, batch)
    assert one == ref, f"one-shot hoisted diverged: {one} != {ref}"
    assert ses == ref, f"session diverged: {ses} != {ref}"
    return ref


def _anti_affinity(topology_key, labels):
    return v1.Affinity(
        pod_anti_affinity=v1.PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[
                v1.PodAffinityTerm(
                    label_selector=v1.LabelSelector(match_labels=dict(labels)),
                    topology_key=topology_key,
                )
            ]
        )
    )


def _affinity(topology_key, labels):
    return v1.Affinity(
        pod_affinity=v1.PodAffinity(
            required_during_scheduling_ignored_during_execution=[
                v1.PodAffinityTerm(
                    label_selector=v1.LabelSelector(match_labels=dict(labels)),
                    topology_key=topology_key,
                )
            ]
        )
    )


def _preferred_affinity(topology_key, labels, weight=10, anti=False):
    term = v1.WeightedPodAffinityTerm(
        weight=weight,
        pod_affinity_term=v1.PodAffinityTerm(
            label_selector=v1.LabelSelector(match_labels=dict(labels)),
            topology_key=topology_key,
        ),
    )
    if anti:
        return v1.Affinity(
            pod_anti_affinity=v1.PodAntiAffinity(
                preferred_during_scheduling_ignored_during_execution=[term]
            )
        )
    return v1.Affinity(
        pod_affinity=v1.PodAffinity(
            preferred_during_scheduling_ignored_during_execution=[term]
        )
    )


class TestTermDetection:
    def test_flags(self):
        nodes, init_pods = synth_cluster(4, pods_per_node=0)
        plain = make_pod("plain", cpu="50m")
        anti = make_pod(
            "anti", cpu="50m", labels={"app": "a"},
            affinity=_anti_affinity(v1.LABEL_HOSTNAME, {"app": "a"}),
        )
        porty = make_pod("porty", cpu="50m", host_port=8080)
        enc, pe = _presized_encoding(nodes, init_pods, [plain, anti, porty])
        a_plain, a_anti, a_port = _encode_all(enc, pe, [plain, anti, porty])
        assert not templates_have_terms([a_plain])
        assert templates_have_terms([a_anti])
        assert not templates_have_ports([a_anti])
        assert templates_have_ports([a_port])


class TestAntiAffinityParity:
    def test_hostname_anti_affinity_one_per_node(self):
        """The IPA-churn shape: every pod repels its own template on
        hostname — exactly one per node, the overflow infeasible."""
        nodes, init_pods = synth_cluster(6, pods_per_node=1)
        pending = [
            make_pod(
                f"aa-{i}", cpu="50m", labels={"app": "churn"},
                affinity=_anti_affinity(v1.LABEL_HOSTNAME, {"app": "churn"}),
            )
            for i in range(9)
        ]
        ref = _assert_all_paths_match(nodes, init_pods, pending, batch=4)
        placed = [d for d in ref if d >= 0]
        assert len(placed) == 6 and len(set(placed)) == 6
        assert ref[6:] == [-1, -1, -1]

    def test_zone_anti_affinity(self):
        nodes, init_pods = synth_cluster(9, pods_per_node=1)  # 3 zones
        pending = [
            make_pod(
                f"za-{i}", cpu="50m", labels={"app": "zonal"},
                affinity=_anti_affinity(v1.LABEL_ZONE, {"app": "zonal"}),
            )
            for i in range(5)
        ]
        ref = _assert_all_paths_match(nodes, init_pods, pending, batch=2)
        assert sum(1 for d in ref if d >= 0) == 3  # one per zone
        assert ref[3:] == [-1, -1]

    def test_cross_template_anti_affinity(self):
        """Template A repels template B's label: B's assumes must flip A's
        feasibility mid-scan (the M_anti cross-template gates)."""
        nodes, init_pods = synth_cluster(4, pods_per_node=1)
        b_pods = [
            make_pod(f"b-{i}", cpu="50m", labels={"role": "db"})
            for i in range(2)
        ]
        a_pods = [
            make_pod(
                f"a-{i}", cpu="50m", labels={"role": "web"},
                affinity=_anti_affinity(v1.LABEL_HOSTNAME, {"role": "db"}),
            )
            for i in range(4)
        ]
        # interleave so assumes of B precede later A pods within one batch
        pending = [b_pods[0], a_pods[0], b_pods[1], a_pods[1], a_pods[2], a_pods[3]]
        _assert_all_paths_match(nodes, init_pods, pending, batch=3)

    def test_existing_pods_anti_affinity_repels_incoming(self):
        """An INIT pod with anti-affinity (static at-table rows) and
        session-assumed pods with anti-affinity must both repel."""
        nodes, init_pods = synth_cluster(4, pods_per_node=0)
        guard = make_pod(
            "guard", cpu="50m", labels={"role": "guard"},
            affinity=_anti_affinity(v1.LABEL_HOSTNAME, {"app": "w"}),
        )
        guard.spec.node_name = nodes[0].metadata.name
        init_pods = init_pods + [guard]
        pending = [
            make_pod(f"w-{i}", cpu="50m", labels={"app": "w"}) for i in range(5)
        ]
        ref = _assert_all_paths_match(nodes, init_pods, pending, batch=2)
        assert 0 not in ref  # node-0 guarded by the static anti term


class TestAffinityParity:
    def test_required_affinity_colocates(self):
        nodes, init_pods = synth_cluster(9, pods_per_node=1)  # 3 zones
        seed = make_pod("seed", cpu="50m", labels={"app": "group"})
        seed.spec.node_name = nodes[4].metadata.name
        init_pods = init_pods + [seed]
        pending = [
            make_pod(
                f"g-{i}", cpu="50m", labels={"app": "member"},
                affinity=_affinity(v1.LABEL_ZONE, {"app": "group"}),
            )
            for i in range(4)
        ]
        ref = _assert_all_paths_match(nodes, init_pods, pending, batch=2)
        zone_of = {i: i % 3 for i in range(9)}  # synth zone layout
        assert all(zone_of[d] == zone_of[4] for d in ref if d >= 0)
        assert all(d >= 0 for d in ref)

    def test_self_affinity_escape_hatch_then_pile_on(self):
        """First pod of a self-affine series lands via the first-pod
        escape hatch (filtering.go:357); later pods must see the ASSUMED
        first pod through the dynamic counts and join its zone."""
        nodes, init_pods = synth_cluster(9, pods_per_node=1)
        pending = [
            make_pod(
                f"s-{i}", cpu="50m", labels={"app": "flock"},
                affinity=_affinity(v1.LABEL_ZONE, {"app": "flock"}),
            )
            for i in range(5)
        ]
        ref = _assert_all_paths_match(nodes, init_pods, pending, batch=2)
        assert all(d >= 0 for d in ref)
        zones = {d % 3 for d in ref}
        assert len(zones) == 1  # the whole flock in one zone

    def test_affinity_unsatisfied_infeasible(self):
        """Affinity to a label nothing carries (and no self-match): every
        pod unschedulable, identically on every path."""
        nodes, init_pods = synth_cluster(4, pods_per_node=1)
        pending = [
            make_pod(
                f"u-{i}", cpu="50m", labels={"app": "orphan"},
                affinity=_affinity(v1.LABEL_ZONE, {"app": "nothing-has-this"}),
            )
            for i in range(3)
        ]
        ref = _assert_all_paths_match(nodes, init_pods, pending, batch=2)
        assert ref == [-1, -1, -1]


class TestPreferredScoringParity:
    def test_preferred_affinity_attracts(self):
        nodes, init_pods = synth_cluster(9, pods_per_node=1)
        pending = [
            make_pod(
                f"p-{i}", cpu="50m", labels={"app": "herd"},
                affinity=_preferred_affinity(v1.LABEL_ZONE, {"app": "herd"}, 50),
            )
            for i in range(6)
        ]
        _assert_all_paths_match(nodes, init_pods, pending, batch=2)

    def test_preferred_anti_affinity_spreads(self):
        nodes, init_pods = synth_cluster(6, pods_per_node=1)
        pending = [
            make_pod(
                f"pa-{i}", cpu="50m", labels={"app": "solo"},
                affinity=_preferred_affinity(
                    v1.LABEL_HOSTNAME, {"app": "solo"}, 50, anti=True
                ),
            )
            for i in range(6)
        ]
        ref = _assert_all_paths_match(nodes, init_pods, pending, batch=3)
        assert len(set(ref)) == 6  # soft spread lands one per node

    def test_mixed_preferred_and_required(self):
        nodes, init_pods = synth_cluster(6, pods_per_node=1)
        a = [
            make_pod(
                f"ma-{i}", cpu="50m", labels={"kind": "a"},
                affinity=v1.Affinity(
                    pod_anti_affinity=v1.PodAntiAffinity(
                        required_during_scheduling_ignored_during_execution=[
                            v1.PodAffinityTerm(
                                label_selector=v1.LabelSelector(
                                    match_labels={"kind": "a"}
                                ),
                                topology_key=v1.LABEL_HOSTNAME,
                            )
                        ],
                        preferred_during_scheduling_ignored_during_execution=[
                            v1.WeightedPodAffinityTerm(
                                weight=25,
                                pod_affinity_term=v1.PodAffinityTerm(
                                    label_selector=v1.LabelSelector(
                                        match_labels={"kind": "b"}
                                    ),
                                    topology_key=v1.LABEL_ZONE,
                                ),
                            )
                        ],
                    )
                ),
            )
            for i in range(3)
        ]
        b = [make_pod(f"mb-{i}", cpu="50m", labels={"kind": "b"}) for i in range(3)]
        pending = [b[0], a[0], b[1], a[1], b[2], a[2]]
        _assert_all_paths_match(nodes, init_pods, pending, batch=3)


class TestHostPortParity:
    def test_host_port_one_per_node(self):
        nodes, init_pods = synth_cluster(4, pods_per_node=1)
        pending = [
            make_pod(f"hp-{i}", cpu="50m", host_port=8080) for i in range(6)
        ]
        ref = _assert_all_paths_match(nodes, init_pods, pending, batch=3)
        placed = [d for d in ref if d >= 0]
        assert len(placed) == 4 and len(set(placed)) == 4
        assert ref[4:] == [-1, -1]

    def test_host_port_against_existing(self):
        nodes, init_pods = synth_cluster(3, pods_per_node=0)
        holder = make_pod("holder", cpu="50m", host_port=9000)
        holder.spec.node_name = nodes[1].metadata.name
        init_pods = init_pods + [holder]
        pending = [
            make_pod(f"hx-{i}", cpu="50m", host_port=9000) for i in range(3)
        ]
        ref = _assert_all_paths_match(nodes, init_pods, pending, batch=2)
        assert 1 not in ref[:2]  # node-1's port already taken
        assert sum(1 for d in ref if d >= 0) == 2

    def test_ports_and_spread_together(self):
        nodes, init_pods = synth_cluster(6, pods_per_node=1)
        pending = [
            make_pod(
                f"ps-{i}", cpu="50m", labels={"app": "ps"}, host_port=7070,
                constraints=[
                    v1.TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=v1.LABEL_ZONE,
                        when_unsatisfiable="ScheduleAnyway",
                        label_selector=v1.LabelSelector(
                            match_labels={"app": "ps"}
                        ),
                    )
                ],
            )
            for i in range(6)
        ]
        ref = _assert_all_paths_match(nodes, init_pods, pending, batch=3)
        assert len(set(d for d in ref if d >= 0)) == len(
            [d for d in ref if d >= 0]
        )


class TestBackendRouting:
    def test_affinity_pods_ride_the_session(self):
        """TPUBackend.schedule_many must route term pods through ONE
        session path (no per-pod dispatches), and decisions must match
        the sequential oracle."""
        import random

        from kubernetes_tpu.scheduler.tpu_backend import TPUBackend
        from kubernetes_tpu.testing.synth import synth_cluster as sc

        nodes, init_pods = sc(6, pods_per_node=1)
        backend = TPUBackend(rng=random.Random(0))
        for n in nodes:
            backend.on_add_node(n)
        for p in init_pods:
            backend.on_add_pod(p, p.spec.node_name)
        pending = [
            make_pod(
                f"rt-{i}", cpu="50m", labels={"app": "rt"},
                affinity=_anti_affinity(v1.LABEL_HOSTNAME, {"app": "rt"}),
            )
            for i in range(8)
        ]
        results = backend.schedule_many(pending)
        assert backend._session is not None, "term pods must build a session"
        placed = [n for _, n in results if n is not None]
        assert len(placed) == 6 and len(set(placed)) == 6
        assert [n for _, n in results][6:] == [None, None]

    def test_pallas_downgrade_is_loud(self, caplog):
        """A pallas->hoisted downgrade must hit the session-builds metric
        and log a warning (VERDICT r1: never lose 60% throughput silently)."""
        import logging
        import random

        from kubernetes_tpu.scheduler import metrics as sched_metrics
        from kubernetes_tpu.scheduler.tpu_backend import TPUBackend
        from kubernetes_tpu.testing.synth import synth_cluster as sc

        nodes, init_pods = sc(4, pods_per_node=1)
        backend = TPUBackend(rng=random.Random(0))
        backend.use_pallas = True  # force the pallas attempt even on CPU
        for n in nodes:
            backend.on_add_node(n)
        for p in init_pods:
            backend.on_add_pod(p, p.spec.node_name)
        # affinity templates ride pallas since r3 (TestPallasTerms); host
        # PORTS are still a hoisted fallback and must downgrade loudly
        pending = [
            make_pod(
                f"dl-{i}", cpu="50m", labels={"app": "dl"},
                host_port=8080 + i,
            )
            for i in range(3)
        ]
        before = sched_metrics.session_builds.value(
            kind="hoisted", reason="host-ports", shards=""
        )
        with caplog.at_level(logging.WARNING):
            backend.schedule_many(pending)
        after = sched_metrics.session_builds.value(
            kind="hoisted", reason="host-ports", shards=""
        )
        assert after == before + 1
        assert any("downgrading" in r.message for r in caplog.records)


class TestPipelinedDispatch:
    """dispatch_many/harvest (the scheduler loop's 1-deep pipeline) must
    be decision-identical to synchronous schedule_many, including when
    foreign mutations invalidate the session between dispatch and
    harvest."""

    def _backend(self, n_nodes=8):
        import random as _random

        from kubernetes_tpu.scheduler.tpu_backend import TPUBackend
        from kubernetes_tpu.testing.synth import synth_cluster as sc

        nodes, init_pods = sc(n_nodes, pods_per_node=1)
        b = TPUBackend(rng=_random.Random(0))
        for n in nodes:
            b.on_add_node(n)
        for p in init_pods:
            b.on_add_pod(p, p.spec.node_name)
        return b

    def _pods(self, prefix, n):
        return [
            make_pod(f"{prefix}-{i}", cpu="50m", labels={"app": "pl"},
                     affinity=_anti_affinity(v1.LABEL_HOSTNAME, {"app": "pl"}))
            for i in range(n)
        ]

    def test_pipeline_matches_sync(self):
        sync_b = self._backend()
        pipe_b = self._backend()
        batches = [self._pods(f"b{k}", 4) for k in range(3)]

        import copy

        sync_out = []
        for batch in batches:
            sync_out.extend(
                n for _, n in sync_b.schedule_many(copy.deepcopy(batch))
            )

        handles = []
        pipe_out = []
        # warm: first dispatch takes the sync path (builds the session)
        for batch in batches:
            h = pipe_b.dispatch_many(batch)
            handles.append((batch, h))
        for batch, h in handles:
            pipe_out.extend(n for _, n in pipe_b.harvest(h))
        assert pipe_out == sync_out
        placed = [n for n in pipe_out if n is not None]
        assert len(placed) == len(set(placed)) == 8  # one per node

    def test_mutation_between_dispatch_and_harvest(self):
        b = self._backend()
        # two warm batches: the first triggers the initial encoding
        # rebuild (vocab growth re-widths the arrays), the second
        # registers templates at the settled caps
        b.schedule_many(self._pods("warm", 2))
        b.schedule_many(self._pods("warm2", 2))
        h = b.dispatch_many(self._pods("x", 3))
        assert h.results is None, "post-warm batch should pipeline"
        # a foreign BATCHABLE pod whose labels match no template term is
        # absorbed as a carry delta mid-flight — the session survives
        foreign = make_pod("foreign", cpu="10m", node_name="n-0")
        b.on_add_pod(foreign, b.enc.node_names[0])
        assert b._session is not None and b._deltas, (
            "batchable foreign add should queue a carry delta"
        )
        # a foreign pod MATCHING a template's own anti-affinity term
        # perturbs prologue statics: still a mid-flight teardown
        matcher = make_pod("matcher", cpu="10m", node_name="n-0",
                           labels={"app": "pl"})
        b.on_add_pod(matcher, b.enc.node_names[0])
        assert b._session is None
        results = b.harvest(h)  # ys stay valid; decode fn was captured
        assert len(results) == 3
        # the next batch rebuilds from an encoding that includes the
        # harvested assumes: no node double-booked across the boundary
        more = b.schedule_many(self._pods("y", 3))
        placed = [n for _, n in results if n] + [n for _, n in more if n]
        assert len(placed) == len(set(placed))

    def test_schedule_flushes_pending(self):
        b = self._backend()
        b.schedule_many(self._pods("warm", 2))
        b.schedule_many(self._pods("warm2", 2))
        h = b.dispatch_many(self._pods("z", 2))
        assert h.results is None
        # the one-pod path must land the pending batch before evaluating
        lone = make_pod("lone", cpu="50m", labels={"app": "pl"},
                        affinity=_anti_affinity(v1.LABEL_HOSTNAME, {"app": "pl"}))
        from kubernetes_tpu.scheduler.framework.interface import FitError

        try:
            r = b.schedule(lone)
            taken = {n for _, n in h.results if n}
            assert r.suggested_host not in taken
        except FitError:
            pass
        assert h.results is not None, "schedule() must flush the pipeline"


class TestHeartbeatGate:
    """Node STATUS heartbeats (conditions/timestamps — what kubelets
    patch every ~10s) must NOT tear down the cross-batch session or
    force an encoding rebuild; scheduling-relevant changes must."""

    def _backend(self):
        import random as _random

        from kubernetes_tpu.scheduler.tpu_backend import TPUBackend
        from kubernetes_tpu.testing.synth import synth_cluster as sc

        nodes, init_pods = sc(6, pods_per_node=1)
        b = TPUBackend(rng=_random.Random(0))
        for n in nodes:
            b.on_add_node(n)
        for p in init_pods:
            b.on_add_pod(p, p.spec.node_name)
        return b, nodes

    def test_heartbeat_keeps_session(self):
        import copy

        from kubernetes_tpu.testing.synth import synth_pending_pods

        b, nodes = self._backend()
        pending = synth_pending_pods(4, spread=True)
        b.schedule_many(pending[:2])
        assert b._session is not None
        # heartbeat: same spec/labels/allocatable, new conditions
        hb = copy.deepcopy(nodes[0])
        hb.status.conditions = [
            __import__("kubernetes_tpu.api.types", fromlist=["x"])
            .NodeCondition(type="Ready", status="True",
                           last_heartbeat_time=12345.0)
        ]
        b.on_update_node(hb)
        assert b._session is not None, "heartbeat must not kill the session"
        # real change: cordon the node
        cordoned = copy.deepcopy(nodes[0])
        cordoned.spec.unschedulable = True
        b.on_update_node(cordoned)
        assert b._session is None, "cordon must invalidate the session"
