"""Round-2 controllers: podgc, serviceaccount(+tokens),
replicationcontroller, attachdetach, pvc/pv-protection, node-ttl.

Reference shape: pkg/controller/{podgc,serviceaccount,replication,
volume/attachdetach,volume/pvcprotection,volume/pvprotection,ttl} unit
tests (controllermanager.go:389-431 initializer registry)."""

import time

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.attachdetach import AttachDetachController
from kubernetes_tpu.controllers.manager import new_controller_initializers
from kubernetes_tpu.controllers.nodettl import TTL_ANNOTATION, TTLController
from kubernetes_tpu.controllers.podgc import PodGCController
from kubernetes_tpu.controllers.replication import (
    ReplicationControllerController,
)
from kubernetes_tpu.controllers.serviceaccount import (
    ServiceAccountController,
    TokensController,
)
from kubernetes_tpu.controllers.volumeprotection import (
    PVC_PROTECTION_FINALIZER,
    PV_PROTECTION_FINALIZER,
    PVCProtectionController,
    PVProtectionController,
)

from .util import make_node, make_pod, wait_until


@pytest.fixture()
def cluster():
    api = APIServer()
    cs = Clientset(api)
    factory = SharedInformerFactory(cs)
    started = []

    def start(*ctrls):
        factory.start()
        assert factory.wait_for_cache_sync()
        for c in ctrls:
            c.run()
            started.append(c)
        return ctrls

    yield api, cs, factory, start
    for c in started:
        c.stop()
    factory.stop()


def test_initializer_registry_has_r2_controllers():
    inits = new_controller_initializers()
    for name in ("podgc", "serviceaccount", "serviceaccount-token",
                 "replicationcontroller", "attachdetach",
                 "pvc-protection", "pv-protection", "ttl"):
        assert name in inits, name
    assert len(inits) >= 22


class TestPodGC:
    def test_orphaned_pods_deleted(self, cluster):
        api, cs, factory, start = cluster
        cs.nodes.create(make_node("alive"))
        ok = make_pod("on-alive", node_name="alive")
        orphan = make_pod("on-dead", node_name="dead-node")
        cs.pods.create(ok)
        cs.pods.create(orphan)
        gc = PodGCController(cs, factory, sync_period=0.2)
        start(gc)
        assert wait_until(
            lambda: {p.metadata.name for p in cs.pods.list()[0]} == {"on-alive"},
            timeout=10,
        )

    def test_terminated_over_threshold(self, cluster):
        api, cs, factory, start = cluster
        cs.nodes.create(make_node("n1"))
        for i in range(6):
            p = make_pod(f"done-{i}", node_name="n1")
            p.status.phase = "Succeeded"
            # NOTE: 0.0 is falsy — the server would re-stamp it as "now"
            p.metadata.creation_timestamp = float(i + 1)
            cs.pods.create(p)
        gc = PodGCController(cs, factory, terminated_pod_threshold=4,
                             sync_period=0.2)
        start(gc)
        # the two OLDEST terminated pods go
        assert wait_until(
            lambda: {p.metadata.name for p in cs.pods.list()[0]}
            == {"done-2", "done-3", "done-4", "done-5"},
            timeout=10,
        )

    def test_unscheduled_terminating_deleted(self, cluster):
        api, cs, factory, start = cluster
        p = make_pod("limbo")
        p.metadata.finalizers = ["example.com/hold"]
        cs.pods.create(p)
        cs.pods.delete("limbo", "default")  # soft-delete: finalizer holds it
        gc = PodGCController(cs, factory, sync_period=0.2)
        start(gc)
        # gc keeps re-issuing the delete; once the finalizer is cleared
        # the pod must vanish
        time.sleep(0.5)
        api.remove_finalizer("pods", "limbo", "default", "example.com/hold")
        assert wait_until(lambda: not cs.pods.list()[0], timeout=10)


class TestServiceAccounts:
    def test_default_sa_created_per_namespace(self, cluster):
        api, cs, factory, start = cluster
        start(ServiceAccountController(cs, factory))
        cs.namespaces.create(v1.Namespace(
            metadata=v1.ObjectMeta(name="team-a")))
        assert wait_until(
            lambda: any(
                sa.metadata.name == "default"
                for sa in cs.serviceaccounts.list(namespace="team-a")[0]
            ),
            timeout=10,
        )

    def test_deleted_default_sa_recreated(self, cluster):
        api, cs, factory, start = cluster
        start(ServiceAccountController(cs, factory))
        cs.namespaces.create(v1.Namespace(metadata=v1.ObjectMeta(name="ns1")))
        assert wait_until(
            lambda: cs.serviceaccounts.list(namespace="ns1")[0], timeout=10)
        cs.serviceaccounts.delete("default", "ns1")
        assert wait_until(
            lambda: any(
                sa.metadata.name == "default"
                for sa in cs.serviceaccounts.list(namespace="ns1")[0]
            ),
            timeout=10,
        )

    def test_token_secret_minted_and_cleaned(self, cluster):
        api, cs, factory, start = cluster
        minted = []

        def mint(ns, name):
            minted.append((ns, name))
            return f"tok-{ns}-{name}"

        start(TokensController(cs, factory, mint=mint))
        from kubernetes_tpu.api import rbac

        cs.serviceaccounts.create(rbac.ServiceAccount(
            metadata=v1.ObjectMeta(name="robot", namespace="default")))

        def token_secrets():
            return [
                s for s in cs.secrets.list(namespace="default")[0]
                if s.type == v1.SECRET_TYPE_SERVICE_ACCOUNT_TOKEN
            ]

        assert wait_until(lambda: len(token_secrets()) == 1, timeout=10)
        s = token_secrets()[0]
        assert s.data["token"] == "tok-default-robot"
        assert (s.metadata.annotations or {})[
            v1.SERVICE_ACCOUNT_NAME_ANNOTATION] == "robot"
        assert minted == [("default", "robot")]

        # a deleted token SECRET is re-minted (the secrets watch)
        name0 = s.metadata.name
        cs.secrets.delete(name0, "default")
        assert wait_until(
            lambda: token_secrets() and token_secrets()[0].metadata.name != name0,
            timeout=10,
        )

        cs.serviceaccounts.delete("robot", "default")
        assert wait_until(lambda: not token_secrets(), timeout=10)


class TestReplicationController:
    def _rc(self, name="rc1", replicas=3):
        return v1.ReplicationController(
            metadata=v1.ObjectMeta(name=name, namespace="default"),
            spec=v1.ReplicationControllerSpec(
                replicas=replicas,
                selector={"app": name},
                template=v1.PodTemplateSpec(
                    metadata=v1.ObjectMeta(labels={"app": name}),
                    spec=v1.PodSpec(containers=[v1.Container(
                        name="c", image="img:1")]),
                ),
            ),
        )

    def test_scales_up_and_down(self, cluster):
        api, cs, factory, start = cluster
        start(ReplicationControllerController(cs, factory))
        cs.replicationcontrollers.create(self._rc(replicas=3))
        assert wait_until(
            lambda: len(cs.pods.list(namespace="default")[0]) == 3, timeout=10)
        rc = cs.replicationcontrollers.get("rc1", "default")
        rc.spec.replicas = 1
        cs.replicationcontrollers.update(rc)
        assert wait_until(
            lambda: len(cs.pods.list(namespace="default")[0]) == 1, timeout=10)

    def test_status_replicas(self, cluster):
        api, cs, factory, start = cluster
        start(ReplicationControllerController(cs, factory))
        cs.replicationcontrollers.create(self._rc(name="rc2", replicas=2))
        assert wait_until(
            lambda: cs.replicationcontrollers.get(
                "rc2", "default").status.replicas == 2,
            timeout=10,
        )


class TestAttachDetach:
    def test_attach_then_detach(self, cluster):
        api, cs, factory, start = cluster
        cs.nodes.create(make_node("n1"))
        cs.persistentvolumeclaims.create(v1.PersistentVolumeClaim(
            metadata=v1.ObjectMeta(name="claim", namespace="default"),
            spec=v1.PersistentVolumeClaimSpec(volume_name="pv-1"),
        ))
        pod = make_pod("user", node_name="n1")
        pod.spec.volumes = [v1.Volume(
            name="data",
            source={"persistentVolumeClaim": {"claimName": "claim"}},
        )]
        cs.pods.create(pod)
        start(AttachDetachController(cs, factory, sync_period=0.2))
        assert wait_until(
            lambda: [
                av.name for av in
                (cs.nodes.get("n1").status.volumes_attached or [])
            ] == ["pv-1"],
            timeout=10,
        )
        cs.pods.delete("user", "default")
        assert wait_until(
            lambda: not cs.nodes.get("n1").status.volumes_attached,
            timeout=10,
        )


class TestVolumeProtection:
    def test_pvc_finalizer_lifecycle(self, cluster):
        api, cs, factory, start = cluster
        cs.persistentvolumeclaims.create(v1.PersistentVolumeClaim(
            metadata=v1.ObjectMeta(name="c1", namespace="default")))
        pod = make_pod("consumer")
        pod.spec.volumes = [v1.Volume(
            name="v", source={"persistentVolumeClaim": {"claimName": "c1"}})]
        cs.pods.create(pod)
        start(PVCProtectionController(cs, factory))
        assert wait_until(
            lambda: PVC_PROTECTION_FINALIZER in (
                cs.persistentvolumeclaims.get("c1", "default")
                .metadata.finalizers or []
            ),
            timeout=10,
        )
        # deletion is held while the pod consumes the claim
        cs.persistentvolumeclaims.delete("c1", "default")
        pvc = cs.persistentvolumeclaims.get("c1", "default")
        assert pvc.metadata.deletion_timestamp is not None
        cs.pods.delete("consumer", "default")
        assert wait_until(
            lambda: not any(
                p.metadata.name == "c1"
                for p in cs.persistentvolumeclaims.list(namespace="default")[0]
            ),
            timeout=10,
        )

    def test_pv_finalizer_removed_when_unbound(self, cluster):
        api, cs, factory, start = cluster
        cs.persistentvolumes.create(v1.PersistentVolume(
            metadata=v1.ObjectMeta(name="pv-x")))
        start(PVProtectionController(cs, factory))
        assert wait_until(
            lambda: PV_PROTECTION_FINALIZER in (
                cs.persistentvolumes.get("pv-x").metadata.finalizers or []
            ),
            timeout=10,
        )
        cs.persistentvolumes.delete("pv-x")
        assert wait_until(
            lambda: not any(
                pv.metadata.name == "pv-x"
                for pv in cs.persistentvolumes.list()[0]
            ),
            timeout=10,
        )


class TestNodeTTL:
    def test_small_cluster_zero_ttl(self, cluster):
        api, cs, factory, start = cluster
        start(TTLController(cs, factory))
        cs.nodes.create(make_node("n1"))
        assert wait_until(
            lambda: (cs.nodes.get("n1").metadata.annotations or {}).get(
                TTL_ANNOTATION) == "0",
            timeout=10,
        )

    def test_boundary_ladder(self):
        assert TTLController.__mro__  # sanity
        from kubernetes_tpu.controllers.nodettl import _BOUNDARIES

        assert _BOUNDARIES[0][2] == 0
        assert _BOUNDARIES[-1][2] == 300
