"""Split-brain-safe scheduler failover: fenced writes, clock-skew
hardening, cold-restart reconciliation, dual-scheduler chaos.

The invariant under test is the split-brain one: across leader crashes,
netsplits, and graceful handoffs, every pod is bound EXACTLY once —
zero lost, zero double-bound — because (a) a deposed leader's writes
carry a dead lease epoch the apiserver rejects (FenceExpired), (b) a
partitioned/paused leader self-fences a margin BEFORE its lease
expires, strictly before any peer's adoption window opens, and (c) a
promoted (or cold-restarted) instance reconciles the authoritative
store — adopt bound pods, clear stale nominations, requeue unbound
pods exactly once — before it pops anything.

Fast deterministic variants run in tier-1; the multi-seed soak is
`slow`.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.server import APIServer, FenceExpired
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.client.leaderelection import (
    FencingToken,
    LeaderElectionConfig,
    LeaderElector,
)
from kubernetes_tpu.cluster import Cluster
from kubernetes_tpu.scheduler import metrics as sched_metrics
from kubernetes_tpu.scheduler.factory import create_scheduler
from kubernetes_tpu.testing.chaos import ChaosMonkey
from kubernetes_tpu.testing.faults import BindIntegrityChecker

from .util import wait_until

# fast lease timings for the dual-scheduler tests (production defaults
# are 15s/10s/2s — a failover per test would blow the tier-1 budget)
FAST_ELECTION = dict(
    lease_duration=1.5,
    renew_deadline=1.0,
    retry_period=0.05,
    fence_margin=0.3,
)


def _pod(name: str, cpu: str = "20m") -> v1.Pod:
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        spec=v1.PodSpec(containers=[v1.Container(
            name="c", image="img:1",
            resources=v1.ResourceRequirements(requests={"cpu": cpu}),
        )]),
    )


# -- satellite 1: clock-skew hardening (self-fence margin) -----------------


class FakeClock:
    def __init__(self):
        self.t = 0.0
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self.t

    def advance(self, dt: float) -> None:
        with self._lock:
            self.t += dt


def test_fence_margin_demotes_partitioned_leader_before_adoption():
    """A partitioned leader must demote at lease_duration - fence_margin
    on its OWN clock, strictly before a peer's adoption window opens at
    lease_duration — the window in which both could believe they lead
    is the margin, by construction, not the clock skew."""
    clock = FakeClock()
    client = Clientset(APIServer())
    demoted_at = []
    adopted_at = []

    # lease_duration - fence_margin (8.0) < renew_deadline-from-now
    # (9.0): the MARGIN governs the self-fence deadline, which is the
    # configuration this test pins (with margin 0 the renew deadline
    # would fire at 9.0 instead — still before expiry, but only by
    # whatever slack renew_deadline happens to leave)
    def cfg(identity):
        return LeaderElectionConfig(
            identity=identity, lease_duration=10.0, renew_deadline=9.0,
            retry_period=0.02, fence_margin=2.0,
        )

    a = LeaderElector(
        client, cfg("a"),
        on_started_leading=lambda: None,
        on_stopped_leading=lambda: demoted_at.append(clock.now()),
        now=clock.now,
    )
    b = LeaderElector(
        client, cfg("b"),
        on_started_leading=lambda: adopted_at.append(clock.now()),
        on_stopped_leading=lambda: None,
        now=clock.now,
    )
    try:
        a.start()
        assert wait_until(a.is_leader.is_set, timeout=5)
        a.partitioned = True  # netsplit: renews fail, token freezes
        b.start()
        # walk fake time past expiry; real-time sleeps let the elector
        # threads observe each step
        while clock.now() < 12.0 and not adopted_at:
            clock.advance(0.25)
            time.sleep(0.04)  # >= 2 retry_periods: both electors poll
        assert demoted_at, "partitioned leader never self-fenced"
        assert adopted_at, "standby never adopted the expired lease"
        # demotion on the margin: at >= 8.0 (the self-fence deadline)
        # but < 9.0 (where the renew deadline would have fired) — the
        # margin, not renew_deadline, ended the leadership
        assert 8.0 <= demoted_at[0] < 9.0, demoted_at
        # adoption only after full expiry at 10.0: the no-overlap gap
        # between the zombie's demotion and the successor is >= margin
        assert adopted_at[0] >= 10.0, adopted_at
        assert b.fencing_token().transitions == 1  # epoch bumped
    finally:
        a.stop()
        b.stop()


def test_elector_rejects_margin_wider_than_lease():
    with pytest.raises(ValueError):
        LeaderElector(
            Clientset(APIServer()),
            LeaderElectionConfig(identity="x", lease_duration=1.0,
                                 renew_deadline=0.5, retry_period=0.1,
                                 fence_margin=1.0),
            on_started_leading=lambda: None,
            on_stopped_leading=lambda: None,
        )


# -- tentpole: fenced writes rejected server-side --------------------------


def test_stale_fence_token_rejected_without_corrupting_store():
    """A deposed epoch's write bounces off the fencing precondition:
    FenceExpired raised, the rejection counter bumped, and the store
    object untouched — while the live epoch's identical write lands."""
    api = APIServer()
    client = Clientset(api)
    leases = client.resource("leases")
    leases.create(v1.Lease(
        metadata=v1.ObjectMeta(name="kube-scheduler", namespace="kube-system"),
        spec=v1.LeaseSpec(holder_identity="sched-a", renew_time=time.time(),
                          lease_duration_seconds=15),
    ))
    token_a = FencingToken("kube-scheduler", "kube-system", "sched-a", 0)
    client.pods.create(_pod("p0"))
    client.pods.bind("default", "p0", "n1", fence=token_a)  # valid epoch
    assert client.pods.get("p0", "default").spec.node_name == "n1"

    # failover: sched-b adopts, bumping the transitions epoch
    lease = leases.get("kube-scheduler", "kube-system")
    lease.spec.holder_identity = "sched-b"
    lease.spec.lease_transitions += 1
    leases.update(lease)

    client.pods.create(_pod("p1"))
    before = sched_metrics.fencing_rejections.value(op="bind")
    with pytest.raises(FenceExpired):
        client.pods.bind("default", "p1", "n1", fence=token_a)
    assert sched_metrics.fencing_rejections.value(op="bind") == before + 1
    assert client.pods.get("p1", "default").spec.node_name == ""

    # same write, stale epoch via bind_many: collected, not raised
    outcomes = client.pods.bind_many([("default", "p1", "n1")], fence=token_a)
    assert isinstance(outcomes[0], FenceExpired)
    assert client.pods.get("p1", "default").spec.node_name == ""

    # the live epoch's token binds the same pod fine
    token_b = FencingToken("kube-scheduler", "kube-system", "sched-b", 1)
    client.pods.bind("default", "p1", "n1", fence=token_b)
    assert client.pods.get("p1", "default").spec.node_name == "n1"

    # stale update_status and delete are fenced through the same gate
    p1 = client.pods.get("p1", "default")
    p1.status.nominated_node_name = "bogus"
    with pytest.raises(FenceExpired):
        client.pods.update_status(p1, fence=token_a)
    assert client.pods.get("p1", "default").status.nominated_node_name == ""
    with pytest.raises(FenceExpired):
        client.pods.delete("p1", "default", fence=token_a)
    assert client.pods.get("p1", "default") is not None


# -- satellite 2: requeue-exactly-once reconciliation ----------------------


def test_reconcile_adopt_requeue_clear_outcomes():
    """reconcile_from_store against a store with one of everything: a
    bound pod (adopt), an unbound pod (requeue), an unbound pod with a
    stale nomination (clear + requeue), a deleting pod (skip). A second
    reconcile is a no-op, and a generation the demotion drain already
    requeued is skipped — requeue-exactly-once."""
    c = Cluster(n_nodes=0)  # components built, nothing started: the
    # queue only sees what reconcile puts there
    try:
        s = c.scheduler
        client = c.client
        client.pods.create(_pod("bound"))
        client.pods.bind("default", "bound", "node-1")
        client.pods.create(_pod("plain"))
        nom = _pod("nominated")
        client.pods.create(nom)
        nom = client.pods.get("nominated", "default")
        nom.status.nominated_node_name = "node-9"
        client.pods.update_status(nom)

        def reading(outcome):
            return sched_metrics.restart_reconcile.value(outcome=outcome)

        base = {k: reading(k) for k in ("adopted", "requeued", "cleared")}
        counts = s.reconcile_from_store()
        assert counts == {"adopted": 1, "requeued": 2, "cleared": 1}, counts
        assert s.cache.has_pod("default/bound")
        queued = {v1.pod_key(p) for p in s.queue.pending_pods()}
        assert queued == {"default/plain", "default/nominated"}
        # the stale nomination is gone from the API object
        assert client.pods.get(
            "nominated", "default").status.nominated_node_name == ""
        for k in ("adopted", "requeued", "cleared"):
            assert reading(k) == base[k] + counts[k]

        # idempotent: everything is adopted/queued already
        counts2 = s.reconcile_from_store()
        assert counts2 == {"adopted": 0, "requeued": 0, "cleared": 0}, counts2

        # a pod the demotion drain requeued (same generation) must NOT
        # be requeued again by the relist
        drained = _pod("drained")
        client.pods.create(drained)
        fresh = client.pods.get("drained", "default")
        s._drain_requeued["default/drained"] = fresh.metadata.generation or 0
        counts3 = s.reconcile_from_store()
        assert counts3["requeued"] == 0, counts3
        # ... and the dedupe record is consumed: the NEXT reconcile (no
        # drain in between) picks the pod up normally
        counts4 = s.reconcile_from_store()
        assert counts4["requeued"] == 1, counts4
    finally:
        c.scheduler.shutdown(timeout=10)
        c._teardown()


# -- tentpole: cold-restart reconciliation parity --------------------------


def test_cold_restart_reconcile_parity():
    """Kill the scheduler with a staged backlog, bring up a FRESH
    instance over the same store, reconcile, finish — the final
    assignment of the backlog must be BIT-IDENTICAL to the control run
    that never crashed. Restart-then-reschedule == never-crashed, on
    the same surviving pod set. Two crash windows share one cluster
    (the session JIT dominates a per-window cluster): 0.0 kills the
    instance before the pipeline moves, 0.15 kills it mid-flight."""
    n_backlog = 24
    with Cluster(n_nodes=4) as c:
        for i in range(8):
            c.client.pods.create(_pod(f"base-{i}"))

        def all_bound(names):
            pods, _ = c.client.pods.list(namespace="default")
            got = {p.metadata.name: p.spec.node_name for p in pods}
            return all(got.get(n) for n in names)

        assert wait_until(
            lambda: all_bound([f"base-{i}" for i in range(8)]), timeout=30)

        names = [f"pod-{i}" for i in range(n_backlog)]

        def stage(sched):
            sched.pause()
            assert sched.wait_idle(timeout=30)
            for n in names:
                c.client.pods.create(_pod(n))
            # let the informer deliver the backlog into the queue
            assert wait_until(
                lambda: len(sched.queue.pending_pods()) >= n_backlog,
                timeout=10)

        def assignments():
            pods, _ = c.client.pods.list(namespace="default")
            return {p.metadata.name: p.spec.node_name
                    for p in pods if p.metadata.name in set(names)}

        def reset(sched):
            for n in names:
                c.client.pods.delete(n, "default")
            assert wait_until(
                lambda: not assignments() and sched.wait_idle(timeout=1),
                timeout=60)

        # control: stage, resume, drain — no crash
        stage(c.scheduler)
        c.scheduler.resume()
        assert wait_until(lambda: all_bound(names), timeout=60)
        control = assignments()
        assert all(control.values())
        reset(c.scheduler)

        current, factories = c.scheduler, []
        try:
            for crash_window in (0.0, 0.15):
                # crash run: stage the same backlog, let the pipeline
                # run for crash_window seconds, then kill the instance
                # mid-whatever
                stage(current)
                current.resume()
                time.sleep(crash_window)
                current.shutdown(timeout=30)

                # cold restart: fresh instance, fresh caches, same store
                factory = SharedInformerFactory(c.client)
                factories.append(factory)
                current = create_scheduler(
                    c.client, factory, c.scheduler_config)
                factory.start()
                assert factory.wait_for_cache_sync()
                current.reconcile_from_store()
                current.start()
                assert wait_until(lambda: all_bound(names), timeout=60), (
                    assignments())
                assert assignments() == control, crash_window
                reset(current)
        finally:
            if current is not c.scheduler:
                current.shutdown(timeout=30)
            for factory in factories:
                factory.stop()
        # hand the (dead) original back to Cluster teardown — shutdown
        # is idempotent


# -- tentpole: dual-scheduler failover chaos -------------------------------


def _failover_mix(seed: int, duration: float, n_pods: int,
                  disruptions=None) -> None:
    rng = random.Random(seed)
    with Cluster(
        n_nodes=4,
        n_schedulers=2,
        election_opts=dict(FAST_ELECTION),
        # nodelifecycle must ride along: admission taints every new node
        # not-ready:NoSchedule, and only its monitor lifts the taint
        controllers=["replicaset", "deployment", "nodelifecycle"],
        controller_opts={
            "node_monitor_period": 0.3,
            "node_monitor_grace_period": 2.0,
        },
    ) as c:
        checker = BindIntegrityChecker().attach(c.kcm.informers.pods())
        assert wait_until(
            lambda: any(s.elector.is_leader.is_set() for s in c.schedulers),
            timeout=15,
        ), "no leader elected"
        transitions0 = sched_metrics.leader_transitions.value()

        monkey = ChaosMonkey(
            c, period=max(0.3, duration / 6), rng=rng,
            disruptions=list(
                disruptions or ["failover-scheduler", "partition-scheduler"]),
        )
        monkey.run()
        created = 0
        deadline = time.monotonic() + duration
        while time.monotonic() < deadline:
            burst = rng.randrange(1, 5)
            for _ in range(burst):
                if created < n_pods:
                    c.client.pods.create(_pod(f"w-{seed}-{created}"))
                    created += 1
            time.sleep(0.05)
        while created < n_pods:
            c.client.pods.create(_pod(f"w-{seed}-{created}"))
            created += 1
        monkey.stop()
        monkey.restart_all_dead(timeout=30)
        assert monkey.history, "chaos injected nothing"

        def all_bound():
            pods, _ = c.client.pods.list(namespace="default")
            return (len(pods) == n_pods
                    and all(p.spec.node_name for p in pods))

        assert wait_until(all_bound, timeout=90), [
            (p.metadata.name, p.spec.node_name)
            for p in c.client.pods.list(namespace="default")[0]
            if not p.spec.node_name
        ]
        # zero double binds across every failover: no pod ever moved
        # node-to-node in place
        assert not checker.violations, checker.violations
        # the mix really failed over: this-instance promotions happened
        # beyond the initial election
        assert sched_metrics.leader_transitions.value() > transitions0


def test_dual_scheduler_failover_deterministic():
    """Tier-1 slice: one seeded failover mix — graceful handoffs and a
    netsplit over a pod stream; zero lost, zero double-bound."""
    _failover_mix(seed=0, duration=2.0, n_pods=30)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_dual_scheduler_failover_soak(seed):
    """The long mix adds pipeline-worker kills on top of the failover
    kinds, per ISSUE's >=3-seed soak bar. (No delete-pod here: the
    stream is bare pods — nothing recreates them, which would void the
    every-pod-bound convergence check.)"""
    _failover_mix(
        seed=seed, duration=12.0, n_pods=150,
        disruptions=["failover-scheduler", "partition-scheduler",
                     "crash-scheduler"],
    )
