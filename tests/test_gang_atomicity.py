"""Atomic gang scheduling: crash-safe all-or-nothing co-placement.

Four contracts pinned here, all arbitrated through the single-assignment
GangGate (plugins/coscheduling.py):

  * permit timeout vs gang completion is a RACE with a deterministic
    winner — whichever side flips the gate wins whole, the loser stands
    down (the pre-gate implementation's documented "tiny, self-healing
    race", made deterministic under directed two-thread tests);
  * a scheduler crash/promotion mid-permit heals through
    reconcile_from_store: orphaned gang waves (older than
    KTPU_GANG_PERMIT_TIMEOUT, or with members gone/bound in the store)
    roll back whole with reason=reconcile;
  * mutually-stalled gangs converge through the deadlock breaker (the
    youngest backs off whole; the elder completes) — never a torn gang;
  * the Permit gate only GATES, it never re-places: a mixed
    gang+singleton stream binds bit-identically with the gate on or
    off, at pipeline depth 0 or 2.
"""

from __future__ import annotations

import threading
import time

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.scheduler import metrics
from kubernetes_tpu.scheduler.framework.runtime import Framework, WaitingPod
from kubernetes_tpu.scheduler.internal import queue as queue_mod
from kubernetes_tpu.scheduler.plugins.coscheduling import (
    GROUP_LABEL,
    MIN_AVAILABLE_LABEL,
    GangGate,
)
from kubernetes_tpu.scheduler.plugins.registry import (
    default_plugins_without,
    new_in_tree_registry,
)
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.scheduler.tpu_backend import TPUBackend
from kubernetes_tpu.testing.faults import GangIntegrityChecker

from .test_coscheduling import _bound_count, _gang_scheduler, gang_pod
from .test_pipeline_parity import _cluster, _drive
from .util import make_node, make_pod, wait_until


# -- the timeout-vs-completion race, deterministic under the gate ------------


def _parked_waiting_pod(name="m-0", timeout=0.01):
    pod = gang_pod(name, "race", 2)
    wp = WaitingPod(pod, {"Coscheduling": timeout})
    return pod, wp


class TestGateArbitration:
    """Directed two-thread coverage for the documented pre-gate race:
    a permit timeout firing while the completing member's allow() is in
    flight. The gate makes the outcome deterministic — exactly one side
    flips it, and the loser observes the flip and stands down."""

    def test_timeout_yields_to_completed_gate(self):
        """Completion flips the gate first; the due timeout must NOT
        resolve the pod (the completing thread's allow() is in flight)
        — the pre-gate bug resolved it unschedulable here and relied
        on the retry loop to self-heal."""
        pod, wp = _parked_waiting_pod()
        fails = []
        gate = GangGate("default", "race", 2,
                        on_fail=lambda g: fails.append(g.reason))
        gate.note_parked(v1.pod_key(pod), time.monotonic())
        wp.set_gate(gate)
        assert gate.complete()
        time.sleep(0.02)  # deadline passes
        # timeout arbitration: gate.fail() loses, pod stays unresolved
        assert wp.timeout_if_due(time.monotonic()) is False
        assert not fails
        wp.allow("Coscheduling")  # the in-flight allow lands
        assert wp.wait() is None  # success, never unschedulable

    def test_timeout_flips_gate_then_completion_bounces(self):
        pod, wp = _parked_waiting_pod()
        fails = []
        gate = GangGate("default", "race", 2,
                        on_fail=lambda g: fails.append(g.reason))
        gate.note_parked(v1.pod_key(pod), time.monotonic())
        wp.set_gate(gate)
        time.sleep(0.02)
        assert wp.timeout_if_due(time.monotonic()) is True
        st = wp.wait()
        assert st is not None and st.is_unschedulable()
        assert fails == ["timeout"]
        # the completing member loses the race and must not bind
        assert gate.complete() is False

    def test_two_thread_race_is_all_or_nothing(self):
        """Barrier-aligned complete() vs timeout_if_due() over many
        trials: whatever the interleaving, exactly one side wins, the
        on_fail cascade fires at most once, and the pod's resolution
        matches the winner — never a half-resolved state."""
        outcomes = {"completed": 0, "failed": 0}
        for trial in range(300):
            pod, wp = _parked_waiting_pod(timeout=0.0001)
            fails = []
            gate = GangGate("default", "race", 2,
                            on_fail=lambda g: fails.append(g.reason))
            gate.note_parked(v1.pod_key(pod), time.monotonic())
            wp.set_gate(gate)
            time.sleep(0.001)  # deadline due before either thread runs
            barrier = threading.Barrier(2)
            complete_won = []

            def completer():
                barrier.wait()
                won = gate.complete()
                complete_won.append(won)
                if won:
                    wp.allow("Coscheduling")

            def timeouter():
                barrier.wait()
                wp.timeout_if_due(time.monotonic())

            threads = [threading.Thread(target=completer),
                       threading.Thread(target=timeouter)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            st = wp.wait()
            if complete_won[0]:
                assert gate.state == GangGate.COMPLETED, trial
                assert st is None, (trial, st)
                assert fails == [], trial
                outcomes["completed"] += 1
            else:
                assert gate.state == GangGate.FAILED, trial
                assert st is not None and st.is_unschedulable(), trial
                assert fails == ["timeout"], trial
                outcomes["failed"] += 1
        assert sum(outcomes.values()) == 300

    def test_concurrent_fails_fire_cascade_once(self):
        """Timeout, unreserve, and the deadlock breaker may all call
        fail() on the same wave concurrently — the rollback cascade
        (requeue members, count the rollback) must fire exactly once."""
        fired = []
        gate = GangGate("default", "g", 3, on_fail=lambda g: fired.append(1))
        barrier = threading.Barrier(4)

        def failer(reason):
            barrier.wait()
            assert gate.fail(reason=reason) is True  # wave IS failed

        threads = [
            threading.Thread(target=failer, args=(r,))
            for r in ("timeout", "member-rejected", "deadlock", "reconcile")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(fired) == 1
        assert gate.state == GangGate.FAILED


# -- crash/promotion mid-permit: reconcile_from_store rollback ---------------


class TestReconcileRollback:
    def test_orphaned_wave_rolls_back_and_gang_heals(self):
        """A wave older than KTPU_GANG_PERMIT_TIMEOUT at promotion is an
        orphaned transaction (the leader that parked it died): the
        reconcile must roll it back whole (reason=reconcile), requeue
        the members, and the gang must still admit later — all-bound,
        never torn."""
        api = APIServer()
        cs = Clientset(api)
        for i in range(4):
            cs.nodes.create(make_node(
                f"node-{i}", labels={v1.LABEL_HOSTNAME: f"node-{i}"}))
        factory, sched = _gang_scheduler(cs, permit_timeout=60.0)
        checker = GangIntegrityChecker(grace=5.0).attach(factory.pods())
        try:
            sched.start()
            cs.pods.create(gang_pod("g-0", "job-r", 3))
            cs.pods.create(gang_pod("g-1", "job-r", 3))
            pl = sched._gang_plugin()
            assert pl is not None
            assert wait_until(
                lambda: any(len(g.members()) == 2
                            for g in pl.waiting_gangs()), 10)
            (gate,) = pl.waiting_gangs()
            v0 = metrics.gang_rollbacks.value(reason="reconcile")
            # age the wave past the knob: the crashed-leader signature
            with gate._lock:
                gate.first_park -= 120.0
            sched.reconcile_from_store()
            assert metrics.gang_rollbacks.value(reason="reconcile") == v0 + 1
            assert gate.state == GangGate.FAILED
            # the members requeued (exactly once) and re-drive; the
            # late third member completes the healed wave
            cs.pods.create(gang_pod("g-2", "job-r", 3))
            assert wait_until(lambda: _bound_count(cs) == 3, 20)
            assert checker.violations == []
            assert checker.partial_gangs() == {}
        finally:
            sched.stop()
            factory.stop()

    def test_wave_with_member_bound_elsewhere_rolls_back(self):
        """A waiting member that the STORE says is bound (a prior
        leader's late bind landed) poisons the wave: the member can
        never re-drive through Permit here, so reconcile rolls the
        wave back instead of letting it camp until timeout."""
        api = APIServer()
        cs = Clientset(api)
        for i in range(4):
            cs.nodes.create(make_node(
                f"node-{i}", labels={v1.LABEL_HOSTNAME: f"node-{i}"}))
        factory, sched = _gang_scheduler(cs, permit_timeout=60.0)
        try:
            sched.start()
            cs.pods.create(gang_pod("g-0", "job-s", 3))
            cs.pods.create(gang_pod("g-1", "job-s", 3))
            pl = sched._gang_plugin()
            assert wait_until(
                lambda: any(len(g.members()) == 2
                            for g in pl.waiting_gangs()), 10)
            (gate,) = pl.waiting_gangs()
            v0 = metrics.gang_rollbacks.value(reason="reconcile")
            # the old leader's bind lands directly in the store
            cs.pods.bind("default", "g-0", "node-3")
            sched.reconcile_from_store()
            assert metrics.gang_rollbacks.value(reason="reconcile") == v0 + 1
            assert gate.state == GangGate.FAILED
        finally:
            sched.stop()
            factory.stop()


# -- deadlock breaker convergence --------------------------------------------


class TestDeadlockBreaker:
    def test_mutually_stalled_gangs_converge(self, monkeypatch):
        """Two gangs of 3 on four one-pod nodes: each parks two members
        and stalls (the remaining member cannot fit). The breaker must
        back off one gang WHOLE so the other completes — the end state
        is one gang fully bound and the other fully unbound, never a
        torn prefix on either side."""
        monkeypatch.setenv("KTPU_GANG_DEADLOCK_TICKS", "2")
        monkeypatch.setenv("KTPU_GANG_DEADLOCK_INTERVAL", "0.1")
        # flush unschedulable members fast: the freed capacity after a
        # back-off must reach the parked sibling within the test window
        monkeypatch.setattr(queue_mod, "UNSCHEDULABLE_Q_TIME_INTERVAL", 0.3)
        api = APIServer()
        cs = Clientset(api)
        for i in range(4):
            cs.nodes.create(make_node(
                f"node-{i}", pods=1,
                labels={v1.LABEL_HOSTNAME: f"node-{i}"}))
        factory, sched = _gang_scheduler(cs, permit_timeout=30.0)
        checker = GangIntegrityChecker(grace=5.0).attach(factory.pods())
        try:
            sched.start()
            v0 = metrics.gang_rollbacks.value(reason="deadlock")
            for i in range(3):
                cs.pods.create(gang_pod(f"a-{i}", "gang-a", 3))
                cs.pods.create(gang_pod(f"b-{i}", "gang-b", 3))

            def bound_by_group():
                pods, _ = cs.pods.list(namespace="default")
                counts = {"gang-a": 0, "gang-b": 0}
                for p in pods:
                    if p.spec.node_name:
                        counts[(p.metadata.labels or {})[GROUP_LABEL]] += 1
                return counts

            assert wait_until(lambda: 3 in bound_by_group().values(), 25), (
                f"no gang converged: {bound_by_group()}"
            )
            assert metrics.gang_rollbacks.value(reason="deadlock") > v0
            counts = bound_by_group()
            # whole-or-none on BOTH sides: winner fully bound, loser
            # fully unbound (capacity 4 can never host the second gang)
            assert sorted(counts.values()) == [0, 3], counts
            assert checker.violations == []
            assert checker.partial_gangs() == {}
        finally:
            sched.stop()
            factory.stop()


# -- joint co-placement feasibility (gang_fits) ------------------------------


class TestGangFeasible:
    def _backend(self, nodes, pods=()):
        b = TPUBackend()
        b.whatif = True  # CPU default is off (platform-gated)
        for n in nodes:
            b.on_add_node(n)
        for p in pods:
            b.on_add_pod(p, p.spec.node_name)
        return b

    def test_definitive_verdicts(self):
        nodes = [make_node(f"n{i}", cpu="4", memory="16Gi", pods=110)
                 for i in range(3)]
        b = self._backend(nodes)
        probe = make_pod("probe", cpu="1", memory="1Gi")
        # 3 nodes x 4 cpu: 3 of these co-place, 100 never can
        assert b.gang_feasible(probe, 3) is True
        assert b.gang_feasible(probe, 100) is False

    def test_feasibility_sees_existing_load(self):
        nodes = [make_node(f"n{i}", cpu="4", memory="16Gi", pods=110)
                 for i in range(2)]
        fill = [make_pod(f"f{i}", cpu="3500m", memory="1Gi",
                         node_name=f"n{i}") for i in range(2)]
        b = self._backend(nodes, fill)
        probe = make_pod("probe", cpu="1", memory="1Gi")
        # 500m headroom per node: zero slots for a 1-cpu member
        assert b.gang_feasible(probe, 1) is False

    def test_advisory_none_when_whatif_off(self):
        b = TPUBackend()  # whatif stays platform-gated off on CPU
        b.on_add_node(make_node("n0", cpu="4", memory="16Gi"))
        probe = make_pod("probe", cpu="1", memory="1Gi")
        assert b.gang_feasible(probe, 1) is None


# -- gang+singleton stream parity vs depth-0 ---------------------------------


def _mk_parity_scheduler(cs, depth, gate_on):
    factory = SharedInformerFactory(cs)
    sched = Scheduler(cs, factory, backend="tpu", pipeline_depth=depth)
    plugins = default_plugins_without("DefaultPreemption")
    if gate_on:
        plugins["permit"] = [("Coscheduling", 1)]
        plugins["reserve"] = plugins.get("reserve", []) + [("Coscheduling", 1)]
    sched.framework = Framework(
        new_in_tree_registry(),
        plugins=plugins,
        plugin_config={"Coscheduling": {"permit_timeout_seconds": 60.0}},
        snapshot_fn=lambda: sched.snapshot,
        handle_extras={"cache": sched.cache},
    )
    sched.framework.nominator = sched.nominator
    sched.framework.pdb_lister = sched._list_pdbs
    factory.start()
    assert factory.wait_for_cache_sync()
    return sched


def _gang_stream(n_gangs=4, gang_size=3, n_singles=12):
    """Deterministic mixed stream: whole gangs interleaved with plain
    singletons and a few permanently-unschedulable churn pods. Gang
    identity rides ANNOTATIONS (the template-hoisting form: every gang
    shares one encoded template)."""
    pods = []
    for g in range(n_gangs):
        for m in range(gang_size):
            p = make_pod(f"p-g{g}-{m}", namespace="default", cpu="200m",
                         memory="128Mi", labels={"app": "gang"})
            p.metadata.annotations = {
                GROUP_LABEL: f"gang-{g}",
                MIN_AVAILABLE_LABEL: str(gang_size),
            }
            pods.append(p)
        for s in range(n_singles // n_gangs):
            if (g + s) % 5 == 4:
                pods.append(make_pod(
                    f"p-s{g}-{s}", namespace="default", cpu="64",
                    memory="1Gi", labels={"app": "hungry"}))
            else:
                pods.append(make_pod(
                    f"p-s{g}-{s}", namespace="default", cpu="500m",
                    memory="256Mi", labels={"app": "plain"}))
    return pods


@pytest.mark.parametrize("seed", [0, 1])
def test_gang_stream_parity_with_depth0_and_no_gate(seed):
    """The Permit gate GATES, it never re-places: the same mixed
    gang+singleton stream, driven through identical batch boundaries,
    must bind bit-identically (a) without Coscheduling at depth 0 —
    the no-gang-regression reference, (b) with the gate at depth 0,
    and (c) with the gate at depth 2 (parked waves resolving under
    pipelined completions)."""
    import random as _random

    rng = _random.Random(seed)
    batch_sizes = [rng.choice([1, 2, 3, 5, 8]) for _ in range(64)]
    maps = {}
    for label, depth, gate_on in (
        ("off-d0", 0, False), ("on-d0", 0, True), ("on-d2", 2, True),
    ):
        _, cs = _cluster()
        sched = _mk_parity_scheduler(cs, depth, gate_on)
        try:
            _drive(sched, cs, _gang_stream(), list(batch_sizes))
            pods, _ = cs.pods.list(namespace="default")
            maps[label] = {
                p.metadata.name: p.spec.node_name for p in pods
            }
        finally:
            sched.stop()
            sched.informers.stop()
    assert maps["off-d0"] == maps["on-d0"], (
        "the gang gate changed placement decisions"
    )
    assert maps["on-d0"] == maps["on-d2"], (
        "pipelined gang waves diverged from the sequential path"
    )
    # every gang admitted whole (the stream is satisfiable by design)
    unbound_gang = [k for k, nd in maps["on-d2"].items()
                    if k.startswith("p-g") and not nd]
    assert not unbound_gang, f"gang members left unbound: {unbound_gang}"
    # churn was actually exercised
    assert any(not nd for nd in maps["on-d2"].values())
