"""kubeadm phases / join / bootstrap tokens / cert lifecycle.

Reference shape: cmd/kubeadm/app/cmd/phases/init (ordered, skippable,
individually runnable phases), app/discovery/token (join validation),
kubeadm certs check-expiration / renew."""

import time

import pytest

from kubernetes_tpu import kubeadm
from kubernetes_tpu.apiserver.auth import SecureAPIServer

from .util import wait_until  # noqa: F401 (symmetry with sibling tests)


@pytest.fixture()
def secure():
    return SecureAPIServer()


class TestInitPhases:
    def test_full_init(self, secure):
        ctx = kubeadm.init(secure)
        assert all(ctx.results.values())
        # admin identity authenticates with system:masters power
        cs = secure.as_user(ctx.admin_token)
        cs.pods.list(namespace="default")
        # control-plane node labeled + tainted
        node = secure.api.get("nodes", "control-plane-0")
        assert kubeadm.CONTROL_PLANE_LABEL in (node.metadata.labels or {})
        assert any(
            t.key == kubeadm.CONTROL_PLANE_TAINT for t in node.spec.taints or []
        )
        # kubeadm-config uploaded; bootstrap token secret exists
        assert secure.api.get("configmaps", "kubeadm-config", "kube-system")
        tid = ctx.bootstrap_token.split(".", 1)[0]
        assert secure.api.get(
            "secrets", f"bootstrap-token-{tid}", "kube-system")

    def test_skip_phases(self, secure):
        ctx = kubeadm.init(secure, skip_phases=["mark-control-plane"])
        assert ctx.results["mark-control-plane"] is False
        with pytest.raises(Exception):
            secure.api.get("nodes", "control-plane-0")

    def test_single_phase(self, secure):
        ctx = kubeadm.init(secure, only_phase="certs")
        assert ctx.results == {"certs": True}
        assert "admin" in ctx.ca.issued

    def test_phase_order_matches_reference(self):
        names = [p.name for p in kubeadm.INIT_PHASES]
        assert names == ["preflight", "certs", "kubeconfig",
                         "upload-config", "mark-control-plane",
                         "bootstrap-token"]


class TestJoin:
    def test_worker_join(self, secure):
        ctx = kubeadm.init(secure)
        cert = kubeadm.join(ctx, "worker-1", token=ctx.bootstrap_token)
        # the minted kubelet identity authenticates as system:node:worker-1
        cs = secure.as_user(cert.token)
        assert cs.user.name == "system:node:worker-1"
        assert "system:nodes" in cs.user.groups

    def test_join_bad_token(self, secure):
        ctx = kubeadm.init(secure)
        with pytest.raises(kubeadm.InvalidToken):
            kubeadm.join(ctx, "w", token="abcdef.0000000000000000")
        with pytest.raises(kubeadm.InvalidToken):
            kubeadm.join(ctx, "w", token="garbage")

    def test_join_expired_token(self, secure):
        ctx = kubeadm.init(secure)
        tid = ctx.bootstrap_token.split(".", 1)[0]
        s = secure.api.get("secrets", f"bootstrap-token-{tid}", "kube-system")
        s.data["expiration"] = str(time.time() - 1)
        secure.api.update("secrets", s)
        with pytest.raises(kubeadm.InvalidToken):
            kubeadm.join(ctx, "w", token=ctx.bootstrap_token)

    def test_control_plane_join_marks_node(self, secure):
        ctx = kubeadm.init(secure)
        kubeadm.join(ctx, "cp-2", control_plane=True,
                     token=ctx.bootstrap_token)
        node = secure.api.get("nodes", "cp-2")
        assert kubeadm.CONTROL_PLANE_LABEL in (node.metadata.labels or {})


class TestCertLifecycle:
    def test_issue_verify_expire(self):
        ca = kubeadm.CertificateAuthority()
        cert = ca.issue("kubelet-n1", "system:node:n1", ["system:nodes"],
                        ttl=0.2)
        assert ca.verify(cert)
        time.sleep(0.25)
        assert not ca.verify(cert)

    def test_tamper_detected(self):
        ca = kubeadm.CertificateAuthority()
        cert = ca.issue("admin", "kubernetes-admin", ["system:masters"])
        cert.organizations = ["system:nodes"]  # privilege rewrite
        assert not ca.verify(cert)

    def test_check_expiration_and_renew(self):
        ca = kubeadm.CertificateAuthority()
        ca.issue("short", "a", [], ttl=10.0)
        ca.issue("long", "b", [], ttl=kubeadm.DEFAULT_CERT_TTL)
        expiring = ca.check_expiration(within=60.0)
        assert set(expiring) == {"short"}
        old_token = ca.issued["short"].token
        renewed = ca.renew("short")
        assert ca.verify(renewed)
        assert renewed.token == old_token  # live components keep working
        assert not ca.check_expiration(within=60.0)


class TestPhaseIdempotence:
    def test_full_init_twice(self, secure):
        ctx = kubeadm.init(secure)
        ctx2 = kubeadm.init(secure, node_name=ctx.node_name)
        assert all(ctx2.results.values())
        # single control-plane taint despite two mark runs
        node = secure.api.get("nodes", "control-plane-0")
        cp_taints = [t for t in node.spec.taints or []
                     if t.key == kubeadm.CONTROL_PLANE_TAINT]
        assert len(cp_taints) == 1

    def test_single_phase_rerun(self, secure):
        kubeadm.init(secure)
        ctx = kubeadm.init(secure, only_phase="upload-config")
        assert ctx.results == {"upload-config": True}
