"""Wire-protocol contract for the watch/list fan-out (ISSUE 18).

Three claims under test:

  * ENCODING EQUIVALENCE — a binary (ktpu-binary, the store/wal.py
    record grammar) and a JSON watch of the same stream decode to
    identical event sequences, property-tested over random op
    interleavings; binary and JSON LIST responses rebuild identical
    objects.
  * KILL SWITCH — KTPU_WIRE_BINARY=0 restores the exact pre-binary wire
    bytes: no Accept header on requests, and JSON frames byte-identical
    to the pre-fan-out encoder.
  * SINGLE SERIALIZE — the hub serializes each event once per encoding
    in use, never per watcher, and the frame memo is keyed on the hub
    generation so a crashed store re-minting (key, revision, type)
    triples can never alias a stale cached frame.

Plus the resume story: an evicted binary reflector re-lists and resumes
cleanly (including across a media-type flip), and a compacted
since_revision surfaces as kv.Compacted through the 410 path in both
encodings.
"""

from __future__ import annotations

import json
import random
import time

import pytest

from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.apiserver.http import (
    HTTPAPIServer,
    RemoteAPIServer,
    watch_evictions,
    wire_events,
    wire_serializations,
)
from kubernetes_tpu.client import Clientset, SharedInformerFactory
from kubernetes_tpu.store import kv
from kubernetes_tpu.utils import serde

from .util import make_pod, wait_until


@pytest.fixture()
def hub():
    server = HTTPAPIServer(APIServer())
    server.start()
    try:
        yield server
    finally:
        server.stop()


def _remote(hub, binary: bool) -> RemoteAPIServer:
    r = RemoteAPIServer(hub.address)
    r.wire_binary = binary
    return r


def _drain(watch, n, timeout=10.0):
    out = []
    deadline = time.monotonic() + timeout
    while len(out) < n and time.monotonic() < deadline:
        ev = watch.poll(timeout=0.2)
        if ev is not None:
            out.append(ev)
    return out


def _sig(ev):
    return (
        ev.type,
        ev.revision,
        ev.object.metadata.name,
        ev.object.metadata.resource_version,
        serde.to_dict(ev.object),
    )


def test_binary_and_json_streams_decode_identically(hub):
    """Property test: random create/update/delete interleavings produce
    BIT-IDENTICAL decoded event sequences on a binary and a JSON watch
    of the same stream."""
    api = hub.api
    rng = random.Random(18)
    wb = _remote(hub, binary=True).watch("pods", namespace="default",
                                         since_revision=0)
    wj = _remote(hub, binary=False).watch("pods", namespace="default",
                                          since_revision=0)
    assert wb.binary and not wj.binary
    live = {}
    n_events = 0
    for i in range(120):
        op = rng.choice(("create", "update", "update", "delete"))
        if op == "create" or not live:
            name = f"p{i}"
            live[name] = api.create(
                "pods", make_pod(name, namespace="default", cpu="10m"))
        elif op == "update":
            name = rng.choice(sorted(live))
            pod = live[name]
            pod.metadata.annotations = {"seq": str(i)}
            live[name] = api.update("pods", pod)
        else:
            name = rng.choice(sorted(live))
            api.delete("pods", name, "default")
            del live[name]
        n_events += 1
    got_b = _drain(wb, n_events)
    got_j = _drain(wj, n_events)
    wb.stop()
    wj.stop()
    assert len(got_b) == n_events and len(got_j) == n_events
    assert [_sig(e) for e in got_b] == [_sig(e) for e in got_j]


def test_binary_and_json_list_equivalence(hub):
    api = hub.api
    for i in range(7):
        api.create("pods", make_pod(f"p{i}", namespace="default", cpu="5m"))
    items_b, rev_b = _remote(hub, True).list("pods", namespace="default")
    items_j, rev_j = _remote(hub, False).list("pods", namespace="default")
    assert rev_b == rev_j
    assert [serde.to_dict(o) for o in items_b] == \
        [serde.to_dict(o) for o in items_j]
    assert items_b[0].metadata.resource_version


def test_kill_switch_restores_pre_binary_wire_bytes(hub):
    """KTPU_WIRE_BINARY=0: the client sends no Accept header and the
    server streams frames byte-identical to the pre-fan-out encoder —
    json.dumps of {type, revision, object-with-stamped-RV} plus a
    newline, heartbeats a literal b' \\n'."""
    import http.client
    from urllib.parse import urlsplit

    api = hub.api
    pod = api.create("pods", make_pod("a", namespace="default", cpu="5m"))
    pod.metadata.annotations = {"n": "1"}
    api.update("pods", pod)

    # the pre-PR encoder, reimplemented literally from the old code
    expected = []
    for ev in api.store.history_since("/registry/pods/", 0):
        obj = dict(ev.value)
        meta = dict(obj.get("metadata") or {})
        meta["resourceVersion"] = str(ev.revision)
        obj["metadata"] = meta
        expected.append(json.dumps({
            "type": ev.type, "revision": ev.revision, "object": obj,
        }).encode() + b"\n")
    assert len(expected) == 2

    split = urlsplit(hub.address)
    conn = http.client.HTTPConnection(split.hostname, split.port)
    conn.request(
        "GET",
        "/api/v1/namespaces/default/pods?watch=true&resourceVersion=0",
    )
    resp = conn.getresponse()
    assert (resp.getheader("Content-Type") or "").startswith(
        "application/json")
    got = []
    while len(got) < 2:
        line = resp.readline()
        assert line, "stream ended before both frames arrived"
        if line == b" \n":  # heartbeat: pre-PR bytes too
            continue
        got.append(line)
    conn.close()
    assert got == expected


def test_serializations_count_encodings_not_watchers(hub):
    """8 watchers (4 binary + 4 JSON) of one stream: each event is
    serialized exactly once per ENCODING, and every watcher still
    receives every event."""
    api = hub.api
    pod = api.create("pods", make_pod("a", namespace="default", cpu="5m"))
    watches = (
        [_remote(hub, True).watch("pods", namespace="default")
         for _ in range(4)]
        + [_remote(hub, False).watch("pods", namespace="default")
           for _ in range(4)]
    )
    assert wait_until(lambda: hub.watcher_count == 8)
    ev0 = wire_events.value()
    sb0 = wire_serializations.value(encoding="binary")
    sj0 = wire_serializations.value(encoding="json")
    n = 25
    for i in range(n):
        pod.metadata.annotations = {"seq": str(i)}
        pod = api.update("pods", pod)
    per_watch = [_drain(w, n) for w in watches]
    for w in watches:
        w.stop()
    assert all(len(evs) == n for evs in per_watch)
    assert wire_events.value() - ev0 == n
    assert wire_serializations.value(encoding="binary") - sb0 == n
    assert wire_serializations.value(encoding="json") - sj0 == n


def test_compacted_resume_raises_410_in_both_encodings(hub):
    """A compacted since_revision must surface as kv.Compacted (the 410
    Gone re-list signal) on watch setup, whatever the encoding."""
    store = kv.KVStore(history_limit=4)
    api = APIServer(store=store)
    server = HTTPAPIServer(api)
    server.start()
    try:
        pod = api.create("pods", make_pod("a", namespace="default",
                                          cpu="5m"))
        for i in range(10):
            pod.metadata.annotations = {"seq": str(i)}
            pod = api.update("pods", pod)
        for binary in (True, False):
            with pytest.raises(kv.Compacted):
                _remote(server, binary).watch(
                    "pods", namespace="default", since_revision=1)
    finally:
        server.stop()


@pytest.mark.parametrize("binary", (True, False), ids=("binary", "json"))
def test_reflector_resumes_after_eviction(hub, binary):
    """An evicted reflector — PR-11 overflow close — re-lists and
    resumes cleanly in either encoding: the informer cache converges on
    post-eviction state."""
    api = hub.api
    pod = api.create("pods", make_pod("victim", namespace="default",
                                      cpu="5m"))
    cs = Clientset(_remote(hub, binary))
    factory = SharedInformerFactory(cs)
    informer = factory.pods()
    factory.start()
    try:
        assert factory.wait_for_cache_sync()
        ev0 = watch_evictions.value()
        # deterministic eviction: force every live sink out, exactly the
        # hard close an overflowed buffer triggers
        assert wait_until(lambda: len(hub.fanout._sinks) >= 1)
        for sink in list(hub.fanout._sinks):
            with sink.cv:
                sink._evict_locked()
        assert wait_until(lambda: watch_evictions.value() > ev0)
        # the reflector must notice the dead stream, re-list, re-watch,
        # and see writes made after the eviction
        pod.metadata.annotations = {"after": "eviction"}
        api.update("pods", pod)

        def converged():
            got = informer.get("default/victim")
            return (got is not None and
                    (got.metadata.annotations or {}).get("after")
                    == "eviction")

        assert wait_until(converged, timeout=15), (
            "reflector did not resume after eviction")
    finally:
        factory.stop()


def test_resume_across_media_types(hub):
    """A watcher evicted mid-stream on the binary wire resumes over JSON
    (kill switch flipped between attempts) with no gap and no duplicate:
    revisions across the boundary are contiguous."""
    api = hub.api
    pod = api.create("pods", make_pod("a", namespace="default", cpu="5m"))
    remote = _remote(hub, True)
    w = remote.watch("pods", namespace="default", since_revision=0)
    assert w.binary
    for i in range(5):
        pod.metadata.annotations = {"seq": str(i)}
        pod = api.update("pods", pod)
    first = _drain(w, 6)
    assert [e.revision for e in first] == list(range(1, 7))
    # hard-close the stream server-side (the eviction shape)
    assert wait_until(lambda: len(hub.fanout._sinks) >= 1)
    for sink in list(hub.fanout._sinks):
        with sink.cv:
            sink._evict_locked()
    assert wait_until(lambda: w.closed)
    w.stop()
    # resume over JSON from the last seen revision
    remote.wire_binary = False
    w2 = remote.watch("pods", namespace="default",
                      since_revision=first[-1].revision)
    assert not w2.binary
    for i in range(5, 8):
        pod.metadata.annotations = {"seq": str(i)}
        pod = api.update("pods", pod)
    second = _drain(w2, 3)
    w2.stop()
    assert [e.revision for e in second] == list(range(7, 10))
    assert (second[-1].object.metadata.annotations or {}) == {"seq": "7"}


def test_frame_memo_keyed_on_generation(tmp_path):
    """A durable-store crash (fsync=False) rolls revisions back and can
    re-mint a (key, revision, type) triple for a DIFFERENT object. The
    frame memo folds the hub generation (store incarnation) into its
    key, so the re-minted event must stream fresh bytes, never the
    pre-crash frame."""
    store = kv.DurableKVStore(str(tmp_path / "s"), fsync=False)
    api = APIServer(store=store)
    server = HTTPAPIServer(api)
    server.start()
    try:
        api.create("pods", make_pod("a", namespace="default", cpu="5m",
                                    labels={"epoch": "one"}))
        w = _remote(server, True).watch("pods", namespace="default",
                                        since_revision=0)
        (first,) = _drain(w, 1)
        assert first.object.metadata.labels == {"epoch": "one"}
        assert first.revision == 1
        w.stop()
        # crash: nothing was synced, so revision 1 is re-mintable
        store.crash()
        assert store.revision == 0
        api.create("pods", make_pod("a", namespace="default", cpu="5m",
                                    labels={"epoch": "two"}))
        w2 = _remote(server, True).watch("pods", namespace="default",
                                         since_revision=0)
        (again,) = _drain(w2, 1)
        w2.stop()
        assert again.revision == 1, "re-minted revision expected"
        assert again.object.metadata.labels == {"epoch": "two"}, (
            "stale pre-crash frame served for a re-minted revision")
    finally:
        server.stop()


def test_binary_idle_heartbeat_keeps_stream_alive(hub):
    """OP_HEARTBEAT records flow on an idle binary watch and are dropped
    by the decoder: the stream stays open with no phantom events, and a
    later write still arrives."""
    api = hub.api
    w = _remote(hub, True).watch("pods", namespace="default")
    time.sleep(1.3)  # > two heartbeat ticks
    assert not w.closed
    assert w.poll(timeout=0.05) is None
    api.create("pods", make_pod("late", namespace="default", cpu="5m"))
    ev = w.poll(timeout=5)
    w.stop()
    assert ev is not None and ev.object.metadata.name == "late"
