"""Chaosmonkey e2e: inject node deaths/restarts and pod deletions while a
deployment runs; the control plane must re-converge to the desired state.

Reference shape: test/e2e/chaosmonkey + the disruptive/reboot suites.
"""

import random
import time

from kubernetes_tpu.cluster import Cluster
from kubernetes_tpu.testing.chaos import ChaosMonkey

from .util import wait_until


def test_cluster_survives_chaos(tmp_path):
    import yaml

    manifest = tmp_path / "app.yaml"
    manifest.write_text(
        yaml.safe_dump({
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": "ha"},
            "spec": {
                "replicas": 6,
                "selector": {"matchLabels": {"app": "ha"}},
                "template": {
                    "metadata": {"labels": {"app": "ha"}},
                    "spec": {
                        "containers": [
                            {
                                "name": "c",
                                "image": "img:1",
                                "resources": {"requests": {"cpu": "50m"}},
                            }
                        ]
                    },
                },
            },
        })
    )
    with Cluster(
        n_nodes=5,
        scheduler_backend="oracle",
        controllers=["replicaset", "deployment", "nodelifecycle"],
        controller_opts={
            "node_monitor_period": 0.3,
            "node_monitor_grace_period": 2.0,
        },
    ) as c:
        c.kubectl("apply", "-f", str(manifest))

        def n_running():
            pods, _ = c.client.pods.list(namespace="default")
            return sum(1 for p in pods if p.status.phase == "Running")

        assert wait_until(lambda: n_running() == 6, timeout=60)

        monkey = ChaosMonkey(c, period=0.5, rng=random.Random(42))
        monkey.run()
        time.sleep(6)  # ~12 disruptions
        monkey.stop()
        assert len(monkey.history) >= 4
        kinds = {d.kind for d in monkey.history}
        assert "delete-pod" in kinds or "kill-kubelet" in kinds
        monkey.restart_all_dead()  # end the experiment with all nodes back

        # convergence: all 6 replicas running on live nodes again
        def converged():
            pods, _ = c.client.pods.list(namespace="default")
            running = [p for p in pods if p.status.phase == "Running"]
            return len(running) == 6 and len(pods) == 6

        assert wait_until(converged, timeout=90), [
            (p.metadata.name, p.spec.node_name, p.status.phase)
            for p in c.client.pods.list(namespace="default")[0]
        ]
