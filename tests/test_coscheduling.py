"""Gang scheduling (Coscheduling Permit plugin) tests.

Reference: Permit extension point (pkg/scheduler/framework/interface.go:384)
+ waiting-pods map (framework/runtime/waiting_pods_map.go); gang semantics
per the sig-scheduling coscheduling plugin the Permit API was built for.
"""

import time

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.scheduler.framework.runtime import Framework, WaitingPod
from kubernetes_tpu.scheduler.framework.interface import CycleState
from kubernetes_tpu.scheduler.plugins.coscheduling import (
    GROUP_LABEL,
    MIN_AVAILABLE_LABEL,
    Coscheduling,
)
from kubernetes_tpu.scheduler.plugins.registry import (
    default_plugins_without,
    new_in_tree_registry,
)
from kubernetes_tpu.scheduler.scheduler import Scheduler

from .util import make_node, make_pod


def gang_pod(name, group, min_avail, namespace="default", cpu="100m"):
    return make_pod(
        name,
        namespace=namespace,
        cpu=cpu,
        labels={GROUP_LABEL: group, MIN_AVAILABLE_LABEL: str(min_avail)},
    )


class _FakeCache:
    def __init__(self, pods=()):
        self.pods = list(pods)

    def list_pods(self):
        return list(self.pods)


class _FakeHandle:
    def __init__(self, cache=None, waiting=()):
        self.cache = cache or _FakeCache()
        self._waiting = list(waiting)

    def iterate_waiting_pods(self):
        return list(self._waiting)


class TestPermitUnit:
    def test_non_gang_pod_passes(self):
        pl = Coscheduling(handle=_FakeHandle())
        status, timeout = pl.permit(CycleState(), make_pod("p"), "n")
        assert status is None and timeout == 0

    def test_incomplete_gang_waits(self):
        p1 = gang_pod("g-0", "job-a", 3)
        pl = Coscheduling(
            args={"permit_timeout_seconds": 5},
            handle=_FakeHandle(cache=_FakeCache([p1])),
        )
        pl.reserve(CycleState(), p1, "n")
        status, timeout = pl.permit(CycleState(), p1, "n")
        assert status is not None and status.code.name == "WAIT"
        assert timeout == 5

    def test_completing_member_allows_waiting(self):
        p1, p2, p3 = (gang_pod(f"g-{i}", "job-a", 3) for i in range(3))
        w1 = WaitingPod(p1, {"Coscheduling": 10})
        w2 = WaitingPod(p2, {"Coscheduling": 10})
        # cache sees all three assumed; two are parked at Permit
        handle = _FakeHandle(cache=_FakeCache([p1, p2, p3]), waiting=[w1, w2])
        pl = Coscheduling(handle=handle)
        for p in (p1, p2, p3):
            pl.reserve(CycleState(), p, "n")
        status, _ = pl.permit(CycleState(), p3, "n")
        assert status is None
        assert w1.wait() is None  # allowed
        assert w2.wait() is None

    def test_unreserve_rejects_gang(self):
        p1, p2 = (gang_pod(f"g-{i}", "job-a", 3) for i in range(2))
        w1 = WaitingPod(p1, {"Coscheduling": 10})
        handle = _FakeHandle(cache=_FakeCache([p1, p2]), waiting=[w1])
        pl = Coscheduling(handle=handle)
        for p in (p1, p2):
            pl.reserve(CycleState(), p, "n")
        pl.unreserve(CycleState(), p2, "n")
        st = w1.wait()
        assert st is not None and st.is_unschedulable()

    def test_other_namespace_not_counted(self):
        p1 = gang_pod("g-0", "job-a", 2)
        other = gang_pod("g-x", "job-a", 2, namespace="other")
        pl = Coscheduling(handle=_FakeHandle(cache=_FakeCache([p1, other])))
        pl.reserve(CycleState(), p1, "n")
        pl.reserve(CycleState(), other, "n")
        status, _ = pl.permit(CycleState(), p1, "n")
        assert status is not None  # only 1 member in this namespace

    def test_stale_members_pruned_before_completion(self):
        # two members reserved then deleted from the cache must not fake a
        # full gang for a late third member
        p1, p2, p3 = (gang_pod(f"g-{i}", "job-a", 3) for i in range(3))
        handle = _FakeHandle(cache=_FakeCache([p3]))  # only p3 still known
        pl = Coscheduling(handle=handle)
        for p in (p1, p2, p3):
            pl.reserve(CycleState(), p, "n")
        status, _ = pl.permit(CycleState(), p3, "n")
        assert status is not None and status.code.name == "WAIT"


def _gang_scheduler(cs, permit_timeout=5.0):
    factory = SharedInformerFactory(cs)
    plugins = default_plugins_without("DefaultPreemption")
    plugins["permit"] = [("Coscheduling", 1)]
    plugins["reserve"] = plugins.get("reserve", []) + [("Coscheduling", 1)]
    sched = Scheduler(cs, factory, backend="oracle")
    sched.framework = Framework(
        new_in_tree_registry(),
        plugins=plugins,
        plugin_config={
            "Coscheduling": {"permit_timeout_seconds": permit_timeout}
        },
        snapshot_fn=lambda: sched.snapshot,
        handle_extras={"cache": sched.cache},
    )
    factory.start()
    assert factory.wait_for_cache_sync()
    return factory, sched


def _bound_count(cs):
    pods, _ = cs.pods.list(namespace="default")
    return sum(1 for p in pods if p.spec.node_name)


class TestGangEndToEnd:
    def test_gang_binds_only_when_complete(self):
        api = APIServer()
        cs = Clientset(api)
        for i in range(4):
            cs.nodes.create(make_node(f"node-{i}", labels={v1.LABEL_HOSTNAME: f"node-{i}"}))
        factory, sched = _gang_scheduler(cs)
        try:
            sched.start()
            cs.pods.create(gang_pod("g-0", "job-a", 3))
            cs.pods.create(gang_pod("g-1", "job-a", 3))
            time.sleep(1.5)
            assert _bound_count(cs) == 0  # parked at Permit, not bound
            cs.pods.create(gang_pod("g-2", "job-a", 3))
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and _bound_count(cs) < 3:
                time.sleep(0.1)
            assert _bound_count(cs) == 3
        finally:
            sched.stop()
            factory.stop()

    def test_gang_timeout_then_completion(self):
        api = APIServer()
        cs = Clientset(api)
        for i in range(4):
            cs.nodes.create(make_node(f"node-{i}", labels={v1.LABEL_HOSTNAME: f"node-{i}"}))
        factory, sched = _gang_scheduler(cs, permit_timeout=0.4)
        try:
            sched.start()
            cs.pods.create(gang_pod("g-0", "job-b", 3))
            cs.pods.create(gang_pod("g-1", "job-b", 3))
            time.sleep(1.5)  # several timeout+retry rounds
            assert _bound_count(cs) == 0
            cs.pods.create(gang_pod("g-2", "job-b", 3))
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and _bound_count(cs) < 3:
                time.sleep(0.1)
            assert _bound_count(cs) == 3
        finally:
            sched.stop()
            factory.stop()

    def test_two_gangs_interleaved(self):
        api = APIServer()
        cs = Clientset(api)
        for i in range(8):
            cs.nodes.create(make_node(f"node-{i}", labels={v1.LABEL_HOSTNAME: f"node-{i}"}))
        factory, sched = _gang_scheduler(cs)
        try:
            sched.start()
            for i in range(2):
                cs.pods.create(gang_pod(f"a-{i}", "job-a", 2))
                cs.pods.create(gang_pod(f"b-{i}", "job-b", 2))
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and _bound_count(cs) < 4:
                time.sleep(0.1)
            assert _bound_count(cs) == 4
        finally:
            sched.stop()
            factory.stop()


def test_invalid_min_available_rejected():
    from kubernetes_tpu.scheduler.framework.interface import Code

    pod = make_pod("g-0", labels={GROUP_LABEL: "job-a"})  # no min-available
    pl = Coscheduling(handle=_FakeHandle())
    status, _ = pl.permit(CycleState(), pod, "n")
    assert status is not None and status.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE
