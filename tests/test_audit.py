"""Audit trail + impersonation in the secured chain.

Reference: staging/src/k8s.io/apiserver/pkg/audit (policy levels, stages)
wired as WithAudit (pkg/server/config.go:737); impersonation filter
(pkg/endpoints/filters/impersonation.go) requires the `impersonate` verb
on users/groups and keeps the real identity for audit.
"""

import pytest

from kubernetes_tpu.api import rbac
from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.audit import (
    LEVEL_METADATA,
    LEVEL_NONE,
    LEVEL_REQUEST_RESPONSE,
    STAGE_REQUEST_RECEIVED,
    STAGE_RESPONSE_COMPLETE,
    AuditLogger,
    Policy,
    PolicyRule,
)
from kubernetes_tpu.apiserver.auth import Forbidden, SecureAPIServer

from .util import make_pod


def _secure(policy=None):
    s = SecureAPIServer(audit=AuditLogger(policy=policy))
    s.authenticator.add_token("admin-token", "admin", ["system:masters"])
    s.authenticator.add_token("dev-token", "dev")
    return s


def _grant_cluster(s, name, rules, user):
    s.api.create("clusterroles", rbac.ClusterRole(
        metadata=v1.ObjectMeta(name=name), rules=rules))
    s.api.create("clusterrolebindings", rbac.ClusterRoleBinding(
        metadata=v1.ObjectMeta(name=name),
        subjects=[rbac.Subject(kind="User", name=user)],
        role_ref=rbac.RoleRef(kind="ClusterRole", name=name)))


class TestAuditTrail:
    def test_request_and_response_stages(self):
        s = _secure()
        cs = s.as_user("admin-token")
        cs.pods.create(make_pod("p1"))
        received = s.audit.events(stage=STAGE_REQUEST_RECEIVED)
        complete = s.audit.events(stage=STAGE_RESPONSE_COMPLETE)
        assert len(received) == 1 and len(complete) == 1
        ev = complete[0]
        assert (ev.user, ev.verb, ev.resource, ev.response_code) == (
            "admin", "create", "pods", 200)
        assert ev.audit_id == received[0].audit_id

    def test_forbidden_recorded_with_403(self):
        s = _secure()
        cs = s.as_user("dev-token")
        with pytest.raises(Forbidden):
            cs.pods.list(namespace="default")
        done = s.audit.events(user="dev", stage=STAGE_RESPONSE_COMPLETE)
        assert len(done) == 1 and done[0].response_code == 403

    def test_not_found_recorded_with_404(self):
        s = _secure()
        cs = s.as_user("admin-token")
        with pytest.raises(Exception):
            cs.pods.get("ghost", "default")
        done = s.audit.events(stage=STAGE_RESPONSE_COMPLETE)
        assert done[-1].response_code == 404

    def test_policy_first_match_wins(self):
        # None for pods, Metadata default: pod requests drop out entirely
        policy = Policy(rules=[
            PolicyRule(level=LEVEL_NONE, resources=["pods"]),
            PolicyRule(level=LEVEL_METADATA),
        ])
        s = _secure(policy)
        cs = s.as_user("admin-token")
        cs.pods.create(make_pod("p1"))
        cs.nodes.list()
        assert s.audit.events(resource="pods") == []
        assert len(s.audit.events(resource="nodes")) == 2

    def test_request_response_level_captures_objects(self):
        policy = Policy(rules=[PolicyRule(level=LEVEL_REQUEST_RESPONSE)])
        s = _secure(policy)
        cs = s.as_user("admin-token")
        cs.pods.create(make_pod("p1"))
        ev = s.audit.events(stage=STAGE_RESPONSE_COMPLETE)[0]
        assert ev.request_object["metadata"]["name"] == "p1"
        assert ev.response_object["metadata"]["name"] == "p1"
        # the stored response carries the assigned resourceVersion
        assert ev.response_object["metadata"]["resourceVersion"]

    def test_metadata_level_omits_objects(self):
        s = _secure()  # default Metadata
        cs = s.as_user("admin-token")
        cs.pods.create(make_pod("p1"))
        ev = s.audit.events(stage=STAGE_RESPONSE_COMPLETE)[0]
        assert ev.request_object is None and ev.response_object is None


class TestImpersonation:
    def test_requires_impersonate_verb(self):
        s = _secure()
        cs = s.as_user("dev-token")
        with pytest.raises(Forbidden):
            cs.impersonate("someone-else")

    def test_impersonated_identity_used_for_authz(self):
        s = _secure()
        _grant_cluster(
            s, "impersonator",
            [rbac.PolicyRule(verbs=["impersonate"], resources=["users"])],
            "dev",
        )
        _grant_cluster(
            s, "viewer-can-list",
            [rbac.PolicyRule(verbs=["list"], resources=["pods"])],
            "viewer",
        )
        cs = s.as_user("dev-token")
        # dev cannot list pods itself...
        with pytest.raises(Forbidden):
            cs.pods.list(namespace="default")
        # ...but can as viewer, who holds list
        as_viewer = cs.impersonate("viewer")
        as_viewer.pods.list(namespace="default")
        # and the audit trail pins BOTH identities
        ev = s.audit.events(user="viewer")[-1]
        assert ev.impersonated_by == "dev"

    def test_group_impersonation_checked(self):
        s = _secure()
        _grant_cluster(
            s, "user-only",
            [rbac.PolicyRule(verbs=["impersonate"], resources=["users"])],
            "dev",
        )
        cs = s.as_user("dev-token")
        with pytest.raises(Forbidden):
            cs.impersonate("viewer", groups=["system:masters"])

    def test_masters_can_impersonate_anyone(self):
        s = _secure()
        cs = s.as_user("admin-token")
        as_dev = cs.impersonate("dev")
        with pytest.raises(Forbidden):
            as_dev.pods.list(namespace="default")  # dev has no grants


class TestAuditChainOrder:
    def test_apf_429_recorded(self):
        """Audit wraps flow control (config.go:737 vs :726): throttled
        requests must appear in the trail with code 429."""
        import threading

        from kubernetes_tpu.apiserver.flowcontrol import (
            FlowController,
            FlowSchema,
            FlowSchemaRule,
            FlowSchemaSpec,
            FlowSchemaSubject,
            PriorityLevelConfiguration,
            PriorityLevelConfigurationSpec,
            PriorityLevelLimited,
            RequestInfo,
            TooManyRequests,
        )

        s = SecureAPIServer(audit=AuditLogger())
        fc = FlowController(s.api, default_timeout=0.5)
        s.flow_controller = fc
        fc.api.create("prioritylevelconfigurations", PriorityLevelConfiguration(
            metadata=v1.ObjectMeta(name="tiny"),
            spec=PriorityLevelConfigurationSpec(
                limited=PriorityLevelLimited(
                    assured_concurrency_shares=1, queue_length_limit=0)
            ),
        ))
        fc.api.create("flowschemas", FlowSchema(
            metadata=v1.ObjectMeta(name="devs"),
            spec=FlowSchemaSpec(
                priority_level_configuration="tiny",
                matching_precedence=1,
                rules=[FlowSchemaRule(
                    subjects=[FlowSchemaSubject(kind="User", name="dev")]
                )],
            ),
        ))
        s.authenticator.add_token("dev-token", "dev")
        cs = s.as_user("dev-token")
        # saturate the single seat from another thread, then overflow
        gate = threading.Event()
        release = threading.Event()

        def hold_seat():
            with fc.dispatch(RequestInfo(user="dev", groups=(), verb="get",
                                         resource="pods")):
                gate.set()
                release.wait(5)

        t = threading.Thread(target=hold_seat, daemon=True)
        t.start()
        assert gate.wait(5)
        try:
            with pytest.raises(TooManyRequests):
                cs.pods.list(namespace="default")
        finally:
            release.set()
            t.join()
        done = s.audit.events(stage=STAGE_RESPONSE_COMPLETE)
        assert done and done[-1].response_code == 429

    def test_omit_response_complete_stage(self):
        from kubernetes_tpu.apiserver.audit import PolicyRule as PR
        policy = Policy(rules=[PR(level=LEVEL_METADATA,
                                  omit_stages=[STAGE_RESPONSE_COMPLETE])])
        s = _secure(policy)
        cs = s.as_user("admin-token")
        cs.pods.create(make_pod("p1"))
        assert s.audit.events(stage=STAGE_REQUEST_RECEIVED)
        assert s.audit.events(stage=STAGE_RESPONSE_COMPLETE) == []

    def test_denied_impersonation_is_audited(self):
        s = _secure()
        cs = s.as_user("dev-token")
        with pytest.raises(Forbidden):
            cs.impersonate("admin")
        done = s.audit.events(user="dev", stage=STAGE_RESPONSE_COMPLETE)
        assert done and done[-1].verb == "impersonate"
        assert done[-1].response_code == 403
        assert done[-1].name == "admin"

    def test_watch_denial_is_audited(self):
        s = _secure()
        cs = s.as_user("dev-token")
        with pytest.raises(Forbidden):
            cs.pods.watch(namespace="default")
        done = s.audit.events(user="dev", stage=STAGE_RESPONSE_COMPLETE)
        assert done and done[-1].verb == "watch" and done[-1].response_code == 403
