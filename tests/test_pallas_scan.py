"""PallasSession decision parity with the jnp HoistedSession (which is
itself pinned to the generic scan and the Go oracle).

Runs the kernel in interpreter mode on CPU — semantics only; the
single-launch performance story is bench.py's job on real hardware.
"""

import copy

import numpy as np
import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.ops.hoisted import HoistedSession, template_fingerprint
from kubernetes_tpu.ops.pallas_scan import PallasSession, PallasUnsupported
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

from .test_hoisted import _encode_all, _presized_encoding
from .util import make_pod


def _templates_of(arrays):
    out, seen = [], set()
    for a in arrays:
        fp = template_fingerprint(a)
        if fp not in seen:
            seen.add(fp)
            out.append(a)
    return out


def _run_pair(nodes, init_pods, pending, batch):
    """(jnp session decisions, pallas session decisions) over batches."""
    enc, pe = _presized_encoding(
        copy.deepcopy(nodes), copy.deepcopy(init_pods), copy.deepcopy(pending))
    arrays = _encode_all(enc, pe, pending)
    templates = _templates_of(arrays)
    jsess = HoistedSession(enc.device_state(), templates)
    ref = []
    for i in range(0, len(pending), batch):
        b = arrays[i:i + batch]
        # decisions() returns the padded batch bucket; real entries first
        ref.extend(HoistedSession.decisions(jsess.schedule(b))[:len(b)])

    enc2, pe2 = _presized_encoding(nodes, init_pods, pending)
    arrays2 = _encode_all(enc2, pe2, pending)
    psess = PallasSession(enc2.device_state(), _templates_of(arrays2),
                          interpret=True)
    got = []
    for i in range(0, len(pending), batch):
        b = arrays2[i:i + batch]
        got.extend(PallasSession.decisions(psess.schedule(b))[:len(b)])
    return ref, got


class TestPallasParity:
    def test_spread_multi_batch(self):
        nodes, init_pods = synth_cluster(16, pods_per_node=2)
        pending = synth_pending_pods(36, spread=True)
        ref, got = _run_pair(nodes, init_pods, pending, batch=12)
        assert got == ref
        assert all(d >= 0 for d in got)

    def test_no_constraints(self):
        nodes, init_pods = synth_cluster(10, pods_per_node=1)
        pending = synth_pending_pods(16, spread=False)
        ref, got = _run_pair(nodes, init_pods, pending, batch=8)
        assert got == ref

    def test_capacity_exhaustion(self):
        nodes, init_pods = synth_cluster(3, pods_per_node=0)
        for node in nodes:
            node.status.allocatable["cpu"] = "350m"
            node.status.capacity["cpu"] = "350m"
        pending = synth_pending_pods(15, spread=True)
        ref, got = _run_pair(nodes, init_pods, pending, batch=5)
        assert got == ref
        assert -1 in got

    def test_hostname_hard_spread(self):
        nodes, init_pods = synth_cluster(6, pods_per_node=1)
        pending = []
        for i in range(10):
            pending.append(make_pod(
                f"hard-{i}", cpu="50m", labels={"app": "hard"},
                constraints=[v1.TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=v1.LABEL_HOSTNAME,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=v1.LabelSelector(
                        match_labels={"app": "hard"}),
                )]))
        ref, got = _run_pair(nodes, init_pods, pending, batch=5)
        assert got == ref
        assert len(set(got[:6])) == 6

    def test_mixed_templates_cross_counting(self):
        nodes, init_pods = synth_cluster(8, pods_per_node=1)
        pending = []
        for i in range(12):
            labels = {"tier": "web", "idx": f"t{i % 2}"}
            pending.append(make_pod(
                f"x-{i}", cpu="50m", labels=labels,
                constraints=[v1.TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=v1.LABEL_ZONE,
                    when_unsatisfiable="ScheduleAnyway",
                    label_selector=v1.LabelSelector(
                        match_labels={"tier": "web"}),
                )]))
        ref, got = _run_pair(nodes, init_pods, pending, batch=6)
        assert got == ref

    def test_tainted_and_labeled_cluster(self):
        # synth_cluster taints some nodes and labels zones; spread pods
        # exercise taint counts + zone spread together
        nodes, init_pods = synth_cluster(12, pods_per_node=2)
        pending = synth_pending_pods(24, spread=True)
        ref, got = _run_pair(nodes, init_pods, pending, batch=24)
        assert got == ref


class TestPallasGuards:
    def test_large_weights_unsupported(self):
        from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods
        nodes, init_pods = synth_cluster(4, pods_per_node=1)
        pending = synth_pending_pods(4, spread=True)
        enc, pe = _presized_encoding(nodes, init_pods, pending)
        arrays = _encode_all(enc, pe, pending)
        with pytest.raises(PallasUnsupported):
            PallasSession(enc.device_state(), _templates_of(arrays),
                          weights={"balanced": 1, "image": 1, "ipa": 1,
                                   "least": 1, "node_affinity": 1,
                                   "prefer_avoid": 10 ** 6, "pts": 2,
                                   "taint": 1}, interpret=True)

    def test_variable_batch_lengths_share_one_compile(self):
        """B_real is dynamic: batches of different lengths (same padded
        width) must hit the same compiled kernel and stay exact."""
        import copy
        nodes, init_pods = synth_cluster(8, pods_per_node=1)
        pending = synth_pending_pods(20, spread=True)
        ref, got = [], []
        enc, pe = _presized_encoding(
            copy.deepcopy(nodes), copy.deepcopy(init_pods),
            copy.deepcopy(pending))
        arrays = _encode_all(enc, pe, pending)
        js = HoistedSession(enc.device_state(), _templates_of(arrays))
        for lo, hi in ((0, 7), (7, 12), (12, 20)):  # lengths 7, 5, 8
            ref.extend(HoistedSession.decisions(js.schedule(arrays[lo:hi])))
        enc2, pe2 = _presized_encoding(nodes, init_pods, pending)
        arrays2 = _encode_all(enc2, pe2, pending)
        ps = PallasSession(enc2.device_state(), _templates_of(arrays2),
                           interpret=True)
        for lo, hi in ((0, 7), (7, 12), (12, 20)):
            got.extend(PallasSession.decisions(ps.schedule(arrays2[lo:hi])))
        assert got == ref


class TestPallasFuzz:
    """Random-shape fuzz of the pallas kernel (interpret mode) against
    the jnp session: the f32 in-kernel score math is fuzz-TESTED, not
    asserted (VERDICT r1 item 10). Since round 3 the kernel carries the
    IPA term machinery (D1-D5 deltas), so fuzz pods KEEP their random
    (anti-)affinity terms; only host ports are stripped (still a
    hoisted-session fallback). Spread constraints, taints, tolerations,
    priorities, images and extended resources all vary."""

    @pytest.mark.parametrize("seed", range(8))
    def test_fuzz_jnp_vs_pallas_interpret(self, seed):
        import random as _random

        from .test_kernel_parity import random_cluster, random_pending

        rng = _random.Random(1000 + seed)
        nodes, init_pods = random_cluster(rng)
        pending = []
        for i in range(10):
            p = random_pending(rng)
            p.metadata.name = f"fz-{seed}-{i}"
            for c in p.spec.containers:
                c.ports = None           # pallas: port-free templates only
            p.spec.node_name = ""
            pending.append(p)
        try:
            ref, got = _run_pair(nodes, init_pods, pending, batch=5)
        except PallasUnsupported as e:
            pytest.skip(f"shape unsupported by pallas: {e}")
        assert got == ref, f"seed={seed}: {got} != {ref}"


def _affinity(zone=False, anti=True, labels=None, pref=None):
    term = v1.PodAffinityTerm(
        label_selector=v1.LabelSelector(match_labels=dict(labels)),
        topology_key=v1.LABEL_ZONE if zone else v1.LABEL_HOSTNAME,
    )
    kw = {}
    if anti:
        kw["pod_anti_affinity"] = v1.PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[term])
    else:
        kw["pod_affinity"] = v1.PodAffinity(
            required_during_scheduling_ignored_during_execution=[term])
    if pref:
        w, plabels, pzone = pref
        pterm = v1.WeightedPodAffinityTerm(
            weight=w,
            pod_affinity_term=v1.PodAffinityTerm(
                label_selector=v1.LabelSelector(match_labels=dict(plabels)),
                topology_key=v1.LABEL_ZONE if pzone else v1.LABEL_HOSTNAME,
            ),
        )
        pa = kw.get("pod_affinity") or v1.PodAffinity()
        pa.preferred_during_scheduling_ignored_during_execution = [pterm]
        kw["pod_affinity"] = pa
    return v1.Affinity(**kw)


class TestPallasTerms:
    """Decision parity for TERM templates riding the pallas kernel (the
    r3 D1-D5 port): required anti-affinity (hostname + zone), required
    affinity incl. the first-pod-in-series escape, preferred terms, and
    cross-template D1 interactions — all vs the jnp hoisted session
    (itself pinned to the Go-semantics oracle in test_hoisted_terms).
    Existing bound pods with terms exercise the static parts."""

    def _nodes(self, n=16):
        from .util import make_node

        return [
            make_node(
                f"n-{i}",
                labels={
                    v1.LABEL_HOSTNAME: f"n-{i}",
                    "zone": f"zone-{i % 4}",
                    v1.LABEL_ZONE: f"zone-{i % 4}",
                },
            )
            for i in range(n)
        ]

    def _case(self, lbl, affinity, n_nodes=16, n_existing=6, n_pending=24,
              batch=10):
        nodes = self._nodes(n_nodes)
        existing = [
            make_pod(f"ex-{i}", labels=dict(lbl), affinity=affinity,
                     node_name=f"n-{i * 2}")
            for i in range(n_existing)
        ]
        pending = [
            make_pod(f"p-{i}", labels=dict(lbl), affinity=affinity)
            for i in range(n_pending)
        ]
        return _run_pair(nodes, existing, pending, batch)

    def test_hostname_required_anti(self):
        ref, got = self._case(
            {"app": "a"}, _affinity(zone=False, anti=True, labels={"app": "a"}))
        assert got == ref

    def test_zone_required_anti(self):
        ref, got = self._case(
            {"app": "z"}, _affinity(zone=True, anti=True, labels={"app": "z"}))
        assert got == ref

    def test_required_affinity_first_pod_escape(self):
        # no existing pods: the first pending pod only lands via the
        # counts-empty + self-match escape (filtering.go:357)
        ref, got = self._case(
            {"svc": "b"}, _affinity(zone=True, anti=False, labels={"svc": "b"}),
            n_existing=0)
        assert got == ref
        assert got[0] >= 0  # the escape must actually fire

    def test_preferred_terms_score(self):
        ref, got = self._case(
            {"w": "c"},
            _affinity(zone=False, anti=True, labels={"w": "c"},
                      pref=(40, {"w": "c"}, True)))
        assert got == ref

    @staticmethod
    def _pref_only_affinity(weight, labels, anti=False):
        """Preferred-only terms at harness weight (no required terms) —
        the SchedulingPreferredPod(Anti)Affinity template shape."""
        pterm = v1.WeightedPodAffinityTerm(
            weight=weight,
            pod_affinity_term=v1.PodAffinityTerm(
                label_selector=v1.LabelSelector(match_labels=dict(labels)),
                topology_key=v1.LABEL_ZONE,
            ),
        )
        if anti:
            return v1.Affinity(pod_anti_affinity=v1.PodAntiAffinity(
                preferred_during_scheduling_ignored_during_execution=[pterm]))
        return v1.Affinity(pod_affinity=v1.PodAffinity(
            preferred_during_scheduling_ignored_during_execution=[pterm]))

    @pytest.mark.parametrize("anti", [False, True])
    def test_weight100_preferred_rides_pallas(self, anti):
        """The bench Preferred-affinity templates (weight-100 preferred
        zone terms toward self labels) must BUILD a PallasSession — the
        w45 GCD rescale keeps the exact-f32 guard satisfied (these
        configs silently rode the ~4x-slower HoistedSession for two
        rounds) — and the decisions must stay bit-identical."""
        nodes = self._nodes(12)
        aff = self._pref_only_affinity(100, {"app": "aff"}, anti=anti)
        # plain init-template pods plus weighted-preferred pods, mixed:
        # cross-template D4/D5 weight rows are where the scale applies
        pending = []
        for i in range(18):
            if i % 3 == 0:
                pending.append(make_pod(f"pl-{i}", labels={"app": "aff"}))
            else:
                pending.append(make_pod(
                    f"pr-{i}", labels={"app": "aff"}, affinity=aff))
        ref, got = _run_pair(nodes, [], pending, batch=6)
        assert got == ref

    @pytest.mark.parametrize("anti", [False, True])
    def test_weight100_preferred_builds_pallas_session(self, anti):
        """Construction-level gate (no kernel launch — runs on any
        host): the weight-100 preferred template must not raise
        PallasUnsupported(ipa-score-weights), and the GCD scale must be
        recorded for the kernel's multiply-back."""
        nodes = self._nodes(12)
        aff = self._pref_only_affinity(100, {"app": "aff"}, anti=anti)
        pending = [make_pod("pl-0", labels={"app": "aff"})] + [
            make_pod(f"pr-{i}", labels={"app": "aff"}, affinity=aff)
            for i in range(3)
        ]
        enc, pe = _presized_encoding(nodes, [], pending)
        arrays = _encode_all(enc, pe, pending)
        sess = PallasSession(enc.device_state(), _templates_of(arrays),
                             interpret=True)
        assert sess._ipa is not None
        assert sess._ipa["w45_scale"] == 100
        assert int(np.abs(sess._ipa["w45"]).sum(axis=1).max()) < 256

    def test_cross_template_anti(self):
        # template A's anti terms must repel template B pods assumed in
        # the SAME session (D1 across templates)
        nodes = self._nodes(12)
        aff_a = _affinity(zone=True, anti=True, labels={"grp": "x"})
        pending = []
        for i in range(16):
            if i % 2 == 0:
                pending.append(make_pod(
                    f"a-{i}", labels={"grp": "x"}, affinity=aff_a))
            else:
                # B pods carry the label A's terms select, but no terms
                pending.append(make_pod(f"b-{i}", labels={"grp": "x"}))
        ref, got = _run_pair(nodes, [], pending, batch=8)
        assert got == ref

    def test_term_session_survives_batches(self):
        # carry correctness across MANY small batches (u_cnt/k_cnt chain)
        ref, got = self._case(
            {"app": "m"}, _affinity(zone=False, anti=True, labels={"app": "m"}),
            n_nodes=10, n_existing=0, n_pending=20, batch=4)
        assert got == ref


class TestEvalApplySplit:
    """The sharded session's building blocks: mode="eval" (no carry
    writes) + mode="apply" (externally-forced placement) replayed
    per-pod must reproduce the full kernel's decisions and carry
    exactly — including -1 (off-shard, in the sharded case) forcing a
    no-op."""

    def test_eval_apply_replays_full(self):
        nodes, init_pods = synth_cluster(12, pods_per_node=1)
        pending = synth_pending_pods(16, spread=True)
        enc, pe = _presized_encoding(
            copy.deepcopy(nodes), copy.deepcopy(init_pods),
            copy.deepcopy(pending))
        arrays = _encode_all(enc, pe, pending)
        full = PallasSession(enc.device_state(), _templates_of(arrays),
                             interpret=True)
        ref = PallasSession.decisions(full.schedule(arrays))[:len(arrays)]

        enc2, pe2 = _presized_encoding(nodes, init_pods, pending)
        arrays2 = _encode_all(enc2, pe2, pending)
        split = PallasSession(enc2.device_state(), _templates_of(arrays2),
                              interpret=True)
        got = []
        for a in arrays2:
            ((best, _score),) = split.evaluate([a])
            got.append(best)
            split.apply_decisions([a], [best])
        assert got == ref

    def test_off_shard_apply_is_noop(self):
        """Forcing -1 (the pod landed on ANOTHER shard's nodes) must not
        move this session's carry: a subsequent eval sees unchanged
        state."""
        nodes, init_pods = synth_cluster(8, pods_per_node=1)
        pending = synth_pending_pods(4, spread=True)
        enc, pe = _presized_encoding(nodes, init_pods, pending)
        arrays = _encode_all(enc, pe, pending)
        s = PallasSession(enc.device_state(), _templates_of(arrays),
                          interpret=True)
        before = s.evaluate([arrays[0]])
        s.apply_decisions([arrays[0]], [-1])  # off-shard: no-op
        after = s.evaluate([arrays[0]])
        assert before == after
        # a real apply then DOES move the carry
        s.apply_decisions([arrays[0]], [before[0][0]])
        moved = s.evaluate([arrays[1]])
        assert isinstance(moved[0][0], int)
