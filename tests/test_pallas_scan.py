"""PallasSession decision parity with the jnp HoistedSession (which is
itself pinned to the generic scan and the Go oracle).

Runs the kernel in interpreter mode on CPU — semantics only; the
single-launch performance story is bench.py's job on real hardware.
"""

import copy

import numpy as np
import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.ops.hoisted import HoistedSession, template_fingerprint
from kubernetes_tpu.ops.pallas_scan import PallasSession, PallasUnsupported
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

from .test_hoisted import _encode_all, _presized_encoding
from .util import make_pod


def _templates_of(arrays):
    out, seen = [], set()
    for a in arrays:
        fp = template_fingerprint(a)
        if fp not in seen:
            seen.add(fp)
            out.append(a)
    return out


def _run_pair(nodes, init_pods, pending, batch):
    """(jnp session decisions, pallas session decisions) over batches."""
    enc, pe = _presized_encoding(
        copy.deepcopy(nodes), copy.deepcopy(init_pods), copy.deepcopy(pending))
    arrays = _encode_all(enc, pe, pending)
    templates = _templates_of(arrays)
    jsess = HoistedSession(enc.device_state(), templates)
    ref = []
    for i in range(0, len(pending), batch):
        b = arrays[i:i + batch]
        # decisions() returns the padded batch bucket; real entries first
        ref.extend(HoistedSession.decisions(jsess.schedule(b))[:len(b)])

    enc2, pe2 = _presized_encoding(nodes, init_pods, pending)
    arrays2 = _encode_all(enc2, pe2, pending)
    psess = PallasSession(enc2.device_state(), _templates_of(arrays2),
                          interpret=True)
    got = []
    for i in range(0, len(pending), batch):
        b = arrays2[i:i + batch]
        got.extend(PallasSession.decisions(psess.schedule(b))[:len(b)])
    return ref, got


class TestPallasParity:
    def test_spread_multi_batch(self):
        nodes, init_pods = synth_cluster(16, pods_per_node=2)
        pending = synth_pending_pods(36, spread=True)
        ref, got = _run_pair(nodes, init_pods, pending, batch=12)
        assert got == ref
        assert all(d >= 0 for d in got)

    def test_no_constraints(self):
        nodes, init_pods = synth_cluster(10, pods_per_node=1)
        pending = synth_pending_pods(16, spread=False)
        ref, got = _run_pair(nodes, init_pods, pending, batch=8)
        assert got == ref

    def test_capacity_exhaustion(self):
        nodes, init_pods = synth_cluster(3, pods_per_node=0)
        for node in nodes:
            node.status.allocatable["cpu"] = "350m"
            node.status.capacity["cpu"] = "350m"
        pending = synth_pending_pods(15, spread=True)
        ref, got = _run_pair(nodes, init_pods, pending, batch=5)
        assert got == ref
        assert -1 in got

    def test_hostname_hard_spread(self):
        nodes, init_pods = synth_cluster(6, pods_per_node=1)
        pending = []
        for i in range(10):
            pending.append(make_pod(
                f"hard-{i}", cpu="50m", labels={"app": "hard"},
                constraints=[v1.TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=v1.LABEL_HOSTNAME,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=v1.LabelSelector(
                        match_labels={"app": "hard"}),
                )]))
        ref, got = _run_pair(nodes, init_pods, pending, batch=5)
        assert got == ref
        assert len(set(got[:6])) == 6

    def test_mixed_templates_cross_counting(self):
        nodes, init_pods = synth_cluster(8, pods_per_node=1)
        pending = []
        for i in range(12):
            labels = {"tier": "web", "idx": f"t{i % 2}"}
            pending.append(make_pod(
                f"x-{i}", cpu="50m", labels=labels,
                constraints=[v1.TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=v1.LABEL_ZONE,
                    when_unsatisfiable="ScheduleAnyway",
                    label_selector=v1.LabelSelector(
                        match_labels={"tier": "web"}),
                )]))
        ref, got = _run_pair(nodes, init_pods, pending, batch=6)
        assert got == ref

    def test_tainted_and_labeled_cluster(self):
        # synth_cluster taints some nodes and labels zones; spread pods
        # exercise taint counts + zone spread together
        nodes, init_pods = synth_cluster(12, pods_per_node=2)
        pending = synth_pending_pods(24, spread=True)
        ref, got = _run_pair(nodes, init_pods, pending, batch=24)
        assert got == ref


class TestPallasGuards:
    def test_large_weights_unsupported(self):
        from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods
        nodes, init_pods = synth_cluster(4, pods_per_node=1)
        pending = synth_pending_pods(4, spread=True)
        enc, pe = _presized_encoding(nodes, init_pods, pending)
        arrays = _encode_all(enc, pe, pending)
        with pytest.raises(PallasUnsupported):
            PallasSession(enc.device_state(), _templates_of(arrays),
                          weights={"balanced": 1, "image": 1, "ipa": 1,
                                   "least": 1, "node_affinity": 1,
                                   "prefer_avoid": 10 ** 6, "pts": 2,
                                   "taint": 1}, interpret=True)

    def test_variable_batch_lengths_share_one_compile(self):
        """B_real is dynamic: batches of different lengths (same padded
        width) must hit the same compiled kernel and stay exact."""
        import copy
        nodes, init_pods = synth_cluster(8, pods_per_node=1)
        pending = synth_pending_pods(20, spread=True)
        ref, got = [], []
        enc, pe = _presized_encoding(
            copy.deepcopy(nodes), copy.deepcopy(init_pods),
            copy.deepcopy(pending))
        arrays = _encode_all(enc, pe, pending)
        js = HoistedSession(enc.device_state(), _templates_of(arrays))
        for lo, hi in ((0, 7), (7, 12), (12, 20)):  # lengths 7, 5, 8
            ref.extend(HoistedSession.decisions(js.schedule(arrays[lo:hi])))
        enc2, pe2 = _presized_encoding(nodes, init_pods, pending)
        arrays2 = _encode_all(enc2, pe2, pending)
        ps = PallasSession(enc2.device_state(), _templates_of(arrays2),
                           interpret=True)
        for lo, hi in ((0, 7), (7, 12), (12, 20)):
            got.extend(PallasSession.decisions(ps.schedule(arrays2[lo:hi])))
        assert got == ref


class TestPallasFuzz:
    """Random-shape fuzz of the pallas kernel (interpret mode) against
    the jnp session: the f32 in-kernel score math is fuzz-TESTED, not
    asserted (VERDICT r1 item 10). Pallas takes only term-free
    templates, so fuzz pods are stripped of (anti-)affinity; spread
    constraints, taints, tolerations, priorities, images and extended
    resources all vary."""

    @pytest.mark.parametrize("seed", range(8))
    def test_fuzz_jnp_vs_pallas_interpret(self, seed):
        import random as _random

        from .test_kernel_parity import random_cluster, random_pending

        rng = _random.Random(1000 + seed)
        nodes, init_pods = random_cluster(rng)
        pending = []
        for i in range(10):
            p = random_pending(rng)
            p.metadata.name = f"fz-{seed}-{i}"
            p.spec.affinity = None       # pallas: term-free templates only
            for c in p.spec.containers:
                c.ports = None           # ...and port-free
            p.spec.node_name = ""
            pending.append(p)
        try:
            ref, got = _run_pair(nodes, init_pods, pending, batch=5)
        except PallasUnsupported as e:
            pytest.skip(f"shape unsupported by pallas: {e}")
        assert got == ref, f"seed={seed}: {got} != {ref}"
