"""SelectorSpread / NodeLabel / ServiceAffinity plugins + legacy Policy API.

Reference: selectorspread/selector_spread.go, nodelabel/node_label.go,
serviceaffinity/service_affinity.go, apis/config/legacy_types.go +
framework/plugins/legacy_registry.go.
"""

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.scheduler.apis.config import merged_plugins_for_profile
from kubernetes_tpu.scheduler.apis.legacy import policy_to_profile
from kubernetes_tpu.scheduler.framework.interface import Code, CycleState, NodeScore
from kubernetes_tpu.scheduler.framework.snapshot import Snapshot
from kubernetes_tpu.scheduler.framework.types import NodeInfo
from kubernetes_tpu.scheduler.plugins.nodelabel import NodeLabel
from kubernetes_tpu.scheduler.plugins.selectorspread import (
    SelectorSpread,
    default_selector,
)
from kubernetes_tpu.scheduler.plugins.serviceaffinity import ServiceAffinity

from .util import make_node, make_pod


def svc(name, selector, namespace="default"):
    return v1.Service(
        metadata=v1.ObjectMeta(name=name, namespace=namespace),
        spec=v1.ServiceSpec(selector=dict(selector)),
    )


class _Handle:
    def __init__(self, snapshot, services=(), rcs=(), rss=(), sss=()):
        self._snapshot = snapshot
        self.service_lister = lambda: list(services)
        self.spread_listers = lambda: (list(services), list(rcs), list(rss), list(sss))

    def snapshot_shared_lister(self):
        return self._snapshot


def _snapshot(pods, nodes):
    return Snapshot.from_objects(pods, nodes)


class TestDefaultSelector:
    def test_conjunction_of_matching_services(self):
        pod = make_pod("p", labels={"app": "web", "tier": "fe"})
        services = [svc("s1", {"app": "web"}), svc("s2", {"app": "other"})]
        sel = default_selector(pod, services, [], [], [])
        assert sel.matches({"app": "web"})
        assert not sel.matches({"app": "other"})

    def test_no_owner_matches_nothing(self):
        pod = make_pod("p", labels={"app": "web"})
        sel = default_selector(pod, [], [], [], [])
        assert not sel.matches({"app": "web"})


class TestSelectorSpread:
    def _cluster(self):
        nodes = [
            make_node("n0", labels={v1.LABEL_HOSTNAME: "n0", v1.LABEL_ZONE: "z0"}),
            make_node("n1", labels={v1.LABEL_HOSTNAME: "n1", v1.LABEL_ZONE: "z1"}),
        ]
        pods = [
            make_pod("e0", node_name="n0", labels={"app": "web"}),
            make_pod("e1", node_name="n0", labels={"app": "web"}),
            make_pod("e2", node_name="n1", labels={"app": "web"}),
        ]
        return pods, nodes

    def test_less_loaded_node_scores_higher(self):
        pods, nodes = self._cluster()
        snapshot = _snapshot(pods, nodes)
        handle = _Handle(snapshot, services=[svc("web", {"app": "web"})])
        pl = SelectorSpread(handle=handle)
        pod = make_pod("new", labels={"app": "web"})
        state = CycleState()
        assert pl.pre_score(state, pod, nodes) is None
        s0, _ = pl.score(state, pod, "n0")
        s1, _ = pl.score(state, pod, "n1")
        assert (s0, s1) == (2, 1)
        scores = [NodeScore("n0", s0), NodeScore("n1", s1)]
        assert pl.normalize_score(state, pod, scores) is None
        # n1 (fewer service pods in node AND zone) must outrank n0
        assert scores[1].score > scores[0].score

    def test_pod_without_owners_scores_zero(self):
        pods, nodes = self._cluster()
        snapshot = _snapshot(pods, nodes)
        handle = _Handle(snapshot)  # no services
        pl = SelectorSpread(handle=handle)
        pod = make_pod("new", labels={"app": "web"})
        state = CycleState()
        pl.pre_score(state, pod, nodes)
        s0, _ = pl.score(state, pod, "n0")
        assert s0 == 0


class TestNodeLabel:
    def test_filter_presence(self):
        pl = NodeLabel(args={"presentLabels": ["zone"], "absentLabels": ["bad"]})
        ni_ok, ni_missing, ni_bad = NodeInfo(), NodeInfo(), NodeInfo()
        ni_ok.set_node(make_node("a", labels={"zone": "z1"}))
        ni_missing.set_node(make_node("b"))
        ni_bad.set_node(make_node("c", labels={"zone": "z1", "bad": "1"}))
        assert pl.filter(CycleState(), make_pod("p"), ni_ok) is None
        assert pl.filter(CycleState(), make_pod("p"), ni_missing).code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE
        assert pl.filter(CycleState(), make_pod("p"), ni_bad).code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_score_fraction_of_preferences(self):
        nodes = [make_node("a", labels={"ssd": "true"})]
        handle = _Handle(_snapshot([], nodes))
        pl = NodeLabel(
            args={
                "presentLabelsPreference": ["ssd"],
                "absentLabelsPreference": ["spinning"],
            },
            handle=handle,
        )
        score, st = pl.score(CycleState(), make_pod("p"), "a")
        assert st is None and score == 100


class TestServiceAffinity:
    def test_filter_pins_label_values(self):
        nodes = [
            make_node("a", labels={"rack": "r1"}),
            make_node("b", labels={"rack": "r2"}),
            make_node("c"),
        ]
        existing = make_pod("e0", node_name="a", labels={"app": "db"})
        snapshot = _snapshot([existing], nodes)
        handle = _Handle(snapshot, services=[svc("db", {"app": "db"})])
        pl = ServiceAffinity(args={"affinityLabels": ["rack"]}, handle=handle)
        pod = make_pod("new", labels={"app": "db"})
        state = CycleState()
        assert pl.pre_filter(state, pod) is None
        ni = {n.metadata.name: NodeInfo() for n in nodes}
        for n in nodes:
            ni[n.metadata.name].set_node(n)
        assert pl.filter(state, pod, ni["a"]) is None  # same rack
        assert pl.filter(state, pod, ni["b"]).code == Code.UNSCHEDULABLE
        assert pl.filter(state, pod, ni["c"]).code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_score_spreads_across_label_values(self):
        nodes = [
            make_node("a", labels={"rack": "r1"}),
            make_node("b", labels={"rack": "r2"}),
        ]
        existing = [
            make_pod("e0", node_name="a", labels={"app": "db"}),
            make_pod("e1", node_name="a", labels={"app": "db"}),
        ]
        snapshot = _snapshot(existing, nodes)
        handle = _Handle(snapshot, services=[svc("db", {"app": "db"})])
        pl = ServiceAffinity(
            args={"antiAffinityLabelsPreference": ["rack"]}, handle=handle
        )
        pod = make_pod("new", labels={"app": "db"})
        state = CycleState()
        sa, _ = pl.score(state, pod, "a")
        sb, _ = pl.score(state, pod, "b")
        assert sa == 2 and sb == 0
        scores = [NodeScore("a", sa), NodeScore("b", sb)]
        pl.normalize_score(state, pod, scores)
        assert scores[1].score > scores[0].score


class TestLegacyPolicy:
    def test_policy_maps_to_plugins(self):
        policy = {
            "kind": "Policy",
            "predicates": [
                {"name": "PodFitsResources"},
                {"name": "PodToleratesNodeTaints"},
                {
                    "name": "CheckNodeLabelPresence",
                    "argument": {
                        "labelsPresence": {"labels": ["zone"], "presence": True}
                    },
                },
            ],
            "priorities": [
                {"name": "LeastRequestedPriority", "weight": 2},
                {
                    "name": "ServiceAntiAffinityPriority",
                    "weight": 3,
                    "argument": {"serviceAntiAffinity": {"label": "rack"}},
                },
            ],
        }
        profile = policy_to_profile(policy)
        merged = merged_plugins_for_profile(profile)
        assert ("NodeResourcesFit", 1) in merged["filter"]
        assert ("TaintToleration", 1) in merged["filter"]
        assert ("NodeLabel", 1) in merged["filter"]
        assert ("NodeResourcesLeastAllocated", 2) in merged["score"]
        assert ("ServiceAffinity", 3) in merged["score"]
        # defaults NOT selected by the policy are gone ('*' disable)
        assert all(n != "InterPodAffinity" for n, _ in merged["filter"])
        assert all(n != "PodTopologySpread" for n, _ in merged["score"])
        # mandatory wiring intact
        assert merged["queueSort"] == [("PrioritySort", 1)]
        assert merged["bind"] == [("DefaultBinder", 1)]
        assert profile.plugin_config["NodeLabel"]["presentLabels"] == ["zone"]
        assert profile.plugin_config["ServiceAffinity"][
            "antiAffinityLabelsPreference"
        ] == ["rack"]

    def test_unknown_predicate_rejected(self):
        import pytest

        from kubernetes_tpu.scheduler.apis.config import ConfigError

        with pytest.raises(ConfigError):
            policy_to_profile({"predicates": [{"name": "NoSuchPredicate"}]})


def test_duplicate_priorities_sum_weights():
    policy = {
        "priorities": [
            {"name": "SelectorSpreadPriority", "weight": 1},
            {"name": "ServiceSpreadingPriority", "weight": 5},
        ]
    }
    profile = policy_to_profile(policy)
    merged = merged_plugins_for_profile(profile)
    assert ("SelectorSpread", 6) in merged["score"]
