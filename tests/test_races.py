"""Concurrency stress: the lock discipline in the scheduler cache and
the TPU backend's CacheListener hooks is load-bearing (VERDICT r1 §5 —
'heavily threaded code... untested for races'). These tests hammer the
shared structures from many threads and assert the invariants that a
torn update would break.

Reference shape: the Go suite runs these paths under -race
(hack/make-rules/test.sh KUBE_RACE); Python has no race detector, so
the assertions target observable corruption instead."""

import random
import threading

import pytest

from kubernetes_tpu.scheduler.internal.cache import SchedulerCache
from kubernetes_tpu.scheduler.tpu_backend import TPUBackend

from .util import make_node, make_pod


def _run_threads(workers, iterations=1):
    errors = []

    def wrap(fn):
        def run():
            try:
                for _ in range(iterations):
                    fn()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        return run

    threads = [threading.Thread(target=wrap(fn)) for fn in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "worker deadlocked"
    assert not errors, errors


class TestCacheRaces:
    def test_assume_confirm_remove_storm(self):
        """4 writer threads × assume/confirm/update/remove on overlapping
        pods; the cache must end exactly consistent with the last
        surviving set (no orphaned assumes, no negative node stats)."""
        cache = SchedulerCache()
        for i in range(8):
            cache.add_node(make_node(f"n{i}"))

        def worker(tid):
            rng = random.Random(tid)

            def run():
                for i in range(150):
                    pod = make_pod(f"p-{tid}-{i}", cpu="10m",
                                   node_name=f"n{rng.randrange(8)}")
                    cache.assume_pod(pod)
                    if rng.random() < 0.5:
                        cache.add_pod(pod)       # confirm
                        cache.remove_pod(pod)
                    else:
                        cache.forget_pod(pod)

            return run

        _run_threads([worker(t) for t in range(4)])
        from kubernetes_tpu.scheduler.framework.snapshot import Snapshot

        snap = cache.update_snapshot(Snapshot())
        for ni in snap.list():
            assert not ni.pods, f"leaked pods on {ni.node.metadata.name}"
            assert ni.requested.milli_cpu == 0

    def test_min_priority_under_churn(self):
        cache = SchedulerCache()
        cache.add_node(make_node("n0"))
        stop = threading.Event()

        def churn(tid):
            def run():
                for i in range(300):
                    p = make_pod(f"c-{tid}-{i}", cpu="1m", node_name="n0",
                                 priority=i % 7 - 3)
                    cache.assume_pod(p)
                    cache.forget_pod(p)

            return run

        def read():
            while not stop.is_set():
                v = cache.min_pod_priority()
                assert -3 <= v <= 3 or v == 0

        reader = threading.Thread(target=read, daemon=True)
        reader.start()
        try:
            _run_threads([churn(0), churn(1)])
        finally:
            stop.set()
        reader.join(timeout=10)
        assert not reader.is_alive()


class TestBackendListenerRaces:
    def test_mutations_racing_schedule_many(self):
        """Cluster mutations (node add/update, foreign pod adds) from
        listener threads while schedule_many batches run: every returned
        decision must name a node that existed, and the encoding must
        stay internally consistent (the session teardown/rebuild path is
        exactly what these mutations exercise)."""
        backend = TPUBackend(rng=random.Random(0))
        for i in range(12):
            backend.on_add_node(make_node(f"n{i}"))

        stop = threading.Event()
        node_names = [f"n{i}" for i in range(12)]

        def mutator():
            # shape-stable mutations only (node UPDATES + foreign pod
            # add/remove on pre-interned labels): each one still tears
            # the session down and races the listener locks, but keeps
            # array shapes fixed so jit caches hold — shape churn here
            # turns the test into an XLA compile marathon, not a race test
            import time as _time

            rng = random.Random(99)
            k = 0
            while not stop.is_set():
                k += 1
                name = rng.choice(node_names)
                backend.on_update_node(make_node(name))
                foreign = make_pod(f"foreign-{k % 8}", cpu="5m",
                                   labels={"app": "race"},
                                   node_name=rng.choice(node_names))
                backend.on_add_pod(foreign, foreign.spec.node_name)
                backend.on_remove_pod(foreign, foreign.spec.node_name)
                _time.sleep(0.005)

        # warm every jit shape BEFORE the storm (compiles under mutation
        # churn would serialize the test, not stress the locks)
        warm = [make_pod(f"warm-{i}", cpu="10m", labels={"app": "race"})
                for i in range(16)]
        backend.schedule_many(warm)
        for p in warm:
            backend.on_remove_pod(p, p.spec.node_name or "n0")

        mut = threading.Thread(target=mutator, daemon=True)
        mut.start()
        try:
            for round_no in range(6):
                pods = [
                    make_pod(f"b{round_no}-{i}", cpu="10m",
                             labels={"app": "race"})
                    for i in range(16)
                ]
                results = backend.schedule_many(pods)
                assert len(results) == 16
                valid = set(backend.enc.node_names)
                for pod, node in results:
                    assert node is None or node in valid
        finally:
            stop.set()
            mut.join(timeout=10)
        assert not mut.is_alive()

    def test_rebuild_survives_node_deletion_with_bound_pods(self):
        """Regression (found by the racing version of this suite): a node
        removed while pods were still bound to it crashed the next
        encoding rebuild with KeyError — which would have killed the
        scheduler loop on any real node deletion racing bound pods."""
        backend = TPUBackend(rng=random.Random(0))
        for i in range(4):
            backend.on_add_node(make_node(f"n{i}"))
        pod = make_pod("survivor", cpu="10m", node_name="n3")
        backend.on_add_pod(pod, "n3")
        backend.on_remove_node("n3")  # pod still referenced n3
        # force a rebuild: must not raise, and n3 contributes nothing
        state = backend.enc.device_state()
        assert "n3" not in backend.enc.node_names
        # the pod re-appears when its node comes back
        backend.on_add_node(make_node("n3"))
        backend.enc.device_state()
        assert backend.enc.pod_index
