"""kube-proxy equivalent: EndpointSlice controller + proxier chain model.

Reference shape: pkg/proxy/iptables/proxier_test.go (syncProxyRules rule
synthesis, session affinity, nodeports, no-endpoints REJECT) and
pkg/controller/endpointslice tests.
"""

import random
from collections import Counter

import pytest

from kubernetes_tpu.api import discovery
from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.endpointslice import EndpointSliceController
from kubernetes_tpu.proxy import Packet, Proxier

from .util import wait_until


def _svc(name, cluster_ip, port=80, target_port=8080, selector=None, **kw):
    return v1.Service(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        spec=v1.ServiceSpec(
            selector=selector or {"app": name},
            cluster_ip=cluster_ip,
            ports=[v1.ServicePort(name="http", port=port, target_port=target_port)],
            **kw,
        ),
    )


def _running_pod(name, ip, labels):
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name, namespace="default", labels=labels),
        spec=v1.PodSpec(node_name="n1", containers=[v1.Container(name="c", image="i")]),
        status=v1.PodStatus(
            phase="Running",
            pod_ip=ip,
            conditions=[v1.PodCondition(type="Ready", status="True")],
        ),
    )


@pytest.fixture()
def cluster():
    api = APIServer()
    cs = Clientset(api)
    factory = SharedInformerFactory(cs)
    ctrl = EndpointSliceController(cs, factory)
    proxier = Proxier(factory, rng=random.Random(7))
    factory.start()
    assert factory.wait_for_cache_sync()
    ctrl.run()
    yield cs, proxier
    ctrl.stop()
    factory.stop()


def _slices_for(cs, name):
    slices, _ = cs.resource("endpointslices").list(namespace="default")
    return [
        s
        for s in slices
        if (s.metadata.labels or {}).get(discovery.LABEL_SERVICE_NAME) == name
    ]


class TestEndpointSliceController:
    def test_slices_mirror_pods(self, cluster):
        cs, _ = cluster
        cs.services.create(_svc("web", "10.0.0.1"))
        for i in range(3):
            cs.pods.create(_running_pod(f"web-{i}", f"10.1.0.{i}", {"app": "web"}))
        assert wait_until(
            lambda: sum(
                len(s.endpoints or []) for s in _slices_for(cs, "web")
            ) == 3
        )
        sl = _slices_for(cs, "web")[0]
        assert sl.ports[0].port == 8080
        assert all(ep.conditions.ready for ep in sl.endpoints)

    def test_slice_chunking(self, cluster):
        cs, _ = cluster
        ctrl_max = discovery.MAX_ENDPOINTS_PER_SLICE
        cs.services.create(_svc("big", "10.0.0.2"))
        for i in range(ctrl_max + 5):
            cs.pods.create(
                _running_pod(f"big-{i}", f"10.2.{i // 250}.{i % 250}", {"app": "big"})
            )
        assert wait_until(
            lambda: sorted(
                len(s.endpoints or []) for s in _slices_for(cs, "big")
            ) == [5, ctrl_max]
        )

    def test_service_delete_removes_slices(self, cluster):
        cs, _ = cluster
        cs.services.create(_svc("gone", "10.0.0.3"))
        cs.pods.create(_running_pod("gone-0", "10.3.0.0", {"app": "gone"}))
        assert wait_until(lambda: len(_slices_for(cs, "gone")) == 1)
        cs.services.delete("gone", "default")
        assert wait_until(lambda: len(_slices_for(cs, "gone")) == 0)


class TestProxier:
    def test_clusterip_dnat_balances(self, cluster):
        cs, proxier = cluster
        cs.services.create(_svc("web", "10.0.0.1"))
        ips = {f"10.1.0.{i}" for i in range(3)}
        for i in range(3):
            cs.pods.create(_running_pod(f"web-{i}", f"10.1.0.{i}", {"app": "web"}))
        assert wait_until(
            lambda: sum(
                1 for n in proxier.netfilter.chains if n.startswith("KUBE-SEP-")
            ) == 3
        )
        hits = Counter()
        for i in range(300):
            ip, port = proxier.route(
                Packet(dst_ip="10.0.0.1", dst_port=80, src_ip=f"10.9.0.{i}")
            )
            assert port == 8080
            hits[ip] += 1
        assert set(hits) == ips
        # statistic-random cascade is roughly uniform
        assert all(60 <= v <= 140 for v in hits.values()), hits

    def test_no_endpoints_rejects(self, cluster):
        cs, proxier = cluster
        cs.services.create(_svc("empty", "10.0.0.9"))
        assert wait_until(lambda: proxier.sync_count > 0)
        wait_until(
            lambda: any(
                r.target == "REJECT" and r.dst_ip == "10.0.0.9"
                for r in proxier.netfilter.chains["KUBE-SERVICES"].rules
            )
        )
        with pytest.raises(ConnectionRefusedError):
            proxier.route(Packet(dst_ip="10.0.0.9", dst_port=80, src_ip="10.9.9.9"))

    def test_unknown_vip_passes_through(self, cluster):
        _, proxier = cluster
        proxier.sync_proxy_rules()
        with pytest.raises(LookupError):
            proxier.route(Packet(dst_ip="192.168.1.1", dst_port=443, src_ip="x"))

    def test_session_affinity_client_ip(self, cluster):
        cs, proxier = cluster
        cs.services.create(
            _svc("sticky", "10.0.0.4", session_affinity="ClientIP")
        )
        for i in range(4):
            cs.pods.create(
                _running_pod(f"sticky-{i}", f"10.4.0.{i}", {"app": "sticky"})
            )
        assert wait_until(
            lambda: sum(len(s.endpoints or []) for s in _slices_for(cs, "sticky")) == 4
            and proxier.sync_count > 0
            and any(
                r.dst_ip == "10.0.0.4" and r.target != "REJECT"
                for r in proxier.netfilter.chains["KUBE-SERVICES"].rules
            )
        )
        first = proxier.route(Packet(dst_ip="10.0.0.4", dst_port=80, src_ip="10.9.0.1"))
        for _ in range(50):
            again = proxier.route(
                Packet(dst_ip="10.0.0.4", dst_port=80, src_ip="10.9.0.1")
            )
            assert again == first
        # a different client may land elsewhere and then sticks too
        other = proxier.route(Packet(dst_ip="10.0.0.4", dst_port=80, src_ip="10.9.0.2"))
        for _ in range(20):
            assert (
                proxier.route(Packet(dst_ip="10.0.0.4", dst_port=80, src_ip="10.9.0.2"))
                == other
            )

    def test_nodeport_routes(self, cluster):
        cs, proxier = cluster
        svc = _svc("np", "10.0.0.5", type="NodePort")
        svc.spec.ports[0].node_port = 30080
        cs.services.create(svc)
        cs.pods.create(_running_pod("np-0", "10.5.0.0", {"app": "np"}))
        assert wait_until(
            lambda: sum(len(s.endpoints or []) for s in _slices_for(cs, "np")) == 1
            and any(
                r.dst_port == 30080
                for r in proxier.netfilter.chains.get(
                    "KUBE-NODEPORTS", type("C", (), {"rules": []})
                ).rules
            )
        )
        # node IP, nodePort -> falls through KUBE-SERVICES to KUBE-NODEPORTS
        ip, port = proxier.route(
            Packet(dst_ip="172.16.0.7", dst_port=30080, src_ip="z")
        )
        assert (ip, port) == ("10.5.0.0", 8080)

    def test_endpoint_removal_resyncs(self, cluster):
        cs, proxier = cluster
        cs.services.create(_svc("shrink", "10.0.0.6"))
        for i in range(2):
            cs.pods.create(
                _running_pod(f"shrink-{i}", f"10.6.0.{i}", {"app": "shrink"})
            )
        assert wait_until(
            lambda: sum(len(s.endpoints or []) for s in _slices_for(cs, "shrink")) == 2
        )
        cs.pods.delete("shrink-0", "default")
        def only_one_left():
            try:
                hits = {
                    proxier.route(
                        Packet(dst_ip="10.0.0.6", dst_port=80, src_ip=f"c{i}")
                    )[0]
                    for i in range(20)
                }
            except ConnectionRefusedError:
                return False
            return hits == {"10.6.0.1"}
        assert wait_until(only_one_left)
