"""End-to-end slice: store → apiserver → informers → scheduler → bind.

The reference's integration tier (test/integration/scheduler/) runs a real
apiserver+etcd with the scheduler under test and asserts pods get bound —
same here, with the in-proc store. Both backends (oracle framework path
and TPU kernel path) must bind every pod and agree on decision quality
(max-score placement)."""

from __future__ import annotations

import time

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import Clientset, SharedInformerFactory
from kubernetes_tpu.scheduler.framework.runtime import Framework
from kubernetes_tpu.scheduler.framework.snapshot import Snapshot
from kubernetes_tpu.scheduler.plugins.registry import (
    default_plugins_without,
    new_in_tree_registry,
)
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.testing.synth import make_node, make_pod


def _cluster(n_nodes=6):
    api = APIServer()
    cs = Clientset(api)
    for i in range(n_nodes):
        cs.nodes.create(
            make_node(
                f"node-{i}",
                labels={v1.LABEL_HOSTNAME: f"node-{i}", v1.LABEL_ZONE: f"z{i % 3}"},
            )
        )
    return api, cs


def _mk_scheduler(cs, backend):
    factory = SharedInformerFactory(cs)
    if backend == "oracle":
        sched = Scheduler(cs, factory, backend="oracle")
        snapshot_ref = [Snapshot()]

        def snap():
            return sched.snapshot

        sched.framework = Framework(
            new_in_tree_registry(),
            plugins=default_plugins_without("DefaultPreemption"),
            snapshot_fn=snap,
        )
    else:
        sched = Scheduler(cs, factory, backend="tpu")
    factory.start()
    assert factory.wait_for_cache_sync()
    return sched


@pytest.mark.parametrize("backend", ["oracle", "tpu"])
def test_pods_get_bound(backend):
    api, cs = _cluster()
    sched = _mk_scheduler(cs, backend)
    try:
        for i in range(10):
            cs.pods.create(make_pod(f"p-{i}", namespace="default", cpu="100m",
                                    labels={"app": "web"}))
        sched.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            pods, _ = cs.pods.list(namespace="default")
            if all(p.spec.node_name for p in pods):
                break
            time.sleep(0.1)
        pods, _ = cs.pods.list(namespace="default")
        bound = {p.metadata.name: p.spec.node_name for p in pods}
        assert all(bound.values()), f"unbound pods: {bound}"
        # spread over multiple nodes (LeastAllocated/BalancedAllocation push
        # away from loaded nodes as requests accumulate)
        assert len(set(bound.values())) > 1
    finally:
        sched.stop()
        sched.informers.stop()


@pytest.mark.parametrize("depth", [0, 1, 3])
def test_pods_get_bound_at_any_pipeline_depth(depth):
    """The full loop binds everything at every pipeline depth — depth 0
    (sequential), 1 (single-buffered), and beyond the default. The
    bit-parity gate over randomized churn is tests/test_pipeline_parity.py;
    this pins the live loop's drain paths (idle/pause/stop) per depth."""
    api, cs = _cluster()
    factory = SharedInformerFactory(cs)
    sched = Scheduler(cs, factory, backend="tpu", pipeline_depth=depth)
    factory.start()
    assert factory.wait_for_cache_sync()
    try:
        for i in range(12):
            cs.pods.create(make_pod(f"p-{i}", namespace="default", cpu="100m",
                                    labels={"app": "web"}))
        sched.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            pods, _ = cs.pods.list(namespace="default")
            if all(p.spec.node_name for p in pods):
                break
            time.sleep(0.1)
        pods, _ = cs.pods.list(namespace="default")
        assert all(p.spec.node_name for p in pods)
    finally:
        sched.stop()
        sched.informers.stop()


@pytest.mark.parametrize("backend", ["oracle", "tpu"])
def test_unschedulable_then_node_arrives(backend):
    """A pod too big for every node parks in unschedulableQ; adding a
    big-enough node triggers MoveAllToActiveOrBackoffQueue and it binds
    (eventhandlers.go:90 addNodeToCache -> queue flush)."""
    api, cs = _cluster(n_nodes=2)
    sched = _mk_scheduler(cs, backend)
    try:
        cs.pods.create(make_pod("hungry", namespace="default", cpu="16"))
        sched.start()
        time.sleep(1.0)
        pod = cs.pods.get("hungry", "default")
        assert not pod.spec.node_name, "must not fit the 4-cpu nodes"
        cs.nodes.create(
            make_node("big", cpu="32", labels={v1.LABEL_HOSTNAME: "big"})
        )
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            pod = cs.pods.get("hungry", "default")
            if pod.spec.node_name:
                break
            time.sleep(0.1)
        assert pod.spec.node_name == "big"
    finally:
        sched.stop()
        sched.informers.stop()


def test_tpu_and_oracle_agree_on_quality():
    """A/B: on identical clusters, every TPU placement must carry the same
    total score the oracle assigns to its own choice for that pod (ties
    are reservoir-sampled in both paths, so exact node equality isn't
    required — score equality is)."""
    api1, cs1 = _cluster()
    api2, cs2 = _cluster()
    s_oracle = _mk_scheduler(cs1, "oracle")
    s_tpu = _mk_scheduler(cs2, "tpu")
    try:
        for i in range(8):
            for cs in (cs1, cs2):
                cs.pods.create(make_pod(f"p-{i}", namespace="default", cpu="200m",
                                        labels={"app": "web"}))
        s_oracle.start()
        s_tpu.start()
        for cs in (cs1, cs2):
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                pods, _ = cs.pods.list(namespace="default")
                if all(p.spec.node_name for p in pods):
                    break
                time.sleep(0.1)
        pods1, _ = cs1.pods.list(namespace="default")
        pods2, _ = cs2.pods.list(namespace="default")
        n1 = sorted(p.spec.node_name for p in pods1)
        n2 = sorted(p.spec.node_name for p in pods2)
        assert all(n1) and all(n2)
        # both paths spread 8 identical pods across the 6 nodes: the
        # placement multiset must match (scores are deterministic; only
        # tie choice varies, which preserves the multiset of loads)
        loads1 = sorted(n1.count(x) for x in set(n1))
        loads2 = sorted(n2.count(x) for x in set(n2))
        assert loads1 == loads2, (n1, n2)
    finally:
        s_oracle.stop()
        s_tpu.stop()
        s_oracle.informers.stop()
        s_tpu.informers.stop()
