"""Admission webhooks over real HTTP: mutate (JSONPatch), validate
(deny), failurePolicy.

Reference shape: apiserver/pkg/admission/plugin/webhook tests with a live
test server.
"""

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.server import APIServer, Invalid
from kubernetes_tpu.apiserver.webhook import (
    MutatingWebhookConfiguration,
    RuleWithOperations,
    ValidatingWebhookConfiguration,
    Webhook,
    WebhookAdmission,
    WebhookClientConfig,
    apply_json_patch,
)
from kubernetes_tpu.client.clientset import Clientset

from .util import make_pod


class _Handler(BaseHTTPRequestHandler):
    behavior = staticmethod(lambda review: {"allowed": True})
    seen = []

    def do_POST(self):
        length = int(self.headers["Content-Length"])
        review = json.loads(self.rfile.read(length))
        type(self).seen.append(review)
        response = type(self).behavior(review)
        body = json.dumps({
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "response": {"uid": review["request"]["uid"], **response},
        }).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # quiet
        pass


@pytest.fixture()
def webhook_server():
    _Handler.seen = []
    _Handler.behavior = staticmethod(lambda review: {"allowed": True})
    server = HTTPServer(("127.0.0.1", 0), _Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{server.server_port}/", _Handler
    server.shutdown()


@pytest.fixture()
def cluster():
    api = APIServer()
    WebhookAdmission(api).install()
    return api, Clientset(api)


class TestJSONPatch:
    def test_ops(self):
        doc = {"spec": {"containers": [{"name": "c"}]}, "metadata": {}}
        out = apply_json_patch(doc, [
            {"op": "add", "path": "/metadata/labels", "value": {"a": "b"}},
            {"op": "replace", "path": "/spec/containers/0/name", "value": "x"},
            {"op": "add", "path": "/spec/containers/-", "value": {"name": "y"}},
            {"op": "remove", "path": "/metadata/labels"},
        ])
        assert out["spec"]["containers"][0]["name"] == "x"
        assert out["spec"]["containers"][1]["name"] == "y"
        assert "labels" not in out["metadata"]


class TestValidatingWebhook:
    def test_deny_and_allow(self, cluster, webhook_server):
        api, cs = cluster
        url, handler = webhook_server
        cs.resource("validatingwebhookconfigurations").create(
            ValidatingWebhookConfiguration(
                metadata=v1.ObjectMeta(name="deny-big"),
                webhooks=[Webhook(
                    name="deny.example.com",
                    client_config=WebhookClientConfig(url=url),
                    rules=[RuleWithOperations(operations=["CREATE"], resources=["pods"])],
                )],
            )
        )
        handler.behavior = staticmethod(lambda review: {
            "allowed": review["request"]["object"]["metadata"]["name"] != "bad",
            "status": {"message": "bad pods not allowed"},
        })
        cs.pods.create(make_pod("ok"))
        with pytest.raises(Invalid, match="bad pods not allowed"):
            cs.pods.create(make_pod("bad"))
        # rule scoping: nodes are not covered
        from .util import make_node

        cs.nodes.create(make_node("n1"))
        kinds = [r["request"]["resource"]["resource"] for r in handler.seen]
        assert "nodes" not in kinds

    def test_failure_policy(self, cluster):
        api, cs = cluster
        dead = "http://127.0.0.1:1/"  # nothing listens
        cs.resource("validatingwebhookconfigurations").create(
            ValidatingWebhookConfiguration(
                metadata=v1.ObjectMeta(name="flaky"),
                webhooks=[Webhook(
                    name="fail.example.com",
                    client_config=WebhookClientConfig(url=dead),
                    rules=[RuleWithOperations(operations=["CREATE"], resources=["pods"])],
                    failure_policy="Fail",
                    timeout_seconds=1,
                )],
            )
        )
        with pytest.raises(Invalid, match="failed calling webhook"):
            cs.pods.create(make_pod("p"))
        cfg = cs.resource("validatingwebhookconfigurations").get("flaky")
        cfg.webhooks[0].failure_policy = "Ignore"
        cs.resource("validatingwebhookconfigurations").update(cfg)
        cs.pods.create(make_pod("p"))  # unreachable hook now ignored


class TestMutatingWebhook:
    def test_jsonpatch_applied(self, cluster, webhook_server):
        api, cs = cluster
        url, handler = webhook_server
        cs.resource("mutatingwebhookconfigurations").create(
            MutatingWebhookConfiguration(
                metadata=v1.ObjectMeta(name="inject"),
                webhooks=[Webhook(
                    name="inject.example.com",
                    client_config=WebhookClientConfig(url=url),
                    rules=[RuleWithOperations(operations=["CREATE"], resources=["pods"])],
                )],
            )
        )
        patch = base64.b64encode(json.dumps([
            {"op": "add", "path": "/metadata/labels", "value": {"injected": "yes"}},
            {"op": "add", "path": "/spec/priority", "value": 7},
        ]).encode()).decode()
        handler.behavior = staticmethod(lambda review: {
            "allowed": True, "patchType": "JSONPatch", "patch": patch,
        })
        created = cs.pods.create(make_pod("p"))
        assert created.metadata.labels["injected"] == "yes"
        assert created.spec.priority == 7
        # the stored object carries the mutation too
        assert cs.pods.get("p", "default").spec.priority == 7


class TestWebhookFixes:
    def test_patched_object_keeps_server_stamps(self, cluster, webhook_server):
        api, cs = cluster
        url, handler = webhook_server
        cs.resource("mutatingwebhookconfigurations").create(
            MutatingWebhookConfiguration(
                metadata=v1.ObjectMeta(name="inject"),
                webhooks=[Webhook(
                    name="inject.example.com",
                    client_config=WebhookClientConfig(url=url),
                    rules=[RuleWithOperations(operations=["CREATE"], resources=["pods"])],
                )],
            )
        )
        patch = base64.b64encode(json.dumps([
            {"op": "add", "path": "/metadata/labels", "value": {"x": "y"}},
        ]).encode()).decode()
        handler.behavior = staticmethod(lambda review: {
            "allowed": True, "patchType": "JSONPatch", "patch": patch,
        })
        created = cs.pods.create(make_pod("p"))
        # server stamps must survive the in-place patch (uid/creation time
        # are stamped via the metadata alias held by create())
        assert created.metadata.uid
        assert created.metadata.creation_timestamp is not None
        assert created.metadata.labels["x"] == "y"

    def test_delete_webhook_fires(self, cluster, webhook_server):
        api, cs = cluster
        url, handler = webhook_server
        cs.pods.create(make_pod("keep"))
        cs.resource("validatingwebhookconfigurations").create(
            ValidatingWebhookConfiguration(
                metadata=v1.ObjectMeta(name="guard"),
                webhooks=[Webhook(
                    name="guard.example.com",
                    client_config=WebhookClientConfig(url=url),
                    rules=[RuleWithOperations(operations=["DELETE"], resources=["pods"])],
                )],
            )
        )
        handler.behavior = staticmethod(lambda review: {
            "allowed": review["request"]["operation"] != "DELETE",
            "status": {"message": "deletion guarded"},
        })
        with pytest.raises(Invalid, match="deletion guarded"):
            cs.pods.delete("keep", "default")
        assert cs.pods.get("keep", "default")
        handler.behavior = staticmethod(lambda review: {"allowed": True})
        cs.pods.delete("keep", "default")

    def test_malformed_response_honors_failure_policy(self, cluster, webhook_server):
        api, cs = cluster
        url, handler = webhook_server

        class Raw:
            pass

        # respond 200 with a body that has no "response" object
        def weird(review):
            return {}  # merged under "response" by the handler... bypass:
        # patch the handler to send a body without "response"
        import json as _json

        def do_POST(self):
            length = int(self.headers["Content-Length"])
            self.rfile.read(length)
            body = _json.dumps({"kind": "AdmissionReview"}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        orig = handler.do_POST
        handler.do_POST = do_POST
        try:
            cs.resource("validatingwebhookconfigurations").create(
                ValidatingWebhookConfiguration(
                    metadata=v1.ObjectMeta(name="weird"),
                    webhooks=[Webhook(
                        name="weird.example.com",
                        client_config=WebhookClientConfig(url=url),
                        rules=[RuleWithOperations(operations=["CREATE"], resources=["pods"])],
                        failure_policy="Ignore",
                    )],
                )
            )
            cs.pods.create(make_pod("ok-despite-weird"))  # Ignore -> allowed
            cfg = cs.resource("validatingwebhookconfigurations").get("weird")
            cfg.webhooks[0].failure_policy = "Fail"
            cs.resource("validatingwebhookconfigurations").update(cfg)
            with pytest.raises(Invalid, match="failed calling webhook"):
                cs.pods.create(make_pod("rejected"))
        finally:
            handler.do_POST = orig
