"""Round-3 admission plugins: DefaultStorageClass,
StorageObjectInUseProtection, AlwaysPullImages,
LimitPodHardAntiAffinityTopology, PodSecurity-lite.

Reference: plugin/pkg/admission/storage/storageclass/setdefault,
.../storageobjectinuse, .../alwayspullimages, .../antiaffinity;
policy/pod-security-admission (the PSP successor the -lite plugin
models). Default-enabled wiring per kubeapiserver/options/plugins.go.
"""

import pytest

from kubernetes_tpu.api import storage
from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.admission import (
    PV_PROTECTION_FINALIZER,
    PVC_PROTECTION_FINALIZER,
    always_pull_images,
    default_storage_class,
    install_default_admission,
    limit_pod_hard_anti_affinity_topology,
    pod_security,
    storage_object_in_use_protection,
)
from kubernetes_tpu.apiserver.server import APIServer, Invalid

from .util import make_pod


def _api(*plugins, mutating=(), validating=()):
    api = APIServer()
    api._mutating.extend(mutating)
    api._validating.extend(validating)
    return api


class TestDefaultStorageClass:
    def _api_with_classes(self, *annotations):
        api = APIServer()
        api._mutating.append(default_storage_class(api))
        for i, ann in enumerate(annotations):
            api.create("storageclasses", storage.StorageClass(
                metadata=v1.ObjectMeta(
                    name=f"sc-{i}",
                    annotations=(
                        {"storageclass.kubernetes.io/is-default-class": "true"}
                        if ann else None
                    ),
                ),
            ))
        return api

    def test_defaults_unset_class(self):
        api = self._api_with_classes(False, True)
        pvc = api.create("persistentvolumeclaims", v1.PersistentVolumeClaim(
            metadata=v1.ObjectMeta(name="data", namespace="default")))
        assert pvc.spec.storage_class_name == "sc-1"

    def test_explicit_class_kept(self):
        api = self._api_with_classes(True)
        pvc = v1.PersistentVolumeClaim(
            metadata=v1.ObjectMeta(name="data", namespace="default"))
        pvc.spec.storage_class_name = "mine"
        out = api.create("persistentvolumeclaims", pvc)
        assert out.spec.storage_class_name == "mine"

    def test_two_defaults_rejected(self):
        api = self._api_with_classes(True, True)
        with pytest.raises(Invalid):
            api.create("persistentvolumeclaims", v1.PersistentVolumeClaim(
                metadata=v1.ObjectMeta(name="data", namespace="default")))


class TestStorageObjectInUseProtection:
    def test_finalizers_stamped_on_create(self):
        api = APIServer()
        api._mutating.append(storage_object_in_use_protection(api))
        pvc = api.create("persistentvolumeclaims", v1.PersistentVolumeClaim(
            metadata=v1.ObjectMeta(name="c", namespace="default")))
        assert PVC_PROTECTION_FINALIZER in (pvc.metadata.finalizers or [])
        pv = api.create("persistentvolumes", v1.PersistentVolume(
            metadata=v1.ObjectMeta(name="v")))
        assert PV_PROTECTION_FINALIZER in (pv.metadata.finalizers or [])

    def test_wired_to_protection_controllers(self):
        """The finalizer the plugin stamps is the one the pvc-protection
        controller removes (VERDICT r2: wire plugin <-> controllers)."""
        from kubernetes_tpu.controllers.volumeprotection import (
            PVC_PROTECTION_FINALIZER as CTRL_FIN,
        )

        assert CTRL_FIN == PVC_PROTECTION_FINALIZER


class TestAlwaysPullImages:
    def test_forces_always(self):
        api = APIServer()
        api._mutating.append(always_pull_images(api))
        pod = make_pod("p")
        pod.spec.containers[0].image_pull_policy = "IfNotPresent"
        out = api.create("pods", pod)
        assert out.spec.containers[0].image_pull_policy == "Always"


class TestLimitPodHardAntiAffinityTopology:
    def _pod_with_anti(self, key):
        pod = make_pod("anti")
        pod.spec.affinity = v1.Affinity(
            pod_anti_affinity=v1.PodAntiAffinity(
                required_during_scheduling_ignored_during_execution=[
                    v1.PodAffinityTerm(
                        label_selector=v1.LabelSelector(
                            match_labels={"app": "x"}),
                        topology_key=key,
                    )
                ]
            )
        )
        return pod

    def test_hostname_allowed_zone_rejected(self):
        api = APIServer()
        api._validating.append(limit_pod_hard_anti_affinity_topology(api))
        api.create("pods", self._pod_with_anti(v1.LABEL_HOSTNAME))
        with pytest.raises(Invalid):
            api.create("pods", self._pod_with_anti(v1.LABEL_ZONE))


class TestPodSecurity:
    def _api(self, level):
        api = APIServer()
        api._validating.append(pod_security(api))
        api.create("namespaces", v1.Namespace(metadata=v1.ObjectMeta(
            name="secure",
            labels={"pod-security.kubernetes.io/enforce": level},
        )))
        return api

    def test_baseline_rejects_privileged(self):
        api = self._api("baseline")
        pod = make_pod("priv", namespace="secure")
        pod.spec.containers[0].security_context = {"privileged": True}
        with pytest.raises(Invalid, match="privileged"):
            api.create("pods", pod)

    def test_baseline_rejects_host_namespaces_and_hostpath(self):
        api = self._api("baseline")
        pod = make_pod("hosty", namespace="secure")
        pod.spec.host_pid = True
        with pytest.raises(Invalid, match="hostPID"):
            api.create("pods", pod)
        pod2 = make_pod("pathy", namespace="secure")
        pod2.spec.volumes = [v1.Volume(
            name="h", source={"hostPath": {"path": "/etc"}})]
        with pytest.raises(Invalid, match="hostPath"):
            api.create("pods", pod2)

    def test_baseline_allows_plain_pod(self):
        api = self._api("baseline")
        api.create("pods", make_pod("plain", namespace="secure"))

    def test_restricted_requires_nonroot(self):
        api = self._api("restricted")
        pod = make_pod("root", namespace="secure")
        with pytest.raises(Invalid, match="runAsNonRoot"):
            api.create("pods", pod)
        ok = make_pod("nonroot", namespace="secure")
        ok.spec.containers[0].security_context = {
            "runAsNonRoot": True, "allowPrivilegeEscalation": False}
        api.create("pods", ok)

    def test_unlabeled_namespace_unrestricted(self):
        api = APIServer()
        api._validating.append(pod_security(api))
        api.create("namespaces", v1.Namespace(
            metadata=v1.ObjectMeta(name="open")))
        pod = make_pod("priv", namespace="open")
        pod.spec.containers[0].security_context = {"privileged": True}
        api.create("pods", pod)  # no enforce label -> allowed


class TestDefaultChainWiring:
    def test_default_chain_includes_r3_plugins(self):
        api = APIServer()
        install_default_admission(api)
        # DefaultStorageClass + in-use protection active by default
        api.create("storageclasses", storage.StorageClass(
            metadata=v1.ObjectMeta(
                name="std",
                annotations={
                    "storageclass.kubernetes.io/is-default-class": "true"}),
        ))
        pvc = api.create("persistentvolumeclaims", v1.PersistentVolumeClaim(
            metadata=v1.ObjectMeta(name="d", namespace="default")))
        assert pvc.spec.storage_class_name == "std"
        assert PVC_PROTECTION_FINALIZER in (pvc.metadata.finalizers or [])
