"""Host-overload monitor: hysteresis, lever wiring, decision inertness.

The tentpole contract (ISSUE 11): under sustained host pressure the
scheduler sheds OPTIONAL work in a fixed order (explain harvest ->
shadow sample -> trace -> speculation) with hysteretic LIFO restore,
and none of it can ever change a placement. Pinned here:

  * OverloadMonitor state machine on a fake clock: fixed shed order,
    LIFO restore, dwell thresholds, the dead band (no flapping),
    cooldown between transitions, counters/gauge/history bookkeeping;
  * the real levers on a live scheduler round-trip every knob back to
    its pre-shed value;
  * decision inertness: a monitor-on-but-never-triggered run and a
    monitor-forced-to-full-shed run both produce BIT-IDENTICAL bindings
    to a KTPU_OVERLOAD=0 control over randomized churn.
"""

from __future__ import annotations

import random

import pytest

from kubernetes_tpu.scheduler import metrics
from kubernetes_tpu.scheduler.degradation import OverloadMonitor
from kubernetes_tpu.utils import tracing

from .test_pipeline_parity import (
    _bound_map,
    _cluster,
    _drive,
    _mk_scheduler,
    _pod_stream,
)
from .util import make_pod


def _label_counts(counter):
    out = {}
    for key, val in counter.items():
        slug = key[0] if key else "-"
        out[slug] = out.get(slug, 0) + int(val)
    return out


def _mk_monitor(events, n_levers=4, **kw):
    names = ["a", "b", "c", "d"][:n_levers]
    levers = [
        (
            name,
            (lambda n=name: events.append(("shed", n))),
            (lambda n=name: events.append(("restore", n))),
        )
        for name in names
    ]
    t = [0.0]
    kw.setdefault("high_fifo_age", 1.0)
    kw.setdefault("low_fifo_age", 0.2)
    kw.setdefault("high_queue_depth", 100)
    kw.setdefault("low_queue_depth", 10)
    kw.setdefault("shed_dwell", 2)
    kw.setdefault("restore_dwell", 2)
    kw.setdefault("cooldown", 0.0)
    mon = OverloadMonitor(levers, now=lambda: t[0], **kw)
    return mon, t


def _hot(mon, t, n=1, **kw):
    out = []
    for _ in range(n):
        t[0] += 0.1
        out.append(mon.observe(fifo_age=5.0, **kw))
    return out


def _calm(mon, t, n=1):
    out = []
    for _ in range(n):
        t[0] += 0.1
        out.append(mon.observe(fifo_age=0.0, queue_depth=0))
    return out


def _mid(mon, t, n=1):
    """Between the water marks: neither hot nor calm (the dead band)."""
    out = []
    for _ in range(n):
        t[0] += 0.1
        out.append(mon.observe(fifo_age=0.5, queue_depth=50))
    return out


class TestOverloadMonitor:
    def test_fixed_shed_order_and_lifo_restore(self):
        events = []
        mon, t = _mk_monitor(events)
        _hot(mon, t, 8)
        assert [e for e in events if e[0] == "shed"] == [
            ("shed", "a"), ("shed", "b"), ("shed", "c"), ("shed", "d")]
        assert mon.level() == 4
        assert mon.shed_names() == ["a", "b", "c", "d"]
        assert mon.triggered and mon.cycles == 0
        _calm(mon, t, 8)
        assert events[4:] == [
            ("restore", "d"), ("restore", "c"),
            ("restore", "b"), ("restore", "a")]
        assert mon.level() == 0 and mon.shed_names() == []
        assert mon.cycles == 1

    def test_dwell_blocks_single_tick_shed(self):
        events = []
        mon, t = _mk_monitor(events, shed_dwell=3)
        assert _hot(mon, t, 2) == [None, None]
        assert not events
        assert _hot(mon, t, 1) == ["a"]
        assert mon.level() == 1

    def test_dead_band_resets_both_streaks(self):
        """Hovering between the water marks must never flap: a hot tick
        alternating with a dead-band tick never accumulates dwell."""
        events = []
        mon, t = _mk_monitor(events, shed_dwell=2)
        for _ in range(10):
            _hot(mon, t, 1)
            _mid(mon, t, 1)
        assert not events and mon.level() == 0 and not mon.triggered
        # ... and on the way down too
        _hot(mon, t, 2)
        assert mon.level() == 1
        for _ in range(10):
            _calm(mon, t, 1)
            _mid(mon, t, 1)
        assert mon.level() == 1  # restore_dwell=2 never reached

    def test_calm_resets_hot_streak(self):
        events = []
        mon, t = _mk_monitor(events, shed_dwell=3)
        _hot(mon, t, 2)
        _calm(mon, t, 1)
        _hot(mon, t, 2)
        assert mon.level() == 0
        _hot(mon, t, 1)
        assert mon.level() == 1

    def test_cooldown_spaces_transitions(self):
        events = []
        mon, t = _mk_monitor(events, shed_dwell=1, cooldown=10.0)
        _hot(mon, t, 5)  # 0.1s apart: only the first shed clears cooldown
        assert mon.level() == 1
        t[0] += 20.0
        _hot(mon, t, 1)
        assert mon.level() == 2

    def test_queue_depth_signal_alone_triggers(self):
        events = []
        mon, t = _mk_monitor(events)
        for _ in range(3):
            t[0] += 0.1
            mon.observe(fifo_age=0.0, queue_depth=500)
        assert mon.level() >= 1

    def test_stage_p99_signal_opt_in(self):
        """high_stage_p99=0 disables the latency signal entirely: an
        enormous p99 alone neither heats nor blocks calm."""
        events = []
        mon, t = _mk_monitor(events)
        for _ in range(6):
            t[0] += 0.1
            mon.observe(fifo_age=0.0, queue_depth=0, stage_p99=1e9)
        assert mon.level() == 0 and not mon.triggered
        # opted in: the same ticks shed
        mon2, t2 = _mk_monitor([], high_stage_p99=1.0)
        for _ in range(3):
            t2[0] += 0.1
            mon2.observe(fifo_age=0.0, queue_depth=0, stage_p99=1e9)
        assert mon2.level() >= 1

    def test_counters_gauge_and_history(self):
        sheds0 = _label_counts(metrics.overload_sheds)
        restores0 = _label_counts(metrics.overload_restores)
        events = []
        mon, t = _mk_monitor(events, n_levers=2)
        _hot(mon, t, 4)
        assert metrics.overload_level.value() == 2
        _calm(mon, t, 4)
        assert metrics.overload_level.value() == 0
        sheds = _label_counts(metrics.overload_sheds)
        restores = _label_counts(metrics.overload_restores)
        for name in ("a", "b"):
            assert sheds.get(name, 0) - sheds0.get(name, 0) == 1
            assert restores.get(name, 0) - restores0.get(name, 0) == 1
        kinds = [(action, what) for _, action, what, _ in mon.history]
        assert kinds == [("shed", "a"), ("shed", "b"),
                         ("restore", "b"), ("restore", "a")]
        # each entry carries the triggering signals
        assert all(set(sig) >= {"fifo_age", "queue_depth"}
                   for _, _, _, sig in mon.history)

    def test_history_stays_bounded(self):
        events = []
        mon, t = _mk_monitor(events, n_levers=1, restore_dwell=1,
                             shed_dwell=1)
        for _ in range(200):
            _hot(mon, t, 1)
            _calm(mon, t, 1)
        assert len(mon.history) <= 128
        assert mon.cycles > 50

    def test_callbacks_fire_per_transition(self):
        calls = []
        mon, t = _mk_monitor(
            [], n_levers=1,
            on_shed=lambda what, sig: calls.append(("shed", what)),
            on_restore=lambda what, sig: calls.append(("restore", what)),
        )
        _hot(mon, t, 3)
        _calm(mon, t, 3)
        assert calls == [("shed", "a"), ("restore", "a")]

    def test_calm_at_level_zero_is_a_noop(self):
        events = []
        mon, t = _mk_monitor(events)
        assert _calm(mon, t, 10) == [None] * 10
        assert not events and mon.cycles == 0


# ---------------------------------------------------------------------------
# the real levers on a live scheduler


def test_levers_round_trip_every_knob(monkeypatch):
    """Shed all five levers in order, restore LIFO: every knob returns
    to its pre-shed value, and no lever tears the session down."""
    from kubernetes_tpu.utils import devtime

    _, cs = _cluster()
    sched = _mk_scheduler(cs, 2)
    tpu = sched.tpu
    trace0 = tracing.level()
    devtime0 = devtime.level()
    try:
        tracing.set_level(2)
        devtime.set_level(1)
        tpu.shadow_sample = 0.25
        assert sched.overload is not None
        levers = sched.overload.levers
        assert [name for name, _, _ in levers] == [
            "explain-harvest", "shadow-sample", "devtime", "trace",
            "speculation"]
        # warm a session so "no teardown" is observable
        pods = [
            make_pod(f"p-{i}", namespace="default", cpu="100m",
                     labels={"app": "plain"})
            for i in range(6)
        ]
        _drive(sched, cs, pods, [3, 3])
        sess = tpu._session
        assert sess is not None
        for _, shed, _ in levers:
            shed()
        assert tpu.explain_harvest is False
        assert tpu.shadow_sample == 0.0
        assert devtime.level() == 0
        assert tracing.level() == 0
        assert tpu.speculation is False
        assert tpu._session is sess, "a shed lever tore the session down"
        for _, _, restore in reversed(levers):
            restore()
        assert tpu.explain_harvest is True
        assert tpu.shadow_sample == 0.25
        assert devtime.level() == 1
        assert tracing.level() == 2
        assert tpu.speculation is True
        assert tpu._session is sess
    finally:
        tracing.set_level(trace0)
        devtime.set_level(devtime0)
        sched.stop()
        sched.informers.stop()


def test_overload_kill_switch(monkeypatch):
    monkeypatch.setenv("KTPU_OVERLOAD", "0")
    _, cs = _cluster()
    sched = _mk_scheduler(cs, 2)
    try:
        assert sched.overload is None
    finally:
        sched.stop()
        sched.informers.stop()


def test_env_water_marks_reach_the_monitor(monkeypatch):
    monkeypatch.setenv("KTPU_OVERLOAD_FIFO_AGE", "2.5")
    monkeypatch.setenv("KTPU_OVERLOAD_QUEUE_DEPTH", "77")
    monkeypatch.setenv("KTPU_OVERLOAD_SHED_DWELL", "5")
    monkeypatch.setenv("KTPU_OVERLOAD_COOLDOWN", "0.25")
    _, cs = _cluster()
    sched = _mk_scheduler(cs, 0)
    try:
        ov = sched.overload
        assert ov is not None
        assert ov.high_fifo_age == 2.5
        assert ov.low_fifo_age == 0.5  # 0.2x the high mark
        assert ov.high_queue_depth == 77
        assert ov.shed_dwell == 5
        assert ov.cooldown == 0.25
    finally:
        sched.stop()
        sched.informers.stop()


# ---------------------------------------------------------------------------
# decision inertness (THE acceptance pin)


@pytest.mark.parametrize("seed", [0, 2])
def test_monitor_on_but_idle_is_bit_identical(seed, monkeypatch):
    """The monitor observing every completed batch but never shedding
    must be invisible: identical pod->node maps to KTPU_OVERLOAD=0."""
    rng = random.Random(seed)
    n = rng.randint(24, 40)
    batch_sizes = [rng.choice([1, 2, 3, 5, 8]) for _ in range(64)]
    maps = {}
    for mode in ("off", "on"):
        if mode == "off":
            monkeypatch.setenv("KTPU_OVERLOAD", "0")
        else:
            monkeypatch.delenv("KTPU_OVERLOAD", raising=False)
            # water marks pinned unreachable: the monitor RUNS on every
            # completion but provably never transitions
            monkeypatch.setenv("KTPU_OVERLOAD_FIFO_AGE", "1e9")
            monkeypatch.setenv("KTPU_OVERLOAD_QUEUE_DEPTH", "1000000000")
        _, cs = _cluster()
        sched = _mk_scheduler(cs, 2)
        try:
            pods = _pod_stream(random.Random(seed), n)
            _drive(sched, cs, pods, batch_sizes)
            if mode == "on":
                assert sched.overload is not None
                assert not sched.overload.triggered
            else:
                assert sched.overload is None
            maps[mode] = _bound_map(cs)
        finally:
            sched.stop()
            sched.informers.stop()
    assert maps["on"] == maps["off"], (
        "an idle overload monitor changed scheduling decisions"
    )
    assert any(maps["off"].values())


def test_full_shed_run_is_bit_identical(monkeypatch):
    """Every lever forced shed mid-run (water marks below zero: every
    tick is hot) — placements must STILL match the monitor-off control.
    This is the 'sheds only optional work' contract end to end."""
    seed = 5
    rng = random.Random(seed)
    batch_sizes = [rng.choice([2, 3, 5]) for _ in range(32)]
    maps = {}
    trace0 = tracing.level()
    try:
        for mode in ("off", "shed"):
            if mode == "off":
                monkeypatch.setenv("KTPU_OVERLOAD", "0")
            else:
                monkeypatch.delenv("KTPU_OVERLOAD", raising=False)
            _, cs = _cluster()
            sched = _mk_scheduler(cs, 2)
            try:
                if mode == "shed":
                    ov = sched.overload
                    assert ov is not None
                    # every observe tick is hot; dwell 1, no cooldown:
                    # all five levers shed within the first batches
                    ov.high_fifo_age = -1.0
                    ov.shed_dwell = 1
                    ov.cooldown = 0.0
                pods = _pod_stream(random.Random(seed), 32)
                _drive(sched, cs, pods, batch_sizes)
                if mode == "shed":
                    assert sched.overload.triggered
                    assert sched.overload.level() == 5, (
                        "forced-hot run did not shed every lever"
                    )
                maps[mode] = _bound_map(cs)
            finally:
                sched.stop()
                sched.informers.stop()
    finally:
        tracing.set_level(trace0)
    assert maps["shed"] == maps["off"], (
        "shedding changed scheduling decisions — a lever is not inert"
    )
    assert any(maps["off"].values())
