"""Batched scan kernel vs sequential single-pod kernel: identical decisions.

The batch path must see exactly the sequential assume semantics — pod i
scored against the state including pods 0..i-1 (reference assume protocol:
pkg/scheduler/internal/cache/cache.go:361) — so spread/affinity/resource
pressure from earlier decisions shifts later ones identically.
"""

from __future__ import annotations

import numpy as np
import pytest

from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.batch import pod_batchable, schedule_batch
from kubernetes_tpu.ops.kernel import schedule_pod_jit
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods


def sequential_decisions(nodes, init_pods, pending):
    enc = ClusterEncoding()
    enc.set_cluster(nodes, init_pods)
    pe = PodEncoder(enc)
    decisions = []
    for pod in pending:
        p = {k: v for k, v in pe.encode(pod).items() if not k.startswith("_")}
        c = enc.device_state()
        out = schedule_pod_jit(c, p)
        total = np.asarray(out["total"])
        best = int(total.argmax())
        if total[best] < 0:
            decisions.append(-1)
            continue
        decisions.append(best)
        enc.add_pod(pod, enc.node_names[best])
    return decisions


def batch_decisions(nodes, init_pods, pending):
    enc = ClusterEncoding()
    enc.set_cluster(nodes, init_pods)
    pe = PodEncoder(enc)
    for pod in pending:  # intern pass: grow vocabs before the rebuild
        pe.encode(pod)
    c = enc.device_state()
    arrays = [
        {k: v for k, v in pe.encode(pod).items() if not k.startswith("_")}
        for pod in pending
    ]
    assert all(pod_batchable(pa) for pa in arrays)
    slots = [enc._pod_free[-1 - i] for i in range(len(pending))]
    decisions, _ = schedule_batch(c, arrays, slots)
    return decisions, enc


def test_batch_matches_sequential_spread():
    nodes, init_pods = synth_cluster(12, pods_per_node=1)
    pending = synth_pending_pods(17, spread=True)
    seq = sequential_decisions(nodes, init_pods, pending)
    got, _ = batch_decisions(nodes, init_pods, pending)
    assert got == seq


def test_batch_matches_sequential_plain():
    nodes, init_pods = synth_cluster(9, pods_per_node=2)
    pending = synth_pending_pods(13, cpu="500m", memory="2Gi")
    seq = sequential_decisions(nodes, init_pods, pending)
    got, _ = batch_decisions(nodes, init_pods, pending)
    assert got == seq


def test_batch_exhausts_capacity():
    """Pods overflow tiny cluster capacity; overflow pods must get -1 in
    BOTH paths at the same positions (resource pressure is sequential)."""
    nodes, _ = synth_cluster(2)
    # node alloc is 4 CPU; 3 pods of 1500m fit two per... 2 nodes * 2 = 4+1 overflow
    pending = synth_pending_pods(6, cpu="1500m", memory="1Gi")
    seq = sequential_decisions(nodes, [], pending)
    got, _ = batch_decisions(nodes, [], pending)
    assert got == seq
    assert -1 in got


def test_unbatchable_detection():
    from kubernetes_tpu.api import types as v1
    from kubernetes_tpu.testing.synth import make_pod

    nodes, _ = synth_cluster(4)
    enc = ClusterEncoding()
    enc.set_cluster(nodes, [])
    pe = PodEncoder(enc)
    aff = v1.Affinity(
        pod_anti_affinity=v1.PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[
                v1.PodAffinityTerm(
                    label_selector=v1.LabelSelector(match_labels={"app": "x"}),
                    topology_key=v1.LABEL_HOSTNAME,
                )
            ]
        )
    )
    pod = make_pod("p", labels={"app": "x"}, affinity=aff)
    assert not pod_batchable(pe.encode(pod))
    plain = make_pod("q", cpu="100m")
    assert pod_batchable(pe.encode(plain))


class TestSessionSurvivesDirtySync:
    def test_two_cycles_with_dirty_rows(self):
        """Two schedule_many calls with host-side add_pod dirt between:
        the live session's device statics must NOT be invalidated by a
        fused-row-scatter donation (the scatter donates the old device
        arrays; the session holds references to them). Regression for
        the donated-buffer crash behind flaky preemption e2e runs."""
        from kubernetes_tpu.scheduler.tpu_backend import TPUBackend
        from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

        import copy

        def presized_backend():
            nodes, init_pods = synth_cluster(6, pods_per_node=1)
            pending = synth_pending_pods(6, spread=True)
            be = TPUBackend()
            phantoms = []
            for i, p in enumerate(pending):
                q = copy.deepcopy(p)
                q.metadata.name = f"ph-{i}"
                q.spec.node_name = nodes[i % len(nodes)].metadata.name
                phantoms.append(q)
            be.enc.set_cluster(nodes, init_pods + phantoms)
            for p in pending:  # pre-intern vocab so shapes stay stable
                be.pe.encode(p)
            be.enc.device_state()
            for q in phantoms:
                be.enc.remove_pod(q)
            return be, pending

        be, pending = presized_backend()
        out1 = be.schedule_many(pending[:2])   # session built; add_pod dirties
        assert all(n for _, n in out1)
        sess = be._session
        assert sess is not None
        # same templates as batch 1 (synth stamps 4 templates round-robin)
        out2 = be.schedule_many(pending[4:6])  # previously: donated-buffer crash
        assert all(n for _, n in out2)
        assert be._session is sess, "session must survive the second cycle"
        # decisions still match a fresh backend scheduling the same stream
        be2, pending2 = presized_backend()
        ref = be2.schedule_many(pending2[:2] + pending2[4:6])
        assert [n for _, n in out1 + out2] == [n for _, n in ref]
