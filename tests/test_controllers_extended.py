"""CronJob, TTL-after-finished, Disruption, HPA, ResourceQuota controllers
and in-tree admission plugins.

Reference shape: pkg/controller/{cronjob,ttlafterfinished,disruption,
podautoscaler,resourcequota} unit tests + plugin/pkg/admission tests.
"""

import time

import pytest

from kubernetes_tpu.api import apps, batch
from kubernetes_tpu.api import types as v1
from kubernetes_tpu.api.storage import PriorityClass
from kubernetes_tpu.apiserver.admission import install_default_admission
from kubernetes_tpu.apiserver.server import APIServer, Invalid
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.cronjob import CronJobController, CronSchedule
from kubernetes_tpu.controllers.disruption import DisruptionController
from kubernetes_tpu.controllers.podautoscaler import HorizontalController
from kubernetes_tpu.controllers.resourcequota import ResourceQuotaController
from kubernetes_tpu.controllers.ttlafterfinished import TTLAfterFinishedController

from .util import make_pod, wait_until


@pytest.fixture()
def cluster():
    api = APIServer()
    cs = Clientset(api)
    factory = SharedInformerFactory(cs)
    return api, cs, factory


class TestCronSchedule:
    def test_every_minute(self):
        s = CronSchedule("* * * * *")
        assert s.matches(time.mktime((2026, 7, 30, 10, 5, 0, 3, 0, 0)))

    def test_fields(self):
        s = CronSchedule("*/15 3 * * *")
        # 03:00, 03:15, ... UTC
        t = 3 * 3600 + 15 * 60  # 1970-01-01T03:15Z
        assert s.matches(t)
        assert not s.matches(t + 60)
        assert not s.matches(t + 3600)

    def test_unmet_times(self):
        s = CronSchedule("* * * * *")
        times = s.unmet_times(0, 600)
        assert times == [float(60 * i) for i in range(1, 11)]

    def test_invalid(self):
        with pytest.raises(ValueError):
            CronSchedule("* * *")
        with pytest.raises(ValueError):
            CronSchedule("99 * * * *")

    def test_latest_unmet_huge_backlog_is_fast(self):
        s = CronSchedule("*/5 * * * *")
        year = 365 * 86400.0
        t0 = time.perf_counter()
        latest = s.latest_unmet(0.0, year + 123.0)
        assert time.perf_counter() - t0 < 0.05  # backlog-size independent
        assert latest == year  # most recent 5-minute mark, not minute 5
        # unsatisfiable schedule (Feb 31): no match, still fast
        dead = CronSchedule("0 0 31 2 *")
        t0 = time.perf_counter()
        assert dead.latest_unmet(0.0, year) is None
        assert time.perf_counter() - t0 < 0.1
        assert dead.next_after(0.0) is None


def _cronjob(name="cj", schedule="* * * * *", **spec_kw):
    return batch.CronJob(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        spec=batch.CronJobSpec(
            schedule=schedule,
            job_template_spec=batch.JobSpec(
                template=v1.PodTemplateSpec(
                    metadata=v1.ObjectMeta(labels={"cron": name}),
                    spec=v1.PodSpec(
                        containers=[v1.Container(name="c", image="i")],
                        restart_policy="Never",
                    ),
                )
            ),
            **spec_kw,
        ),
    )


class TestCronJobController:
    def test_creates_job_at_schedule(self, cluster):
        api, cs, factory = cluster
        ctrl = CronJobController(cs, factory)
        cj = _cronjob()
        cj.metadata.creation_timestamp = 1.0
        cs.cronjobs.create(cj)
        ctrl.sync_all(now=61.0)
        jobs, _ = cs.jobs.list()
        assert len(jobs) == 1
        assert jobs[0].metadata.name == "cj-1"
        assert jobs[0].metadata.owner_references[0].kind == "CronJob"
        got = cs.cronjobs.get("cj", "default")
        assert got.status.last_schedule_time == 60.0
        assert got.status.active == ["cj-1"]
        # re-sync at the same time: no duplicate
        ctrl.sync_all(now=61.0)
        assert len(cs.jobs.list()[0]) == 1

    def test_suspend(self, cluster):
        api, cs, factory = cluster
        ctrl = CronJobController(cs, factory)
        cj = _cronjob(suspend=True)
        cj.metadata.creation_timestamp = 1.0
        cs.cronjobs.create(cj)
        ctrl.sync_all(now=61.0)
        assert cs.jobs.list()[0] == []

    def test_forbid_concurrency(self, cluster):
        api, cs, factory = cluster
        ctrl = CronJobController(cs, factory)
        cj = _cronjob(concurrency_policy="Forbid")
        cj.metadata.creation_timestamp = 1.0
        cs.cronjobs.create(cj)
        ctrl.sync_all(now=61.0)
        assert len(cs.jobs.list()[0]) == 1
        # first job still active -> second tick must not create another
        ctrl.sync_all(now=121.0)
        assert len(cs.jobs.list()[0]) == 1

    def test_replace_concurrency(self, cluster):
        api, cs, factory = cluster
        ctrl = CronJobController(cs, factory)
        cj = _cronjob(concurrency_policy="Replace")
        cj.metadata.creation_timestamp = 1.0
        cs.cronjobs.create(cj)
        ctrl.sync_all(now=61.0)
        ctrl.sync_all(now=121.0)
        jobs, _ = cs.jobs.list()
        assert [j.metadata.name for j in jobs] == ["cj-2"]

    def test_history_limits(self, cluster):
        api, cs, factory = cluster
        ctrl = CronJobController(cs, factory)
        cj = _cronjob(successful_jobs_history_limit=1)
        cj.metadata.creation_timestamp = 1.0
        cs.cronjobs.create(cj)
        for minute in (1, 2, 3):
            ctrl.sync_all(now=60.0 * minute + 1)
            jobs, _ = cs.jobs.list()
            newest = max(jobs, key=lambda j: j.metadata.name)
            newest.status.conditions = [
                batch.JobCondition(type="Complete", status="True")
            ]
            newest.status.completion_time = 60.0 * minute + 30
            cs.jobs.update_status(newest)
        ctrl.sync_all(now=241.0)
        names = {j.metadata.name for j in cs.jobs.list()[0]}
        # only the newest finished job plus the one created at t=241
        assert names == {"cj-3", "cj-4"}


class TestTTLAfterFinished:
    def test_deletes_after_ttl(self, cluster):
        api, cs, factory = cluster
        ctrl = TTLAfterFinishedController(cs, factory)
        job = batch.Job(
            metadata=v1.ObjectMeta(name="j", namespace="default"),
            spec=batch.JobSpec(
                ttl_seconds_after_finished=100,
                template=v1.PodTemplateSpec(
                    spec=v1.PodSpec(containers=[v1.Container(name="c", image="i")])
                ),
            ),
        )
        cs.jobs.create(job)
        ctrl.sync_all(now=1000.0)  # not finished: kept
        assert len(cs.jobs.list()[0]) == 1
        live = cs.jobs.get("j", "default")
        live.status.conditions = [batch.JobCondition(type="Complete", status="True")]
        live.status.completion_time = 1000.0
        cs.jobs.update_status(live)
        ctrl.sync_all(now=1099.0)
        assert len(cs.jobs.list()[0]) == 1  # TTL not yet expired
        ctrl.sync_all(now=1101.0)
        assert cs.jobs.list()[0] == []


class TestDisruptionController:
    def test_status_from_min_available(self, cluster):
        api, cs, factory = cluster
        ctrl = DisruptionController(cs, factory)
        factory.start()
        assert factory.wait_for_cache_sync()
        ctrl.run()
        try:
            cs.resource("poddisruptionbudgets").create(
                v1.PodDisruptionBudget(
                    metadata=v1.ObjectMeta(name="pdb", namespace="default"),
                    spec=v1.PodDisruptionBudgetSpec(
                        min_available="2",
                        selector=v1.LabelSelector(match_labels={"app": "db"}),
                    ),
                )
            )
            for i in range(3):
                pod = make_pod(f"db-{i}", labels={"app": "db"}, node_name="n1")
                pod.status.phase = "Running"
                pod.status.conditions = [v1.PodCondition(type="Ready", status="True")]
                cs.pods.create(pod)

            def ok():
                pdb = cs.resource("poddisruptionbudgets").get("pdb", "default")
                return (
                    pdb.status.current_healthy == 3
                    and pdb.status.desired_healthy == 2
                    and pdb.status.disruptions_allowed == 1
                    and pdb.status.expected_pods == 3
                )

            assert wait_until(ok)
        finally:
            ctrl.stop()
            factory.stop()

    def test_percentage_max_unavailable(self, cluster):
        api, cs, factory = cluster
        ctrl = DisruptionController(cs, factory)
        factory.start()
        assert factory.wait_for_cache_sync()
        ctrl.run()
        try:
            rs = apps.ReplicaSet(
                metadata=v1.ObjectMeta(name="rs", namespace="default"),
                spec=apps.ReplicaSetSpec(
                    replicas=4,
                    selector=v1.LabelSelector(match_labels={"app": "web"}),
                ),
            )
            created_rs = cs.replicasets.create(rs)
            cs.resource("poddisruptionbudgets").create(
                v1.PodDisruptionBudget(
                    metadata=v1.ObjectMeta(name="pdb", namespace="default"),
                    spec=v1.PodDisruptionBudgetSpec(
                        max_unavailable="50%",
                        selector=v1.LabelSelector(match_labels={"app": "web"}),
                    ),
                )
            )
            for i in range(4):
                pod = make_pod(f"web-{i}", labels={"app": "web"}, node_name="n1")
                pod.metadata.owner_references = [
                    v1.OwnerReference(
                        kind="ReplicaSet",
                        name="rs",
                        uid=created_rs.metadata.uid,
                        controller=True,
                    )
                ]
                pod.status.phase = "Running"
                pod.status.conditions = [v1.PodCondition(type="Ready", status="True")]
                cs.pods.create(pod)

            def ok():
                pdb = cs.resource("poddisruptionbudgets").get("pdb", "default")
                # expected 4, maxUnavailable 50% -> desired 2, allowed 2
                return (
                    pdb.status.expected_pods == 4
                    and pdb.status.desired_healthy == 2
                    and pdb.status.disruptions_allowed == 2
                )

            assert wait_until(ok)
        finally:
            ctrl.stop()
            factory.stop()


def _deployment(name="web", replicas=2):
    return apps.Deployment(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        spec=apps.DeploymentSpec(
            replicas=replicas,
            selector=v1.LabelSelector(match_labels={"app": name}),
            template=v1.PodTemplateSpec(
                metadata=v1.ObjectMeta(labels={"app": name}),
                spec=v1.PodSpec(containers=[v1.Container(name="c", image="i")]),
            ),
        ),
    )


class TestHorizontalController:
    def _pods(self, cs, n, util):
        for i in range(n):
            pod = make_pod(f"web-{i}", labels={"app": "web"}, node_name="n1")
            pod.status.phase = "Running"
            cs.pods.create(pod)
        return lambda pod: util

    def test_scales_up_and_clamps(self, cluster):
        api, cs, factory = cluster
        cs.deployments.create(_deployment(replicas=2))
        metrics = self._pods(cs, 2, 200)  # 200% of target 80 -> ratio 2.5
        ctrl = HorizontalController(cs, factory, metrics=metrics)
        from kubernetes_tpu.api.autoscaling import (
            CrossVersionObjectReference,
            HorizontalPodAutoscaler,
            HorizontalPodAutoscalerSpec,
        )

        cs.resource("horizontalpodautoscalers").create(
            HorizontalPodAutoscaler(
                metadata=v1.ObjectMeta(name="hpa", namespace="default"),
                spec=HorizontalPodAutoscalerSpec(
                    scale_target_ref=CrossVersionObjectReference(
                        kind="Deployment", name="web"
                    ),
                    min_replicas=1,
                    max_replicas=4,
                    target_cpu_utilization_percentage=80,
                ),
            )
        )
        ctrl.sync_all()
        dep = cs.deployments.get("web", "default")
        assert dep.spec.replicas == 4  # ceil(2*2.5)=5 clamped to max 4
        hpa = cs.resource("horizontalpodautoscalers").get("hpa", "default")
        assert hpa.status.desired_replicas == 4
        assert hpa.status.current_cpu_utilization_percentage == 200

    def test_tolerance_band_holds(self, cluster):
        api, cs, factory = cluster
        cs.deployments.create(_deployment(replicas=2))
        metrics = self._pods(cs, 2, 85)  # ratio 1.0625 < 1.1 tolerance
        ctrl = HorizontalController(cs, factory, metrics=metrics)
        from kubernetes_tpu.api.autoscaling import (
            CrossVersionObjectReference,
            HorizontalPodAutoscaler,
            HorizontalPodAutoscalerSpec,
        )

        cs.resource("horizontalpodautoscalers").create(
            HorizontalPodAutoscaler(
                metadata=v1.ObjectMeta(name="hpa", namespace="default"),
                spec=HorizontalPodAutoscalerSpec(
                    scale_target_ref=CrossVersionObjectReference(
                        kind="Deployment", name="web"
                    ),
                    max_replicas=10,
                    target_cpu_utilization_percentage=80,
                ),
            )
        )
        ctrl.sync_all()
        assert cs.deployments.get("web", "default").spec.replicas == 2


class TestAdmission:
    def test_priority_resolution(self):
        api = install_default_admission(APIServer())
        cs = Clientset(api)
        cs.resource("priorityclasses").create(
            PriorityClass(
                metadata=v1.ObjectMeta(name="high"), value=1000
            )
        )
        pod = make_pod("p")
        pod.spec.priority_class_name = "high"
        created = cs.pods.create(pod)
        assert created.spec.priority == 1000
        bad = make_pod("q")
        bad.spec.priority_class_name = "nope"
        with pytest.raises(Invalid):
            cs.pods.create(bad)

    def test_default_toleration_seconds(self):
        api = install_default_admission(APIServer())
        cs = Clientset(api)
        created = cs.pods.create(make_pod("p"))
        tols = {
            t.key: t.toleration_seconds for t in created.spec.tolerations or []
        }
        assert tols.get(v1.TAINT_NODE_NOT_READY) == 300
        assert tols.get(v1.TAINT_NODE_UNREACHABLE) == 300

    def test_limit_ranger_defaults_and_max(self):
        api = install_default_admission(APIServer())
        cs = Clientset(api)
        cs.resource("limitranges").create(
            v1.LimitRange(
                metadata=v1.ObjectMeta(name="lr", namespace="default"),
                spec=v1.LimitRangeSpec(
                    limits=[
                        v1.LimitRangeItem(
                            type="Container",
                            default_request={"cpu": "100m"},
                            max={"cpu": "1"},
                        )
                    ]
                ),
            )
        )
        created = cs.pods.create(make_pod("p"))
        assert created.spec.containers[0].resources.requests["cpu"] == "100m"
        big = make_pod("q", cpu="2")
        with pytest.raises(Invalid):
            cs.pods.create(big)

    def test_namespace_lifecycle(self):
        api = install_default_admission(APIServer())
        cs = Clientset(api)
        with pytest.raises(Invalid):
            cs.pods.create(make_pod("p", namespace="nope"))
        cs.namespaces.create(v1.Namespace(metadata=v1.ObjectMeta(name="ok")))
        cs.pods.create(make_pod("p", namespace="ok"))

    def test_resource_quota_enforced_and_status(self):
        api = install_default_admission(APIServer())
        cs = Clientset(api)
        factory = SharedInformerFactory(cs)
        cs.resource("resourcequotas").create(
            v1.ResourceQuota(
                metadata=v1.ObjectMeta(name="rq", namespace="default"),
                spec=v1.ResourceQuotaSpec(hard={"cpu": "1", "pods": "2"}),
            )
        )
        cs.pods.create(make_pod("a", cpu="600m"))
        with pytest.raises(Invalid):
            cs.pods.create(make_pod("b", cpu="600m"))  # cpu would exceed 1
        cs.pods.create(make_pod("c", cpu="100m"))
        with pytest.raises(Invalid):
            cs.pods.create(make_pod("d"))  # pod count would exceed 2
        ctrl = ResourceQuotaController(cs, factory)
        ctrl.sync_once()
        rq = cs.resource("resourcequotas").get("rq", "default")
        assert rq.status.used["cpu"] == "700m"
        assert rq.status.used["pods"] == "2"
