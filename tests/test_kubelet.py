"""Kubelet node agent tests: PLEG, syncPod state machine, restart policy,
status/heartbeat managers, eviction, hollow-cluster scale.

Reference: pkg/kubelet (kubelet.go syncLoop, pleg/generic.go,
kuberuntime_manager.go SyncPod, kubelet_node_status.go, eviction/) and
pkg/kubemark.
"""

import time

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.kubelet.cri import CONTAINER_RUNNING, FakeRuntimeService
from kubernetes_tpu.kubelet.kubelet import LEASE_NAMESPACE, Kubelet, KubeletConfig
from kubernetes_tpu.kubelet.pleg import (
    CONTAINER_DIED,
    CONTAINER_REMOVED,
    CONTAINER_STARTED,
    PLEG,
)
from kubernetes_tpu.kubemark import HollowCluster

from .util import FAST_KUBELET as FAST, make_pod, wait_until as _wait



class TestPLEG:
    def test_start_die_remove_events(self):
        rt = FakeRuntimeService()
        pleg = PLEG(rt)
        assert pleg.relist() == []
        sid = rt.run_pod_sandbox("p", "default", "uid-1")
        cid = rt.create_container(sid, "c0", "img")
        rt.start_container(cid)
        events = pleg.relist()
        assert [e.type for e in events] == [CONTAINER_STARTED]
        assert events[0].pod_uid == "uid-1"
        rt.stop_container(cid, exit_code=1)
        assert [e.type for e in pleg.relist()] == [CONTAINER_DIED]
        rt.remove_container(cid)
        assert [e.type for e in pleg.relist()] == [CONTAINER_REMOVED]
        assert pleg.relist() == []


def _cluster_with_kubelet(node_name="node-0", runtime=None, stats=None):
    api = APIServer()
    cs = Clientset(api)
    factory = SharedInformerFactory(cs)
    kl = Kubelet(
        cs,
        factory,
        config=KubeletConfig(node_name=node_name, **FAST),
        runtime=runtime or FakeRuntimeService(),
        stats_provider=stats,
    )
    factory.start()
    assert factory.wait_for_cache_sync()
    kl.run()
    return api, cs, factory, kl


class TestKubeletSyncPod:
    def test_pod_runs_to_running(self):
        api, cs, factory, kl = _cluster_with_kubelet()
        try:
            cs.pods.create(make_pod("web-0", node_name="node-0", cpu="100m"))

            def running():
                p = cs.pods.get("web-0", "default")
                return p.status.phase == "Running"

            assert _wait(running)
            p = cs.pods.get("web-0", "default")
            assert p.status.pod_ip
            assert p.status.host_ip == "node-0"
            assert p.status.container_statuses[0].state == "running"
            assert any(
                c.type == "Ready" and c.status == "True"
                for c in p.status.conditions
            )
        finally:
            kl.stop()
            factory.stop()

    def test_crashed_container_restarts(self):
        rt = FakeRuntimeService()
        api, cs, factory, kl = _cluster_with_kubelet(runtime=rt)
        try:
            cs.pods.create(make_pod("crashy", node_name="node-0"))
            assert _wait(
                lambda: cs.pods.get("crashy", "default").status.phase == "Running"
            )
            uid = cs.pods.get("crashy", "default").metadata.uid
            assert rt.kill_container(uid, "c0", exit_code=1)
            # restartPolicy Always: kubelet restarts with restart_count+1
            assert _wait(
                lambda: any(
                    (s.restart_count or 0) >= 1 and s.state == "running"
                    for s in (
                        cs.pods.get("crashy", "default").status.container_statuses
                        or []
                    )
                )
            )
        finally:
            kl.stop()
            factory.stop()

    def test_restart_policy_never_failed(self):
        rt = FakeRuntimeService()
        rt.fail_starts["c0"] = 2  # container exits immediately with code 2
        api, cs, factory, kl = _cluster_with_kubelet(runtime=rt)
        try:
            pod = make_pod("oneshot", node_name="node-0")
            pod.spec.restart_policy = "Never"
            cs.pods.create(pod)
            assert _wait(
                lambda: cs.pods.get("oneshot", "default").status.phase == "Failed"
            )
            st = cs.pods.get("oneshot", "default").status.container_statuses[0]
            assert st.state == "terminated" and st.exit_code == 2
        finally:
            kl.stop()
            factory.stop()

    def test_deleted_pod_cleans_runtime(self):
        rt = FakeRuntimeService()
        api, cs, factory, kl = _cluster_with_kubelet(runtime=rt)
        try:
            cs.pods.create(make_pod("gone", node_name="node-0"))
            assert _wait(lambda: len(rt.list_containers()) == 1)
            cs.pods.delete("gone", "default")
            assert _wait(lambda: not rt.list_pod_sandboxes())
            assert not rt.list_containers()
        finally:
            kl.stop()
            factory.stop()


class TestHeartbeats:
    def test_node_registered_with_lease_and_ready(self):
        api, cs, factory, kl = _cluster_with_kubelet()
        try:
            node = cs.nodes.get("node-0")
            assert node.status.capacity["pods"] == "110"
            ready = [c for c in node.status.conditions if c.type == "Ready"]
            assert ready and ready[0].status == "True"

            def lease_fresh():
                try:
                    lease = cs.resource("leases").get("node-0", LEASE_NAMESPACE)
                except Exception:
                    return False
                return (
                    lease.spec.renew_time is not None
                    and time.time() - lease.spec.renew_time < 5
                )

            assert _wait(lease_fresh)
            # renewal advances
            t1 = cs.resource("leases").get("node-0", LEASE_NAMESPACE).spec.renew_time
            assert _wait(
                lambda: cs.resource("leases")
                .get("node-0", LEASE_NAMESPACE)
                .spec.renew_time
                > t1
            )
        finally:
            kl.stop()
            factory.stop()


class TestEviction:
    def test_memory_pressure_evicts_lowest_priority(self):
        # Deterministic pressure: report pressure exactly while the intended
        # victim still exists server-side. The pressured status tick reads
        # stats first, then evicts the lowest-priority pod ("low"); the next
        # tick sees "low" gone and reports no pressure — so exactly one pod
        # is ever evicted regardless of scheduling delays (under sustained
        # pressure the eviction manager takes one victim per interval, which
        # would race the survival assertion below).
        armed = [False]
        holder = {}

        def stats():
            if not armed[0]:
                return 0.0
            try:
                holder["cs"].pods.get("low", "default")
                return 0.99
            except Exception:
                return 0.0

        api, cs, factory, kl = _cluster_with_kubelet(stats=stats)
        holder["cs"] = cs
        try:
            low = make_pod("low", node_name="node-0", priority=1)
            high = make_pod("high", node_name="node-0", priority=100)
            cs.pods.create(low)
            cs.pods.create(high)
            assert _wait(
                lambda: all(
                    cs.pods.get(n, "default").status.phase == "Running"
                    for n in ("low", "high")
                )
            )
            # watch node updates from here: the MemoryPressure=True condition
            # is only reported during the pressured tick, so assert it from
            # the event stream rather than racing the subsequent clear
            _, rev = cs.nodes.list()
            watch = cs.nodes.watch(since_revision=rev)
            armed[0] = True

            def evicted():
                try:
                    cs.pods.get("low", "default")
                    return False
                except Exception:
                    return True

            assert _wait(evicted)
            # the high-priority pod survives (no further pressured ticks)
            _wait(lambda: False, timeout=0.8)  # one full status period
            assert cs.pods.get("high", "default").status.phase == "Running"
            # the node reported MemoryPressure during the pressured tick
            saw_pressure = False
            while True:
                ev = watch.poll(timeout=1.0)
                if ev is None:
                    break
                for c in ev.object.status.conditions or []:
                    if c.type == "MemoryPressure" and c.status == "True":
                        saw_pressure = True
                if saw_pressure:
                    break
            watch.stop()
            assert saw_pressure
        finally:
            kl.stop()
            factory.stop()


class TestHollowCluster:
    def test_scale_pods_run_everywhere(self):
        api = APIServer()
        cs = Clientset(api)
        hollow = HollowCluster(cs, n_nodes=10, config_overrides=FAST)
        hollow.start()
        try:
            assert _wait(lambda: len(cs.nodes.list()[0]) == 10)
            # bind 3 pods per node directly (scheduler integration is
            # covered end-to-end in test_cluster_e2e)
            for i in range(30):
                cs.pods.create(make_pod(f"w-{i}", node_name=f"hollow-{i % 10}"))

            def all_running():
                pods, _ = cs.pods.list(namespace="default")
                return len(pods) == 30 and all(
                    p.status.phase == "Running" for p in pods
                )

            assert _wait(all_running, timeout=30)
            # every runtime actually holds its pods' containers
            total = sum(
                len(rt.list_containers()) for rt in hollow.runtimes.values()
            )
            assert total == 30
        finally:
            hollow.stop()


class TestFakeCRIIPAM:
    def test_pod_ip_reuse_no_collision_under_churn(self):
        # /24 mode: monotonic allocation would wrap at 256 and hand a live
        # pod's IP to a new sandbox; first-fit reuse must not
        rt = FakeRuntimeService(ip_prefix="10.64.0")
        keeper = rt.run_pod_sandbox("keep", "default", "uid-keep")
        keep_ip = next(s.ip for s in rt.list_pod_sandboxes() if s.id == keeper)
        for i in range(300):  # churn well past the 256 range
            sid = rt.run_pod_sandbox(f"p{i}", "default", f"uid-{i}")
            rt.stop_pod_sandbox(sid)
            rt.remove_pod_sandbox(sid)
        fresh = rt.run_pod_sandbox("new", "default", "uid-new")
        ips = [s.ip for s in rt.list_pod_sandboxes()]
        assert len(ips) == len(set(ips)) == 2
        assert keep_ip in ips


class TestInitContainers:
    """Init containers run sequentially to completion before app
    containers (kuberuntime SyncPod: sandbox -> init -> app)."""

    def test_inits_gate_app_containers(self):
        rt = FakeRuntimeService()
        # inits "run to completion" instantly: exit 0 on start
        rt.fail_starts["init-a"] = 0
        rt.fail_starts["init-b"] = 0
        _, cs, _, kl = _cluster_with_kubelet(runtime=rt)
        try:
            pod = make_pod("with-init", node_name="node-0")
            pod.spec.init_containers = [
                v1.Container(name="init-a", image="img"),
                v1.Container(name="init-b", image="img"),
            ]
            cs.pods.create(pod)
            _wait(lambda: cs.pods.get("with-init", "default").status.phase == "Running",
                  timeout=10)
            # both inits ran and exited 0; app container running
            names = {c.name: c for c in rt.list_containers()}
            assert names["init-a"].exit_code == 0
            assert names["init-b"].exit_code == 0
            assert names["c0"].state == CONTAINER_RUNNING
            # ordering: init-a created before init-b before c0
            assert (names["init-a"].created_at <= names["init-b"].created_at
                    <= names["c0"].created_at)
        finally:
            kl.stop()

    def test_failing_init_with_never_fails_pod(self):
        rt = FakeRuntimeService()
        rt.fail_starts["init-bad"] = 1
        _, cs, _, kl = _cluster_with_kubelet(runtime=rt)
        try:
            pod = make_pod("doomed", node_name="node-0")
            pod.spec.restart_policy = "Never"
            pod.spec.init_containers = [v1.Container(name="init-bad", image="img")]
            cs.pods.create(pod)

            def failed():
                p = cs.pods.get("doomed", "default")
                return (p.status.phase == "Failed"
                        and p.status.reason == "InitContainerFailed")

            _wait(failed, timeout=10)
            # app container never created
            assert all(c.name != "c0" for c in rt.list_containers())
        finally:
            kl.stop()

    def test_failing_init_retries_until_success(self):
        rt = FakeRuntimeService()
        rt.fail_starts["init-flaky"] = 1
        _, cs, _, kl = _cluster_with_kubelet(runtime=rt)
        try:
            pod = make_pod("retry", node_name="node-0")
            pod.spec.init_containers = [v1.Container(name="init-flaky", image="img")]
            cs.pods.create(pod)

            def retried():
                for c in rt.list_containers():
                    if c.name == "init-flaky" and c.restart_count >= 2:
                        return True
                return False

            _wait(retried, timeout=10)
            rt.fail_starts["init-flaky"] = 0  # heals
            _wait(lambda: cs.pods.get("retry", "default").status.phase == "Running",
                  timeout=10)
        finally:
            kl.stop()
