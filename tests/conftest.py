"""Test configuration: force an 8-device virtual CPU mesh before jax imports.

Multi-chip shardings are validated on virtual CPU devices (the real
environment has a single TPU chip); the driver's dryrun_multichip does the
same. x64 is enabled because score math is int64 (framework.MaxNodeScore
scale, reference pkg/scheduler/framework/interface.go:95) and resource math
is int64 milli-units.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env may point at a TPU
os.environ["JAX_ENABLE_X64"] = "1"

import jax  # noqa: E402

# The axon TPU plugin prepends itself to jax_platforms regardless of the env
# var; force the virtual CPU mesh after import.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running soak tests, excluded from tier-1 (-m 'not slow')",
    )


@pytest.fixture
def sim_mesh():
    """8-device simulated CPU mesh over the node axis — the tier-1 stand-in
    for a real multi-host topology (the module docstring's XLA_FLAGS recipe
    provides the virtual devices). Parametrize shard counts by slicing:
    `Mesh(np.asarray(jax.devices()[:n]), ("nodes",))` or
    `make_mesh(n_devices=n)`."""
    from kubernetes_tpu.parallel.sharded import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(n_devices=8)
