"""Incremental device-state deltas: live sessions survive cluster churn.

The tentpole contract (ISSUE 5): every CacheListener event classifies as
carry-delta (batchable pod add/remove on a known node), prologue-patch
(allocatable-only node update), or structural (full rebuild — node
add/remove, term/port pods, capacity growth), and a delta-patched
session produces BIT-IDENTICAL decisions to a fresh rebuild from the
mutated encoding.

Pinned here on the CPU hoisted path (the env tops out there; pallas
carry-patching gets the construction-level parity check below plus the
chip rerun):

  * property test over randomized interleavings of {batchable
    add/remove, affinity-pod add/remove, node update/heartbeat, victim
    evictions mid-pipeline} — delta-patched (KTPU_SESSION_DELTAS on) vs
    rebuild-everything (patching off) backends must decide identically;
  * the rebuild-storm regression: a preemption churn workload through
    the full loop keeps churn-reason session teardowns under a pinned
    bound while the victim-delete echoes apply as deltas;
  * pallas carry-layout parity: apply_deltas on PallasSession (numpy
    seed path AND the fused _carry_delta_scan device path) must equal a
    fresh session built from the mutated encoding, without running the
    Mosaic kernel (CPU-verifiable);
  * the on_remove_pod no-op gate and the GCD-compatibility envelope.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.hoisted import match_matrices_np
from kubernetes_tpu.scheduler import metrics
from kubernetes_tpu.scheduler.internal.cache import SchedulerCache
from kubernetes_tpu.scheduler.tpu_backend import TPUBackend

from .util import anti_affinity, make_node, make_pod, spread_constraint


def _counter_total(counter, kinds=None) -> float:
    return sum(
        val for key, val in counter.items()
        if kinds is None or (key and key[0] in kinds)
    )


def _mk_cluster(n_nodes: int = 6):
    cache = SchedulerCache()
    be = TPUBackend()
    cache.add_listener(be)
    for i in range(n_nodes):
        cache.add_node(make_node(
            f"node-{i}", cpu=str(4 + (i % 2) * 2), memory="16Gi", pods=64,
            labels={v1.LABEL_HOSTNAME: f"node-{i}", "zone": f"z{i % 3}"},
        ))
    return cache, be


def _spread_pod(name, cpu="150m", node=None, labels=None):
    labels = labels or {"app": "spread"}
    return make_pod(
        name, namespace="default", cpu=cpu, memory="64Mi", labels=labels,
        constraints=[spread_constraint(1, "zone", "ScheduleAnyway", labels)],
        node_name=node or "",
    )


def _plain_pod(name, cpu="100m", node=None, labels=None):
    return make_pod(
        name, namespace="default", cpu=cpu, memory="32Mi",
        labels=labels or {"app": "plain"}, node_name=node or "",
    )


def _anti_pod(name, node=None, labels=None):
    labels = labels or {"app": "anti"}
    return make_pod(
        name, namespace="default", cpu="100m", memory="32Mi", labels=labels,
        affinity=anti_affinity(v1.LABEL_HOSTNAME, labels),
        node_name=node or "",
    )


def _event_stream(seed: int):
    """Deterministic randomized interleaving of schedule batches and
    foreign cluster events. Yields (op, payload) tuples the driver
    replays identically against both backends."""
    rng = random.Random(seed)
    ops = []
    added = []  # names of foreign-bound pods currently in the cluster
    for step in range(10):
        kind = rng.random()
        batch = []
        for b in range(rng.randint(1, 4)):
            name = f"p{step}-{b}"
            r = rng.random()
            if r < 0.5:
                batch.append(("spread", name))
            elif r < 0.8:
                batch.append(("plain", name))
            else:
                batch.append(("anti", name))
        ops.append(("schedule", batch))
        if kind < 0.35:
            # foreign batchable add — half of them share the spread
            # template's labels (their counts must patch the carry)
            name = f"f{step}"
            labels = "spread" if rng.random() < 0.5 else "other"
            ops.append(("add", (name, f"node-{rng.randrange(6)}", labels)))
            added.append(name)
        elif kind < 0.55 and added:
            # victim eviction: remove a previously-added bound pod —
            # interleaved between dispatch and the next batch, i.e. the
            # delete echo arrives against a live session mid-stream
            ops.append(("remove", added.pop(rng.randrange(len(added)))))
        elif kind < 0.7:
            # affinity-pod add/remove: structural either way
            name = f"a{step}"
            ops.append(("add-anti", (name, f"node-{rng.randrange(6)}")))
            if rng.random() < 0.5:
                ops.append(("remove-anti", name))
        elif kind < 0.85:
            ops.append(("heartbeat", rng.randrange(6)))
        else:
            ops.append(("alloc-update", rng.randrange(6)))
    return ops


def _replay(ops, delta_patching: bool):
    cache, be = _mk_cluster()
    be.delta_patching = delta_patching
    decisions = {}
    bound = {}
    alloc_bumped = set()
    for op, payload in ops:
        if op == "schedule":
            pods = []
            for tmpl, name in payload:
                mk = {"spread": _spread_pod, "plain": _plain_pod,
                      "anti": _anti_pod}[tmpl]
                pods.append(mk(name))
            handle = be.dispatch_many(pods)
            for p, node in be.harvest(handle):
                decisions[p.metadata.name] = node
        elif op == "add":
            name, node, labels = payload
            p = _plain_pod(
                name, node=node,
                labels={"app": "spread" if labels == "spread" else "x"},
            )
            bound[name] = p
            cache.add_pod(p)
        elif op == "remove":
            cache.remove_pod(bound.pop(payload))
        elif op == "add-anti":
            name, node = payload
            p = _anti_pod(name, node=node)
            bound[name] = p
            cache.add_pod(p)
        elif op == "remove-anti":
            cache.remove_pod(bound.pop(payload))
        elif op == "heartbeat":
            i = payload
            # identical scheduling-relevant fields: the fingerprint gate
            # must swallow it without touching the session
            cache.update_node(make_node(
                f"node-{i}", cpu=str(4 + (i % 2) * 2), memory="16Gi",
                pods=64,
                labels={v1.LABEL_HOSTNAME: f"node-{i}", "zone": f"z{i % 3}"},
            ))
        elif op == "alloc-update":
            i = payload
            # allocatable-only change (same labels/taints): the
            # prologue-patch class
            alloc_bumped.add(i)
            cache.update_node(make_node(
                f"node-{i}", cpu=str(8 + (i % 2) * 2), memory="16Gi",
                pods=64,
                labels={v1.LABEL_HOSTNAME: f"node-{i}", "zone": f"z{i % 3}"},
            ))
    return decisions, be


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_delta_vs_rebuild_parity(seed):
    """Randomized event interleavings: the delta-patched session must
    decide bit-identically to the rebuild-everything control."""
    ops = _event_stream(seed)
    applies0 = _counter_total(metrics.session_delta_applies)
    rebuilds0 = _counter_total(metrics.session_rebuilds)
    with_deltas, _ = _replay(ops, delta_patching=True)
    applies = _counter_total(metrics.session_delta_applies) - applies0
    rebuilds_patched = _counter_total(metrics.session_rebuilds) - rebuilds0
    rebuilds1 = _counter_total(metrics.session_rebuilds)
    without, _ = _replay(ops, delta_patching=False)
    rebuilds_control = _counter_total(metrics.session_rebuilds) - rebuilds1
    assert with_deltas == without, (
        "delta-patched decisions diverged from fresh-rebuild decisions"
    )
    # the stream must actually exercise the fast path (not vacuous)
    assert applies > 0, "no event rode the carry-delta path"
    assert any(node for node in with_deltas.values())
    if rebuilds_control:
        assert rebuilds_patched < rebuilds_control


def test_remove_unknown_pod_is_noop():
    """The on_remove_pod mirror of the assume-echo gate: removing a pod
    the encoding never contained (never encoded, or bound to no node)
    must not tear the session down."""
    _, be = _mk_cluster()
    be.schedule_many([_plain_pod("warm-0"), _plain_pod("warm-1")])
    assert be._session is not None
    sess = be._session
    ghost = _plain_pod("ghost", node="node-0")
    be.on_remove_pod(ghost, "node-0")   # never encoded
    be.on_remove_pod(ghost, "")         # no node
    assert be._session is sess
    assert not be._deltas


def test_batchable_events_keep_session_alive():
    """Foreign batchable add + its delete echo both ride the delta queue
    and the next dispatch applies them — no teardown, same decisions as
    the encoding ground truth."""
    cache, be = _mk_cluster()
    be.schedule_many([_spread_pod("warm-0"), _spread_pod("warm-1")])
    sess = be._session
    assert sess is not None
    squatter = _plain_pod("squatter", cpu="2", node="node-1",
                          labels={"app": "spread"})
    cache.add_pod(squatter)
    assert be._session is sess and len(be._deltas) == 1
    cache.remove_pod(squatter)
    assert be._session is sess and len(be._deltas) == 2
    applies0 = _counter_total(metrics.session_delta_applies)
    res = be.schedule_many([_spread_pod("after-0")])
    assert be._session is sess
    assert _counter_total(metrics.session_delta_applies) - applies0 == 2
    assert all(node for _, node in res)


def test_term_matching_pod_is_structural():
    """With a dyn-IPA session (anti-affinity templates), a foreign pod
    whose labels match a template's own term selector perturbs prologue
    STATICS — it must tear the session down, not ride the carry."""
    cache, be = _mk_cluster()
    be.schedule_many([_anti_pod("warm-0"), _anti_pod("warm-1")])
    sess = be._session
    assert sess is not None and sess.dyn_ipa
    # matching labels (the anti template selects app=anti): structural
    cache.add_pod(_plain_pod("match", node="node-3",
                             labels={"app": "anti"}))
    assert be._session is None
    # rebuild, then a NON-matching batchable pod rides the delta
    be.schedule_many([_anti_pod("warm-2")])
    sess = be._session
    cache.add_pod(_plain_pod("nomatch", node="node-4",
                             labels={"app": "bystander"}))
    assert be._session is sess and len(be._deltas) == 1


def test_node_alloc_update_is_prologue_patch():
    """An allocatable-only node update patches the session statics in
    place; any other fingerprint change stays structural."""
    cache, be = _mk_cluster()
    be.schedule_many([_plain_pod("warm-0")])
    sess = be._session
    assert sess is not None
    cache.update_node(make_node(
        "node-0", cpu="16", memory="16Gi", pods=64,
        labels={v1.LABEL_HOSTNAME: "node-0", "zone": "z0"},
    ))
    assert be._session is sess
    assert [d["kind"] for d in be._deltas] == ["node-alloc"]
    # label change: structural
    cache.update_node(make_node(
        "node-1", cpu="6", memory="16Gi", pods=64,
        labels={v1.LABEL_HOSTNAME: "node-1", "zone": "z9"},
    ))
    assert be._session is None


def test_rebuild_storm_regression():
    """The churn workload's acceptance gate at CI scale: a preemption
    wave's victim-delete echoes and the preemptors' nominated binds must
    NOT tear the session down per event — churn-reason teardowns stay
    under a pinned bound while the events apply as deltas. (The full
    Preemption-PDB/IPA-churn >=5x session_builds_total drop is the chip
    rerun's counter-based check; this pins the mechanism.)"""
    from kubernetes_tpu.perf.harness import PodTemplate, Workload, run_workload

    w = Workload(
        "delta-storm-ci", num_nodes=6, num_init_pods=24, num_pods=12,
        init_template=PodTemplate(cpu="900m", memory="64Mi", priority=1,
                                  labels={"app": "victim"}),
        # every 2nd measured pod is a high-priority preemptor; the rest
        # are small pods that keep dispatches (and so delta flushes)
        # flowing through the measured window
        template=PodTemplate(cpu="50m", memory="16Mi"),
        second_template=PodTemplate(cpu="900m", memory="64Mi",
                                    priority=100),
        second_every=2,
        timeout=180, stall_stop=30.0, max_batch=8,
    )
    r = run_workload(w)
    assert r.num_bound == 12, f"bound {r.num_bound}/12"
    # THE storm signal: on the old path every victim-delete echo (and
    # every preemptor's nominated bind) tore a live session down —
    # churn-reason teardowns tracked the event count. Now they stay
    # under a pinned bound...
    churn = sum(
        (r.session_rebuild_reasons or {}).get(k, 0)
        for k in ("pod-remove", "foreign-pod-add")
    )
    assert churn <= 2, (
        f"rebuild storm: {churn} churn-reason teardowns "
        f"(reasons={r.session_rebuild_reasons})"
    )
    # ...and so does the in-window session-build count (the ISSUE's
    # counter-based acceptance gate at CI scale)
    builds = sum((r.session_builds or {}).values())
    assert builds <= 6, (
        f"{builds} in-window session builds "
        f"(builds={r.session_builds}, reasons={r.session_rebuild_reasons})"
    )
    # NOTE: delta-APPLY counts here depend on dispatch cadence (a fast
    # run binds every preemptor through the nominated short-circuit and
    # never flushes the queue); the deterministic apply/flush assertions
    # live in test_batchable_events_keep_session_alive above.


def test_assume_expiry_is_a_listener_event():
    """Assume-TTL expiry (cleanup_expired_assumed_pods) must route
    through the cache listeners like any other remove: the live session
    SURVIVES, the expiries ride the carry-delta queue, the expired
    counter and assumed-pod gauges move, and post-expiry decisions are
    bit-identical to a fresh rebuild from the same cache state."""
    t = [0.0]
    cache = SchedulerCache(ttl=5.0, now=lambda: t[0])
    be = TPUBackend()
    cache.add_listener(be)
    for i in range(6):
        cache.add_node(make_node(
            f"node-{i}", cpu=str(4 + (i % 2) * 2), memory="16Gi", pods=64,
            labels={v1.LABEL_HOSTNAME: f"node-{i}", "zone": f"z{i % 3}"},
        ))
    res = be.schedule_many([_spread_pod(f"w{i}") for i in range(3)])
    assert all(node for _, node in res)
    sess = be._session
    assert sess is not None
    for p, node in res:
        assumed = _spread_pod(p.metadata.name, node=node)
        cache.assume_pod(assumed)
        cache.finish_binding(assumed)
    # mid-TTL sweep: nothing expires, the age gauge tracks the oldest
    t[0] = 2.0
    assert cache.cleanup_expired_assumed_pods() == 0
    assert metrics.assumed_pods.value() == 3
    assert abs(metrics.oldest_assume_age.value() - 2.0) < 1e-6
    # past the TTL: every assume expires THROUGH the listener
    exp0 = metrics.expired_assumes.value()
    t[0] = 10.0
    assert cache.cleanup_expired_assumed_pods() == 3
    assert metrics.expired_assumes.value() - exp0 == 3
    assert metrics.assumed_pods.value() == 0
    assert metrics.oldest_assume_age.value() == 0.0
    assert be._session is sess, "expiry tore the live session down"
    assert len(be._deltas) == 3, "expiries did not ride the delta queue"
    # parity: the delta-patched session vs a fresh rebuild over the
    # post-expiry cache state must decide identically
    live = {
        p.metadata.name: node
        for p, node in be.schedule_many(
            [_spread_pod(f"probe{i}") for i in range(4)])
    }
    assert be._session is sess
    cache2 = SchedulerCache()
    be2 = TPUBackend()
    cache2.add_listener(be2)
    for i in range(6):
        cache2.add_node(make_node(
            f"node-{i}", cpu=str(4 + (i % 2) * 2), memory="16Gi", pods=64,
            labels={v1.LABEL_HOSTNAME: f"node-{i}", "zone": f"z{i % 3}"},
        ))
    want = {
        p.metadata.name: node
        for p, node in be2.schedule_many(
            [_spread_pod(f"probe{i}") for i in range(4)])
    }
    assert live == want, "post-expiry decisions diverged from rebuild"
    assert any(live.values())


# ---------------------------------------------------------------------------
# pallas carry-layout parity (CPU-verifiable without running the kernel)


def _pallas_fixture():
    from kubernetes_tpu.ops.pallas_scan import PallasSession

    enc = ClusterEncoding()
    nodes = [
        make_node(f"n{i}", labels={v1.LABEL_HOSTNAME: f"n{i}",
                                   "zone": f"z{i % 3}"})
        for i in range(5)
    ]
    bound = [_spread_pod(f"b{i}", node=f"n{i % 5}") for i in range(7)]
    enc.set_cluster(nodes, bound)
    pe = PodEncoder(enc)
    tmpl = {
        k: va for k, va in pe.encode(_spread_pod("t0")).items()
        if not k.startswith("_")
    }
    cluster = {k: np.asarray(va) for k, va in enc.device_state().items()}
    return PallasSession, enc, bound, tmpl, cluster


def _remove_delta(enc, victim):
    nidx = enc.node_index[victim.spec.node_name]
    A = enc._arrays
    before = (A["requested"][nidx].copy(), A["nz_requested"][nidx].copy(),
              int(A["pod_count"][nidx]))
    enc.remove_pod(victim)
    dres = A["requested"][nidx] - before[0]
    dnz = A["nz_requested"][nidx] - before[1]
    dcount = int(A["pod_count"][nidx]) - before[2]
    pp = np.zeros(enc.pod_pair_vocab.capacity, bool)
    pk = np.zeros(enc.pod_key_vocab.capacity, bool)
    for k, va in victim.metadata.labels.items():
        if enc.pod_key_vocab.get(k):
            pk[enc.pod_key_vocab.get(k)] = True
        if enc.pod_pair_vocab.get((k, va)):
            pp[enc.pod_pair_vocab.get((k, va))] = True
    rows = {"self_ppair": pp, "self_pkey": pk,
            "self_ns": np.int32(enc.ns_vocab.get("default"))}
    return nidx, dres, dnz, dcount, rows


@pytest.mark.parametrize("device_path", [False, True])
def test_pallas_delta_carry_parity(device_path):
    """apply_deltas on the pallas carry layout (numpy seed path and the
    fused _carry_delta_scan) must equal a FRESH PallasSession built from
    the mutated encoding — compared on valid node lanes, bit for bit."""
    PallasSession, enc, bound, tmpl, cluster = _pallas_fixture()
    sess = PallasSession(cluster, [tmpl])
    victim = bound[3]
    nidx, dres, dnz, dcount, rows = _remove_delta(enc, victim)
    assert sess.delta_compatible(dres, dnz)
    mfa, msa = match_matrices_np(sess._tp_np, [rows])
    delta = {
        "kind": "pod-remove", "node": nidx, "dres": dres, "dnz": dnz,
        "dcount": dcount,
        "mf": mfa[:, 0, :].astype(np.int32) * -1,
        "ms": msa[:, 0, :].astype(np.int32) * -1,
    }
    if device_path:
        sess._carry = sess._initial_carry()
    sess.apply_deltas([delta])
    fresh_cluster = {
        k: np.asarray(va) for k, va in enc.device_state().items()
    }
    fresh = PallasSession(fresh_cluster, [tmpl])
    valid = fresh_cluster["valid"].astype(bool)
    n = valid.shape[0]
    if device_path:
        got = {k: np.asarray(va) for k, va in sess._carry.items()}
    else:
        got = {
            "requested": sess._requested0, "nzpc": sess._nzpc0,
            "cnt_fn": sess._cnt_fn0, "cnt_sn": sess._cnt_sn0,
        }
    want = {
        "requested": fresh._requested0, "nzpc": fresh._nzpc0,
        "cnt_fn": fresh._cnt_fn0, "cnt_sn": fresh._cnt_sn0,
    }
    for key in want:
        a = np.asarray(got[key])[:, :n][:, valid]
        b = want[key][:, :n][:, valid]
        assert (a == b).all(), f"carry {key} diverged from fresh build"


def test_pallas_gcd_incompatible_delta_rejected():
    """A utilization delta the build-time GCD rescale cannot divide
    exactly must be refused (the backend then takes the structural
    path) — never silently truncated."""
    PallasSession, enc, bound, tmpl, cluster = _pallas_fixture()
    sess = PallasSession(cluster, [tmpl])
    r = sess._gcd.shape[0]
    if int(sess._gcd[0]) <= 1:
        pytest.skip("cpu dimension has gcd 1 — every delta divides")
    dres = np.zeros(r, np.int64)
    dres[0] = int(sess._gcd[0]) + 1  # not a multiple
    assert not sess.delta_compatible(dres, np.zeros(2, np.int64))


def test_sharded_delta_carry_parity():
    """The sharded mirror's per-shard counts patch through the same
    fused delta scan: apply on an 8-device virtual mesh must equal a
    fresh sharded session from the mutated encoding."""
    import jax
    from jax.sharding import Mesh

    from kubernetes_tpu.ops.sharded_scan import ShardedPallasSession
    from kubernetes_tpu.parallel.sharded import NODE_AXIS

    PallasSession, enc, bound, tmpl, cluster = _pallas_fixture()
    mesh = Mesh(np.array(jax.devices("cpu")[:8]), (NODE_AXIS,))
    sess = ShardedPallasSession(cluster, [tmpl], mesh=mesh)
    victim = bound[2]
    nidx, dres, dnz, dcount, rows = _remove_delta(enc, victim)
    assert sess.delta_compatible(dres, dnz)
    mfa, msa = match_matrices_np(sess._tp_np, [rows])
    sess.apply_deltas([{
        "kind": "pod-remove", "node": nidx, "dres": dres, "dnz": dnz,
        "dcount": dcount,
        "mf": mfa[:, 0, :].astype(np.int32) * -1,
        "ms": msa[:, 0, :].astype(np.int32) * -1,
    }])
    fresh_cluster = {
        k: np.asarray(va) for k, va in enc.device_state().items()
    }
    fresh = ShardedPallasSession(fresh_cluster, [tmpl], mesh=mesh)
    valid = fresh_cluster["valid"].astype(bool)
    n = valid.shape[0]
    for key in ("requested", "nzpc", "cnt_fn", "cnt_sn"):
        a = np.asarray(sess._carry[key])[:, :n][:, valid]
        b = np.asarray(fresh._carry[key])[:, :n][:, valid]
        assert (a == b).all(), f"sharded carry {key} diverged"
