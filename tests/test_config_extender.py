"""Config API, extender protocol, and factory assembly tests.

Mirrors pkg/scheduler/apis/config/validation tests and the extender
integration tier (test/integration/scheduler/extender_test.go — a live
HTTP extender filtering/prioritizing real scheduling cycles)."""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import Clientset, SharedInformerFactory
from kubernetes_tpu.scheduler.apis.config import (
    ConfigError,
    Extender,
    KubeSchedulerConfiguration,
    KubeSchedulerProfile,
    Plugin,
    PluginSet,
    Plugins,
    default_configuration,
    load_configuration,
    merged_plugins_for_profile,
    validate_configuration,
)
from kubernetes_tpu.scheduler.extender import HTTPExtender
from kubernetes_tpu.scheduler.factory import create_scheduler
from kubernetes_tpu.testing.synth import make_node, make_pod

# ---------------------------------------------------------------------------
# config


def test_default_config_valid():
    cfg = default_configuration()
    validate_configuration(cfg)
    merged = merged_plugins_for_profile(cfg.profiles[0])
    assert ("NodeResourcesFit", 1) in merged["filter"]
    assert ("PodTopologySpread", 2) in merged["score"]


def test_merge_disable_star_and_enable():
    profile = KubeSchedulerProfile(
        plugins=Plugins(
            score=PluginSet(
                enabled=[Plugin("NodeResourcesLeastAllocated", 5)],
                disabled=[Plugin("*")],
            )
        )
    )
    merged = merged_plugins_for_profile(profile)
    assert merged["score"] == [("NodeResourcesLeastAllocated", 5)]
    # other points untouched
    assert any(n == "NodeResourcesFit" for n, _ in merged["filter"])


def test_validation_rejects_bad_configs():
    cfg = default_configuration()
    cfg.percentage_of_nodes_to_score = 150
    with pytest.raises(ConfigError):
        validate_configuration(cfg)
    cfg = default_configuration()
    cfg.profiles.append(KubeSchedulerProfile())  # duplicate name
    with pytest.raises(ConfigError):
        validate_configuration(cfg)
    cfg = default_configuration()
    cfg.profiles[0].backend = "gpu"
    with pytest.raises(ConfigError):
        validate_configuration(cfg)
    cfg = default_configuration()
    cfg.profiles[0].plugins = Plugins(queue_sort=PluginSet(disabled=[Plugin("*")]))
    with pytest.raises(ConfigError):
        validate_configuration(cfg)


def test_load_configuration_yaml():
    text = """
apiVersion: kubescheduler.config.k8s.io/v1beta1
kind: KubeSchedulerConfiguration
percentageOfNodesToScore: 50
podInitialBackoffSeconds: 2
profiles:
  - schedulerName: tpu-scheduler
    backend: tpu
    plugins:
      score:
        disabled:
          - name: ImageLocality
    pluginConfig:
      - name: NodeResourcesFit
        args:
          ignoredResources: ["example.com/foo"]
extenders: []
"""
    cfg = load_configuration(text)
    assert cfg.percentage_of_nodes_to_score == 50
    assert cfg.profiles[0].scheduler_name == "tpu-scheduler"
    merged = merged_plugins_for_profile(cfg.profiles[0])
    assert not any(n == "ImageLocality" for n, _ in merged["score"])
    assert cfg.profiles[0].plugin_config["NodeResourcesFit"]["ignoredResources"] == [
        "example.com/foo"
    ]


def test_factory_tpu_weights_follow_profile():
    api = APIServer()
    cs = Clientset(api)
    factory = SharedInformerFactory(cs)
    cfg = default_configuration()
    cfg.profiles[0].plugins = Plugins(
        score=PluginSet(enabled=[Plugin("PodTopologySpread", 7)],
                        disabled=[Plugin("ImageLocality")])
    )
    sched = create_scheduler(cs, factory, cfg)
    assert sched.tpu.weights["pts"] == 7
    assert sched.tpu.weights["image"] == 0
    cfg2 = default_configuration()
    cfg2.extenders = [Extender(url_prefix="http://localhost:9", filter_verb="filter")]
    with pytest.raises(ConfigError):
        create_scheduler(cs, factory, cfg2)
    cfg2.profiles[0].backend = "oracle"
    sched2 = create_scheduler(cs, factory, cfg2)
    assert len(sched2.algorithm.extenders) == 1


# ---------------------------------------------------------------------------
# extender protocol against a live HTTP server


class _ExtenderHandler(BaseHTTPRequestHandler):
    calls = []

    def do_POST(self):
        body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
        self.calls.append((self.path, body))
        if self.path.endswith("/filter"):
            names = [
                n["metadata"]["name"] for n in body["nodes"]["items"]
            ]
            kept = [n for n in body["nodes"]["items"] if n["metadata"]["name"] != "node-0"]
            resp = {
                "nodes": {"items": kept},
                "failedNodes": {"node-0": "extender says no"} if "node-0" in names else {},
            }
        elif self.path.endswith("/prioritize"):
            resp = [
                {"host": n["metadata"]["name"],
                 "score": 10 if n["metadata"]["name"] == "node-2" else 0}
                for n in body["nodes"]["items"]
            ]
        else:
            resp = {"error": f"unknown verb {self.path}"}
        data = json.dumps(resp).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):
        pass


@pytest.fixture()
def extender_server():
    _ExtenderHandler.calls = []
    server = HTTPServer(("127.0.0.1", 0), _ExtenderHandler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{server.server_port}"
    server.shutdown()


def test_http_extender_roundtrip(extender_server):
    ext = HTTPExtender(
        Extender(
            url_prefix=extender_server,
            filter_verb="filter",
            prioritize_verb="prioritize",
            weight=3,
        )
    )
    nodes = [make_node(f"node-{i}") for i in range(3)]
    pod = make_pod("p", cpu="100m")
    kept, failed = ext.filter(pod, nodes)
    assert [n.metadata.name for n in kept] == ["node-1", "node-2"]
    assert failed == {"node-0": "extender says no"}
    scores, weight = ext.prioritize(pod, nodes)
    assert weight == 3
    assert {s["host"]: s["score"] for s in scores}["node-2"] == 10


def test_extender_in_live_scheduling(extender_server):
    """Oracle loop + extender: node-0 excluded by Filter, node-2 boosted by
    Prioritize (extender_test.go pattern)."""
    api = APIServer()
    cs = Clientset(api)
    for i in range(3):
        cs.nodes.create(make_node(f"node-{i}", labels={v1.LABEL_HOSTNAME: f"node-{i}"}))
    factory = SharedInformerFactory(cs)
    cfg = default_configuration()
    cfg.profiles[0].backend = "oracle"
    cfg.extenders = [
        Extender(
            url_prefix=extender_server,
            filter_verb="filter",
            prioritize_verb="prioritize",
            weight=100,  # dominate in-tree scores
        )
    ]
    sched = create_scheduler(cs, factory, cfg)
    factory.start()
    assert factory.wait_for_cache_sync()
    try:
        sched.start()
        cs.pods.create(make_pod("p", namespace="default", cpu="100m"))
        deadline = time.monotonic() + 20
        pod = None
        while time.monotonic() < deadline:
            pod = cs.pods.get("p", "default")
            if pod.spec.node_name:
                break
            time.sleep(0.1)
        assert pod.spec.node_name == "node-2"
    finally:
        sched.stop()
        factory.stop()
