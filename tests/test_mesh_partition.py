"""Declarative GSPMD placement: the partition-rule tables of
parallel/partition.py.

Every array the mesh ever sees — cluster encoding, session statics/
tables/carry — gets its PartitionSpec from a regex-on-leaf-path rule
table (match_partition_rules), not per-key wiring. These tests pin the
three contracts that make that safe at 100k nodes:

  * coverage: every leaf of every live tree matches a rule (an
    unmatched leaf is a loud ValueError, not silent replication);
  * placement: the rules reproduce the hand-wired placements they
    replaced (node rows split over the "nodes" axis, everything else
    replicated), so per-host memory stays bounded by shard size;
  * padding: pad_node_axis quantizes the node axis to shard multiples
    with growth headroom, and the all-zero padding rows can never win
    a scheduling cycle.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.parallel.partition import (
    CLUSTER_PARTITION_RULES,
    NODE_AXIS,
    SESSION_PARTITION_RULES,
    match_partition_rules,
    session_specs,
    shard_map_compat,
    tree_path_to_string,
)
from kubernetes_tpu.parallel.sharded import (
    NODE_DIM0_KEYS,
    ShardedScheduler,
    make_mesh,
    node_capacity_multiple,
    pad_node_axis,
    shard_cluster,
)
from kubernetes_tpu.scheduler.internal.cache import SchedulerCache
from kubernetes_tpu.scheduler.tpu_backend import TPUBackend

from .util import make_node, make_pod


def _mesh_or_skip(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual devices")
    return make_mesh(n_devices=n)


def _backend(n_nodes=6, mesh=None, fill=True):
    cache = SchedulerCache()
    be = TPUBackend(mesh=mesh)
    cache.add_listener(be)
    for i in range(n_nodes):
        cache.add_node(make_node(
            f"node-{i}", cpu="8", memory="32Gi",
            labels={v1.LABEL_HOSTNAME: f"node-{i}"}))
    if fill:
        # every LIVE node carries allocation, so an all-zero padding row
        # would win the least-allocated leg if it ever reached scoring
        for i in range(n_nodes):
            cache.add_pod(make_pod(
                f"fill-{i}", namespace="default", cpu="2", memory="4Gi",
                labels={"app": "fill"}, node_name=f"node-{i}"))
    return cache, be


# ---------------------------------------------------------------- rules


class TestClusterRules:
    def test_rules_cover_every_device_state_leaf(self):
        """The REAL cluster dict (encoding device_state) is fully
        covered, and the specs reproduce the hand-wired placement the
        table replaced: NODE_DIM0_KEYS split on dim 0, rest replicated."""
        _, be = _backend()
        cluster = {k: np.asarray(v) for k, v in be.enc.device_state().items()}
        specs = match_partition_rules(CLUSTER_PARTITION_RULES, cluster)
        assert set(specs) == set(cluster)
        for k, spec in specs.items():
            arr = cluster[k]
            if k in NODE_DIM0_KEYS:
                assert spec == P(NODE_AXIS), (k, spec)
            else:
                assert spec == P(), (k, spec)
                # scalar/1-elem short circuit never sees the node axis
            if arr.ndim == 0 or arr.size <= 1:
                assert spec == P(), (k, spec)

    def test_unmatched_leaf_raises(self):
        """A leaf no rule covers fails construction loudly — new state
        must be placed deliberately, not silently replicated."""
        with pytest.raises(ValueError, match="partition rule not found"):
            match_partition_rules(
                [("^valid$", P(NODE_AXIS))], {"mystery": np.zeros((8, 4))})

    def test_scalar_short_circuit(self):
        """Scalars and 1-element arrays replicate even when a
        node-axis rule matches their path (nothing to split)."""
        specs = match_partition_rules(
            [(".*", P(NODE_AXIS))],
            {"s": np.int32(3), "one": np.zeros((1,)), "v": np.zeros((8,))})
        assert specs["s"] == P()
        assert specs["one"] == P()
        assert specs["v"] == P(NODE_AXIS)

    def test_tree_path_to_string_nested(self):
        tree = {"a": {"b": [np.zeros(2), np.zeros(2)]}}
        flat = jax.tree_util.tree_flatten_with_path(tree)[0]
        paths = [tree_path_to_string(p) for p, _ in flat]
        assert paths == ["a/b/0", "a/b/1"]


class TestSessionRules:
    def test_rules_cover_every_session_leaf(self, sim_mesh):
        """Every statics/tables/delta/carry leaf of a LIVE
        ShardedPallasSession matches a rule, and every node-sharded
        leaf's shard is bounded to Npl = Nps/nsh rows — the per-host
        memory contract that makes 100k nodes survivable."""
        from kubernetes_tpu.ops.sharded_scan import ShardedPallasSession

        _, be = _backend(n_nodes=19, mesh=sim_mesh)
        pa = {k: va for k, va in be.pe.encode(
            make_pod("probe", namespace="default", cpu="100m",
                     memory="64Mi", labels={"app": "p"})).items()
            if not k.startswith("_")}
        sess = ShardedPallasSession(
            be.enc.device_state(), [pa], be.weights, mesh=sim_mesh)
        nsh = sim_mesh.devices.size
        tree = {"statics": sess._statics, "tables": sess._tables,
                "delta": sess._delta_statics, "carry": sess._carry}
        specs = match_partition_rules(SESSION_PARTITION_RULES, tree)
        flat_specs = jax.tree_util.tree_flatten_with_path(specs)[0]
        flat_arrs = jax.tree_util.tree_flatten_with_path(tree)[0]
        assert len(flat_specs) == len(flat_arrs)
        sharded = 0
        for (path, spec), (_, arr) in zip(flat_specs, flat_arrs):
            name = tree_path_to_string(path)
            if NODE_AXIS in tuple(spec):
                dim = tuple(spec).index(NODE_AXIS)
                assert arr.shape[dim] == sess.Nps, (name, arr.shape)
                got = arr.sharding.shard_shape(arr.shape)[dim]
                assert got == sess.Npl == sess.Nps // nsh, (name, got)
                sharded += 1
            else:
                # replicated leaf: one full copy per device
                assert arr.sharding.is_fully_replicated, name
        # the carry (all 4+ leaves) and the big statics ride the mesh
        assert sharded >= len(sess._carry) + 10
        # the per-group helper agrees with the full-tree match
        assert session_specs("carry", sess._carry) == specs["carry"]

    def test_session_rules_reject_unknown_group(self):
        with pytest.raises(ValueError, match="partition rule not found"):
            match_partition_rules(
                SESSION_PARTITION_RULES, {"mystery": {"x": np.zeros((8, 8))}})


# ----------------------------------------------------------- make_mesh


class TestMakeMesh:
    def test_env_device_count(self, monkeypatch):
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        monkeypatch.setenv("KTPU_MESH_DEVICES", "4")
        mesh = make_mesh()
        assert mesh.devices.size == 4
        assert mesh.axis_names == (NODE_AXIS,)

    def test_env_zero_means_all(self, monkeypatch):
        monkeypatch.setenv("KTPU_MESH_DEVICES", "0")
        assert make_mesh().devices.size == len(jax.devices())

    def test_explicit_count_wins(self, monkeypatch):
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 virtual devices")
        monkeypatch.setenv("KTPU_MESH_DEVICES", "1")
        assert make_mesh(n_devices=2).devices.size == 2

    def test_sim_mesh_fixture(self, sim_mesh):
        """The conftest recipe (XLA_FLAGS --xla_force_host_platform_
        device_count=8) yields a real 8-way mesh on CPU."""
        assert sim_mesh.devices.size == 8
        assert node_capacity_multiple(sim_mesh) == 8


# ------------------------------------------------------- pad_node_axis


class TestPadNodeAxis:
    def _cluster(self, n):
        _, be = _backend(n_nodes=n, fill=False)
        return {k: np.asarray(v) for k, v in be.enc.device_state().items()}

    def test_quantized_to_shard_multiple(self, monkeypatch):
        monkeypatch.delenv("KTPU_NODE_HEADROOM", raising=False)
        c = self._cluster(6)
        ncap = c["valid"].shape[0]
        out = pad_node_axis(c, 8)
        want = -(-ncap // 8) * 8
        for k in NODE_DIM0_KEYS:
            assert out[k].shape[0] == want, k
        # non-node arrays untouched
        assert out["n_nodes"] is c["n_nodes"]

    def test_headroom_over_pads(self):
        c = self._cluster(6)
        ncap = c["valid"].shape[0]
        out = pad_node_axis(c, 4, headroom=1.0)
        # ceil(ncap * 2) rounded up to the multiple
        want = -(-(ncap * 2) // 4) * 4
        assert out["valid"].shape[0] == want

    def test_already_aligned_is_identity(self):
        c = self._cluster(6)
        ncap = c["valid"].shape[0]
        out = pad_node_axis(c, 1, headroom=0.0)
        assert out is c or out["valid"].shape[0] == ncap

    def test_padding_rows_are_infeasible_zeros(self):
        c = self._cluster(6)
        ncap = c["valid"].shape[0]
        out = pad_node_axis(c, 64)
        assert not np.asarray(out["valid"][ncap:]).any()
        for k in NODE_DIM0_KEYS:
            assert not np.asarray(out[k][ncap:]).any(), k

    def test_env_headroom_applies(self, monkeypatch):
        monkeypatch.setenv("KTPU_NODE_HEADROOM", "0.5")
        c = self._cluster(6)
        ncap = c["valid"].shape[0]
        out = pad_node_axis(c, 2)
        want = -(-int(np.ceil(ncap * 1.5)) // 2) * 2
        assert out["valid"].shape[0] == want


# -------------------------------------------- padding never schedules


class TestPaddingExclusion:
    """Directed: every live node carries allocation, so the all-zero
    padding rows (alloc=0, requested=0) would WIN the least-allocated
    tiebreak if they ever reached scoring — `valid` stays False in the
    pad, so they must be filtered at every shard count."""

    @pytest.mark.parametrize("nsh", [2, 4, 8])
    def test_single_cycle_never_picks_padding(self, nsh):
        mesh = _mesh_or_skip(nsh)
        _, be = _backend(n_nodes=5, fill=True)
        n_live = be.enc.n_nodes
        cluster = be.enc.device_state()
        pod = {k: va for k, va in be.pe.encode(
            make_pod("probe", namespace="default", cpu="100m",
                     memory="64Mi", labels={"app": "p"})).items()
            if not k.startswith("_")}
        out = ShardedScheduler(mesh=mesh).schedule(dict(cluster), pod)
        best = int(out["best_idx"])
        total = np.asarray(out["total"])
        assert total.shape[0] % nsh == 0  # padded to the shard multiple
        assert best < n_live, (best, n_live)
        assert int(out["n_feasible"]) == n_live
        # the padded tail is scored infeasible, not zero-allocated-best
        assert (total[n_live:] < total[best]).all()

    @pytest.mark.parametrize("nsh", [2, 4, 8])
    def test_session_never_picks_padding(self, nsh):
        from kubernetes_tpu.ops.sharded_scan import ShardedPallasSession

        mesh = _mesh_or_skip(nsh)
        _, be = _backend(n_nodes=5, fill=True)
        n_live = be.enc.n_nodes
        pods = [make_pod(f"w-{i}", namespace="default", cpu="100m",
                         memory="64Mi", labels={"app": "w"})
                for i in range(6)]
        arrays = [{k: va for k, va in be.pe.encode(p).items()
                   if not k.startswith("_")} for p in pods]
        sess = ShardedPallasSession(
            be.enc.device_state(), [arrays[0]], be.weights, mesh=mesh)
        assert sess.Nps >= n_live and sess.Nps % nsh == 0
        got = ShardedPallasSession.decisions(sess.schedule(arrays))
        assert all(0 <= d < n_live for d in got), (got, n_live)

    def test_whole_shard_of_padding(self):
        """Headroom large enough that ENTIRE shards are fake nodes —
        the regime after mass node removal. No fake lane may win."""
        mesh = _mesh_or_skip(8)
        _, be = _backend(n_nodes=3, fill=True)
        n_live = be.enc.n_nodes
        cluster = pad_node_axis(
            {k: np.asarray(v) for k, v in be.enc.device_state().items()},
            node_capacity_multiple(mesh), headroom=4.0)
        assert cluster["valid"].shape[0] >= 5 * n_live
        pod = {k: va for k, va in be.pe.encode(
            make_pod("probe", namespace="default", cpu="100m",
                     memory="64Mi", labels={"app": "p"})).items()
            if not k.startswith("_")}
        out = ShardedScheduler(mesh=mesh).schedule(cluster, pod)
        assert int(out["best_idx"]) < n_live
        assert int(out["n_feasible"]) == n_live


# ------------------------------------------------------ shard_map smoke


class TestShardMapCompat:
    def test_psum_over_node_axis(self, sim_mesh):
        """shard_map_compat papers over the jax.shard_map /
        jax.experimental.shard_map split; a psum over the node axis is
        the canonical collective every kernel reduction builds on."""
        x = jnp.arange(16.0)

        def f(xs):
            return jax.lax.psum(jnp.sum(xs), NODE_AXIS)

        f_sharded = shard_map_compat(
            f, sim_mesh, in_specs=(P(NODE_AXIS),), out_specs=P())
        assert float(f_sharded(x)) == float(jnp.sum(x))

    def test_shard_cluster_places_on_mesh(self, sim_mesh):
        _, be = _backend(n_nodes=6, fill=False)
        c = shard_cluster(
            {k: np.asarray(v) for k, v in be.enc.device_state().items()},
            sim_mesh)
        nsh = sim_mesh.devices.size
        for k in NODE_DIM0_KEYS:
            arr = c[k]
            assert arr.shape[0] % nsh == 0, k
            assert (arr.sharding.shard_shape(arr.shape)[0]
                    == arr.shape[0] // nsh), k
        assert c["n_nodes"].sharding.is_fully_replicated
