"""CSI migration (volume/csi_translation.py) — translation parity and
the migrated-PV ride on the kernel volume path.

Reference: staging/src/k8s.io/csi-translation-lib/translate.go:30 with
plugins/{gce_pd,aws_ebs,azure_disk}.go; consumed like the scheduler's
CSIMigration feature — the kernel resolver and the oracle
NodeVolumeLimits plugin must see the SAME driver for a migrated PV.
"""

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.volume.csi_translation import (
    migratable_plugin,
    pv_csi_source,
    translate_pv,
)


def _pv(name="pv0", zone=None, **spec_kw):
    labels = {v1.LABEL_ZONE: zone} if zone else {}
    return v1.PersistentVolume(
        metadata=v1.ObjectMeta(name=name, labels=labels),
        spec=v1.PersistentVolumeSpec(
            capacity={"storage": "1Gi"},
            access_modes=["ReadWriteOnce"],
            **spec_kw,
        ),
        status=v1.PersistentVolumeStatus(phase="Bound"),
    )


class TestTranslate:
    def test_gce_pd_zonal_handle(self):
        pv = _pv(zone="us-central1-a",
                 gce_persistent_disk={"pdName": "disk-1"})
        assert migratable_plugin(pv) == "gce_persistent_disk"
        out = translate_pv(pv)
        assert out.spec.gce_persistent_disk is None
        assert out.spec.csi["driver"] == "pd.csi.storage.gke.io"
        # gce_pd.go volIDZonalFmt
        assert out.spec.csi["volumeHandle"] == \
            "projects/UNSPECIFIED/zones/us-central1-a/disks/disk-1"
        # zone label lifted into node affinity (translateTopology)
        terms = out.spec.node_affinity.required.node_selector_terms
        assert terms[0].match_expressions[0].key == v1.LABEL_ZONE
        assert terms[0].match_expressions[0].values == ["us-central1-a"]
        # the original is untouched (translation returns a copy)
        assert pv.spec.gce_persistent_disk is not None
        assert pv.spec.csi is None

    def test_gce_pd_regional(self):
        pv = _pv(zone="us-east1-b__us-east1-c",
                 gce_persistent_disk={"pdName": "r-disk"})
        out = translate_pv(pv)
        assert out.spec.csi["volumeHandle"] == \
            "projects/UNSPECIFIED/zones/us-east1/disks/r-disk"
        vals = out.spec.node_affinity.required \
            .node_selector_terms[0].match_expressions[0].values
        assert vals == ["us-east1-b", "us-east1-c"]

    def test_aws_and_azure(self):
        ebs = _pv(aws_elastic_block_store={"volumeID": "vol-123"})
        assert pv_csi_source(ebs) == {
            "driver": "ebs.csi.aws.com", "volumeHandle": "vol-123"}
        az = _pv(azure_disk={"diskName": "d1"})
        assert pv_csi_source(az)["driver"] == "disk.csi.azure.com"

    def test_native_csi_passthrough(self):
        pv = _pv(csi={"driver": "x.example", "volumeHandle": "h"})
        assert migratable_plugin(pv) is None
        assert translate_pv(pv) is pv
        assert pv_csi_source(pv) == {"driver": "x.example",
                                     "volumeHandle": "h"}

    def test_untranslatable_pv(self):
        pv = _pv()
        assert migratable_plugin(pv) is None
        assert pv_csi_source(pv) is None

    def test_existing_node_affinity_preserved(self):
        na = v1.VolumeNodeAffinity(required=v1.NodeSelector(
            node_selector_terms=[v1.NodeSelectorTerm(match_expressions=[
                v1.NodeSelectorRequirement(
                    key="disk", operator="In", values=["ssd"])
            ])]
        ))
        pv = _pv(zone="z-a", gce_persistent_disk={"pdName": "d"})
        pv.spec.node_affinity = na
        out = translate_pv(pv)
        # translateTopology must not clobber an explicit affinity
        assert out.spec.node_affinity.required \
            .node_selector_terms[0].match_expressions[0].key == "disk"

    def test_serde_roundtrip(self):
        from kubernetes_tpu.utils import serde

        pv = _pv(zone="z-a", gce_persistent_disk={"pdName": "d"})
        back = serde.from_dict(v1.PersistentVolume, serde.to_dict(pv))
        assert back.spec.gce_persistent_disk == {"pdName": "d"}


class TestMigratedOnKernelPath:
    """A bound migrated PV resolves into the kernel envelope with the
    translated driver's attach scalar + zone terms — exactly like a
    native CSI PV."""

    def _resolver(self, pvs, pvcs):
        from kubernetes_tpu.scheduler.volume_device import (
            VolumeDeviceResolver,
        )

        return VolumeDeviceResolver(
            list_pvcs=lambda: pvcs, list_pvs=lambda: pvs,
            list_csinodes=lambda: [],
        )

    def test_resolve_migrated(self):
        pv = _pv(zone="zone-0",
                 aws_elastic_block_store={"volumeID": "vol-9"})
        pvc = v1.PersistentVolumeClaim(
            metadata=v1.ObjectMeta(name="c0", namespace="default"),
            spec=v1.PersistentVolumeClaimSpec(volume_name="pv0"),
        )
        pod = v1.Pod(
            metadata=v1.ObjectMeta(name="p", namespace="default"),
            spec=v1.PodSpec(
                containers=[v1.Container(name="c")],
                volumes=[v1.Volume(name="d", source={
                    "persistentVolumeClaim": {"claimName": "c0"}})],
            ),
        )
        res = self._resolver([pv], [pvc]).resolve(pod)
        assert res is not None
        assert res.extra_scalars == {
            "attachable-volumes-csi-ebs.csi.aws.com": 1}
        # zone label -> zone term group
        assert any(
            any(r.key == v1.LABEL_ZONE for r in (t.match_expressions or []))
            for g in res.term_groups for t in g
        )

    def test_oracle_limits_see_migrated_driver(self):
        from kubernetes_tpu.scheduler.plugins.volumes import (
            NodeVolumeLimits,
        )

        pv = _pv(aws_elastic_block_store={"volumeID": "vol-9"})
        pvc = v1.PersistentVolumeClaim(
            metadata=v1.ObjectMeta(name="c0", namespace="default"),
            spec=v1.PersistentVolumeClaimSpec(volume_name="pv0"),
        )

        class H:
            volume_listers = (lambda: [pvc], lambda: [pv])
            csi_node_lister = None

        plug = NodeVolumeLimits(handle=H())
        lookup = plug._pvc_to_driver()
        assert lookup("default", "c0") == ("ebs.csi.aws.com", "vol-9")
