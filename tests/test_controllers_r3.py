"""Round-3 controllers long tail: csrsigning/csrapproving/csrcleaner,
bootstrapsigner/tokencleaner, clusterrole-aggregation,
endpointslicemirroring, ephemeral-volume, persistentvolume-expander,
root-ca-cert-publisher — plus the kubeadm join-through-CSR flow.

Reference: cmd/kube-controller-manager/app/controllermanager.go:391,
406-428 initializers; pkg/controller/{certificates,bootstrap,
clusterroleaggregation,endpointslicemirroring,volume/ephemeral,
volume/expand}; rootcacertpublisher.
"""

import time

import pytest

from kubernetes_tpu.api import certificates as certsapi
from kubernetes_tpu.api import discovery, rbac, storage
from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.server import APIServer, NotFound
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.bootstrap import (
    BootstrapSignerController,
    TokenCleanerController,
    sign_kubeconfig,
)
from kubernetes_tpu.controllers.certificates import (
    CSRApprovingController,
    CSRCleanerController,
    CSRSigningController,
)
from kubernetes_tpu.controllers.clusterroleaggregation import (
    ClusterRoleAggregationController,
)
from kubernetes_tpu.controllers.endpointslicemirroring import (
    MANAGED_BY,
    MANAGED_BY_LABEL,
    EndpointSliceMirroringController,
)
from kubernetes_tpu.controllers.ephemeral import (
    EphemeralVolumeController,
    ExpandController,
)
from kubernetes_tpu.controllers.manager import new_controller_initializers
from kubernetes_tpu.controllers.rootcacertpublisher import (
    ROOT_CA_CONFIGMAP,
    RootCACertPublisher,
)
from kubernetes_tpu.kubeadm import CertificateAuthority

from .util import make_pod, wait_until


@pytest.fixture()
def cluster():
    api = APIServer()
    cs = Clientset(api)
    factory = SharedInformerFactory(cs)
    started = []

    def start(*ctrls):
        factory.start()
        assert factory.wait_for_cache_sync()
        for c in ctrls:
            c.run()
            started.append(c)
        return ctrls

    yield api, cs, factory, start
    for c in started:
        c.stop()
    factory.stop()


def test_initializer_registry_has_r3_controllers():
    inits = new_controller_initializers()
    for name in ("csrsigning", "csrapproving", "csrcleaner",
                 "bootstrapsigner", "tokencleaner",
                 "clusterrole-aggregation", "endpointslicemirroring",
                 "ephemeral-volume", "persistentvolume-expander",
                 "root-ca-cert-publisher"):
        assert name in inits, name
    assert len(inits) >= 34


def _bootstrap_csr(name="node-csr-w0", node="w0"):
    return certsapi.CertificateSigningRequest(
        metadata=v1.ObjectMeta(name=name),
        spec=certsapi.CertificateSigningRequestSpec(
            request=certsapi.encode_request(
                f"system:node:{node}", ["system:nodes"]),
            signer_name=certsapi.SIGNER_KUBE_APISERVER_CLIENT_KUBELET,
            usages=["client auth"],
            username="system:bootstrap:abcdef",
            groups=["system:bootstrappers"],
        ),
    )


class TestCSRControllers:
    def test_approve_then_sign(self, cluster):
        api, cs, factory, start = cluster
        ca = CertificateAuthority()
        start(CSRApprovingController(cs, factory),
              CSRSigningController(cs, factory, ca=ca))
        cs.resource("certificatesigningrequests").create(_bootstrap_csr())

        def issued():
            csr = cs.resource("certificatesigningrequests").get("node-csr-w0")
            return bool(csr.status.certificate)

        assert wait_until(issued), "CSR was not approved+signed"
        csr = cs.resource("certificatesigningrequests").get("node-csr-w0")
        assert certsapi.has_condition(csr, certsapi.APPROVED)
        import json

        rec = json.loads(csr.status.certificate)
        assert rec["commonName"] == "system:node:w0"
        # the issued record verifies against the same CA
        from kubernetes_tpu.kubeadm import Certificate

        assert ca.verify(Certificate(
            common_name=rec["commonName"],
            organizations=rec["organizations"],
            not_after=rec["notAfter"], signature=rec["signature"],
        ))

    def test_authenticated_requester_identity_is_stamped(self):
        """spec.username/groups come from the AUTHENTICATED requester
        (certificates types.go:89-99), so an ordinary user cannot assert
        a bootstrap identity in the body and mint auto-approved node
        credentials (identity-hijack guard)."""
        from kubernetes_tpu.apiserver.auth import SecureAPIServer
        from kubernetes_tpu.apiserver.requestcontext import request_user
        from kubernetes_tpu.apiserver.auth import UserInfo

        secure = SecureAPIServer(APIServer())
        csr = _bootstrap_csr(name="spoofed")
        csr.spec.username = "system:bootstrap:abcdef"  # attacker-asserted
        with request_user(UserInfo(name="mallory", groups=("devs",))):
            created = secure.api.create("certificatesigningrequests", csr)
        assert created.spec.username == "mallory"
        assert created.spec.groups == ["devs"]
        assert CSRApprovingController._recognize(created) is None

    def test_create_drops_caller_supplied_status(self):
        """A CSR created WITH a forged Approved condition must reach the
        store with an empty status — else the signer would mint
        credentials no approver granted."""
        api = APIServer()
        csr = _bootstrap_csr(name="forged")
        csr.status.conditions = [certsapi.CertificateSigningRequestCondition(
            type=certsapi.APPROVED, reason="Forged")]
        created = api.create("certificatesigningrequests", csr)
        assert not (created.status.conditions or [])
        assert not certsapi.has_condition(created, certsapi.APPROVED)

    def test_authenticated_update_cannot_rewrite_spec(self):
        """spec is immutable post-create for authenticated callers: a
        user with update rights must not be able to swap in a bootstrap
        username after the fact."""
        from kubernetes_tpu.apiserver.requestcontext import request_user
        from kubernetes_tpu.apiserver.auth import UserInfo

        api = APIServer()
        with request_user(UserInfo(name="mallory", groups=("devs",))):
            created = api.create(
                "certificatesigningrequests", _bootstrap_csr(name="mut"))
            created.spec.username = "system:bootstrap:abcdef"
            created.spec.groups = ["system:bootstrappers"]
            updated = api.update("certificatesigningrequests", created)
        assert updated.spec.username == "mallory"
        assert updated.spec.groups == ["devs"]

    def test_malformed_request_marks_failed_not_wedged(self, cluster):
        """Non-JSON spec.request must not wedge the signer in a requeue
        loop: it gets a Failed condition (approver simply ignores it)."""
        api, cs, factory, start = cluster
        ca = CertificateAuthority()
        start(CSRApprovingController(cs, factory),
              CSRSigningController(cs, factory, ca=ca))
        bad = _bootstrap_csr(name="garbled")
        bad.spec.request = "not-json"
        created = cs.resource("certificatesigningrequests").create(bad)
        # approve it manually so the signer actually looks at it
        created.status.conditions = [
            certsapi.CertificateSigningRequestCondition(
                type=certsapi.APPROVED, reason="Manual")]
        cs.resource("certificatesigningrequests").update_status(created)

        def failed():
            cur = cs.resource("certificatesigningrequests").get("garbled")
            return certsapi.has_condition(cur, certsapi.FAILED)

        assert wait_until(failed), "malformed CSR not marked Failed"
        cur = cs.resource("certificatesigningrequests").get("garbled")
        assert not cur.status.certificate

    def test_join_refuses_foreign_csr(self):
        """join(via_csr=True) must not adopt a pre-existing CSR for a
        different identity (credential-harvest guard)."""
        from kubernetes_tpu import kubeadm
        from kubernetes_tpu.apiserver.auth import SecureAPIServer

        secure = SecureAPIServer(APIServer())
        ctx = kubeadm.init(secure, node_name="cp-0")
        foreign = _bootstrap_csr(name="node-csr-victim", node="attacker")
        secure.api.create("certificatesigningrequests", foreign)
        with pytest.raises(kubeadm.InvalidToken, match="different identity"):
            kubeadm.join(ctx, "victim", via_csr=True, csr_timeout=2.0)

    def test_non_bootstrap_csr_not_auto_approved(self, cluster):
        api, cs, factory, start = cluster
        start(CSRApprovingController(cs, factory))
        csr = _bootstrap_csr(name="rogue")
        csr.spec.username = "random-user"
        csr.spec.groups = []
        cs.resource("certificatesigningrequests").create(csr)
        time.sleep(0.5)
        cur = cs.resource("certificatesigningrequests").get("rogue")
        assert not certsapi.has_condition(cur, certsapi.APPROVED)

    def test_cleaner_removes_stale(self, cluster):
        api, cs, factory, start = cluster
        old = _bootstrap_csr(name="stale")
        created = cs.resource("certificatesigningrequests").create(old)
        # age it: creation_timestamp in the past beyond the pending TTL
        created.metadata.creation_timestamp = time.time() - 100000
        cs.resource("certificatesigningrequests").update(created)
        start(CSRCleanerController(cs, factory, sync_period=0.2))

        def gone():
            try:
                cs.resource("certificatesigningrequests").get("stale")
                return False
            except NotFound:
                return True

        assert wait_until(gone), "stale CSR not cleaned"


class TestBootstrapControllers:
    def _token_secret(self, tid="abcdef", tsec="0123456789abcdef",
                      expired=False):
        return v1.Secret(
            metadata=v1.ObjectMeta(
                name=f"bootstrap-token-{tid}", namespace="kube-system"),
            type="bootstrap.kubernetes.io/token",
            data={
                "token-id": tid, "token-secret": tsec,
                "expiration": str(
                    time.time() + (-10 if expired else 3600)),
                "usage-bootstrap-authentication": "true",
                "usage-bootstrap-signing": "true",
            },
        )

    def test_signer_signs_cluster_info(self, cluster):
        api, cs, factory, start = cluster
        cs.configmaps.create(v1.ConfigMap(
            metadata=v1.ObjectMeta(name="cluster-info",
                                   namespace="kube-public"),
            data={"kubeconfig": "cluster=test;ca=sha256:deadbeef"},
        ))
        cs.secrets.create(self._token_secret())
        start(BootstrapSignerController(cs, factory))

        def signed():
            cm = cs.configmaps.get("cluster-info", "kube-public")
            return "jws-kubeconfig-abcdef" in (cm.data or {})

        assert wait_until(signed)
        cm = cs.configmaps.get("cluster-info", "kube-public")
        assert cm.data["jws-kubeconfig-abcdef"] == sign_kubeconfig(
            cm.data["kubeconfig"], "abcdef", "0123456789abcdef")

    def test_signer_removes_stale_signature(self, cluster):
        api, cs, factory, start = cluster
        cs.configmaps.create(v1.ConfigMap(
            metadata=v1.ObjectMeta(name="cluster-info",
                                   namespace="kube-public"),
            data={"kubeconfig": "x", "jws-kubeconfig-zzzzzz": "stale"},
        ))
        start(BootstrapSignerController(cs, factory))

        def unsigned():
            cm = cs.configmaps.get("cluster-info", "kube-public")
            return "jws-kubeconfig-zzzzzz" not in (cm.data or {})

        assert wait_until(unsigned)

    def test_token_cleaner(self, cluster):
        api, cs, factory, start = cluster
        cs.secrets.create(self._token_secret(tid="dead00", expired=True))
        cs.secrets.create(self._token_secret(tid="live00"))
        start(TokenCleanerController(cs, factory, sync_period=0.2))

        def cleaned():
            try:
                cs.secrets.get("bootstrap-token-dead00", "kube-system")
                return False
            except NotFound:
                return True

        assert wait_until(cleaned)
        assert cs.secrets.get("bootstrap-token-live00", "kube-system")


class TestClusterRoleAggregation:
    def test_union_and_update(self, cluster):
        api, cs, factory, start = cluster
        cs.resource("clusterroles").create(rbac.ClusterRole(
            metadata=v1.ObjectMeta(name="admin"),
            aggregation_rule=rbac.AggregationRule(
                cluster_role_selectors=[
                    {"rbac.example/aggregate-to-admin": "true"}]),
        ))
        cs.resource("clusterroles").create(rbac.ClusterRole(
            metadata=v1.ObjectMeta(
                name="edit-pods",
                labels={"rbac.example/aggregate-to-admin": "true"}),
            rules=[rbac.PolicyRule(verbs=["get", "update"],
                                   resources=["pods"])],
        ))
        start(ClusterRoleAggregationController(cs, factory))

        def aggregated():
            role = cs.resource("clusterroles").get("admin")
            return any("pods" in (r.resources or []) for r in role.rules or [])

        assert wait_until(aggregated)
        # a new matching role extends the union
        cs.resource("clusterroles").create(rbac.ClusterRole(
            metadata=v1.ObjectMeta(
                name="view-secrets",
                labels={"rbac.example/aggregate-to-admin": "true"}),
            rules=[rbac.PolicyRule(verbs=["list"], resources=["secrets"])],
        ))

        def extended():
            role = cs.resource("clusterroles").get("admin")
            return any("secrets" in (r.resources or [])
                       for r in role.rules or [])

        assert wait_until(extended)


class TestEndpointSliceMirroring:
    def test_mirrors_custom_endpoints(self, cluster):
        api, cs, factory, start = cluster
        # selector-less Service + hand-made Endpoints = mirrorable
        cs.services.create(v1.Service(
            metadata=v1.ObjectMeta(name="ext", namespace="default"),
            spec=v1.ServiceSpec(selector=None),
        ))
        cs.endpoints.create(v1.Endpoints(
            metadata=v1.ObjectMeta(name="ext", namespace="default"),
            subsets=[v1.EndpointSubset(
                addresses=[v1.EndpointAddress(ip="10.0.0.9")],
                ports=[v1.EndpointPort(name="http", port=80)],
            )],
        ))
        start(EndpointSliceMirroringController(cs, factory))

        def mirrored():
            slices, _ = cs.resource("endpointslices").list(
                namespace="default")
            return any(
                (s.metadata.labels or {}).get(MANAGED_BY_LABEL) == MANAGED_BY
                and (s.metadata.labels or {}).get(
                    discovery.LABEL_SERVICE_NAME) == "ext"
                and s.endpoints and s.endpoints[0].addresses == ["10.0.0.9"]
                for s in slices
            )

        assert wait_until(mirrored)

    def test_selector_service_not_mirrored(self, cluster):
        api, cs, factory, start = cluster
        cs.services.create(v1.Service(
            metadata=v1.ObjectMeta(name="sel", namespace="default"),
            spec=v1.ServiceSpec(selector={"app": "x"}),
        ))
        cs.endpoints.create(v1.Endpoints(
            metadata=v1.ObjectMeta(name="sel", namespace="default"),
            subsets=[v1.EndpointSubset(
                addresses=[v1.EndpointAddress(ip="10.0.0.1")])],
        ))
        start(EndpointSliceMirroringController(cs, factory))
        time.sleep(0.5)
        slices, _ = cs.resource("endpointslices").list(namespace="default")
        assert not any(
            (s.metadata.labels or {}).get(MANAGED_BY_LABEL) == MANAGED_BY
            for s in slices
        )


class TestEphemeralVolume:
    def test_creates_owned_pvc(self, cluster):
        api, cs, factory, start = cluster
        pod = make_pod("eph-pod")
        pod.spec.volumes = [v1.Volume(
            name="scratch",
            source={"ephemeral": {"volumeClaimTemplate": {"spec": {
                "accessModes": ["ReadWriteOnce"],
                "resources": {"requests": {"storage": "1Gi"}},
                "storageClassName": "standard",
            }}}},
        )]
        created = cs.pods.create(pod)
        start(EphemeralVolumeController(cs, factory))

        def pvc_exists():
            try:
                pvc = cs.persistentvolumeclaims.get(
                    "eph-pod-scratch", "default")
            except NotFound:
                return False
            refs = pvc.metadata.owner_references or []
            return any(r.uid == created.metadata.uid and r.controller
                       for r in refs)

        assert wait_until(pvc_exists)
        pvc = cs.persistentvolumeclaims.get("eph-pod-scratch", "default")
        assert (pvc.spec.resources.requests or {}).get("storage") == "1Gi"


class TestExpandController:
    def test_expands_bound_pvc(self, cluster):
        api, cs, factory, start = cluster
        cs.storageclasses.create(storage.StorageClass(
            metadata=v1.ObjectMeta(name="exp"),
            allow_volume_expansion=True,
        ))
        cs.persistentvolumes.create(v1.PersistentVolume(
            metadata=v1.ObjectMeta(name="pv-1"),
            spec=v1.PersistentVolumeSpec(
                capacity={"storage": "1Gi"}, storage_class_name="exp"),
        ))
        pvc = v1.PersistentVolumeClaim(
            metadata=v1.ObjectMeta(name="data", namespace="default"),
            spec=v1.PersistentVolumeClaimSpec(
                resources=v1.ResourceRequirements(
                    requests={"storage": "2Gi"}),
                storage_class_name="exp", volume_name="pv-1",
            ),
        )
        pvc.status.phase = "Bound"
        pvc.status.capacity = {"storage": "1Gi"}
        cs.persistentvolumeclaims.create(pvc)
        start(ExpandController(cs, factory))

        def expanded():
            pv = cs.persistentvolumes.get("pv-1")
            claim = cs.persistentvolumeclaims.get("data", "default")
            return ((pv.spec.capacity or {}).get("storage") == "2Gi"
                    and (claim.status.capacity or {}).get("storage") == "2Gi")

        assert wait_until(expanded)

    def test_no_expansion_without_storageclass_permission(self, cluster):
        api, cs, factory, start = cluster
        cs.storageclasses.create(storage.StorageClass(
            metadata=v1.ObjectMeta(name="fixed"),
            allow_volume_expansion=False,
        ))
        cs.persistentvolumes.create(v1.PersistentVolume(
            metadata=v1.ObjectMeta(name="pv-2"),
            spec=v1.PersistentVolumeSpec(capacity={"storage": "1Gi"}),
        ))
        pvc = v1.PersistentVolumeClaim(
            metadata=v1.ObjectMeta(name="fixed-data", namespace="default"),
            spec=v1.PersistentVolumeClaimSpec(
                resources=v1.ResourceRequirements(
                    requests={"storage": "2Gi"}),
                storage_class_name="fixed", volume_name="pv-2",
            ),
        )
        pvc.status.phase = "Bound"
        pvc.status.capacity = {"storage": "1Gi"}
        cs.persistentvolumeclaims.create(pvc)
        start(ExpandController(cs, factory))
        time.sleep(0.5)
        pv = cs.persistentvolumes.get("pv-2")
        assert (pv.spec.capacity or {}).get("storage") == "1Gi"


class TestRootCAPublisher:
    def test_publishes_to_every_namespace(self, cluster):
        api, cs, factory, start = cluster
        cs.namespaces.create(v1.Namespace(
            metadata=v1.ObjectMeta(name="team-a")))
        start(RootCACertPublisher(cs, factory, root_ca="sha256:rootca"))

        def published():
            try:
                cm = cs.configmaps.get(ROOT_CA_CONFIGMAP, "team-a")
            except NotFound:
                return False
            return (cm.data or {}).get("ca.crt") == "sha256:rootca"

        assert wait_until(published)
        # tampering is reverted
        cm = cs.configmaps.get(ROOT_CA_CONFIGMAP, "team-a")
        cm.data = {"ca.crt": "tampered"}
        cs.configmaps.update(cm)

        def reverted():
            cur = cs.configmaps.get(ROOT_CA_CONFIGMAP, "team-a")
            return (cur.data or {}).get("ca.crt") == "sha256:rootca"

        assert wait_until(reverted)


class TestKubeadmJoinViaCSR:
    def test_join_through_csr_approval(self):
        from kubernetes_tpu import kubeadm
        from kubernetes_tpu.apiserver.auth import SecureAPIServer

        secure = SecureAPIServer(APIServer())
        ctx = kubeadm.init(secure, node_name="cp-0")
        cs = Clientset(secure.api)
        factory = SharedInformerFactory(cs)
        approver = CSRApprovingController(cs, factory)
        signer = CSRSigningController(cs, factory, ca=ctx.ca)
        factory.start()
        assert factory.wait_for_cache_sync()
        approver.run()
        signer.run()
        try:
            cert = kubeadm.join(ctx, "worker-9", via_csr=True,
                                csr_timeout=10.0)
            assert cert.common_name == "system:node:worker-9"
            assert ctx.ca.verify(cert)
            # the CSR object records the whole flow
            csr = secure.api.get(
                "certificatesigningrequests", "node-csr-worker-9")
            assert certsapi.has_condition(csr, certsapi.APPROVED)
            assert csr.status.certificate
        finally:
            approver.stop()
            signer.stop()
            factory.stop()
