"""Crash-recoverable control plane: WAL+snapshot durable store, restart-
surviving watches, and the supervised controller manager.

Reference shapes: etcd's WAL/snapshot cycle (server/storage/wal, snap)
behind the apiserver's storage.Interface — replay must reproduce the
exact revisioned state acknowledged before the crash — and
kube-controller-manager's crash-and-restart HA model, narrowed to
per-loop supervision (controllers/manager.Supervisor).
"""

import os
import random
import threading
import time

import pytest

from kubernetes_tpu.store import kv, wal
from kubernetes_tpu.store.kv import DurableKVStore

from .util import wait_until


def state_of(store):
    items, rev = store.list("")
    return (
        rev,
        store.compacted_revision,
        [(i.key, i.value, i.create_revision, i.mod_revision) for i in items],
    )


def history_of(store):
    inner = getattr(store, "_inner", store)
    return list(inner._history)


def apply_random_op(store, rng, keys, i):
    """One random create/update/delete/compact; returns the outcome token
    (revision or exception class name) so two stores can be compared."""
    op = rng.random()
    key = rng.choice(keys)
    try:
        if op < 0.45:
            return store.create(key, {"i": i})
        if op < 0.75:
            return store.update(key, {"i": i, "u": True})
        if op < 0.92:
            return store.delete(key)
        store.compact(rng.randrange(0, store.revision + 1))
        return "compacted"
    except kv.StoreError as e:
        return type(e).__name__


class TestWalReplayParity:
    """Any interleaving of create/update/delete/compact followed by
    crash+recover() reproduces the exact (rev, compacted_rev,
    list(prefix)) state — and the retained event history with it."""

    @pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
    def test_random_interleavings(self, tmp_path, seed):
        rng = random.Random(seed)
        durable = DurableKVStore(
            str(tmp_path / "db"), history_limit=25, snapshot_every=13
        )
        shadow = kv.KVStore(history_limit=25)
        keys = [f"/registry/pods/ns/{i}" for i in range(9)]
        for i in range(rng.randrange(50, 150)):
            op_rng = random.Random((seed, i).__hash__())
            out_d = apply_random_op(durable, op_rng, keys, i)
            op_rng = random.Random((seed, i).__hash__())
            out_s = apply_random_op(shadow, op_rng, keys, i)
            assert out_d == out_s
        # fresh-process recovery (the restarted apiserver)
        recovered = DurableKVStore.recover(str(tmp_path / "db"), history_limit=25)
        assert state_of(recovered) == state_of(shadow)
        assert history_of(recovered) == history_of(shadow)
        # in-place crash (SIGKILL-equivalent, fsync'd so nothing is lost)
        durable.crash(torn=bool(seed % 2))
        assert state_of(durable) == state_of(shadow)
        assert history_of(durable) == history_of(shadow)

    def test_recovery_is_idempotent(self, tmp_path):
        path = str(tmp_path / "db")
        d = DurableKVStore(path, snapshot_every=5)
        for i in range(12):
            d.create(f"/k{i}", i)
        d.delete("/k3")
        once = DurableKVStore.recover(path)
        twice = DurableKVStore.recover(path)
        assert state_of(once) == state_of(twice) == state_of(d)
        assert history_of(once) == history_of(twice)

    def test_truncated_tail_is_dropped_and_healed(self, tmp_path):
        path = str(tmp_path / "db")
        d = DurableKVStore(path, snapshot_every=10_000)
        d.create("/a", {"v": 1})
        d.create("/b", {"v": 2})
        d.close()
        # a half-written record at the tail (the crash's own write)
        with open(os.path.join(path, "wal.log"), "ab") as f:
            rec = wal.encode_record(wal.Record(wal.OP_CREATE, "/c", {"v": 3}, 3, 0))
            f.write(rec[: len(rec) - 7])
        r = DurableKVStore.recover(path)
        assert r.revision == 2
        with pytest.raises(kv.KeyNotFound):
            r.get("/c")
        # the torn bytes were truncated: the next write lands on a clean
        # record boundary and survives another recovery
        r.create("/c", {"v": 3})
        r.close()
        again = DurableKVStore.recover(path)
        assert again.get("/c").value == {"v": 3} and again.revision == 3

    def test_unsynced_tail_is_lost_like_a_power_cut(self, tmp_path):
        d = DurableKVStore(str(tmp_path / "db"), fsync=False)
        d.create("/a", 1)
        d.create("/b", 2)
        d.sync()  # durability watermark: everything above survives
        d.create("/c", 3)
        d.crash()
        assert d.revision == 2
        with pytest.raises(kv.KeyNotFound):
            d.get("/c")
        # and the store keeps working: revisions resume from the recovered
        # point, exactly as etcd would after losing its page cache
        assert d.create("/c2", 4) == 3

    def test_snapshot_rotation_bounds_the_wal(self, tmp_path):
        path = str(tmp_path / "db")
        d = DurableKVStore(path, history_limit=10, snapshot_every=5)
        for i in range(37):
            d.create(f"/k{i:02d}", {"i": i})
        assert os.path.exists(os.path.join(path, "snapshot.db"))
        # the WAL holds only the records that rebuild the retained history,
        # not all 37 writes
        records, _ = wal.read_wal(os.path.join(path, "wal.log"))
        assert len(records) <= 10 + 5
        recovered = DurableKVStore.recover(path, history_limit=10)
        assert state_of(recovered) == state_of(d)
        assert history_of(recovered) == history_of(d)


class TestRestartSurvivingWatches:
    def test_watches_die_closed_and_resume_from_recovered_revision(self, tmp_path):
        d = DurableKVStore(str(tmp_path / "db"))
        d.create("/a", 1)
        w = d.watch("/")
        d.crash()
        # the crash killed the stream — the reflector's re-list signal
        assert w.closed and w.poll(timeout=0.05) is None
        # a new watch from the recovered revision sees new events only
        w2 = d.watch("/", since_revision=d.revision)
        d.create("/b", 2)
        ev = w2.poll(timeout=1)
        assert ev.key == "/b" and ev.revision == 2
        w2.stop()

    def test_compacted_still_raises_after_recovery(self, tmp_path):
        path = str(tmp_path / "db")
        d = DurableKVStore(path, history_limit=100)
        for i in range(10):
            d.create(f"/k{i}", i)
        d.compact(6)
        d.crash()
        with pytest.raises(kv.Compacted):
            d.watch("/", since_revision=3)
        w = d.watch("/", since_revision=8)
        assert w.poll(timeout=1).revision == 9
        w.stop()
        recovered = DurableKVStore.recover(path, history_limit=100)
        with pytest.raises(kv.Compacted):
            recovered.watch("/", since_revision=3)

    def test_compacted_is_410_gone_on_the_wire(self):
        """PR 1's wire contract: a watch below the compaction floor serves
        410/Compacted and the remote client rebuilds kv.Compacted, which
        is what drives the remote reflector's re-list."""
        from kubernetes_tpu.apiserver.http import HTTPAPIServer, RemoteAPIServer
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.api import types as v1

        api = APIServer(store=kv.KVStore(history_limit=5))
        hub = HTTPAPIServer(api).start()
        try:
            for i in range(12):
                api.create(
                    "configmaps",
                    v1.ConfigMap(
                        metadata=v1.ObjectMeta(name=f"c{i}", namespace="default")
                    ),
                )
            remote = RemoteAPIServer(hub.address)
            with pytest.raises(kv.Compacted):
                remote.watch("configmaps", since_revision=1)
        finally:
            hub.stop()

    def test_informer_relists_across_apiserver_crash(self, tmp_path):
        """The reflector contract end-to-end, in-proc: a crash kills the
        watch, the informer re-lists against the recovered revision, and
        acknowledged writes are all still there."""
        from kubernetes_tpu.api import types as v1
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.client.clientset import Clientset
        from kubernetes_tpu.client.informer import SharedInformerFactory

        store = DurableKVStore(str(tmp_path / "db"))
        api = APIServer(store=store)
        cs = Clientset(api)
        factory = SharedInformerFactory(cs)
        informer = factory.informer_for("configmaps")
        factory.start()
        assert factory.wait_for_cache_sync()
        try:
            acked = []
            for i in range(8):
                cs.resource("configmaps").create(
                    v1.ConfigMap(
                        metadata=v1.ObjectMeta(name=f"cm-{i}", namespace="default")
                    )
                )
                acked.append(f"default/cm-{i}")
            store.crash(torn=True)
            for i in range(8, 12):
                cs.resource("configmaps").create(
                    v1.ConfigMap(
                        metadata=v1.ObjectMeta(name=f"cm-{i}", namespace="default")
                    )
                )
                acked.append(f"default/cm-{i}")
            assert wait_until(
                lambda: all(informer.get(k) is not None for k in acked), timeout=10
            ), sorted(set(acked) - {k for k in acked if informer.get(k)})
        finally:
            factory.stop()


class _SteadyController:
    """Minimal long-lived loop (the healthy neighbor)."""

    name = "steady"

    def __init__(self):
        self._stop = threading.Event()
        self._thread = None

    def run(self):
        self._thread = threading.Thread(target=self._stop.wait, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()


class _PoisonedController:
    """Raises on every resync: its loop thread dies instantly, every
    time — the supervisor must keep restarting it, not the manager."""

    name = "poisoned"

    def __init__(self):
        self._thread = None

    def run(self):
        self._thread = threading.Thread(target=self._resync, daemon=True)
        self._thread.start()

    def _resync(self):
        raise RuntimeError("poisoned resync")

    def stop(self):
        pass


@pytest.mark.filterwarnings(
    # the poisoned loop's thread dies raising ON PURPOSE every restart
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
class TestSupervisor:
    def test_poisoned_controller_restarts_capped_while_others_run(self, capsys):
        from kubernetes_tpu.api.metrics import controller_restarts_total
        from kubernetes_tpu.controllers.manager import Supervisor

        # cap == base: with the cap honored the poisoned loop restarts on
        # a fixed beat; pure doubling would manage only ~5 restarts here
        sup = Supervisor(
            base_backoff=0.05, max_backoff=0.05, jitter=0.0, probe_period=0.01
        )
        steady = _SteadyController()
        sup.supervise("steady", steady, factory=_SteadyController)
        sup.supervise("poisoned", _PoisonedController(), factory=_PoisonedController)
        sup.start()
        try:
            assert wait_until(lambda: sup.restart_count("poisoned") >= 8, timeout=5)
            assert sup.restart_count("steady") == 0
            assert sup.running("steady")
            assert steady._thread.is_alive()
            assert controller_restarts_total.value(controller="poisoned") >= 8
        finally:
            sup.stop()
        capsys.readouterr()  # swallow the poisoned loop's tracebacks

    def test_manager_restarts_crashed_loop_fresh_instance(self):
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.client.clientset import Clientset
        from kubernetes_tpu.controllers.manager import ControllerManager

        api = APIServer()
        cs = Clientset(api)
        m = ControllerManager(
            cs,
            controllers=["replicaset", "podgc"],
            supervisor_opts=dict(base_backoff=0.05, probe_period=0.02),
        )
        m.run(wait_sync=5)
        try:
            old = m.controllers["replicaset"]
            handlers_before = {
                res: len(inf.event_handlers())
                for res, inf in m.informers.informers().items()
            }
            m.supervisor.crash("replicaset")
            assert wait_until(
                lambda: m.supervisor.restart_count("replicaset") >= 1
                and m.supervisor.running("replicaset"),
                timeout=10,
            )
            assert m.controllers["replicaset"] is not old
            assert m.supervisor.restart_count("podgc") == 0
            # the dead instance's informer handlers were retired: the
            # rebuild replaces its fan-out instead of stacking a new one
            handlers_after = {
                res: len(inf.event_handlers())
                for res, inf in m.informers.informers().items()
            }
            assert handlers_after == handlers_before
        finally:
            m.stop()


class TestSatellites:
    def test_queue_shutdown_flushes_pending_and_joins_timer(self):
        from kubernetes_tpu.client.workqueue import RateLimitingQueue

        q = RateLimitingQueue()
        q.add_after("deferred", 60.0)  # far future: would park the timer
        assert q._timer.is_alive()
        q.shutdown()
        # the pending delay heap is flushed (a stopping loop's retries die
        # with it) and consumers see a prompt shutdown, not a 60s park
        item, shutdown = q.get(timeout=0.5)
        assert item is None and shutdown
        assert not q._waiting
        # the drain timer was cancelled — no leaked parked thread
        assert wait_until(lambda: not q._timer.is_alive(), timeout=2)

    def test_stopped_leader_releases_lease_for_immediate_failover(self):
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.client.clientset import Clientset
        from kubernetes_tpu.client.leaderelection import (
            LeaderElectionConfig,
            LeaderElector,
        )

        api = APIServer()
        cs = Clientset(api)
        # LONG lease: without the release, the successor waits out all 30s
        cfg = dict(lease_duration=30.0, renew_deadline=20.0, retry_period=0.2)
        ea = LeaderElector(
            cs, LeaderElectionConfig(identity="a", **cfg),
            lambda: None, lambda: None,
        )
        ea.start()
        assert ea.is_leader.wait(5)
        eb = LeaderElector(
            cs, LeaderElectionConfig(identity="b", **cfg),
            lambda: None, lambda: None,
        )
        eb.start()
        try:
            time.sleep(0.5)
            assert not eb.is_leader.is_set(), "b must not steal a live lease"
            ea.stop()  # graceful handoff: releases instead of expiring
            assert eb.is_leader.wait(5), "successor should acquire immediately"
            assert eb.leader_identity == "b"
        finally:
            eb.stop()


class TestCrashDrillCycle:
    """The tier-1 crash/recover cycle: kill the control plane mid-churn,
    assert zero lost acknowledged writes and workload re-convergence."""

    def test_cluster_survives_apiserver_and_controller_crashes(self, tmp_path):
        from kubernetes_tpu.cluster import Cluster
        from kubernetes_tpu.testing.chaos import ChaosMonkey

        from .util import make_pod

        with Cluster(
            n_nodes=2,
            durable_path=str(tmp_path / "db"),
            scheduler_backend="oracle",
            controllers=["replicaset", "deployment", "nodelifecycle"],
            controller_opts={
                "node_monitor_period": 0.3,
                "node_monitor_grace_period": 2.0,
                "supervisor_opts": dict(base_backoff=0.05, probe_period=0.02),
            },
        ) as c:
            from kubernetes_tpu.api import apps, types as v1

            c.client.resource("deployments").create(
                apps.Deployment(
                    metadata=v1.ObjectMeta(name="ha", namespace="default"),
                    spec=apps.DeploymentSpec(
                        replicas=3,
                        selector=v1.LabelSelector(match_labels={"app": "ha"}),
                        template=apps.PodTemplateSpec(
                            metadata=v1.ObjectMeta(labels={"app": "ha"}),
                            spec=make_pod("t", cpu="10m").spec,
                        ),
                    ),
                )
            )

            def n_running():
                pods, _ = c.client.pods.list(namespace="default")
                return sum(1 for p in pods if p.status.phase == "Running")

            assert wait_until(lambda: n_running() == 3, timeout=30)

            monkey = ChaosMonkey(
                c, rng=random.Random(7),
                disruptions=["crash-apiserver", "crash-controller"],
            )
            acked = []
            cm = c.client.resource("configmaps")
            for i in range(6):
                from kubernetes_tpu.api import types as v1t

                cm.create(v1t.ConfigMap(
                    metadata=v1t.ObjectMeta(name=f"acked-{i}", namespace="default")
                ))
                acked.append(f"acked-{i}")
                if i in (2, 4):
                    assert monkey.do_one("crash-apiserver") is not None
            assert monkey.do_one("crash-controller") is not None
            monkey.restart_all_dead(timeout=15)

            # zero lost acknowledged writes
            names = {o.metadata.name for o in cm.list(namespace="default")[0]}
            assert set(acked) <= names, sorted(set(acked) - names)
            # informers re-listed and the workload re-converged
            assert wait_until(lambda: n_running() == 3, timeout=30)
            # the crashed controller came back under supervision
            sup = c.kcm.supervisor
            assert all(sup.running(n) for n in sup.names())
