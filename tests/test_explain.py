"""Decision explainability + shadow parity sentinel tests.

Covers scheduler/explain.py and the KTPU_EXPLAIN / KTPU_SHADOW_SAMPLE
surfaces end to end: randomized explain-vs-oracle attribution parity on
the hoisted session (per-plugin filter masks and weighted score
components must bit-match the framework's plugin outputs on CPU), the
off-switch overhead pin (explain-off / sample=0 is decision-inert and
launch-free, mirroring the KTPU_TRACE=0 pin), a sentinel drill that
injects a score-weight perturbation and asserts drift is counted by
plugin + ring-dumped + bundled + replayable, the triage CLIs, and the
/metricsz Prometheus exposition on the apiserver debug surface.
"""

from __future__ import annotations

import json
import os
import random
import re
import sys
import time
import urllib.request

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import Clientset, SharedInformerFactory
from kubernetes_tpu.ops.hoisted import HoistedSession
from kubernetes_tpu.scheduler import explain, metrics
from kubernetes_tpu.scheduler.framework.snapshot import Snapshot
from kubernetes_tpu.scheduler.internal.cache import SchedulerCache
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.scheduler.tpu_backend import DEFAULT_WEIGHTS, TPUBackend
from kubernetes_tpu.utils import tracing

from .test_kernel_parity import random_cluster, random_pending
from .util import make_node, make_pod, spread_constraint

SCRIPTS_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")


def _counter_total(counter) -> float:
    return sum(val for _, val in counter.items())


def _label_counts(counter):
    out = {}
    for key, val in counter.items():
        slug = key[0] if key else "-"
        out[slug] = out.get(slug, 0) + int(val)
    return out


def _prefilter_rejected(oracle_bd) -> bool:
    """True when the oracle breakdown carries the PreFilter-rejection
    shape (one failing plugin per node instead of full verdict rows)."""
    return any(len(v) == 1 for v in oracle_bd["filters"].values())


# -- explain-vs-oracle attribution parity -----------------------------------


@pytest.mark.parametrize("seed", range(8))
def test_randomized_attribution_parity(seed):
    """device_breakdown (fused kernel, per-plugin mask/score decode) must
    bit-match oracle_breakdown (every filter plugin run on every node, the
    real score runners) on randomized clusters: same per-plugin verdicts
    on the shared plugins, identical weighted score components and totals
    for every feasible node."""
    rng = random.Random(seed)
    nodes, pods = random_cluster(rng)
    for trial in range(2):
        pending = random_pending(rng)
        snap = Snapshot.from_objects(list(pods), list(nodes))
        oracle_bd = explain.oracle_breakdown(snap, pending)
        device_bd = explain.device_breakdown(nodes, pods, pending)
        ctx = f"seed={seed} trial={trial}"
        if _prefilter_rejected(oracle_bd):
            assert device_bd["totals"] == {}, (
                f"{ctx}: oracle PreFilter rejected the pod but the device "
                f"found feasible nodes {device_bd['totals']}")
            continue
        diff = explain.attribution_diff(oracle_bd, device_bd)
        assert diff == [], (
            f"{ctx}: per-plugin attribution drifted: {diff}\n"
            + (explain.diff_table(oracle_bd, device_bd,
                                  device_bd["decision"])
               if device_bd["decision"] else ""))
        # totals carry the weighted sum: keyset equality pins that the
        # oracle-only volume plugins (no device names) were all neutral
        # on these volume-free pods
        assert oracle_bd["totals"] == device_bd["totals"], ctx
        assert sorted(oracle_bd["best"]) == sorted(device_bd["best"]), ctx


@pytest.mark.parametrize("seed", [0, 3])
def test_session_explain_payload_matches_oracle(seed):
    """The HOISTED SESSION's explain payload (packed mask bits + top-k
    score stacks harvested from the device) must decode to the same
    per-plugin attribution the oracle computes — this is the production
    harvest path, not the standalone replay kernel."""
    rng = random.Random(seed + 40)
    nodes, pods = random_cluster(rng)
    cache = SchedulerCache()
    be = TPUBackend()
    cache.add_listener(be)
    for node in nodes:
        cache.add_node(node)
    for p in pods:
        cache.add_pod(p)
    be.enc.reserve(pods=256)
    be.enc.device_state()  # build vocabs before encoding pending pods
    for trial in range(2):
        pending = random_pending(rng)
        arrays = {k: val for k, val in be.pe.encode(pending).items()
                  if not k.startswith("_")}
        cluster = be.enc.device_state()
        sess = HoistedSession(cluster, [arrays], be.weights, explain_k=3)
        assert sess.supports_explain and sess.explain_k == 3
        ys = sess.schedule([arrays])
        payloads = HoistedSession.explain_payload(ys)
        assert payloads is not None and len(payloads) == 1
        names = [None] * len(be.enc.node_index)
        for name, idx in be.enc.node_index.items():
            names[idx] = name
        device_bd = explain.payload_breakdown(payloads[0], names)
        oracle_bd = explain.oracle_breakdown(
            Snapshot.from_objects(list(pods), list(nodes)), pending)
        ctx = f"seed={seed} trial={trial}"
        if _prefilter_rejected(oracle_bd):
            assert device_bd["totals"] == {}, ctx
            continue
        # masks cover every node; scores cover the top-k the device
        # shipped — attribution_diff restricts to exactly that
        assert explain.attribution_diff(oracle_bd, device_bd) == [], ctx
        for name, total in device_bd["totals"].items():
            assert oracle_bd["totals"].get(name) == total, ctx
        if device_bd["totals"]:
            assert max(device_bd["totals"].values()) == \
                max(oracle_bd["totals"].values()), ctx


# -- overhead pin: explain-off / sample=0 is inert --------------------------


def _mini_backend(n_nodes=5):
    cache = SchedulerCache()
    be = TPUBackend()
    cache.add_listener(be)
    for i in range(n_nodes):
        cache.add_node(make_node(
            f"node-{i}", cpu=str(4 + (i % 3) * 2), memory="16Gi", pods=64,
            labels={v1.LABEL_HOSTNAME: f"node-{i}", "zone": f"z{i % 3}"},
        ))
    be.enc.reserve(pods=256)
    return cache, be


def _stream(n):
    return [
        make_pod(f"p-{i}", namespace="default", cpu="200m", memory="128Mi",
                 labels={"app": "spread"},
                 constraints=[spread_constraint(
                     1, "zone", "ScheduleAnyway", {"app": "spread"})])
        for i in range(n)
    ]


def test_explain_off_is_decision_inert_and_launch_free(monkeypatch):
    """Mirrors the KTPU_TRACE=0 pin: with KTPU_EXPLAIN unset and
    KTPU_SHADOW_SAMPLE=0 the session carries no explain arms (no expl
    keys in ys, no per-pod payload allocation, explain/shadow counters
    untouched) and turning explain ON changes no decision."""
    monkeypatch.delenv("KTPU_EXPLAIN", raising=False)
    monkeypatch.delenv("KTPU_SHADOW_SAMPLE", raising=False)
    harvests0 = _counter_total(metrics.explain_harvests)
    samples0 = _counter_total(metrics.shadow_samples)
    drift0 = _counter_total(metrics.parity_drift)

    _, off = _mini_backend()
    assert off.explain is False and off.shadow_sample == 0.0
    warm = off.schedule_many(_stream(4))
    assert off._session is not None
    assert off._session.explain_k == 0
    h = off.dispatch_many(_stream(3)[:3])
    assert h.ys is not None, "batch did not ride the session path"
    assert not any(k.startswith("expl") for k in h.ys), (
        f"explain-off session shipped explain arrays: "
        f"{[k for k in h.ys if k.startswith('expl')]}")
    off_results = off.harvest(h)
    assert h.explain is None, "explain-off harvest allocated a payload"
    assert _counter_total(metrics.explain_harvests) == harvests0
    assert _counter_total(metrics.shadow_samples) == samples0
    assert _counter_total(metrics.parity_drift) == drift0

    monkeypatch.setenv("KTPU_EXPLAIN", "1")
    _, on = _mini_backend()
    assert on.explain is True
    warm_on = on.schedule_many(_stream(4))
    assert on._session is not None and on._session.explain_k >= 1
    h2 = on.dispatch_many(_stream(3)[:3])
    assert h2.ys is not None and "expl_bits" in h2.ys
    on_results = on.harvest(h2)
    assert h2.explain is not None and len(h2.explain) == 3
    assert _counter_total(metrics.explain_harvests) > harvests0

    def nodes_of(results):
        return [node for _, node in results]

    assert nodes_of(warm) == nodes_of(warm_on)
    assert nodes_of(off_results) == nodes_of(on_results), (
        "explain mode changed scheduling decisions")


# -- sentinel drill: injected divergence -> counted, dumped, replayable -----


def _cluster(n_nodes):
    api = APIServer()
    cs = Clientset(api)
    for i in range(n_nodes):
        cs.nodes.create(make_node(
            f"node-{i}", cpu=str(4 + (i % 3) * 2), memory="16Gi", pods=64,
            labels={v1.LABEL_HOSTNAME: f"node-{i}", "zone": f"z{i % 3}"},
        ))
    return api, cs


def _drive(sched, cs, pods, batch=4):
    for p in pods:
        cs.pods.create(p)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if sched.queue.num_active() >= len(pods):
            break
        time.sleep(0.02)
    while True:
        info = sched.queue.pop(timeout=0.2)
        if info is None:
            break
        infos = [info]
        while len(infos) < batch:
            nxt = sched.queue.pop(timeout=0)
            if nxt is None:
                break
            infos.append(nxt)
        sched._schedule_batch_tpu(infos)
    assert sched._drain_pipeline(timeout=30)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with sched._inflight_lock:
            if sched._inflight == 0:
                return
        time.sleep(0.02)
    raise AssertionError("binder pool did not drain")


def _run_script(monkeypatch, name, argv):
    """Import a scripts/ CLI in-process and run its main() (a subprocess
    would pay the full jax import again)."""
    monkeypatch.syspath_prepend(SCRIPTS_DIR)
    mod = __import__(name)
    monkeypatch.setattr(sys, "argv", [f"{name}.py"] + list(argv))
    return mod.main()


def test_shadow_sentinel_drill(monkeypatch, tmp_path):
    """Inject a score-weight perturbation into a throwaway session and
    assert the full sentinel chain: drift counted per plugin, the flight
    recorder ring dumped through the shadow-drift seam, a repro bundle
    written — and the bundle replays to nonzero exit under
    scripts/replay_drift.py while scripts/explain_decision.py renders the
    decision end to end."""
    monkeypatch.setenv("KTPU_SHADOW_BUNDLE_DIR", str(tmp_path))
    old_level = tracing.set_level(max(tracing.level(), 1))
    _, cs = _cluster(5)
    factory = SharedInformerFactory(cs)
    sched = Scheduler(cs, factory, backend="tpu", pipeline_depth=2)
    factory.start()
    assert factory.wait_for_cache_sync()
    sched.tpu.set_shadow_sample(1.0)
    assert sched.tpu.shadow_sample == 1.0 and sched.tpu.explain
    samples0 = _counter_total(metrics.shadow_samples)
    drift_by_plugin0 = _label_counts(metrics.parity_drift)
    dumps0 = _counter_total(metrics.trace_dumps)
    ndumps0 = len(tracing.RECORDER.dump_history)
    try:
        # clean warm-up: sentinel samples everything, zero drift
        _drive(sched, cs, [
            make_pod(f"w-{i}", namespace="default", cpu="300m",
                     memory="128Mi", labels={"app": "x"})
            for i in range(4)
        ])
        assert _counter_total(metrics.shadow_samples) > samples0
        assert _label_counts(metrics.parity_drift) == drift_by_plugin0, (
            "clean warm-up produced parity drift")
        # inject: rebuild the session with a perturbed balanced-allocation
        # weight (rebind, never mutate — DEFAULT_WEIGHTS is shared)
        perturbed = dict(DEFAULT_WEIGHTS)
        perturbed["balanced"] = perturbed.get("balanced", 1) * 7
        sched.tpu.weights = perturbed
        sched.tpu._invalidate_session("drill-weights")
        _drive(sched, cs, [
            make_pod(f"d-{i}", namespace="default", cpu="300m",
                     memory="128Mi", labels={"app": "x"})
            for i in range(8)
        ])
    finally:
        sched.stop()
        factory.stop()
        tracing.set_level(old_level)

    drift = {
        k: val - drift_by_plugin0.get(k, 0)
        for k, val in _label_counts(metrics.parity_drift).items()
        if val - drift_by_plugin0.get(k, 0)
    }
    assert drift.get("NodeResourcesBalancedAllocation", 0) >= 1, (
        f"weight perturbation not attributed to the plugin: {drift}")
    assert _counter_total(metrics.trace_dumps) > dumps0
    seam_dumps = tracing.RECORDER.dump_history[ndumps0:]
    assert any(d["reason"] == "shadow-drift" for d in seam_dumps), (
        f"no shadow-drift ring dump: {[d['reason'] for d in seam_dumps]}")

    bundles = sorted(str(p) for p in tmp_path.glob("shadow-drift-*.json"))
    assert bundles, "sentinel wrote no repro bundle"
    b = explain.load_bundle(bundles[0])
    assert b["plugins"] and b["weights"]["balanced"] == perturbed["balanced"]
    # the bundle must REPRODUCE: replay_drift exits nonzero on it
    assert _run_script(monkeypatch, "replay_drift", [bundles[0]]) == 1
    # and the explain CLI renders the decision as the oracle would log it
    assert _run_script(monkeypatch, "explain_decision", [bundles[0]]) == 0


def test_explain_decision_renders_oracle_style(monkeypatch, tmp_path, capsys):
    """scripts/explain_decision.py end to end on a directed bundle: the
    render names the winner, the per-plugin score split, and who filtered
    the rejected node."""
    nodes = [
        make_node("big", cpu="8", memory="32Gi", pods=64,
                  labels={v1.LABEL_HOSTNAME: "big", "zone": "z0"}),
        make_node("small", cpu="2", memory="4Gi", pods=64,
                  labels={v1.LABEL_HOSTNAME: "small", "zone": "z1"}),
        make_node("cordoned", cpu="8", memory="32Gi", pods=64,
                  labels={v1.LABEL_HOSTNAME: "cordoned", "zone": "z2"},
                  unschedulable=True),
    ]
    filler = make_pod("filler", namespace="default", cpu="1500m",
                      memory="1Gi", labels={"app": "f"}, node_name="small")
    pending = make_pod("web", namespace="default", cpu="1", memory="1Gi",
                       labels={"app": "web"})
    snap = Snapshot.from_objects([filler], nodes)
    oracle_bd = explain.oracle_breakdown(snap, pending)
    path = explain.write_bundle(
        pending, nodes, [filler], oracle_bd["best"][0],
        [], oracle_bd, dir_path=str(tmp_path))
    assert _run_script(monkeypatch, "explain_decision", [path]) == 0
    out = capsys.readouterr().out
    assert 'pod "default/web": scheduled on' in out
    assert "cordoned: rejected by" in out
    assert "NodeUnschedulable" in out
    assert "NodeResourcesBalancedAllocation" in out and "total" in out


# -- /metricsz Prometheus exposition ----------------------------------------


def test_metricsz_exposition_over_http():
    """/metricsz on the apiserver debug surface serves the process-wide
    registry in Prometheus text format: HELP/TYPE headers for every
    scheduler_* metric (drift + explain counters included) and
    well-formed sample lines; /configz serves JSON beside it."""
    from kubernetes_tpu.apiserver.http import HTTPAPIServer

    # touch the labeled counters so sample lines (not just headers) exist
    metrics.parity_drift.inc(0, plugin="ExpositionSelfTest")
    metrics.shadow_samples.inc(0)
    metrics.explain_harvests.inc(0)
    srv = HTTPAPIServer(api=APIServer()).start()
    try:
        with urllib.request.urlopen(srv.address + "/metricsz") as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode()
        for name in ("scheduler_shadow_samples_total",
                     "scheduler_parity_drift_total",
                     "scheduler_explain_harvests_total",
                     "scheduler_schedule_attempts_total",
                     "scheduler_trace_dumps_total"):
            assert f"# TYPE {name} counter" in body, name
            assert f"# HELP {name} " in body, name
        assert 'scheduler_parity_drift_total{plugin="ExpositionSelfTest"}' \
            in body
        sample = re.compile(
            r"^[A-Za-z_:][A-Za-z0-9_:]*(\{[^}]*\})? -?[0-9.e+-]+(inf)?$")
        for line in body.strip().splitlines():
            if line.startswith("#"):
                continue
            assert sample.match(line), f"malformed exposition line: {line}"
        with urllib.request.urlopen(srv.address + "/configz") as resp:
            assert resp.headers["Content-Type"].startswith("application/json")
            json.loads(resp.read().decode())
    finally:
        srv.stop()
