"""kube-aggregator equivalent: APIService routing to delegate servers."""

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.aggregator import (
    AggregatedAPIServer,
    APIService,
    APIServiceSpec,
)
from kubernetes_tpu.apiserver.server import APIServer, NotFound, ResourceInfo
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory

from .util import make_pod, wait_until


def _delegate_with_widgets():
    from dataclasses import dataclass, field

    @dataclass
    class Widget:
        metadata: v1.ObjectMeta = field(default_factory=v1.ObjectMeta)
        size: int = 0
        kind: str = "Widget"
        api_version: str = "ext.example.com/v1"

    delegate = APIServer(resources=(ResourceInfo("widgets", Widget, True),))
    return delegate, Widget


class TestAggregator:
    def test_routes_to_delegate_and_local(self):
        agg = AggregatedAPIServer()
        delegate, Widget = _delegate_with_widgets()
        agg.register_api_service(
            APIService(
                metadata=v1.ObjectMeta(name="v1.ext.example.com"),
                spec=APIServiceSpec(group="ext.example.com", version="v1"),
            ),
            delegate,
        )
        cs = Clientset(agg)
        # local resources unaffected
        cs.pods.create(make_pod("p"))
        assert cs.pods.get("p", "default")
        # extension resource served through the aggregator
        cs.resource("widgets").create(
            Widget(metadata=v1.ObjectMeta(name="w", namespace="default"), size=3)
        )
        assert cs.resource("widgets").get("w", "default").size == 3
        # ...and lives in the DELEGATE's store, not the local one
        assert delegate.get("widgets", "w", "default").size == 3
        with pytest.raises(NotFound):
            agg.local.get("widgets", "w", "default")
        # APIService object is visible as a resource
        svcs, _ = cs.resource("apiservices").list()
        assert [s.metadata.name for s in svcs] == ["v1.ext.example.com"]
        assert svcs[0].status.conditions[0].status == "True"

    def test_name_validation(self):
        agg = AggregatedAPIServer()
        delegate, _ = _delegate_with_widgets()
        with pytest.raises(ValueError):
            agg.register_api_service(
                APIService(
                    metadata=v1.ObjectMeta(name="wrong"),
                    spec=APIServiceSpec(group="ext.example.com", version="v1"),
                ),
                delegate,
            )

    def test_informer_watches_extension_resource(self):
        agg = AggregatedAPIServer()
        delegate, Widget = _delegate_with_widgets()
        agg.register_api_service(
            APIService(
                metadata=v1.ObjectMeta(name="v1.ext.example.com"),
                spec=APIServiceSpec(group="ext.example.com", version="v1"),
            ),
            delegate,
        )
        cs = Clientset(agg)
        factory = SharedInformerFactory(cs)
        inf = factory.informer_for("widgets")
        factory.start()
        assert factory.wait_for_cache_sync()
        try:
            cs.resource("widgets").create(
                Widget(metadata=v1.ObjectMeta(name="w", namespace="default"))
            )
            assert wait_until(lambda: inf.get("default/w") is not None)
        finally:
            factory.stop()

    def test_local_wins_name_collisions(self):
        agg = AggregatedAPIServer()
        delegate = APIServer()  # serves "pods" too
        agg.register_api_service(
            APIService(
                metadata=v1.ObjectMeta(name="v1.core.example.com"),
                spec=APIServiceSpec(group="core.example.com", version="v1"),
            ),
            delegate,
        )
        cs = Clientset(agg)
        cs.pods.create(make_pod("p"))
        assert agg.local.get("pods", "p", "default")
        with pytest.raises(NotFound):
            delegate.get("pods", "p", "default")
