"""API Priority & Fairness: classification, seat limits, queue overflow,
exempt bypass.

Reference shape: apiserver/pkg/util/flowcontrol tests.
"""

import threading
import time

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.flowcontrol import (
    FlowController,
    FlowSchema,
    FlowSchemaRule,
    FlowSchemaSpec,
    FlowSchemaSubject,
    PriorityLevelConfiguration,
    PriorityLevelConfigurationSpec,
    PriorityLevelLimited,
    RequestInfo,
    TooManyRequests,
)
from kubernetes_tpu.apiserver.server import APIServer


@pytest.fixture()
def fc():
    return FlowController(APIServer(), default_timeout=0.5)


class TestClassification:
    def test_defaults_installed(self, fc):
        plcs, _ = fc.api.list("prioritylevelconfigurations")
        assert {p.metadata.name for p in plcs} == {"exempt", "global-default"}
        schemas, _ = fc.api.list("flowschemas")
        assert {s.metadata.name for s in schemas} == {"exempt", "catch-all"}

    def test_masters_exempt_catchall_rest(self, fc):
        admin = fc.classify(RequestInfo(user="root", groups=("system:masters",)))
        assert admin.exempt
        dev = fc.classify(RequestInfo(user="dev", verb="list", resource="pods"))
        assert dev.name == "global-default"

    def test_precedence_and_rules(self, fc):
        fc.api.create("prioritylevelconfigurations", PriorityLevelConfiguration(
            metadata=v1.ObjectMeta(name="workload-low"),
            spec=PriorityLevelConfigurationSpec(
                limited=PriorityLevelLimited(assured_concurrency_shares=2)
            ),
        ))
        fc.api.create("flowschemas", FlowSchema(
            metadata=v1.ObjectMeta(name="controllers"),
            spec=FlowSchemaSpec(
                priority_level_configuration="workload-low",
                matching_precedence=100,
                rules=[FlowSchemaRule(
                    subjects=[FlowSchemaSubject(kind="Group", name="controllers")],
                    resources=["pods"],
                )],
            ),
        ))
        req = RequestInfo(user="rs-controller", groups=("controllers",),
                          verb="create", resource="pods")
        assert fc.classify(req).name == "workload-low"
        # non-matching resource falls through to catch-all
        other = RequestInfo(user="rs-controller", groups=("controllers",),
                            verb="create", resource="nodes")
        assert fc.classify(other).name == "global-default"


class TestSeats:
    def _tight_level(self, fc, seats=1, queue=1):
        fc.api.create("prioritylevelconfigurations", PriorityLevelConfiguration(
            metadata=v1.ObjectMeta(name="tight"),
            spec=PriorityLevelConfigurationSpec(
                limited=PriorityLevelLimited(
                    assured_concurrency_shares=seats, queue_length_limit=queue
                )
            ),
        ))
        fc.api.create("flowschemas", FlowSchema(
            metadata=v1.ObjectMeta(name="tight"),
            spec=FlowSchemaSpec(
                priority_level_configuration="tight",
                matching_precedence=10,
                rules=[FlowSchemaRule(
                    subjects=[FlowSchemaSubject(kind="User", name="busy")]
                )],
            ),
        ))
        return RequestInfo(user="busy", verb="create", resource="pods")

    def test_seat_serialization(self, fc):
        req = self._tight_level(fc, seats=1, queue=10)
        running = []
        peak = []

        def work(i):
            with fc.dispatch(req, timeout=5):
                running.append(i)
                peak.append(len(running))
                time.sleep(0.05)
                running.remove(i)

        threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert max(peak) == 1  # one seat -> fully serialized

    def test_queue_overflow_rejects(self, fc):
        req = self._tight_level(fc, seats=1, queue=1)
        hold = threading.Event()
        entered = threading.Event()

        def holder():
            with fc.dispatch(req, timeout=5):
                entered.set()
                hold.wait(2)

        t = threading.Thread(target=holder)
        t.start()
        assert entered.wait(2)
        # one waiter fits the queue...
        rejected = []

        def waiter():
            try:
                with fc.dispatch(req, timeout=1.5):
                    pass
            except TooManyRequests:
                rejected.append("waiter")

        w = threading.Thread(target=waiter)
        w.start()
        time.sleep(0.1)
        # ...the next overflows immediately
        with pytest.raises(TooManyRequests, match="queue full"):
            with fc.dispatch(req, timeout=1):
                pass
        hold.set()
        t.join()
        w.join()
        assert not rejected  # the queued waiter got the seat after release

    def test_exempt_never_blocks(self, fc):
        req = self._tight_level(fc, seats=1, queue=1)
        admin = RequestInfo(user="root", groups=("system:masters",))
        with fc.dispatch(req, timeout=5):
            for _ in range(5):  # exempt traffic unaffected by the full level
                with fc.dispatch(admin):
                    pass

    def test_seat_timeout(self, fc):
        req = self._tight_level(fc, seats=1, queue=5)
        hold = threading.Event()
        entered = threading.Event()

        def holder():
            with fc.dispatch(req, timeout=5):
                entered.set()
                hold.wait(3)

        t = threading.Thread(target=holder)
        t.start()
        assert entered.wait(2)
        with pytest.raises(TooManyRequests, match="timed out"):
            with fc.dispatch(req, timeout=0.2):
                pass
        hold.set()
        t.join()


class TestConfigRefreshStability:
    def test_seats_survive_unrelated_store_writes(self, fc):
        """Any store write bumps the revision; the level cache must NOT
        rebuild (minting fresh semaphores while seats are held would
        bypass the concurrency limit)."""
        fc.api.create("prioritylevelconfigurations", PriorityLevelConfiguration(
            metadata=v1.ObjectMeta(name="one-seat"),
            spec=PriorityLevelConfigurationSpec(
                limited=PriorityLevelLimited(
                    assured_concurrency_shares=1, queue_length_limit=8
                )
            ),
        ))
        fc.api.create("flowschemas", FlowSchema(
            metadata=v1.ObjectMeta(name="one-seat"),
            spec=FlowSchemaSpec(
                priority_level_configuration="one-seat",
                matching_precedence=5,
                rules=[FlowSchemaRule(
                    subjects=[FlowSchemaSubject(kind="User", name="writer")]
                )],
            ),
        ))
        req = RequestInfo(user="writer", verb="create", resource="pods")
        peak = []
        active = []
        lock = threading.Lock()

        def work(i):
            from .util import make_pod

            from kubernetes_tpu.client.clientset import Clientset

            cs = Clientset(fc.api)
            with fc.dispatch(req, timeout=5):
                with lock:
                    active.append(i)
                    peak.append(len(active))
                cs.pods.create(make_pod(f"w-{i}"))  # store write mid-seat
                time.sleep(0.02)
                with lock:
                    active.remove(i)

        threads = [threading.Thread(target=work, args=(i,)) for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert max(peak) == 1


class TestSecuredChainIntegration:
    def test_apf_wired_between_authn_and_authz(self):
        """SecureAPIServer(flow_controller=...) gates every verb: a full
        priority level 429s a user even when RBAC would allow the call."""
        from kubernetes_tpu.apiserver.auth import SecureAPIServer

        from .util import make_pod

        api = APIServer()
        fc = FlowController(api, default_timeout=0.2)
        secure = SecureAPIServer(api, flow_controller=fc)
        secure.authenticator.add_token("root", "root", ["system:masters"])
        secure.authenticator.add_token("busy-t", "busy")
        # grant 'busy' full pod access; then choke its priority level
        from kubernetes_tpu.api import rbac

        api.create("clusterroles", rbac.ClusterRole(
            metadata=v1.ObjectMeta(name="pods-all"),
            rules=[rbac.PolicyRule(verbs=["*"], resources=["pods"])]))
        api.create("clusterrolebindings", rbac.ClusterRoleBinding(
            metadata=v1.ObjectMeta(name="pods-all"),
            subjects=[rbac.Subject(kind="User", name="busy")],
            role_ref=rbac.RoleRef(kind="ClusterRole", name="pods-all")))
        api.create("prioritylevelconfigurations", PriorityLevelConfiguration(
            metadata=v1.ObjectMeta(name="choke"),
            spec=PriorityLevelConfigurationSpec(
                limited=PriorityLevelLimited(
                    assured_concurrency_shares=1, queue_length_limit=0))))
        api.create("flowschemas", FlowSchema(
            metadata=v1.ObjectMeta(name="choke"),
            spec=FlowSchemaSpec(priority_level_configuration="choke",
                matching_precedence=5,
                rules=[FlowSchemaRule(
                    subjects=[FlowSchemaSubject(kind="User", name="busy")])])))
        cs = secure.as_user("busy-t")
        cs.pods.create(make_pod("ok"))  # one seat free -> succeeds
        # hold the single seat; the next call must 429, not Forbidden
        level = fc.classify(RequestInfo(user="busy", verb="get", resource="pods"))
        level.acquire(timeout=1)
        try:
            with pytest.raises(TooManyRequests):
                cs.pods.get("ok", "default")
        finally:
            level.release()
        cs.pods.get("ok", "default")  # seat released -> flows again
        # exempt masters unaffected throughout
        secure.as_user("root").pods.list()
