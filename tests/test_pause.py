"""The sandbox-hold (pause-equivalent) binary: builds, ignores SIGCHLD,
exits 0 on SIGTERM/SIGINT (behavioral spec in native/pause.c; role of the
reference's pause container per SURVEY.md §2.4.1)."""

import signal
import subprocess
import time
from pathlib import Path

import pytest

NATIVE = Path(__file__).resolve().parent.parent / "native"


@pytest.fixture(scope="module")
def pause_bin():
    subprocess.run(["make", "build/pause"], cwd=NATIVE, check=True,
                   capture_output=True)
    return NATIVE / "build" / "pause"


def test_version_flag(pause_bin):
    out = subprocess.run([str(pause_bin), "--version"], capture_output=True,
                         text=True, timeout=10)
    assert out.returncode == 0
    assert "sandbox-hold" in out.stdout


@pytest.mark.parametrize("sig", [signal.SIGTERM, signal.SIGINT])
def test_exits_cleanly_on_signal(pause_bin, sig):
    proc = subprocess.Popen([str(pause_bin)], stderr=subprocess.DEVNULL)
    try:
        time.sleep(0.2)  # let it install its signal mask
        assert proc.poll() is None, "holder must keep running unprompted"
        proc.send_signal(signal.SIGCHLD)
        time.sleep(0.2)
        assert proc.poll() is None, "SIGCHLD must not terminate the holder"
        proc.send_signal(sig)
        assert proc.wait(timeout=10) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
