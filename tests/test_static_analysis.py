"""Tier-1 gate for ktpu-lint (kubernetes_tpu/analysis/).

Four layers:
  * fixture corpus — every checker demonstrably catches its violation
    class (tests/fixtures/lint/*_flag.py) and stays quiet on the
    legal twin (*_pass.py), including pragma waivers;
  * framework — pragma parsing, line-free baseline keys, baseline
    add/remove round-trip on a synthetic mini-repo, warm-cache reuse;
  * the repo itself — `test_repo_clean` runs the full suite over the
    package and fails on any non-baselined violation, and the
    committed baseline may only shrink;
  * the dynamic lock-order sentinel — opposite-order acquisition from
    two threads is detected, consistent order passes, and
    `threading.Condition` built on a tracked lock still works.

The linter is stdlib-ast only, so this whole module runs in seconds.
"""

import ast
import os
import threading
import time

import pytest

from kubernetes_tpu.analysis import (core, decision_inert, host_sync,
                                     knob_registry, lock_order, seam_pairing)
from kubernetes_tpu.testing.locks import LockOrderSentinel, lock_order_sentinel

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "lint")

# fixture sources are checked AS IF they lived at these in-repo paths,
# so the checkers' path gates (hot modules, inert modules) apply
HOT_REL = "kubernetes_tpu/ops/fixture_case.py"
INERT_REL = "kubernetes_tpu/utils/tracing.py"
ANY_REL = "kubernetes_tpu/scheduler/fixture_case.py"


def read_fixture(name: str) -> str:
    with open(os.path.join(FIXTURES, name), "r", encoding="utf-8") as f:
        return f.read()


def lint_source(checker, rel: str, src: str):
    """Run one checker through the full per-file pipeline (pragmas
    applied, facts collected) — the same flow core.run uses."""
    tree = ast.parse(src)
    pragmas = core.Pragmas(src, tree)
    scope_of = core.enclosing_func(tree)
    facts = {}
    found = checker.check_file(rel, tree, src, scope_of, facts)
    rule = core.CHECKER_TO_RULE[checker.CHECKER]
    violations, allowed = [], []
    for v in found:
        reason = pragmas.waiver(rule, v.line)
        (allowed if reason is not None else violations).append(v)
    return violations, allowed, facts


# ---------------------------------------------------------------------------
# fixtures: each checker catches its violation class and passes the twin


class TestHostSyncFixtures:
    def test_flag_corpus_catches_every_sink(self):
        violations, _, _ = lint_source(
            host_sync, HOT_REL, read_fixture("host_sync_flag.py"))
        codes = {v.code for v in violations}
        assert codes >= {"item-call", "scalar-coerce", "numpy-readback",
                         "device-get", "block-until-ready"}
        # aliasing: both tuple-unpacked names stay tainted
        assert sum(v.code == "scalar-coerce" for v in violations) >= 3

    def test_pass_corpus_is_clean(self):
        violations, allowed, _ = lint_source(
            host_sync, HOT_REL, read_fixture("host_sync_pass.py"))
        assert violations == []
        # the pragma'd sites are reported as allowed, with reasons
        assert len(allowed) >= 2

    def test_cold_modules_are_not_checked(self):
        violations, _, _ = lint_source(
            host_sync, "kubernetes_tpu/utils/fixture_case.py",
            read_fixture("host_sync_flag.py"))
        assert violations == []


class TestKnobFixtures:
    def test_flag_corpus(self):
        violations, _, _ = lint_source(
            knob_registry, ANY_REL, read_fixture("knob_flag.py"))
        assert len(violations) == 4
        assert {v.code for v in violations} == {"env-read"}

    def test_pass_corpus_writes_and_accessors_legal(self):
        violations, _, facts = lint_source(
            knob_registry, ANY_REL, read_fixture("knob_pass.py"))
        assert violations == []
        # the accessor read is recorded as a fact for the global phase
        assert ["KTPU_TRACE"] == [name for name, _, _ in
                                  facts["knob_reads"]]

    def test_registry_module_itself_exempt(self):
        violations, _, _ = lint_source(
            knob_registry, "kubernetes_tpu/utils/knobs.py",
            read_fixture("knob_flag.py"))
        assert violations == []


class TestInertFixtures:
    def test_flag_corpus(self):
        violations, _, _ = lint_source(
            decision_inert, INERT_REL, read_fixture("inert_flag.py"))
        codes = [v.code for v in violations]
        assert "inert-deny-import" in codes
        assert codes.count("inert-mutation-call") == 2

    def test_pass_corpus(self):
        violations, _, _ = lint_source(
            decision_inert, INERT_REL, read_fixture("inert_pass.py"))
        assert violations == []

    def test_relative_import_resolution(self):
        src = "from ..scheduler import tpu_backend\n"
        violations, _, _ = lint_source(decision_inert, INERT_REL, src)
        assert [v.code for v in violations] == ["inert-deny-import"]

    def test_non_inert_module_unchecked(self):
        violations, _, _ = lint_source(
            decision_inert, ANY_REL, read_fixture("inert_flag.py"))
        assert violations == []


class TestSeamFixtures:
    def test_flag_corpus(self):
        violations, _, _ = lint_source(
            seam_pairing, ANY_REL, read_fixture("seam_flag.py"))
        assert [v.code for v in violations] == ["seam-unpaired"]

    def test_pass_corpus(self):
        violations, _, _ = lint_source(
            seam_pairing, ANY_REL, read_fixture("seam_pass.py"))
        assert violations == []

    def test_metrics_module_exempt(self):
        violations, _, _ = lint_source(
            seam_pairing, "kubernetes_tpu/scheduler/metrics.py",
            read_fixture("seam_flag.py"))
        assert violations == []


class TestLockOrderFixtures:
    def test_flag_corpus_cycle_detected(self):
        _, _, facts = lint_source(
            lock_order, ANY_REL, read_fixture("lock_flag.py"))
        violations = lock_order.check_global("", {ANY_REL: facts})
        assert [v.code for v in violations] == ["lock-cycle"]
        assert "a_lock" in violations[0].message
        assert "b_lock" in violations[0].message

    def test_pass_corpus_acyclic_including_call_edge(self):
        _, _, facts = lint_source(
            lock_order, ANY_REL, read_fixture("lock_pass.py"))
        # the helper-call edge IS tracked (a_lock -> b_lock via
        # forward_via_call), but consistent order has no cycle
        calls = facts["locks"]["forward_via_call"]["calls"]
        assert any(c[0] == "_take_b" and "a_lock" in c[1] for c in calls)
        assert lock_order.check_global("", {ANY_REL: facts}) == []


# ---------------------------------------------------------------------------
# framework: pragmas, keys, baseline, cache


class TestPragmas:
    def _pragmas(self, src):
        return core.Pragmas(src, ast.parse(src))

    def test_line_and_line_above(self):
        src = ("x = 1  # ktpu: allow-sync(same line)\n"
               "# ktpu: allow-knob(line above)\n"
               "y = 2\n")
        p = self._pragmas(src)
        assert p.waiver("sync", 1) == "same line"
        assert p.waiver("knob", 3) == "line above"
        assert p.waiver("sync", 3) is None      # rule must match
        assert p.waiver("knob", 1) is None

    def test_function_span(self):
        src = ("# ktpu: allow-sync(whole body)\n"
               "def f(ys):\n"
               "    a = 1\n"
               "    return a\n"
               "def g(ys):\n"
               "    return 2\n")
        p = self._pragmas(src)
        assert p.waiver("sync", 3) == "whole body"
        assert p.waiver("sync", 4) == "whole body"
        assert p.waiver("sync", 6) is None      # next function not covered

    def test_reason_required_by_grammar(self):
        # a pragma without parens does not parse -> no waiver
        src = "# ktpu: allow-sync\nx = 1\n"
        assert self._pragmas(src).waiver("sync", 2) is None


class TestBaselineKeys:
    def test_keys_are_line_free_and_ordinal_stable(self):
        mk = lambda line: core.Violation(  # noqa: E731
            "host-sync", "kubernetes_tpu/ops/x.py", line, "f",
            "item-call", "m")
        keyed = core._assign_keys([mk(10), mk(90)])
        assert [v.key for v in keyed] == [
            "host-sync:kubernetes_tpu/ops/x.py:f:item-call:0",
            "host-sync:kubernetes_tpu/ops/x.py:f:item-call:1",
        ]
        # shifting every line leaves the keys identical
        shifted = core._assign_keys([mk(110), mk(190)])
        assert [v.key for v in shifted] == [v.key for v in keyed]


BAD_OPS = '''import jax.numpy as jnp

def hot(ys):
    return float(jnp.sum(ys))
'''
MINI_KNOBS = '''_REGISTRY = {}

def _declare(name, kind, default, description):
    _REGISTRY[name] = (kind, default, description)

_declare("KTPU_X", "int", 1, "fixture knob")
'''


class TestBaselineRoundTrip:
    @pytest.fixture
    def mini_repo(self, tmp_path, monkeypatch):
        (tmp_path / "kubernetes_tpu" / "ops").mkdir(parents=True)
        (tmp_path / "kubernetes_tpu" / "utils").mkdir(parents=True)
        (tmp_path / "kubernetes_tpu" / "ops" / "bad.py").write_text(BAD_OPS)
        (tmp_path / "kubernetes_tpu" / "utils" / "knobs.py").write_text(
            MINI_KNOBS)
        (tmp_path / "README.md").write_text("knob table: KTPU_X\n")
        monkeypatch.setattr(core, "BASELINE_PATH",
                            str(tmp_path / "baseline.json"))
        monkeypatch.setattr(core, "CACHE_PATH",
                            str(tmp_path / "cache.json"))
        return str(tmp_path)

    def test_add_remove_round_trip(self, mini_repo):
        report = core.run(mini_repo, use_cache=False)
        assert not report.clean
        keys = [v.key for v in report.violations]
        assert keys, "mini repo must produce a violation"

        # add: grandfather everything -> clean, counted as baselined
        core.save_baseline({v.key: v.message for v in report.violations})
        report2 = core.run(mini_repo, use_cache=False)
        assert report2.clean
        assert [v.key for v in report2.baselined] == keys
        assert report2.stale_baseline == []

        # remove: shrink the baseline -> the violation is live again
        core.save_baseline({})
        report3 = core.run(mini_repo, use_cache=False)
        assert [v.key for v in report3.violations] == keys

        # stale: an entry no live violation matches is surfaced
        core.save_baseline({"host-sync:gone.py:f:item-call:0": "fixed"})
        report4 = core.run(mini_repo, use_cache=False)
        assert report4.stale_baseline == [
            "host-sync:gone.py:f:item-call:0"]

    def test_warm_cache_reuses_file_results(self, mini_repo):
        first = core.run(mini_repo, use_cache=True)
        assert first.files_from_cache == 0
        second = core.run(mini_repo, use_cache=True)
        assert second.files_from_cache == second.files_checked
        assert [v.key for v in second.violations] == \
            [v.key for v in first.violations]


# ---------------------------------------------------------------------------
# the repo itself


class TestRepoClean:
    def test_repo_clean(self):
        """The tier-1 gate: zero non-baselined violations, repo-wide."""
        report = core.run()
        assert report.clean, (
            "ktpu-lint violations (fix, pragma with a reason, or — for "
            "pre-existing debt only — baseline):\n" + "\n".join(
                f"  {v.path}:{v.line} [{v.checker}/{v.code}] {v.message}"
                for v in report.violations))

    def test_repo_warm_run_is_fast(self):
        core.run()  # prime
        t0 = time.monotonic()
        report = core.run()
        elapsed = time.monotonic() - t0
        assert report.files_from_cache == report.files_checked
        assert elapsed < 10.0, f"warm lint took {elapsed:.1f}s"

    def test_baseline_only_shrinks(self):
        """The committed baseline is empty; it may never grow again.

        New exceptions must be annotated in place with
        `# ktpu: allow-<rule>(<reason>)` — the baseline exists only to
        grandfather pre-existing debt, and all of it has been triaged.
        """
        entries = core.load_baseline()
        assert entries == {}, (
            "analysis/baseline.json grew — annotate new exceptions with "
            "pragmas instead of baselining them")

    def test_no_stale_baseline_entries(self):
        report = core.run()
        assert report.stale_baseline == [], (
            "baseline entries no longer match any violation; shrink with "
            "scripts/lint.py --update-baseline")


class TestConfigzCompleteness:
    def test_every_declared_knob_on_configz(self):
        """Runtime half of the knob-registry contract: the live /configz
        snapshot exposes every declared knob with value+default+source."""
        from kubernetes_tpu.utils import configz, knobs
        snap = configz.snapshot()
        assert "ktpu-env" in snap
        view = snap["ktpu-env"]
        for name in knobs.registry():
            assert name in view, f"{name} missing from /configz"
            assert {"value", "default", "source"} <= set(view[name])

    def test_env_override_shows_as_env_source(self, monkeypatch):
        from kubernetes_tpu.utils import configz, knobs
        monkeypatch.setenv("KTPU_TRACE", "2")
        view = configz.snapshot()["ktpu-env"]
        assert view["KTPU_TRACE"]["value"] == "2"
        assert view["KTPU_TRACE"]["source"] == "env"
        assert knobs.get_int("KTPU_TRACE") == 2


# ---------------------------------------------------------------------------
# dynamic lock-order sentinel


class TestLockSentinel:
    def test_opposite_order_across_threads_is_a_cycle(self):
        s = LockOrderSentinel()
        s.install()
        try:
            a = threading.Lock()
            b = threading.Lock()

            def ab():
                with a:
                    with b:
                        pass

            def ba():
                with b:
                    with a:
                        pass

            t1 = threading.Thread(target=ab)
            t1.start()
            t1.join()
            t2 = threading.Thread(target=ba)
            t2.start()
            t2.join()
        finally:
            s.uninstall()
        with pytest.raises(AssertionError, match="lock-order cycle"):
            s.assert_cycle_free()

    def test_consistent_order_passes(self):
        with lock_order_sentinel() as s:
            a = threading.Lock()
            b = threading.RLock()
            with a:
                with b:
                    pass
            with a:
                with b:
                    pass
        assert s.edges  # the a->b edge was observed, and no cycle raised

    def test_release_out_of_lifo_order(self):
        with lock_order_sentinel() as s:
            a = threading.Lock()
            b = threading.Lock()
            a.acquire()
            b.acquire()
            a.release()   # not LIFO
            b.release()
        assert s._stack() == []

    def test_condition_on_tracked_lock(self):
        """Condition(tracked Lock) must stay correct: wait() releases
        through the wrapper, so the held stack balances."""
        with lock_order_sentinel() as s:
            lock = threading.Lock()
            cv = threading.Condition(lock)
            ready = []

            def waiter():
                with cv:
                    while not ready:
                        cv.wait(timeout=5)

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.05)
            with cv:
                ready.append(1)
                cv.notify()
            t.join(timeout=5)
            assert not t.is_alive()
            # main thread's stack is balanced after the with-blocks
            assert s._stack() == []

    def test_untracked_locks_after_uninstall(self):
        s = LockOrderSentinel()
        s.install()
        s.uninstall()
        lock = threading.Lock()
        with lock:
            pass
        assert s.edges == {}
