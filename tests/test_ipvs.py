"""IPVS proxier mode: virtual-server table, schedulers, persistence.

Reference shape: pkg/proxy/ipvs/proxier_test.go.
"""

from collections import Counter

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.endpointslice import EndpointSliceController
from kubernetes_tpu.proxy import IPVSProxier, Packet
from kubernetes_tpu.proxy.ipvs import IPVSTable, RealServer, VirtualServer

from .util import wait_until


class TestIPVSTable:
    def _vs(self, scheduler="rr", persistence=0.0, n=3):
        return VirtualServer(
            ip="10.0.0.1", port=80, scheduler=scheduler,
            persistence_seconds=persistence,
            reals=[RealServer(ip=f"10.1.0.{i}", port=8080) for i in range(n)],
        )

    def test_round_robin(self):
        t = IPVSTable()
        t.replace([self._vs()])
        got = [t.route(Packet("10.0.0.1", 80, src_ip=f"c{i}"))[0] for i in range(6)]
        assert got == ["10.1.0.0", "10.1.0.1", "10.1.0.2"] * 2

    def test_least_connection(self):
        t = IPVSTable()
        t.replace([self._vs(scheduler="lc")])
        first = t.route(Packet("10.0.0.1", 80, src_ip="a"))
        second = t.route(Packet("10.0.0.1", 80, src_ip="b"))
        third = t.route(Packet("10.0.0.1", 80, src_ip="c"))
        assert {first[0], second[0], third[0]} == {
            "10.1.0.0", "10.1.0.1", "10.1.0.2"
        }
        # close a connection: that real becomes least-loaded again
        t.conn_close(("10.0.0.1", 80, "TCP"), (first[0], 8080))
        assert t.route(Packet("10.0.0.1", 80, src_ip="d"))[0] == first[0]

    def test_source_hash_stable(self):
        t = IPVSTable()
        t.replace([self._vs(scheduler="sh")])
        picks = {t.route(Packet("10.0.0.1", 80, src_ip="client-1"))[0] for _ in range(10)}
        assert len(picks) == 1

    def test_persistence(self):
        t = IPVSTable()
        t.replace([self._vs(persistence=60.0)])
        first = t.route(Packet("10.0.0.1", 80, src_ip="sticky"))
        for _ in range(5):
            assert t.route(Packet("10.0.0.1", 80, src_ip="sticky")) == first

    def test_no_reals_refused_and_unknown_none(self):
        t = IPVSTable()
        t.replace([VirtualServer(ip="10.0.0.1", port=80)])
        with pytest.raises(ConnectionRefusedError):
            t.route(Packet("10.0.0.1", 80, src_ip="x"))
        assert t.route(Packet("10.9.9.9", 80, src_ip="x")) is None

    def test_replace_preserves_connections_and_rr_position(self):
        t = IPVSTable()
        t.replace([self._vs(scheduler="lc")])
        t.route(Packet("10.0.0.1", 80, src_ip="a"))  # one conn on real 0
        t.replace([self._vs(scheduler="lc")])
        # real 0 still has the active connection after resync
        vs = t.virtual_servers()[0]
        assert sum(r.active_conn for r in vs.reals) == 1


class TestIPVSProxier:
    def test_end_to_end_sync_and_route(self):
        api = APIServer()
        cs = Clientset(api)
        factory = SharedInformerFactory(cs)
        ctrl = EndpointSliceController(cs, factory)
        proxier = IPVSProxier(factory)
        factory.start()
        assert factory.wait_for_cache_sync()
        ctrl.run()
        try:
            cs.services.create(
                v1.Service(
                    metadata=v1.ObjectMeta(name="web", namespace="default"),
                    spec=v1.ServiceSpec(
                        selector={"app": "web"},
                        cluster_ip="10.0.0.10",
                        type="NodePort",
                        ports=[
                            v1.ServicePort(
                                name="http", port=80, target_port=8080,
                                node_port=30080,
                            )
                        ],
                    ),
                )
            )
            for i in range(3):
                cs.pods.create(
                    v1.Pod(
                        metadata=v1.ObjectMeta(
                            name=f"w{i}", namespace="default",
                            labels={"app": "web"},
                        ),
                        spec=v1.PodSpec(
                            node_name="n1",
                            containers=[v1.Container(name="c", image="i")],
                        ),
                        status=v1.PodStatus(
                            phase="Running", pod_ip=f"10.1.0.{i}",
                            conditions=[v1.PodCondition(type="Ready", status="True")],
                        ),
                    )
                )
            assert wait_until(
                lambda: any(
                    len(vs.reals) == 3 for vs in proxier.table.virtual_servers()
                )
            )
            hits = Counter(
                proxier.route(Packet("10.0.0.10", 80, src_ip=f"c{i}"))[0]
                for i in range(9)
            )
            assert set(hits) == {"10.1.0.0", "10.1.0.1", "10.1.0.2"}
            assert all(v == 3 for v in hits.values())  # strict rr fairness
            # nodePort on any node address
            ip, port = proxier.route(Packet("172.16.0.9", 30080, src_ip="z"))
            assert port == 8080 and ip.startswith("10.1.0.")
        finally:
            ctrl.stop()
            factory.stop()
