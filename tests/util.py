"""Pod/Node builders for tests (reference: pkg/scheduler/testing/wrappers.go)."""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from kubernetes_tpu.api import types as v1

# fast kubelet timing for hollow-cluster tests (seconds)
FAST_KUBELET = dict(
    sync_period=0.5,
    pleg_period=0.1,
    housekeeping_period=0.3,
    lease_renew_period=0.3,
    node_status_period=0.3,
)


def wait_until(fn, timeout: float = 30.0, interval: float = 0.05) -> bool:
    """Poll fn until truthy or timeout (level-triggered test waits)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def make_node(
    name: str,
    cpu: str = "4",
    memory: str = "32Gi",
    pods: int = 110,
    labels: Optional[Dict[str, str]] = None,
    taints: Optional[List[v1.Taint]] = None,
    unschedulable: bool = False,
    images: Optional[List[v1.ContainerImage]] = None,
    extended: Optional[Dict[str, str]] = None,
) -> v1.Node:
    alloc = {"cpu": cpu, "memory": memory, "pods": str(pods)}
    if extended:
        alloc.update(extended)
    return v1.Node(
        metadata=v1.ObjectMeta(name=name, labels=dict(labels or {})),
        spec=v1.NodeSpec(unschedulable=unschedulable, taints=taints),
        status=v1.NodeStatus(capacity=dict(alloc), allocatable=alloc, images=images),
    )


_counter = [0]


def make_pod(
    name: Optional[str] = None,
    namespace: str = "default",
    cpu: Optional[str] = None,
    memory: Optional[str] = None,
    node_name: str = "",
    labels: Optional[Dict[str, str]] = None,
    priority: Optional[int] = None,
    node_selector: Optional[Dict[str, str]] = None,
    affinity: Optional[v1.Affinity] = None,
    tolerations: Optional[List[v1.Toleration]] = None,
    constraints: Optional[List[v1.TopologySpreadConstraint]] = None,
    host_port: int = 0,
    image: str = "registry.example/app:v1",
    extended: Optional[Dict[str, str]] = None,
    containers: int = 1,
) -> v1.Pod:
    if name is None:
        _counter[0] += 1
        name = f"pod-{_counter[0]}"
    requests: Dict[str, str] = {}
    if cpu is not None:
        requests["cpu"] = cpu
    if memory is not None:
        requests["memory"] = memory
    if extended:
        requests.update(extended)
    ports = [v1.ContainerPort(host_port=host_port, container_port=host_port)] if host_port else None
    specs = [
        v1.Container(
            name=f"c{i}",
            image=image,
            resources=v1.ResourceRequirements(requests=dict(requests) or None),
            ports=ports if i == 0 else None,
        )
        for i in range(containers)
    ]
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name, namespace=namespace, labels=dict(labels or {})),
        spec=v1.PodSpec(
            containers=specs,
            node_name=node_name,
            priority=priority,
            node_selector=node_selector,
            affinity=affinity,
            tolerations=tolerations,
            topology_spread_constraints=constraints,
        ),
    )


def anti_affinity(topology_key: str, match_labels: Dict[str, str]) -> v1.Affinity:
    return v1.Affinity(
        pod_anti_affinity=v1.PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[
                v1.PodAffinityTerm(
                    label_selector=v1.LabelSelector(match_labels=match_labels),
                    topology_key=topology_key,
                )
            ]
        )
    )


def pod_affinity(topology_key: str, match_labels: Dict[str, str]) -> v1.Affinity:
    return v1.Affinity(
        pod_affinity=v1.PodAffinity(
            required_during_scheduling_ignored_during_execution=[
                v1.PodAffinityTerm(
                    label_selector=v1.LabelSelector(match_labels=match_labels),
                    topology_key=topology_key,
                )
            ]
        )
    )


def spread_constraint(
    max_skew: int, topology_key: str, when: str, match_labels: Dict[str, str]
) -> v1.TopologySpreadConstraint:
    return v1.TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=topology_key,
        when_unsatisfiable=when,
        label_selector=v1.LabelSelector(match_labels=match_labels),
    )
