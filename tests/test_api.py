"""Unit tests for the api layer: quantity, labels, taints, serde.

Case values mirror the reference's table tests
(staging/src/k8s.io/apimachinery/pkg/api/resource/quantity_test.go,
staging/src/k8s.io/apimachinery/pkg/labels/selector_test.go).
"""

import pytest

from kubernetes_tpu.api.quantity import Quantity, parse_quantity
from kubernetes_tpu.api.labels import (
    Selector,
    match_node_selector_terms,
    pod_matches_node_selector_and_affinity,
)
from kubernetes_tpu.api.taints import (
    find_matching_untolerated_taint,
    toleration_tolerates_taint,
)
from kubernetes_tpu.api import types as t
from kubernetes_tpu.utils import serde


class TestQuantity:
    @pytest.mark.parametrize(
        "s,value",
        [
            ("0", 0),
            ("100m", 1),  # ceil(0.1)
            ("1", 1),
            ("1500m", 2),  # ceil(1.5)
            ("2k", 2000),
            ("2Ki", 2048),
            ("1Gi", 1073741824),
            ("32Gi", 34359738368),
            ("12e6", 12000000),
            ("1.5Gi", 1610612736),
            ("100M", 100000000),
        ],
    )
    def test_value(self, s, value):
        assert Quantity(s).value() == value

    @pytest.mark.parametrize(
        "s,milli",
        [
            ("100m", 100),
            ("1", 1000),
            ("4", 4000),
            ("2500m", 2500),
            ("1u", 1),  # ceil(0.001)
            ("500n", 1),
            ("0", 0),
        ],
    )
    def test_milli_value(self, s, milli):
        assert Quantity(s).milli_value() == milli

    def test_invalid(self):
        for bad in ["", "abc", "1.5.2", "--1", "1Kii"]:
            with pytest.raises(ValueError):
                parse_quantity(bad)

    def test_compare(self):
        assert Quantity("1000m") == Quantity("1")
        assert Quantity("999m") < Quantity("1")


class TestSelector:
    def test_nil_matches_nothing(self):
        assert not Selector.from_label_selector(None).matches({"a": "b"})
        assert not Selector.from_label_selector(None).matches({})

    def test_empty_matches_everything(self):
        sel = Selector.from_label_selector(t.LabelSelector())
        assert sel.matches({}) and sel.matches({"a": "b"})

    def test_match_labels(self):
        sel = Selector.from_label_selector(t.LabelSelector(match_labels={"a": "b"}))
        assert sel.matches({"a": "b", "c": "d"})
        assert not sel.matches({"a": "x"})
        assert not sel.matches({})

    def test_expressions(self):
        sel = Selector.from_label_selector(
            t.LabelSelector(
                match_expressions=[
                    t.LabelSelectorRequirement(key="env", operator="In", values=["p", "q"]),
                    t.LabelSelectorRequirement(key="gone", operator="DoesNotExist"),
                ]
            )
        )
        assert sel.matches({"env": "p"})
        assert not sel.matches({"env": "z"})
        assert not sel.matches({"env": "p", "gone": "1"})

    def test_not_in_absent_key_matches(self):
        sel = Selector.from_label_selector(
            t.LabelSelector(
                match_expressions=[
                    t.LabelSelectorRequirement(key="k", operator="NotIn", values=["v"])
                ]
            )
        )
        assert sel.matches({})
        assert sel.matches({"k": "other"})
        assert not sel.matches({"k": "v"})

    def test_node_selector_terms_or_semantics(self):
        terms = [
            t.NodeSelectorTerm(
                match_expressions=[
                    t.NodeSelectorRequirement(key="zone", operator="In", values=["z1"])
                ]
            ),
            t.NodeSelectorTerm(
                match_expressions=[
                    t.NodeSelectorRequirement(key="zone", operator="In", values=["z2"])
                ]
            ),
        ]
        assert match_node_selector_terms(terms, {"zone": "z2"}, {})
        assert not match_node_selector_terms(terms, {"zone": "z3"}, {})
        # empty term matches nothing
        assert not match_node_selector_terms([t.NodeSelectorTerm()], {"a": "b"}, {})

    def test_gt_lt(self):
        terms = [
            t.NodeSelectorTerm(
                match_expressions=[
                    t.NodeSelectorRequirement(key="cores", operator="Gt", values=["4"])
                ]
            )
        ]
        assert match_node_selector_terms(terms, {"cores": "8"}, {})
        assert not match_node_selector_terms(terms, {"cores": "4"}, {})
        assert not match_node_selector_terms(terms, {"cores": "abc"}, {})

    def test_match_fields(self):
        terms = [
            t.NodeSelectorTerm(
                match_fields=[
                    t.NodeSelectorRequirement(
                        key="metadata.name", operator="In", values=["node-1"]
                    )
                ]
            )
        ]
        assert match_node_selector_terms(terms, {}, {"metadata.name": "node-1"})
        assert not match_node_selector_terms(terms, {}, {"metadata.name": "node-2"})

    def test_pod_node_selector(self):
        pod = t.Pod(spec=t.PodSpec(node_selector={"disk": "ssd"}))
        node = t.Node(metadata=t.ObjectMeta(name="n", labels={"disk": "ssd"}))
        assert pod_matches_node_selector_and_affinity(pod, node)
        node2 = t.Node(metadata=t.ObjectMeta(name="n2", labels={"disk": "hdd"}))
        assert not pod_matches_node_selector_and_affinity(pod, node2)


class TestTaints:
    def test_exists_empty_key_matches_all(self):
        tol = t.Toleration(operator="Exists")
        assert toleration_tolerates_taint(tol, t.Taint(key="k", value="v", effect="NoSchedule"))

    def test_effect_mismatch(self):
        tol = t.Toleration(key="k", operator="Exists", effect="NoSchedule")
        assert not toleration_tolerates_taint(tol, t.Taint(key="k", effect="NoExecute"))

    def test_equal(self):
        tol = t.Toleration(key="k", operator="Equal", value="v")
        assert toleration_tolerates_taint(tol, t.Taint(key="k", value="v", effect="NoSchedule"))
        assert not toleration_tolerates_taint(tol, t.Taint(key="k", value="w", effect="NoSchedule"))

    def test_find_untolerated_with_filter(self):
        taints = [
            t.Taint(key="a", effect="PreferNoSchedule"),
            t.Taint(key="b", effect="NoSchedule"),
        ]
        # filter only NoSchedule/NoExecute (the Filter plugin predicate)
        pred = lambda taint: taint.effect in ("NoSchedule", "NoExecute")
        taint, found = find_matching_untolerated_taint(taints, [], pred)
        assert found and taint.key == "b"
        tol = [t.Toleration(key="b", operator="Exists")]
        _, found = find_matching_untolerated_taint(taints, tol, pred)
        assert not found


class TestSerde:
    def test_pod_roundtrip(self):
        pod = t.Pod(
            metadata=t.ObjectMeta(name="p", namespace="ns", labels={"app": "web"}),
            spec=t.PodSpec(
                containers=[
                    t.Container(
                        name="c",
                        resources=t.ResourceRequirements(
                            requests={"cpu": "500m", "memory": "1Gi"}
                        ),
                        ports=[t.ContainerPort(host_port=8080, container_port=80)],
                    )
                ],
                tolerations=[t.Toleration(key="k", operator="Exists")],
                priority=100,
            ),
        )
        d = serde.to_dict(pod)
        assert d["metadata"]["name"] == "p"
        assert d["spec"]["containers"][0]["resources"]["requests"]["cpu"] == "500m"
        assert d["spec"]["containers"][0]["ports"][0]["hostPort"] == 8080
        pod2 = serde.from_dict(t.Pod, d)
        assert pod2 == pod

    def test_omitempty(self):
        d = serde.to_dict(t.Pod())
        assert "nodeName" not in d["spec"]
        assert "labels" not in d["metadata"]
