"""GC propagation policies (foreground/orphan) and Deployment revision
history + rollback.

Reference: pkg/controller/garbagecollector (attemptToDeleteItem,
processDeletingDependentsItem, orphanDependents), deployment_util.go
revision annotations + cleanupDeployment, kubectl polymorphichelpers
history/rollback."""

import io

import pytest

from kubernetes_tpu.api import apps
from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.server import APIServer, NotFound
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.deployment import (
    REVISION_ANNOTATION,
    DeploymentController,
    rs_revision,
)
from kubernetes_tpu.controllers.garbagecollector import GarbageCollector
from kubernetes_tpu.controllers.replicaset import ReplicaSetController
from kubernetes_tpu.kubectl.cli import Kubectl

from .util import make_pod, wait_until


def _owned_pod(name, owner_uid, block=True):
    p = make_pod(name)
    p.metadata.owner_references = [v1.OwnerReference(
        api_version="apps/v1", kind="ReplicaSet", name="owner-rs",
        uid=owner_uid, controller=True, block_owner_deletion=block,
    )]
    return p


def _rs(name="owner-rs", replicas=0):
    return apps.ReplicaSet(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        spec=apps.ReplicaSetSpec(
            replicas=replicas,
            selector=v1.LabelSelector(match_labels={"app": name}),
            template=v1.PodTemplateSpec(
                metadata=v1.ObjectMeta(labels={"app": name}),
                spec=v1.PodSpec(containers=[v1.Container(name="c", image="i")]),
            ),
        ),
    )


class TestGCPropagation:
    def _gc(self, api):
        gc = GarbageCollector(Clientset(api), scan_interval=3600)
        return gc

    def test_foreground_blocks_until_dependents_gone(self):
        api = APIServer()
        cs = Clientset(api)
        rs = cs.replicasets.create(_rs())
        cs.pods.create(_owned_pod("dep-1", rs.metadata.uid, block=True))
        cs.pods.create(_owned_pod("dep-2", rs.metadata.uid, block=True))
        gc = self._gc(api)

        cs.replicasets.delete("owner-rs", "default",
                              propagation_policy="Foreground")
        # soft-deleted, finalizer held, still visible
        held = cs.replicasets.get("owner-rs", "default")
        assert held.metadata.deletion_timestamp is not None
        assert "foregroundDeletion" in (held.metadata.finalizers or [])

        gc.collect_once()   # deletes the blocking dependents
        assert not cs.pods.list(namespace="default")[0]
        gc.collect_once()   # no blockers left -> finalizer removed
        with pytest.raises(NotFound):
            cs.replicasets.get("owner-rs", "default")

    def test_orphan_strips_owner_refs(self):
        api = APIServer()
        cs = Clientset(api)
        rs = cs.replicasets.create(_rs())
        cs.pods.create(_owned_pod("kid", rs.metadata.uid))
        gc = self._gc(api)

        cs.replicasets.delete("owner-rs", "default",
                              propagation_policy="Orphan")
        gc.collect_once()
        with pytest.raises(NotFound):
            cs.replicasets.get("owner-rs", "default")
        kid = cs.pods.get("kid", "default")
        assert not kid.metadata.owner_references  # orphaned, NOT deleted
        gc.collect_once()
        assert cs.pods.get("kid", "default")  # still alive

    def test_background_default_collects_dependents(self):
        api = APIServer()
        cs = Clientset(api)
        rs = cs.replicasets.create(_rs())
        cs.pods.create(_owned_pod("kid", rs.metadata.uid))
        gc = self._gc(api)
        cs.replicasets.delete("owner-rs", "default")  # background
        gc.collect_once()
        assert not cs.pods.list(namespace="default")[0]


class TestDeploymentRevisions:
    def _cluster(self):
        api = APIServer()
        cs = Clientset(api)
        factory = SharedInformerFactory(cs)
        dc = DeploymentController(cs, factory)
        rc = ReplicaSetController(cs, factory)
        factory.start()
        assert factory.wait_for_cache_sync()
        dc.run()
        rc.run()
        return api, cs, factory, dc, rc

    def _deployment(self, image="img:1", replicas=2):
        return apps.Deployment(
            metadata=v1.ObjectMeta(name="web", namespace="default"),
            spec=apps.DeploymentSpec(
                replicas=replicas,
                selector=v1.LabelSelector(match_labels={"app": "web"}),
                template=v1.PodTemplateSpec(
                    metadata=v1.ObjectMeta(labels={"app": "web"}),
                    spec=v1.PodSpec(containers=[v1.Container(
                        name="c", image=image)]),
                ),
            ),
        )

    def test_revisions_stamp_and_undo(self):
        api, cs, factory, dc, rc = self._cluster()
        try:
            cs.deployments.create(self._deployment("img:1"))

            def rs_with_rev(rev):
                return [
                    rs for rs in cs.replicasets.list(namespace="default")[0]
                    if rs_revision(rs) == rev
                ]

            assert wait_until(lambda: rs_with_rev(1), timeout=10)

            dep = cs.deployments.get("web", "default")
            dep.spec.template.spec.containers[0].image = "img:2"
            cs.deployments.update(dep)
            assert wait_until(lambda: rs_with_rev(2), timeout=10)
            assert wait_until(
                lambda: all(
                    (rs.spec.replicas or 0) == 0 for rs in rs_with_rev(1)
                ),
                timeout=15,
            )

            # rollout history shows both revisions
            buf = io.StringIO()
            k = Kubectl(cs, out=buf)
            k.run(["rollout", "history", "deployment/web"])
            out = buf.getvalue()
            assert "1 " in out and "2 " in out

            # undo -> img:1 comes back as revision 3 (re-activated RS)
            k.run(["rollout", "undo", "deployment/web"])
            assert wait_until(
                lambda: cs.deployments.get("web", "default")
                .spec.template.spec.containers[0].image == "img:1",
                timeout=10,
            )
            assert wait_until(lambda: rs_with_rev(3), timeout=15)
        finally:
            dc.stop()
            rc.stop()
            factory.stop()

    def test_history_pruned_to_limit(self):
        api, cs, factory, dc, rc = self._cluster()
        try:
            d = self._deployment("img:1")
            d.spec.revision_history_limit = 1
            cs.deployments.create(d)
            for i in range(2, 5):
                # the previous revision's RS must exist before updating,
                # or revision numbers telescope and the waits deadlock
                assert wait_until(
                    lambda i=i: any(
                        rs_revision(rs) == i - 1
                        for rs in cs.replicasets.list(namespace="default")[0]
                    ),
                    timeout=15,
                )
                dep = cs.deployments.get("web", "default")
                dep.spec.template.spec.containers[0].image = f"img:{i}"
                cs.deployments.update(dep)
                assert wait_until(
                    lambda i=i: any(
                        rs_revision(rs) == i
                        for rs in cs.replicasets.list(namespace="default")[0]
                    ),
                    timeout=15,
                )
            # 4 revisions existed; limit=1 keeps the active RS + 1 old
            def inactive():
                return [
                    rs for rs in cs.replicasets.list(namespace="default")[0]
                    if (rs.spec.replicas or 0) == 0 and rs.status.replicas == 0
                ]

            assert wait_until(lambda: len(inactive()) <= 1, timeout=20)
        finally:
            dc.stop()
            rc.stop()
            factory.stop()
