"""Device-fault-tolerant scheduling pipeline tests.

The pipelined loop (PR 3) assumed every XLA dispatch succeeds; this suite
pins the fault half of the contract: a raising launch, a NaN/garbage
harvest, and a wedged device wait are detected (watchdog + validation
guard), recovered (bounded retry with a rebuilt session), contained
(degradation ladder pallas -> hoisted -> oracle under persistent faults,
background-probe re-promotion), and survived by the pipeline workers
(supervised scheduler/completion threads, FIFO drained back to the queue
on a worker crash). Fault parity: transient faults recovered IN ORDER
must not change a single decision vs the clean depth-0 reference; worker
kills must preserve the bound SET (every pod bound exactly once or still
queued — zero lost, zero double-bound).
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from kubernetes_tpu.scheduler import metrics
from kubernetes_tpu.scheduler.degradation import (
    RUNG_HOISTED,
    RUNG_ORACLE,
    RUNG_PALLAS,
    DegradationLadder,
)
from kubernetes_tpu.scheduler.scheduler import PipelineStalled
from kubernetes_tpu.testing.faults import FaultInjector, InjectedFault

from .test_pipeline_parity import (
    _bound_map,
    _cluster,
    _drive,
    _mk_scheduler,
    _pod_stream,
)
from .util import make_pod, wait_until


def _counter_snapshot():
    return {
        "faults": dict(metrics.device_faults.items()),
        "retries": metrics.dispatch_retries.value(),
        "restarts": dict(metrics.worker_restarts.items()),
    }


def _fault_delta(before, kind):
    after = dict(metrics.device_faults.items())
    return after.get((kind,), 0.0) - before["faults"].get((kind,), 0.0)


def _restart_delta(before, worker):
    after = dict(metrics.worker_restarts.items())
    return after.get((worker,), 0.0) - before["restarts"].get((worker,), 0.0)


# -- unit: injector ---------------------------------------------------------


class TestFaultInjector:
    def test_arm_shots_consume_and_count(self):
        inj = FaultInjector()
        inj.arm("raise-dispatch", shots=2)
        for _ in range(2):
            with pytest.raises(InjectedFault):
                inj.on_dispatch(rung=RUNG_HOISTED)
        inj.on_dispatch(rung=RUNG_HOISTED)  # shots exhausted: clean
        assert inj.injected["raise-dispatch"] == 2

    def test_min_rung_filter(self):
        """A pallas-only fault must not fire on hoisted dispatches —
        the shape the ladder demotion is supposed to escape."""
        inj = FaultInjector()
        inj.arm("raise-dispatch", shots=-1, min_rung=RUNG_PALLAS)
        inj.on_dispatch(rung=RUNG_HOISTED)  # below min_rung: clean
        with pytest.raises(InjectedFault):
            inj.on_dispatch(rung=RUNG_PALLAS)
        inj.disarm("raise-dispatch")
        inj.on_dispatch(rung=RUNG_PALLAS)

    def test_wedge_consume(self):
        inj = FaultInjector()
        inj.arm("wedge-wait", shots=1)
        assert inj.wedge_active()
        inj.consume_wedge()
        assert not inj.wedge_active()
        assert inj.injected["wedge-wait"] == 1

    def test_wedge_rejects_min_rung(self):
        """A rung-filtered wedge could never consume its shot (the wait
        loop has no rung context) — a permanent outage masquerading as
        transient; arm() must refuse it."""
        inj = FaultInjector()
        with pytest.raises(ValueError):
            inj.arm("wedge-wait", shots=1, min_rung=RUNG_PALLAS)

    def test_wedged_probe_consumes_shot(self):
        """A wedge armed while the backend is demoted (no dispatch
        traffic) must be consumed by the probe's own timed-out wait, or
        the backend could never re-promote."""
        from kubernetes_tpu.scheduler.tpu_backend import TPUBackend

        b = TPUBackend()
        b.watchdog_timeout = 0.1
        inj = FaultInjector()
        b.faults = inj
        inj.arm("wedge-wait", shots=1)
        assert b._probe_device() is False  # wedged canary
        assert not inj.wedge_active()
        assert b._probe_device() is True  # shot consumed: device answers

    def test_corrupt_harvest_saturates_ints_and_nans_floats(self):
        import numpy as np

        inj = FaultInjector()
        inj.arm("nan-harvest", shots=1)
        ys = {"rows": np.zeros((8, 4), np.int32), "score": np.ones(4), "n": 2}
        bad = inj.corrupt_harvest(ys)
        assert bad["n"] == 2  # host scalars steer decode: untouched
        assert (np.asarray(bad["rows"]) == np.iinfo(np.int32).max).all()
        assert np.isnan(np.asarray(bad["score"])).all()
        # one shot: the next harvest is clean
        assert inj.corrupt_harvest(ys) is ys


class TestExecQuarantine:
    def test_retire_exec_pre_pins_fresh_cache(self):
        """A quarantined bucket must stay jit-only on a REBUILT session:
        retire_exec(bucket=...) pins entries that do not exist yet, and
        the serving/warm paths never recompile a pinned (None) entry."""
        from types import SimpleNamespace

        from kubernetes_tpu.ops.pallas_scan import PallasSession

        fresh = SimpleNamespace(_exec={})
        n = PallasSession.retire_exec(fresh, bucket=128)
        assert n == 3
        assert fresh._exec == {(128, "full"): None, (128, "eval"): None,
                               (128, "apply"): None}
        # idempotent; other buckets untouched
        assert PallasSession.retire_exec(fresh, bucket=128) == 0
        live = SimpleNamespace(_exec={(256, "full"): object(),
                                      (128, "full"): object()})
        assert PallasSession.retire_exec(live, bucket=128, mode="full") == 1
        assert live._exec[(128, "full")] is None
        assert live._exec[(256, "full")] is not None
        # blanket retirement pins every existing entry
        assert PallasSession.retire_exec(live) == 1
        assert live._exec[(256, "full")] is None

    def test_backend_tracks_and_lifts_suspect_buckets(self):
        from kubernetes_tpu.scheduler.tpu_backend import TPUBackend

        b = TPUBackend()
        b._device_fault_locked("invalid", buckets={128, None})
        assert b._suspect_buckets == {128}
        # a clean harvest of that bucket lifts the quarantine
        # (_harvest_locked discards on success — exercised end-to-end in
        # the parity tests; here the bookkeeping contract)
        b._suspect_buckets.discard(128)
        assert not b._suspect_buckets


class TestScheduleRetryPaths:
    def test_zero_feasible_still_raises_fit_error(self):
        """The watchdog/retry refactor must keep schedule()'s FitError
        contract intact: an unfittable pod gets per-node statuses, not a
        crash (regression: `out` once leaked into the nested attempt)."""
        from kubernetes_tpu.scheduler.framework.interface import FitError
        from kubernetes_tpu.scheduler.tpu_backend import TPUBackend

        from .util import make_node

        b = TPUBackend()
        for i in range(3):
            b.on_add_node(make_node(f"n-{i}", cpu="2", memory="4Gi"))
        giant = make_pod("giant", cpu="64", memory="1Gi")
        with pytest.raises(FitError) as e:
            b.schedule(giant)
        assert len(e.value.filtered_nodes_statuses) == 3

    def test_oracle_rung_raises_device_fault_without_dispatch(self):
        """At the oracle rung schedule()/reevaluate() must not touch the
        device at all — raise/RETRY immediately (the scheduler routes
        the pods through the oracle)."""
        from kubernetes_tpu.scheduler.degradation import DeviceFault
        from kubernetes_tpu.scheduler.tpu_backend import RETRY_NODE, TPUBackend

        from .util import make_node

        b = TPUBackend()
        b.on_add_node(make_node("n-0", cpu="8", memory="16Gi"))
        while b.ladder.demote():
            pass
        assert b.ladder.rung() == RUNG_ORACLE
        inj = FaultInjector()
        b.faults = inj
        inj.arm("raise-dispatch", shots=-1)  # would fire on any dispatch
        with pytest.raises(DeviceFault):
            b.schedule(make_pod("p", cpu="100m"))
        nodes = b.reevaluate([make_pod("q", cpu="100m")])
        assert nodes == [(RETRY_NODE, {})]
        assert not inj.injected, "device was dispatched at the oracle rung"


# -- unit: degradation ladder ----------------------------------------------


class TestDegradationLadder:
    def test_demotes_pallas_hoisted_oracle_and_repromotes(self):
        """The full ladder walk the acceptance criterion names, with the
        scheduler_backend_mode gauge tracking every transition."""
        ladder = DegradationLadder(top=RUNG_PALLAS, threshold=3)
        assert ladder.mode() == "pallas"
        assert metrics.backend_mode.value() == RUNG_PALLAS
        for expected in ("hoisted", "oracle"):
            demoted = [ladder.record_fault("raise") for _ in range(3)]
            assert demoted == [False, False, True]
            assert ladder.mode() == expected
            assert metrics.backend_mode.value() == ladder.rung()
        # already at the floor: more faults cannot demote further
        for _ in range(5):
            assert not ladder.record_fault("raise")
        assert ladder.mode() == "oracle" and ladder.demotions == 2
        # probe recovery is stepwise: oracle -> hoisted -> pallas
        assert ladder.on_probe(True) and ladder.mode() == "hoisted"
        assert ladder.on_probe(True) and ladder.mode() == "pallas"
        assert not ladder.on_probe(True)  # at top: no-op
        assert ladder.promotions == 2
        assert metrics.backend_mode.value() == RUNG_PALLAS

    def test_success_resets_consecutive_count(self):
        ladder = DegradationLadder(top=RUNG_HOISTED, threshold=2)
        assert not ladder.record_fault()
        ladder.record_success()
        assert not ladder.record_fault()  # count restarted: no demotion
        assert ladder.mode() == "hoisted"

    def test_failed_probe_backs_off_capped(self):
        ladder = DegradationLadder(
            top=RUNG_HOISTED, threshold=1, probe_interval=0.1, probe_max=0.4,
            rng=random.Random(0),
        )
        ladder.record_fault()
        delays = []
        for _ in range(4):
            delays.append(ladder.probe_delay())
            ladder.on_probe(False)
        # base delay doubles each failure, capped (jitter <= 2x base)
        assert delays[0] < delays[-1] <= 0.4 * 2
        # promotion does NOT restore the cadence (flap hysteresis: the
        # canary vouches for the device, not the kernel at the target
        # rung — a fault right after re-promotion must find the probe
        # still backed off) …
        ladder.on_probe(True)
        assert ladder.probe_delay() > 0.1 * 2
        # … only a clean harvest at the top rung does
        ladder.record_success()
        assert ladder.probe_delay() <= 0.1 * 2

    def test_flap_hysteresis_decays_to_probe_max(self):
        """Kernel-level fault invisible to the canary: demote → clean
        probe → promote → demote … — each demotion doubles the cadence,
        so the whipsaw decays to once per probe_max instead of spinning
        at probe_interval forever."""
        ladder = DegradationLadder(
            top=RUNG_HOISTED, threshold=1, probe_interval=0.1, probe_max=0.4,
            rng=random.Random(0),
        )
        for _ in range(4):  # flap cycles
            ladder.record_fault()
            assert ladder.on_probe(True)
        assert ladder.probe_delay() >= 0.4  # pinned at the cap


# -- fault parity: transient faults, exact-decision recovery ----------------


def _drive_with_faults(seed, arm_plan, n=32, watchdog=0.5):
    """Run the same pod stream at depth 0 (clean) and depth 2 (faults
    armed per `arm_plan`: batch_index -> (kind, shots kwargs)); return
    both bound maps plus the injector."""
    rng = random.Random(seed)
    batch_sizes = [rng.choice([2, 3, 5]) for _ in range(32)]
    maps = {}
    inj = None
    for depth in (0, 2):
        _, cs = _cluster()
        sched = _mk_scheduler(cs, depth)
        try:
            if depth:
                inj = FaultInjector()
                sched.install_fault_injector(inj)
                sched.tpu.watchdog_timeout = watchdog
                orig = type(sched.tpu).dispatch_many
                count = {"batches": 0}

                def arming(self, pods, _orig=orig, _c=count, _inj=inj):
                    kind = arm_plan.get(_c["batches"])
                    if kind is not None:
                        _inj.arm(kind, shots=1)
                    _c["batches"] += 1
                    return _orig(self, pods)

                sched.tpu.dispatch_many = arming.__get__(sched.tpu)
            pods = _pod_stream(random.Random(seed), n)
            _drive(sched, cs, pods, batch_sizes)
            maps[depth] = _bound_map(cs)
        finally:
            sched.shutdown()
            sched.informers.stop()
    return maps, inj


class TestFaultParity:
    def test_raise_dispatch_recovers_bit_identical(self):
        before = _counter_snapshot()
        maps, inj = _drive_with_faults(3, {1: "raise-dispatch"})
        assert inj.injected.get("raise-dispatch", 0) >= 1
        assert maps[0] == maps[2], "raise-recovery changed decisions"
        assert _fault_delta(before, "raise") >= 1

    def test_nan_harvest_detected_and_recovered(self):
        """Garbage payloads must be caught by the validation guard BEFORE
        assume — silently corrupt placements are the worst outcome."""
        before = _counter_snapshot()
        maps, inj = _drive_with_faults(4, {2: "nan-harvest"})
        assert inj.injected.get("nan-harvest", 0) >= 1
        assert maps[0] == maps[2], "NaN harvest leaked into decisions"
        assert _fault_delta(before, "invalid") >= 1

    def test_wedged_wait_hits_watchdog_and_recovers(self):
        before = _counter_snapshot()
        maps, inj = _drive_with_faults(5, {1: "wedge-wait"}, watchdog=0.3)
        assert inj.injected.get("wedge-wait", 0) >= 1
        assert maps[0] == maps[2], "wedge recovery changed decisions"
        assert _fault_delta(before, "timeout") >= 1

    def test_fault_storm_parity(self):
        """Rotating transient faults across the stream: in-order
        synchronous re-drive keeps exact decision parity."""
        plan = {1: "raise-dispatch", 3: "nan-harvest", 5: "wedge-wait",
                7: "raise-dispatch"}
        before = _counter_snapshot()
        maps, inj = _drive_with_faults(6, plan, n=40, watchdog=0.3)
        assert sum(inj.injected.values()) >= 3
        assert maps[0] == maps[2]
        assert metrics.dispatch_retries.value() > before["retries"]
        # transient faults spaced out by clean batches never demote
        # (consecutive-fault accounting resets on every clean harvest)


# -- supervised workers ------------------------------------------------------


class TestSupervisedWorkers:
    def test_completion_worker_kill_drains_fifo_and_restarts(self):
        """Kill the completion worker mid-stream: the supervisor drains
        the in-flight FIFO back to the queue, restarts the worker, and
        every schedulable pod still binds exactly once (same bound SET
        as the clean reference; placements may legally differ because
        requeued pods re-enter in a different order)."""
        seed = 11
        rng = random.Random(seed)
        batch_sizes = [rng.choice([2, 3, 5]) for _ in range(32)]
        sets = {}
        before = _counter_snapshot()
        for depth in (0, 2):
            _, cs = _cluster()
            sched = _mk_scheduler(cs, depth)
            try:
                if depth:
                    inj = FaultInjector()
                    sched.install_fault_injector(inj)
                    orig = type(sched.tpu).dispatch_many
                    count = {"batches": 0}

                    def arming(self, pods, _orig=orig, _c=count, _inj=inj):
                        if _c["batches"] == 2:
                            _inj.arm("kill-completion", shots=1)
                        _c["batches"] += 1
                        return _orig(self, pods)

                    sched.tpu.dispatch_many = arming.__get__(sched.tpu)
                pods = _pod_stream(random.Random(seed), 32)
                _drive(sched, cs, pods, batch_sizes)
                if depth:
                    # requeued pods from the drained FIFO: keep popping
                    # until the queue is quiet again
                    deadline = time.monotonic() + 30
                    while time.monotonic() < deadline:
                        if not sched.schedule_one(timeout=0.2):
                            break
                    assert sched._drain_pipeline(timeout=30)
                    assert inj.injected.get("kill-completion", 0) == 1
                bound = _bound_map(cs)
                sets[depth] = {k for k, v in bound.items() if v}
            finally:
                sched.shutdown()
                sched.informers.stop()
        assert sets[0] == sets[2], "worker kill lost or duplicated pods"
        assert _restart_delta(before, "completion") >= 1

    def test_scheduler_thread_kill_restarts_and_schedules(self):
        before = _counter_snapshot()
        _, cs = _cluster()
        sched = _mk_scheduler(cs, 2)
        try:
            inj = FaultInjector()
            sched.install_fault_injector(inj)
            sched.start()
            inj.arm("kill-scheduler", shots=1)
            assert wait_until(
                lambda: inj.injected.get("kill-scheduler", 0) == 1, 10
            ), "kill never fired"
            for i in range(8):
                cs.pods.create(make_pod(
                    f"p-{i}", namespace="default", cpu="100m",
                    labels={"app": "plain"},
                ))
            assert wait_until(
                lambda: all(_bound_map(cs).values()) and len(_bound_map(cs)) == 8,
                30,
            ), f"pods not scheduled after restart: {_bound_map(cs)}"
            assert _restart_delta(before, "scheduler") >= 1
        finally:
            sched.shutdown()
            sched.informers.stop()


# -- degradation ladder end-to-end ------------------------------------------


class TestLadderIntegration:
    def test_demote_to_oracle_then_repromote(self):
        """Persistent dispatch faults walk the backend down to the
        oracle rung (scheduling continues!); disarming the fault lets
        the background probe re-promote — asserted through the
        scheduler_backend_mode gauge and the fault/retry counters, per
        the acceptance criteria."""
        before = _counter_snapshot()
        _, cs = _cluster()
        sched = _mk_scheduler(cs, 2)
        try:
            inj = FaultInjector()
            sched.install_fault_injector(inj)
            tpu = sched.tpu
            tpu.watchdog_timeout = 0.5
            tpu.retry_base = 0.01
            tpu.ladder.threshold = 2
            tpu.ladder._probe_interval = 0.05
            tpu.ladder._probe_delay = 0.05
            assert tpu.ladder.rung() == RUNG_HOISTED  # CPU top rung
            inj.arm("raise-dispatch", shots=-1)  # persistent device fault
            sched.start()
            for i in range(8):
                cs.pods.create(make_pod(
                    f"p-{i}", namespace="default", cpu="100m",
                    labels={"app": "plain"},
                ))
            # the ladder must hit the oracle rung and STILL schedule
            assert wait_until(
                lambda: tpu.ladder.rung() == RUNG_ORACLE, 30
            ), "never demoted to oracle"
            assert metrics.backend_mode.value() == RUNG_ORACLE
            assert wait_until(
                lambda: all(_bound_map(cs).values()) and len(_bound_map(cs)) == 8,
                30,
            ), f"oracle rung failed to bind: {_bound_map(cs)}"
            assert _fault_delta(before, "raise") >= 2
            assert metrics.dispatch_retries.value() > before["retries"]
            assert tpu.ladder.demotions >= 1
            # device heals: the probe must re-promote to the top rung
            inj.disarm("raise-dispatch")
            assert wait_until(
                lambda: tpu.ladder.rung() == RUNG_HOISTED, 30
            ), "probe never re-promoted"
            assert metrics.backend_mode.value() == RUNG_HOISTED
            assert tpu.ladder.promotions >= 1
            # and the kernel path serves again at the restored rung
            for i in range(8, 12):
                cs.pods.create(make_pod(
                    f"p-{i}", namespace="default", cpu="100m",
                    labels={"app": "plain"},
                ))
            assert wait_until(
                lambda: all(_bound_map(cs).values()) and len(_bound_map(cs)) == 12,
                30,
            )
        finally:
            sched.shutdown()
            sched.informers.stop()


# -- drain timeout + shutdown ------------------------------------------------


class TestDrainAndShutdown:
    def test_drain_pipeline_times_out_and_demotes(self):
        """A wedge that outlives even the watchdog budget must not hang
        _drain_pipeline (the oracle/nominated paths run through it):
        it demotes and raises instead."""
        _, cs = _cluster()
        sched = _mk_scheduler(cs, 2)
        try:
            inj = FaultInjector()
            sched.install_fault_injector(inj)
            sched.tpu.watchdog_timeout = 60  # wedge outlives the drain
            for i in range(4):
                cs.pods.create(make_pod(
                    f"p-{i}", namespace="default", cpu="100m",
                    labels={"app": "plain"},
                ))
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and sched.queue.num_active() < 4:
                time.sleep(0.02)
            infos = []
            while True:
                nxt = sched.queue.pop(timeout=0)
                if nxt is None:
                    break
                infos.append(nxt)
            # first batch rides the sync path and builds the session …
            sched._schedule_batch_tpu(infos[:2])
            assert sched._drain_pipeline(timeout=30)
            # … the second is a genuinely async dispatch that wedges
            inj.arm("wedge-wait", shots=-1)
            sched._schedule_batch_tpu(infos[2:])
            rung_before = sched.tpu.ladder.rung()
            with pytest.raises(PipelineStalled):
                sched._drain_pipeline(timeout=0.5)
            assert sched.tpu.ladder.rung() < rung_before
        finally:
            inj.disarm()
            sched.tpu.watchdog_timeout = 0.5
            sched.shutdown()
            sched.informers.stop()

    def test_shutdown_joins_workers_and_flushes_fifo(self):
        _, cs = _cluster()
        sched = _mk_scheduler(cs, 2)
        sched.start()
        try:
            for i in range(12):
                cs.pods.create(make_pod(
                    f"p-{i}", namespace="default", cpu="100m",
                    labels={"app": "plain"},
                ))
            assert wait_until(
                lambda: len(_bound_map(cs)) == 12 and
                all(_bound_map(cs).values()), 30)
        finally:
            assert sched.shutdown() is True
            sched.informers.stop()
        assert not sched._completions, "pending FIFO not flushed"
        for t in (sched._thread, sched._completion_thread,
                  sched._permit_thread):
            assert t is None or not t.is_alive(), f"leaked thread {t}"
        probe = sched.tpu._probe_thread
        assert probe is None or not probe.is_alive(), "leaked probe thread"
        assert sched.shutdown() is True  # idempotent


# -- what-if (device preemption planner) fault drills -----------------------


class TestWhatifFaults:
    """PR-7 drill: a device fault MID-WHAT-IF falls the preemptor one
    planner rung (device -> fast) with zero double-claimed victims, a
    clean BindIntegrityChecker, and ZERO live-session invalidations —
    the what-if runs on a scratch snapshot, so planning must never
    charge the session-rebuild counter."""

    def _preemption_cluster(self):
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client import Clientset, SharedInformerFactory
        from kubernetes_tpu.scheduler.scheduler import Scheduler
        from kubernetes_tpu.testing.synth import make_node

        api = APIServer()
        cs = Clientset(api)
        cs.nodes.create(make_node("n0", cpu="4", pods=10))
        for j in range(4):
            cs.pods.create(make_pod(
                f"low{j}", namespace="default", cpu="900m", memory="64Mi",
                priority=1,
            ))
        factory = SharedInformerFactory(cs)
        sched = Scheduler(cs, factory, backend="tpu",
                          pod_initial_backoff=30.0, pod_max_backoff=30.0)
        sched.tpu.whatif = True  # platform default is off on CPU
        factory.start()
        assert factory.wait_for_cache_sync()
        return cs, factory, sched

    def _run_drill(self, arm_fault: bool):
        from kubernetes_tpu.testing.faults import BindIntegrityChecker

        cs, factory, sched = self._preemption_cluster()
        checker = BindIntegrityChecker().attach(
            factory.informer_for("pods"))
        inj = FaultInjector()
        sched.install_fault_injector(inj)
        sched.start()
        try:
            assert wait_until(
                lambda: sum(
                    1 for p in cs.pods.list(namespace="default")[0]
                    if p.spec.node_name
                ) == 4,
                timeout=30,
            ), "low pods did not bind"
            rebuilds0 = sum(
                v for _, v in metrics.session_rebuilds.items())
            paths0 = dict(metrics.preemption_planner.items())
            fb0 = dict(metrics.whatif_fallbacks.items())
            if arm_fault:
                inj.arm("raise-whatif", shots=1)
            hi = make_pod("hi", namespace="default", cpu="900m",
                          memory="64Mi", priority=100)
            cs.pods.create(hi)
            assert wait_until(
                lambda: bool(
                    cs.pods.get("hi", "default").spec.node_name),
                timeout=20,
            ), "preemptor did not bind"
            assert cs.pods.get("hi", "default").spec.node_name == "n0"
            # exactly one victim evicted (no double-claim): 3 low pods
            # survive bound
            pods, _ = cs.pods.list(namespace="default")
            survivors = [
                p for p in pods
                if p.metadata.name.startswith("low") and p.spec.node_name
            ]
            assert len(survivors) == 3
            assert checker.violations == []
            # planning never tore the live session down
            assert sum(
                v for _, v in metrics.session_rebuilds.items()
            ) == rebuilds0
            paths = {
                k: v - paths0.get(k, 0)
                for k, v in metrics.preemption_planner.items()
                if v - paths0.get(k, 0)
            }
            fb = {
                k: v - fb0.get(k, 0)
                for k, v in metrics.whatif_fallbacks.items()
                if v - fb0.get(k, 0)
            }
            return paths, fb, inj
        finally:
            sched.stop()
            factory.stop()

    def test_clean_run_plans_on_device_without_rebuilds(self):
        paths, fb, _ = self._run_drill(arm_fault=False)
        assert paths.get(("device",), 0) >= 1, paths
        assert not fb, fb

    def test_injected_fault_falls_one_rung_cleanly(self):
        before = _counter_snapshot()
        paths, fb, inj = self._run_drill(arm_fault=True)
        assert inj.injected.get("raise-whatif") == 1
        assert fb.get(("fault",), 0) >= 1, fb
        assert paths.get(("fast",), 0) >= 1, paths
        # the fault is a real device fault to the ladder/counters
        assert _fault_delta(before, "raise") >= 1


# -- flight-recorder dump-on-fault drills (observability PR) ----------------
# The fault seams must leave a TRIAGEABLE record, not just counters: a
# watchdog timeout / validation fault dumps the ring (with the faulted
# batch's bucket/rung/speculation state in the fault attrs and the
# faulted dispatch's spans in the events) BEFORE recovery proceeds, and
# the recovery re-drive itself lands in the ring after. With KTPU_TRACE=0
# the dispatch path allocates nothing for tracing (the overhead pin).


class TestFlightRecorderDumpDrills:
    @pytest.fixture(autouse=True)
    def _traced(self):
        from kubernetes_tpu.utils import tracing

        old = tracing.set_level(tracing.TRACE_PODS)
        tracing.RECORDER.clear()
        yield
        tracing.set_level(old)
        tracing.RECORDER.clear()

    def _dump_drill(self, seed, kind, watchdog=0.5):
        from kubernetes_tpu.utils import tracing

        h0 = len(tracing.RECORDER.dump_history)
        dumps0 = sum(v for _, v in metrics.trace_dumps.items())
        maps, inj = _drive_with_faults(seed, {1: kind}, watchdog=watchdog)
        assert inj.injected.get(kind, 0) >= 1
        assert maps[0] == maps[2], "fault recovery changed decisions"
        new_dumps = tracing.RECORDER.dump_history[h0:]
        assert sum(v for _, v in metrics.trace_dumps.items()) > dumps0
        return maps, new_dumps

    def test_wedge_dump_names_faulted_batch_and_redrives(self):
        from kubernetes_tpu.utils import tracing

        _, dumps = self._dump_drill(11, "wedge-wait", watchdog=0.3)
        timeout_dumps = [
            d for d in dumps if d["reason"] == "device-fault-timeout"
        ]
        assert timeout_dumps, "watchdog fault fired without a dump"
        d = timeout_dumps[0]
        # the dump names the faulted batch's bucket, rung, speculation
        assert d["attrs"]["kind"] == "timeout"
        assert d["attrs"]["rung"] in ("pallas", "hoisted", "oracle")
        assert "speculative" in d["attrs"] and "bucket" in d["attrs"]
        stages = {e["stage"] for e in d["events"]}
        assert "dispatch" in stages, "faulted dispatch's spans missing"
        assert any(
            e["stage"] == "fault" and e.get("kind") == "timeout"
            for e in d["events"]
        )
        # the recovery re-drive is recorded after the dump: a final
        # snapshot holds the synchronous replay span, and the snapshot
        # itself lands in the dump history like any other dump
        events = tracing.RECORDER.dump("drill-final")
        assert any(
            e[2] == "replay" and e[1] == "re-drive"
            and e[6] and e[6].get("kind") == "timeout"
            for e in events
        ), "recovery re-drive span missing from the record"
        assert tracing.RECORDER.dump_history[-1]["reason"] == "drill-final"

    def test_nan_harvest_dump_fires_on_validation_fault(self):
        _, dumps = self._dump_drill(4, "nan-harvest")
        invalid = [
            d for d in dumps if d["reason"] == "device-fault-invalid"
        ]
        assert invalid, "validation fault fired without a dump"
        assert invalid[0]["attrs"]["kind"] == "invalid"
        assert "rung" in invalid[0]["attrs"]
        stages = {e["stage"] for e in invalid[0]["events"]}
        assert "dispatch" in stages

    def test_disabled_trace_adds_no_per_pod_state_on_dispatch(self):
        """KTPU_TRACE=0 overhead pin: the dispatch path must not
        allocate tracing state — span() returns the shared no-op
        singleton, handles carry prov=None, the ring stays empty, and
        no dump fires on a clean run."""
        from kubernetes_tpu.utils import tracing

        tracing.set_level(0)
        tracing.RECORDER.clear()
        h0 = len(tracing.RECORDER.dump_history)
        assert tracing.span("dispatch", "dispatch", n=8) \
            is tracing.NOOP_SPAN
        assert tracing.span("harvest", "harvest") is tracing.NOOP_SPAN
        _, cs = _cluster()
        sched = _mk_scheduler(cs, 2)
        handles = []
        orig = type(sched.tpu).dispatch_many

        def capture(self, pods, _orig=orig):
            h = _orig(self, pods)
            handles.append(h)
            return h

        sched.tpu.dispatch_many = capture.__get__(sched.tpu)
        try:
            pods = _pod_stream(random.Random(3), 16)
            _drive(sched, cs, pods, [4, 4, 4, 4])
        finally:
            sched.shutdown()
            sched.informers.stop()
        assert handles, "no batches dispatched"
        assert all(h.prov is None for h in handles)
        assert tracing.RECORDER.snapshot() == []
        assert len(tracing.RECORDER.dump_history) == h0
