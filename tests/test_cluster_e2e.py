"""Full-cluster end-to-end: apiserver + controller manager + scheduler +
hollow kubelets, including node-failure detection and elastic recovery.

Reference shape: test/e2e (real cluster suites) + kubemark scale runs +
nodelifecycle failure handling (node_lifecycle_controller.go:756
monitorNodeHealth, taint manager NoExecute eviction).
"""

import time

import pytest

from kubernetes_tpu.api import apps
from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.manager import ControllerManager
from kubernetes_tpu.kubemark import HollowCluster
from kubernetes_tpu.scheduler.apis.config import default_configuration
from kubernetes_tpu.scheduler.factory import create_scheduler

from .util import FAST_KUBELET, wait_until



@pytest.fixture()
def full_cluster():
    api = APIServer()
    cs = Clientset(api)
    hollow = HollowCluster(cs, n_nodes=5, config_overrides=FAST_KUBELET)
    hollow.start()

    kcm = ControllerManager(
        cs,
        controllers=["replicaset", "deployment", "nodelifecycle"],
        node_monitor_period=0.3,
        node_monitor_grace_period=2.0,
    )
    kcm.run()

    sched_factory = SharedInformerFactory(cs)
    cfg = default_configuration()
    cfg.profiles[0].backend = "oracle"
    sched = create_scheduler(cs, sched_factory, cfg)
    sched_factory.start()
    assert sched_factory.wait_for_cache_sync()
    sched.start()

    yield api, cs, hollow

    sched.stop()
    sched_factory.stop()
    kcm.stop()
    hollow.stop()


def test_deployment_runs_on_hollow_nodes(full_cluster):
    api, cs, hollow = full_cluster
    cs.deployments.create(
        apps.Deployment(
            metadata=v1.ObjectMeta(name="web", namespace="default"),
            spec=apps.DeploymentSpec(
                replicas=10,
                selector=v1.LabelSelector(match_labels={"app": "web"}),
                template=v1.PodTemplateSpec(
                    metadata=v1.ObjectMeta(labels={"app": "web"}),
                    spec=v1.PodSpec(
                        containers=[
                            v1.Container(
                                name="c",
                                image="img:1",
                                resources=v1.ResourceRequirements(
                                    requests={"cpu": "100m"}
                                ),
                            )
                        ]
                    ),
                ),
            ),
        )
    )

    def all_running():
        pods, _ = cs.pods.list(namespace="default")
        return len(pods) == 10 and all(
            p.spec.node_name and p.status.phase == "Running" for p in pods
        )

    assert wait_until(all_running, timeout=60), [
        (p.metadata.name, p.spec.node_name, p.status.phase)
        for p in cs.pods.list(namespace="default")[0]
    ]
    assert wait_until(
        lambda: cs.deployments.get("web", "default").status.available_replicas == 10
    )


def test_node_failure_detection_and_recovery(full_cluster):
    """Kill a kubelet; the nodelifecycle controller must detect the stale
    heartbeat, taint the node NoExecute, evict its pods, and the
    replicaset + scheduler must re-run them elsewhere."""
    api, cs, hollow = full_cluster
    cs.deployments.create(
        apps.Deployment(
            metadata=v1.ObjectMeta(name="ha", namespace="default"),
            spec=apps.DeploymentSpec(
                replicas=5,
                selector=v1.LabelSelector(match_labels={"app": "ha"}),
                template=v1.PodTemplateSpec(
                    metadata=v1.ObjectMeta(labels={"app": "ha"}),
                    spec=v1.PodSpec(
                        containers=[
                            v1.Container(
                                name="c",
                                image="img:1",
                                resources=v1.ResourceRequirements(
                                    requests={"cpu": "100m"}
                                ),
                            )
                        ]
                    ),
                ),
            ),
        )
    )

    def n_running():
        pods, _ = cs.pods.list(namespace="default")
        return sum(
            1
            for p in pods
            if p.spec.node_name and p.status.phase == "Running"
        )

    assert wait_until(lambda: n_running() == 5, timeout=60)

    # pick a node that actually runs a pod and kill its kubelet
    pods, _ = cs.pods.list(namespace="default")
    victim_node = next(p.spec.node_name for p in pods if p.spec.node_name)
    victim = next(
        kl for kl in hollow.kubelets if kl.config.node_name == victim_node
    )
    victim.stop()

    def node_unreachable():
        node = cs.nodes.get(victim_node)
        return any(
            t.key == v1.TAINT_NODE_UNREACHABLE for t in node.spec.taints or []
        )

    assert wait_until(node_unreachable, timeout=30)

    def recovered():
        pods, _ = cs.pods.list(namespace="default")
        running = [
            p
            for p in pods
            if p.status.phase == "Running" and p.spec.node_name != victim_node
        ]
        return len(running) == 5

    assert wait_until(recovered, timeout=60), [
        (p.metadata.name, p.spec.node_name, p.status.phase)
        for p in cs.pods.list(namespace="default")[0]
    ]
