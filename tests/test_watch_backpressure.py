"""Slow-consumer backpressure on the HTTP watch wire (ISSUE 11 tentpole).

A watcher that cannot drain its bounded send buffer must be EVICTED —
counted (apiserver_watch_evictions_total) and hard-closed — while every
other watcher of the same hub keeps streaming untouched. Eviction is
safe by the existing contract: the client sees EOF, RemoteWatch sets
`closed`, and its reflector recovers via re-list+re-watch.

Exercised over REAL sockets (HTTPAPIServer): the stalled reader is a raw
socket that never reads, with the kernel buffers pinned small (listener
SO_SNDBUF + client SO_RCVBUF) so the writer thread wedges after a few
KiB instead of megabytes.
"""

from __future__ import annotations

import socket
import threading
import time

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.apiserver.http import (
    HTTPAPIServer,
    RemoteAPIServer,
    watch_evictions,
)
from kubernetes_tpu.client import Clientset, SharedInformerFactory

from .util import make_pod


def _wait(fn, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def hub():
    api = APIServer()
    server = HTTPAPIServer(api)
    # pin the kernel buffers SMALL so a non-reading peer wedges the
    # writer thread within a few KiB: accepted sockets inherit SNDBUF
    # from the listener; the client side caps RCVBUF before connect
    server._httpd.socket.setsockopt(
        socket.SOL_SOCKET, socket.SO_SNDBUF, 8192)
    server.start()
    try:
        yield server
    finally:
        server.stop()


def _stalled_watcher(hub):
    """A raw-socket pod watcher that NEVER reads its response."""
    host, port = hub._httpd.server_address[:2]
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
    s.connect((host, port))
    s.sendall(
        b"GET /api/v1/namespaces/default/pods?watch=true HTTP/1.1\r\n"
        b"Host: x\r\n\r\n"
    )
    return s


def _pump(api, pod, n, payload_kib=2):
    """n MODIFIED events of ~payload_kib KiB each through the store."""
    blob = "x" * (payload_kib * 1024)
    for i in range(n):
        pod.metadata.annotations = {"seq": str(i), "blob": blob}
        pod = api.update("pods", pod)
    return pod


def _socket_saw_eof(s, timeout=10.0):
    """Drain until EOF/RST: either is the close the reflector acts on."""
    s.settimeout(timeout)
    try:
        while True:
            if not s.recv(65536):
                return True
    except (ConnectionResetError, OSError):
        return True
    finally:
        s.close()


def test_byte_budget_eviction_over_real_http(hub):
    """Overflow the bounded send buffer of a never-reading watcher: it
    is evicted and hard-closed; a fast RemoteWatch on the same hub
    streams through the whole storm and keeps receiving afterwards."""
    hub.watch_buffer_bytes = 32 * 1024
    api = hub.api
    pod = api.create("pods", make_pod("victim", namespace="default",
                                      cpu="100m"))
    ev0 = watch_evictions.value()
    remote = RemoteAPIServer(hub.address)
    fast = remote.watch("pods", namespace="default")
    fast_seen = []
    stop = threading.Event()

    def drain():
        while not stop.is_set():
            ev = fast.poll(timeout=0.1)
            if ev is not None:
                fast_seen.append(ev)

    dt = threading.Thread(target=drain, daemon=True)
    dt.start()
    slow = _stalled_watcher(hub)
    assert _wait(lambda: hub.watcher_count == 2)

    # pump in waves with drain gaps: the wedged watcher's buffer only
    # grows until it bursts its budget, while the fast consumer keeps
    # emptying its own between waves
    for _ in range(100):
        pod = _pump(api, pod, 10)
        time.sleep(0.02)
        if watch_evictions.value() > ev0:
            break
    assert watch_evictions.value() - ev0 == 1, (
        "expected exactly the stalled watcher evicted"
    )
    # the evicted stream is hard-closed: EOF/RST at the client = the
    # re-list signal (RemoteWatch.closed fires on exactly this)
    assert _socket_saw_eof(slow)
    assert _wait(lambda: hub.watcher_count == 1), (
        "evicted stream never released its watcher slot"
    )

    # the fast consumer lived through the storm AND still receives
    pod.metadata.annotations = {"after": "eviction"}
    pod = api.update("pods", pod)
    assert _wait(lambda: any(
        (e.object.metadata.annotations or {}).get("after") == "eviction"
        for e in fast_seen))
    stop.set()
    dt.join(timeout=2)
    fast.stop()
    assert _wait(lambda: hub.watcher_count == 0)


def test_no_drain_stall_eviction(hub):
    """The stall clock: a watcher with frames queued and NO socket-write
    progress for watch_evict_after seconds is evicted even far below the
    byte budget (heartbeats run the clock on an otherwise idle watch)."""
    hub.watch_buffer_bytes = 64 * 1024 * 1024  # byte budget out of play
    hub.watch_evict_after = 0.5
    api = hub.api
    pod = api.create("pods", make_pod("victim", namespace="default",
                                      cpu="100m"))
    ev0 = watch_evictions.value()
    slow = _stalled_watcher(hub)
    assert _wait(lambda: hub.watcher_count == 1)
    # enough volume to wedge the writer mid-write (kernel buffers are
    # pinned to a few KiB), then go IDLE: the heartbeat path must still
    # notice the stall and evict
    for _ in range(50):
        pod = _pump(api, pod, 5)
        if watch_evictions.value() > ev0:
            break
        time.sleep(0.1)
    assert _wait(lambda: watch_evictions.value() > ev0, timeout=15), (
        "stalled watcher with queued frames was never evicted"
    )
    assert _socket_saw_eof(slow)
    assert _wait(lambda: hub.watcher_count == 0)


def test_informer_survives_a_neighboring_eviction(hub):
    """A full reflector/informer stack on the same hub keeps its cache
    in sync while a stalled neighbor is evicted — the hub's fan-out is
    never blocked by the wedged peer."""
    hub.watch_buffer_bytes = 16 * 1024
    api = hub.api
    pod = api.create("pods", make_pod("victim", namespace="default",
                                      cpu="100m"))
    cs = Clientset(RemoteAPIServer(hub.address))
    factory = SharedInformerFactory(cs)
    informer = factory.pods()
    factory.start()
    assert factory.wait_for_cache_sync()
    ev0 = watch_evictions.value()
    slow = _stalled_watcher(hub)
    assert _wait(lambda: hub.watcher_count >= 2)
    for _ in range(100):
        pod = _pump(api, pod, 10)
        if watch_evictions.value() > ev0:
            break
    assert watch_evictions.value() > ev0
    assert _socket_saw_eof(slow)
    # the informer's cache converges on the post-storm state
    pod.metadata.annotations = {"final": "1"}
    api.update("pods", pod)
    def cache_final():
        got = informer.get("default/victim")
        return (got is not None
                and (got.metadata.annotations or {}).get("final") == "1")

    assert _wait(cache_final, timeout=10), (
        "informer cache fell behind after a neighbor eviction"
    )
    factory.stop()
