"""CRDs (apiextensions equivalent), feature gates, configz.

Reference shape: apiextensions-apiserver integration tests (CRD create ->
CR serving -> schema validation), component-base featuregate/configz unit
tests.
"""

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.crd import (
    CRDManager,
    CustomResourceDefinition,
    CustomResourceDefinitionNames,
    CustomResourceDefinitionSpec,
    CustomResourceDefinitionVersion,
    CustomResourceValidation,
    JSONSchemaProps,
    Unstructured,
)
from kubernetes_tpu.apiserver.server import APIServer, Invalid, NotFound
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.utils import configz
from kubernetes_tpu.utils.featuregate import (
    ALPHA,
    GA,
    FeatureGate,
    FeatureSpec,
)

from .util import wait_until


def _crd(with_schema=False):
    schema = None
    if with_schema:
        schema = CustomResourceValidation(
            open_apiv3_schema=JSONSchemaProps(
                type="object",
                required=["spec"],
                properties={
                    "spec": JSONSchemaProps(
                        type="object",
                        required=["replicas"],
                        properties={
                            "replicas": JSONSchemaProps(type="integer"),
                            "backends": JSONSchemaProps(
                                type="array",
                                items=JSONSchemaProps(type="string"),
                            ),
                        },
                    )
                },
            )
        )
    return CustomResourceDefinition(
        metadata=v1.ObjectMeta(name="widgets.example.com"),
        spec=CustomResourceDefinitionSpec(
            group="example.com",
            names=CustomResourceDefinitionNames(
                plural="widgets", singular="widget", kind="Widget"
            ),
            versions=[
                CustomResourceDefinitionVersion(name="v1", schema=schema)
            ],
        ),
    )


@pytest.fixture()
def cluster():
    api = APIServer()
    CRDManager(api).install()
    return api, Clientset(api)


class TestCRD:
    def test_crd_serves_custom_resource(self, cluster):
        api, cs = cluster
        cs.resource("customresourcedefinitions").create(_crd())
        created = cs.resource("widgets").create(
            Unstructured({
                "apiVersion": "example.com/v1",
                "kind": "Widget",
                "metadata": {"name": "w1", "namespace": "default"},
                "spec": {"replicas": 3},
            })
        )
        assert created.metadata.resource_version
        got = cs.resource("widgets").get("w1", "default")
        assert got["spec"] == {"replicas": 3}
        assert got.kind == "Widget"
        items, _ = cs.resource("widgets").list(namespace="default")
        assert len(items) == 1
        cs.resource("widgets").delete("w1", "default")
        with pytest.raises(NotFound):
            cs.resource("widgets").get("w1", "default")

    def test_crd_watch_and_informer(self, cluster):
        api, cs = cluster
        cs.resource("customresourcedefinitions").create(_crd())
        factory = SharedInformerFactory(cs)
        inf = factory.informer_for("widgets")
        factory.start()
        assert factory.wait_for_cache_sync()
        try:
            cs.resource("widgets").create(
                Unstructured({
                    "kind": "Widget",
                    "metadata": {"name": "w1", "namespace": "default"},
                })
            )
            assert wait_until(lambda: inf.get("default/w1") is not None)
        finally:
            factory.stop()

    def test_schema_validation(self, cluster):
        api, cs = cluster
        cs.resource("customresourcedefinitions").create(_crd(with_schema=True))
        with pytest.raises(Invalid):  # missing required spec
            cs.resource("widgets").create(
                Unstructured({"metadata": {"name": "bad", "namespace": "default"}})
            )
        with pytest.raises(Invalid):  # replicas wrong type
            cs.resource("widgets").create(
                Unstructured({
                    "metadata": {"name": "bad", "namespace": "default"},
                    "spec": {"replicas": "three"},
                })
            )
        with pytest.raises(Invalid):  # array item wrong type
            cs.resource("widgets").create(
                Unstructured({
                    "metadata": {"name": "bad", "namespace": "default"},
                    "spec": {"replicas": 1, "backends": ["a", 2]},
                })
            )
        cs.resource("widgets").create(
            Unstructured({
                "metadata": {"name": "ok", "namespace": "default"},
                "spec": {"replicas": 1, "backends": ["a", "b"]},
            })
        )

    def test_crd_name_validation(self, cluster):
        api, cs = cluster
        bad = _crd()
        bad.metadata.name = "wrong"
        with pytest.raises(Invalid):
            cs.resource("customresourcedefinitions").create(bad)

    def test_unknown_resource_without_crd(self, cluster):
        api, cs = cluster
        with pytest.raises(NotFound):
            cs.resource("widgets").list()

    def test_kubectl_resolves_custom_kind(self, cluster, tmp_path):
        import io

        import yaml

        from kubernetes_tpu.kubectl import Kubectl

        api, cs = cluster
        cs.resource("customresourcedefinitions").create(_crd())
        out = io.StringIO()
        k = Kubectl(cs, out=out)
        f = tmp_path / "w.yaml"
        f.write_text(
            yaml.safe_dump({
                "apiVersion": "example.com/v1",
                "kind": "Widget",
                "metadata": {"name": "w1"},
                "spec": {"replicas": 2},
            })
        )
        assert k.run(["create", "-f", str(f)]) == 0
        assert cs.resource("widgets").get("w1", "default")["spec"]["replicas"] == 2
        out.truncate(0), out.seek(0)
        assert k.run(["get", "widgets", "w1", "-o", "yaml"]) == 0
        doc = yaml.safe_load(out.getvalue())
        assert doc["spec"] == {"replicas": 2}


class TestFeatureGate:
    def test_stages_and_overrides(self):
        fg = FeatureGate({
            "A": FeatureSpec(default=False, pre_release=ALPHA),
            "B": FeatureSpec(default=True),
            "Locked": FeatureSpec(default=True, pre_release=GA, lock_to_default=True),
        })
        assert not fg.enabled("A")
        assert fg.enabled("B")
        fg.set_from_string("A=true, B=false")
        assert fg.enabled("A") and not fg.enabled("B")
        with pytest.raises(ValueError):
            fg.set("Locked", False)
        with pytest.raises(KeyError):
            fg.enabled("Nope")
        with pytest.raises(ValueError):
            fg.set_from_string("A=maybe")
        assert fg.state() == {"A": True, "B": False, "Locked": True}

    def test_duplicate_registration(self):
        fg = FeatureGate({"A": FeatureSpec(default=False)})
        fg.add({"A": FeatureSpec(default=False)})  # identical: ok
        with pytest.raises(ValueError):
            fg.add({"A": FeatureSpec(default=True)})


class TestConfigz:
    def test_install_snapshot(self):
        from kubernetes_tpu.scheduler.apis.config import default_configuration

        configz.install("kubescheduler.config.k8s.io", default_configuration())
        try:
            snap = configz.snapshot()
            assert "kubescheduler.config.k8s.io" in snap
            assert isinstance(snap["kubescheduler.config.k8s.io"], dict)
            body = configz.handler_body()
            assert "kubescheduler" in body
        finally:
            configz.delete("kubescheduler.config.k8s.io")
        assert "kubescheduler.config.k8s.io" not in configz.snapshot()


class TestCRDLifecycle:
    def test_crd_delete_unregisters(self, cluster):
        api, cs = cluster
        cs.resource("customresourcedefinitions").create(_crd())
        cs.resource("widgets").create(
            Unstructured({"metadata": {"name": "w1", "namespace": "default"}})
        )
        cs.resource("customresourcedefinitions").delete("widgets.example.com")
        with pytest.raises(NotFound):
            cs.resource("widgets").list()

    def test_rejected_write_does_not_change_serving(self, cluster):
        api, cs = cluster
        cs.resource("customresourcedefinitions").create(_crd(with_schema=True))
        # re-create same name WITHOUT schema: AlreadyExists — and the
        # schema must still be enforced afterwards
        from kubernetes_tpu.apiserver.server import AlreadyExists

        with pytest.raises(AlreadyExists):
            cs.resource("customresourcedefinitions").create(_crd())
        with pytest.raises(Invalid):
            cs.resource("widgets").create(
                Unstructured({"metadata": {"name": "bad", "namespace": "default"}})
            )


class TestFeatureGateRestore:
    def test_cluster_restores_gates(self):
        from kubernetes_tpu.cluster import Cluster
        from kubernetes_tpu.utils.featuregate import default_feature_gate

        assert not default_feature_gate.enabled("CSIStorageCapacity")
        with Cluster(n_nodes=0, controllers=[], feature_gates="CSIStorageCapacity=true"):
            assert default_feature_gate.enabled("CSIStorageCapacity")
        assert not default_feature_gate.enabled("CSIStorageCapacity")
