"""The apiserver over real HTTP: REST verbs, streaming watch, bearer
authn/RBAC on the wire, and the full-cluster e2e slice with EVERY
component connected via the socket (VERDICT r1 item 5).

Reference shape: apiserver/pkg/server/config.go:719 handler chain,
pkg/endpoints/installer.go:190 route install, handlers/watch.go
streaming; integration tests run real components against a real
apiserver (test/integration/framework/master_utils.go)."""

import threading
import time

import pytest

from kubernetes_tpu.api import apps
from kubernetes_tpu.api import rbac
from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.auth import (
    Forbidden,
    SecureAPIServer,
    Unauthorized,
)
from kubernetes_tpu.apiserver.http import HTTPAPIServer, RemoteAPIServer
from kubernetes_tpu.apiserver.server import APIServer, Conflict, NotFound
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory

from .util import make_node, make_pod, wait_until


@pytest.fixture()
def wire():
    srv = HTTPAPIServer(api=APIServer()).start()
    yield srv, RemoteAPIServer(srv.address)
    srv.stop()


class TestRESTVerbs:
    def test_create_get_list_update_delete(self, wire):
        srv, remote = wire
        pod = make_pod("alpha")
        created = remote.create("pods", pod)
        assert created.metadata.uid and created.metadata.resource_version

        got = remote.get("pods", "alpha", "default")
        assert got.metadata.name == "alpha"

        items, rev = remote.list("pods", "default")
        assert [p.metadata.name for p in items] == ["alpha"] and rev > 0

        got.metadata.labels = {"touched": "yes"}
        updated = remote.update("pods", got)
        assert updated.metadata.labels == {"touched": "yes"}
        assert int(updated.metadata.resource_version) > int(
            got.metadata.resource_version
        )

        remote.delete("pods", "alpha", "default")
        with pytest.raises(NotFound):
            remote.get("pods", "alpha", "default")

    def test_optimistic_concurrency_conflict_over_wire(self, wire):
        _, remote = wire
        remote.create("pods", make_pod("occ"))
        a = remote.get("pods", "occ", "default")
        b = remote.get("pods", "occ", "default")
        a.metadata.labels = {"w": "a"}
        remote.update("pods", a)
        b.metadata.labels = {"w": "b"}
        with pytest.raises(Conflict):
            remote.update("pods", b)

    def test_cluster_scoped_and_status(self, wire):
        _, remote = wire
        remote.create("nodes", make_node("n1"))
        n = remote.get("nodes", "n1")
        n.status.allocatable["cpu"] = "7"
        updated = remote.update_status("nodes", n)
        assert remote.get("nodes", "n1").status.allocatable["cpu"] == "7"
        assert updated.metadata.resource_version

    def test_binding_subresource(self, wire):
        _, remote = wire
        remote.create("nodes", make_node("n1"))
        remote.create("pods", make_pod("bindme"))
        remote.bind_pod("default", "bindme", "n1")
        assert remote.get("pods", "bindme", "default").spec.node_name == "n1"

    def test_discovery(self, wire):
        _, remote = wire
        names = {r["name"] for r in remote.server_resources()}
        assert {"pods", "nodes", "deployments"} <= names


class TestStreamingWatch:
    def test_watch_streams_events(self, wire):
        _, remote = wire
        _, rev = remote.list("pods", "default")
        w = remote.watch("pods", "default", since_revision=rev)
        try:
            remote.create("pods", make_pod("w1"))
            ev = w.poll(timeout=10)
            assert ev is not None and ev.type == "ADDED"
            assert ev.object.metadata.name == "w1"

            remote.delete("pods", "w1", "default")
            types = []
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and "DELETED" not in types:
                ev = w.poll(timeout=1)
                if ev is not None:
                    types.append(ev.type)
            assert "DELETED" in types
        finally:
            w.stop()

    def test_watch_frames_do_not_leak_across_servers(self):
        """Two apiservers in one process mint colliding (key, revision,
        type) triples for DIFFERENT objects; a process-global frame memo
        served server A's cached bytes to server B's watcher (ADVICE
        high). The memo is per hub now: each watcher must stream its own
        cluster's object."""
        srv_a = HTTPAPIServer(api=APIServer()).start()
        srv_b = HTTPAPIServer(api=APIServer()).start()
        try:
            ra = RemoteAPIServer(srv_a.address)
            rb = RemoteAPIServer(srv_b.address)
            _, rev_a = ra.list("pods", "default")
            _, rev_b = rb.list("pods", "default")
            wa = ra.watch("pods", "default", since_revision=rev_a)
            wb = rb.watch("pods", "default", since_revision=rev_b)
            try:
                # same name + namespace -> same store key; fresh stores
                # -> same revision: the memo keys collide exactly
                pa = make_pod("twin")
                pa.metadata.labels = {"cluster": "a"}
                pb = make_pod("twin")
                pb.metadata.labels = {"cluster": "b"}
                ra.create("pods", pa)
                rb.create("pods", pb)
                ev_a = wa.poll(timeout=10)
                ev_b = wb.poll(timeout=10)
                assert ev_a is not None and ev_b is not None
                assert ev_a.object.metadata.labels == {"cluster": "a"}
                assert ev_b.object.metadata.labels == {"cluster": "b"}
            finally:
                wa.stop()
                wb.stop()
        finally:
            srv_a.stop()
            srv_b.stop()

    def test_informer_over_the_wire(self, wire):
        _, remote = wire
        cs = Clientset(remote)
        factory = SharedInformerFactory(cs)
        pods = factory.pods()
        factory.start()
        assert factory.wait_for_cache_sync()
        try:
            remote.create("pods", make_pod("inf-1"))
            assert wait_until(
                lambda: any(
                    p.metadata.name == "inf-1" for p in pods.list()
                ),
                timeout=10,
            )
        finally:
            factory.stop()


class TestWireAuth:
    @pytest.fixture()
    def secure_wire(self):
        secure = SecureAPIServer()
        secure.authenticator.add_token("root-token", "admin", ["system:masters"])
        secure.authenticator.add_token("peon-token", "peon")
        srv = HTTPAPIServer(secure).start()
        yield srv, secure
        srv.stop()

    def test_no_token_401(self, secure_wire):
        srv, _ = secure_wire
        remote = RemoteAPIServer(srv.address)  # no token
        with pytest.raises(Unauthorized):
            remote.list("pods", "default")

    def test_bad_token_401(self, secure_wire):
        srv, _ = secure_wire
        remote = RemoteAPIServer(srv.address, token="nope")
        with pytest.raises(Unauthorized):
            remote.list("pods", "default")

    def test_rbac_denied_403_and_grant(self, secure_wire):
        srv, secure = secure_wire
        peon = RemoteAPIServer(srv.address, token="peon-token")
        with pytest.raises(Forbidden):
            peon.create("pods", make_pod("px"))
        secure.api.create("clusterroles", rbac.ClusterRole(
            metadata=v1.ObjectMeta(name="podder"),
            rules=[rbac.PolicyRule(verbs=["*"], resources=["pods"])]))
        secure.api.create("clusterrolebindings", rbac.ClusterRoleBinding(
            metadata=v1.ObjectMeta(name="podder"),
            subjects=[rbac.Subject(kind="User", name="peon")],
            role_ref=rbac.RoleRef(kind="ClusterRole", name="podder")))
        created = peon.create("pods", make_pod("px"))
        assert created.metadata.name == "px"

    def test_admin_full_flow(self, secure_wire):
        srv, _ = secure_wire
        root = RemoteAPIServer(srv.address, token="root-token")
        root.create("nodes", make_node("n1"))
        root.create("pods", make_pod("p1"))
        root.bind_pod("default", "p1", "n1")
        assert root.get("pods", "p1", "default").spec.node_name == "n1"


class TestHTTPClusterE2E:
    def test_full_stack_over_the_wire(self):
        """Every component — hollow kubelets, controller manager, the
        scheduler, kubectl — connects to the apiserver via HTTP only."""
        from kubernetes_tpu.controllers.manager import ControllerManager
        from kubernetes_tpu.kubectl.cli import Kubectl
        from kubernetes_tpu.kubemark import HollowCluster
        from kubernetes_tpu.scheduler.apis.config import default_configuration
        from kubernetes_tpu.scheduler.factory import create_scheduler

        from .util import FAST_KUBELET

        srv = HTTPAPIServer(api=APIServer()).start()
        try:
            # each component gets its OWN remote client (separate
            # sockets, like separate processes)
            hollow = HollowCluster(
                Clientset(RemoteAPIServer(srv.address)),
                n_nodes=3, config_overrides=FAST_KUBELET,
            )
            hollow.start()

            kcm = ControllerManager(
                Clientset(RemoteAPIServer(srv.address)),
                controllers=["replicaset", "deployment"],
            )
            kcm.run()

            sched_cs = Clientset(RemoteAPIServer(srv.address))
            factory = SharedInformerFactory(sched_cs)
            cfg = default_configuration()
            cfg.profiles[0].backend = "oracle"
            sched = create_scheduler(sched_cs, factory, cfg)
            factory.start()
            assert factory.wait_for_cache_sync()
            sched.start()

            kubectl_cs = Clientset(RemoteAPIServer(srv.address))
            kubectl_cs.deployments.create(apps.Deployment(
                metadata=v1.ObjectMeta(name="web", namespace="default"),
                spec=apps.DeploymentSpec(
                    replicas=6,
                    selector=v1.LabelSelector(match_labels={"app": "web"}),
                    template=v1.PodTemplateSpec(
                        metadata=v1.ObjectMeta(labels={"app": "web"}),
                        spec=v1.PodSpec(containers=[v1.Container(
                            name="c", image="img:1",
                            resources=v1.ResourceRequirements(
                                requests={"cpu": "100m"}),
                        )]),
                    ),
                ),
            ))

            def all_running():
                pods, _ = kubectl_cs.pods.list(namespace="default")
                return len(pods) == 6 and all(
                    p.spec.node_name and p.status.phase == "Running"
                    for p in pods
                )

            assert wait_until(all_running, timeout=60), [
                (p.metadata.name, p.spec.node_name, p.status.phase)
                for p in kubectl_cs.pods.list(namespace="default")[0]
            ]

            import io

            buf = io.StringIO()
            kubectl = Kubectl(kubectl_cs, out=buf)
            kubectl.run(["get", "pods"])
            assert sum(1 for line in buf.getvalue().splitlines()
                       if "web-" in line) == 6

            sched.stop()
            factory.stop()
            kcm.stop()
            hollow.stop()
        finally:
            srv.stop()


class TestWireRoutingEdges:
    def test_namespace_subresources_route_to_namespaces(self, wire):
        """/api/v1/namespaces/{name}/status and /finalize are namespace
        SUBRESOURCES, not namespaced collections (installer registers
        them explicitly in the reference) — the namespace controller's
        Terminating drain depends on both working over the wire."""
        _, remote = wire
        remote.create("namespaces", v1.Namespace(
            metadata=v1.ObjectMeta(name="doomed")))
        ns = remote.get("namespaces", "doomed")
        ns.status.phase = "Terminating"
        remote.update_status("namespaces", ns)
        assert remote.get("namespaces", "doomed").status.phase == "Terminating"
        remote.delete("namespaces", "doomed")  # soft: kubernetes finalizer
        remote.remove_finalizer("namespaces", "doomed", "", "kubernetes")
        with pytest.raises(NotFound):
            remote.get("namespaces", "doomed")

    def test_create_defaults_to_path_namespace(self, wire):
        """POST /api/v1/namespaces/team-a/pods with a body that omits
        metadata.namespace lands in team-a (handlers/create.go scope
        defaulting)."""
        srv, remote = wire
        import http.client
        import json as _json

        conn = http.client.HTTPConnection(remote._host, remote._port)
        body = {"metadata": {"name": "bare"},
                "spec": {"containers": [{"name": "c", "image": "i"}]}}
        conn.request("POST", "/api/v1/namespaces/team-a/pods",
                     body=_json.dumps(body).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        created = _json.loads(resp.read())
        conn.close()
        assert resp.status == 201
        assert created["metadata"]["namespace"] == "team-a"
        assert remote.get("pods", "bare", "team-a").metadata.name == "bare"


class TestBulkBindings:
    def test_bulk_bind_outcomes(self, wire):
        srv, remote = wire
        cs = Clientset(remote)
        cs.nodes.create(make_node("n1"))
        cs.pods.create(make_pod("a"))
        cs.pods.create(make_pod("b"))
        # b is pre-bound elsewhere: its bulk outcome must be a Conflict
        remote.bind_pod("default", "b", "n-other")
        outcomes = remote.bind_pods([
            ("default", "a", "n1"),
            ("default", "b", "n1"),       # already bound -> error
            ("default", "missing", "n1"),  # no such pod -> error
        ])
        assert outcomes[0] is None
        assert outcomes[1] is not None and "already assigned" in str(outcomes[1])
        assert outcomes[2] is not None
        assert cs.pods.get("a", "default").spec.node_name == "n1"
        assert cs.pods.get("b", "default").spec.node_name == "n-other"
