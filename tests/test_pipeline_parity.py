"""Pipelined-vs-sequential parity gate for the scheduling loop.

The pipelined loop (scheduler.py pipeline_depth >= 1: double-buffered
device dispatch + the async completion/bind worker) must produce
BIT-IDENTICAL binding decisions to the sequential depth-0 path on the
same pod stream — the acceptance gate for the kernel-to-loop pipeline
work. Randomized churn: mixed templates (PTS spread terms make decisions
depend on the assumed-count carry, so ordering bugs surface as different
placements), permanently-unschedulable pods failing mid-stream, ragged
randomized batch boundaries, and a mid-stream foreign cluster mutation
that tears the session down while batches are still in flight.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import Clientset, SharedInformerFactory
from kubernetes_tpu.ops.hoisted import HoistedSession
from kubernetes_tpu.scheduler import metrics
from kubernetes_tpu.scheduler.internal.cache import SchedulerCache
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.scheduler.tpu_backend import TPUBackend
from kubernetes_tpu.testing.faults import BindIntegrityChecker, FaultInjector

from .util import make_node, make_pod, spread_constraint


def _cluster(n_nodes=8):
    api = APIServer()
    cs = Clientset(api)
    for i in range(n_nodes):
        cs.nodes.create(make_node(
            f"node-{i}",
            cpu=str(4 + (i % 3) * 2), memory="16Gi", pods=64,
            labels={v1.LABEL_HOSTNAME: f"node-{i}", "zone": f"z{i % 3}"},
        ))
    return api, cs


def _mk_scheduler(cs, depth):
    factory = SharedInformerFactory(cs)
    sched = Scheduler(cs, factory, backend="tpu", pipeline_depth=depth)
    factory.start()
    assert factory.wait_for_cache_sync()
    return sched


def _pod_stream(rng: random.Random, n: int):
    """Deterministic randomized churn stream: three templates, one of
    them permanently unschedulable."""
    pods = []
    for i in range(n):
        kind = rng.random()
        if kind < 0.5:
            pods.append(make_pod(
                f"p-{i}", namespace="default", cpu="200m", memory="128Mi",
                labels={"app": "spread"},
                constraints=[spread_constraint(
                    1, "zone", "ScheduleAnyway", {"app": "spread"})],
            ))
        elif kind < 0.85:
            pods.append(make_pod(
                f"p-{i}", namespace="default", cpu="500m", memory="256Mi",
                labels={"app": "plain"},
            ))
        else:
            # can never fit: fails, parks in the unschedulable queue
            pods.append(make_pod(
                f"p-{i}", namespace="default", cpu="64", memory="1Gi",
                labels={"app": "hungry"},
            ))
    return pods


def _drive(sched, cs, pods, batch_sizes, mutate_at=None):
    """Create the pods, then pop + dispatch them through
    _schedule_batch_tpu in the given batch partition — the same pod
    stream and the same batch boundaries for every scheduler under
    comparison; only the pipeline depth differs. `mutate_at` injects a
    foreign cluster mutation (a directly-bound pod) after that many
    batches, while the pipelined scheduler still has dispatches in
    flight."""
    for p in pods:
        cs.pods.create(p)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if sched.queue.num_active() >= len(pods):
            break
        time.sleep(0.02)
    n_batches = 0
    sizes = list(batch_sizes)
    while True:
        info = sched.queue.pop(timeout=0.2)
        if info is None:
            break
        infos = [info]
        want = sizes.pop(0) if sizes else 4
        while len(infos) < want:
            nxt = sched.queue.pop(timeout=0)
            if nxt is None:
                break
            infos.append(nxt)
        sched._schedule_batch_tpu(infos)
        n_batches += 1
        if mutate_at is not None and n_batches == mutate_at:
            # foreign mutation: an externally-bound pod lands in the
            # cache via the informer and invalidates the live session
            # while the pipeline still holds undispatched completions
            squatter = make_pod(
                "squatter", namespace="default", cpu="1", memory="512Mi",
                node_name="node-0", labels={"app": "foreign"},
            )
            cs.pods.create(squatter)
            mdl = time.monotonic() + 10
            while time.monotonic() < mdl:
                if sched.cache.has_pod("default/squatter"):
                    break
                time.sleep(0.01)
    # land every completion, then wait for the binder pool to drain
    # (wait_idle won't do: churn pods park in the unschedulable queue
    # forever by design, and pending_pods() counts them)
    assert sched._drain_pipeline(timeout=30)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with sched._inflight_lock:
            if sched._inflight == 0:
                break
        time.sleep(0.02)
    else:
        raise AssertionError("binder pool did not drain")


def _bound_map(cs):
    pods, _ = cs.pods.list(namespace="default")
    return {
        p.metadata.name: p.spec.node_name
        for p in pods if p.metadata.name.startswith("p-")
    }


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_pipelined_matches_sequential(seed):
    rng = random.Random(seed)
    n = rng.randint(24, 48)
    batch_sizes = [rng.choice([1, 2, 3, 5, 8]) for _ in range(64)]
    maps = {}
    for depth in (0, 2):
        _, cs = _cluster()
        sched = _mk_scheduler(cs, depth)
        try:
            pods = _pod_stream(random.Random(seed), n)
            _drive(sched, cs, pods, batch_sizes)
            maps[depth] = _bound_map(cs)
        finally:
            sched.stop()
            sched.informers.stop()
    assert maps[0] == maps[2], (
        "pipelined decisions diverged from the sequential path"
    )
    # the stream must actually exercise churn: some bound, some not
    assert any(maps[0].values())
    hungry_unbound = [k for k, nd in maps[0].items() if not nd]
    assert hungry_unbound, "stream produced no failures — churn untested"


def test_pipelined_matches_sequential_with_foreign_mutation():
    """A mid-stream session teardown (foreign bound pod) with batches in
    flight must not change any decision: the in-flight batches' decode
    was captured at dispatch, and the encoding applies decisions in
    dispatch order either way."""
    seed = 7
    rng = random.Random(seed)
    n = 32
    batch_sizes = [rng.choice([2, 3, 5]) for _ in range(32)]
    maps = {}
    for depth in (0, 2):
        _, cs = _cluster()
        sched = _mk_scheduler(cs, depth)
        try:
            pods = _pod_stream(random.Random(seed), n)
            _drive(sched, cs, pods, batch_sizes, mutate_at=2)
            maps[depth] = _bound_map(cs)
        finally:
            sched.stop()
            sched.informers.stop()
    assert maps[0] == maps[2]


# -- columnar cache A/B (round 14) -------------------------------------------


def _counter_total(counter) -> float:
    return sum(val for _, val in counter.items())


@pytest.mark.parametrize("seed", [0, 3])
def test_columnar_cache_matches_object_path(seed, monkeypatch):
    """KTPU_COLUMNAR_CACHE A/B through the FULL pipelined loop: the
    batched columnar assume (single delta-apply + batched listener
    echo + swap_pod_object fast path) vs the per-pod object writeback
    must produce bit-identical bindings over randomized churn. Run at
    depth 2 so the completion worker, speculation, and the batched
    bind fan-out are all on the measured path."""
    rng = random.Random(seed)
    n = rng.randint(24, 48)
    batch_sizes = [rng.choice([1, 2, 3, 5, 8]) for _ in range(64)]
    maps = {}
    for mode in ("0", "1"):
        monkeypatch.setenv("KTPU_COLUMNAR_CACHE", mode)
        _, cs = _cluster()
        sched = _mk_scheduler(cs, 2)
        assert sched.cache.columnar is (mode == "1")
        try:
            pods = _pod_stream(random.Random(seed), n)
            _drive(sched, cs, pods, batch_sizes)
            maps[mode] = _bound_map(cs)
        finally:
            sched.stop()
            sched.informers.stop()
    assert maps["0"] == maps["1"], (
        "columnar cache decisions diverged from the object path"
    )
    assert any(maps["0"].values())


def test_columnar_zero_drift_at_sample_rate(monkeypatch):
    """Acceptance gate: with the columnar audit view feeding the shadow
    sentinel at sample rate 0.1, a churn stream must audit without a
    single parity drift — the cheap O(changed) clone snapshot must be
    oracle-equivalent to the dump()-rebuilt one."""
    monkeypatch.setenv("KTPU_COLUMNAR_CACHE", "1")
    seed = 21
    rng = random.Random(seed)
    batch_sizes = [rng.choice([2, 3, 5]) for _ in range(64)]
    _, cs = _cluster()
    sched = _mk_scheduler(cs, 2)
    sched.tpu.set_shadow_sample(0.1)
    samples0 = _counter_total(metrics.shadow_samples)
    drift0 = _counter_total(metrics.parity_drift)
    try:
        pods = _pod_stream(random.Random(seed), 48)
        _drive(sched, cs, pods, batch_sizes)
    finally:
        sched.stop()
        sched.informers.stop()
    audited = _counter_total(metrics.shadow_samples) - samples0
    assert audited > 0, "sample rate 0.1 never fired — gate untested"
    assert _counter_total(metrics.parity_drift) - drift0 == 0, (
        "columnar audit view drifted from the oracle replay"
    )


# -- multi-pod scan steps + speculative dispatch (round 9) -------------------


def _label_counts(counter):
    out = {}
    for key, val in counter.items():
        slug = key[0] if key else "-"
        out[slug] = out.get(slug, 0) + int(val)
    return out


def _spec_counts():
    return _label_counts(metrics.speculative_dispatches)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_multipod_speculation_matches_depth0(seed, monkeypatch):
    """Multi-pod scan steps (k=4) + speculative pipelining (depth 2)
    vs the one-pod-per-step depth-0 reference over randomized churn:
    decisions must be bit-identical — the exact-conflict-replay
    contract, end to end through the scheduler loop."""
    rng = random.Random(seed)
    n = rng.randint(24, 48)
    batch_sizes = [rng.choice([1, 2, 3, 5, 8]) for _ in range(64)]
    maps = {}
    for depth, k in ((0, 1), (2, 4)):
        monkeypatch.setenv("KTPU_MULTIPOD_K", str(k))
        _, cs = _cluster()
        sched = _mk_scheduler(cs, depth)
        try:
            pods = _pod_stream(random.Random(seed), n)
            _drive(sched, cs, pods, batch_sizes)
            if depth:
                s = sched.tpu._session
                assert s is None or s.multipod_k == 4, (
                    "multipod width did not reach the session"
                )
            maps[depth] = _bound_map(cs)
        finally:
            sched.stop()
            sched.informers.stop()
    assert maps[0] == maps[2], (
        "multipod+speculation decisions diverged from one-pod-per-step"
    )
    assert any(maps[0].values())


def test_speculation_kill_switch(monkeypatch):
    """KTPU_SPECULATION=0: no dispatch ever chains on a not-yet-
    harvested carry (every handle leaves dispatch_many non-speculative)
    and decisions still match the depth-0 reference."""
    seed = 11
    rng = random.Random(seed)
    batch_sizes = [rng.choice([2, 3, 5]) for _ in range(32)]
    maps = {}
    for depth in (0, 2):
        if depth:
            monkeypatch.setenv("KTPU_SPECULATION", "0")
        _, cs = _cluster()
        sched = _mk_scheduler(cs, depth)
        spec_flags = []
        if depth:
            assert sched.tpu.speculation is False
            orig = type(sched.tpu).dispatch_many

            def spy(self, pods, _orig=orig, _f=spec_flags):
                h = _orig(self, pods)
                _f.append(h.speculative)
                return h

            sched.tpu.dispatch_many = spy.__get__(sched.tpu)
        try:
            pods = _pod_stream(random.Random(seed), 32)
            _drive(sched, cs, pods, batch_sizes)
            maps[depth] = _bound_map(cs)
        finally:
            sched.stop()
            sched.informers.stop()
    assert maps[0] == maps[2]
    assert spec_flags and not any(spec_flags), (
        f"speculation off but a dispatch chained on an unharvested "
        f"carry: {spec_flags}"
    )


def _mini_backend(node_cpus, reserve=256):
    """Cache + backend with the given per-node cpu sizes (no apiserver:
    these tests pin SESSION-level multipod semantics)."""
    cache = SchedulerCache()
    be = TPUBackend()
    cache.add_listener(be)
    for i, cpu in enumerate(node_cpus):
        cache.add_node(make_node(
            f"node-{i}", cpu=cpu, memory="16Gi", pods=64,
            labels={v1.LABEL_HOSTNAME: f"node-{i}"},
        ))
    be.enc.reserve(pods=reserve)
    return cache, be


def _encode(be, pods):
    return [
        {k: v for k, v in be.pe.encode(p).items() if not k.startswith("_")}
        for p in pods
    ]


def test_directed_conflict_replay_last_slot():
    """Two pods of ONE multipod step racing for the last slot on a node:
    the speculative evals both pick it; the conflict test must catch the
    second (same-node + fit-flip) and the replay must leave it exactly
    where the sequential reference does (unschedulable)."""
    _, be = _mini_backend(["3", "1"])  # node-0 fits ONE 2-cpu pod
    pods = [
        make_pod(f"race-{i}", namespace="default", cpu="2", memory="128Mi",
                 labels={"app": "race"})
        for i in range(2)
    ]
    arrays = _encode(be, pods)
    cluster = be.enc.device_state()
    ref = HoistedSession(cluster, [arrays[0]], be.weights, multipod_k=1)
    ys_ref = ref.schedule(list(arrays))
    want = HoistedSession.decisions(ys_ref)
    assert want == [0, -1], f"reference surprised us: {want}"

    sess = HoistedSession(cluster, [arrays[0]], be.weights, multipod_k=2)
    assert sess.multipod_k == 2
    ys = sess.schedule(list(arrays))
    got = HoistedSession.decisions(ys)
    n_conf, suffix = HoistedSession.conflict_stats(ys)
    assert got == want, "conflict replay changed the race outcome"
    assert n_conf >= 1, "last-slot race produced no conflict"
    assert suffix is None  # hoisted replays in-device


def test_directed_conflict_replay_overtake():
    """Isolates the OVERTAKE leg of the utilization conflict algebra:
    pod 1 commits on a node the second pod did NOT speculatively pick
    (so the same-node predicate cannot fire, and the pods carry no
    PTS/IPA terms), yet that commit REBALANCES the node's cpu/mem
    fractions enough that its refreshed total overtakes the second
    pod's speculative winner — only kernel.multipod_utilization_
    conflicts' overtake comparison can catch it."""
    cache = SchedulerCache()
    be = TPUBackend()
    cache.add_listener(be)
    for i in range(2):
        cache.add_node(make_node(
            f"node-{i}", cpu="10", memory="10Gi", pods=64,
            labels={v1.LABEL_HOSTNAME: f"node-{i}"},
        ))
    # node-0: cpu-heavy and mem-empty (imbalanced -> poor balanced
    # score); node-1: balanced and slightly fuller (the speculative
    # winner for a tiny pod)
    cache.add_pod(make_pod(
        "fill0", namespace="default", cpu="4", memory="1Mi",
        labels={"app": "f"}, node_name="node-0"))
    cache.add_pod(make_pod(
        "fill1", namespace="default", cpu="4300m", memory="4400Mi",
        labels={"app": "f"}, node_name="node-1"))
    be.enc.reserve(pods=128)
    # pod 1: mem-heavy -> lands on node-0 (rebalances it); pod 2: tiny
    p1 = make_pod("big", namespace="default", cpu="50m", memory="4Gi",
                  labels={"app": "x"})
    p2 = make_pod("small", namespace="default", cpu="100m",
                  memory="100Mi", labels={"app": "y"})
    a1, a2 = _encode(be, [p1, p2])
    cluster = be.enc.device_state()

    # pod 2 ALONE picks node-1: that is its (stale) speculative winner
    solo = HoistedSession(cluster, [a1, a2], be.weights, multipod_k=1)
    assert HoistedSession.decisions(solo.schedule([a2])) == [1]
    # sequential reference: pod 1 -> node-0, whose rebalanced total then
    # overtakes node-1 for pod 2
    ref = HoistedSession(cluster, [a1, a2], be.weights, multipod_k=1)
    want = HoistedSession.decisions(ref.schedule([a1, a2]))
    assert want == [0, 0], f"reference surprised us: {want}"

    sess = HoistedSession(cluster, [a1, a2], be.weights, multipod_k=2)
    ys = sess.schedule([a1, a2])
    got = HoistedSession.decisions(ys)
    n_conf, _ = HoistedSession.conflict_stats(ys)
    assert got == want, "overtake replay diverged from the reference"
    # same-node could not have fired (committed node-0 != speculative
    # winner node-1) and the pods carry no terms: this conflict IS the
    # overtake leg
    assert n_conf >= 1, "argmax moved but no conflict was recorded"


class TestMultipodHostHalves:
    """The CPU env cannot execute the pallas/sharded multipod kernels
    (interpret mode cannot lower here) — these pin their HOST halves,
    which the backend's suffix handling depends on: the k resolution
    rules and the conflict_stats decode of the suffix contract."""

    def test_multipod_k_resolution(self, monkeypatch):
        from kubernetes_tpu.ops.kernel import multipod_k

        monkeypatch.delenv("KTPU_MULTIPOD_K", raising=False)
        # port-carrying sessions are pinned to 1 whatever else says
        assert multipod_k(8, dyn_ports=True) == 1
        # explicit beats env; clamped to a pow2 <= 64
        monkeypatch.setenv("KTPU_MULTIPOD_K", "16")
        assert multipod_k(8) == 8
        assert multipod_k(6) == 4
        assert multipod_k(200) == 64
        assert multipod_k(0) == 1
        # env beats the platform default (the kill switch)
        assert multipod_k() == 16
        monkeypatch.setenv("KTPU_MULTIPOD_K", "1")
        assert multipod_k() == 1
        # platform default: TPU rides DEFAULT_MULTIPOD_K, others 1
        monkeypatch.delenv("KTPU_MULTIPOD_K")
        assert multipod_k(platform="tpu") == 4
        assert multipod_k(platform="cpu") == 1

    def test_pallas_conflict_stats_decodes_suffix(self):
        import numpy as np

        from kubernetes_tpu.ops.pallas_scan import PallasSession

        rows = np.full((8, 8), -1, np.int32)
        # one-pod-per-step batches never report conflicts
        assert PallasSession.conflict_stats(
            {"rows": rows, "n": 6, "mk": 1}) == (0, None)
        rows[3, :6] = 0
        assert PallasSession.conflict_stats(
            {"rows": rows, "n": 6, "mk": 4}) == (0, None)
        # suffix from the first flagged pod; ONE detection per suffix
        # (later flags are collateral), padding rows ignored
        rows[3, 2:] = 1
        assert PallasSession.conflict_stats(
            {"rows": rows, "n": 6, "mk": 4}) == (1, 2)

    def test_sharded_conflict_stats_decodes_suffix(self):
        import numpy as np

        from kubernetes_tpu.ops.sharded_scan import ShardedPallasSession

        ys = {"best": np.zeros(8), "_b_real": 6}
        assert ShardedPallasSession.conflict_stats(ys) == (0, None)
        conf = np.zeros(8, np.int32)
        conf[3:] = 1  # flags run to the batch end (incl. padding)
        ys["conflicts"] = conf
        assert ShardedPallasSession.conflict_stats(ys) == (1, 3)
        ys["conflicts"] = np.zeros(8, np.int32)
        assert ShardedPallasSession.conflict_stats(ys) == (0, None)


class _FakeSuffixSession:
    """Simulates the pallas/sharded conflict-SUFFIX contract (the CPU
    env cannot run those kernels): schedule() "commits" a prefix and
    flags everything from `suffix_at` on as an uncommitted conflict
    suffix; the replayed suffix then lands clean. Lets the sync-path
    suffix loop in TPUBackend._session_schedule be pinned on CPU."""

    def __init__(self, suffix_at):
        self.suffix_at = suffix_at
        self.calls = []

    def schedule(self, arrays):
        n = len(arrays)
        first = not self.calls
        self.calls.append(n)
        if first and n > self.suffix_at:
            return {"best": list(range(n)), "suffix": self.suffix_at,
                    "n": n}
        # replay round: distinct decisions so the test can see which
        # round produced each pod's answer
        return {"best": [100 + i for i in range(n)], "suffix": None,
                "n": n}

    @staticmethod
    def decisions(ys):
        return list(ys["best"])

    @staticmethod
    def conflict_stats(ys):
        if ys["suffix"] is None:
            return 0, None
        return 1, ys["suffix"]


def test_sync_path_replays_conflict_suffix():
    """The SYNCHRONOUS dispatch path (depth-0, fault re-drives, and
    _harvest_locked's own suffix replay all route through
    _session_schedule) must honor the conflict-SUFFIX contract: keep
    the committed prefix, replay exactly the suffix through the live
    session, and never report an uncommitted pod as unschedulable."""
    _, be = _mini_backend(["4"] * 4)
    pod = make_pod("seed", namespace="default", cpu="100m", memory="64Mi",
                   labels={"app": "sx"})
    arrays = _encode(be, [pod] * 5)
    # register the template through the real path, then swap in the fake
    be.schedule_many([pod])
    fake = _FakeSuffixSession(suffix_at=2)
    be._session = fake
    conf0 = _label_counts(metrics.multipod_conflicts).get("-", 0)
    repl0 = _label_counts(metrics.conflict_replays).get("-", 0)
    got = be._session_schedule(arrays)
    # prefix [0, 1] from round 1; suffix pods re-decided in round 2
    assert got == [0, 1, 100, 101, 102], got
    assert fake.calls == [5, 3], fake.calls
    assert _label_counts(metrics.multipod_conflicts).get("-", 0) \
        - conf0 == 1
    assert _label_counts(metrics.conflict_replays).get("-", 0) \
        - repl0 == 3

    # a suffix at the batch head would loop forever — the invariant
    # says it cannot happen; _session_schedule must fail loudly
    from kubernetes_tpu.scheduler.tpu_backend import DeviceFault

    be._session = _FakeSuffixSession(suffix_at=0)
    with pytest.raises(DeviceFault):
        be._session_schedule(arrays)


def test_speculation_miss_redrives_bit_identical():
    """Deterministic speculation miss at the backend seam: batch 2 is
    dispatched chained on batch 1's unharvested carry, then batch 1's
    harvest is corrupted (nan-harvest). The recovery must count exactly
    one miss and re-drive BOTH batches to the same decisions a clean
    sequential backend makes."""
    warm = [
        make_pod(f"w-{i}", namespace="default", cpu="100m", memory="64Mi",
                 labels={"app": "m"})
        for i in range(4)
    ]
    b1 = [
        make_pod(f"a-{i}", namespace="default", cpu="100m", memory="64Mi",
                 labels={"app": "m"})
        for i in range(3)
    ]
    b2 = [
        make_pod(f"b-{i}", namespace="default", cpu="100m", memory="64Mi",
                 labels={"app": "m"})
        for i in range(3)
    ]

    def nodes_of(results):
        return [node for _, node in results]

    # clean sequential control (the depth-0 reference semantics)
    _, ctrl = _mini_backend(["4"] * 6)
    ctrl.schedule_many([make_pod(
        p.metadata.name, namespace="default", cpu="100m", memory="64Mi",
        labels={"app": "m"}) for p in warm])
    want = nodes_of(ctrl.schedule_many(list(b1))) \
        + nodes_of(ctrl.schedule_many(list(b2)))

    _, be = _mini_backend(["4"] * 6)
    be.schedule_many(warm)  # builds the session: later batches pipeline
    assert be._session is not None
    spec0 = _spec_counts()
    h1 = be.dispatch_many(b1)
    h2 = be.dispatch_many(b2)
    assert h1.ys is not None and h2.ys is not None, (
        "batches did not ride the pipelined session path"
    )
    assert not h1.speculative and h2.speculative, (
        "speculation flags wrong at dispatch"
    )
    inj = FaultInjector()
    be.faults = inj
    inj.arm("nan-harvest", shots=1)
    got = nodes_of(be.harvest(h1)) + nodes_of(be.harvest(h2))
    assert inj.injected.get("nan-harvest", 0) == 1
    spec1 = _spec_counts()
    assert spec1.get("miss", 0) - spec0.get("miss", 0) == 1, (
        "the dropped chained batch was not counted as a miss"
    )
    assert spec1.get("hit", 0) == spec0.get("hit", 0)
    assert got == want, "speculation-miss re-drive changed decisions"

    # clean second round: the chained batch now harvests as a HIT
    h3 = be.dispatch_many([make_pod(
        "c-0", namespace="default", cpu="100m", memory="64Mi",
        labels={"app": "m"})])
    h4 = be.dispatch_many([make_pod(
        "c-1", namespace="default", cpu="100m", memory="64Mi",
        labels={"app": "m"})])
    be.harvest(h3)
    be.harvest(h4)
    spec2 = _spec_counts()
    assert spec2.get("hit", 0) - spec1.get("hit", 0) >= 1
    assert spec2.get("miss", 0) == spec1.get("miss", 0)


def test_speculation_miss_drill_through_loop(monkeypatch):
    """Speculation-miss drill through the FULL loop: multipod k=4,
    depth 2, a wedged device wait injected mid-stream while later
    batches pile up behind it. The watchdog fault must roll the chained
    batches back through the re-drive path bit-identically, with the
    BindIntegrityChecker clean (no pod bound twice) and the misses
    counted."""
    seed = 13
    rng = random.Random(seed)
    batch_sizes = [rng.choice([2, 3, 5]) for _ in range(32)]
    maps = {}
    inj = None
    checker = None
    spec0 = _spec_counts()
    for depth, k in ((0, 1), (2, 4)):
        monkeypatch.setenv("KTPU_MULTIPOD_K", str(k))
        _, cs = _cluster()
        sched = _mk_scheduler(cs, depth)
        try:
            if depth:
                checker = BindIntegrityChecker().attach(
                    sched.informers.pods())
                inj = FaultInjector()
                sched.install_fault_injector(inj)
                sched.tpu.watchdog_timeout = 0.5
                orig = type(sched.tpu).dispatch_many
                count = {"batches": 0}

                def arming(self, pods, _orig=orig, _c=count, _inj=inj):
                    if _c["batches"] == 2:
                        _inj.arm("wedge-wait", shots=1)
                    _c["batches"] += 1
                    return _orig(self, pods)

                sched.tpu.dispatch_many = arming.__get__(sched.tpu)
            pods = _pod_stream(random.Random(seed), 32)
            _drive(sched, cs, pods, batch_sizes)
            maps[depth] = _bound_map(cs)
        finally:
            sched.shutdown()
            sched.informers.stop()
    assert inj.injected.get("wedge-wait", 0) >= 1
    assert maps[0] == maps[2], "speculation-miss recovery changed decisions"
    assert checker.violations == [], checker.violations
    spec1 = _spec_counts()
    assert spec1.get("miss", 0) - spec0.get("miss", 0) >= 1, (
        "wedge drill produced no speculation miss — nothing was chained"
    )


def test_backpressure_never_harvests_on_dispatch_thread():
    """dispatch_many back-pressure at depth >= 1 must WAIT for the
    completion worker instead of harvesting inline: the dispatching
    thread never decodes a harvest (the regression this pins used to
    charge harvest+assume+decode to the dispatch critical path)."""
    _, cs = _cluster()
    sched = _mk_scheduler(cs, 2)
    assert sched.tpu.async_harvest_drain is True
    sched.tpu.max_pending = 1  # force back-pressure on every overlap
    harvest_threads = []
    orig_h = type(sched.tpu)._harvest_locked

    def spy_h(self, _orig=orig_h, _t=harvest_threads):
        _t.append(threading.current_thread().name)
        return _orig(self)

    sched.tpu._harvest_locked = spy_h.__get__(sched.tpu)
    full_seen = []
    orig_d = type(sched.tpu).dispatch_many

    def spy_d(self, pods, _orig=orig_d, _f=full_seen):
        _f.append(len(self._pending))
        return _orig(self, pods)

    sched.tpu.dispatch_many = spy_d.__get__(sched.tpu)
    try:
        pods = [
            make_pod(f"p-{i}", namespace="default", cpu="100m",
                     labels={"app": "plain"})
            for i in range(24)
        ]
        _drive(sched, cs, pods, [3] * 8)
        assert all(v for v in _bound_map(cs).values())
        # back-pressure was actually exercised (a dispatch arrived with
        # the FIFO at max_pending) ...
        assert any(v >= 1 for v in full_seen), full_seen
        assert harvest_threads, "pipeline never harvested"
        # ... and every harvest ran on the completion worker
        bad = [t for t in harvest_threads if t != "batch-completions"]
        assert not bad, (
            f"harvest decoded on non-completion threads: {set(bad)}"
        )
    finally:
        sched.stop()
        sched.informers.stop()


def test_depth2_overlaps_dispatches():
    """Sanity: with depth 2 the backend genuinely holds more than one
    in-flight dispatch at some point (the double buffer is real, not
    silently serialized)."""
    _, cs = _cluster()
    sched = _mk_scheduler(cs, 2)
    seen = []
    orig = type(sched.tpu).dispatch_many

    def spy(self, pods):
        h = orig(self, pods)
        seen.append(len(self._pending))
        return h

    sched.tpu.dispatch_many = spy.__get__(sched.tpu)
    try:
        pods = [
            make_pod(f"p-{i}", namespace="default", cpu="100m",
                     labels={"app": "plain"})
            for i in range(24)
        ]
        _drive(sched, cs, pods, [4] * 6)
        assert all(v for v in _bound_map(cs).values())
        assert max(seen, default=0) >= 2, (
            f"never saw 2 in-flight dispatches: {seen}"
        )
    finally:
        sched.stop()
        sched.informers.stop()
