"""Pipelined-vs-sequential parity gate for the scheduling loop.

The pipelined loop (scheduler.py pipeline_depth >= 1: double-buffered
device dispatch + the async completion/bind worker) must produce
BIT-IDENTICAL binding decisions to the sequential depth-0 path on the
same pod stream — the acceptance gate for the kernel-to-loop pipeline
work. Randomized churn: mixed templates (PTS spread terms make decisions
depend on the assumed-count carry, so ordering bugs surface as different
placements), permanently-unschedulable pods failing mid-stream, ragged
randomized batch boundaries, and a mid-stream foreign cluster mutation
that tears the session down while batches are still in flight.
"""

from __future__ import annotations

import random
import time

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import Clientset, SharedInformerFactory
from kubernetes_tpu.scheduler.scheduler import Scheduler

from .util import make_node, make_pod, spread_constraint


def _cluster(n_nodes=8):
    api = APIServer()
    cs = Clientset(api)
    for i in range(n_nodes):
        cs.nodes.create(make_node(
            f"node-{i}",
            cpu=str(4 + (i % 3) * 2), memory="16Gi", pods=64,
            labels={v1.LABEL_HOSTNAME: f"node-{i}", "zone": f"z{i % 3}"},
        ))
    return api, cs


def _mk_scheduler(cs, depth):
    factory = SharedInformerFactory(cs)
    sched = Scheduler(cs, factory, backend="tpu", pipeline_depth=depth)
    factory.start()
    assert factory.wait_for_cache_sync()
    return sched


def _pod_stream(rng: random.Random, n: int):
    """Deterministic randomized churn stream: three templates, one of
    them permanently unschedulable."""
    pods = []
    for i in range(n):
        kind = rng.random()
        if kind < 0.5:
            pods.append(make_pod(
                f"p-{i}", namespace="default", cpu="200m", memory="128Mi",
                labels={"app": "spread"},
                constraints=[spread_constraint(
                    1, "zone", "ScheduleAnyway", {"app": "spread"})],
            ))
        elif kind < 0.85:
            pods.append(make_pod(
                f"p-{i}", namespace="default", cpu="500m", memory="256Mi",
                labels={"app": "plain"},
            ))
        else:
            # can never fit: fails, parks in the unschedulable queue
            pods.append(make_pod(
                f"p-{i}", namespace="default", cpu="64", memory="1Gi",
                labels={"app": "hungry"},
            ))
    return pods


def _drive(sched, cs, pods, batch_sizes, mutate_at=None):
    """Create the pods, then pop + dispatch them through
    _schedule_batch_tpu in the given batch partition — the same pod
    stream and the same batch boundaries for every scheduler under
    comparison; only the pipeline depth differs. `mutate_at` injects a
    foreign cluster mutation (a directly-bound pod) after that many
    batches, while the pipelined scheduler still has dispatches in
    flight."""
    for p in pods:
        cs.pods.create(p)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if sched.queue.num_active() >= len(pods):
            break
        time.sleep(0.02)
    n_batches = 0
    sizes = list(batch_sizes)
    while True:
        info = sched.queue.pop(timeout=0.2)
        if info is None:
            break
        infos = [info]
        want = sizes.pop(0) if sizes else 4
        while len(infos) < want:
            nxt = sched.queue.pop(timeout=0)
            if nxt is None:
                break
            infos.append(nxt)
        sched._schedule_batch_tpu(infos)
        n_batches += 1
        if mutate_at is not None and n_batches == mutate_at:
            # foreign mutation: an externally-bound pod lands in the
            # cache via the informer and invalidates the live session
            # while the pipeline still holds undispatched completions
            squatter = make_pod(
                "squatter", namespace="default", cpu="1", memory="512Mi",
                node_name="node-0", labels={"app": "foreign"},
            )
            cs.pods.create(squatter)
            mdl = time.monotonic() + 10
            while time.monotonic() < mdl:
                if sched.cache.has_pod("default/squatter"):
                    break
                time.sleep(0.01)
    # land every completion, then wait for the binder pool to drain
    # (wait_idle won't do: churn pods park in the unschedulable queue
    # forever by design, and pending_pods() counts them)
    assert sched._drain_pipeline(timeout=30)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with sched._inflight_lock:
            if sched._inflight == 0:
                break
        time.sleep(0.02)
    else:
        raise AssertionError("binder pool did not drain")


def _bound_map(cs):
    pods, _ = cs.pods.list(namespace="default")
    return {
        p.metadata.name: p.spec.node_name
        for p in pods if p.metadata.name.startswith("p-")
    }


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_pipelined_matches_sequential(seed):
    rng = random.Random(seed)
    n = rng.randint(24, 48)
    batch_sizes = [rng.choice([1, 2, 3, 5, 8]) for _ in range(64)]
    maps = {}
    for depth in (0, 2):
        _, cs = _cluster()
        sched = _mk_scheduler(cs, depth)
        try:
            pods = _pod_stream(random.Random(seed), n)
            _drive(sched, cs, pods, batch_sizes)
            maps[depth] = _bound_map(cs)
        finally:
            sched.stop()
            sched.informers.stop()
    assert maps[0] == maps[2], (
        "pipelined decisions diverged from the sequential path"
    )
    # the stream must actually exercise churn: some bound, some not
    assert any(maps[0].values())
    hungry_unbound = [k for k, nd in maps[0].items() if not nd]
    assert hungry_unbound, "stream produced no failures — churn untested"


def test_pipelined_matches_sequential_with_foreign_mutation():
    """A mid-stream session teardown (foreign bound pod) with batches in
    flight must not change any decision: the in-flight batches' decode
    was captured at dispatch, and the encoding applies decisions in
    dispatch order either way."""
    seed = 7
    rng = random.Random(seed)
    n = 32
    batch_sizes = [rng.choice([2, 3, 5]) for _ in range(32)]
    maps = {}
    for depth in (0, 2):
        _, cs = _cluster()
        sched = _mk_scheduler(cs, depth)
        try:
            pods = _pod_stream(random.Random(seed), n)
            _drive(sched, cs, pods, batch_sizes, mutate_at=2)
            maps[depth] = _bound_map(cs)
        finally:
            sched.stop()
            sched.informers.stop()
    assert maps[0] == maps[2]


def test_depth2_overlaps_dispatches():
    """Sanity: with depth 2 the backend genuinely holds more than one
    in-flight dispatch at some point (the double buffer is real, not
    silently serialized)."""
    _, cs = _cluster()
    sched = _mk_scheduler(cs, 2)
    seen = []
    orig = type(sched.tpu).dispatch_many

    def spy(self, pods):
        h = orig(self, pods)
        seen.append(len(self._pending))
        return h

    sched.tpu.dispatch_many = spy.__get__(sched.tpu)
    try:
        pods = [
            make_pod(f"p-{i}", namespace="default", cpu="100m",
                     labels={"app": "plain"})
            for i in range(24)
        ]
        _drive(sched, cs, pods, [4] * 6)
        assert all(v for v in _bound_map(cs).values())
        assert max(seen, default=0) >= 2, (
            f"never saw 2 in-flight dispatches: {seen}"
        )
    finally:
        sched.stop()
        sched.informers.stop()
