"""Device-timeline attribution: zero-overhead off, decision inertness,
timeline<->stage-span reconciliation, SLO histograms, recompile events.

The tentpole contract (ISSUE 14): per-launch device timing + overlap
accounting + kube-style SLO histograms, decision-inert by construction.
Pinned here:

  * KTPU_DEVTIME=0 is the no-op singleton fast path: launch() returns
    the shared NOOP_LAUNCH (zero per-launch allocation), record() drops,
    the timeline stays empty;
  * decisions are BIT-IDENTICAL with the timeline on vs off over
    randomized churn (the overload lever can flip the level mid-run, so
    inertness is load-bearing, not cosmetic);
  * a live run's device records reconcile with the flight-recorder
    spans: ready >= submit per record, device_busy <= window,
    overlapped <= min(host_busy, device_busy) — the same gate
    scripts/trace_report.py --devtime enforces on dump files;
  * the SLO histograms (scheduler_e2e_duration_seconds /
    scheduler_attempt_duration_seconds{stage} /
    scheduler_queue_wait_seconds) bucket synthetic bind timestamps
    correctly, read through the invariant library's /metricsz parser —
    the exact surface an operator's SLO reader uses;
  * the AOT executable-cache miss path records a compile event exactly
    when a bucket is force-evicted, never on a cache hit.
"""

from __future__ import annotations

import random
from types import SimpleNamespace

import pytest

from kubernetes_tpu.scheduler import metrics
from kubernetes_tpu.testing import invariants
from kubernetes_tpu.utils import configz, devtime, tracing

from .test_pipeline_parity import (
    _bound_map,
    _cluster,
    _drive,
    _mk_scheduler,
    _pod_stream,
)


@pytest.fixture(autouse=True)
def _devtime_off_after():
    lvl0 = devtime.level()
    yield
    devtime.set_level(lvl0)


# ---------------------------------------------------------------------------
# level 0: zero overhead, empty timeline


def test_level0_launch_is_the_noop_singleton():
    devtime.set_level(0)
    lt = devtime.launch("kernel", "dispatch", h2d_bytes=123, n=7)
    assert lt is devtime.NOOP_LAUNCH
    # done()/set() chain on the singleton without allocating
    assert lt.done(d2h_bytes=5, bucket=64) is devtime.NOOP_LAUNCH
    assert lt.set(extra=1) is devtime.NOOP_LAUNCH
    mark = devtime.TIMELINE.mark()
    devtime.TIMELINE.record("kernel", "x", 0.0, 1.0)
    devtime.TIMELINE.compile_event("x", 0.0, 0.5)
    assert devtime.TIMELINE.snapshot(since=mark) == []
    assert devtime.dump("level0") == []


def test_level1_records_and_level_roundtrip():
    devtime.set_level(1)
    mark = devtime.TIMELINE.mark()
    lt = devtime.launch("kernel", "dispatch", h2d_bytes=10, bucket=64)
    assert lt is not devtime.NOOP_LAUNCH
    lt.done(d2h_bytes=3, speculative=False)
    lt.done(d2h_bytes=999)  # idempotent: second done is a no-op
    recs = devtime.TIMELINE.snapshot(since=mark)
    assert len(recs) == 1
    seq, kind, name, submit, ready, h2d, d2h, tid, attrs = recs[0]
    assert (kind, name, h2d, d2h) == ("kernel", "dispatch", 10, 3)
    assert ready >= submit
    assert attrs["bucket"] == 64 and attrs["speculative"] is False


# ---------------------------------------------------------------------------
# decision inertness: off vs on, bit-identical bindings


def test_devtime_on_is_bit_identical_to_off():
    seed = 9
    rng = random.Random(seed)
    batch_sizes = [rng.choice([2, 3, 5]) for _ in range(24)]
    maps = {}
    for mode, lvl in (("off", 0), ("on", 1)):
        devtime.set_level(lvl)
        mark = devtime.TIMELINE.mark()
        _, cs = _cluster()
        sched = _mk_scheduler(cs, 2)
        try:
            pods = _pod_stream(random.Random(seed), 24)
            _drive(sched, cs, pods, batch_sizes)
            maps[mode] = _bound_map(cs)
            recs = devtime.TIMELINE.snapshot(since=mark)
            if mode == "on":
                assert recs, "level 1 run recorded no device launches"
            else:
                assert recs == [], "level 0 run wrote timeline records"
        finally:
            sched.stop()
            sched.informers.stop()
    assert maps["on"] == maps["off"], (
        "device timeline changed scheduling decisions"
    )
    assert any(maps["off"].values())


# ---------------------------------------------------------------------------
# timeline <-> stage-span reconciliation on a live run


def test_timeline_reconciles_with_stage_spans():
    trace0 = tracing.level()
    devtime.set_level(1)
    try:
        tracing.set_level(1)
        dt_mark = devtime.TIMELINE.mark()
        tr_mark = tracing.RECORDER.mark()
        _, cs = _cluster()
        sched = _mk_scheduler(cs, 2)
        try:
            pods = _pod_stream(random.Random(11), 18)
            _drive(sched, cs, pods, [3] * 6)
        finally:
            sched.stop()
            sched.informers.stop()
        records = devtime.TIMELINE.snapshot(since=dt_mark)
        events = tracing.RECORDER.snapshot(since=tr_mark)
        assert records and events
        for r in records:
            assert r[4] >= r[3], "record with ready < submit"
        ov = devtime.overlap(records, events)
        eps = 1e-6
        assert ov["device_busy_s"] <= ov["window_s"] + eps
        assert ov["host_busy_s"] <= ov["window_s"] + eps
        assert ov["overlapped_s"] <= min(
            ov["device_busy_s"], ov["host_busy_s"]) + eps
        summary = devtime.device_time_summary(records)
        assert summary["launches"] == len(records)
        assert summary["kernel_s"] > 0.0
        # the dispatch path stamps H2D bytes from the encoding payloads
        assert summary["h2d_bytes"] > 0
        # per-shard device-time slug fed by the backend
        kinds = {k[1] for k, _ in metrics.device_time.items()}
        assert "kernel" in kinds
    finally:
        tracing.set_level(trace0)


def test_overlap_synthetic_invariants():
    # device: [0,2) and [3,4); host spans: [1,3.5) work + excluded wait
    records = [
        (0, "kernel", "a", 0.0, 2.0, 0, 0, 1, None),
        (1, "kernel", "b", 3.0, 4.0, 0, 0, 1, None),
    ]
    host = [
        (0, "encode", "encode", 1.0, 1.5, 1, None),  # [1.0, 2.5)
        (1, "wait", "wait", 0.0, 4.0, 1, None),  # excluded stage
    ]
    ov = devtime.overlap(records, host)
    assert ov["window_s"] == pytest.approx(4.0)
    assert ov["device_busy_s"] == pytest.approx(3.0)
    assert ov["host_busy_s"] == pytest.approx(1.5)
    # intersection: host [1,2.5) against device [0,2) -> [1,2) only
    assert ov["overlapped_s"] == pytest.approx(1.0)
    assert ov["overlap_ratio"] == pytest.approx(1.0 / 1.5, abs=1e-3)
    # empty side reports 0, never NaN
    assert devtime.overlap([], host)["overlap_ratio"] == 0.0
    assert devtime.overlap(records, [])["overlap_ratio"] == 0.0


# ---------------------------------------------------------------------------
# SLO histograms over synthetic bind timestamps, via the invariant reader


def test_slo_histograms_bucket_synthetic_bind_timestamps():
    _, cs = _cluster()
    sched = _mk_scheduler(cs, 0)
    try:
        before = invariants.parse_metrics(configz.metricsz_body())
        now = 1000.0
        # (e2e, attempt) pairs: queue_wait = e2e - attempt. Values sit
        # mid-bucket so float subtraction noise cannot straddle a bound.
        cases = [(0.003, 0.0015), (0.010, 0.007), (0.300, 0.250)]
        for e2e, attempt in cases:
            info = SimpleNamespace(
                initial_attempt_timestamp=now - e2e,
                pop_timestamp=now - attempt,
                attempts=1,
            )
            sched._observe_bound(info, now)
        after = invariants.parse_metrics(configz.metricsz_body())

        def delta(name):
            a = invariants.bucket_counts(after, name)
            b = invariants.bucket_counts(before, name)
            return {le: a[le] - b.get(le, 0.0) for le in a}

        e2e_d = delta("scheduler_e2e_duration_seconds")
        # cumulative counts: 0.003 -> first bound >= is 0.004; 0.010 ->
        # 0.016; 0.300 -> 0.512 (exponential 0.001 * 2**i buckets)
        assert e2e_d[0.002] == 0
        assert e2e_d[0.004] == 1
        assert e2e_d[0.016] == 2
        assert e2e_d[0.512] == 3
        assert e2e_d[float("inf")] == 3
        qw_d = delta("scheduler_queue_wait_seconds")
        # waits: 0.0015, 0.003, 0.05 -> cumulative 1 at 0.002, 2 at
        # 0.004, 3 at 0.064
        assert qw_d[0.002] == 1
        assert qw_d[0.004] == 2
        assert qw_d[0.064] == 3
        # attempt histogram is labeled by stage; the synthetic feeds all
        # land in stage="attempt"
        att = invariants.total(
            after, "scheduler_attempt_duration_seconds_count"
        ) - invariants.total(
            before, "scheduler_attempt_duration_seconds_count")
        assert att == 3
        # the watch-delivery SLI reads through the same parser
        from kubernetes_tpu.apiserver.http import watch_delivery

        watch_delivery.observe(0.002)
        final = invariants.parse_metrics(configz.metricsz_body())
        wd = invariants.bucket_counts(
            final, "apiserver_watch_delivery_seconds")
        assert wd, "apiserver_watch_delivery_seconds not exposed"
        assert invariants.total(
            final, "apiserver_watch_delivery_seconds_count") >= 1
    finally:
        sched.stop()
        sched.informers.stop()


# ---------------------------------------------------------------------------
# recompile events: exactly on a forced bucket eviction


def test_recompile_event_fires_exactly_on_forced_eviction():
    from kubernetes_tpu.ops.pallas_scan import PallasSession

    from .test_hoisted import _encode_all, _presized_encoding
    from kubernetes_tpu.testing.synth import synth_cluster, \
        synth_pending_pods

    nodes, init_pods = synth_cluster(8, pods_per_node=1)
    pending = synth_pending_pods(6)
    enc, pe = _presized_encoding(nodes, init_pods, pending)
    arrays = _encode_all(enc, pe, pending)
    templates = []
    seen = set()
    from kubernetes_tpu.ops.hoisted import template_fingerprint

    for a in arrays:
        fp = template_fingerprint(a)
        if fp not in seen:
            seen.add(fp)
            templates.append(a)
    sess = PallasSession(enc.device_state(), templates, interpret=True)

    def dispatch():
        # The COUNTED MISS fires before any dispatch result is used, so
        # the accounting contract holds even where this jax build cannot
        # lower the interpret-mode kernel (the compile event then simply
        # carries ok=False and the jit fallback's failure is irrelevant
        # to what this test pins).
        try:
            sess.schedule(arrays)
        except Exception:  # noqa: BLE001
            pass

    devtime.set_level(1)
    c0 = devtime.TIMELINE.compiles
    dispatch()  # first dispatch of this bucket: a counted miss
    c1 = devtime.TIMELINE.compiles
    assert c1 == c0 + 1, "bucket miss did not record a compile event"
    dispatch()  # cache hit (even a pinned failed compile): no new event
    assert devtime.TIMELINE.compiles == c1
    # forced eviction: drop the bucket's executables, next dispatch is a
    # fresh counted miss
    evicted = [k for k in list(sess._exec)]
    assert evicted
    for k in evicted:
        del sess._exec[k]
    dispatch()
    assert devtime.TIMELINE.compiles == c1 + 1, (
        "forced eviction did not record exactly one compile event"
    )
    recs = [r for r in devtime.TIMELINE.snapshot() if r[1] == "compile"]
    assert recs and recs[-1][2] == "pallas-bucket"
