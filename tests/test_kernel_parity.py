"""TPU kernel vs Go-semantics oracle: exact filter/score parity.

The north-star requirement (BASELINE.md) is identical binding decisions at
percentageOfNodesToScore=100. These tests fuzz randomized clusters and
pending pods, then assert the fused kernel (ops/kernel.py) reproduces the
oracle Framework's per-node feasibility mask and per-plugin weighted scores
bit-for-bit — the reference's own strategy of table-driven plugin tests
(pkg/scheduler/framework/plugins/*_test.go) generalized into an A/B fuzzer.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.kernel import schedule_pod
from kubernetes_tpu.scheduler.framework.interface import CycleState
from kubernetes_tpu.scheduler.framework.runtime import Framework
from kubernetes_tpu.scheduler.framework.snapshot import Snapshot
from kubernetes_tpu.scheduler.plugins.registry import (
    default_plugins,
    new_in_tree_registry,
)

from .util import make_node, make_pod

# oracle plugin name -> kernel score key
SCORE_KEYS = {
    "NodeResourcesBalancedAllocation": "score_balanced",
    "ImageLocality": "score_image",
    "InterPodAffinity": "score_ipa",
    "NodeResourcesLeastAllocated": "score_least",
    "NodeAffinity": "score_node_affinity",
    "NodePreferAvoidPods": "score_prefer_avoid",
    "PodTopologySpread": "score_pts",
    "TaintToleration": "score_taint",
}


def oracle_eval(snapshot: Snapshot, pod: v1.Pod):
    fwk = Framework(
        new_in_tree_registry(), plugins=default_plugins(), snapshot_fn=lambda: snapshot
    )
    state = CycleState()
    status = fwk.run_pre_filter_plugins(state, pod)
    assert status is None, status
    mask = {}
    for ni in snapshot.list():
        statuses = fwk.run_filter_plugins(state, pod, ni)
        mask[ni.node.metadata.name] = not statuses
    feasible = [ni.node for ni in snapshot.list() if mask[ni.node.metadata.name]]
    scores = {}
    if feasible:
        st = fwk.run_pre_score_plugins(state, pod, feasible)
        assert st is None, st
        scores_map, st = fwk.run_score_plugins(state, pod, feasible)
        assert st is None, st
        for plugin, node_scores in scores_map.items():
            scores[plugin] = {ns.name: ns.score for ns in node_scores}
    return mask, scores


def kernel_eval(nodes, pods, pod: v1.Pod):
    enc = ClusterEncoding()
    enc.set_cluster(nodes, pods)
    cluster = enc.device_state()
    pe = PodEncoder(enc)
    # encode may grow vocab capacities; refresh the device state afterwards
    parrays = pe.encode(pod)
    cluster = enc.device_state()
    out = schedule_pod(cluster, parrays)
    return enc, {k: np.asarray(vv) for k, vv in out.items()}


def assert_parity(nodes, pods, pending, label=""):
    snapshot = Snapshot.from_objects(pods, nodes)
    omask, oscores = oracle_eval(snapshot, pending)
    enc, kout = kernel_eval(nodes, pods, pending)
    for name, idx in enc.node_index.items():
        assert bool(kout["feasible"][idx]) == omask[name], (
            f"{label}: feasibility mismatch on {name}: "
            f"kernel={bool(kout['feasible'][idx])} oracle={omask[name]}"
        )
    for plugin, key in SCORE_KEYS.items():
        for name, score in oscores.get(plugin, {}).items():
            idx = enc.node_index[name]
            assert int(kout[key][idx]) == score, (
                f"{label}: {plugin} score mismatch on {name}: "
                f"kernel={int(kout[key][idx])} oracle={score}"
            )


# ---------------------------------------------------------------------------
# directed cases


def test_fit_and_ports():
    nodes = [
        make_node("n0", cpu="4", memory="8Gi", pods=10),
        make_node("n1", cpu="2", memory="8Gi", pods=10),
        make_node("n2", cpu="4", memory="8Gi", pods=1),
    ]
    pods = [
        make_pod(node_name="n2"),
        make_pod(node_name="n0", cpu="1", host_port=8080),
    ]
    pending = make_pod(cpu="3", host_port=8080)
    assert_parity(nodes, pods, pending, "fit/ports")


def test_taints_and_unschedulable():
    nodes = [
        make_node("n0", taints=[v1.Taint("k1", "v1", "NoSchedule")]),
        make_node("n1", taints=[v1.Taint("k2", "v2", "PreferNoSchedule")]),
        make_node("n2", unschedulable=True),
        make_node("n3"),
    ]
    pending = make_pod(
        tolerations=[v1.Toleration(key="k1", operator="Equal", value="v1")]
    )
    assert_parity(nodes, [], pending, "taints")


def test_topology_spread():
    nodes = [
        make_node(f"n{i}", labels={"zone": f"z{i % 3}", v1.LABEL_HOSTNAME: f"n{i}"})
        for i in range(6)
    ]
    pods = [
        make_pod(node_name="n0", labels={"app": "x"}),
        make_pod(node_name="n0", labels={"app": "x"}),
        make_pod(node_name="n1", labels={"app": "x"}),
        make_pod(node_name="n3", labels={"app": "y"}),
    ]
    from .util import spread_constraint

    pending = make_pod(
        labels={"app": "x"},
        constraints=[
            spread_constraint(1, "zone", "DoNotSchedule", {"app": "x"}),
            spread_constraint(2, v1.LABEL_HOSTNAME, "ScheduleAnyway", {"app": "x"}),
        ],
    )
    assert_parity(nodes, pods, pending, "topology-spread")


def test_inter_pod_affinity():
    nodes = [
        make_node(f"n{i}", labels={"zone": f"z{i % 2}", v1.LABEL_HOSTNAME: f"n{i}"})
        for i in range(4)
    ]
    from .util import anti_affinity, pod_affinity

    pods = [
        make_pod(node_name="n0", labels={"app": "db"}),
        make_pod(
            node_name="n1", labels={"app": "web"},
            affinity=anti_affinity("zone", {"app": "web"}),
        ),
    ]
    pending = make_pod(labels={"app": "web"}, affinity=pod_affinity("zone", {"app": "db"}))
    assert_parity(nodes, pods, pending, "ipa-affinity")
    pending2 = make_pod(labels={"app": "web"})
    assert_parity(nodes, pods, pending2, "ipa-existing-anti")


# ---------------------------------------------------------------------------
# randomized fuzz


def _rand_affinity(rng: random.Random):
    apps = ["a", "b", "c"]
    kind = rng.random()
    term = v1.PodAffinityTerm(
        label_selector=v1.LabelSelector(match_labels={"app": rng.choice(apps)}),
        topology_key=rng.choice(["zone", v1.LABEL_HOSTNAME]),
        namespaces=rng.choice([None, ["default"], ["default", "other"]]),
    )
    wterm = v1.WeightedPodAffinityTerm(weight=rng.randint(1, 100), pod_affinity_term=term)
    if kind < 0.3:
        return v1.Affinity(
            pod_affinity=v1.PodAffinity(
                required_during_scheduling_ignored_during_execution=[term]
            )
        )
    if kind < 0.6:
        return v1.Affinity(
            pod_anti_affinity=v1.PodAntiAffinity(
                required_during_scheduling_ignored_during_execution=[term]
            )
        )
    if kind < 0.8:
        return v1.Affinity(
            pod_affinity=v1.PodAffinity(
                preferred_during_scheduling_ignored_during_execution=[wterm]
            )
        )
    return v1.Affinity(
        pod_anti_affinity=v1.PodAntiAffinity(
            preferred_during_scheduling_ignored_during_execution=[wterm]
        )
    )


def _rand_node_affinity(rng: random.Random):
    ops = [
        v1.NodeSelectorRequirement(key="zone", operator="In", values=["z0", "z1"]),
        v1.NodeSelectorRequirement(key="disk", operator="Exists"),
        v1.NodeSelectorRequirement(key="disk", operator="DoesNotExist"),
        v1.NodeSelectorRequirement(key="zone", operator="NotIn", values=["z2"]),
        v1.NodeSelectorRequirement(key="cap", operator="Gt", values=["5"]),
        v1.NodeSelectorRequirement(key="cap", operator="Lt", values=["3"]),
    ]
    terms = [
        v1.NodeSelectorTerm(match_expressions=rng.sample(ops, rng.randint(1, 2)))
        for _ in range(rng.randint(1, 2))
    ]
    required = v1.NodeSelector(node_selector_terms=terms) if rng.random() < 0.7 else None
    preferred = None
    if rng.random() < 0.5:
        preferred = [
            v1.PreferredSchedulingTerm(
                weight=rng.randint(1, 100),
                preference=v1.NodeSelectorTerm(match_expressions=[rng.choice(ops)]),
            )
            for _ in range(rng.randint(1, 2))
        ]
    if required is None and preferred is None:
        return None
    return v1.Affinity(
        node_affinity=v1.NodeAffinity(
            required_during_scheduling_ignored_during_execution=required,
            preferred_during_scheduling_ignored_during_execution=preferred,
        )
    )


def random_cluster(rng: random.Random):
    n = rng.randint(4, 10)
    nodes = []
    taint_pool = [
        v1.Taint("dedicated", "infra", "NoSchedule"),
        v1.Taint("spot", "true", "PreferNoSchedule"),
        v1.Taint("gpu", "yes", "NoExecute"),
    ]
    for i in range(n):
        labels = {
            "zone": f"z{i % 3}",
            v1.LABEL_HOSTNAME: f"n{i}",
            "cap": str(rng.randint(0, 9)),
        }
        if rng.random() < 0.4:
            labels["disk"] = "ssd"
        images = None
        if rng.random() < 0.5:
            images = [
                v1.ContainerImage(
                    names=[f"registry.example/app:v{rng.randint(1, 2)}"],
                    size_bytes=rng.randint(10, 2000) * 1024 * 1024,
                )
            ]
        node = make_node(
            f"n{i}",
            cpu=str(rng.randint(2, 8)),
            memory=f"{rng.randint(4, 32)}Gi",
            pods=rng.randint(2, 8),
            labels=labels,
            taints=rng.sample(taint_pool, rng.randint(0, 2)) or None,
            unschedulable=rng.random() < 0.15,
            images=images,
            extended={"example.com/gpu": str(rng.randint(0, 4))}
            if rng.random() < 0.3
            else None,
        )
        if rng.random() < 0.2:
            node.metadata.annotations = {
                "scheduler.alpha.kubernetes.io/preferAvoidPods": (
                    '{"preferAvoidPods":[{"podSignature":{"podController":'
                    '{"kind":"ReplicaSet","uid":"rs-1"}}}]}'
                )
            }
        nodes.append(node)
    pods = []
    for i in range(rng.randint(0, 3 * n)):
        pod = make_pod(
            name=f"existing-{i}",
            namespace=rng.choice(["default", "other"]),
            node_name=f"n{rng.randrange(n)}",
            labels={"app": rng.choice(["a", "b", "c"])},
            cpu=rng.choice([None, "100m", "500m", "1"]),
            memory=rng.choice([None, "128Mi", "1Gi"]),
            host_port=rng.choice([0, 0, 0, 8080, 9090]),
            affinity=_rand_affinity(rng) if rng.random() < 0.4 else None,
        )
        if rng.random() < 0.1:
            pod.metadata.deletion_timestamp = 1.0
        pods.append(pod)
    return nodes, pods


def random_pending(rng: random.Random):
    from .util import spread_constraint

    constraints = None
    if rng.random() < 0.5:
        constraints = [
            spread_constraint(
                rng.randint(1, 2),
                rng.choice(["zone", v1.LABEL_HOSTNAME]),
                rng.choice(["DoNotSchedule", "ScheduleAnyway"]),
                {"app": rng.choice(["a", "b"])},
            )
            for _ in range(rng.randint(1, 2))
        ]
    tolerations = None
    if rng.random() < 0.5:
        tolerations = [
            v1.Toleration(
                key=rng.choice(["dedicated", "spot", ""]),
                operator=rng.choice(["Exists", "Equal"]),
                value=rng.choice(["infra", "true", ""]),
                effect=rng.choice(["", "NoSchedule", "PreferNoSchedule"]),
            )
        ]
    pod = make_pod(
        name="pending",
        namespace=rng.choice(["default", "other"]),
        labels={"app": rng.choice(["a", "b", "c"])},
        cpu=rng.choice([None, "500m", "2"]),
        memory=rng.choice([None, "512Mi", "4Gi"]),
        host_port=rng.choice([0, 0, 8080]),
        node_selector={"zone": "z0"} if rng.random() < 0.2 else None,
        affinity=None,
        tolerations=tolerations,
        constraints=constraints,
        image=f"registry.example/app:v{rng.randint(1, 2)}",
        containers=rng.randint(1, 2),
        extended={"example.com/gpu": "1"} if rng.random() < 0.2 else None,
    )
    affs = []
    if rng.random() < 0.5:
        affs.append(_rand_affinity(rng))
    na = _rand_node_affinity(rng) if rng.random() < 0.5 else None
    affinity = v1.Affinity()
    used = False
    for a in affs:
        if a.pod_affinity:
            affinity.pod_affinity = a.pod_affinity
            used = True
        if a.pod_anti_affinity:
            affinity.pod_anti_affinity = a.pod_anti_affinity
            used = True
    if na is not None:
        affinity.node_affinity = na.node_affinity
        used = True
    if used:
        pod.spec.affinity = affinity
    if rng.random() < 0.3:
        pod.metadata.owner_references = [
            v1.OwnerReference(kind="ReplicaSet", uid="rs-1", controller=True)
        ]
    if rng.random() < 0.2 and pod.spec.node_name == "":
        pod.spec.node_name = ""  # keep unset; NodeName covered by directed test
    return pod


@pytest.mark.parametrize("seed", range(30))
def test_fuzz_parity(seed):
    rng = random.Random(seed)
    nodes, pods = random_cluster(rng)
    for trial in range(3):
        pending = random_pending(rng)
        assert_parity(nodes, pods, pending, f"seed={seed} trial={trial}")
