"""kubectl CLI tests against an in-proc cluster.

Reference shape: staging/src/k8s.io/kubectl command tests (cmd/*_test.go)
— verbs over a fake cluster, asserting output and API effects.
"""

import io
import json
import sys

import pytest
import yaml

from kubernetes_tpu.api import apps
from kubernetes_tpu.api import types as v1
from kubernetes_tpu.api.labels import Selector
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.kubectl import Kubectl

from .util import make_node, make_pod


@pytest.fixture()
def kubectl():
    api = APIServer()
    cs = Clientset(api)
    out = io.StringIO()
    return Kubectl(cs, out=out), cs, out


def _lines(out):
    return out.getvalue().strip().splitlines()


class TestSelectorParse:
    def test_grammar(self):
        sel = Selector.parse("a=1,b!=2,c in (x, y),d notin (z),e,!f,g>5")
        assert sel.matches({"a": "1", "c": "x", "e": "", "g": "7"})
        assert not sel.matches({"a": "1", "c": "x", "e": "", "g": "7", "f": ""})
        assert not sel.matches({"a": "1", "c": "q", "e": "", "g": "7"})
        assert not sel.matches({"a": "1", "c": "x", "e": "", "g": "7", "b": "2"})

    def test_set_op_without_space_before_paren(self):
        # real kubectl lexer splits on '(' — no space required
        sel = Selector.parse("app in(web,api)")
        assert sel.matches({"app": "web"})
        assert not sel.matches({"app": "db"})
        sel = Selector.parse("app notin(web)")
        assert sel.matches({"app": "db"})
        assert not sel.matches({"app": "web"})


class TestGet:
    def test_get_pods_table(self, kubectl):
        k, cs, out = kubectl
        cs.nodes.create(make_node("n1"))
        p = make_pod("web-1", node_name="n1", labels={"app": "web"})
        cs.pods.create(p)
        assert k.run(["get", "pods"]) == 0
        lines = _lines(out)
        assert lines[0].split()[:3] == ["NAME", "READY", "STATUS"]
        assert lines[1].startswith("web-1")

    def test_get_with_selector_and_output(self, kubectl):
        k, cs, out = kubectl
        cs.pods.create(make_pod("a", labels={"app": "x"}))
        cs.pods.create(make_pod("b", labels={"app": "y"}))
        assert k.run(["get", "pods", "-l", "app=x", "-o", "name"]) == 0
        assert _lines(out) == ["pods/a"]

    def test_get_yaml_roundtrip(self, kubectl):
        k, cs, out = kubectl
        cs.pods.create(make_pod("a", labels={"app": "x"}))
        assert k.run(["get", "pods", "a", "-o", "yaml"]) == 0
        doc = yaml.safe_load(out.getvalue())
        assert doc["metadata"]["name"] == "a"
        assert doc["metadata"]["labels"] == {"app": "x"}

    def test_get_nodes_status(self, kubectl):
        k, cs, out = kubectl
        cs.nodes.create(make_node("n1"))
        assert k.run(["cordon", "n1"]) == 0
        out.truncate(0), out.seek(0)
        assert k.run(["get", "nodes"]) == 0
        assert "SchedulingDisabled" in _lines(out)[1]


class TestManifests:
    DEPLOY = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": "web"},
        "spec": {
            "replicas": 2,
            "selector": {"matchLabels": {"app": "web"}},
            "template": {
                "metadata": {"labels": {"app": "web"}},
                "spec": {"containers": [{"name": "c", "image": "img:1"}]},
            },
        },
    }

    def test_create_from_file(self, kubectl, tmp_path):
        k, cs, out = kubectl
        f = tmp_path / "d.yaml"
        f.write_text(yaml.safe_dump(self.DEPLOY))
        assert k.run(["create", "-f", str(f)]) == 0
        dep = cs.deployments.get("web", "default")
        assert dep.spec.replicas == 2

    def test_apply_three_way(self, kubectl, tmp_path):
        k, cs, out = kubectl
        f = tmp_path / "d.yaml"
        f.write_text(yaml.safe_dump(self.DEPLOY))
        assert k.run(["apply", "-f", str(f)]) == 0
        assert "created" in out.getvalue()
        # server-side mutation not tracked by apply: status update
        dep = cs.deployments.get("web", "default")
        dep.status.replicas = 2
        cs.deployments.update_status(dep)
        # re-apply with replicas gone (field removal) and image changed
        doc = json.loads(json.dumps(self.DEPLOY))
        del doc["spec"]["replicas"]
        doc["spec"]["template"]["spec"]["containers"][0]["image"] = "img:2"
        f.write_text(yaml.safe_dump(doc))
        assert k.run(["apply", "-f", str(f)]) == 0
        dep = cs.deployments.get("web", "default")
        assert dep.spec.replicas is None  # removed by 3-way merge
        assert dep.spec.template.spec.containers[0].image == "img:2"
        assert dep.status.replicas == 2  # live-only field preserved

    def test_delete_from_file(self, kubectl, tmp_path):
        k, cs, out = kubectl
        f = tmp_path / "d.yaml"
        f.write_text(yaml.safe_dump(self.DEPLOY))
        assert k.run(["create", "-f", str(f)]) == 0
        assert k.run(["delete", "-f", str(f)]) == 0
        from kubernetes_tpu.apiserver.server import NotFound

        with pytest.raises(NotFound):
            cs.deployments.get("web", "default")


class TestNodeOps:
    def test_scale(self, kubectl):
        k, cs, out = kubectl
        cs.deployments.create(
            apps.Deployment(
                metadata=v1.ObjectMeta(name="web", namespace="default"),
                spec=apps.DeploymentSpec(
                    replicas=1,
                    selector=v1.LabelSelector(match_labels={"a": "b"}),
                    template=v1.PodTemplateSpec(
                        metadata=v1.ObjectMeta(labels={"a": "b"}),
                        spec=v1.PodSpec(containers=[v1.Container(name="c", image="i")]),
                    ),
                ),
            )
        )
        assert k.run(["scale", "deploy/web", "--replicas", "5"]) == 0
        assert cs.deployments.get("web", "default").spec.replicas == 5

    def test_label_annotate(self, kubectl):
        k, cs, out = kubectl
        cs.pods.create(make_pod("p"))
        assert k.run(["label", "pods", "p", "tier=db"]) == 0
        assert cs.pods.get("p", "default").metadata.labels["tier"] == "db"
        # no overwrite without flag
        assert k.run(["label", "pods", "p", "tier=web"]) == 1
        assert k.run(["label", "pods", "p", "tier=web", "--overwrite"]) == 0
        assert cs.pods.get("p", "default").metadata.labels["tier"] == "web"
        assert k.run(["label", "pods", "p", "tier-"]) == 0
        assert "tier" not in (cs.pods.get("p", "default").metadata.labels or {})
        assert k.run(["annotate", "pods", "p", "note=x"]) == 0
        assert cs.pods.get("p", "default").metadata.annotations["note"] == "x"

    def test_taint(self, kubectl):
        k, cs, out = kubectl
        cs.nodes.create(make_node("n1"))
        assert k.run(["taint", "nodes", "n1", "gpu=true:NoSchedule"]) == 0
        node = cs.nodes.get("n1")
        assert node.spec.taints[0].key == "gpu"
        assert node.spec.taints[0].effect == "NoSchedule"
        assert k.run(["taint", "nodes", "n1", "gpu-"]) == 0
        assert not cs.nodes.get("n1").spec.taints

    def test_drain(self, kubectl):
        k, cs, out = kubectl
        cs.nodes.create(make_node("n1"))
        managed = make_pod("m", node_name="n1")
        managed.metadata.owner_references = [
            v1.OwnerReference(kind="ReplicaSet", name="rs")
        ]
        ds_pod = make_pod("d", node_name="n1")
        ds_pod.metadata.owner_references = [
            v1.OwnerReference(kind="DaemonSet", name="ds")
        ]
        bare = make_pod("b", node_name="n1")
        for p in (managed, ds_pod, bare):
            cs.pods.create(p)
        # refuses: daemonset pod present
        assert k.run(["drain", "n1"]) == 1
        assert (
            k.run(["drain", "n1", "--ignore-daemonsets"]) == 1
        )  # bare pod needs --force
        assert k.run(["drain", "n1", "--ignore-daemonsets", "--force"]) == 0
        remaining = {p.metadata.name for p in cs.pods.list()[0]}
        assert remaining == {"d"}  # only the DaemonSet pod stays
        assert cs.nodes.get("n1").spec.unschedulable

    def test_rollout_status(self, kubectl):
        k, cs, out = kubectl
        cs.deployments.create(
            apps.Deployment(
                metadata=v1.ObjectMeta(name="web", namespace="default"),
                spec=apps.DeploymentSpec(
                    replicas=2,
                    selector=v1.LabelSelector(match_labels={"a": "b"}),
                    template=v1.PodTemplateSpec(
                        metadata=v1.ObjectMeta(labels={"a": "b"}),
                        spec=v1.PodSpec(containers=[v1.Container(name="c", image="i")]),
                    ),
                ),
            )
        )
        assert k.run(["rollout", "status", "deploy/web"]) == 0
        assert "Waiting" in out.getvalue()
        dep = cs.deployments.get("web", "default")
        dep.status.available_replicas = 2
        cs.deployments.update_status(dep)
        out.truncate(0), out.seek(0)
        assert k.run(["rollout", "status", "deploy/web"]) == 0
        assert "successfully rolled out" in out.getvalue()


class TestLogsExec:
    """kubectl logs/exec: apiserver pod subresource → node proxy →
    kubelet → CRI (registry/core/pod/rest/{log,exec}; kubelet server)."""

    def _cluster(self):
        from kubernetes_tpu.client.informer import SharedInformerFactory
        from kubernetes_tpu.kubelet.cri import FakeRuntimeService
        from kubernetes_tpu.kubelet.kubelet import Kubelet, KubeletConfig

        from .util import FAST_KUBELET as FAST, wait_until

        api = APIServer()
        cs = Clientset(api)
        factory = SharedInformerFactory(cs)
        kl = Kubelet(cs, factory,
                     config=KubeletConfig(node_name="node-0", **FAST),
                     runtime=FakeRuntimeService())
        factory.start()
        assert factory.wait_for_cache_sync()
        kl.run()
        cs.pods.create(make_pod("web", node_name="node-0"))
        wait_until(
            lambda: cs.pods.get("web", "default").status.phase == "Running",
            timeout=10,
        )
        return api, cs, kl

    def test_logs_and_exec(self):
        api, cs, kl = self._cluster()
        try:
            out = io.StringIO()
            assert Kubectl(cs, out=out).run(["logs", "web"]) == 0
            assert "starting c0" in out.getvalue()

            out = io.StringIO()
            assert Kubectl(cs, out=out).run(["exec", "web", "ps"]) == 0
            assert "pid 1: c0" in out.getvalue()
        finally:
            kl.stop()

    def test_logs_unscheduled_pod_errors(self):
        api = APIServer()
        cs = Clientset(api)
        cs.pods.create(make_pod("pending-pod"))
        out = io.StringIO()
        assert Kubectl(cs, out=out).run(["logs", "pending-pod"]) == 1
        assert "not scheduled" in out.getvalue()

    def test_logs_no_kubelet_connection(self):
        api = APIServer()
        cs = Clientset(api)
        cs.pods.create(make_pod("orphan", node_name="gone-node"))
        out = io.StringIO()
        assert Kubectl(cs, out=out).run(["logs", "orphan"]) == 1
        assert "no kubelet connection" in out.getvalue()

    def test_logs_after_kubelet_stop(self):
        api, cs, kl = self._cluster()
        kl.stop()
        out = io.StringIO()
        assert Kubectl(cs, out=out).run(["logs", "web"]) == 1
        assert "no kubelet connection" in out.getvalue()


class TestPatch:
    def test_merge_patch_labels(self, kubectl):
        k, cs, out = kubectl
        cs.pods.create(make_pod("p1", labels={"app": "a", "tier": "web"}))
        assert k.run([
            "patch", "pods", "p1",
            "-p", '{"metadata":{"labels":{"app":"b","tier":null}}}',
        ]) == 0
        pod = cs.pods.get("p1", "default")
        assert pod.metadata.labels == {"app": "b"}
        assert "patched" in out.getvalue()

    def test_json_patch_replace_and_remove(self, kubectl):
        k, cs, out = kubectl
        cs.pods.create(make_pod("p2", labels={"app": "a", "x": "1"}))
        assert k.run([
            "patch", "pods", "p2", "--type", "json",
            "-p", json.dumps([
                {"op": "replace", "path": "/metadata/labels/app",
                 "value": "z"},
                {"op": "remove", "path": "/metadata/labels/x"},
            ]),
        ]) == 0
        pod = cs.pods.get("p2", "default")
        assert pod.metadata.labels == {"app": "z"}

    def test_strategic_patch_merges_containers_by_name(self, kubectl):
        """The default --type strategic merges list fields by their
        patchMergeKey (containers by name): patching one container's
        image must keep the other container."""
        k, cs, out = kubectl
        pod = make_pod("p4")
        from kubernetes_tpu.api import types as v1

        pod.spec.containers.append(
            v1.Container(name="sidecar", image="registry.example/side:v1")
        )
        cs.pods.create(pod)
        assert k.run([
            "patch", "pods", "p4",
            "-p", '{"spec":{"containers":[{"name":"c0","image":"new:v2"}]}}',
        ]) == 0
        got = cs.pods.get("p4", "default")
        by_name = {c.name: c for c in got.spec.containers}
        assert set(by_name) == {"c0", "sidecar"}
        assert by_name["c0"].image == "new:v2"
        assert by_name["sidecar"].image == "registry.example/side:v1"

    def test_merge_patch_replaces_containers_wholesale(self, kubectl):
        """--type merge keeps RFC 7386 list semantics: replace."""
        k, cs, out = kubectl
        pod = make_pod("p5")
        from kubernetes_tpu.api import types as v1

        pod.spec.containers.append(
            v1.Container(name="sidecar", image="registry.example/side:v1")
        )
        cs.pods.create(pod)
        assert k.run([
            "patch", "pods", "p5", "--type", "merge",
            "-p", '{"spec":{"containers":[{"name":"c0","image":"new:v2"}]}}',
        ]) == 0
        got = cs.pods.get("p5", "default")
        assert [c.name for c in got.spec.containers] == ["c0"]

    def test_strategic_patch_delete_directive(self, kubectl):
        k, cs, out = kubectl
        pod = make_pod("p6")
        from kubernetes_tpu.api import types as v1

        pod.spec.containers.append(
            v1.Container(name="sidecar", image="registry.example/side:v1")
        )
        cs.pods.create(pod)
        assert k.run([
            "patch", "pods", "p6",
            "-p",
            '{"spec":{"containers":[{"name":"sidecar","$patch":"delete"}]}}',
        ]) == 0
        got = cs.pods.get("p6", "default")
        assert [c.name for c in got.spec.containers] == ["c0"]

    def test_strategic_patch_service_ports_merge_by_port(self, kubectl):
        """ServiceSpec.Ports merges by `port` (not containerPort): adding
        a nodePort to one port must keep the other ports."""
        k, cs, out = kubectl
        from kubernetes_tpu.api import types as v1

        cs.resource("services").create(
            v1.Service(
                metadata=v1.ObjectMeta(name="svc", namespace="default"),
                spec=v1.ServiceSpec(
                    selector={"app": "a"},
                    ports=[
                        v1.ServicePort(name="http", port=80, target_port=8080),
                        v1.ServicePort(name="https", port=443, target_port=8443),
                    ],
                ),
            )
        )
        assert k.run([
            "patch", "services", "svc",
            "-p", '{"spec":{"ports":[{"port":80,"nodePort":30080}]}}',
        ]) == 0
        got = cs.resource("services").get("svc", "default")
        by_port = {p.port: p for p in got.spec.ports}
        assert set(by_port) == {80, 443}
        assert by_port[80].node_port == 30080
        assert by_port[80].target_port == 8080

    def test_patch_status_subresource(self, kubectl):
        k, cs, out = kubectl
        cs.pods.create(make_pod("p3"))
        assert k.run([
            "patch", "pods", "p3", "--subresource", "status",
            "-p", '{"status":{"phase":"Running"}}',
        ]) == 0
        assert cs.pods.get("p3", "default").status.phase == "Running"


class TestWait:
    def test_wait_for_field_and_delete(self, kubectl):
        import threading
        import time as _time

        k, cs, out = kubectl
        cs.pods.create(make_pod("w1"))

        def later():
            _time.sleep(0.3)
            p = cs.pods.get("w1", "default")
            p.status.phase = "Running"
            cs.pods.update_status(p)

        threading.Thread(target=later, daemon=True).start()
        assert k.run([
            "wait", "pods", "w1", "--for", "status.phase=Running",
            "--timeout", "5",
        ]) == 0

        def delete_later():
            _time.sleep(0.3)
            cs.pods.delete("w1", "default")

        threading.Thread(target=delete_later, daemon=True).start()
        assert k.run([
            "wait", "pods", "w1", "--for", "delete", "--timeout", "5",
        ]) == 0

    def test_wait_timeout_fails(self, kubectl):
        k, cs, out = kubectl
        cs.pods.create(make_pod("w2"))
        assert k.run([
            "wait", "pods", "w2", "--for", "status.phase=Running",
            "--timeout", "0.4",
        ]) == 1
        assert "timed out" in out.getvalue()


class TestAttachPortForward:
    """kubectl attach / port-forward over the streaming sessions
    (kubelet/streaming.py; staging kubectl pkg/cmd/{attach,portforward})."""

    def test_attach_streams_container_output(self):
        t = TestLogsExec()
        api, cs, kl = t._cluster()
        try:
            out = io.StringIO()
            assert Kubectl(cs, out=out).run(
                ["attach", "web", "--read-timeout", "0.5"]
            ) == 0
            assert "starting" in out.getvalue()
        finally:
            kl.stop()

    def test_port_forward_roundtrip(self):
        t = TestLogsExec()
        api, cs, kl = t._cluster()
        try:
            for sb in kl.runtime.list_pod_sandboxes():
                if sb.pod_name == "web":
                    kl.runtime.register_port_server(
                        sb.id, 8080, lambda b: b"echo:" + b)
            out = io.StringIO()
            assert Kubectl(cs, out=out).run(
                ["port-forward", "web", "8080", "--send", "hello"]
            ) == 0
            assert out.getvalue() == "echo:hello"
        finally:
            kl.stop()


class TestRound4Verbs:
    def test_api_resources_lists_table(self, kubectl):
        k, cs, out = kubectl
        assert k.run(["api-resources"]) == 0
        lines = _lines(out)
        assert lines[0].split()[:3] == ["NAME", "APIVERSION", "NAMESPACED"]
        names = {ln.split()[0] for ln in lines[1:]}
        assert {"pods", "nodes", "ingresses", "networkpolicies"} <= names

    def test_explain_walks_fields(self, kubectl):
        k, cs, out = kubectl
        assert k.run(["explain", "pods.spec.nodeName"]) == 0
        text = out.getvalue()
        assert "KIND:     Pod" in text
        assert "FIELD TYPE: str" in text

    def test_explain_lists_subfields(self, kubectl):
        k, cs, out = kubectl
        assert k.run(["explain", "pods.spec"]) == 0
        text = out.getvalue()
        assert "containers" in text
        assert "nodeName" in text

    def test_explain_bad_field(self, kubectl):
        k, cs, out = kubectl
        assert k.run(["explain", "pods.spec.bogus"]) == 1
        assert "does not exist" in out.getvalue()

    def test_edit_applies_editor_changes(self, kubectl, tmp_path, monkeypatch):
        k, cs, out = kubectl
        cs.pods.create(make_pod("p-edit", labels={"app": "old"}))
        # a scripted "editor": rewrites the label value in place
        script = tmp_path / "ed.py"
        script.write_text(
            "import sys\n"
            "p = sys.argv[1]\n"
            "s = open(p).read().replace('old', 'new')\n"
            "open(p, 'w').write(s)\n"
        )
        monkeypatch.setenv("KUBE_EDITOR", f"{sys.executable} {script}")
        assert k.run(["edit", "pods", "p-edit"]) == 0
        assert cs.pods.get("p-edit", "default").metadata.labels["app"] == "new"
        assert "edited" in out.getvalue()

    def test_edit_no_changes(self, kubectl, tmp_path, monkeypatch):
        k, cs, out = kubectl
        cs.pods.create(make_pod("p-same"))
        script = tmp_path / "noop.py"
        script.write_text("pass\n")
        monkeypatch.setenv("KUBE_EDITOR", f"{sys.executable} {script}")
        assert k.run(["edit", "pods", "p-same"]) == 0
        assert "no changes" in out.getvalue()

    def test_auth_can_i_without_rbac(self, kubectl):
        k, cs, out = kubectl
        assert k.run(["auth", "can-i", "create", "pods"]) == 0
        assert out.getvalue().strip() == "yes"

    def test_auth_can_i_with_rbac(self):
        from kubernetes_tpu.api import rbac
        from kubernetes_tpu.apiserver.auth import SecureAPIServer

        secure = SecureAPIServer()
        api = secure.api
        api.create("clusterroles", rbac.ClusterRole(
            metadata=v1.ObjectMeta(name="pod-reader"),
            rules=[rbac.PolicyRule(verbs=["get", "list"],
                                   resources=["pods"])],
        ))
        api.create("clusterrolebindings", rbac.ClusterRoleBinding(
            metadata=v1.ObjectMeta(name="rb"),
            role_ref=rbac.RoleRef(kind="ClusterRole", name="pod-reader"),
            subjects=[rbac.Subject(kind="User", name="alice")],
        ))
        api.authorizer = secure.authorizer  # the CLI reads api.authorizer
        cs = Clientset(api)
        out = io.StringIO()
        k = Kubectl(cs, out=out)
        assert k.run(["auth", "can-i", "list", "pods", "--as", "alice"]) == 0
        assert out.getvalue().strip() == "yes"
        out2 = io.StringIO()
        k2 = Kubectl(cs, out=out2)
        assert k2.run(["auth", "can-i", "delete", "pods", "--as", "alice"]) == 1
        assert "no" in out2.getvalue()


class TestAuthCanIImpersonationGate:
    """Advisor r4: --as/--as-group requires the caller to hold the
    impersonate verb (filters/impersonation.go); the loopback (no
    request context) client is system:masters and always may."""

    def _secure(self):
        from kubernetes_tpu.api import rbac
        from kubernetes_tpu.apiserver.auth import SecureAPIServer

        secure = SecureAPIServer()
        api = secure.api
        api.create("clusterroles", rbac.ClusterRole(
            metadata=v1.ObjectMeta(name="pod-reader"),
            rules=[rbac.PolicyRule(verbs=["get", "list"],
                                   resources=["pods"])],
        ))
        api.create("clusterrolebindings", rbac.ClusterRoleBinding(
            metadata=v1.ObjectMeta(name="rb"),
            role_ref=rbac.RoleRef(kind="ClusterRole", name="pod-reader"),
            subjects=[rbac.Subject(kind="User", name="alice")],
        ))
        api.authorizer = secure.authorizer
        return secure, api

    def test_plain_caller_cannot_impersonate(self):
        from kubernetes_tpu.apiserver.auth import UserInfo
        from kubernetes_tpu.apiserver.requestcontext import request_user

        _, api = self._secure()
        cs = Clientset(api)
        out = io.StringIO()
        k = Kubectl(cs, out=out)
        with request_user(UserInfo(name="bob", groups=())):
            assert k.run(
                ["auth", "can-i", "list", "pods", "--as", "alice"]) == 1
        assert "impersonate" in out.getvalue()

    def test_impersonate_verb_grants_access(self):
        from kubernetes_tpu.api import rbac
        from kubernetes_tpu.apiserver.auth import UserInfo
        from kubernetes_tpu.apiserver.requestcontext import request_user

        _, api = self._secure()
        api.create("clusterroles", rbac.ClusterRole(
            metadata=v1.ObjectMeta(name="impersonator"),
            rules=[rbac.PolicyRule(verbs=["impersonate"],
                                   resources=["users"])],
        ))
        api.create("clusterrolebindings", rbac.ClusterRoleBinding(
            metadata=v1.ObjectMeta(name="rb-imp"),
            role_ref=rbac.RoleRef(kind="ClusterRole", name="impersonator"),
            subjects=[rbac.Subject(kind="User", name="bob")],
        ))
        cs = Clientset(api)
        out = io.StringIO()
        k = Kubectl(cs, out=out)
        with request_user(UserInfo(name="bob", groups=())):
            assert k.run(
                ["auth", "can-i", "list", "pods", "--as", "alice"]) == 0
        assert "yes" in out.getvalue()

    def test_loopback_still_allowed(self):
        _, api = self._secure()
        cs = Clientset(api)
        out = io.StringIO()
        k = Kubectl(cs, out=out)
        assert k.run(["auth", "can-i", "list", "pods", "--as", "alice"]) == 0


class TestDiffExposeAutoscaleCreate:
    """Round-5 daily-driver tail: diff, expose, autoscale, create
    generators (pkg/cmd/{diff,expose,autoscale,create})."""

    def test_diff_reports_changes_and_exit_code(self, kubectl, tmp_path):
        k, cs, out = kubectl
        manifest = tmp_path / "cm.yaml"
        manifest.write_text(yaml.safe_dump({
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": "cfg", "namespace": "default"},
            "data": {"k": "v1"},
        }))
        # new object: everything is a difference, exit 1
        assert k.run(["diff", "-f", str(manifest)]) == 1
        assert "MERGED/configmaps/cfg" in out.getvalue()
        # apply it, then diff again: no differences, exit 0
        assert k.run(["apply", "-f", str(manifest)]) == 0
        out2 = io.StringIO()
        k2 = Kubectl(cs, out=out2)
        assert k2.run(["diff", "-f", str(manifest)]) == 0
        assert out2.getvalue() == ""
        # change a value: diff shows it without writing
        manifest.write_text(yaml.safe_dump({
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": "cfg", "namespace": "default"},
            "data": {"k": "v2"},
        }))
        out3 = io.StringIO()
        k3 = Kubectl(cs, out=out3)
        assert k3.run(["diff", "-f", str(manifest)]) == 1
        assert '+    "k": "v2"' in out3.getvalue()
        assert cs.resource("configmaps").get("cfg", "default") \
            .data == {"k": "v1"}  # diff never writes

    def test_expose_deployment(self, kubectl):
        k, cs, out = kubectl
        dep = apps.Deployment(
            metadata=v1.ObjectMeta(name="web", namespace="default"),
            spec=apps.DeploymentSpec(
                replicas=2,
                selector=v1.LabelSelector(match_labels={"app": "web"}),
                template=v1.PodTemplateSpec(
                    metadata=v1.ObjectMeta(labels={"app": "web"}),
                    spec=v1.PodSpec(containers=[
                        v1.Container(name="c", image="img")]),
                ),
            ),
        )
        cs.resource("deployments").create(dep)
        assert k.run(["expose", "deployment/web", "--port", "80",
                      "--target-port", "8080"]) == 0
        svc = cs.resource("services").get("web", "default")
        assert svc.spec.selector == {"app": "web"}
        assert svc.spec.ports[0].port == 80
        assert svc.spec.ports[0].target_port == 8080

    def test_expose_pod_by_labels(self, kubectl):
        k, cs, out = kubectl
        cs.pods.create(make_pod("p1", labels={"run": "p1"}))
        assert k.run(["expose", "pod/p1", "--port", "9090",
                      "--name", "p1-svc"]) == 0
        svc = cs.resource("services").get("p1-svc", "default")
        assert svc.spec.selector == {"run": "p1"}
        assert svc.spec.ports[0].target_port == 9090

    def test_autoscale(self, kubectl):
        k, cs, out = kubectl
        dep = apps.Deployment(
            metadata=v1.ObjectMeta(name="web", namespace="default"),
            spec=apps.DeploymentSpec(replicas=1),
        )
        cs.resource("deployments").create(dep)
        assert k.run(["autoscale", "deployment/web", "--min", "2",
                      "--max", "5", "--cpu-percent", "70"]) == 0
        hpa = cs.resource("horizontalpodautoscalers").get("web", "default")
        assert hpa.spec.min_replicas == 2
        assert hpa.spec.max_replicas == 5
        assert hpa.spec.target_cpu_utilization_percentage == 70
        assert hpa.spec.scale_target_ref.name == "web"

    def test_create_generators(self, kubectl):
        k, cs, out = kubectl
        assert k.run(["create", "namespace", "prod"]) == 0
        assert cs.resource("namespaces").get("prod")
        assert k.run(["create", "deployment", "api",
                      "--image", "reg/app:v2", "--replicas", "3"]) == 0
        dep = cs.resource("deployments").get("api", "default")
        assert dep.spec.replicas == 3
        assert dep.spec.template.spec.containers[0].image == "reg/app:v2"
        assert dep.spec.selector.match_labels == {"app": "api"}
        assert k.run(["create", "configmap", "cfg",
                      "--from-literal", "a=1",
                      "--from-literal", "b=2"]) == 0
        assert cs.resource("configmaps").get("cfg", "default").data == {
            "a": "1", "b": "2"}
        assert k.run(["create", "secret", "generic", "tok",
                      "--from-literal", "t=s3cr3t"]) == 0
        import base64

        sec = cs.resource("secrets").get("tok", "default")
        assert base64.b64decode(sec.data["t"]).decode() == "s3cr3t"
        assert k.run(["create", "serviceaccount", "robot"]) == 0
        assert cs.resource("serviceaccounts").get("robot", "default")

    def test_create_manifest_still_works(self, kubectl, tmp_path):
        k, cs, out = kubectl
        manifest = tmp_path / "ns.yaml"
        manifest.write_text(yaml.safe_dump({
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "x"},
        }))
        assert k.run(["create", "-f", str(manifest)]) == 0
        assert cs.resource("namespaces").get("x")
