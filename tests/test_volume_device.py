"""Volume device path: bound-PVC pods on the kernel, parity vs the oracle.

Pins (scheduler/volume_device.py):
  * envelope gating — unbound PVCs, shared claims, oversized term
    products stay on the oracle path;
  * PV nodeAffinity + VolumeZone constraints ride the kernel's
    node-affinity mask with decisions identical to the oracle plugins
    (volume_binding.go bound-check, volume_zone.go);
  * CSI attach limits ride the resource-fit mask
    (nodevolumelimits/csi.go semantics via attachable-volumes-csi-*);
  * the live scheduler loop binds PVC pods through the kernel path
    (no oracle diversion) with correct placement.
"""

from __future__ import annotations

import random

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.scheduler.volume_device import (
    VolumeDeviceResolver,
    attach_resource_name,
    distribute_term_groups,
)

from .test_volumes import mk_pv, mk_pvc, pod_with_pvc
from .util import make_node, wait_until


def mk_resolver(pvcs=(), pvs=(), csinodes=()):
    return VolumeDeviceResolver(
        lambda: list(pvcs), lambda: list(pvs), lambda: list(csinodes)
    )


class TestEnvelope:
    def test_unbound_pvc_is_oracle(self):
        pvc = mk_pvc("c1")  # no volume_name
        r = mk_resolver(pvcs=[pvc])
        assert r.resolve(pod_with_pvc("p", "c1")) is None

    def test_missing_pvc_is_oracle(self):
        r = mk_resolver()
        assert r.resolve(pod_with_pvc("p", "ghost")) is None

    def test_shared_claim_is_oracle(self):
        pvc = mk_pvc("c1", volume_name="pv1")
        pv = mk_pv("pv1")
        r = mk_resolver(pvcs=[pvc], pvs=[pv])
        assert r.resolve(pod_with_pvc("a", "c1")) is not None
        r.pod_added(pod_with_pvc("a", "c1"))  # a is now assumed/assigned
        assert r.resolve(pod_with_pvc("b", "c1")) is None
        r.pod_removed(pod_with_pvc("a", "c1"))
        assert r.resolve(pod_with_pvc("b", "c1")) is not None

    def test_bound_resolves_with_affinity_and_scalars(self):
        pvc = mk_pvc("c1", volume_name="pv1")
        pv = mk_pv("pv1", node="node-3")
        pv.spec.csi = {"driver": "ebs.csi.aws.com", "volumeHandle": "h1"}
        r = mk_resolver(pvcs=[pvc], pvs=[pv])
        res = r.resolve(pod_with_pvc("p", "c1"))
        assert res is not None
        assert len(res.term_groups) == 1  # the PV's required terms
        assert res.extra_scalars == {
            attach_resource_name("ebs.csi.aws.com"): 1
        }


class TestDistribution:
    def test_two_groups_distribute(self):
        t = lambda k, vals: v1.NodeSelectorTerm(match_expressions=[
            v1.NodeSelectorRequirement(key=k, operator="In", values=vals)
        ])
        out = distribute_term_groups(
            None, [[t("a", ["1"]), t("a", ["2"])], [t("b", ["x"])]]
        )
        assert len(out) == 2
        for term in out:
            keys = [r.key for r in term.match_expressions]
            assert keys.count("b") == 1

    def test_empty_group_is_never(self):
        out = distribute_term_groups(
            None, [[v1.NodeSelectorTerm()]]  # empty term matches nothing
        )
        assert len(out) == 1
        assert out[0].match_expressions[0].values == []


def _live_cluster(n_nodes=6):
    from kubernetes_tpu.apiserver import APIServer
    from kubernetes_tpu.client import Clientset, SharedInformerFactory
    from kubernetes_tpu.scheduler.scheduler import Scheduler

    api = APIServer()
    cs = Clientset(api)
    for i in range(n_nodes):
        cs.nodes.create(make_node(
            f"node-{i}",
            labels={
                v1.LABEL_HOSTNAME: f"node-{i}",
                v1.LABEL_ZONE: f"zone-{i % 3}",
            },
        ))
    factory = SharedInformerFactory(cs)
    sched = Scheduler(cs, factory, backend="tpu")
    factory.start()
    assert factory.wait_for_cache_sync()
    return api, cs, factory, sched


class TestLiveLoop:
    def test_pv_node_affinity_steers_placement(self):
        api, cs, factory, sched = _live_cluster()
        try:
            for i in range(3):
                cs.resource("persistentvolumes").create(
                    mk_pv(f"pv{i}", node=f"node-{2 * i}", phase="Bound")
                )
                cs.resource("persistentvolumeclaims").create(
                    mk_pvc(f"c{i}", volume_name=f"pv{i}")
                )
            sched.start()
            for i in range(3):
                cs.pods.create(pod_with_pvc(f"p{i}", f"c{i}"))
            assert wait_until(
                lambda: all(
                    cs.pods.get(f"p{i}", "default").spec.node_name
                    for i in range(3)
                ),
                timeout=60,
            )
            for i in range(3):
                assert cs.pods.get(f"p{i}", "default").spec.node_name \
                    == f"node-{2 * i}", i
            # a fresh bound claim rides the kernel (no oracle diversion);
            # a claim already in use by a bound pod correctly does NOT
            cs.resource("persistentvolumes").create(
                mk_pv("pv9", node="node-1", phase="Bound")
            )
            cs.resource("persistentvolumeclaims").create(
                mk_pvc("c9", volume_name="pv9")
            )
            assert wait_until(
                lambda: not sched._needs_oracle(pod_with_pvc("probe", "c9"))
            )
            assert sched._needs_oracle(pod_with_pvc("probe2", "c0"))
        finally:
            sched.stop()
            factory.stop()

    def test_zone_labelled_pv_constrains_to_zone(self):
        api, cs, factory, sched = _live_cluster()
        try:
            cs.resource("persistentvolumes").create(
                mk_pv("pvz", labels={v1.LABEL_ZONE: "zone-1"}, phase="Bound")
            )
            cs.resource("persistentvolumeclaims").create(
                mk_pvc("cz", volume_name="pvz")
            )
            sched.start()
            cs.pods.create(pod_with_pvc("pz", "cz"))
            assert wait_until(
                lambda: cs.pods.get("pz", "default").spec.node_name,
                timeout=60,
            )
            node = cs.pods.get("pz", "default").spec.node_name
            got = cs.nodes.get(node)
            assert got.metadata.labels[v1.LABEL_ZONE] == "zone-1"
        finally:
            sched.stop()
            factory.stop()

    def test_csi_attach_limits_enforced(self):
        """2 nodes x limit 1: three 1-volume pods -> exactly two bind;
        the third parks unschedulable (csi.go CSILimits)."""
        from kubernetes_tpu.api.storage import (
            CSINode,
            CSINodeDriver,
            CSINodeSpec,
        )

        api, cs, factory, sched = _live_cluster(n_nodes=2)
        try:
            for i in range(2):
                cs.resource("csinodes").create(CSINode(
                    metadata=v1.ObjectMeta(name=f"node-{i}"),
                    spec=CSINodeSpec(drivers=[
                        CSINodeDriver(name="x.csi.example", count=1)
                    ]),
                ))
            for i in range(3):
                pv = mk_pv(f"pv{i}", phase="Bound")
                pv.spec.csi = {"driver": "x.csi.example",
                               "volumeHandle": f"h{i}"}
                cs.resource("persistentvolumes").create(pv)
                cs.resource("persistentvolumeclaims").create(
                    mk_pvc(f"c{i}", volume_name=f"pv{i}")
                )
            sched.start()
            for i in range(3):
                cs.pods.create(pod_with_pvc(f"p{i}", f"c{i}"))

            def bound():
                pods, _ = cs.pods.list(namespace="default")
                return sum(1 for p in pods if p.spec.node_name)

            assert wait_until(lambda: bound() == 2, timeout=60)
            import time

            time.sleep(2.0)  # the third must STAY unschedulable
            assert bound() == 2
            nodes_used = {
                p.spec.node_name
                for p, in [(p,) for p in cs.pods.list(namespace="default")[0]]
                if p.spec.node_name
            }
            assert nodes_used == {"node-0", "node-1"}
        finally:
            sched.stop()
            factory.stop()


class TestOracleParity:
    def test_fuzz_kernel_vs_oracle_decision(self):
        """Randomized clusters with per-node PVs: the kernel's feasible
        set must equal the oracle filter chain's on every trial."""
        from kubernetes_tpu.scheduler.framework.interface import CycleState
        from kubernetes_tpu.scheduler.framework.runtime import Framework
        from kubernetes_tpu.scheduler.framework.snapshot import Snapshot
        from kubernetes_tpu.scheduler.plugins.registry import (
            default_plugins,
            new_in_tree_registry,
        )
        from kubernetes_tpu.scheduler.tpu_backend import TPUBackend
        from kubernetes_tpu.scheduler.framework.interface import FitError

        rng = random.Random(7)
        for trial in range(10):
            n = rng.randint(2, 6)
            nodes = [
                make_node(
                    f"n{i}",
                    labels={
                        v1.LABEL_HOSTNAME: f"n{i}",
                        v1.LABEL_ZONE: f"z{i % 2}",
                    },
                )
                for i in range(n)
            ]
            # one PV, randomly zone-labelled or host-pinned
            if rng.random() < 0.5:
                pv = mk_pv("pv0", labels={v1.LABEL_ZONE: f"z{rng.randint(0, 1)}"},
                           phase="Bound")
            else:
                pv = mk_pv("pv0", node=f"n{rng.randrange(n)}", phase="Bound")
            pvc = mk_pvc("c0", volume_name="pv0")
            pod = pod_with_pvc("pend", "c0")
            resolver = mk_resolver(pvcs=[pvc], pvs=[pv])

            # oracle: full filter chain over the snapshot
            from kubernetes_tpu.volume.binder import SchedulerVolumeBinder

            snapshot = Snapshot.from_objects([], nodes)
            fwk = Framework(
                new_in_tree_registry(), plugins=default_plugins(),
                snapshot_fn=lambda: snapshot,
                handle_extras={
                    "volume_binder": SchedulerVolumeBinder(
                        lambda: [pvc], lambda: [pv], lambda: []
                    ),
                    "volume_listers": (lambda: [pvc], lambda: [pv]),
                    "csi_node_lister": lambda: [],
                },
            )
            state = CycleState()
            assert fwk.run_pre_filter_plugins(state, pod) is None
            oracle_ok = {
                ni.node.metadata.name
                for ni in snapshot.list()
                if not fwk.run_filter_plugins(state, pod, ni)
            }

            # kernel: backend with the resolver, same cluster
            backend = TPUBackend()
            backend.set_volume_resolver(resolver)
            for node in nodes:
                backend.on_add_node(node)
            try:
                r = backend.schedule(pod)
                assert r.suggested_host in oracle_ok, trial
                assert len(oracle_ok) >= 1, trial
                assert r.feasible_nodes == len(oracle_ok), trial
            except FitError:
                assert not oracle_ok, trial


class TestUniqueHandleAccounting:
    def test_shared_handle_counts_once_per_node(self):
        """attach_delta refcounts handles per node (NodeVolumeLimits
        unions idents): the second sharer contributes 0, and removal
        only frees the slot when the LAST sharer leaves."""
        pvc = mk_pvc("c1", volume_name="pv1")
        pv = mk_pv("pv1", phase="Bound")
        pv.spec.csi = {"driver": "x.csi", "volumeHandle": "h1"}
        r = mk_resolver(pvcs=[pvc], pvs=[pv])
        name = attach_resource_name("x.csi")
        a, b = pod_with_pvc("a", "c1"), pod_with_pvc("b", "c1")
        assert r.attach_delta(a, "n0", +1) == {name: 1}
        assert r.attach_delta(b, "n0", +1) == {}  # shared: no new attach
        assert r.attach_delta(a, "n0", -1) == {}  # b still holds it
        assert r.attach_delta(b, "n0", -1) == {name: 1}  # last one frees

    def test_distinct_nodes_count_independently(self):
        pvc = mk_pvc("c1", volume_name="pv1")
        pv = mk_pv("pv1", phase="Bound")
        pv.spec.csi = {"driver": "x.csi", "volumeHandle": "h1"}
        r = mk_resolver(pvcs=[pvc], pvs=[pv])
        name = attach_resource_name("x.csi")
        assert r.attach_delta(pod_with_pvc("a", "c1"), "n0", +1) == {name: 1}
        assert r.attach_delta(pod_with_pvc("b", "c1"), "n1", +1) == {name: 1}

    def test_batch_sharers_split_kernel_oracle(self):
        """Two pods sharing a claim arriving in ONE batch: the first
        rides the kernel, the second is diverted to the oracle (both
        still bind)."""
        import time

        api, cs, factory, sched = _live_cluster(n_nodes=2)
        try:
            cs.resource("persistentvolumes").create(
                mk_pv("pvs", phase="Bound", access=("ReadWriteMany",))
            )
            cs.resource("persistentvolumeclaims").create(
                mk_pvc("cs1", volume_name="pvs", access=("ReadWriteMany",))
            )
            sched.start()
            cs.pods.create(pod_with_pvc("sh-a", "cs1"))
            cs.pods.create(pod_with_pvc("sh-b", "cs1"))
            assert wait_until(
                lambda: all(
                    cs.pods.get(n, "default").spec.node_name
                    for n in ("sh-a", "sh-b")
                ),
                timeout=60,
            )
        finally:
            sched.stop()
            factory.stop()
