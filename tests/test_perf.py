"""scheduler_perf harness sanity (the reference's perf tier shrunk to CI
size: test/integration/scheduler_perf/scheduler_test.go density test)."""

from __future__ import annotations

import pytest

from kubernetes_tpu.perf import Workload, run_workload
from kubernetes_tpu.perf.harness import PodTemplate


@pytest.mark.parametrize("backend", ["tpu"])
def test_density_small(backend):
    w = Workload(
        "density-ci", num_nodes=20, num_pods=60, backend=backend, timeout=120
    )
    r = run_workload(w)
    assert r.throughput_avg > 0
    assert r.num_pods == 60
    d = r.to_dict()
    assert {"name", "backend", "throughput_avg", "throughput_p50"} <= set(d)


def test_spread_template_shapes():
    t = PodTemplate(spread_zone=True, spread_hostname_hard=True)
    pod = t.build("x")
    assert len(pod.spec.topology_spread_constraints) == 2
    t2 = PodTemplate(anti_affinity_zone=True)
    pod2 = t2.build("y")
    assert pod2.spec.affinity.pod_anti_affinity is not None


@pytest.mark.parametrize("backend", ["tpu", "oracle"])
def test_gang_workload_small(backend):
    """North-star gang stress shrunk to CI size: 4-pod gangs over GPU nodes;
    every gang must bind atomically via the Coscheduling Permit gate."""
    w = Workload(
        "gang-ci",
        num_nodes=8,
        num_pods=16,
        gang_size=4,
        backend=backend,
        timeout=120,
        gang_permit_timeout=30,
        template=PodTemplate(extended={"example.com/gpu": "1"}),
        node_extended={"example.com/gpu": "4"},
    )
    r = run_workload(w)
    assert r.throughput_avg > 0
    assert r.num_bound == 16  # every gang bound, none parked at Permit


def test_migrated_pvs_small():
    """SchedulingMigratedInTreePVs at CI size: in-tree EBS PVs translate
    to CSI and every pod binds through the harness."""
    w = Workload(
        "migrated-ci", num_nodes=8, num_pods=16,
        template=PodTemplate(with_pvc="migrated"), timeout=180,
    )
    r = run_workload(w)
    assert r.num_bound == 16


def test_preemption_pdb_small():
    """Preemption with PDB-covered victims at CI size: the planner's
    PDB partitioning rides the live loop."""
    w = Workload(
        "preempt-pdb-ci", num_nodes=4, num_init_pods=16, num_pods=4,
        init_template=PodTemplate(cpu="900m", memory="64Mi", priority=1,
                                  labels={"app": "victim"}),
        template=PodTemplate(cpu="900m", memory="64Mi", priority=100),
        timeout=180, stall_stop=30.0, pdb_disruptions_allowed=16,
    )
    r = run_workload(w)
    assert r.num_bound == 4
