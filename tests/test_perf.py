"""scheduler_perf harness sanity (the reference's perf tier shrunk to CI
size: test/integration/scheduler_perf/scheduler_test.go density test)."""

from __future__ import annotations

import pytest

from kubernetes_tpu.perf import Workload, run_workload
from kubernetes_tpu.perf.harness import PodTemplate


@pytest.mark.parametrize("backend", ["tpu"])
def test_density_small(backend):
    w = Workload(
        "density-ci", num_nodes=20, num_pods=60, backend=backend, timeout=120
    )
    r = run_workload(w)
    assert r.throughput_avg > 0
    assert r.num_pods == 60
    d = r.to_dict()
    assert {"name", "backend", "throughput_avg", "throughput_p50"} <= set(d)


def test_spread_template_shapes():
    t = PodTemplate(spread_zone=True, spread_hostname_hard=True)
    pod = t.build("x")
    assert len(pod.spec.topology_spread_constraints) == 2
    t2 = PodTemplate(anti_affinity_zone=True)
    pod2 = t2.build("y")
    assert pod2.spec.affinity.pod_anti_affinity is not None
