"""networking.k8s.io group: types + REST, NetworkPolicy evaluation, and
the round-4 admission long tail (PVC resize, node taint, RuntimeClass,
certificate gates, DefaultIngressClass).

Reference: staging/src/k8s.io/api/networking/v1/types.go;
plugin/pkg/admission/{storage/persistentvolume/resize,nodetaint,
runtimeclass,certificates,network/defaultingressclass}.
"""

from __future__ import annotations

import json

import pytest

from kubernetes_tpu.api import networking, types as v1
from kubernetes_tpu.api.storage import (
    RuntimeClass,
    RuntimeClassOverhead,
    RuntimeClassScheduling,
    StorageClass,
)
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.apiserver.admission import install_default_admission
from kubernetes_tpu.apiserver.server import Invalid
from kubernetes_tpu.proxy.netpol import Endpoint, NetworkPolicyEvaluator

from .util import make_pod


def _api() -> APIServer:
    return install_default_admission(APIServer())


class TestNetworkingREST:
    def test_crud_roundtrip(self):
        api = _api()
        api.create("networkpolicies", networking.NetworkPolicy(
            metadata=v1.ObjectMeta(name="deny", namespace="default"),
            spec=networking.NetworkPolicySpec(
                pod_selector=v1.LabelSelector(match_labels={"app": "db"}),
            ),
        ))
        got = api.get("networkpolicies", "deny", "default")
        assert got.spec.pod_selector.match_labels == {"app": "db"}
        api.create("ingressclasses", networking.IngressClass(
            metadata=v1.ObjectMeta(name="nginx"),
            spec=networking.IngressClassSpec(controller="example.com/nginx"),
        ))
        api.create("ingresses", networking.Ingress(
            metadata=v1.ObjectMeta(name="web", namespace="default"),
            spec=networking.IngressSpec(
                ingress_class_name="nginx",
                rules=[networking.IngressRule(
                    host="x.example",
                    http=networking.HTTPIngressRuleValue(paths=[
                        networking.HTTPIngressPath(
                            path="/", backend=networking.IngressBackend(
                                service=networking.IngressServiceBackend(
                                    name="web",
                                    port=networking.ServiceBackendPort(
                                        number=80),
                                )
                            )
                        )
                    ]),
                )],
            ),
        ))
        ing = api.get("ingresses", "web", "default")
        assert ing.spec.rules[0].http.paths[0].backend.service.port.number == 80

    def test_except_serde_roundtrip(self):
        from kubernetes_tpu.utils import serde

        blk = networking.IPBlock(cidr="10.0.0.0/8",
                                 except_=["10.1.0.0/16"])
        d = serde.to_dict(blk)
        assert d["except"] == ["10.1.0.0/16"]
        back = serde.from_dict(networking.IPBlock, d)
        assert back.except_ == ["10.1.0.0/16"]


def _pol(name, ns, pod_sel, ingress=None, egress=None, types=None):
    return networking.NetworkPolicy(
        metadata=v1.ObjectMeta(name=name, namespace=ns),
        spec=networking.NetworkPolicySpec(
            pod_selector=v1.LabelSelector(match_labels=pod_sel),
            ingress=ingress, egress=egress, policy_types=types,
        ),
    )


class TestNetworkPolicyEvaluator:
    def _eps(self):
        web = Endpoint("default", {"app": "web"}, "10.0.0.1")
        db = Endpoint("default", {"app": "db"}, "10.0.0.2")
        other = Endpoint("other", {"app": "web"}, "10.0.1.1")
        return web, db, other

    def test_default_allow_when_unselected(self):
        web, db, _ = self._eps()
        ev = NetworkPolicyEvaluator([])
        assert ev.allowed(web, db, 5432)

    def test_selected_denies_unmatched(self):
        web, db, other = self._eps()
        pol = _pol("db-in", "default", {"app": "db"}, ingress=[
            networking.NetworkPolicyIngressRule(from_=[
                networking.NetworkPolicyPeer(
                    pod_selector=v1.LabelSelector(match_labels={"app": "web"})
                )
            ]),
        ])
        ev = NetworkPolicyEvaluator([pol])
        assert ev.allowed(web, db, 5432)  # same-ns web matches
        stranger = Endpoint("default", {"app": "job"}, "10.0.0.9")
        assert not ev.allowed(stranger, db, 5432)
        # peer without namespaceSelector never crosses namespaces
        assert not ev.allowed(other, db, 5432)

    def test_port_ranges(self):
        web, db, _ = self._eps()
        pol = _pol("db-in", "default", {"app": "db"}, ingress=[
            networking.NetworkPolicyIngressRule(
                from_=[networking.NetworkPolicyPeer(
                    pod_selector=v1.LabelSelector(match_labels={"app": "web"})
                )],
                ports=[networking.NetworkPolicyPort(
                    protocol="TCP", port=5000, end_port=5999)],
            ),
        ])
        ev = NetworkPolicyEvaluator([pol])
        assert ev.allowed(web, db, 5432)
        assert not ev.allowed(web, db, 6000)
        assert not ev.allowed(web, db, 5432, protocol="UDP")

    def test_namespace_selector_and_ipblock(self):
        web, db, other = self._eps()
        pol = _pol("db-in", "default", {"app": "db"}, ingress=[
            networking.NetworkPolicyIngressRule(from_=[
                networking.NetworkPolicyPeer(
                    namespace_selector=v1.LabelSelector(
                        match_labels={"team": "a"})
                ),
                networking.NetworkPolicyPeer(ip_block=networking.IPBlock(
                    cidr="192.168.0.0/16", except_=["192.168.9.0/24"],
                )),
            ]),
        ])
        ev = NetworkPolicyEvaluator([pol], namespaces={"other": {"team": "a"}})
        assert ev.allowed(other, db, 80)  # namespace labels match
        assert not ev.allowed(web, db, 80)  # own ns has no team=a label
        assert ev.allowed(Endpoint.external("192.168.1.5"), db, 80)
        assert not ev.allowed(Endpoint.external("192.168.9.5"), db, 80)

    def test_egress_direction(self):
        web, db, _ = self._eps()
        pol = _pol("web-out", "default", {"app": "web"}, egress=[
            networking.NetworkPolicyEgressRule(to=[
                networking.NetworkPolicyPeer(
                    pod_selector=v1.LabelSelector(match_labels={"app": "db"})
                )
            ]),
        ])
        ev = NetworkPolicyEvaluator([pol])
        assert ev.allowed(web, db, 5432)
        stranger = Endpoint("default", {"app": "cache"}, "10.0.0.8")
        assert not ev.allowed(web, stranger, 6379)
        # ingress to web is unconstrained (policy only types Egress via
        # defaulting? no — defaulting adds Ingress ONLY when unset...)
        # explicit: policy_types defaulted to [Ingress, Egress] because
        # egress rules exist; web has no ingress RULES -> ingress denied
        assert not ev.allowed(db, web, 80)

    def test_empty_peers_allow_all_on_port(self):
        web, db, _ = self._eps()
        pol = _pol("db-in", "default", {"app": "db"}, ingress=[
            networking.NetworkPolicyIngressRule(
                ports=[networking.NetworkPolicyPort(protocol="TCP", port=5432)]
            ),
        ])
        ev = NetworkPolicyEvaluator([pol])
        assert ev.allowed(Endpoint.external("8.8.8.8"), db, 5432)
        assert not ev.allowed(Endpoint.external("8.8.8.8"), db, 80)


class TestResizeAdmission:
    def _api_with_pvc(self, expand: bool):
        api = _api()
        api.create("storageclasses", StorageClass(
            metadata=v1.ObjectMeta(name="fast"),
            allow_volume_expansion=expand,
        ))
        api.create("persistentvolumeclaims", v1.PersistentVolumeClaim(
            metadata=v1.ObjectMeta(name="c", namespace="default"),
            spec=v1.PersistentVolumeClaimSpec(
                storage_class_name="fast",
                resources=v1.ResourceRequirements(
                    requests={"storage": "5Gi"}),
            ),
        ))
        return api

    def test_growth_requires_expandable_class(self):
        api = self._api_with_pvc(expand=False)
        pvc = api.get("persistentvolumeclaims", "c", "default")
        pvc.spec.resources.requests["storage"] = "10Gi"
        with pytest.raises(Invalid):
            api.update("persistentvolumeclaims", pvc)

    def test_growth_allowed_when_class_expands(self):
        api = self._api_with_pvc(expand=True)
        pvc = api.get("persistentvolumeclaims", "c", "default")
        pvc.spec.resources.requests["storage"] = "10Gi"
        api.update("persistentvolumeclaims", pvc)

    def test_shrink_rejected(self):
        api = self._api_with_pvc(expand=True)
        pvc = api.get("persistentvolumeclaims", "c", "default")
        pvc.spec.resources.requests["storage"] = "1Gi"
        with pytest.raises(Invalid):
            api.update("persistentvolumeclaims", pvc)


class TestNodeTaintAdmission:
    def test_new_node_gets_not_ready_taint(self):
        from kubernetes_tpu.testing.synth import make_node

        api = _api()
        api.create("nodes", make_node("n0"))
        got = api.get("nodes", "n0")
        assert any(
            t.key == "node.kubernetes.io/not-ready" and t.effect == "NoSchedule"
            for t in got.spec.taints or []
        )


class TestRuntimeClassAdmission:
    def test_overhead_and_scheduling_merge(self):
        api = _api()
        api.create("runtimeclasses", RuntimeClass(
            metadata=v1.ObjectMeta(name="gvisor"),
            handler="runsc",
            overhead=RuntimeClassOverhead(
                pod_fixed={"cpu": "250m", "memory": "64Mi"}),
            scheduling=RuntimeClassScheduling(
                node_selector={"sandbox": "gvisor"}),
        ))
        pod = make_pod("p")
        pod.spec.runtime_class_name = "gvisor"
        api.create("pods", pod)
        got = api.get("pods", "p", "default")
        assert got.spec.overhead == {"cpu": "250m", "memory": "64Mi"}
        assert got.spec.node_selector == {"sandbox": "gvisor"}

    def test_missing_class_rejected(self):
        api = _api()
        pod = make_pod("p")
        pod.spec.runtime_class_name = "ghost"
        with pytest.raises(Invalid):
            api.create("pods", pod)

    def test_conflicting_overhead_rejected(self):
        api = _api()
        api.create("runtimeclasses", RuntimeClass(
            metadata=v1.ObjectMeta(name="kata"),
            overhead=RuntimeClassOverhead(pod_fixed={"cpu": "1"}),
        ))
        pod = make_pod("p")
        pod.spec.runtime_class_name = "kata"
        pod.spec.overhead = {"cpu": "2"}
        with pytest.raises(Invalid):
            api.create("pods", pod)


class TestCertificateAdmission:
    def test_subject_restriction_blocks_masters(self):
        from kubernetes_tpu.api.certificates import CertificateSigningRequest

        api = _api()
        csr = CertificateSigningRequest(
            metadata=v1.ObjectMeta(name="bad"),
        )
        csr.spec.signer_name = "kubernetes.io/kube-apiserver-client"
        csr.spec.request = json.dumps(
            {"commonName": "eve", "groups": ["system:masters"]}
        )
        with pytest.raises(Invalid):
            api.create("certificatesigningrequests", csr)

    def test_other_signer_unrestricted(self):
        from kubernetes_tpu.api.certificates import CertificateSigningRequest

        api = _api()
        csr = CertificateSigningRequest(metadata=v1.ObjectMeta(name="ok"))
        csr.spec.signer_name = "kubernetes.io/kubelet-serving"
        csr.spec.request = json.dumps(
            {"commonName": "n", "groups": ["system:masters"]}
        )
        api.create("certificatesigningrequests", csr)


class TestDefaultIngressClass:
    def test_default_applied(self):
        api = _api()
        api.create("ingressclasses", networking.IngressClass(
            metadata=v1.ObjectMeta(
                name="nginx",
                annotations={
                    networking.DEFAULT_INGRESS_CLASS_ANNOTATION: "true"},
            ),
            spec=networking.IngressClassSpec(controller="x"),
        ))
        api.create("ingresses", networking.Ingress(
            metadata=v1.ObjectMeta(name="web", namespace="default"),
        ))
        assert api.get("ingresses", "web", "default") \
            .spec.ingress_class_name == "nginx"

    def test_two_defaults_rejected(self):
        api = _api()
        for n in ("a", "b"):
            api.create("ingressclasses", networking.IngressClass(
                metadata=v1.ObjectMeta(
                    name=n,
                    annotations={
                        networking.DEFAULT_INGRESS_CLASS_ANNOTATION: "true"},
                ),
            ))
        with pytest.raises(Invalid):
            api.create("ingresses", networking.Ingress(
                metadata=v1.ObjectMeta(name="web", namespace="default"),
            ))

    def test_explicit_class_untouched(self):
        api = _api()
        api.create("ingresses", networking.Ingress(
            metadata=v1.ObjectMeta(name="web", namespace="default"),
            spec=networking.IngressSpec(ingress_class_name="custom"),
        ))
        assert api.get("ingresses", "web", "default") \
            .spec.ingress_class_name == "custom"


class TestAdviceR4Fixes:
    """Round-4 advisor findings: fail-closed CSR parse, semantic overhead
    quantities, named policy ports."""

    def test_unparseable_csr_request_fails_closed(self):
        from kubernetes_tpu.api.certificates import CertificateSigningRequest

        api = _api()
        csr = CertificateSigningRequest(metadata=v1.ObjectMeta(name="junk"))
        csr.spec.signer_name = "kubernetes.io/kube-apiserver-client"
        csr.spec.request = "{not json"
        with pytest.raises(Invalid):
            api.create("certificatesigningrequests", csr)

    def test_overhead_semantic_quantity_equality(self):
        api = _api()
        api.create("runtimeclasses", RuntimeClass(
            metadata=v1.ObjectMeta(name="kata"),
            overhead=RuntimeClassOverhead(pod_fixed={"cpu": "1000m"}),
        ))
        pod = make_pod("p")
        pod.spec.runtime_class_name = "kata"
        pod.spec.overhead = {"cpu": "1"}  # == 1000m semantically
        api.create("pods", pod)  # must NOT be rejected as a conflict
        assert api.get("pods", "p", "default").spec.overhead == {
            "cpu": "1000m"}

    def test_named_policy_port(self):
        db = Endpoint("default", {"app": "db"}, "10.0.0.2",
                      named_ports={"postgres": 5432})
        web = Endpoint("default", {"app": "web"}, "10.0.0.1")
        pol = _pol("db-in", "default", {"app": "db"}, ingress=[
            networking.NetworkPolicyIngressRule(
                from_=[networking.NetworkPolicyPeer(
                    pod_selector=v1.LabelSelector(match_labels={"app": "web"})
                )],
                ports=[networking.NetworkPolicyPort(
                    protocol="TCP", port="postgres")],
            ),
        ])
        ev = NetworkPolicyEvaluator([pol])
        assert ev.allowed(web, db, 5432)
        assert not ev.allowed(web, db, 80)
        # a destination without the named port matches nothing
        anon = Endpoint("default", {"app": "db"}, "10.0.0.3")
        assert not ev.allowed(web, anon, 5432)

    def test_named_policy_port_matches_per_protocol(self):
        """Named ports resolve per (name, protocol): a UDP "web"
        container port must not satisfy a TCP policy port (and vice
        versa) — the lookup matches both fields (types.go)."""
        udp_db = Endpoint("default", {"app": "db"}, "10.0.0.2",
                          named_ports={"web": (5432, "UDP")})
        web = Endpoint("default", {"app": "web"}, "10.0.0.1")
        pol = _pol("db-in", "default", {"app": "db"}, ingress=[
            networking.NetworkPolicyIngressRule(
                from_=[networking.NetworkPolicyPeer(
                    pod_selector=v1.LabelSelector(match_labels={"app": "web"})
                )],
                ports=[networking.NetworkPolicyPort(
                    protocol="TCP", port="web")],
            ),
        ])
        ev = NetworkPolicyEvaluator([pol])
        # the policy's TCP "web" resolves to nothing on a pod whose
        # "web" port is UDP: no rule matches, default-deny for selected
        assert not ev.allowed(web, udp_db, 5432)
        assert not ev.allowed(web, udp_db, 5432, protocol="UDP")
        # the same shape with a matching protocol passes
        tcp_db = Endpoint("default", {"app": "db"}, "10.0.0.4",
                          named_ports={"web": (5432, "TCP")})
        assert ev.allowed(web, tcp_db, 5432)
        # from_pod carries the container port's declared protocol
        pod = make_pod("udp-pod")
        pod.spec.containers[0].ports = [v1.ContainerPort(
            name="web", container_port=5432, protocol="UDP")]
        pod.metadata.labels = {"app": "db"}
        pod.status.pod_ip = "10.0.0.5"
        assert not ev.allowed(web, Endpoint.from_pod(pod), 5432)

    def test_named_port_from_pod_and_serde_roundtrip(self):
        from kubernetes_tpu.utils import serde

        pod = make_pod("p")
        pod.spec.containers[0].ports = [
            v1.ContainerPort(name="metrics", container_port=9090)]
        pod.status.pod_ip = "10.0.0.7"
        ep = Endpoint.from_pod(pod)
        assert ep.named_ports == {"metrics": (9090, "TCP")}
        npp = networking.NetworkPolicyPort(port="metrics")
        back = serde.from_dict(
            networking.NetworkPolicyPort, serde.to_dict(npp))
        assert back.port == "metrics"
        npp2 = networking.NetworkPolicyPort(port=443)
        assert serde.from_dict(
            networking.NetworkPolicyPort, serde.to_dict(npp2)).port == 443

    def test_non_dict_csr_request_fails_closed(self):
        from kubernetes_tpu.api.certificates import CertificateSigningRequest

        api = _api()
        for payload in ('["system:masters"]', 'null', '"x"'):
            csr = CertificateSigningRequest(
                metadata=v1.ObjectMeta(name=f"j{hash(payload) % 100}"))
            csr.spec.signer_name = "kubernetes.io/kube-apiserver-client"
            csr.spec.request = payload
            with pytest.raises(Invalid):
                api.create("certificatesigningrequests", csr)

    def test_unparseable_overhead_value_rejected_not_crashed(self):
        api = _api()
        api.create("runtimeclasses", RuntimeClass(
            metadata=v1.ObjectMeta(name="kata2"),
            overhead=RuntimeClassOverhead(pod_fixed={"cpu": "100m"}),
        ))
        pod = make_pod("p2")
        pod.spec.runtime_class_name = "kata2"
        pod.spec.overhead = {"cpu": None}
        with pytest.raises(Invalid):
            api.create("pods", pod)
