"""Oracle plugin unit tests.

Values cross-checked against the reference plugin table tests
(pkg/scheduler/framework/plugins/*/..._test.go).
"""

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.scheduler.core import GenericScheduler
from kubernetes_tpu.scheduler.framework.interface import Code, CycleState, NodeScore
from kubernetes_tpu.scheduler.framework.runtime import Framework
from kubernetes_tpu.scheduler.framework.snapshot import Snapshot
from kubernetes_tpu.scheduler.plugins.registry import (
    default_plugins,
    default_plugins_without,
    new_in_tree_registry,
)

from .util import (
    anti_affinity,
    make_node,
    make_pod,
    pod_affinity,
    spread_constraint,
)


def build_framework(snapshot, plugins=None, plugin_config=None):
    return Framework(
        new_in_tree_registry(),
        plugins=plugins or default_plugins(),
        plugin_config=plugin_config,
        snapshot_fn=lambda: snapshot,
    )


def run_filter(snapshot, pod, node_name, plugins=None):
    fwk = build_framework(snapshot, plugins)
    state = CycleState()
    status = fwk.run_pre_filter_plugins(state, pod)
    assert status is None, status
    return fwk.run_filter_plugins(state, pod, snapshot.get(node_name))


def run_scores(snapshot, pod, plugins=None, plugin_config=None):
    """Returns {plugin: {node: weighted score}} over all nodes."""
    fwk = build_framework(snapshot, plugins, plugin_config)
    state = CycleState()
    status = fwk.run_pre_filter_plugins(state, pod)
    assert status is None, status
    nodes = [ni.node for ni in snapshot.list()]
    assert fwk.run_pre_score_plugins(state, pod, nodes) is None
    scores_map, status = fwk.run_score_plugins(state, pod, nodes)
    assert status is None, status
    return {
        plugin: {ns.name: ns.score for ns in scores}
        for plugin, scores in scores_map.items()
    }


class TestNodeResourcesFit:
    def test_insufficient_cpu(self):
        node = make_node("n1", cpu="2")
        existing = make_pod(cpu="1500m", node_name="n1")
        snap = Snapshot.from_objects([existing], [node])
        statuses = run_filter(snap, make_pod(cpu="1"), "n1")
        assert statuses["NodeResourcesFit"].code == Code.UNSCHEDULABLE
        assert "Insufficient cpu" in statuses["NodeResourcesFit"].reasons

    def test_fits_exactly(self):
        node = make_node("n1", cpu="2")
        existing = make_pod(cpu="1", node_name="n1")
        snap = Snapshot.from_objects([existing], [node])
        assert run_filter(snap, make_pod(cpu="1"), "n1") == {}

    def test_too_many_pods(self):
        node = make_node("n1", pods=1)
        existing = make_pod(node_name="n1")
        snap = Snapshot.from_objects([existing], [node])
        statuses = run_filter(snap, make_pod(), "n1")
        assert "Too many pods" in statuses["NodeResourcesFit"].reasons

    def test_extended_resource(self):
        node = make_node("n1", extended={"nvidia.com/gpu": "2"})
        existing = make_pod(node_name="n1", extended={"nvidia.com/gpu": "1"})
        snap = Snapshot.from_objects([existing], [node])
        assert run_filter(snap, make_pod(extended={"nvidia.com/gpu": "1"}), "n1") == {}
        statuses = run_filter(snap, make_pod(extended={"nvidia.com/gpu": "2"}), "n1")
        assert "Insufficient nvidia.com/gpu" in statuses["NodeResourcesFit"].reasons

    def test_init_container_max(self):
        node = make_node("n1", cpu="2")
        pod = make_pod(cpu="1")
        pod.spec.init_containers = [
            v1.Container(name="init", resources=v1.ResourceRequirements(requests={"cpu": "1800m"}))
        ]
        snap = Snapshot.from_objects([make_pod(cpu="500m", node_name="n1")], [node])
        # request = max(1000, 1800) = 1800m > 2000-500
        statuses = run_filter(snap, pod, "n1")
        assert "Insufficient cpu" in statuses["NodeResourcesFit"].reasons


class TestResourceScorers:
    def test_least_allocated(self):
        # reference least_allocated_test.go "nothing scheduled, resources requested"
        node = make_node("n1", cpu="4", memory="10Gi")
        snap = Snapshot.from_objects([], [node])
        pod = make_pod(cpu="1", memory="2560Mi")
        scores = run_scores(snap, pod)
        # cpu: (4000-1000)*100/4000 = 75 ; mem: (10240-2560)*100/10240 = 75
        assert scores["NodeResourcesLeastAllocated"]["n1"] == 75

    def test_balanced_allocation_perfect(self):
        node = make_node("n1", cpu="4", memory="8Gi")
        snap = Snapshot.from_objects([], [node])
        pod = make_pod(cpu="2", memory="4Gi")
        scores = run_scores(snap, pod)
        # cpuFrac == memFrac -> 100
        assert scores["NodeResourcesBalancedAllocation"]["n1"] == 100

    def test_balanced_allocation_skewed(self):
        node = make_node("n1", cpu="4", memory="8Gi")
        snap = Snapshot.from_objects([], [node])
        pod = make_pod(cpu="4", memory="2Gi")  # frac 1.0 vs 0.25
        scores = run_scores(snap, pod)
        assert scores["NodeResourcesBalancedAllocation"]["n1"] == 0  # cpuFrac >= 1

    def test_nonzero_default_requests(self):
        # pod with no requests uses 100m/200MB defaults in scoring
        node = make_node("n1", cpu="1", memory="400Mi")
        snap = Snapshot.from_objects([], [node])
        scores = run_scores(snap, make_pod())
        # cpu: (1000-100)*100/1000 = 90; mem: (419430400-209715200)*100/419430400 = 50
        assert scores["NodeResourcesLeastAllocated"]["n1"] == (90 + 50) // 2


class TestTaintToleration:
    def test_filter_untolerated(self):
        node = make_node("n1", taints=[v1.Taint(key="k", value="v", effect="NoSchedule")])
        snap = Snapshot.from_objects([], [node])
        statuses = run_filter(snap, make_pod(), "n1")
        assert statuses["TaintToleration"].code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_filter_tolerated(self):
        node = make_node("n1", taints=[v1.Taint(key="k", value="v", effect="NoSchedule")])
        snap = Snapshot.from_objects([], [node])
        pod = make_pod(tolerations=[v1.Toleration(key="k", operator="Equal", value="v", effect="NoSchedule")])
        assert run_filter(snap, pod, "n1") == {}

    def test_prefer_no_schedule_scoring(self):
        n1 = make_node("n1", taints=[v1.Taint(key="k", value="v", effect="PreferNoSchedule")])
        n2 = make_node("n2")
        snap = Snapshot.from_objects([], [n1, n2])
        scores = run_scores(snap, make_pod())
        # n1 has 1 intolerable PreferNoSchedule taint -> normalized to 0; n2 -> 100
        assert scores["TaintToleration"]["n1"] == 0
        assert scores["TaintToleration"]["n2"] == 100


class TestNodeBasics:
    def test_node_name_mismatch(self):
        snap = Snapshot.from_objects([], [make_node("n1"), make_node("n2")])
        pod = make_pod()
        pod.spec.node_name = "n2"
        statuses = run_filter(snap, pod, "n1")
        assert statuses["NodeName"].code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_unschedulable_node(self):
        snap = Snapshot.from_objects([], [make_node("n1", unschedulable=True)])
        statuses = run_filter(snap, make_pod(), "n1")
        assert statuses["NodeUnschedulable"].code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_host_port_conflict(self):
        node = make_node("n1")
        existing = make_pod(node_name="n1", host_port=8080)
        snap = Snapshot.from_objects([existing], [node])
        statuses = run_filter(snap, make_pod(host_port=8080), "n1")
        assert statuses["NodePorts"].code == Code.UNSCHEDULABLE
        assert run_filter(snap, make_pod(host_port=8081), "n1") == {}

    def test_node_affinity_required(self):
        n1 = make_node("n1", labels={"zone": "z1"})
        n2 = make_node("n2", labels={"zone": "z2"})
        snap = Snapshot.from_objects([], [n1, n2])
        pod = make_pod(node_selector={"zone": "z2"})
        statuses = run_filter(snap, pod, "n1")
        assert statuses["NodeAffinity"].code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE
        assert run_filter(snap, pod, "n2") == {}

    def test_node_affinity_preferred_score(self):
        n1 = make_node("n1", labels={"tier": "gold"})
        n2 = make_node("n2")
        snap = Snapshot.from_objects([], [n1, n2])
        pod = make_pod()
        pod.spec.affinity = v1.Affinity(
            node_affinity=v1.NodeAffinity(
                preferred_during_scheduling_ignored_during_execution=[
                    v1.PreferredSchedulingTerm(
                        weight=80,
                        preference=v1.NodeSelectorTerm(
                            match_expressions=[
                                v1.NodeSelectorRequirement(key="tier", operator="In", values=["gold"])
                            ]
                        ),
                    )
                ]
            )
        )
        scores = run_scores(snap, pod)
        assert scores["NodeAffinity"]["n1"] == 100
        assert scores["NodeAffinity"]["n2"] == 0


class TestImageLocality:
    def test_image_present(self):
        img = v1.ContainerImage(names=["registry.example/app:v1"], size_bytes=500 * 1024 * 1024)
        n1 = make_node("n1", images=[img])
        n2 = make_node("n2")
        snap = Snapshot.from_objects([], [n1, n2])
        scores = run_scores(snap, make_pod(image="registry.example/app:v1"))
        # n1: sum = 500MB * (1/2 nodes) = 250MB -> (250-23)/(1000-23)*100 = 23
        assert scores["ImageLocality"]["n1"] == 23
        assert scores["ImageLocality"]["n2"] == 0

    def test_untagged_normalized(self):
        img = v1.ContainerImage(names=["repo/app:latest"], size_bytes=300 * 1024 * 1024)
        n1 = make_node("n1", images=[img])
        snap = Snapshot.from_objects([], [n1])
        scores = run_scores(snap, make_pod(image="repo/app"))
        assert scores["ImageLocality"]["n1"] > 0


class TestPodTopologySpread:
    def _cluster(self):
        nodes = [
            make_node("n1", labels={"zone": "z1", v1.LABEL_HOSTNAME: "n1"}),
            make_node("n2", labels={"zone": "z1", v1.LABEL_HOSTNAME: "n2"}),
            make_node("n3", labels={"zone": "z2", v1.LABEL_HOSTNAME: "n3"}),
        ]
        pods = [
            make_pod(labels={"app": "web"}, node_name="n1"),
            make_pod(labels={"app": "web"}, node_name="n1"),
            make_pod(labels={"app": "web"}, node_name="n2"),
        ]
        return pods, nodes

    def test_filter_max_skew(self):
        pods, nodes = self._cluster()
        snap = Snapshot.from_objects(pods, nodes)
        pod = make_pod(
            labels={"app": "web"},
            constraints=[spread_constraint(1, "zone", "DoNotSchedule", {"app": "web"})],
        )
        # zone z1 has 3 matching pods, z2 has 0 -> min=0; placing in z1: 3+1-0 > 1
        statuses = run_filter(snap, pod, "n1")
        assert statuses["PodTopologySpread"].code == Code.UNSCHEDULABLE
        assert run_filter(snap, pod, "n3") == {}

    def test_filter_missing_topology_label(self):
        pods, nodes = self._cluster()
        nodes.append(make_node("n4"))  # no zone label
        snap = Snapshot.from_objects(pods, nodes)
        pod = make_pod(
            labels={"app": "web"},
            constraints=[spread_constraint(1, "zone", "DoNotSchedule", {"app": "web"})],
        )
        statuses = run_filter(snap, pod, "n4")
        assert statuses["PodTopologySpread"].code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_score_prefers_empty_zone(self):
        pods, nodes = self._cluster()
        snap = Snapshot.from_objects(pods, nodes)
        pod = make_pod(
            labels={"app": "web"},
            constraints=[spread_constraint(1, "zone", "ScheduleAnyway", {"app": "web"})],
        )
        scores = run_scores(snap, pod)
        s = scores["PodTopologySpread"]
        assert s["n3"] > s["n1"]
        assert s["n1"] == s["n2"]


class TestInterPodAffinity:
    def test_required_anti_affinity_blocks(self):
        nodes = [
            make_node("n1", labels={v1.LABEL_HOSTNAME: "n1"}),
            make_node("n2", labels={v1.LABEL_HOSTNAME: "n2"}),
        ]
        existing = make_pod(
            labels={"app": "db"},
            node_name="n1",
            affinity=anti_affinity(v1.LABEL_HOSTNAME, {"app": "db"}),
        )
        snap = Snapshot.from_objects([existing], nodes)
        pod = make_pod(labels={"app": "db"}, affinity=anti_affinity(v1.LABEL_HOSTNAME, {"app": "db"}))
        statuses = run_filter(snap, pod, "n1")
        assert statuses["InterPodAffinity"].code == Code.UNSCHEDULABLE
        assert run_filter(snap, pod, "n2") == {}

    def test_existing_anti_affinity_blocks_incoming(self):
        nodes = [make_node("n1", labels={"zone": "z1"}), make_node("n2", labels={"zone": "z2"})]
        existing = make_pod(
            labels={"app": "db"},
            node_name="n1",
            affinity=anti_affinity("zone", {"app": "web"}),
        )
        snap = Snapshot.from_objects([existing], nodes)
        pod = make_pod(labels={"app": "web"})
        statuses = run_filter(snap, pod, "n1")
        assert statuses["InterPodAffinity"].code == Code.UNSCHEDULABLE
        assert run_filter(snap, pod, "n2") == {}

    def test_required_affinity(self):
        nodes = [make_node("n1", labels={"zone": "z1"}), make_node("n2", labels={"zone": "z2"})]
        existing = make_pod(labels={"app": "db"}, node_name="n1")
        snap = Snapshot.from_objects([existing], nodes)
        pod = make_pod(affinity=pod_affinity("zone", {"app": "db"}))
        assert run_filter(snap, pod, "n1") == {}
        statuses = run_filter(snap, pod, "n2")
        assert statuses["InterPodAffinity"].code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_self_affinity_first_pod_allowed(self):
        nodes = [make_node("n1", labels={"zone": "z1"})]
        snap = Snapshot.from_objects([], nodes)
        pod = make_pod(labels={"app": "db"}, affinity=pod_affinity("zone", {"app": "db"}))
        assert run_filter(snap, pod, "n1") == {}

    def test_preferred_affinity_score(self):
        nodes = [make_node("n1", labels={"zone": "z1"}), make_node("n2", labels={"zone": "z2"})]
        existing = make_pod(labels={"app": "cache"}, node_name="n1")
        snap = Snapshot.from_objects([existing], nodes)
        pod = make_pod()
        pod.spec.affinity = v1.Affinity(
            pod_affinity=v1.PodAffinity(
                preferred_during_scheduling_ignored_during_execution=[
                    v1.WeightedPodAffinityTerm(
                        weight=100,
                        pod_affinity_term=v1.PodAffinityTerm(
                            label_selector=v1.LabelSelector(match_labels={"app": "cache"}),
                            topology_key="zone",
                        ),
                    )
                ]
            )
        )
        scores = run_scores(snap, pod)
        assert scores["InterPodAffinity"]["n1"] == 100
        assert scores["InterPodAffinity"]["n2"] == 0


class TestGenericScheduler:
    def test_schedules_to_least_allocated(self):
        nodes = [make_node("n1"), make_node("n2")]
        existing = make_pod(cpu="3", node_name="n1")
        snap = Snapshot.from_objects([existing], nodes)
        fwk = build_framework(snap, default_plugins_without("DefaultPreemption"))
        sched = GenericScheduler(percentage_of_nodes_to_score=100)
        result = sched.schedule(CycleState(), fwk, make_pod(cpu="1"), snap)
        assert result.suggested_host == "n2"

    def test_fit_error_collects_statuses(self):
        from kubernetes_tpu.scheduler.framework.interface import FitError

        snap = Snapshot.from_objects([], [make_node("n1", cpu="1")])
        fwk = build_framework(snap, default_plugins_without("DefaultPreemption"))
        sched = GenericScheduler()
        with pytest.raises(FitError) as ei:
            sched.schedule(CycleState(), fwk, make_pod(cpu="2"), snap)
        assert "n1" in ei.value.filtered_nodes_statuses

    def test_num_feasible_nodes_adaptive(self):
        s = GenericScheduler()
        assert s.num_feasible_nodes_to_find(50) == 50
        assert s.num_feasible_nodes_to_find(5000) == 500  # (50-40)% of 5000
        assert s.num_feasible_nodes_to_find(1000) == 420  # 42% of 1000
        s2 = GenericScheduler(percentage_of_nodes_to_score=100)
        assert s2.num_feasible_nodes_to_find(5000) == 5000

    def test_select_host_reservoir(self):
        import random

        s = GenericScheduler(rng=random.Random(42))
        scores = [NodeScore("a", 10), NodeScore("b", 10), NodeScore("c", 5)]
        picks = {s.select_host(scores) for _ in range(50)}
        assert picks <= {"a", "b"}
        assert len(picks) == 2
