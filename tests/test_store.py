"""KV store semantics, run identically over ALL backends: the pure-Python
store (store/kv.py), the native C++ library (store/native.py over
native/kvstore.cpp), and the WAL+snapshot durable store (store/kv.py
DurableKVStore) — the etcd-equivalent semantics must be
indistinguishable (reference: staging/src/k8s.io/apiserver/pkg/storage/
etcd3 store semantics; SURVEY.md §2.4.2). Recovery/crash semantics of
the durable backend live in tests/test_durable_store.py.
"""

import threading

import pytest

from kubernetes_tpu.store import kv
from kubernetes_tpu.store.native import NativeKVStore


@pytest.fixture(params=["python", "native", "durable"])
def store(request):
    if request.param == "python":
        return kv.KVStore(history_limit=50)
    if request.param == "durable":
        tmp = request.getfixturevalue("tmp_path")
        return kv.DurableKVStore(str(tmp / "db"), history_limit=50)
    return NativeKVStore(history_limit=50)


class TestCRUD:
    def test_create_get(self, store):
        rev = store.create("/registry/pods/default/a", {"x": 1})
        assert rev == 1
        got = store.get("/registry/pods/default/a")
        assert got.value == {"x": 1}
        assert got.create_revision == got.mod_revision == 1
        with pytest.raises(kv.KeyExists):
            store.create("/registry/pods/default/a", {})

    def test_get_missing(self, store):
        with pytest.raises(kv.KeyNotFound):
            store.get("/nope")

    def test_update_revisions_and_conflict(self, store):
        store.create("/k", {"v": 0})
        rev = store.update("/k", {"v": 1})
        assert rev == 2
        got = store.get("/k")
        assert got.create_revision == 1 and got.mod_revision == 2
        with pytest.raises(kv.Conflict):
            store.update("/k", {"v": 2}, expected_mod_revision=1)
        rev = store.update("/k", {"v": 2}, expected_mod_revision=2)
        assert rev == 3
        with pytest.raises(kv.KeyNotFound):
            store.update("/missing", {})

    def test_delete(self, store):
        store.create("/k", 1)
        with pytest.raises(kv.Conflict):
            store.delete("/k", expected_mod_revision=99)
        store.delete("/k", expected_mod_revision=1)
        with pytest.raises(kv.KeyNotFound):
            store.get("/k")
        with pytest.raises(kv.KeyNotFound):
            store.delete("/k")

    def test_list_prefix_ordered(self, store):
        store.create("/registry/pods/ns2/b", 2)
        store.create("/registry/pods/ns1/a", 1)
        store.create("/registry/nodes/n1", 3)
        items, rev = store.list("/registry/pods/")
        assert [i.key for i in items] == [
            "/registry/pods/ns1/a",
            "/registry/pods/ns2/b",
        ]
        assert rev == store.revision == 3
        items, _ = store.list("/registry/")
        assert len(items) == 3

    def test_guaranteed_update(self, store):
        store.create("/k", {"n": 0})
        store.guaranteed_update("/k", lambda v: {"n": v["n"] + 1})
        assert store.get("/k").value == {"n": 1}


class TestWatch:
    def test_replay_from_revision(self, store):
        store.create("/a", 1)
        store.create("/b", 2)
        w = store.watch("/", since_revision=1)
        ev = w.poll(timeout=1)
        assert ev.type == kv.ADDED and ev.key == "/b" and ev.revision == 2
        store.update("/a", 10)
        ev = w.poll(timeout=1)
        assert ev.type == kv.MODIFIED and ev.key == "/a" and ev.value == 10
        store.delete("/b")
        ev = w.poll(timeout=1)
        assert ev.type == kv.DELETED and ev.key == "/b" and ev.value == 2
        w.stop()
        assert w.poll(timeout=0.05) is None

    def test_default_watch_is_live_only(self, store):
        store.create("/a", 1)
        w = store.watch("/")  # since_revision=None -> from now
        assert w.poll(timeout=0.05) is None
        store.create("/b", 2)
        ev = w.poll(timeout=1)
        assert ev.key == "/b"
        w.stop()

    def test_since_revision_zero_replays_from_start(self, store):
        # an informer listing an EMPTY store sees revision 0; its watch
        # from 0 must replay anything written between list and watch or
        # the event is lost forever (no informer resync) — the flake this
        # pins down
        w = store.watch("/", since_revision=0)
        store.create("/a", 1)
        got = store.watch("/", since_revision=0)  # created after the write
        assert got.poll(timeout=1).key == "/a"
        assert w.poll(timeout=1).key == "/a"
        w.stop(), got.stop()

    def test_prefix_filter(self, store):
        w = store.watch("/registry/pods/", since_revision=0)
        # explicit 0 on an empty store: replay-from-start (nothing yet)
        w2 = store.watch("/registry/pods/")
        store.create("/registry/nodes/n", 1)
        store.create("/registry/pods/default/p", 2)
        ev = w2.poll(timeout=1)
        assert ev.key == "/registry/pods/default/p"
        w.stop(), w2.stop()

    def test_compaction(self, store):
        # history_limit=50: blow past it, then ask for an ancient revision
        for i in range(60):
            store.create(f"/k{i:03d}", i)
        with pytest.raises(kv.Compacted):
            store.watch("/", since_revision=1)
        # recent revision still watchable
        w = store.watch("/", since_revision=store.revision)
        store.create("/fresh", 1)
        assert w.poll(timeout=1).key == "/fresh"
        w.stop()

    def test_concurrent_writers_one_revision_stream(self, store):
        errs = []

        def writer(base):
            try:
                for i in range(50):
                    store.create(f"/w/{base}/{i}", i)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(b,)) for b in range(4)]
        w = store.watch("/w/")
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        revs = []
        while True:
            ev = w.poll(timeout=0.3)
            if ev is None:
                break
            revs.append(ev.revision)
        assert len(revs) == 200
        assert revs == sorted(revs) and len(set(revs)) == 200
        w.stop()


class TestNativeBackedAPIServer:
    def test_cluster_on_native_store(self):
        """The whole apiserver + informer stack over the C++ store."""
        from kubernetes_tpu.api import types as v1
        from kubernetes_tpu.apiserver.server import APIServer
        from kubernetes_tpu.client.clientset import Clientset
        from kubernetes_tpu.client.informer import SharedInformerFactory

        from .util import make_node, make_pod, wait_until

        api = APIServer(store=NativeKVStore())
        cs = Clientset(api)
        factory = SharedInformerFactory(cs)
        informer = factory.informer_for("pods")
        factory.start()
        assert factory.wait_for_cache_sync()
        try:
            cs.nodes.create(make_node("n1"))
            cs.pods.create(make_pod("p1", node_name="n1"))
            assert wait_until(lambda: informer.get("default/p1") is not None)
            live = cs.pods.get("p1", "default")
            live.status.phase = "Running"
            cs.pods.update_status(live)
            assert wait_until(
                lambda: (informer.get("default/p1") or make_pod("x")).status.phase
                == "Running"
            )
            # optimistic concurrency through the full stack
            stale = cs.pods.get("p1", "default")
            cs.pods.update(cs.pods.get("p1", "default"))
            from kubernetes_tpu.apiserver.server import Conflict

            with pytest.raises(Conflict):
                cs.pods.update(stale)
        finally:
            factory.stop()


class TestParityExtras:
    @pytest.mark.parametrize("backend", ["python", "native"])
    def test_explicit_compact(self, backend):
        store = (
            kv.KVStore(history_limit=1000)
            if backend == "python"
            else NativeKVStore(history_limit=1000)
        )
        for i in range(10):
            store.create(f"/k{i}", i)
        store.compact(5)
        with pytest.raises(kv.Compacted):
            store.watch("/", since_revision=3)
        w = store.watch("/", since_revision=7)
        assert w.poll(timeout=0.5).revision == 8
        w.stop()

    def test_native_poll_none_blocks_until_event(self):
        import threading
        import time as _time

        store = NativeKVStore()
        w = store.watch("/")
        got = []

        def waiter():
            got.append(w.poll())  # timeout=None must block, not spin/return

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        _time.sleep(0.2)
        assert not got  # still blocked
        store.create("/x", 1)
        t.join(timeout=2)
        assert got and got[0].key == "/x"
        w.stop()
