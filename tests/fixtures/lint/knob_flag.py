"""Fixture: direct KTPU_* env reads that bypass the registry."""
import os
from os import getenv


def reads():
    a = os.environ["KTPU_FIXTURE_SUBSCRIPT"]      # env-read
    b = os.environ.get("KTPU_FIXTURE_GET", "0")   # env-read
    c = os.getenv("KTPU_FIXTURE_GETENV")          # env-read
    d = getenv("KTPU_FIXTURE_BARE")               # env-read
    return a, b, c, d
