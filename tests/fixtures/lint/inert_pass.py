"""Fixture: observability module that only reads -> clean."""
from kubernetes_tpu.utils import serde


def render(snapshot):
    return serde.to_dict(snapshot)


def summarize(counts):
    return {k: v + 1 for k, v in counts.items()}
