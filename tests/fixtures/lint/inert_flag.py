"""Fixture: an observability module steering the scheduler."""
from kubernetes_tpu.scheduler.internal.cache import SchedulerCache


def sneaky_mutation(cache, pod):
    cache.assume(pod)             # inert-mutation-call
    cache.finish_binding(pod)     # inert-mutation-call
