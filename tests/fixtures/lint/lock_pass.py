"""Fixture: consistent order, including through a helper call."""
import threading

a_lock = threading.Lock()
b_lock = threading.Lock()


def _take_b():
    with b_lock:
        pass


def forward_direct():
    with a_lock:
        with b_lock:
            pass


def forward_via_call():
    with a_lock:
        _take_b()
