"""Fixture: seam counter bumped without the paired ring dump."""
from kubernetes_tpu.scheduler import metrics


def silent_fault(kind):
    metrics.device_faults.inc(kind=kind)   # seam-unpaired
    return kind
