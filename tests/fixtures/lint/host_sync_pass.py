"""Fixture: host-only code plus pragma'd intentional syncs -> clean."""
import jax.numpy as jnp
import numpy as np


def host_only(rows):
    arr = np.asarray(rows)              # np-sourced: not a readback
    n = int(arr.shape[0])               # metadata only
    return float(arr.sum()) + n


def shapes(ys):
    return int(ys.shape[0]), ys.dtype   # device metadata never syncs


# ktpu: allow-sync(fixture: harvest decode reads verdicts by design)
def pragma_function(ys):
    return [int(v) for v in np.asarray(ys)]


def pragma_line(ys):
    total = jnp.sum(ys)
    # ktpu: allow-sync(fixture: measured fence inside a timing window)
    total.block_until_ready()
    return total
