"""Fixture: every host-sync sink fires (checked as a hot-path file)."""
import jax
import jax.numpy as jnp
import numpy as np


def readbacks(ys):
    total = jnp.sum(ys)
    n = total.item()               # item-call
    f = float(total)               # scalar-coerce
    host = np.asarray(ys)          # numpy-readback
    g = jax.device_get(total)      # device-get
    total.block_until_ready()      # block-until-ready
    return n, f, host, g


def propagation(xs):
    a = jnp.ones(4) + xs
    b, c = a, a * 2
    lo = int(b)                    # scalar-coerce through alias b
    hi = int(c[0])                 # scalar-coerce through alias c
    return lo + hi
