"""Fixture: accessor reads and env WRITES are legal."""
import os

from kubernetes_tpu.utils import knobs


def accessor_read():
    return knobs.get_int("KTPU_TRACE")


def harness_writes():
    os.environ["KTPU_FIXTURE_LEVER"] = "1"    # Store context: allowed
    os.environ.pop("KTPU_FIXTURE_LEVER", None)  # write: allowed
    return os.environ.get("PATH", "")         # non-KTPU read: allowed
