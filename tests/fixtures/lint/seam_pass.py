"""Fixture: counter and dump travel together -> clean."""
from kubernetes_tpu.scheduler import metrics
from kubernetes_tpu.scheduler.metrics import dump_seam


def loud_fault(kind):
    metrics.device_faults.inc(kind=kind)
    dump_seam(f"device-fault-{kind}")


def unrelated_counter(m):
    m.dispatches.inc()   # not a seam counter
