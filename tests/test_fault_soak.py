"""Randomized fault-soak: device faults + pipeline-worker kills + node
deaths over a churning cluster.

The ChaosMonkey drives the NEW fault kinds (`wedge-device` arms a
one-shot dispatch raise / NaN harvest / wedged wait on the scheduler's
FaultInjector; `crash-scheduler` kills the scheduling loop or the
completion worker) interleaved with the classic kubelet kills and pod
deletions, while a ReplicaSet keeps re-creating the workload. The
control plane must re-converge with ZERO lost pods and ZERO double
binds — the invariant the device-fault-tolerance subsystem exists for.

Fast deterministic variant runs in tier-1; the long soak is `slow`.
"""

from __future__ import annotations

import random
import time

import pytest

from kubernetes_tpu.api import apps, types as v1
from kubernetes_tpu.cluster import Cluster
from kubernetes_tpu.testing.chaos import ChaosMonkey
from kubernetes_tpu.testing.faults import BindIntegrityChecker, FaultInjector
from kubernetes_tpu.testing.locks import lock_order_sentinel

from .util import wait_until


def _deployment(name: str, replicas: int) -> apps.Deployment:
    return apps.Deployment(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        spec=apps.DeploymentSpec(
            replicas=replicas,
            selector=v1.LabelSelector(match_labels={"app": name}),
            template=apps.PodTemplateSpec(
                metadata=v1.ObjectMeta(labels={"app": name}),
                spec=v1.PodSpec(containers=[v1.Container(
                    name="c", image="img:1",
                    resources=v1.ResourceRequirements(requests={"cpu": "20m"}),
                )]),
            ),
        ),
    )


def _soak(seed: int, duration: float, n_nodes: int, replicas: int,
          period: float = 0.25) -> None:
    # every lock the cluster creates is order-tracked; teardown asserts
    # the observed acquisition graph is cycle-free (testing/locks.py)
    with lock_order_sentinel():
        _soak_impl(seed, duration, n_nodes, replicas, period)


def _soak_impl(seed: int, duration: float, n_nodes: int, replicas: int,
               period: float = 0.25) -> None:
    inj = FaultInjector()
    rng = random.Random(seed)
    with Cluster(
        n_nodes=n_nodes,
        controllers=["replicaset", "deployment", "nodelifecycle"],
        controller_opts={
            "node_monitor_period": 0.3,
            "node_monitor_grace_period": 2.0,
        },
        fault_injector=inj,
    ) as c:
        tpu = c.scheduler.tpu
        assert tpu is not None, "soak must run the TPU backend"
        # fast fault cadence: the watchdog/retry/probe knobs scaled to
        # the test budget (production defaults are seconds-scale)
        tpu.watchdog_timeout = 0.5
        tpu.retry_base = 0.01
        tpu.ladder._probe_interval = 0.1
        tpu.ladder._probe_delay = 0.1
        checker = BindIntegrityChecker().attach(c.kcm.informers.pods())
        c.client.resource("deployments").create(_deployment("ha", replicas))

        def n_running():
            pods, _ = c.client.pods.list(namespace="default")
            return sum(1 for p in pods if p.status.phase == "Running")

        assert wait_until(lambda: n_running() == replicas, timeout=60)

        monkey = ChaosMonkey(
            c, period=period, rng=rng,
            disruptions=[
                "wedge-device", "crash-scheduler",
                "kill-kubelet", "restart-kubelet", "delete-pod",
            ],
        )
        monkey.run()
        time.sleep(duration)
        monkey.stop()
        kinds = {d.kind for d in monkey.history}
        assert "wedge-device" in kinds or "crash-scheduler" in kinds, (
            f"soak never exercised the fault kinds: {monkey.history}"
        )
        # end the experiment: clear armed faults, restart dead kubelets,
        # and let the probe re-promote a demoted ladder
        inj.disarm()
        monkey.restart_all_dead()
        assert wait_until(
            lambda: tpu.ladder.rung() >= tpu.ladder.top, timeout=30
        ), f"ladder stuck at {tpu.ladder.mode()} after faults cleared"

        # convergence: desired replicas running, zero lost pods
        def converged():
            pods, _ = c.client.pods.list(namespace="default")
            running = [p for p in pods if p.status.phase == "Running"]
            return len(running) == replicas and len(pods) == replicas

        assert wait_until(converged, timeout=90), [
            (p.metadata.name, p.spec.node_name, p.status.phase)
            for p in c.client.pods.list(namespace="default")[0]
        ]
        # zero double binds: no pod ever moved node-to-node in place
        assert not checker.violations, checker.violations
        # every injected fault kind was actually consumed by the
        # pipeline (the injector's ledger is the ground truth)
        armed = sum(1 for d in monkey.history if d.kind == "wedge-device")
        if armed:
            assert sum(
                inj.injected.get(k, 0)
                for k in ("raise-dispatch", "nan-harvest", "wedge-wait")
            ) >= 1, f"wedge-device armed {armed}x but nothing fired: " \
                    f"{inj.injected}"


def test_fault_soak_fast():
    """Deterministic tier-1 soak: ~16 disruptions over a small cluster."""
    _soak(seed=42, duration=4.0, n_nodes=4, replicas=8)


@pytest.mark.slow
def test_fault_soak_long():
    """The long soak: more nodes, more churn, more disruptions."""
    _soak(seed=7, duration=20.0, n_nodes=8, replicas=24, period=0.2)
