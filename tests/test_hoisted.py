"""Template-hoisted batch scheduler: decision parity with the generic
batched scan (which is itself pinned to the per-pod kernel and the Go
oracle by tests/test_batch.py)."""

import numpy as np
import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.batch import schedule_batch
from kubernetes_tpu.ops.hoisted import schedule_batch_hoisted, template_fingerprint
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

from .util import make_pod


def _encode_all(enc, pe, pods):
    # NOTE: no pod_batchable assertion — the hoisted session (r2) and the
    # pallas kernel (r3) both take term templates; only callers that
    # exercise the plain batch path feed strictly batchable pods
    return [
        {k: v for k, v in pe.encode(p).items() if not k.startswith("_")}
        for p in pods
    ]


def _presized_encoding(nodes, init_pods, pending):
    """Encoding with the pod table pre-sized for the whole batch
    (bench.py's phantom-bind trick)."""
    import copy

    enc = ClusterEncoding()
    phantoms = []
    for i, p in enumerate(pending):
        q = copy.deepcopy(p)
        q.metadata.name = f"phantom-{i}"
        q.spec.node_name = nodes[i % len(nodes)].metadata.name
        phantoms.append(q)
    enc.set_cluster(nodes, init_pods + phantoms)
    pe = PodEncoder(enc)
    for p in pending:
        pe.encode(p)
    enc.device_state()
    for q in phantoms:
        enc.remove_pod(q)
    return enc, pe


def _run_both(nodes, init_pods, pending):
    enc, pe = _presized_encoding(nodes, init_pods, pending)
    arrays = _encode_all(enc, pe, pending)
    c = enc.device_state()
    slots = [enc._pod_free[-1 - i] for i in range(len(pending))]
    generic, _ = schedule_batch(c, arrays, slots)
    hoisted, ys = schedule_batch_hoisted(c, arrays)
    return generic, hoisted, ys


def _bind_pending(pods, nodes):
    for i, p in enumerate(pods):
        p.spec.node_name = nodes[i % len(nodes)].metadata.name
    return pods


class TestHoistedParity:
    def test_spread_templates(self):
        nodes, init_pods = synth_cluster(24, pods_per_node=2)
        pending = synth_pending_pods(40, spread=True)
        generic, hoisted, ys = _run_both(nodes, init_pods, pending)
        assert hoisted == generic
        assert all(d >= 0 for d in hoisted)

    def test_no_constraints(self):
        nodes, init_pods = synth_cluster(10, pods_per_node=1)
        pending = synth_pending_pods(16, spread=False)
        generic, hoisted, _ = _run_both(nodes, init_pods, pending)
        assert hoisted == generic

    def test_capacity_pressure_infeasible_tail(self):
        # tiny nodes: later pods must become infeasible identically
        nodes, init_pods = synth_cluster(3, pods_per_node=0)
        for node in nodes:
            node.status.allocatable["cpu"] = "250m"
            node.status.capacity["cpu"] = "250m"
        pending = synth_pending_pods(12, spread=True)  # 100m each
        generic, hoisted, _ = _run_both(nodes, init_pods, pending)
        assert hoisted == generic
        assert -1 in hoisted  # capacity exhausted for the tail

    def test_hostname_hard_spread(self):
        nodes, init_pods = synth_cluster(6, pods_per_node=1)
        pending = []
        for i in range(10):
            pending.append(
                make_pod(
                    f"hard-{i}",
                    cpu="50m",
                    labels={"app": "hard"},
                    constraints=[
                        v1.TopologySpreadConstraint(
                            max_skew=1,
                            topology_key=v1.LABEL_HOSTNAME,
                            when_unsatisfiable="DoNotSchedule",
                            label_selector=v1.LabelSelector(
                                match_labels={"app": "hard"}
                            ),
                        )
                    ],
                )
            )
        generic, hoisted, _ = _run_both(nodes, init_pods, pending)
        assert hoisted == generic
        # maxSkew=1 over 6 nodes: first 6 land on distinct nodes
        assert len({d for d in hoisted[:6]}) == 6

    def test_mixed_templates_cross_counting(self):
        # two templates whose selectors MATCH EACH OTHER's pods: assumed
        # pods of template A must update template B's counts
        nodes, init_pods = synth_cluster(8, pods_per_node=1)
        pending = []
        for i in range(12):
            labels = {"tier": "web", "idx": f"t{i % 2}"}
            pending.append(
                make_pod(
                    f"x-{i}",
                    cpu="50m",
                    labels=labels,
                    constraints=[
                        v1.TopologySpreadConstraint(
                            max_skew=1,
                            topology_key=v1.LABEL_ZONE,
                            when_unsatisfiable="ScheduleAnyway",
                            label_selector=v1.LabelSelector(
                                match_labels={"tier": "web"}  # matches BOTH
                            ),
                        )
                    ],
                )
            )
        generic, hoisted, _ = _run_both(nodes, init_pods, pending)
        assert hoisted == generic

    def test_fingerprint_groups_identical_specs(self):
        nodes, init_pods = synth_cluster(4, pods_per_node=0)
        pending = synth_pending_pods(8, n_templates=2, spread=True)
        enc = ClusterEncoding()
        enc.set_cluster(nodes, init_pods + _bind_pending(pending, nodes))
        pe = PodEncoder(enc)
        for p in pending:
            p.spec.node_name = ""
            pe.encode(p)
        enc.device_state()
        arrays = _encode_all(enc, pe, pending)
        fps = {template_fingerprint(a) for a in arrays}
        assert len(fps) == 2


class TestShardedHoisted:
    def test_mesh_parity(self):
        """The hoisted scan sharded over an 8-device mesh must make the
        SAME decisions as the single-device scan (GSPMD collectives for
        normalization + count scatters)."""
        import jax

        from kubernetes_tpu.parallel.sharded import ShardedScheduler, make_mesh

        # 26 nodes on an 8-device mesh: NOT divisible, so pad_node_axis
        # adds 6 all-zero rows — the parity assert also proves padded
        # nodes are never chosen
        nodes, init_pods = synth_cluster(26, pods_per_node=2)
        pending = synth_pending_pods(24, spread=True)
        enc, pe = _presized_encoding(nodes, init_pods, pending)
        arrays = _encode_all(enc, pe, pending)
        c = enc.device_state()
        single, _ = schedule_batch_hoisted(c, arrays)
        mesh = make_mesh(n_devices=min(8, len(jax.devices())))
        sharded, _ = ShardedScheduler(mesh=mesh).schedule_batch_hoisted(c, arrays)
        assert sharded == single
        assert all(d < 26 for d in sharded)  # real node indices only


class TestHoistedSession:
    """Cross-batch device-resident carry vs per-batch hoisted + host sync.

    The session never syncs assumed pods back into the pod table between
    batches; the reference path does after every batch. For batchable
    pods the decisions must be bit-identical."""

    def _reference_path(self, nodes, init_pods, pending, batch):
        """schedule_batch_hoisted per batch, host add_pod sync between."""
        enc, pe = _presized_encoding(nodes, init_pods, pending)
        arrays = _encode_all(enc, pe, pending)
        out = []
        for i in range(0, len(pending), batch):
            c = enc.device_state()
            decisions, _ = schedule_batch_hoisted(c, arrays[i : i + batch])
            out.extend(decisions)
            for pod, best in zip(pending[i : i + batch], decisions):
                if best >= 0:
                    pod.spec.node_name = enc.node_names[best]
                    enc.add_pod(pod, enc.node_names[best])
        return out

    def _session_path(self, nodes, init_pods, pending, batch):
        from kubernetes_tpu.ops.hoisted import HoistedSession

        enc, pe = _presized_encoding(nodes, init_pods, pending)
        arrays = _encode_all(enc, pe, pending)
        templates, seen = [], set()
        for a in arrays:
            fp = template_fingerprint(a)
            if fp not in seen:
                seen.add(fp)
                templates.append(a)
        sess = HoistedSession(enc.device_state(), templates)
        ys_all = [
            sess.schedule(arrays[i : i + batch])
            for i in range(0, len(pending), batch)
        ]
        out = []
        for ys in ys_all:
            out.extend(HoistedSession.decisions(ys))
        return out

    def test_multi_batch_parity_spread(self):
        nodes, init_pods = synth_cluster(16, pods_per_node=2)
        pending = synth_pending_pods(36, spread=True)
        import copy

        ref = self._reference_path(nodes, copy.deepcopy(init_pods),
                                   copy.deepcopy(pending), batch=12)
        got = self._session_path(nodes, init_pods, pending, batch=12)
        assert got == ref
        assert all(d >= 0 for d in got)

    def test_capacity_exhaustion_across_batches(self):
        # carry must track utilization across batch boundaries: the tail
        # becomes infeasible at exactly the same pod as the synced path
        nodes, init_pods = synth_cluster(3, pods_per_node=0)
        for node in nodes:
            node.status.allocatable["cpu"] = "350m"
            node.status.capacity["cpu"] = "350m"
        pending = synth_pending_pods(15, spread=True)  # 100m each
        import copy

        ref = self._reference_path(nodes, copy.deepcopy(init_pods),
                                   copy.deepcopy(pending), batch=5)
        got = self._session_path(nodes, init_pods, pending, batch=5)
        assert got == ref
        assert -1 in got

    def test_unknown_template_raises(self):
        from kubernetes_tpu.ops.hoisted import HoistedSession

        nodes, init_pods = synth_cluster(4, pods_per_node=1)
        pending = synth_pending_pods(4, spread=True)
        other = synth_pending_pods(2, spread=False)
        enc, pe = _presized_encoding(nodes, init_pods, pending + other)
        arrays = _encode_all(enc, pe, pending)
        other_arrays = _encode_all(enc, pe, other)
        sess = HoistedSession(enc.device_state(), [arrays[0]])
        with pytest.raises(KeyError):
            sess.schedule(other_arrays)
