"""Cluster bootstrap (the kubeadm-init-equivalent wiring): full control
plane in one object, over both store backends."""

import pytest

from kubernetes_tpu.cluster import Cluster

from .util import wait_until


@pytest.mark.parametrize("native_store", [False, True])
def test_full_stack_bootstrap(native_store, tmp_path):
    import yaml

    manifest = tmp_path / "app.yaml"
    manifest.write_text(
        yaml.safe_dump_all([
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": "web"},
                "spec": {
                    "replicas": 4,
                    "selector": {"matchLabels": {"app": "web"}},
                    "template": {
                        "metadata": {"labels": {"app": "web"}},
                        "spec": {
                            "containers": [
                                {
                                    "name": "c",
                                    "image": "img:1",
                                    "resources": {"requests": {"cpu": "50m"}},
                                }
                            ]
                        },
                    },
                },
            },
            {
                "apiVersion": "v1",
                "kind": "Service",
                "metadata": {"name": "web"},
                "spec": {
                    "selector": {"app": "web"},
                    "clusterIP": "10.0.0.20",
                    "ports": [{"name": "http", "port": 80, "targetPort": 8080}],
                },
            },
        ])
    )
    with Cluster(
        n_nodes=3, scheduler_backend="oracle", native_store=native_store
    ) as c:
        c.kubectl("apply", "-f", str(manifest))

        def all_running():
            pods, _ = c.client.pods.list(namespace="default")
            return (
                sum(1 for p in pods if p.status.phase == "Running") == 4
            )

        assert wait_until(all_running, timeout=60)
        # endpoints + endpointslices materialized
        assert wait_until(
            lambda: sum(
                len(s.endpoints or [])
                for s in c.client.resource("endpointslices").list(
                    namespace="default"
                )[0]
            )
            == 4
        )
        # the deployment status controller syncs asynchronously; poll
        # instead of asserting a racy snapshot (flaky under machine load)
        assert wait_until(lambda: "4/4" in c.kubectl("get", "deploy"))
        # default admission ran (tolerations stamped)
        pod = c.client.pods.list(namespace="default")[0][0]
        tol_keys = {t.key for t in pod.spec.tolerations or []}
        assert "node.kubernetes.io/not-ready" in tol_keys
        # configz live
        from kubernetes_tpu.utils import configz

        assert "kubescheduler.config.k8s.io" in configz.snapshot()
