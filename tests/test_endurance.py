"""Deterministic endurance slice: the soak's machinery in tier-1 time.

scripts/soak.py is the hours-capable harness; this is its CI-sized
deterministic core (~10s): a live cluster in the production shape,
directed `overload` waves (completion-worker stall) driving the
host-overload monitor through at least one FULL shed->restore cycle,
workload churn throughout, and the same invariant library
(testing/invariants.py) reading /metricsz over the run — zero shadow
drift, zero expired assumes, zero double binds, bounded thread/fd
growth, queue back to baseline, no assume outliving its TTL.

The `slow` variant runs the same body under a randomized ChaosMonkey
mix for a longer window (the soak's shape, pytest-managed):

    pytest tests/test_endurance.py -m slow
"""

from __future__ import annotations

import random
import time

import pytest

from kubernetes_tpu.api import apps, types as v1
from kubernetes_tpu.cluster import Cluster
from kubernetes_tpu.testing import invariants as inv
from kubernetes_tpu.testing.chaos import ChaosMonkey
from kubernetes_tpu.testing.faults import BindIntegrityChecker, FaultInjector
from kubernetes_tpu.testing.locks import lock_order_sentinel


def _wait(fn, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def _deployment(name: str, replicas: int) -> apps.Deployment:
    return apps.Deployment(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        spec=apps.DeploymentSpec(
            replicas=replicas,
            selector=v1.LabelSelector(match_labels={"app": name}),
            template=apps.PodTemplateSpec(
                metadata=v1.ObjectMeta(labels={"app": name}),
                spec=v1.PodSpec(containers=[v1.Container(
                    name="c", image="img:1",
                    resources=v1.ResourceRequirements(
                        requests={"cpu": "20m"}),
                )]),
            ),
        ),
    )


def _suite(checker, assume_ttl):
    """The soak's invariant set minus the long-window-only monitors
    (RSS and p99 flatness need a window this slice doesn't have)."""
    return inv.InvariantSuite([
        inv.CounterFlat("scheduler_parity_drift_total",
                        label="zero-shadow-drift"),
        inv.CounterFlat("scheduler_cache_expired_assumes_total",
                        label="zero-expired-assumes"),
        inv.Callback("zero-double-binds",
                     lambda: list(checker.violations)),
        inv.BoundedGrowth("process_open_fds", max_abs=32,
                          label="fd-growth"),
        inv.BoundedGrowth("process_threads", max_abs=16,
                          label="thread-growth"),
        inv.GaugeBaseline("scheduler_pending_pods", slack=4,
                          label="queue-returns-to-baseline"),
        inv.GaugeCeiling("scheduler_cache_oldest_assume_seconds",
                         ceiling=assume_ttl + 5.0,
                         label="no-assume-outlives-ttl"),
    ])


def _endurance_body(seconds: float, directed: bool, seed: int = 11):
    # dynamic lock-order sentinel: the chaos mix must not only avoid
    # deadlock by timing luck — the acquisition graph itself is checked
    with lock_order_sentinel():
        _endurance_impl(seconds, directed, seed)


def _endurance_impl(seconds: float, directed: bool, seed: int = 11):
    rng = random.Random(seed)
    inj = FaultInjector()
    inj.stall_delay = 0.3
    replicas = 8
    with Cluster(
        n_nodes=3,
        controllers=["replicaset", "deployment", "nodelifecycle"],
        controller_opts={
            "node_monitor_period": 0.3,
            "node_monitor_grace_period": 2.0,
        },
        fault_injector=inj,
    ) as c:
        sched = c.scheduler
        tpu = sched.tpu
        assert tpu is not None and sched.overload is not None
        tpu.watchdog_timeout = 0.5
        tpu.retry_base = 0.01
        tpu.ladder._probe_interval = 0.1
        tpu.ladder._probe_delay = 0.1
        ov = sched.overload
        # CI-speed water marks: one stalled batch (0.3s) out-ages the
        # high mark; two clean batches restore a lever
        ov.high_fifo_age = 0.15
        ov.low_fifo_age = 0.05
        ov.shed_dwell = 2
        ov.restore_dwell = 2
        ov.cooldown = 0.05
        checker = BindIntegrityChecker().attach(c.kcm.informers.pods())
        c.client.resource("deployments").create(
            _deployment("soak", replicas))

        def n_running():
            pods, _ = c.client.pods.list(namespace="default")
            return sum(1 for p in pods if p.status.phase == "Running")

        assert _wait(lambda: n_running() == replicas, timeout=60), (
            f"initial convergence: {n_running()}/{replicas}"
        )
        suite = _suite(checker, assume_ttl=sched.cache._ttl)
        suite.sample()  # baseline

        def churn_tick():
            pods, _ = c.client.pods.list(namespace="default")
            live = [p for p in pods
                    if p.metadata.deletion_timestamp is None]
            if live:
                p = rng.choice(live)
                c.client.pods.delete(p.metadata.name, p.metadata.namespace)

        monkey = None
        if directed:
            # one directed wave: stall until shed, clear, churn until
            # fully restored — a guaranteed full cycle, deterministically
            inj.arm("stall-completion", shots=12)
            deadline = time.monotonic() + 20
            while ov.level() == 0 and time.monotonic() < deadline:
                churn_tick()
                time.sleep(0.15)
                suite.sample()
            assert ov.level() > 0, "stall wave never tripped a shed"
            inj.disarm("stall-completion")
            deadline = time.monotonic() + 25
            while ov.level() > 0 and time.monotonic() < deadline:
                churn_tick()
                time.sleep(0.15)
                suite.sample()
            assert ov.level() == 0, (
                f"levers never restored: {ov.shed_names()}"
            )
        else:
            monkey = ChaosMonkey(
                c, period=0.25, rng=rng,
                disruptions=[
                    "delete-pod", "delete-pod", "delete-pod",
                    "overload", "wedge-device", "crash-scheduler",
                ],
            )
            monkey.run()
            deadline = time.monotonic() + seconds
            while time.monotonic() < deadline:
                time.sleep(0.5)
                suite.sample()
            monkey.stop()
            inj.disarm()
            monkey.restart_all_dead(timeout=30)
            # guarantee the full cycle even if the random mix missed it
            if ov.cycles < 1:
                inj.arm("stall-completion", shots=20)
                deadline = time.monotonic() + 20
                while ov.level() == 0 and time.monotonic() < deadline:
                    churn_tick()
                    time.sleep(0.15)
                    suite.sample()
                inj.disarm("stall-completion")
                deadline = time.monotonic() + 25
                while ov.level() > 0 and time.monotonic() < deadline:
                    churn_tick()
                    time.sleep(0.15)
                    suite.sample()

        assert _wait(lambda: tpu.ladder.rung() >= tpu.ladder.top,
                     timeout=30), "ladder stuck after faults cleared"

        def converged():
            pods, _ = c.client.pods.list(namespace="default")
            running = [p for p in pods if p.status.phase == "Running"]
            return (len(running) == replicas
                    and len(pods) == replicas)

        assert _wait(converged, timeout=60), (
            f"lost pods: {n_running()}/{replicas} after recovery"
        )
        time.sleep(1.0)
        violations = suite.finish()
        assert not violations, f"invariants violated: {violations}"
        assert ov.triggered and ov.cycles >= 1, (
            f"no full shed->restore cycle (cycles={ov.cycles}, "
            f"history={[(a, w) for _, a, w, _ in ov.history]})"
        )
        assert ov.level() == 0 and not checker.violations


def test_ghost_queue_entry_is_dropped():
    """The stale-queue-entry race the soak's queue-returns-to-baseline
    invariant surfaced: a pod deleted during its in-flight window
    (popped, so the delete event's queue.delete was a no-op) and then
    re-queued by a failed bind must be DROPPED at the next pop — before
    the _skip fix it was rescheduled, 404-bound, forgotten and
    re-queued forever, a ghost cycling the queue and pinning
    scheduler_pending_pods above baseline."""
    from .test_pipeline_parity import _cluster, _mk_scheduler
    from .util import make_pod

    api, cs = _cluster(n_nodes=2)
    sched = _mk_scheduler(cs, depth=0)
    try:
        cs.pods.create(make_pod("ghost", namespace="default", cpu="100m"))
        assert _wait(lambda: sched.queue.num_active() == 1)
        info = sched.queue.pop(timeout=5)
        assert info is not None
        # the delete lands while the pod is in flight: nothing queued,
        # so the event handler's queue.delete removes nothing
        cs.pods.delete("ghost", "default")
        assert _wait(
            lambda: sched.informers.pods().get("default/ghost") is None)
        # absent from the informer cache == deleted, even though the
        # stale pod object carries no deletion_timestamp
        assert sched._skip(info.pod)
        # the failed-bind path re-queues it; the next cycle must drop
        # it on the floor — no dispatch, no assume, queue drained
        sched.queue.add(info.pod)
        ghost = sched.queue.pop(timeout=5)
        assert ghost is not None
        sched._schedule_batch_tpu([ghost])
        assert sched._drain_pipeline(timeout=10)
        assert sched.queue.depths() == (0, 0, 0)
        assert sched.cache.pod_count() == 0
    finally:
        sched.stop()
        sched.informers.stop()


def test_endurance_directed_cycle():
    """Tier-1: a directed overload wave through a churning cluster —
    one full shed->restore cycle, every invariant held."""
    _endurance_body(seconds=0.0, directed=True)


@pytest.mark.slow
def test_endurance_random_mix_long():
    """The soak's shape under pytest: randomized ChaosMonkey mix for a
    longer window (still bounded), same invariants, same cycle gate."""
    _endurance_body(seconds=20.0, directed=False, seed=23)
