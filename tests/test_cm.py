"""Kubelet container-manager subsystems: checkpoint manager (CRC files),
device plugin manager, CPU manager static policy, pod-resources API, and
kubelet wiring (node capacity, admit-time allocation, rejection).

Reference: pkg/kubelet/checkpointmanager/checkpoint_manager.go,
pkg/kubelet/cm/devicemanager/manager.go, cpumanager/policy_static.go,
staging/src/k8s.io/kubelet/pkg/apis/podresources.
"""

import json
import time

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.kubelet.cm import (
    AdmissionError,
    CheckpointManager,
    CorruptCheckpointError,
    CPUManager,
    Device,
    DeviceManager,
    DevicePlugin,
    PodResourcesServer,
)
from kubernetes_tpu.kubelet.cri import FakeRuntimeService
from kubernetes_tpu.kubelet.kubelet import Kubelet, KubeletConfig

from .util import FAST_KUBELET as FAST, make_pod, wait_until as _wait


class TestCheckpointManager:
    def test_roundtrip(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        cm.create_checkpoint("state", {"a": [1, 2], "b": "x"})
        assert cm.get_checkpoint("state") == {"a": [1, 2], "b": "x"}
        assert cm.list_checkpoints() == ["state"]
        cm.remove_checkpoint("state")
        assert cm.list_checkpoints() == []

    def test_corrupt_detected(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        cm.create_checkpoint("state", {"a": 1})
        p = tmp_path / "state"
        obj = json.loads(p.read_text())
        obj["data"]["a"] = 2  # flip payload, keep stale checksum
        p.write_text(json.dumps(obj))
        with pytest.raises(CorruptCheckpointError):
            cm.get_checkpoint("state")

    def test_garbage_file_is_corrupt(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        (tmp_path / "state").write_text("not json")
        with pytest.raises(CorruptCheckpointError):
            cm.get_checkpoint("state")

    def test_missing_raises_filenotfound(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        with pytest.raises(FileNotFoundError):
            cm.get_checkpoint("absent")


def _plugin(n=4, resource="vendor.example/accel"):
    return DevicePlugin(resource, [Device(f"dev-{i}") for i in range(n)])


class TestDeviceManager:
    def test_capacity_counts_healthy_only(self):
        dm = DeviceManager()
        pl = _plugin(4)
        dm.register_plugin(pl)
        cap, alloc, removed = dm.get_capacity()
        assert cap == {"vendor.example/accel": "4"}
        assert alloc == {"vendor.example/accel": "4"}
        pl.set_health("dev-2", False)  # ListAndWatch update
        cap, alloc, _ = dm.get_capacity()
        assert (cap, alloc) == ({"vendor.example/accel": "4"}, {"vendor.example/accel": "3"})

    def test_allocate_and_free(self):
        dm = DeviceManager()
        dm.register_plugin(_plugin(2))
        pod = make_pod("p1", extended={"vendor.example/accel": "2"})
        resp = dm.allocate(pod)
        assert set(resp) == {"c0"}
        assert len(resp["c0"].envs) == 2
        uid = "default/p1"
        assert dm.pod_devices(uid) == {"c0": {"vendor.example/accel": ["dev-0", "dev-1"]}}
        # exhausted: a second pod must be rejected
        with pytest.raises(AdmissionError):
            dm.allocate(make_pod("p2", extended={"vendor.example/accel": "1"}))
        dm.remove_pod(uid)
        dm.allocate(make_pod("p3", extended={"vendor.example/accel": "1"}))

    def test_unhealthy_devices_not_allocated(self):
        dm = DeviceManager()
        pl = _plugin(2)
        dm.register_plugin(pl)
        pl.set_health("dev-0", False)
        with pytest.raises(AdmissionError):
            dm.allocate(make_pod("p", extended={"vendor.example/accel": "2"}))

    def test_checkpoint_restore(self, tmp_path):
        ckpt = CheckpointManager(str(tmp_path))
        dm = DeviceManager(ckpt)
        dm.register_plugin(_plugin(3))
        dm.allocate(make_pod("p1", extended={"vendor.example/accel": "2"}))
        # kubelet restart: a fresh manager over the same checkpoint dir
        dm2 = DeviceManager(ckpt)
        dm2.register_plugin(_plugin(3))
        assert dm2.pod_devices("default/p1") == {
            "c0": {"vendor.example/accel": ["dev-0", "dev-1"]}
        }
        # only dev-2 is still free
        with pytest.raises(AdmissionError):
            dm2.allocate(make_pod("p2", extended={"vendor.example/accel": "2"}))
        dm2.allocate(make_pod("p3", extended={"vendor.example/accel": "1"}))

    def test_corrupt_checkpoint_starts_clean(self, tmp_path):
        ckpt = CheckpointManager(str(tmp_path))
        dm = DeviceManager(ckpt)
        dm.register_plugin(_plugin(2))
        dm.allocate(make_pod("p1", extended={"vendor.example/accel": "1"}))
        (tmp_path / DeviceManager.CHECKPOINT).write_text("garbage")
        dm2 = DeviceManager(ckpt)
        assert dm2.pod_devices("default/p1") == {}

    def test_unregister_reports_removed(self):
        dm = DeviceManager()
        dm.register_plugin(_plugin(2))
        dm.get_capacity()
        dm.unregister_plugin("vendor.example/accel")
        cap, alloc, removed = dm.get_capacity()
        assert removed == ["vendor.example/accel"]
        assert cap == {} and alloc == {}


class TestCPUManager:
    def _guaranteed_pod(self, name, cpus="2"):
        pod = make_pod(name, cpu=cpus, memory="1Gi")
        c = pod.spec.containers[0]
        c.resources.limits = dict(c.resources.requests)
        return pod

    def test_guaranteed_integral_gets_exclusive(self):
        cm = CPUManager(4)
        pod = self._guaranteed_pod("g1")
        cpus = cm.add_container(pod, "c0")
        assert len(cpus) == 2
        assert sorted(cm.shared_pool() + cpus) == [0, 1, 2, 3]

    def test_burstable_uses_shared_pool(self):
        cm = CPUManager(4)
        pod = make_pod("b1", cpu="2")  # requests only: Burstable
        assert cm.add_container(pod, "c0") == [0, 1, 2, 3]
        assert cm.assignments() == {}

    def test_fractional_cpu_uses_shared_pool(self):
        cm = CPUManager(4)
        pod = self._guaranteed_pod("f1", cpus="1500m")
        assert cm.add_container(pod, "c0") == [0, 1, 2, 3]

    def test_exhaustion_rejects(self):
        cm = CPUManager(2)
        cm.add_container(self._guaranteed_pod("g1"), "c0")
        with pytest.raises(AdmissionError):
            cm.add_container(self._guaranteed_pod("g2"), "c0")

    def test_checkpoint_restore(self, tmp_path):
        ckpt = CheckpointManager(str(tmp_path))
        cm = CPUManager(4, ckpt)
        cm.add_container(self._guaranteed_pod("g1"), "c0")
        cm2 = CPUManager(4, ckpt)
        assert cm2.assignments() == cm.assignments()
        cm2.remove_pod("default/g1")
        assert cm2.assignments() == {}


class TestPodResourcesServer:
    def test_list(self):
        dm = DeviceManager()
        dm.register_plugin(_plugin(2))
        cpu = CPUManager(4)
        pod = make_pod("p1", extended={"vendor.example/accel": "1"}, cpu="1", memory="1Gi")
        pod.spec.containers[0].resources.limits = dict(
            pod.spec.containers[0].resources.requests
        )
        dm.allocate(pod)
        cpu.add_container(pod, "c0")
        srv = PodResourcesServer(lambda: [pod], dm, cpu)
        out = srv.list()
        assert len(out) == 1
        assert out[0].containers[0].devices == {"vendor.example/accel": ["dev-0"]}
        assert len(out[0].containers[0].cpu_ids) == 1


class TestKubeletDeviceWiring:
    def _cluster(self, device_manager):
        api = APIServer()
        cs = Clientset(api)
        factory = SharedInformerFactory(cs)
        kl = Kubelet(
            cs,
            factory,
            config=KubeletConfig(node_name="node-0", **FAST),
            runtime=FakeRuntimeService(),
            device_manager=device_manager,
        )
        factory.start()
        assert factory.wait_for_cache_sync()
        kl.run()
        return cs, kl

    def test_node_advertises_plugin_resources(self):
        dm = DeviceManager()
        dm.register_plugin(_plugin(4))
        cs, kl = self._cluster(dm)
        try:
            node = cs.nodes.get("node-0")
            assert node.status.capacity["vendor.example/accel"] == "4"
            assert node.status.allocatable["vendor.example/accel"] == "4"
        finally:
            kl.stop()

    def test_admission_failure_fails_pod(self):
        dm = DeviceManager()
        dm.register_plugin(_plugin(1))
        cs, kl = self._cluster(dm)
        try:
            ok = make_pod("ok", extended={"vendor.example/accel": "1"},
                          node_name="node-0")
            cs.pods.create(ok)
            bad = make_pod("bad", extended={"vendor.example/accel": "1"},
                           node_name="node-0")
            cs.pods.create(bad)

            def settled():
                a = cs.pods.get("ok", "default").status.phase
                b = cs.pods.get("bad", "default")
                return a == "Running" and b.status.phase == "Failed" and (
                    b.status.reason == "UnexpectedAdmissionError"
                )

            _wait(settled, timeout=10)
        finally:
            kl.stop()

    def test_kubelet_stop_preserves_allocations(self, tmp_path):
        """Shutdown is not deletion: device allocations must survive a
        kubelet restart via the checkpoint (the reason checkpoint files
        exist); only real pod deletion frees devices."""
        ckpt = CheckpointManager(str(tmp_path))
        dm = DeviceManager(ckpt)
        dm.register_plugin(_plugin(2))
        cs, kl = self._cluster(dm)
        try:
            p = make_pod("keep", extended={"vendor.example/accel": "1"},
                         node_name="node-0")
            cs.pods.create(p)
            _wait(lambda: cs.pods.get("keep", "default").status.phase == "Running",
                  timeout=10)
        finally:
            kl.stop()
        uid = cs.pods.get("keep", "default").metadata.uid
        dm2 = DeviceManager(ckpt)
        assert dm2.pod_devices(uid), "restart lost the device allocation"


class TestAdmissionRollback:
    def test_partial_failure_frees_devices(self):
        """Devices committed before a later AdmissionError must be rolled
        back by the kubelet so a rejected pod holds nothing."""
        dm = DeviceManager()
        dm.register_plugin(_plugin(2))
        cpu = CPUManager(2)
        api = APIServer()
        cs = Clientset(api)
        factory = SharedInformerFactory(cs)
        kl = Kubelet(
            cs, factory,
            config=KubeletConfig(node_name="node-0", **FAST),
            runtime=FakeRuntimeService(),
            device_manager=dm, cpu_manager=cpu,
        )
        factory.start()
        assert factory.wait_for_cache_sync()
        kl.run()
        try:
            # guaranteed pod wanting 2 devices (fine) + 4 exclusive CPUs
            # (pool has 2): device allocation succeeds, CPU rejects
            bad = make_pod("bad", extended={"vendor.example/accel": "2"},
                           cpu="4", memory="1Gi", node_name="node-0")
            bad.spec.containers[0].resources.limits = dict(
                bad.spec.containers[0].resources.requests)
            cs.pods.create(bad)
            _wait(lambda: cs.pods.get("bad", "default").status.phase == "Failed",
                  timeout=10)
            uid = cs.pods.get("bad", "default").metadata.uid
            assert dm.pod_devices(uid) == {}, "rejected pod still holds devices"
            # the freed devices are usable by the next pod
            ok = make_pod("ok", extended={"vendor.example/accel": "2"},
                          node_name="node-0")
            cs.pods.create(ok)
            _wait(lambda: cs.pods.get("ok", "default").status.phase == "Running",
                  timeout=10)
        finally:
            kl.stop()

    def test_removed_signal_idempotent(self):
        dm = DeviceManager()
        dm.register_plugin(_plugin(2))
        dm.get_capacity()
        dm.unregister_plugin("vendor.example/accel")
        assert dm.get_capacity()[2] == ["vendor.example/accel"]
        # a discarded read does NOT consume the signal
        assert dm.get_capacity()[2] == ["vendor.example/accel"]
        # re-registration clears it
        dm.register_plugin(_plugin(2))
        assert dm.get_capacity()[2] == []
