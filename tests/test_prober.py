"""Liveness/readiness probing: prober streaks, liveness restarts,
readiness gating of the Ready condition and Endpoints membership.

Reference: pkg/kubelet/prober (worker.go thresholds, results manager
initial values), endpoints controller readiness split.
"""

import time

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.kubelet.cri import FakeRuntimeService
from kubernetes_tpu.kubelet.kubelet import Kubelet, KubeletConfig

from .util import FAST_KUBELET as FAST, make_pod, wait_until as _wait

FAST_PROBE = v1.Probe(exec_command=["check"], period_seconds=0.1,
                      failure_threshold=2, success_threshold=1)


def _cluster(runtime=None):
    api = APIServer()
    cs = Clientset(api)
    factory = SharedInformerFactory(cs)
    kl = Kubelet(cs, factory,
                 config=KubeletConfig(node_name="node-0", **FAST),
                 runtime=runtime or FakeRuntimeService())
    factory.start()
    assert factory.wait_for_cache_sync()
    kl.run()
    return cs, kl


def _ready(cs, name):
    pod = cs.pods.get(name, "default")
    for c in pod.status.conditions or []:
        if c.type == "Ready":
            return c.status == "True"
    return False


class TestReadinessProbe:
    def test_readiness_gates_ready_condition(self):
        rt = FakeRuntimeService()
        cs, kl = _cluster(rt)
        try:
            pod = make_pod("web", node_name="node-0")
            pod.spec.containers[0].readiness_probe = FAST_PROBE
            cs.pods.create(pod)
            _wait(lambda: cs.pods.get("web", "default").status.phase == "Running",
                  timeout=10)
            # passing probe: becomes Ready
            _wait(lambda: _ready(cs, "web"), timeout=10)
            # probe starts failing: Ready flips False (pod stays Running)
            rt.exec_results["c0"] = 1
            _wait(lambda: not _ready(cs, "web"), timeout=10)
            assert cs.pods.get("web", "default").status.phase == "Running"
            # recovers
            rt.exec_results["c0"] = 0
            _wait(lambda: _ready(cs, "web"), timeout=10)
        finally:
            kl.stop()

    def test_no_probe_ready_by_running(self):
        cs, kl = _cluster()
        try:
            cs.pods.create(make_pod("plain", node_name="node-0"))
            _wait(lambda: _ready(cs, "plain"), timeout=10)
        finally:
            kl.stop()


class TestLivenessProbe:
    def test_liveness_failure_restarts_container(self):
        rt = FakeRuntimeService()
        cs, kl = _cluster(rt)
        try:
            pod = make_pod("frail", node_name="node-0")
            pod.spec.containers[0].liveness_probe = FAST_PROBE
            cs.pods.create(pod)
            _wait(lambda: cs.pods.get("frail", "default").status.phase == "Running",
                  timeout=10)
            rt.exec_results["c0"] = 1  # liveness starts failing

            def restarted():
                st = cs.pods.get("frail", "default").status.container_statuses
                return bool(st) and st[0].restart_count >= 1

            _wait(restarted, timeout=10)
            # heal: settles back to Running with the restarted container
            rt.exec_results["c0"] = 0
            _wait(lambda: cs.pods.get("frail", "default").status.phase == "Running",
                  timeout=10)
        finally:
            kl.stop()


class TestEndpointsReadiness:
    def test_unready_pod_moves_to_not_ready_addresses(self):
        from kubernetes_tpu.controllers.endpoints import EndpointsController

        rt = FakeRuntimeService()
        api = APIServer()
        cs = Clientset(api)
        factory = SharedInformerFactory(cs)
        kl = Kubelet(cs, factory,
                     config=KubeletConfig(node_name="node-0", **FAST),
                     runtime=rt)
        ctrl = EndpointsController(cs, factory)
        factory.start()
        assert factory.wait_for_cache_sync()
        kl.run()
        ctrl.run()
        try:
            cs.services.create(v1.Service(
                metadata=v1.ObjectMeta(name="svc", namespace="default"),
                spec=v1.ServiceSpec(
                    selector={"app": "web"},
                    ports=[v1.ServicePort(port=80)],
                ),
            ))
            pod = make_pod("web-1", labels={"app": "web"}, node_name="node-0")
            pod.spec.containers[0].readiness_probe = FAST_PROBE
            cs.pods.create(pod)

            def ready_addr():
                try:
                    ep = cs.endpoints.get("svc", "default")
                except Exception:  # noqa: BLE001
                    return False
                return any(s.addresses for s in ep.subsets or [])

            _wait(ready_addr, timeout=10)
            rt.exec_results["c0"] = 1  # readiness fails

            def not_ready_addr():
                ep = cs.endpoints.get("svc", "default")
                subsets = ep.subsets or []
                return (subsets
                        and not any(s.addresses for s in subsets)
                        and any(s.not_ready_addresses for s in subsets))

            _wait(not_ready_addr, timeout=10)
        finally:
            ctrl.stop()
            kl.stop()


class TestReadinessInitialValue:
    def test_never_ready_pod_not_published_ready(self):
        """A readiness-probed container must NOT be Ready before its first
        probe success (results manager initial value) — even in the first
        status write after start."""
        rt = FakeRuntimeService()
        rt.exec_results["c0"] = 1  # failing from the start
        cs, kl = _cluster(rt)
        try:
            pod = make_pod("never", node_name="node-0")
            pod.spec.containers[0].readiness_probe = FAST_PROBE
            cs.pods.create(pod)
            _wait(lambda: cs.pods.get("never", "default").status.phase == "Running",
                  timeout=10)
            # observe several status cycles: Ready must stay False
            for _ in range(5):
                assert not _ready(cs, "never")
                time.sleep(0.1)
        finally:
            kl.stop()
