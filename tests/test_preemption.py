"""DefaultPreemption: unit cases on the dry-run algorithm + the live loop.

Mirrors the reference's table-driven plugin tests
(pkg/scheduler/framework/plugins/defaultpreemption/default_preemption_test.go)
and the preemption integration tier (test/integration/scheduler/
preemption_test.go): high-priority pods evict the cheapest adequate set of
lower-priority victims, PDB-protected victims are avoided when possible,
and Never-policy pods never preempt.
"""

from __future__ import annotations

import time

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import Clientset, SharedInformerFactory
from kubernetes_tpu.scheduler.framework.interface import CycleState
from kubernetes_tpu.scheduler.framework.runtime import Framework
from kubernetes_tpu.scheduler.framework.snapshot import Snapshot
from kubernetes_tpu.scheduler.internal.nominator import PodNominator
from kubernetes_tpu.scheduler.plugins.defaultpreemption import DefaultPreemption
from kubernetes_tpu.scheduler.plugins.registry import (
    default_plugins,
    new_in_tree_registry,
)
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.testing.synth import make_node, make_pod


def _framework(snapshot, pdbs=None):
    f = Framework(
        new_in_tree_registry(),
        plugins=default_plugins(),
        snapshot_fn=lambda: snapshot,
    )
    f.nominator = PodNominator()
    f.pdb_lister = lambda: list(pdbs or [])
    return f


def _post_filter(snapshot, pod, pdbs=None):
    f = _framework(snapshot, pdbs)
    state = CycleState()
    st = f.run_pre_filter_plugins(state, pod)
    assert st is None
    statuses = {}
    for ni in snapshot.list():
        s = f.run_filter_plugins(state, pod, ni)
        if s:
            statuses[ni.node.metadata.name] = next(iter(s.values()))
    plugin = f.plugins["DefaultPreemption"]
    return plugin.post_filter(state, pod, statuses)


def test_preempts_lowest_priority_victim():
    nodes = [make_node("n0", cpu="4"), make_node("n1", cpu="4")]
    low0 = make_pod("low0", cpu="3500m", node_name="n0", priority=1)
    low1 = make_pod("low1", cpu="3500m", node_name="n1", priority=5)
    snapshot = Snapshot.from_objects([low0, low1], nodes)
    pending = make_pod("high", cpu="3", priority=100)
    result, status = _post_filter(snapshot, pending)
    assert status is not None and status.is_success()
    # n0's victim has lower priority -> preferred (pickOneNode criterion 2)
    assert result.nominated_node_name == "n0"
    assert [p.metadata.name for p in result.victims] == ["low0"]


def test_never_policy_does_not_preempt():
    nodes = [make_node("n0", cpu="4")]
    low = make_pod("low", cpu="3500m", node_name="n0", priority=1)
    snapshot = Snapshot.from_objects([low], nodes)
    pending = make_pod("high", cpu="3", priority=100)
    pending.spec.preemption_policy = "Never"
    result, status = _post_filter(snapshot, pending)
    assert result is None
    assert not status.is_success()


def test_no_preemption_of_equal_or_higher_priority():
    nodes = [make_node("n0", cpu="4")]
    peer = make_pod("peer", cpu="3500m", node_name="n0", priority=100)
    snapshot = Snapshot.from_objects([peer], nodes)
    pending = make_pod("high", cpu="3", priority=100)
    result, status = _post_filter(snapshot, pending)
    assert result is None


def test_minimal_victim_set_reprieve():
    """Reprieve keeps victims whose removal isn't needed
    (selectVictimsOnNode:633): 3 low pods of 1 cpu each; pending needs 2 —
    only two 1-cpu victims die, the highest-priority one survives."""
    nodes = [make_node("n0", cpu="4", pods=10)]
    lows = [
        make_pod(f"low{i}", cpu="1", node_name="n0", priority=i) for i in range(3)
    ]
    # node: 3 cpu used, 1 free; pending wants 2.9 -> needs 2 evictions
    snapshot = Snapshot.from_objects(lows, nodes)
    pending = make_pod("high", cpu="2900m", priority=50)
    result, status = _post_filter(snapshot, pending)
    assert status.is_success()
    names = sorted(p.metadata.name for p in result.victims)
    assert names == ["low0", "low1"], names  # low2 (highest) reprieved


def test_pdb_protected_avoided():
    """Two equivalent nodes; one victim is PDB-protected with 0 allowed
    disruptions -> pick the other node (pickOneNode criterion 1)."""
    nodes = [make_node("n0", cpu="4"), make_node("n1", cpu="4")]
    a = make_pod("a", cpu="3500m", node_name="n0", priority=1,
                 labels={"app": "guarded"})
    b = make_pod("b", cpu="3500m", node_name="n1", priority=1)
    snapshot = Snapshot.from_objects([a, b], nodes)
    pdb = v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="pdb", namespace="default"),
        spec=v1.PodDisruptionBudgetSpec(
            selector=v1.LabelSelector(match_labels={"app": "guarded"})
        ),
        status=v1.PodDisruptionBudgetStatus(disruptions_allowed=0),
    )
    pending = make_pod("high", cpu="3", priority=100)
    result, status = _post_filter(snapshot, pending, pdbs=[pdb])
    assert status.is_success()
    assert result.nominated_node_name == "n1"


@pytest.mark.parametrize("backend", ["oracle", "tpu"])
def test_preemption_end_to_end(backend):
    """Live loop: cluster full of low-priority pods; a critical pod arrives,
    victims get deleted, the pod binds (integration preemption_test.go)."""
    api = APIServer()
    cs = Clientset(api)
    for i in range(2):
        cs.nodes.create(make_node(f"node-{i}", cpu="4",
                                  labels={v1.LABEL_HOSTNAME: f"node-{i}"}))
    factory = SharedInformerFactory(cs)
    sched = Scheduler(cs, factory, backend=backend)
    factory.start()
    assert factory.wait_for_cache_sync()
    try:
        sched.start()
        for i in range(2):
            cs.pods.create(make_pod(f"low-{i}", namespace="default",
                                    cpu="3500m", priority=1))
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            pods, _ = cs.pods.list(namespace="default")
            if all(p.spec.node_name for p in pods):
                break
            time.sleep(0.1)
        cs.pods.create(make_pod("critical", namespace="default",
                                cpu="3", priority=1000))
        deadline = time.monotonic() + 30
        critical = None
        while time.monotonic() < deadline:
            critical = cs.pods.get("critical", "default")
            if critical.spec.node_name:
                break
            time.sleep(0.1)
        assert critical.spec.node_name, "critical pod must preempt and bind"
        pods, _ = cs.pods.list(namespace="default")
        low_remaining = [p for p in pods if p.metadata.name.startswith("low")]
        assert len(low_remaining) == 1, "exactly one victim evicted"
    finally:
        sched.stop()
        factory.stop()


class TestPreemptionThroughTPULoop:
    """Device-path preemption (VERDICT r3 #8): the TPU batch loop's
    failure wave recovers per-node statuses via ONE chunked vmapped
    kernel dispatch (TPUBackend.reevaluate), feeds the same
    DefaultPreemption dry-run as the oracle path, and converges to the
    same outcome: every high-priority pod bound, one victim evicted
    each. Parity is outcome-level (batching changes pod processing
    order; victim selection per dry-run is the same deterministic
    pickOneNodeForPreemption both ways)."""

    def _run(self, backend):
        import time as _t

        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client import Clientset, SharedInformerFactory
        from kubernetes_tpu.scheduler.scheduler import Scheduler
        from .util import make_node, make_pod, wait_until

        api = APIServer()
        cs = Clientset(api)
        for i in range(6):
            cs.nodes.create(make_node(f"n-{i}"))
        factory = SharedInformerFactory(cs)
        sched = Scheduler(cs, factory, backend=backend, max_batch=8)
        factory.start()
        assert factory.wait_for_cache_sync()
        sched.start()
        # saturate: 4 x 900m on 4-CPU nodes
        for i in range(24):
            cs.pods.create(make_pod(
                f"low-{i}", cpu="900m", memory="64Mi", priority=1))
        assert wait_until(
            lambda: sum(
                1 for p in cs.pods.list(namespace="default")[0]
                if p.spec.node_name) == 24,
            timeout=60,
        ), "init pods did not bind"
        # sequential arrivals: concurrent failure waves can nominate the
        # same node twice before the first victim's deletion lands (an
        # eviction-count race the reference shares); one-at-a-time makes
        # the victim count deterministic for exact A/B
        ok = True
        for i in range(6):
            cs.pods.create(make_pod(
                f"hi-{i}", cpu="900m", memory="64Mi", priority=100))

            def bound(name=f"hi-{i}"):
                try:
                    return bool(cs.pods.get(name, "default").spec.node_name)
                except Exception:  # noqa: BLE001
                    return False

            ok = wait_until(bound, timeout=60)
            if not ok:
                break
        pods, _ = cs.pods.list(namespace="default")
        low = [p for p in pods if p.metadata.name.startswith("low-")]
        sched.stop()
        factory.stop()
        assert ok, f"{backend}: high-priority pods did not all bind"
        return len(low)

    def test_tpu_loop_matches_oracle_outcome(self):
        low_tpu = self._run("tpu")
        low_oracle = self._run("oracle")
        # exactly one victim evicted per high-priority pod, both paths
        assert low_tpu == low_oracle == 24 - 6
