"""Mesh-sharded kernel vs single-device kernel: identical outputs.

Runs on the 8-device virtual CPU mesh (conftest.py). This is the
multi-chip analog of the oracle parity suite: sharding the node axis must
not change any mask, score, or the selected node (the reference's
parallelize.Until chunking is likewise decision-invariant,
pkg/scheduler/internal/parallelize/parallelism.go:56).
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.kernel import schedule_pod
from kubernetes_tpu.parallel.sharded import (
    NODE_DIM0_KEYS,
    ShardedScheduler,
    make_mesh,
    pad_node_axis,
)
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods


@pytest.fixture(scope="module")
def encoded():
    nodes, pods = synth_cluster(24, pods_per_node=2)
    enc = ClusterEncoding()
    enc.set_cluster(nodes, pods)
    pe = PodEncoder(enc)
    pending = synth_pending_pods(3, spread=True)
    pod_arrays = [
        {k: v for k, v in pe.encode(p).items() if not k.startswith("_")}
        for p in pending
    ]
    cluster = enc.device_state()
    return enc, cluster, pod_arrays


def test_node_dim0_keys_cover_cluster(encoded):
    """Every node-axis array is listed; everything listed exists."""
    enc, cluster, _ = encoded
    ncap = cluster["valid"].shape[0]
    for k in NODE_DIM0_KEYS:
        assert k in cluster, k
        assert cluster[k].shape[0] == ncap, k
    # arrays NOT listed must not accidentally share the node capacity
    for k, v in cluster.items():
        if k not in NODE_DIM0_KEYS and np.ndim(v) >= 1:
            assert v.shape[0] != ncap or k in ("img_nodes", "taint_effect"), (
                f"{k} looks node-axis-shaped but is not sharded"
            )


def test_pad_preserves_outputs(encoded):
    _, cluster, pod_arrays = encoded
    base = jax.tree.map(np.asarray, schedule_pod(cluster, pod_arrays[0]))
    padded = pad_node_axis(cluster, 7)  # deliberately odd multiple
    out = jax.tree.map(np.asarray, schedule_pod(padded, pod_arrays[0]))
    n = cluster["valid"].shape[0]
    assert not out["feasible"][n:].any(), "padding rows must be infeasible"
    for k, v in base.items():
        np.testing.assert_array_equal(v, out[k][:n] if out[k].ndim else out[k], err_msg=k)


def test_sharded_matches_single_device(encoded):
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    _, cluster, pod_arrays = encoded
    mesh = make_mesh(n_devices=8)
    sharded = ShardedScheduler(mesh=mesh)
    n = cluster["valid"].shape[0]
    for p in pod_arrays:
        base = jax.tree.map(np.asarray, schedule_pod(cluster, p))
        out = sharded.schedule(cluster, p)
        out = jax.tree.map(np.asarray, out)
        for k, v in base.items():
            got = out[k]
            if got.ndim and got.shape[0] >= n:
                got = got[:n]
            np.testing.assert_array_equal(v, got, err_msg=k)
        # device-side reduction agrees with host argmax
        assert int(out["best_idx"]) == int(np.asarray(base["total"]).argmax())
        assert int(out["n_feasible"]) == int(base["feasible"].sum())


def test_graft_entry_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert bool(np.asarray(out["feasible"]).any())


def test_graft_entry_dryrun():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


class TestShardedSession:
    def test_session_parity_with_terms(self):
        """The mesh-sharded cross-batch session must make bit-identical
        decisions to the single-device session, including the dynamic
        anti-affinity carries (parallel/sharded.py ShardedScheduler.session)."""
        import jax

        from kubernetes_tpu.api import types as v1
        from kubernetes_tpu.ops.hoisted import (
            HoistedSession,
            template_fingerprint,
        )
        from kubernetes_tpu.parallel.sharded import ShardedScheduler, make_mesh
        from kubernetes_tpu.testing.synth import synth_cluster

        from .test_hoisted import _presized_encoding
        from .util import make_pod

        nodes, init_pods = synth_cluster(26, pods_per_node=1)
        anti = v1.Affinity(pod_anti_affinity=v1.PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[
                v1.PodAffinityTerm(
                    label_selector=v1.LabelSelector(
                        match_labels={"app": "ss"}),
                    topology_key=v1.LABEL_HOSTNAME,
                )
            ]
        ))
        pending = [
            make_pod(f"s-{i}", cpu="50m", labels={"app": "ss"}, affinity=anti)
            for i in range(12)
        ]
        enc, pe = _presized_encoding(nodes, init_pods, pending)
        arrays = [
            {k: v for k, v in pe.encode(p).items() if not k.startswith("_")}
            for p in pending
        ]
        cluster = enc.device_state()
        templates, seen = [], set()
        for a in arrays:
            fp = template_fingerprint(a)
            if fp not in seen:
                seen.add(fp)
                templates.append(a)
        single = HoistedSession(cluster, templates)
        mesh = make_mesh(n_devices=min(8, len(jax.devices())))
        multi = ShardedScheduler(mesh=mesh).session(cluster, templates)
        got_s, got_m = [], []
        for lo in range(0, len(arrays), 6):
            batch = arrays[lo : lo + 6]
            got_s.extend(
                HoistedSession.decisions(single.schedule(batch))[: len(batch)]
            )
            got_m.extend(
                HoistedSession.decisions(multi.schedule(batch))[: len(batch)]
            )
        assert got_m == got_s
        placed = [d for d in got_m if d >= 0]
        assert len(placed) == len(set(placed)) == 12
