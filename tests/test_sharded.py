"""Mesh-sharded kernel vs single-device kernel: identical outputs.

Runs on the 8-device virtual CPU mesh (conftest.py). This is the
multi-chip analog of the oracle parity suite: sharding the node axis must
not change any mask, score, or the selected node (the reference's
parallelize.Until chunking is likewise decision-invariant,
pkg/scheduler/internal/parallelize/parallelism.go:56).
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.kernel import schedule_pod
from kubernetes_tpu.parallel.sharded import (
    NODE_DIM0_KEYS,
    ShardedScheduler,
    make_mesh,
    pad_node_axis,
)
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods


@pytest.fixture(scope="module")
def encoded():
    nodes, pods = synth_cluster(24, pods_per_node=2)
    enc = ClusterEncoding()
    enc.set_cluster(nodes, pods)
    pe = PodEncoder(enc)
    pending = synth_pending_pods(3, spread=True)
    pod_arrays = [
        {k: v for k, v in pe.encode(p).items() if not k.startswith("_")}
        for p in pending
    ]
    cluster = enc.device_state()
    return enc, cluster, pod_arrays


def test_node_dim0_keys_cover_cluster(encoded):
    """Every node-axis array is listed; everything listed exists."""
    enc, cluster, _ = encoded
    ncap = cluster["valid"].shape[0]
    for k in NODE_DIM0_KEYS:
        assert k in cluster, k
        assert cluster[k].shape[0] == ncap, k
    # arrays NOT listed must not accidentally share the node capacity
    for k, v in cluster.items():
        if k not in NODE_DIM0_KEYS and np.ndim(v) >= 1:
            assert v.shape[0] != ncap or k in ("img_nodes", "taint_effect"), (
                f"{k} looks node-axis-shaped but is not sharded"
            )


def test_pad_preserves_outputs(encoded):
    _, cluster, pod_arrays = encoded
    base = jax.tree.map(np.asarray, schedule_pod(cluster, pod_arrays[0]))
    padded = pad_node_axis(cluster, 7)  # deliberately odd multiple
    out = jax.tree.map(np.asarray, schedule_pod(padded, pod_arrays[0]))
    n = cluster["valid"].shape[0]
    assert not out["feasible"][n:].any(), "padding rows must be infeasible"
    for k, v in base.items():
        np.testing.assert_array_equal(v, out[k][:n] if out[k].ndim else out[k], err_msg=k)


def test_sharded_matches_single_device(encoded):
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    _, cluster, pod_arrays = encoded
    mesh = make_mesh(n_devices=8)
    sharded = ShardedScheduler(mesh=mesh)
    n = cluster["valid"].shape[0]
    for p in pod_arrays:
        base = jax.tree.map(np.asarray, schedule_pod(cluster, p))
        out = sharded.schedule(cluster, p)
        out = jax.tree.map(np.asarray, out)
        for k, v in base.items():
            got = out[k]
            if got.ndim and got.shape[0] >= n:
                got = got[:n]
            np.testing.assert_array_equal(v, got, err_msg=k)
        # device-side reduction agrees with host argmax
        assert int(out["best_idx"]) == int(np.asarray(base["total"]).argmax())
        assert int(out["n_feasible"]) == int(base["feasible"].sum())


def test_graft_entry_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert bool(np.asarray(out["feasible"]).any())


def test_graft_entry_dryrun():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


class TestShardedSession:
    def test_session_parity_with_terms(self):
        """The mesh-sharded cross-batch session must make bit-identical
        decisions to the single-device session, including the dynamic
        anti-affinity carries (parallel/sharded.py ShardedScheduler.session)."""
        import jax

        from kubernetes_tpu.api import types as v1
        from kubernetes_tpu.ops.hoisted import (
            HoistedSession,
            template_fingerprint,
        )
        from kubernetes_tpu.parallel.sharded import ShardedScheduler, make_mesh
        from kubernetes_tpu.testing.synth import synth_cluster

        from .test_hoisted import _presized_encoding
        from .util import make_pod

        nodes, init_pods = synth_cluster(26, pods_per_node=1)
        anti = v1.Affinity(pod_anti_affinity=v1.PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[
                v1.PodAffinityTerm(
                    label_selector=v1.LabelSelector(
                        match_labels={"app": "ss"}),
                    topology_key=v1.LABEL_HOSTNAME,
                )
            ]
        ))
        pending = [
            make_pod(f"s-{i}", cpu="50m", labels={"app": "ss"}, affinity=anti)
            for i in range(12)
        ]
        enc, pe = _presized_encoding(nodes, init_pods, pending)
        arrays = [
            {k: v for k, v in pe.encode(p).items() if not k.startswith("_")}
            for p in pending
        ]
        cluster = enc.device_state()
        templates, seen = [], set()
        for a in arrays:
            fp = template_fingerprint(a)
            if fp not in seen:
                seen.add(fp)
                templates.append(a)
        single = HoistedSession(cluster, templates)
        mesh = make_mesh(n_devices=min(8, len(jax.devices())))
        multi = ShardedScheduler(mesh=mesh).session(cluster, templates)
        got_s, got_m = [], []
        for lo in range(0, len(arrays), 6):
            batch = arrays[lo : lo + 6]
            got_s.extend(
                HoistedSession.decisions(single.schedule(batch))[: len(batch)]
            )
            got_m.extend(
                HoistedSession.decisions(multi.schedule(batch))[: len(batch)]
            )
        assert got_m == got_s
        placed = [d for d in got_m if d >= 0]
        assert len(placed) == len(set(placed)) == 12


class TestMeshedProductBackend:
    """TPUBackend(mesh=...) drives the PRODUCT Scheduler loop over the
    virtual mesh (VERDICT r2 #4: multi-chip must be a product path, not a
    demo path): full APIServer + informers + queue + cache + Scheduler,
    decisions bit-identical to the single-device loop."""

    def _run_loop(self, mesh):
        import random as _random

        from kubernetes_tpu.api import types as v1
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client import Clientset, SharedInformerFactory
        from kubernetes_tpu.scheduler.scheduler import Scheduler
        from kubernetes_tpu.scheduler.tpu_backend import TPUBackend
        from .util import make_node, make_pod

        api = APIServer()
        cs = Clientset(api)
        for i in range(40):
            cs.nodes.create(make_node(
                f"node-{i}",
                labels={
                    v1.LABEL_HOSTNAME: f"node-{i}",
                    "zone": f"zone-{i % 3}",
                    v1.LABEL_ZONE: f"zone-{i % 3}",
                },
            ))
        import time as _t

        factory = SharedInformerFactory(cs)
        backend = TPUBackend(rng=_random.Random(0), mesh=mesh)
        sched = Scheduler(
            cs, factory, backend="tpu", tpu_backend=backend, max_batch=64
        )
        factory.start()
        assert factory.wait_for_cache_sync(60)
        # stage the full backlog so the loop drains ONE batch bucket (on
        # the virtual CPU mesh every distinct scan length is a multi-
        # minute XLA compile; the perf harness stages the same way)
        sched.start()
        sched.pause()
        _t.sleep(0.3)
        anti = v1.Affinity(pod_anti_affinity=v1.PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[
                v1.PodAffinityTerm(
                    label_selector=v1.LabelSelector(
                        match_labels={"app": "mesh"}),
                    topology_key=v1.LABEL_HOSTNAME,
                )
            ]
        ))
        n_pods = 36
        for i in range(n_pods):
            cs.pods.create(make_pod(
                f"p-{i}", cpu="100m", labels={"app": "mesh"},
                affinity=anti if i % 2 == 0 else None,
            ))
        deadline = _t.monotonic() + 60
        while _t.monotonic() < deadline and \
                sched.queue.num_active() < n_pods:
            _t.sleep(0.05)
        sched.resume()
        assert sched.wait_idle(420), "scheduler did not settle"
        pods, _ = cs.pods.list(namespace="default")
        out = {p.metadata.name: p.spec.node_name for p in pods}
        sched.stop()
        factory.stop()
        return out

    def test_scheduler_loop_parity_mesh_vs_single(self):
        import jax

        mesh = make_mesh(n_devices=min(8, len(jax.devices())))
        with_mesh = self._run_loop(mesh)
        without = self._run_loop(None)
        bound_m = {k: v for k, v in with_mesh.items() if v}
        bound_s = {k: v for k, v in without.items() if v}
        assert bound_m == bound_s, "mesh vs single-device decisions differ"
        # the anti-affinity pods must be spread one-per-node
        anti_nodes = [v for k, v in bound_m.items()
                      if int(k.split("-")[1]) % 2 == 0]
        assert len(set(anti_nodes)) == len(anti_nodes)
