"""Columnar-vs-object SchedulerCache bit-parity (ISSUE 12 tentpole).

Two caches — columnar arrays on (KTPU_COLUMNAR_CACHE default) and the
per-pod object path (the =0 kill switch) — are driven through identical
randomized interleavings of the full mutation surface: batched assumes,
informer confirms (same node and relocations), foreign adds, updates,
removes, forgets, TTL expiry sweeps on a fake clock, and node
add/update/remove churn. After every step the externally observable
state must be identical: dump() sequences, foreign_mutations(),
min_pod_priority(), per-node NodeInfo aggregates, TTL expiry counts.
The columnar arrays themselves must recompute exactly from the object
NodeInfos at every step — the lock-step invariant.

Also pinned here: the incremental image-spread index against an in-test
full rebuild (satellite), the min_pod_priority multiset against the
O(n) scan under churn (satellite), and the batched on_assume_pods
listener default emitting the per-pod event stream unchanged.
"""

from __future__ import annotations

import random
from typing import Dict, List

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.scheduler.internal.cache import (
    CacheListener,
    SchedulerCache,
)
from kubernetes_tpu.testing.synth import make_node, make_pod


def _mk_pod(i: int, node: str, prio=None, cpu="100m", memory="64Mi"):
    return make_pod(f"p-{i}", cpu=cpu, memory=memory, node_name=node,
                    priority=prio)


def _aggregates(cache: SchedulerCache) -> Dict[str, tuple]:
    out = {}
    for name, ni in cache._nodes.items():
        out[name] = (
            ni.node is not None,
            ni.requested.milli_cpu, ni.requested.memory,
            ni.requested.ephemeral_storage,
            ni.non_zero_requested.milli_cpu,
            ni.non_zero_requested.memory,
            sorted(v1.pod_key(pi.pod) for pi in ni.pods),
        )
    return out


def _assert_same_external_state(a: SchedulerCache, b: SchedulerCache):
    an, ap = a.dump()
    bn, bp = b.dump()
    assert [n.metadata.name for n in an] == [n.metadata.name for n in bn]
    assert [v1.pod_key(p) for p in ap] == [v1.pod_key(p) for p in bp]
    assert [p.spec.node_name for p in ap] == [p.spec.node_name for p in bp]
    assert a.foreign_mutations() == b.foreign_mutations()
    assert a.min_pod_priority() == b.min_pod_priority()
    assert a.pod_count() == b.pod_count()
    assert a.node_count() == b.node_count()
    assert sorted(a._assumed_pods) == sorted(b._assumed_pods)
    assert _aggregates(a) == _aggregates(b)


def _assert_columnar_lockstep(cache: SchedulerCache, check_assumed=True):
    """Columnar rows recompute exactly from the object NodeInfos."""
    assert cache._col_len == len(cache._nodes)
    assumed_by_node: Dict[str, int] = {}
    for key in cache._assumed_pods:
        ps = cache._pod_states[key]
        n = ps.pod.spec.node_name
        assumed_by_node[n] = assumed_by_node.get(n, 0) + 1
    for name, ni in cache._nodes.items():
        i = cache._col_index[name]
        assert int(cache._col_req[i, 0]) == ni.requested.milli_cpu
        assert int(cache._col_req[i, 1]) == ni.requested.memory
        assert int(cache._col_req[i, 2]) == ni.requested.ephemeral_storage
        assert int(cache._col_nz[i, 0]) == ni.non_zero_requested.milli_cpu
        assert int(cache._col_nz[i, 1]) == ni.non_zero_requested.memory
        assert int(cache._col_counts[i, 0]) == len(ni.pods)
        if ni.node is not None:
            assert int(cache._col_alloc[i, 0]) == ni.allocatable.milli_cpu
            assert int(cache._col_alloc[i, 3]) == \
                ni.allocatable.allowed_pod_number
        if check_assumed:
            assert int(cache._col_counts[i, 1]) == \
                assumed_by_node.get(name, 0)
    # freed/unused rows stay zeroed (swap-compaction hygiene)
    assert not cache._col_req[cache._col_len:].any()
    assert not cache._col_counts[cache._col_len:].any()


def _scan_min_priority(cache: SchedulerCache) -> int:
    return min(
        (ps.pod.spec.priority or 0 for ps in cache._pod_states.values()),
        default=0,
    )


@pytest.mark.parametrize("seed", [1, 7, 42])
@pytest.mark.parametrize("node_churn", [False, True])
def test_columnar_object_equivalence(seed, node_churn):
    """The tentpole property test: identical op interleavings produce
    identical external state in both modes, and the columnar arrays
    stay in lock-step with the object NodeInfos throughout. node_churn
    adds node remove/re-add under live pods; the assumed-count column
    is exempt there (a freed row forgets flags for pods that outlive
    their node — the object path has no analogous state at all)."""
    rng = random.Random(seed)
    clock = [0.0]
    obj = SchedulerCache(ttl=10.0, now=lambda: clock[0], columnar=False)
    col = SchedulerCache(ttl=10.0, now=lambda: clock[0], columnar=True)
    caches = (obj, col)

    node_names = [f"node-{i}" for i in range(6)]
    for n in node_names:
        node = make_node(n)
        for c in caches:
            c.add_node(node)

    next_id = [0]
    assumed: List[v1.Pod] = []       # assumed, unconfirmed
    confirmed: List[v1.Pod] = []     # informer-confirmed

    def mk(node):
        next_id[0] += 1
        return _mk_pod(next_id[0], node,
                       prio=rng.choice([None, -5, 0, 3, 100]))

    for step in range(250):
        op = rng.randrange(10)
        if op <= 2:  # batched assume harvest
            pods = [mk(rng.choice(node_names))
                    for _ in range(rng.randrange(1, 9))]
            res_o = obj.assume_pods(list(pods))
            res_c = col.assume_pods(list(pods))
            assert res_o == res_c
            for c in caches:
                c.finish_binding_many(pods)
            assumed.extend(pods)
        elif op == 3 and assumed:  # informer confirm (maybe relocated)
            p = assumed.pop(rng.randrange(len(assumed)))
            confirm = v1.Pod(
                metadata=p.metadata,
                spec=v1.PodSpec(
                    node_name=(rng.choice(node_names) if rng.random() < 0.2
                               else p.spec.node_name),
                    priority=p.spec.priority,
                    containers=p.spec.containers,
                ),
            )
            for c in caches:
                c.add_pod(confirm)
            confirmed.append(confirm)
        elif op == 4 and assumed:  # forget (failed bind)
            p = assumed.pop(rng.randrange(len(assumed)))
            for c in caches:
                c.forget_pod(p)
        elif op == 5 and confirmed:  # informer update
            p = confirmed[rng.randrange(len(confirmed))]
            for c in caches:
                c.update_pod(p, p)
        elif op == 6 and confirmed:  # informer remove
            p = confirmed.pop(rng.randrange(len(confirmed)))
            for c in caches:
                c.remove_pod(p)
        elif op == 7:  # clock advance + TTL sweep
            clock[0] += rng.choice([1.0, 6.0, 11.0])
            n_o = obj.cleanup_expired_assumed_pods()
            n_c = col.cleanup_expired_assumed_pods()
            assert n_o == n_c
            if n_o:
                # expired pods left both caches; prune the mirror
                live = set(obj._pod_states)
                assumed[:] = [p for p in assumed
                              if v1.pod_key(p) in live]
        elif op == 8:  # node heartbeat/update
            node = make_node(rng.choice(node_names))
            for c in caches:
                c.update_node(node)
        elif op == 9 and node_churn:  # remove + re-add a node
            name = rng.choice(node_names)
            for c in caches:
                c.remove_node(name)
            # pods bound there survive in _pod_states (informer truth);
            # drop them from our mirror lists only when later ops would
            # trip NodeInfo.remove_pod on the fresh empty node
            confirmed[:] = [p for p in confirmed
                            if p.spec.node_name != name]
            assumed[:] = [p for p in assumed
                          if p.spec.node_name != name]
            for key in [k for k, ps in obj._pod_states.items()
                        if ps.pod.spec.node_name == name]:
                for c in caches:
                    ps = c._pod_states.get(key)
                    if ps is not None:
                        c.remove_pod(ps.pod)
            node = make_node(name)
            for c in caches:
                c.add_node(node)
        _assert_same_external_state(obj, col)
        _assert_columnar_lockstep(col, check_assumed=not node_churn)
        assert obj.min_pod_priority() == _scan_min_priority(obj)
        assert col.min_pod_priority() == _scan_min_priority(col)


def test_min_pod_priority_multiset_under_churn():
    """Satellite regression: the incremental multiset tracks the O(n)
    scan through every add/confirm/update/remove/forget/expiry
    transition, including duplicate priorities and the empty-cache
    default of 0."""
    rng = random.Random(99)
    clock = [0.0]
    cache = SchedulerCache(ttl=5.0, now=lambda: clock[0])
    assert cache.min_pod_priority() == 0
    cache.add_node(make_node("n0"))
    live = []
    for i in range(400):
        r = rng.random()
        if r < 0.5 or not live:
            p = _mk_pod(1000 + i, "n0",
                        prio=rng.choice([None, -3, -3, 0, 2, 2, 50]))
            assert cache.assume_pods([p]) == [True]
            cache.finish_binding_many([p])
            live.append(p)
        elif r < 0.7:
            p = live.pop(rng.randrange(len(live)))
            cache.forget_pod(p)
        elif r < 0.9:
            p = live.pop(rng.randrange(len(live)))
            cache.add_pod(p)     # confirm
            cache.remove_pod(p)  # then informer delete
        else:
            clock[0] += 6.0
            cache.cleanup_expired_assumed_pods()
            keys = set(cache._pod_states)
            live[:] = [p for p in live if v1.pod_key(p) in keys]
        assert cache.min_pod_priority() == _scan_min_priority(cache)
    for p in list(live):
        cache.forget_pod(p)
    assert cache.min_pod_priority() == 0
    assert cache._prio_counts == {}


def _full_rebuild_image_states(cache: SchedulerCache):
    """The pre-satellite algorithm, verbatim: index over ALL nodes."""
    names_with_node = [
        n for n, ni in cache._nodes.items() if ni.node is not None
    ]
    image_nodes: Dict[str, set] = {}
    for name in names_with_node:
        node = cache._nodes[name].node
        for image in node.status.images or []:
            for nm in image.names or []:
                image_nodes.setdefault(nm, set()).add(name)
    out = {}
    for name in names_with_node:
        ni = cache._nodes[name]
        states = {}
        for image in ni.node.status.images or []:
            for nm in image.names or []:
                states[nm] = (image.size_bytes, len(image_nodes[nm]))
        out[name] = states
    return out


def _node_with_images(name: str, images: Dict[str, int]) -> v1.Node:
    node = make_node(name)
    node.status.images = [
        v1.ContainerImage(names=[nm], size_bytes=sz)
        for nm, sz in images.items()
    ]
    return node


def test_incremental_image_index_matches_full_rebuild():
    """Satellite: ImageStateSummary equivalence against the full
    rebuild through add/update/remove node churn — including the
    spread-count (num_nodes) updates on OTHER holders when one node
    gains or loses an image."""
    from kubernetes_tpu.scheduler.framework.snapshot import Snapshot

    rng = random.Random(5)
    cache = SchedulerCache()
    image_pool = [f"registry.example/img-{i}:v1" for i in range(7)]
    snap = Snapshot([])
    current: Dict[str, Dict[str, int]] = {}

    def check():
        nonlocal snap
        snap = cache.update_snapshot(snap)
        expected = _full_rebuild_image_states(cache)
        actual = {}
        for ni in snap.list():
            actual[ni.node.metadata.name] = {
                nm: (st.size, st.num_nodes)
                for nm, st in ni.image_states.items()
            }
        assert actual == expected

    for step in range(60):
        op = rng.randrange(4)
        name = f"inode-{rng.randrange(5)}"
        if op <= 1:  # add/update with a random image subset
            imgs = {nm: (i + 1) * 1000
                    for i, nm in enumerate(image_pool)
                    if rng.random() < 0.4}
            current[name] = imgs
            cache.add_node(_node_with_images(name, imgs))
        elif op == 2 and name in current:  # mutate one image in/out
            imgs = dict(current[name])
            nm = rng.choice(image_pool)
            if nm in imgs:
                del imgs[nm]
            else:
                imgs[nm] = 12345
            current[name] = imgs
            cache.update_node(_node_with_images(name, imgs))
        elif op == 3 and name in current:
            del current[name]
            cache.remove_node(name)
        check()


class _RecordingListener(CacheListener):
    def __init__(self):
        self.events = []

    def on_add_pod(self, pod, node_name):
        self.events.append(("add", v1.pod_key(pod), node_name))

    def on_remove_pod(self, pod, node_name):
        self.events.append(("remove", v1.pod_key(pod), node_name))


def test_on_assume_pods_default_preserves_per_pod_stream():
    """A listener that only implements the per-pod hooks must observe
    the exact same event stream from the batched columnar assume as
    from the object path — the CacheListener.on_assume_pods default."""
    streams = {}
    for columnar in (False, True):
        cache = SchedulerCache(columnar=columnar)
        rec = _RecordingListener()
        cache.add_listener(rec)
        cache.add_node(make_node("n0"))
        cache.add_node(make_node("n1"))
        pods = [_mk_pod(i, f"n{i % 2}") for i in range(10)]
        assert all(cache.assume_pods(pods))
        cache.forget_pod(pods[0])
        streams[columnar] = rec.events
    assert streams[False] == streams[True]
    assert streams[True][:3] == [
        ("add", "default/p-0", "n0"),
        ("add", "default/p-1", "n1"),
        ("add", "default/p-2", "n0"),
    ]
