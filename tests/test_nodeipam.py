"""NodeIpamController: central podCIDR allocation.

Reference behaviors pinned (pkg/controller/nodeipam/ipam/
range_allocator.go + cidr_set.go): lowest-free-subnet allocation,
occupation of pre-recorded CIDRs at startup, release + reuse on node
delete, exhaustion handling, and the kubelet consuming spec.podCIDR
into its CNI range.
"""

from __future__ import annotations

import pytest

from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import Clientset, SharedInformerFactory
from kubernetes_tpu.controllers.manager import new_controller_initializers
from kubernetes_tpu.controllers.nodeipam import CIDRSet, NodeIpamController

from .util import wait_until
from kubernetes_tpu.testing.synth import make_node


@pytest.fixture()
def cluster():
    api = APIServer()
    cs = Clientset(api)
    factory = SharedInformerFactory(cs)
    started = []

    def start(*ctrls):
        factory.start()
        assert factory.wait_for_cache_sync()
        for c in ctrls:
            c.run()
            started.append(c)
        return ctrls

    yield api, cs, factory, start
    for c in started:
        c.stop()
    factory.stop()


class TestCIDRSet:
    def test_lowest_free_and_reuse(self):
        s = CIDRSet("10.244.0.0/16", 24)
        assert s.max_cidrs == 256
        assert s.allocate_next() == "10.244.0.0/24"
        assert s.allocate_next() == "10.244.1.0/24"
        s.release("10.244.0.0/24")
        assert s.allocate_next() == "10.244.0.0/24"

    def test_occupy_blocks_allocation(self):
        s = CIDRSet("10.244.0.0/16", 24)
        s.occupy("10.244.0.0/24")
        assert s.allocate_next() == "10.244.1.0/24"

    def test_exhaustion_returns_none(self):
        s = CIDRSet("10.244.0.0/24", 26)
        got = [s.allocate_next() for _ in range(4)]
        assert got == ["10.244.0.0/26", "10.244.0.64/26",
                       "10.244.0.128/26", "10.244.0.192/26"]
        assert s.allocate_next() is None

    def test_foreign_cidr_rejected(self):
        s = CIDRSet("10.244.0.0/16", 24)
        with pytest.raises(ValueError):
            s.occupy("192.168.0.0/24")


class TestController:
    def test_allocates_to_new_nodes(self, cluster):
        api, cs, factory, start = cluster
        ctrl = NodeIpamController(cs, factory)
        start(ctrl)
        for i in range(3):
            cs.nodes.create(make_node(f"n{i}"))
        assert wait_until(
            lambda: all(
                cs.nodes.get(f"n{i}").spec.pod_cidr for i in range(3)
            )
        )
        cidrs = {cs.nodes.get(f"n{i}").spec.pod_cidr for i in range(3)}
        assert len(cidrs) == 3
        assert all(c.startswith("10.244.") and c.endswith("/24") for c in cidrs)

    def test_occupies_existing_and_releases_on_delete(self, cluster):
        api, cs, factory, start = cluster
        pre = make_node("pre")
        pre.spec.pod_cidr = "10.244.0.0/24"
        cs.nodes.create(pre)
        ctrl = NodeIpamController(cs, factory)
        start(ctrl)
        cs.nodes.create(make_node("fresh"))
        assert wait_until(lambda: cs.nodes.get("fresh").spec.pod_cidr)
        # pre-recorded subnet was occupied, not re-handed out
        assert cs.nodes.get("fresh").spec.pod_cidr != "10.244.0.0/24"
        cs.nodes.delete("pre")
        assert wait_until(lambda: ctrl.cidrs.used_count() == 1)
        cs.nodes.create(make_node("next"))
        assert wait_until(
            lambda: cs.nodes.get("next").spec.pod_cidr == "10.244.0.0/24"
        )

    def test_exhaustion_then_release_recovers(self, cluster):
        api, cs, factory, start = cluster
        ctrl = NodeIpamController(cs, factory,
                                  cluster_cidr="10.9.0.0/24",
                                  node_cidr_mask_size=26)
        start(ctrl)
        for i in range(5):  # only 4 subnets exist
            cs.nodes.create(make_node(f"n{i}"))
        assert wait_until(
            lambda: sum(
                1 for i in range(5) if cs.nodes.get(f"n{i}").spec.pod_cidr
            ) == 4
        )
        starved = next(
            f"n{i}" for i in range(5) if not cs.nodes.get(f"n{i}").spec.pod_cidr
        )
        victim = next(
            f"n{i}" for i in range(5) if cs.nodes.get(f"n{i}").spec.pod_cidr
        )
        cs.nodes.delete(victim)
        # the release may be claimed by the starved node's still-queued
        # sync immediately; otherwise a poke re-enqueues it
        n = cs.nodes.get(starved)
        n.metadata.labels["poke"] = "1"
        cs.nodes.update(n)
        assert wait_until(lambda: cs.nodes.get(starved).spec.pod_cidr)
        assert ctrl.cidrs.used_count() == 4  # 4 nodes, 4 subnets

    def test_registered_as_initializer(self):
        assert "nodeipam" in new_controller_initializers()


class TestKubeletConsumption:
    def test_kubelet_applies_pod_cidr_to_cni(self):
        from kubernetes_tpu.kubelet.cri import FakeRuntimeService

        rt = FakeRuntimeService()
        rt.set_pod_cidr("10.244.7.0/24")
        sid = rt.run_pod_sandbox("p", "default", "uid-1")
        ip = next(
            sb.ip for sb in rt.list_pod_sandboxes() if sb.id == sid
        )
        assert ip.startswith("10.244.7.")

    def test_kubelet_status_sync_consumes_spec(self, cluster):
        from kubernetes_tpu.kubelet.kubelet import Kubelet, KubeletConfig

        api, cs, factory, start = cluster
        ctrl = NodeIpamController(cs, factory)
        start(ctrl)
        kl = Kubelet(
            cs, factory,
            config=KubeletConfig(node_name="kn0", node_status_period=0.1),
        )
        kl.run()
        try:
            assert wait_until(lambda: cs.nodes.get("kn0").spec.pod_cidr)
            cidr = cs.nodes.get("kn0").spec.pod_cidr
            prefix = ".".join(cidr.split("/")[0].split(".")[:3])
            assert wait_until(lambda: kl.runtime._ip_prefix == prefix)
        finally:
            kl.stop()


class TestCidrMaskLengths:
    """Advisor r4: the CNI range must follow the actual mask length, not
    a two-bucket octet heuristic."""

    def test_slash23_uses_both_24s(self):
        from kubernetes_tpu.kubelet.cri import FakeRuntimeService

        rt = FakeRuntimeService()
        rt.set_pod_cidr("10.244.6.0/23")
        ips = set()
        for i in range(300):  # > 254, must spill into 10.244.7.x
            sid = rt.run_pod_sandbox(f"p{i}", "default", f"uid-{i}")
            ips.add(next(
                sb.ip for sb in rt.list_pod_sandboxes() if sb.id == sid))
        assert len(ips) == 300
        assert any(ip.startswith("10.244.7.") for ip in ips)
        assert all(
            ip.startswith("10.244.6.") or ip.startswith("10.244.7.")
            for ip in ips
        )

    def test_slash25_exhausts_at_126(self):
        from kubernetes_tpu.kubelet.cri import FakeRuntimeService

        rt = FakeRuntimeService()
        rt.set_pod_cidr("10.1.2.128/25")
        got = []
        for i in range(127):
            sid = rt.run_pod_sandbox(f"p{i}", "default", f"uid-{i}")
            got.append(next(
                sb.ip for sb in rt.list_pod_sandboxes() if sb.id == sid))
        # 127 usable host slots (skip network addr .128): .129-.255
        assert len(set(got)) == 127
        assert all(129 <= int(ip.rsplit(".", 1)[1]) <= 255 for ip in got)
        with pytest.raises(RuntimeError):
            rt.run_pod_sandbox("overflow", "default", "uid-x")

    def test_slash24_unchanged(self):
        from kubernetes_tpu.kubelet.cri import FakeRuntimeService

        rt = FakeRuntimeService()
        rt.set_pod_cidr("10.244.7.0/24")
        sid = rt.run_pod_sandbox("p", "default", "u")
        ip = next(sb.ip for sb in rt.list_pod_sandboxes() if sb.id == sid)
        assert ip.startswith("10.244.7.")
