"""Volume subsystem tests: binder matching, VolumeBinding plugin semantics,
VolumeRestrictions/VolumeZone/NodeVolumeLimits filters, PV controller.

Reference semantics: pkg/controller/volume/scheduling/scheduler_binder.go,
pkg/scheduler/framework/plugins/volumebinding/volume_binding.go,
volumerestrictions/volume_restrictions.go, volumezone/volume_zone.go,
nodevolumelimits/csi.go, pkg/controller/volume/persistentvolume.
"""

import time

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.api.storage import CSINode, CSINodeDriver, CSINodeSpec, StorageClass
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.persistentvolume import PersistentVolumeController
from kubernetes_tpu.scheduler.framework.interface import Code, CycleState
from kubernetes_tpu.scheduler.framework.types import NodeInfo
from kubernetes_tpu.scheduler.plugins.volumebinding import (
    ERR_REASON_UNBOUND_IMMEDIATE,
    VolumeBinding,
)
from kubernetes_tpu.scheduler.plugins.volumes import (
    NodeVolumeLimits,
    VolumeRestrictions,
    VolumeZone,
)
from kubernetes_tpu.volume.binder import (
    SchedulerVolumeBinder,
    find_matching_volume,
    pv_matches_claim,
)

from .util import make_node, make_pod


def mk_pv(name, capacity="10Gi", cls="", node=None, access=("ReadWriteOnce",),
          phase="Available", labels=None):
    affinity = None
    if node:
        affinity = v1.VolumeNodeAffinity(
            required=v1.NodeSelector(
                node_selector_terms=[
                    v1.NodeSelectorTerm(
                        match_expressions=[
                            v1.NodeSelectorRequirement(
                                key=v1.LABEL_HOSTNAME, operator="In", values=[node]
                            )
                        ]
                    )
                ]
            )
        )
    return v1.PersistentVolume(
        metadata=v1.ObjectMeta(name=name, labels=dict(labels or {})),
        spec=v1.PersistentVolumeSpec(
            capacity={"storage": capacity},
            access_modes=list(access),
            storage_class_name=cls,
            node_affinity=affinity,
        ),
        status=v1.PersistentVolumeStatus(phase=phase),
    )


def mk_pvc(name, request="5Gi", cls="", volume_name="", namespace="default",
           access=("ReadWriteOnce",)):
    return v1.PersistentVolumeClaim(
        metadata=v1.ObjectMeta(name=name, namespace=namespace),
        spec=v1.PersistentVolumeClaimSpec(
            access_modes=list(access),
            resources=v1.ResourceRequirements(requests={"storage": request}),
            storage_class_name=cls,
            volume_name=volume_name,
        ),
    )


def pod_with_pvc(name, *claims, namespace="default"):
    pod = make_pod(name, namespace=namespace, cpu="100m")
    pod.spec.volumes = [
        v1.Volume(name=f"v{i}", source={"persistentVolumeClaim": {"claimName": c}})
        for i, c in enumerate(claims)
    ]
    return pod


def mk_binder(pvcs=(), pvs=(), classes=(), client=None):
    return SchedulerVolumeBinder(
        list_pvcs=lambda: list(pvcs),
        list_pvs=lambda: list(pvs),
        list_storage_classes=lambda: list(classes),
        client=client,
    )


WFFC = StorageClass(
    metadata=v1.ObjectMeta(name="wffc"),
    provisioner="kubernetes.io/no-provisioner",
    volume_binding_mode="WaitForFirstConsumer",
)
WFFC_PROV = StorageClass(
    metadata=v1.ObjectMeta(name="wffc-prov"),
    provisioner="tpu.example/provisioner",
    volume_binding_mode="WaitForFirstConsumer",
)
IMMEDIATE = StorageClass(
    metadata=v1.ObjectMeta(name="fast"),
    provisioner="kubernetes.io/no-provisioner",
)


class TestPVMatching:
    def test_smallest_fitting_pv_wins(self):
        pvs = [mk_pv("big", "100Gi"), mk_pv("small", "5Gi"), mk_pv("tiny", "1Gi")]
        got = find_matching_volume(mk_pvc("c", request="5Gi"), pvs)
        assert got.metadata.name == "small"

    def test_class_and_access_and_phase_gates(self):
        claim = mk_pvc("c", cls="fast", access=("ReadWriteMany",))
        assert not pv_matches_claim(mk_pv("p1", cls=""), claim)
        assert not pv_matches_claim(mk_pv("p2", cls="fast"), claim)  # access modes
        bound = mk_pv("p3", cls="fast", access=("ReadWriteMany",), phase="Bound")
        assert not pv_matches_claim(bound, claim)
        ok = mk_pv("p4", cls="fast", access=("ReadWriteMany", "ReadWriteOnce"))
        assert pv_matches_claim(ok, claim)

    def test_node_affinity_gate(self):
        node_a = make_node("a", labels={v1.LABEL_HOSTNAME: "a"})
        node_b = make_node("b", labels={v1.LABEL_HOSTNAME: "b"})
        pv = mk_pv("p", node="a")
        claim = mk_pvc("c")
        assert pv_matches_claim(pv, claim, node_a)
        assert not pv_matches_claim(pv, claim, node_b)


class TestVolumeBindingPlugin:
    def test_no_pvcs_skips(self):
        pl = VolumeBinding(binder=mk_binder())
        state = CycleState()
        assert pl.pre_filter(state, make_pod("p")) is None
        ni = NodeInfo()
        ni.set_node(make_node("n"))
        assert pl.filter(state, make_pod("p"), ni) is None

    def test_missing_claim_unresolvable(self):
        pl = VolumeBinding(binder=mk_binder())
        status = pl.pre_filter(CycleState(), pod_with_pvc("p", "nope"))
        assert status.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_unbound_immediate_claim_unresolvable(self):
        claim = mk_pvc("c", cls="fast")
        pl = VolumeBinding(binder=mk_binder(pvcs=[claim], classes=[IMMEDIATE]))
        status = pl.pre_filter(CycleState(), pod_with_pvc("p", "c"))
        assert status.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE
        assert ERR_REASON_UNBOUND_IMMEDIATE in status.message()

    def test_bound_claim_node_affinity(self):
        pv = mk_pv("pv-a", node="a", phase="Bound")
        claim = mk_pvc("c", volume_name="pv-a")
        pl = VolumeBinding(binder=mk_binder(pvcs=[claim], pvs=[pv]))
        state = CycleState()
        pod = pod_with_pvc("p", "c")
        assert pl.pre_filter(state, pod) is None
        ni_a, ni_b = NodeInfo(), NodeInfo()
        ni_a.set_node(make_node("a", labels={v1.LABEL_HOSTNAME: "a"}))
        ni_b.set_node(make_node("b", labels={v1.LABEL_HOSTNAME: "b"}))
        assert pl.filter(state, pod, ni_a) is None
        status = pl.filter(state, pod, ni_b)
        assert status.code == Code.UNSCHEDULABLE

    def test_wffc_static_binding_and_assume_excludes_pv(self):
        pv = mk_pv("pv-a", node="a", cls="wffc")
        claim1 = mk_pvc("c1", cls="wffc")
        claim2 = mk_pvc("c2", cls="wffc")
        binder = mk_binder(pvcs=[claim1, claim2], pvs=[pv], classes=[WFFC])
        pl = VolumeBinding(binder=binder)
        node_a = make_node("a", labels={v1.LABEL_HOSTNAME: "a"})
        ni = NodeInfo()
        ni.set_node(node_a)

        state1 = CycleState()
        pod1 = pod_with_pvc("p1", "c1")
        assert pl.pre_filter(state1, pod1) is None
        assert pl.filter(state1, pod1, ni) is None
        assert pl.reserve(state1, pod1, "a") is None

        # second pod can't get the same PV and has no provisioner
        state2 = CycleState()
        pod2 = pod_with_pvc("p2", "c2")
        assert pl.pre_filter(state2, pod2) is None
        status = pl.filter(state2, pod2, ni)
        assert status is not None and status.code == Code.UNSCHEDULABLE

        # unreserve releases it
        pl.unreserve(state1, pod1, "a")
        state3 = CycleState()
        assert pl.pre_filter(state3, pod2) is None
        assert pl.filter(state3, pod2, ni) is None

    def test_provisionable_class_passes_filter(self):
        claim = mk_pvc("c", cls="wffc-prov")
        pl = VolumeBinding(binder=mk_binder(pvcs=[claim], classes=[WFFC_PROV]))
        state = CycleState()
        pod = pod_with_pvc("p", "c")
        ni = NodeInfo()
        ni.set_node(make_node("a", labels={v1.LABEL_HOSTNAME: "a"}))
        assert pl.pre_filter(state, pod) is None
        assert pl.filter(state, pod, ni) is None

    def test_prebind_binds_via_api(self):
        api = APIServer()
        cs = Clientset(api)
        cs.storageclasses.create(WFFC)
        cs.persistentvolumes.create(mk_pv("pv-a", node="a", cls="wffc"))
        cs.persistentvolumeclaims.create(mk_pvc("c1", cls="wffc"))

        def list_pvcs():
            return cs.persistentvolumeclaims.list()[0]

        def list_pvs():
            return cs.persistentvolumes.list()[0]

        binder = SchedulerVolumeBinder(
            list_pvcs, list_pvs, lambda: cs.storageclasses.list()[0], client=cs
        )
        pl = VolumeBinding(binder=binder)
        state = CycleState()
        pod = pod_with_pvc("p1", "c1")
        ni = NodeInfo()
        ni.set_node(make_node("a", labels={v1.LABEL_HOSTNAME: "a"}))
        assert pl.pre_filter(state, pod) is None
        assert pl.filter(state, pod, ni) is None
        assert pl.reserve(state, pod, "a") is None
        assert pl.pre_bind(state, pod, "a") is None

        claim = cs.persistentvolumeclaims.get("c1", "default")
        pv = cs.persistentvolumes.get("pv-a")
        assert claim.spec.volume_name == "pv-a"
        assert claim.status.phase == "Bound"
        assert pv.spec.claim_ref_name == "c1"
        assert pv.status.phase == "Bound"

    def test_prebind_provisions_dynamically(self):
        api = APIServer()
        cs = Clientset(api)
        cs.storageclasses.create(WFFC_PROV)
        cs.persistentvolumeclaims.create(mk_pvc("c1", cls="wffc-prov"))
        binder = SchedulerVolumeBinder(
            lambda: cs.persistentvolumeclaims.list()[0],
            lambda: cs.persistentvolumes.list()[0],
            lambda: cs.storageclasses.list()[0],
            client=cs,
        )
        pl = VolumeBinding(binder=binder)
        state = CycleState()
        pod = pod_with_pvc("p1", "c1")
        ni = NodeInfo()
        ni.set_node(make_node("a", labels={v1.LABEL_HOSTNAME: "a"}))
        assert pl.pre_filter(state, pod) is None
        assert pl.filter(state, pod, ni) is None
        assert pl.reserve(state, pod, "a") is None
        assert pl.pre_bind(state, pod, "a") is None

        claim = cs.persistentvolumeclaims.get("c1", "default")
        assert claim.status.phase == "Bound"
        pv = cs.persistentvolumes.get(claim.spec.volume_name)
        assert pv.spec.claim_ref_name == "c1"
        # provisioned PV is node-affine to the selected node
        assert pv.spec.node_affinity.required.node_selector_terms[0].match_expressions[0].values == ["a"]


class TestVolumeRestrictions:
    def _ni_with(self, source):
        ni = NodeInfo()
        ni.set_node(make_node("n"))
        existing = make_pod("existing", node_name="n")
        existing.spec.volumes = [v1.Volume(name="v", source=source)]
        ni.add_pod(existing)
        return ni

    def test_gce_pd_rw_conflict(self):
        pl = VolumeRestrictions()
        ni = self._ni_with({"gcePersistentDisk": {"pdName": "d1"}})
        pod = make_pod("p")
        pod.spec.volumes = [v1.Volume(name="v", source={"gcePersistentDisk": {"pdName": "d1"}})]
        status = pl.filter(CycleState(), pod, ni)
        assert status is not None and status.code == Code.UNSCHEDULABLE

    def test_gce_pd_both_readonly_ok(self):
        pl = VolumeRestrictions()
        ni = self._ni_with({"gcePersistentDisk": {"pdName": "d1", "readOnly": True}})
        pod = make_pod("p")
        pod.spec.volumes = [
            v1.Volume(name="v", source={"gcePersistentDisk": {"pdName": "d1", "readOnly": True}})
        ]
        assert pl.filter(CycleState(), pod, ni) is None

    def test_aws_ebs_conflicts_even_readonly(self):
        pl = VolumeRestrictions()
        ni = self._ni_with({"awsElasticBlockStore": {"volumeID": "vol-1", "readOnly": True}})
        pod = make_pod("p")
        pod.spec.volumes = [
            v1.Volume(name="v", source={"awsElasticBlockStore": {"volumeID": "vol-1", "readOnly": True}})
        ]
        status = pl.filter(CycleState(), pod, ni)
        assert status is not None and status.code == Code.UNSCHEDULABLE

    def test_different_disks_ok(self):
        pl = VolumeRestrictions()
        ni = self._ni_with({"gcePersistentDisk": {"pdName": "d1"}})
        pod = make_pod("p")
        pod.spec.volumes = [v1.Volume(name="v", source={"gcePersistentDisk": {"pdName": "d2"}})]
        assert pl.filter(CycleState(), pod, ni) is None


class _Handle:
    def __init__(self, pvcs=(), pvs=(), csinodes=()):
        self.volume_listers = (lambda: list(pvcs), lambda: list(pvs))
        self.csi_node_lister = lambda: list(csinodes)


class TestVolumeZone:
    def test_zone_conflict(self):
        pv = mk_pv("pv-z", labels={v1.LABEL_ZONE: "z1"})
        claim = mk_pvc("c", volume_name="pv-z")
        pl = VolumeZone(handle=_Handle(pvcs=[claim], pvs=[pv]))
        pod = pod_with_pvc("p", "c")
        ni_match, ni_other = NodeInfo(), NodeInfo()
        ni_match.set_node(make_node("a", labels={v1.LABEL_ZONE: "z1"}))
        ni_other.set_node(make_node("b", labels={v1.LABEL_ZONE: "z2"}))
        assert pl.filter(CycleState(), pod, ni_match) is None
        status = pl.filter(CycleState(), pod, ni_other)
        assert status is not None and status.code == Code.UNSCHEDULABLE

    def test_node_without_zone_labels_passes(self):
        pv = mk_pv("pv-z", labels={v1.LABEL_ZONE: "z1"})
        claim = mk_pvc("c", volume_name="pv-z")
        pl = VolumeZone(handle=_Handle(pvcs=[claim], pvs=[pv]))
        ni = NodeInfo()
        ni.set_node(make_node("a"))
        assert pl.filter(CycleState(), pod_with_pvc("p", "c"), ni) is None


class TestNodeVolumeLimits:
    def _csi_pod(self, name, *handles):
        pod = make_pod(name)
        pod.spec.volumes = [
            v1.Volume(name=f"v{i}", source={"csi": {"driver": "d1", "volumeHandle": h}})
            for i, h in enumerate(handles)
        ]
        return pod

    def test_limit_enforced(self):
        csinode = CSINode(
            metadata=v1.ObjectMeta(name="n"),
            spec=CSINodeSpec(drivers=[CSINodeDriver(name="d1", count=2)]),
        )
        pl = NodeVolumeLimits(handle=_Handle(csinodes=[csinode]))
        ni = NodeInfo()
        ni.set_node(make_node("n"))
        ni.add_pod(self._csi_pod("existing", "h1", "h2"))
        status = pl.filter(CycleState(), self._csi_pod("new", "h3"), ni)
        assert status is not None and status.code == Code.UNSCHEDULABLE

    def test_shared_volume_not_double_counted(self):
        csinode = CSINode(
            metadata=v1.ObjectMeta(name="n"),
            spec=CSINodeSpec(drivers=[CSINodeDriver(name="d1", count=2)]),
        )
        pl = NodeVolumeLimits(handle=_Handle(csinodes=[csinode]))
        ni = NodeInfo()
        ni.set_node(make_node("n"))
        ni.add_pod(self._csi_pod("existing", "h1", "h2"))
        assert pl.filter(CycleState(), self._csi_pod("new", "h2"), ni) is None

    def test_no_limit_driver_passes(self):
        pl = NodeVolumeLimits(handle=_Handle())
        ni = NodeInfo()
        ni.set_node(make_node("n"))
        assert pl.filter(CycleState(), self._csi_pod("new", "h1"), ni) is None


class TestPVController:
    def _run(self, cs):
        factory = SharedInformerFactory(cs)
        ctrl = PersistentVolumeController(cs, factory)
        factory.start()
        assert factory.wait_for_cache_sync()
        ctrl.run()
        return factory, ctrl

    def _wait(self, fn, timeout=10.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if fn():
                return True
            time.sleep(0.05)
        return False

    def test_immediate_claim_binds_to_matching_pv(self):
        api = APIServer()
        cs = Clientset(api)
        cs.storageclasses.create(IMMEDIATE)
        cs.persistentvolumes.create(mk_pv("pv-1", cls="fast"))
        factory, ctrl = self._run(cs)
        try:
            cs.persistentvolumeclaims.create(mk_pvc("c1", cls="fast"))
            assert self._wait(
                lambda: cs.persistentvolumeclaims.get("c1", "default").status.phase == "Bound"
            )
            pv = cs.persistentvolumes.get("pv-1")
            assert pv.spec.claim_ref_name == "c1"
        finally:
            ctrl.stop()
            factory.stop()

    def test_immediate_provisioning(self):
        api = APIServer()
        cs = Clientset(api)
        cs.storageclasses.create(
            StorageClass(
                metadata=v1.ObjectMeta(name="fast-prov"),
                provisioner="tpu.example/provisioner",
            )
        )
        factory, ctrl = self._run(cs)
        try:
            cs.persistentvolumeclaims.create(mk_pvc("c1", cls="fast-prov"))
            assert self._wait(
                lambda: cs.persistentvolumeclaims.get("c1", "default").status.phase == "Bound"
            )
        finally:
            ctrl.stop()
            factory.stop()

    def test_reclaim_delete_on_claim_removal(self):
        api = APIServer()
        cs = Clientset(api)
        cs.storageclasses.create(IMMEDIATE)
        pv = mk_pv("pv-1", cls="fast")
        pv.spec.persistent_volume_reclaim_policy = "Delete"
        cs.persistentvolumes.create(pv)
        factory, ctrl = self._run(cs)
        try:
            cs.persistentvolumeclaims.create(mk_pvc("c1", cls="fast"))
            assert self._wait(
                lambda: cs.persistentvolumeclaims.get("c1", "default").status.phase == "Bound"
            )
            cs.persistentvolumeclaims.delete("c1", "default")
            def gone():
                try:
                    cs.persistentvolumes.get("pv-1")
                    return False
                except Exception:
                    return True
            assert self._wait(gone)
        finally:
            ctrl.stop()
            factory.stop()
