"""FastPreemptionPlanner parity vs the oracle DefaultPreemption plugin.

The fast planner (scheduler/preemption.py) replaces the per-node
selectVictimsOnNode dry-run with one vectorized pass whenever the
preemptor's filter envelope reduces to static node gates + resource fit.
Inside that envelope its decisions must be EXACTLY the oracle's —
default_preemption.go:320 dryRunPreemption semantics — which this suite
pins with randomized clusters (the same strategy test_kernel_parity.py
uses for the scheduling kernel).
"""

from __future__ import annotations

import random

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.scheduler.framework.interface import CycleState
from kubernetes_tpu.scheduler.framework.snapshot import Snapshot
from kubernetes_tpu.scheduler.internal.nominator import PodNominator
from kubernetes_tpu.scheduler.preemption import (
    FastPreemptionPlanner,
    fast_eligible,
)
from kubernetes_tpu.testing.synth import make_node, make_pod

from .test_preemption import _post_filter


def _random_cluster(rng: random.Random, n_nodes: int):
    nodes = []
    pods = []
    for i in range(n_nodes):
        taints = None
        if rng.random() < 0.1:
            taints = [v1.Taint(key="dedicated", value="x", effect="NoSchedule")]
        nodes.append(
            make_node(
                f"n{i}",
                cpu=str(rng.choice([2, 4, 8])),
                memory="16Gi",
                pods=rng.choice([3, 5, 110]),
                unschedulable=rng.random() < 0.05,
                taints=taints,
            )
        )
        # mostly-saturated nodes: preemption paths only exercise when
        # the pending pod cannot fit anywhere as-is
        for j in range(rng.randint(2, 4)):
            pods.append(
                make_pod(
                    f"p{i}-{j}",
                    cpu=f"{rng.choice([900, 1500, 2000, 2500])}m",
                    memory=rng.choice(["64Mi", "512Mi", "2Gi"]),
                    node_name=f"n{i}",
                    priority=rng.choice([0, 1, 5, 50, 200]),
                )
            )
    return nodes, pods


def _plan_single(snapshot, pod, nominator=None):
    planner = FastPreemptionPlanner(snapshot, nominator)
    (cand,) = planner.plan([pod])
    return cand, planner.fits_now[0]


class TestParityFuzz:
    def test_matches_oracle_on_random_clusters(self):
        rng = random.Random(4)
        agree_preempt = 0
        agree_none = 0
        for trial in range(40):
            nodes, pods = _random_cluster(rng, rng.randint(3, 12))
            snapshot = Snapshot.from_objects(pods, nodes)
            pending = make_pod(
                "high",
                # 9000m exceeds every node shape: exercises the
                # no-candidate agreement too
                cpu=f"{rng.choice([1000, 2500, 3500, 9000])}m",
                memory="1Gi",
                priority=100,
            )
            assert fast_eligible(pending, snapshot, [], [])
            cand, fits_now = _plan_single(snapshot, pending)
            if fits_now:
                # the oracle never sees such pods (the scheduler only
                # preempts after a failed cycle); skip
                continue
            result, status = _post_filter(snapshot, pending)
            if cand is None:
                assert result is None, (
                    f"trial {trial}: planner found nothing, oracle chose "
                    f"{result.nominated_node_name} "
                    f"{[p.metadata.name for p in result.victims]}"
                )
                agree_none += 1
            else:
                assert result is not None, (
                    f"trial {trial}: planner chose {cand.node_name}, "
                    "oracle found nothing"
                )
                assert cand.node_name == result.nominated_node_name, trial
                assert sorted(p.metadata.name for p in cand.victims) == sorted(
                    p.metadata.name for p in result.victims
                ), trial
                agree_preempt += 1
        # the fuzz must actually exercise both outcomes
        assert agree_preempt >= 5
        assert agree_none >= 1

    def test_matches_oracle_with_nominated_load(self):
        """A node already nominated by an equal-priority pod has less
        usable capacity (framework.go:610 double-filtering)."""
        rng = random.Random(11)
        checked = 0
        for trial in range(20):
            nodes, pods = _random_cluster(rng, rng.randint(2, 6))
            snapshot = Snapshot.from_objects(pods, nodes)
            nominator = PodNominator()
            ghost = make_pod("ghost", cpu="2", memory="1Gi", priority=100)
            nominator.add_nominated_pod(
                ghost, nodes[rng.randrange(len(nodes))].metadata.name
            )
            pending = make_pod("high", cpu="2500m", memory="1Gi", priority=100)
            cand, fits_now = _plan_single(snapshot, pending, nominator)
            if fits_now:
                continue
            from .test_preemption import _framework

            f = _framework(snapshot)
            f.nominator = nominator
            state = CycleState()
            assert f.run_pre_filter_plugins(state, pending) is None
            statuses = {}
            for ni in snapshot.list():
                s = f.run_filter_plugins(state, pending, ni)
                if s:
                    statuses[ni.node.metadata.name] = next(iter(s.values()))
            plugin = f.plugins["DefaultPreemption"]
            result, status = plugin.post_filter(state, pending, statuses)
            if cand is None:
                assert result is None, trial
            else:
                assert result is not None, trial
                assert cand.node_name == result.nominated_node_name, trial
                assert sorted(p.metadata.name for p in cand.victims) == sorted(
                    p.metadata.name for p in result.victims
                ), trial
                checked += 1
        assert checked >= 3


class TestWaveSemantics:
    def test_wave_claims_distinct_victims_and_capacity(self):
        """A wave of identical preemptors on a saturated cluster: every
        pod gets a candidate, no victim is claimed twice, and no node is
        oversubscribed by the nominations."""
        nodes = [make_node(f"n{i}", cpu="4", pods=10) for i in range(20)]
        pods = [
            make_pod(f"low-{i}-{j}", cpu="900m", memory="64Mi",
                     node_name=f"n{i}", priority=1)
            for i in range(20)
            for j in range(4)
        ]
        snapshot = Snapshot.from_objects(pods, nodes)
        wave = [
            make_pod(f"hi-{k}", cpu="900m", memory="64Mi", priority=100)
            for k in range(20)
        ]
        planner = FastPreemptionPlanner(snapshot, PodNominator())
        cands = planner.plan(wave)
        assert all(c is not None for c in cands)
        victim_keys = [v1.pod_key(v) for c in cands for v in c.victims]
        assert len(victim_keys) == len(set(victim_keys)), "victim claimed twice"
        # nominations must never oversubscribe a node: each node holds
        # 4 victims x 0.9 cpu on 4 cpu, so at most 4 preemptors (0.9
        # each) fit even with every victim evicted
        per_node = {}
        for c in cands:
            per_node[c.node_name] = per_node.get(c.node_name, 0) + 1
            assert len(c.victims) == 1
        for node, count in per_node.items():
            assert count <= 4

    def test_wave_saturates_then_fails(self):
        """Once every lower-priority pod on a node is spoken for, later
        wave pods must not plan preemption there."""
        nodes = [make_node("n0", cpu="4", pods=10)]
        pods = [
            make_pod(f"low{j}", cpu="1900m", memory="64Mi",
                     node_name="n0", priority=1)
            for j in range(2)
        ]
        snapshot = Snapshot.from_objects(pods, nodes)
        wave = [
            make_pod(f"hi-{k}", cpu="1900m", memory="64Mi", priority=100)
            for k in range(4)
        ]
        planner = FastPreemptionPlanner(snapshot, PodNominator())
        cands = planner.plan(wave)
        # 2 victims, each freeing room for one preemptor; the first two
        # plans claim them, the rest find nothing
        assert sum(1 for c in cands if c is not None) == 2
        assert sum(1 for c in cands if c is None) == 2

    def test_fits_now_detected(self):
        nodes = [make_node("n0", cpu="4"), make_node("n1", cpu="4")]
        pods = [make_pod("low", cpu="3500m", node_name="n0", priority=1)]
        snapshot = Snapshot.from_objects(pods, nodes)
        pending = make_pod("hi", cpu="1", priority=100)
        cand, fits_now = _plan_single(snapshot, pending)
        assert fits_now and cand is None


class TestQueueActivate:
    def test_activate_skips_backoff(self):
        from kubernetes_tpu.scheduler.internal.queue import PriorityQueue

        q = PriorityQueue(pod_initial_backoff=100.0, pod_max_backoff=100.0)
        pod = make_pod("p", cpu="1")
        q.add(pod)
        info = q.pop(timeout=0)
        assert info is not None
        q.add_unschedulable_if_not_present(info, q.scheduling_cycle)
        # parked in unschedulableQ: a plain pop times out
        assert q.pop(timeout=0) is None
        assert q.activate(pod)
        got = q.pop(timeout=0)
        assert got is not None and got.pod.metadata.name == "p"
        # not parked anywhere now
        assert not q.activate(pod)

    def test_activate_from_backoff_queue(self):
        from kubernetes_tpu.scheduler.internal.queue import PriorityQueue

        q = PriorityQueue(pod_initial_backoff=100.0, pod_max_backoff=100.0)
        pod = make_pod("p", cpu="1")
        q.add(pod)
        info = q.pop(timeout=0)
        q.move_all_to_active_or_backoff_queue("NodeAdd")  # bump move cycle
        q.add_unschedulable_if_not_present(info, 0)  # -> backoffQ (raced)
        assert q.pop(timeout=0) is None  # 100s backoff
        assert q.activate(pod)
        assert q.pop(timeout=0) is not None


class TestInFlightTracking:
    def test_preemptor_activates_after_last_victim_echo(self):
        """End-to-end through the live loop on the CPU backend of the
        TPU scheduler: a preemptor waits parked until every victim's
        delete echoes, then binds on its nominated node without waiting
        out backoff."""
        import time

        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client import Clientset, SharedInformerFactory

        api = APIServer()
        cs = Clientset(api)
        cs.nodes.create(make_node("n0", cpu="4", pods=10))
        for j in range(4):
            cs.pods.create(
                make_pod(f"low{j}", cpu="900m", memory="64Mi",
                         node_name="", priority=1)
            )
        factory = SharedInformerFactory(cs)
        from kubernetes_tpu.scheduler.scheduler import Scheduler

        sched = Scheduler(cs, factory, backend="tpu",
                          pod_initial_backoff=30.0, pod_max_backoff=30.0)
        factory.start()
        assert factory.wait_for_cache_sync()
        sched.start()
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                pods, _ = cs.pods.list(namespace="default")
                if sum(1 for p in pods if p.spec.node_name) == 4:
                    break
                time.sleep(0.05)
            hi = make_pod("hi", cpu="900m", memory="64Mi", priority=100)
            cs.pods.create(hi)
            # 30s backoff configured: binding within a few seconds proves
            # the activate path, not the backoff clock, re-admitted it
            deadline = time.monotonic() + 20
            bound = False
            while time.monotonic() < deadline:
                got = cs.pods.get("hi", "default")
                if got.spec.node_name:
                    bound = True
                    break
                time.sleep(0.05)
            assert bound, "preemptor did not bind"
            assert got.spec.node_name == "n0"
            pods, _ = cs.pods.list(namespace="default")
            assert sum(1 for p in pods if p.metadata.name.startswith("low")
                       and p.spec.node_name) == 3
            # tracking state drained
            assert not sched._node_waves
            assert not sched._inflight_preemptors
        finally:
            sched.stop()
            factory.stop()


class TestEligibility:
    def test_gates(self):
        nodes = [make_node("n0")]
        snapshot = Snapshot.from_objects([], nodes)
        pod = make_pod("p", cpu="1", priority=10)
        assert fast_eligible(pod, snapshot, [], [])
        # PDBs are inside the envelope now (vectorized PDB partitioning)
        assert fast_eligible(pod, snapshot, [object()], [])
        assert not fast_eligible(pod, snapshot, [], [object()])  # extenders
        never = make_pod("p2", cpu="1", priority=10)
        never.spec.preemption_policy = "Never"
        assert not fast_eligible(never, snapshot, [], [])
        spread = make_pod("p3", cpu="1", priority=10)
        spread.spec.topology_spread_constraints = [
            v1.TopologySpreadConstraint(
                max_skew=1, topology_key="zone",
                when_unsatisfiable="DoNotSchedule",
            )
        ]
        assert not fast_eligible(spread, snapshot, [], [])
        # required anti-affinity gates per POD: only a preemptor the
        # term MATCHES falls back (one anti pod must no longer disable
        # the planner for the whole cluster — VERDICT r4 #6)
        anti = make_pod(
            "anti", cpu="1", node_name="n0",
            affinity=v1.Affinity(
                pod_anti_affinity=v1.PodAntiAffinity(
                    required_during_scheduling_ignored_during_execution=[
                        v1.PodAffinityTerm(
                            label_selector=v1.LabelSelector(
                                match_labels={"app": "x"}
                            ),
                            topology_key="kubernetes.io/hostname",
                        )
                    ]
                )
            ),
        )
        snapshot2 = Snapshot.from_objects([anti], nodes)
        assert fast_eligible(pod, snapshot2, [], [])  # no label match
        matched = make_pod("pm", cpu="1", priority=10,
                           labels={"app": "x"})
        assert not fast_eligible(matched, snapshot2, [], [])


class TestPDBParityFuzz:
    """PDB-covered victims ride the planner: filterPodsWithPDBViolation
    partitioning, violating-first reprieve, and the violations-first
    pick ladder must match the oracle exactly."""

    def _random_pdb_cluster(self, rng: random.Random, n_nodes: int):
        nodes, pods = [], []
        # sometimes every pod shares one app + an exhausted budget, so
        # violations are unavoidable and survive into the chosen
        # candidate (the violations ladder + violating-first reprieve
        # both get exercised)
        apps = ["a", "b", "c"] if rng.random() < 0.5 else ["a"]
        for i in range(n_nodes):
            nodes.append(make_node(
                f"n{i}", cpu=str(rng.choice([2, 4, 8])), memory="16Gi",
                pods=rng.choice([4, 6, 110]),
            ))
            for j in range(rng.randint(2, 6)):
                pod = make_pod(
                    f"p{i}-{j}",
                    cpu=f"{rng.choice([900, 1500, 2000, 2500])}m",
                    memory=rng.choice(["64Mi", "512Mi"]),
                    node_name=f"n{i}",
                    priority=rng.choice([0, 1, 5, 50]),
                    labels={"app": rng.choice(apps)},
                )
                # randomized start times: MoreImportantPod order (prio
                # desc, start asc) must genuinely differ from ni.pods
                # order, or the allowance-consumption-order contract
                # (:612 sort before filterPodsWithPDBViolation) is
                # untested
                pod.status.start_time = rng.random() * 100.0
                pods.append(pod)
        pdbs = []
        for k in range(rng.randint(1, 2)):
            pdbs.append(v1.PodDisruptionBudget(
                metadata=v1.ObjectMeta(name=f"pdb{k}", namespace="default"),
                spec=v1.PodDisruptionBudgetSpec(
                    selector=v1.LabelSelector(
                        match_labels={"app": rng.choice(apps)}),
                ),
                status=v1.PodDisruptionBudgetStatus(
                    # 1/2/3 with up to 6 matching victims per node: the
                    # PARTIALLY consumable range, where which victims
                    # land in the violating group depends entirely on
                    # consumption order
                    disruptions_allowed=rng.choice([0, 1, 2, 3]),
                ),
            ))
        return nodes, pods, pdbs

    def test_pdb_partial_budget_consumed_in_importance_order(self):
        """A budget covering MORE victims than it allows must be
        consumed in MoreImportantPod order (priority desc, earlier start
        first — the :612 sort runs before filterPodsWithPDBViolation),
        so the LEAST important victims land in the violating group.
        Consuming in ni.pods order instead flips which pods violate, and
        the violating-first eviction ORDER makes that observable."""
        nodes = [make_node("n0", cpu="4", memory="16Gi", pods=110)]
        specs = [  # (name, priority, start) in ni.pods order
            ("p0", 0, 5.0), ("p1", 10, 1.0), ("p2", 10, 3.0), ("p3", 5, 2.0),
        ]
        pods = []
        for name, prio, start in specs:
            p = make_pod(name, cpu="900m", node_name="n0", priority=prio,
                         labels={"app": "db"})
            p.status.start_time = start
            pods.append(p)
        pdb = v1.PodDisruptionBudget(
            metadata=v1.ObjectMeta(name="db-pdb", namespace="default"),
            spec=v1.PodDisruptionBudgetSpec(
                selector=v1.LabelSelector(match_labels={"app": "db"})),
            status=v1.PodDisruptionBudgetStatus(disruptions_allowed=2),
        )
        snapshot = Snapshot.from_objects(pods, nodes)
        # needs every victim gone: no reprieve, all four evicted
        pending = make_pod("high", cpu="3900m", priority=100)
        planner = FastPreemptionPlanner(snapshot, None, pdbs=[pdb])
        (cand,) = planner.plan([pending])
        assert cand is not None and not planner.fits_now[0]
        # consumption order p1(10,1) p2(10,3) p3(5) p0(0): the budget's
        # two allowances go to p1+p2, so p3+p0 violate — and evict FIRST
        assert cand.num_pdb_violations == 2
        assert [p.metadata.name for p in cand.victims] == \
            ["p3", "p0", "p1", "p2"]
        result, status = _post_filter(snapshot, pending, pdbs=[pdb])
        assert result is not None
        assert [p.metadata.name for p in result.victims] == \
            [p.metadata.name for p in cand.victims]

    def test_matches_oracle_with_pdbs(self):
        rng = random.Random(21)
        agree_preempt = 0
        saw_violations = 0
        for trial in range(40):
            nodes, pods, pdbs = self._random_pdb_cluster(
                rng, rng.randint(3, 10))
            snapshot = Snapshot.from_objects(pods, nodes)
            pending = make_pod(
                "high",
                cpu=f"{rng.choice([1000, 2500, 3500, 9000])}m",
                memory="1Gi", priority=100,
            )
            assert fast_eligible(pending, snapshot, pdbs, [])
            planner = FastPreemptionPlanner(snapshot, None, pdbs=pdbs)
            (cand,) = planner.plan([pending])
            if planner.fits_now[0]:
                continue
            result, status = _post_filter(snapshot, pending, pdbs=pdbs)
            if cand is None:
                assert result is None, trial
            else:
                assert result is not None, trial
                assert cand.node_name == result.nominated_node_name, trial
                assert [p.metadata.name for p in cand.victims] == [
                    p.metadata.name for p in result.victims
                ], trial
                agree_preempt += 1
                if cand.num_pdb_violations:
                    saw_violations += 1
        assert agree_preempt >= 8
        assert saw_violations >= 1  # the fuzz must exercise violations

    def test_pdb_protected_node_avoided(self):
        """Two equivalent nodes; the victims on one are PDB-protected
        with no disruptions left — the planner must pick the other
        (fewest violations is the FIRST pick-one criterion)."""
        nodes = [make_node("n0", cpu="4"), make_node("n1", cpu="4")]
        pods = [
            make_pod("v0", cpu="3500m", node_name="n0", priority=1,
                     labels={"app": "db"}),
            make_pod("v1", cpu="3500m", node_name="n1", priority=1,
                     labels={"app": "web"}),
        ]
        pdb = v1.PodDisruptionBudget(
            metadata=v1.ObjectMeta(name="db-pdb", namespace="default"),
            spec=v1.PodDisruptionBudgetSpec(
                selector=v1.LabelSelector(match_labels={"app": "db"})),
            status=v1.PodDisruptionBudgetStatus(disruptions_allowed=0),
        )
        snapshot = Snapshot.from_objects(pods, nodes)
        pending = make_pod("hi", cpu="2", priority=100)
        planner = FastPreemptionPlanner(snapshot, None, pdbs=[pdb])
        (cand,) = planner.plan([pending])
        assert cand is not None
        assert cand.node_name == "n1"
        assert cand.num_pdb_violations == 0

    def test_pdb_wave_throughput_envelope(self):
        """A whole wave with PDBs present plans through the planner (no
        oracle fallback) and claims distinct victims."""
        from kubernetes_tpu.scheduler.internal.nominator import PodNominator

        nodes = [make_node(f"n{i}", cpu="4", pods=10) for i in range(10)]
        pods = [
            make_pod(f"low-{i}-{j}", cpu="900m", memory="64Mi",
                     node_name=f"n{i}", priority=1,
                     labels={"app": "w"})
            for i in range(10) for j in range(4)
        ]
        pdb = v1.PodDisruptionBudget(
            metadata=v1.ObjectMeta(name="w-pdb", namespace="default"),
            spec=v1.PodDisruptionBudgetSpec(
                selector=v1.LabelSelector(match_labels={"app": "w"})),
            status=v1.PodDisruptionBudgetStatus(disruptions_allowed=100),
        )
        snapshot = Snapshot.from_objects(pods, nodes)
        wave = [
            make_pod(f"hi-{k}", cpu="900m", memory="64Mi", priority=100)
            for k in range(10)
        ]
        planner = FastPreemptionPlanner(
            snapshot, PodNominator(), pdbs=[pdb])
        cands = planner.plan(wave)
        assert all(c is not None for c in cands)
        victim_keys = [v1.pod_key(v) for c in cands for v in c.victims]
        assert len(victim_keys) == len(set(victim_keys))
        assert all(c.num_pdb_violations == 0 for c in cands)
